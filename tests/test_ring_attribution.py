"""Round 8: device-resident metric ring + cost-model attribution.

Five contracts, each pinned here:

* ``obs/ringbuf`` — the ring primitive: wraparound-correct drains, the
  overwrite refusal, and exact marker reconstruction.
* Trainer wiring — the ``--metrics-ring`` windowed epoch reports a loss
  trajectory BITWISE-identical to the non-ring path (ragged last window
  and buffer wraparound included), with device->host round-trips pinned
  at <= windows + 2 per epoch, and memory gauges at window boundaries
  that stay allocation-free through a disabled recorder.
* ``analysis/costmodel`` — analytic FLOPs pinned against hand-computed
  values for the VGG-11 forward (convs + fc) and an MLP train step
  (fwd + dw + the DCE-surviving dx dots), plus scan trip inference.
* Audit host-sync certification — a seeded ring-drain-inside-the-scan
  program FAILS; the real ring-write lowering (pure
  dynamic-update-slice) passes, with the donation floor raised by the
  two ring leaves.
* Serving causality + report rendering — every request's trace id rides
  its dispatch, queue-wait + service-time compose to the client latency,
  events.jsonl rotation round-trips through ``read_events_jsonl``, and
  tools/telemetry_report renders the ``attribution``/``traces`` sections
  (tolerantly absent on older runs).
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cs744_ddp_tpu import models as model_zoo
from cs744_ddp_tpu.analysis import audit as auditlib
from cs744_ddp_tpu.analysis import costmodel
from cs744_ddp_tpu.obs import NULL, Telemetry, ringbuf
from cs744_ddp_tpu.obs import attribution as attrlib
from cs744_ddp_tpu.obs.telemetry import read_events_jsonl
from cs744_ddp_tpu.train.loop import Trainer, emit_memory_gauges

from tinynet import tiny_cnn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def setup_module(module):
    model_zoo.register_model("tiny", tiny_cnn)


# ---------------------------------------------------------------------------
# ringbuf: the primitive
# ---------------------------------------------------------------------------

def test_ring_write_drain_wraparound():
    cap = 5
    ring = ringbuf.make_ring(cap)

    @jax.jit
    def fill(ring, vals):
        def step(r, v):
            return ringbuf.ring_write(r, (v, 2 * v, 1.0, v + 100.0)), None
        r, _ = jax.lax.scan(step, ring, vals)
        return r

    # 8 writes through a 5-slot ring: the last 3 drains all wrap.
    ring = fill(ring, jnp.arange(8, dtype=jnp.float32))
    buf = np.asarray(ring[0])
    assert int(ring[1]) == 8                     # total writes, not mod cap
    rows = ringbuf.drain_rows(buf, 8, 4)
    losses, gsq, oks, steps = ringbuf.split_columns(rows)
    np.testing.assert_array_equal(losses, [4.0, 5.0, 6.0, 7.0])
    np.testing.assert_array_equal(gsq, [8.0, 10.0, 12.0, 14.0])
    np.testing.assert_array_equal(oks, [1.0, 1.0, 1.0, 1.0])
    np.testing.assert_array_equal(steps, [104, 105, 106, 107])
    # Overwritten rows refuse to drain; so do more rows than ever written.
    with pytest.raises(ValueError, match="exceeds ring capacity"):
        ringbuf.drain_rows(buf, 8, 6)
    with pytest.raises(ValueError, match="exceeds total writes"):
        ringbuf.drain_rows(np.zeros((5, ringbuf.N_METRICS)), 2, 3)


def test_ring_marker_exactness_guard():
    rows = np.zeros((2, ringbuf.N_METRICS), np.float32)
    rows[:, ringbuf.METRICS.index("marker")] = [2.0 ** 24 - 1, 2.0 ** 24]
    with pytest.raises(ValueError, match="exact-f32"):
        ringbuf.marker_steps(rows)
    rows[:, ringbuf.METRICS.index("marker")] = [0.0, 2.0 ** 24 - 1]
    assert list(ringbuf.marker_steps(rows)) == [0, 2 ** 24 - 1]


def test_ring_capacity_validation():
    with pytest.raises(ValueError, match=">= 1"):
        ringbuf.make_ring(0)
    with pytest.raises(ValueError, match="expected 4 metrics"):
        ringbuf.ring_write(ringbuf.make_ring(2), (1.0, 2.0))


# ---------------------------------------------------------------------------
# Trainer wiring: bitwise parity, round-trip pin, memory gauges
# ---------------------------------------------------------------------------

def _ring_trainer(tmp_path, mesh4, telemetry, metrics_ring):
    return Trainer(model=tiny_cnn(), strategy="ddp", mesh=mesh4,
                   global_batch=64, data_dir=str(tmp_path), augment=False,
                   limit_train_batches=25, limit_eval_batches=2,
                   log=lambda s: None, telemetry=telemetry,
                   metrics_ring=metrics_ring)


def test_ring_epoch_bitwise_parity_and_round_trip_pin(tmp_path, mesh4):
    """The acceptance bar: capacity 20 over 25 batches forces BOTH a
    ragged 5-step window and a buffer wraparound on the second drain, and
    the reported trajectory must still be bitwise-identical to the
    non-ring windowed path — with exactly <= windows + 2 host round-trips
    for the whole epoch + eval."""
    tel_ring = Telemetry()
    tr = _ring_trainer(tmp_path, mesh4, tel_ring, metrics_ring=20)
    assert tr.train_window_ring is not None
    tr.train_model(0)
    tr.test_model()

    tel_plain = Telemetry()
    tr2 = _ring_trainer(tmp_path, mesh4, tel_plain, metrics_ring=0)
    assert tr2.train_window_ring is None
    tr2.train_model(0)

    ring_steps = [r for r in tel_ring.records if r["kind"] == "step"]
    plain_steps = [r for r in tel_plain.records if r["kind"] == "step"]
    assert len(ring_steps) == len(plain_steps) == 25
    # Bitwise: both paths run the SAME scanned program; the ring only
    # observes.  Exact float equality, not approx.
    assert [s["loss"] for s in ring_steps] == \
        [s["loss"] for s in plain_steps]
    # Ring-only enrichment: reconstructed absolute indices + grad norms.
    assert [s["step_index"] for s in ring_steps] == list(range(25))
    assert all(np.isfinite(s["grad_sqnorm"]) and s["grad_sqnorm"] > 0
               for s in ring_steps)

    # The round-trip pin: ceil(25/20) = 2 window drains + 1 eval fetch,
    # and NO per-step fetches anywhere.
    trips = [r for r in tel_ring.records
             if r["kind"] == "counter" and r["name"] == "host_round_trips"]
    sites = [t["site"] for t in trips]
    assert sites.count("window_drain") == 2
    assert sites.count("eval") == 1
    assert "step_fetch" not in sites and "window_fetch" not in sites
    windows = -(-25 // 20)
    assert len(trips) <= windows + 2

    # Per-window memory gauges at the boundaries the drain creates.
    mems = [r for r in tel_ring.records
            if r["kind"] == "gauge" and r["name"] == "memory"]
    assert len(mems) == 2
    assert all(m["value"]["host_rss_peak_mib"] > 0 for m in mems)
    assert all(m["value"]["device_live_mib"] >= 0 for m in mems)


def test_metrics_ring_validation(tmp_path):
    with pytest.raises(ValueError, match=">= 0"):
        Trainer(model=tiny_cnn(), strategy="single", num_devices=1,
                global_batch=8, data_dir=str(tmp_path), log=lambda s: None,
                metrics_ring=-1)
    with pytest.raises(ValueError, match="below the scan"):
        Trainer(model=tiny_cnn(), strategy="single", num_devices=1,
                global_batch=8, data_dir=str(tmp_path), log=lambda s: None,
                metrics_ring=7)


def test_memory_gauges_skip_disabled_recorder_entirely():
    class Exploding:
        enabled = False

        def __getattr__(self, name):
            raise AssertionError(f"telemetry.{name} touched while disabled")

    emit_memory_gauges(Exploding(), epoch=0, step=20)   # must not raise
    emit_memory_gauges(NULL, epoch=0, step=20)
    tel = Telemetry()
    emit_memory_gauges(tel, epoch=1, step=40)
    (rec,) = tel.records
    assert rec["name"] == "memory" and rec["epoch"] == 1
    assert rec["value"]["host_rss_peak_mib"] > 0


# ---------------------------------------------------------------------------
# costmodel: FLOPs pinned against hand-computed values
# ---------------------------------------------------------------------------

def test_costmodel_vgg11_forward_flops_pinned():
    """Conv FLOPs of the VGG-11 forward at batch 8, hand-computed from
    the config table (3x3 SAME convs: 2*B*H^2*Cout*9*Cin per stage) plus
    the 512->10 head dot."""
    from cs744_ddp_tpu.models import vgg
    init_fn, apply_fn = vgg.VGG11()
    params, state = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    x = jax.ShapeDtypeStruct((8, 32, 32, 3), jnp.float32)
    hlo = jax.jit(
        lambda p, s, xx: apply_fn(p, s, xx, train=False)[0]
    ).lower(params, state, x).compiler_ir(dialect="hlo").as_hlo_text()
    rep = costmodel.cost_report(hlo, "vgg11/fwd")

    stages = [(32, 3, 64), (16, 64, 128), (8, 128, 256), (8, 256, 256),
              (4, 256, 512), (4, 512, 512), (2, 512, 512), (2, 512, 512)]
    expected_conv = sum(2 * 8 * h * h * cout * 9 * cin
                        for h, cin, cout in stages)
    assert expected_conv == 2_444_230_656          # the hand computation
    assert rep.flops_by_op["convolution"] == float(expected_conv)
    assert rep.flops_by_op["dot"] == 2.0 * 8 * 10 * 512
    assert rep.hbm_bytes > 0 and rep.wire_bytes == 0


def test_costmodel_mlp_train_step_dots_pinned():
    """Dot FLOPs of a full 32->16->10 MLP SGD step at batch 8: forward
    (2*B*i*o per layer) + dw (same) + dx for every layer but the first
    (the input gradient is dead and DCE'd)."""
    B, I, H, O = 8, 32, 16, 10

    def loss_fn(params, x, y):
        h = jax.nn.relu(x @ params["w0"] + params["b0"])
        logits = h @ params["w1"] + params["b1"]
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.sum(jax.nn.one_hot(y, O) * logp, axis=-1))

    def train_step(params, x, y):
        grads = jax.grad(loss_fn)(params, x, y)
        return jax.tree.map(lambda p, g: p - 0.1 * g, params, grads)

    params = {"w0": jax.ShapeDtypeStruct((I, H), jnp.float32),
              "b0": jax.ShapeDtypeStruct((H,), jnp.float32),
              "w1": jax.ShapeDtypeStruct((H, O), jnp.float32),
              "b1": jax.ShapeDtypeStruct((O,), jnp.float32)}
    hlo = jax.jit(train_step).lower(
        params, jax.ShapeDtypeStruct((B, I), jnp.float32),
        jax.ShapeDtypeStruct((B,), jnp.int32)).compiler_ir(dialect="hlo").as_hlo_text()
    rep = costmodel.cost_report(hlo, "mlp/train_step")

    fwd = 2 * B * I * H + 2 * B * H * O
    dw = 2 * B * I * H + 2 * B * H * O
    dx = 2 * B * H * O                       # layer 1 only; layer 0 DCE'd
    assert fwd + dw + dx == 24_064           # the hand computation
    assert rep.flops_by_op["dot"] == float(fwd + dw + dx)


def test_costmodel_scan_trip_inference():
    def scanned(c):
        def step(c, _):
            return c * 1.5 + 1.0, None
        out, _ = jax.lax.scan(step, c, None, length=7)
        return out

    rep = costmodel.cost_report(
        jax.jit(scanned).lower(jnp.float32(0)).compiler_ir(dialect="hlo").as_hlo_text(), "scan7")
    assert max(rep.trip_counts.values()) == 7
    # The scanned body's 2 elementwise flops are charged per trip.
    assert rep.flops_by_op["elementwise"] >= 14.0


def test_costmodel_mfu_fields_single_source():
    f = costmodel.mfu_fields(1000.0, 2e9)
    assert f == {"tflops_per_sec": 2.0,
                 "mfu_vs_bf16_peak": round(2e12 / 197e12, 4)}
    assert costmodel.mfu_fields(1000.0, None) == {}     # absent, not null
    # Every consumer delegates here: same numbers from the metrics shim.
    from cs744_ddp_tpu.utils import metrics
    assert metrics.mfu_fields(1000.0, 2e9) == f
    import bench
    assert bench._mfu_fields(1000.0, 2e9) == f


# ---------------------------------------------------------------------------
# audit: ring host-sync certification (seeded positive + real negative)
# ---------------------------------------------------------------------------

_RING_DRAIN_IN_SCAN = """\
HloModule ring_drain_in_scan

wbody {
  p = f32[4] parameter(0)
  tok = token[] after-all()
  of = token[] outfeed(p, tok), outfeed_config="ring-drain"
  ROOT r = f32[4] add(p, p)
}

wcond {
  q = f32[4] parameter(0)
  ROOT lt = pred[] constant(false)
}

ENTRY main {
  a = f32[4] parameter(0)
  w = f32[4] while(a), body=wbody, condition=wcond
  ROOT out = f32[4] add(w, w)
}
"""


def test_ring_drain_inside_scan_fails_host_sync():
    """The anti-pattern the ring exists to avoid: draining (outfeeding)
    metric rows INSIDE the scanned body is a per-step host sync and the
    audit must refuse to certify it."""
    r = auditlib.audit_program(_RING_DRAIN_IN_SCAN,
                               auditlib.ProgramContract(name="t/ring"))
    assert r.rules["host-sync"] == "fail"
    assert "wbody" in r.findings[0].message


def test_ring_write_lowering_is_host_sync_clean():
    """The REAL ring write — one dynamic-update-slice per scanned step,
    drained by the host AFTER the dispatch — lowers with no host op
    inside the while body and certifies clean."""
    def scanned(ring, xs):
        def step(r, x):
            return ringbuf.ring_write(r, (x, x * x, 1.0, x + 1.0)), None
        r, _ = jax.lax.scan(step, ring, xs)
        return r

    hlo = jax.jit(scanned).lower(
        (jax.ShapeDtypeStruct((8, ringbuf.N_METRICS), jnp.float32),
         jax.ShapeDtypeStruct((), jnp.int32)),
        jax.ShapeDtypeStruct((6,), jnp.float32)).compiler_ir(dialect="hlo").as_hlo_text()
    assert "dynamic-update-slice" in hlo
    assert "outfeed" not in hlo
    r = auditlib.audit_program(hlo, auditlib.ProgramContract(name="t/ring"))
    assert r.rules["host-sync"] == "pass", r.findings


def test_zoo_ring_raises_donation_floor_and_collects_hlo():
    """Ring-carrying windowed programs donate the two extra ring leaves
    (state floor + 2) and the collected HLO feeds zoo_attribution."""
    res = auditlib.audit_zoo(model="tiny", global_batch=64, window=3,
                             strategies=("ddp", "overlap"),
                             paths=("window",), include_eval=False,
                             num_devices=4, collect_hlo=True)
    assert res.clean, "\n".join(res.format_lines())
    by_name = {r.program: r for r in res.reports}
    n_state = by_name["train/window/ddp"].stats["donated"]
    # tiny_cnn: 6 params + 2 BN state + momentum leaves, then the ring
    # buffer + counter on top — the floor held, so donated >= leaves + 2.
    assert n_state >= 8 + 2
    assert set(res.hlo) == {"train/window/ddp", "train/window/overlap"}

    attr = auditlib.zoo_attribution(res)
    assert set(attr["programs"]) == set(res.hlo)
    ddp = attr["programs"]["train/window/ddp"]
    assert ddp["gflops"] > 0 and ddp["wire_mib"] > 0
    assert ddp["roofline_bound"] in ("compute", "bandwidth")
    ov = attr["overlap_vs_ddp"]
    assert ov["ddp_chained_bytes"] >= ov["overlap_exposed_bytes_upper_bound"]
    json.dumps(attr)                              # manifest-ready

    # No collected HLO -> a loud error, not a silent empty record.
    bare = auditlib.audit_zoo(model="tiny", global_batch=64, window=3,
                              strategies=("ddp",), paths=("window",),
                              include_eval=False, num_devices=4)
    with pytest.raises(ValueError, match="collect_hlo"):
        auditlib.zoo_attribution(bare)


def test_record_attribution_manifest_merge(tmp_path):
    class Exploding:
        enabled = False

        def __getattr__(self, name):
            raise AssertionError(f"telemetry.{name} touched while disabled")

    auditlib.record_attribution(Exploding(), {"programs": {}})  # no-op
    tel = Telemetry(str(tmp_path))
    tel.write_manifest({"model": "tiny"})
    auditlib.record_attribution(tel, {"programs": {"p": {"gflops": 1.0}}})
    manifest = json.loads((tmp_path / "manifest.json").read_text())
    assert manifest["model"] == "tiny"            # merged, not clobbered
    assert manifest["attribution"]["programs"]["p"]["gflops"] == 1.0
    tel.finalize()


# ---------------------------------------------------------------------------
# serving causality: trace ids + the latency split
# ---------------------------------------------------------------------------

def test_serving_trace_causality_and_latency_split():
    from cs744_ddp_tpu.serve import InferenceEngine, MicroBatcher
    tel = Telemetry()
    eng = InferenceEngine("tiny", buckets=(2, 4), seed=0, telemetry=tel)
    eng.startup()
    rng = np.random.default_rng(0)
    img = rng.integers(0, 256, (2, 32, 32, 3), dtype=np.uint8)
    with MicroBatcher(eng, max_wait_ms=1.0, telemetry=tel) as mb:
        futs = [mb.submit(img) for _ in range(5)]
        for f in futs:
            f.result(timeout=30)

    spans = [r for r in tel.records if r["kind"] == "span"]
    enq = {s["trace"] for s in spans if s["name"] == "serve_enqueue"}
    assert len(enq) == 5                         # process-unique ids
    dispatched = set()
    for s in spans:
        if s["name"] == "serve_dispatch":
            assert s["traces"]                   # never an anonymous batch
            dispatched.update(s["traces"])
    assert enq <= dispatched                     # causality: all accounted
    fetch_traces = set()
    for s in spans:
        if s["name"] == "serve_fetch":
            fetch_traces.update(s["traces"])
    assert enq <= fetch_traces

    # Per-request decomposition: queue wait + service time = latency.
    gauges = [r for r in tel.records if r["kind"] == "gauge"]
    by_trace = {}
    for g in gauges:
        if g["name"] in ("serve_latency_ms", "serve_queue_wait_ms",
                         "serve_service_ms"):
            by_trace.setdefault(g["trace"], {})[g["name"]] = g["value"]
    assert enq <= set(by_trace)
    for t in enq:
        rec = by_trace[t]
        assert set(rec) == {"serve_latency_ms", "serve_queue_wait_ms",
                            "serve_service_ms"}
        assert rec["serve_queue_wait_ms"] >= 0
        assert rec["serve_service_ms"] >= 0
        assert rec["serve_queue_wait_ms"] + rec["serve_service_ms"] == \
            pytest.approx(rec["serve_latency_ms"], abs=0.01)


# ---------------------------------------------------------------------------
# events.jsonl rotation: size-aware, read back in order, truncated-tail
# ---------------------------------------------------------------------------

def test_events_rotation_round_trip(tmp_path):
    d = str(tmp_path / "run")
    tel = Telemetry(d, rotate_bytes=256, rotate_keep=3)
    for i in range(40):
        tel.gauge("seq", i)
    tel.finalize()

    names = sorted(os.listdir(d))
    assert "events.jsonl" in names
    assert "events.1.jsonl" in names             # rotation actually fired
    assert sum(n.startswith("events.") for n in names) <= 4  # keep bound

    events, n_bad = read_events_jsonl(os.path.join(d, "events.jsonl"))
    assert n_bad == 0
    seqs = [e["value"] for e in events if e["name"] == "seq"]
    # Oldest-first across the rotated set, ending at the newest write;
    # generations past rotate_keep are the only permitted loss.
    assert seqs == sorted(seqs)
    assert seqs[-1] == 39
    assert len(seqs) == len(set(seqs))

    # A preempted run's torn final line is tolerated, not fatal.
    with open(os.path.join(d, "events.jsonl"), "a") as f:
        f.write('{"kind": "gauge", "name": "seq", "val')
    warnings = []
    events2, n_bad2 = read_events_jsonl(os.path.join(d, "events.jsonl"),
                                        warn=warnings.append)
    assert n_bad2 == 1 and len(warnings) == 1
    assert [e["value"] for e in events2 if e["name"] == "seq"] == seqs


def test_rotation_disabled_and_validation(tmp_path):
    with pytest.raises(ValueError, match="rotate_keep"):
        Telemetry(str(tmp_path / "x"), rotate_keep=0)
    d = str(tmp_path / "run")
    tel = Telemetry(d, rotate_bytes=0)           # rotation off
    for i in range(50):
        tel.gauge("g", i)
    tel.finalize()
    assert sorted(os.listdir(d)) == ["events.jsonl", "summary.json"]


# ---------------------------------------------------------------------------
# telemetry_report: the attribution and traces sections
# ---------------------------------------------------------------------------

def _report_module(monkeypatch):
    monkeypatch.syspath_prepend(os.path.join(REPO, "tools"))
    import telemetry_report
    return telemetry_report


def test_report_renders_attribution_section(tmp_path, monkeypatch):
    telemetry_report = _report_module(monkeypatch)
    (tmp_path / "events.jsonl").write_text("")
    (tmp_path / "manifest.json").write_text(json.dumps({
        "model": "tiny",
        "attribution": {
            "programs": {
                "train/window/ddp": {
                    "gflops": 12.5, "hbm_mib": 420.0, "wire_mib": 0.36,
                    "roofline_bound": "bandwidth",
                    "comm_compute_ratio": 1.52},
                "eval/window": {
                    "gflops": 4.1, "hbm_mib": 130.0, "wire_mib": 0.0,
                    "roofline_bound": "bandwidth",
                    "comm_compute_ratio": 0.0}},
            "measured": {"program": "train/window/ddp",
                         "images_per_sec_per_chip": 176.69,
                         "mfu_vs_bf16_peak": 1e-06,
                         "roofline_bound": "bandwidth"},
            "overlap_vs_ddp": {"overlap_exposed_bytes_upper_bound": 95080,
                               "ddp_chained_bytes": 99400,
                               "hiding_ratio_lower_bound": 1.05}},
    }))
    out = telemetry_report.render(str(tmp_path))
    assert "== attribution (static cost model) ==" in out
    assert "train/window/ddp" in out and "bandwidth" in out
    assert "measured join" in out and "176.69" in out
    assert "hiding ratio >= 1.05" in out
    # Tolerant when absent: older manifests render without the section.
    (tmp_path / "manifest.json").write_text(json.dumps({"model": "tiny"}))
    assert "attribution" not in telemetry_report.render(str(tmp_path))


def test_report_renders_traces_section(tmp_path, monkeypatch):
    telemetry_report = _report_module(monkeypatch)
    d = str(tmp_path / "run")
    tel = Telemetry(d)
    tel.write_manifest({"model": "tiny"})
    with tel.span("serve_enqueue", n=2, trace=1):
        pass
    with tel.span("serve_enqueue", n=2, trace=2):
        pass
    with tel.span("serve_dispatch", bucket=2, n=2, traces=[1, 2]):
        pass
    tel.gauge("serve_queue_wait_ms", 1.5, trace=1)
    tel.gauge("serve_queue_wait_ms", 2.5, trace=2)
    tel.gauge("serve_service_ms", 10.0, trace=1)
    tel.gauge("serve_service_ms", 12.0, trace=2)
    tel.finalize()
    out = telemetry_report.render(d)
    assert "== traces (request causality) ==" in out
    assert "traced requests        2" in out
    assert "1 carrying trace ids" in out
    assert "queue wait" in out and "service time" in out
    # A run with no serving signal renders without the section.
    d2 = str(tmp_path / "run2")
    tel2 = Telemetry(d2)
    tel2.write_manifest({"model": "tiny"})
    tel2.gauge("epoch_time_s", 1.0)
    tel2.finalize()
    assert "traces (request causality)" not in telemetry_report.render(d2)


# ---------------------------------------------------------------------------
# bench: the committed attribution section + the head budget
# ---------------------------------------------------------------------------

def test_committed_bench_full_carries_attribution(tmp_path):
    """BENCH_FULL.json ships the round-8 attribution sheet: cost-model
    records for every zoo program plus the measured join — and the
    section stays in the sidecar, outside the driver's head budget."""
    import bench
    with open(os.path.join(REPO, "BENCH_FULL.json")) as f:
        full = json.load(f)
    attr = full["attribution"]
    progs = attr["programs"]
    assert len(progs) >= 20                      # the whole zoo, not a sample
    assert "train/window/ddp" in progs and "eval/window" in progs
    for rec in progs.values():
        assert rec["roofline_bound"] in ("compute", "bandwidth")
        assert rec["gflops"] >= 0
    meas = attr["measured"]
    assert meas["program"] == "train/window/ddp"
    assert meas["measured_s"] > 0 and meas["mfu_vs_bf16_peak"] > 0
    assert attr["overlap_vs_ddp"]["hiding_ratio_lower_bound"] is not None

    lines = []
    head = bench.emit_result(full, str(tmp_path / "FULL.json"),
                             out=lines.append)
    assert "attribution" not in head
    assert len(lines[-1].encode()) <= bench.HEAD_LINE_BUDGET
    assert json.loads(lines[-1]) == head
