"""Train-to-serve weight hot-swap tests (round 10, cs744_ddp_tpu/publish/).

The pins, mirroring the ISSUE's acceptance bar:

* The CCWB1 bundle round-trips bitwise, and every corruption class —
  flipped payload byte, truncation, trailing garbage, bad magic, torn
  LATEST pointer — is rejected with the failing leaf named: no torn
  bundle is ever installable.
* The publisher is atomic and monotonic: bundle file first, LATEST
  pointer last, versions continue an existing directory's sequence
  across publisher restarts, no tmp litter.
* The watcher validates against each ENGINE's abstract signature (a
  drifted pytree or a wrong-model fingerprint is rejected BEFORE any
  replica is touched — a bad bundle can never desync the AOT ladder).
* The bitwise A/B pin, end to end: train an epoch, publish v1, serve;
  train another epoch, publish v2 mid-serve; every reply's logits are
  bitwise what its tagged model_version computes, requests dispatched
  pre-swap are answered by the old model and post-swap by the new, with
  zero drops, zero duplicate replies, and ZERO recompiles (the
  executable-cache size is unchanged across the swap).
* The wire codec carries model_version end to end (absent -> -1).
* The audit's swap re-certification rung catches a planted baked
  weight: an engine that folds installed weights into its programs must
  fail ``serve_swap/*`` on the baked-constants rule.
* tools/telemetry_report.py renders the ``== publish ==`` section from
  both sides' counters/gauges, absent-safe for runs without publishes.

The chaos-site recovery pins (publish_torn / publish_stale /
swap_mid_batch) live in tests/test_ft.py with the other per-site pins.
"""

import os

import numpy as np

import jax
import pytest

from cs744_ddp_tpu import models as model_zoo
from cs744_ddp_tpu.data import cifar10
from cs744_ddp_tpu.publish import (BundleError, WeightPublisher,
                                   WeightWatcher, bundle_nbytes,
                                   leaf_signature, read_bundle, read_latest,
                                   read_manifest, write_bundle)
from cs744_ddp_tpu.serve import EngineReplica, InferenceEngine, ReplicaRouter
from cs744_ddp_tpu.serve.frontend import decode_reply, encode_reply
from cs744_ddp_tpu.train.loop import Trainer
from cs744_ddp_tpu.train.step import init_train_state

from tinynet import tiny_cnn, tiny_cnn_nobn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def setup_module(module):
    model_zoo.register_model("tiny", tiny_cnn)


@pytest.fixture(scope="module")
def pool():
    return cifar10._synthetic_split(64, seed=5)


def _state(seed):
    init_fn, _ = tiny_cnn()
    return init_train_state(init_fn, jax.random.PRNGKey(seed))


def _leaves():
    return [np.arange(12, dtype=np.float32).reshape(3, 4),
            np.array([1, -2], dtype=np.int32)]


# -- bundle container ---------------------------------------------------------


def test_bundle_roundtrip_bitwise(tmp_path):
    leaves = _leaves()
    path = str(tmp_path / "b.ccwb")
    write_bundle(path, leaves, version=3, treedef="TD",
                 fingerprint={"model": "tiny"})
    man, out = read_bundle(path)
    assert man["version"] == 3 and man["treedef"] == "TD"
    assert man["fingerprint"] == {"model": "tiny"}
    assert bundle_nbytes(man) == sum(l.nbytes for l in leaves)
    assert leaf_signature(out) == leaf_signature(leaves)
    for a, b in zip(leaves, out):
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(a, b)


def test_bundle_rejects_every_corruption_class(tmp_path):
    path = str(tmp_path / "b.ccwb")

    def fresh():
        write_bundle(path, _leaves(), version=1, treedef="TD")
        return os.path.getsize(path)

    # One flipped byte in the LAST leaf's payload: crc fails, leaf named.
    size = fresh()
    with open(path, "r+b") as f:
        f.seek(size - 1)
        b = f.read(1)
        f.seek(size - 1)
        f.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(BundleError, match="leaf 1 crc32 mismatch"):
        read_bundle(path)
    # ... but the manifest alone still parses (staleness peek stays cheap).
    assert read_manifest(path)["version"] == 1

    # Truncation mid-payload: the short leaf is named.
    size = fresh()
    with open(path, "r+b") as f:
        f.truncate(size - 4)
    with pytest.raises(BundleError, match="leaf 1 truncated"):
        read_bundle(path)

    # Trailing garbage after the last leaf.
    fresh()
    with open(path, "ab") as f:
        f.write(b"x")
    with pytest.raises(BundleError, match="trailing bytes"):
        read_bundle(path)

    # Bad magic.
    fresh()
    with open(path, "r+b") as f:
        f.write(b"Z")
    with pytest.raises(BundleError, match="bad magic"):
        read_bundle(path)

    # Torn/malformed LATEST pointer (written atomically, so a malformed
    # one is a real fault, not a race).
    (tmp_path / "LATEST").write_text("{not json")
    with pytest.raises(BundleError, match="malformed LATEST"):
        read_latest(str(tmp_path))
    (tmp_path / "LATEST").write_text('{"version": 1}')
    with pytest.raises(BundleError, match="missing version/file"):
        read_latest(str(tmp_path))


def test_publisher_monotonic_versions_latest_last(tmp_path):
    d = str(tmp_path / "pub")
    assert read_latest(d) is None if os.path.isdir(d) else True
    pub = WeightPublisher(d, fingerprint={"model": "tiny"})
    r1 = pub.publish(_state(1))
    r2 = pub.publish(_state(2))
    assert (r1["version"], r2["version"]) == (1, 2)
    latest = read_latest(d)
    assert latest == {"version": 2, "file": "v000002.ccwb"}
    # A restarted publisher continues the sequence — never re-issues v1.
    assert WeightPublisher(d).publish(_state(3))["version"] == 3
    # tmp+rename left no litter, and both early bundles verify in full.
    assert not [f for f in os.listdir(d) if f.endswith(".tmp")]
    man = read_manifest(os.path.join(d, "v000001.ccwb"))
    assert man["version"] == 1 and man["fingerprint"]["model"] == "tiny"
    read_bundle(os.path.join(d, "v000002.ccwb"))


# -- validation: engine signature is the gate ---------------------------------


def test_engine_install_weights_validates_abstract_signature():
    engine = InferenceEngine("tiny", buckets=(2,), seed=0)
    init_fn, _ = tiny_cnn_nobn()
    alien = init_train_state(init_fn, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="abstract"):
        engine.install_weights(alien.params, alien.bn_state, 1)
    assert engine.weights_version == 0


def test_watcher_rejects_mismatched_bundle(tmp_path):
    replica = EngineReplica(0, model="tiny", buckets=(2,), seed=0)
    replica.startup()
    # A different ARCHITECTURE's weights (the no-BN variant): pytree
    # drift, rejected against the engine's abstract signature.
    d = str(tmp_path / "pub")
    init_fn, _ = tiny_cnn_nobn()
    alien = init_train_state(init_fn, jax.random.PRNGKey(0))
    WeightPublisher(d).publish(alien)
    watcher = WeightWatcher(d, [replica])
    assert watcher.poll_once() == "rejected"
    assert watcher.report()["rejected"] == 1
    assert replica.engine.weights_version == 0
    # The right weights under the wrong model fingerprint: also rejected
    # before any replica is touched.
    d2 = str(tmp_path / "pub2")
    WeightPublisher(d2, fingerprint={"model": "vgg11"}).publish(_state(1))
    watcher2 = WeightWatcher(d2, [replica])
    assert watcher2.poll_once() == "rejected"
    assert replica.engine.weights_version == 0


# -- wire protocol ------------------------------------------------------------


def test_wire_codec_roundtrips_model_version():
    logits = np.arange(10, dtype=np.float32).reshape(1, 10)
    rep = decode_reply(encode_reply(5, {
        "status": "ok", "trace": 9, "logits": logits, "reason": "",
        "queue_wait_ms": 1.0, "service_ms": 2.0, "retry_after_ms": 0.0,
        "model_version": 7}))
    assert rep["model_version"] == 7
    assert np.array_equal(rep["logits"], logits)
    # Replies minted before any install (or error paths) carry -1.
    rep2 = decode_reply(encode_reply(6, {
        "status": "error", "trace": 0, "logits": None, "reason": "x",
        "queue_wait_ms": 0.0, "service_ms": 0.0, "retry_after_ms": 0.0}))
    assert rep2["model_version"] == -1


# -- trainer integration ------------------------------------------------------


def _mini_trainer(tmp_path, seed=3):
    return Trainer(model="tiny", strategy="single", num_devices=1,
                   global_batch=64, data_dir=str(tmp_path), seed=seed,
                   limit_train_batches=2, limit_eval_batches=1,
                   log=lambda s: None)


def test_trainer_publishes_every_k_epochs(tmp_path):
    pub_dir = str(tmp_path / "pub")
    tr = _mini_trainer(tmp_path)
    tr.run(2, publish_dir=pub_dir, publish_every=2)
    latest = read_latest(pub_dir)
    assert latest["version"] == 1          # one publish, after epoch 2
    man = read_manifest(os.path.join(pub_dir, latest["file"]))
    fp = man["fingerprint"]
    assert fp["model"] == "tiny" and fp["global_batch"] == 64
    assert fp["seed"] == 3 and "state_digest" in fp
    assert "state_format_version" in fp
    with pytest.raises(ValueError, match="publish_every"):
        tr.run(1, publish_dir=pub_dir, publish_every=0)


# -- the bitwise A/B pin, end to end ------------------------------------------


def _install_version(engine, pub_dir, version):
    """Install bundle ``version`` into a reference engine through the
    same entry point a live swap uses."""
    _, leaves = read_bundle(os.path.join(pub_dir, f"v{version:06d}.ccwb"))
    _, treedef = jax.tree_util.tree_flatten((engine.params,
                                             engine.bn_state))
    params, bn_state = jax.tree_util.tree_unflatten(treedef, leaves)
    engine.install_weights(params, bn_state, version)


def test_hot_swap_ab_pin_end_to_end(tmp_path, pool):
    pub_dir = str(tmp_path / "pub")
    tr = _mini_trainer(tmp_path)
    tr.run(1, publish_dir=pub_dir)                    # trains + publishes v1
    assert read_latest(pub_dir)["version"] == 1

    replicas = [EngineReplica(i, model="tiny", buckets=(2, 4), seed=0)
                for i in range(2)]
    for r in replicas:
        r.startup()
    watcher = WeightWatcher(pub_dir, replicas)
    assert watcher.poll_once() == "installed"
    exec_sizes = [len(r.engine._exec) for r in replicas]

    router = ReplicaRouter(replicas)
    with router:
        pre = [(pool.images[2 * i:2 * i + 2],
                router.submit(pool.images[2 * i:2 * i + 2], slo_ms=None))
               for i in range(6)]
        pre = [(imgs, f.result(30.0)) for imgs, f in pre]
        tr.run(1, publish_dir=pub_dir)                # epoch 2 -> publishes v2
        assert read_latest(pub_dir)["version"] == 2
        assert watcher.poll_once() == "installed"     # flips at boundaries
        post = [(pool.images[2 * i:2 * i + 2],
                 router.submit(pool.images[2 * i:2 * i + 2], slo_ms=None))
                for i in range(6, 12)]
        post = [(imgs, f.result(30.0)) for imgs, f in post]

    replies = pre + post
    # No drops, no duplicates: 12 requests, 12 ok replies, 12 traces.
    assert [r.status for _, r in replies] == ["ok"] * 12
    assert len({r.trace for _, r in replies}) == 12
    # The A/B pin's ordering half: dispatched pre-swap -> old model,
    # post-swap -> new, per-request via the model_version tag.
    assert [r.model_version for _, r in pre] == [1] * 6
    assert [r.model_version for _, r in post] == [2] * 6
    # Zero recompiles: the executable caches did not grow.
    assert [len(r.engine._exec) for r in replicas] == exec_sizes
    assert watcher.report()["installed_version"] == 2

    # The bitwise half: every reply matches what its TAGGED version
    # computes on the same images, via a reference engine fed each
    # bundle through the same install entry point.
    ref = InferenceEngine("tiny", buckets=(2, 4), seed=0)
    probe = {}
    for v in (1, 2):
        _install_version(ref, pub_dir, v)
        probe[v] = np.asarray(ref.infer_counts(pool.images[:2])[0])
        for imgs, r in replies:
            if r.model_version == v:
                want, _, _ = ref.infer_counts(imgs)
                np.testing.assert_array_equal(r.logits, np.asarray(want))
    # The swap is observable: v1 and v2 genuinely answer differently.
    assert not np.array_equal(probe[1], probe[2])


# -- audit: swap path re-certified weight-agnostic ----------------------------


_BAKED = """\
HloModule {name}

ENTRY main {{
  img = u8[2,32,32,3]{{3,2,1,0}} parameter(1)
  x = f32[2,32,32,3]{{3,2,1,0}} convert(img)
  c = f32[{n}]{{0}} constant({{...}})
  p = f32[{n}]{{0}} parameter(0)
  ROOT o = f32[{n}]{{0}} add(c, p)
}}
"""


class _BakingEngine:
    """Simulates the failure mode the swap-recert rung exists to catch:
    an engine that FOLDS installed weights into its programs as
    constants (so a swap would silently keep serving stale weights)."""

    buckets = (2,)
    model_name = "tiny"
    weights_version = 1

    def __init__(self):
        init_fn, _ = tiny_cnn()
        self.params, self.bn_state = init_fn(jax.random.PRNGKey(0))
        self._baked = False

    def install_weights(self, params, bn_state, version, **kw):
        self.params, self.bn_state = params, bn_state
        self.weights_version = int(version)
        self._baked = True

    def lowered_hlo(self, b, precision):
        # Pre-swap: a small (legitimate) constant.  Post-install: 1.6 MB
        # of baked weights, over the 1 MiB contract.
        n = 400000 if self._baked else 1000
        return _BAKED.format(name=f"serve_b{b}", n=n)


def test_audit_swap_recert_catches_baked_weights():
    from cs744_ddp_tpu.analysis import audit as auditlib
    eng = _BakingEngine()
    reports = auditlib.audit_serving(engine=eng, precision="f32",
                                     swap_recert=True)
    assert eng._baked and eng.weights_version == 2
    pre = [r for r in reports if r.program.startswith("serve/")]
    post = [r for r in reports if r.program.startswith("serve_swap/")]
    assert pre and all(r.passed for r in pre)
    assert post and not any(r.passed for r in post)
    assert {f.rule for r in post for f in r.findings} == {"baked-constants"}


def test_audit_swap_recert_real_engine_stays_clean():
    """The real ladder keeps weights as runtime arguments: the post-swap
    rungs re-lowered after a genuine install must stay constant-lean."""
    from cs744_ddp_tpu.analysis import audit as auditlib
    engine = InferenceEngine("tiny", buckets=(2,), seed=0,
                             use_staging=False,
                             enable_compilation_cache=False)
    reports = auditlib.audit_serving(engine=engine, precision="f32",
                                     swap_recert=True, swap_seed=9)
    assert engine.weights_version == 1
    names = [r.program for r in reports]
    assert "serve/b2/f32" in names and "serve_swap/b2/f32" in names
    assert all(r.passed for r in reports)


# -- telemetry report ---------------------------------------------------------


def test_telemetry_report_publish_section(tmp_path, monkeypatch):
    """Both sides' publish counters/gauges render as the report's
    ``== publish ==`` section; runs with no publish signal render
    without it — absent-safe for older runs."""
    from cs744_ddp_tpu.obs import Telemetry
    monkeypatch.syspath_prepend(os.path.join(REPO, "tools"))
    import telemetry_report

    run = tmp_path / "pubrun"
    tel = Telemetry(out_dir=str(run))
    pub = WeightPublisher(str(tmp_path / "pub"), telemetry=tel,
                          fingerprint={"model": "tiny"})
    replica = EngineReplica(0, model="tiny", buckets=(2,), seed=0)
    replica.startup()
    watcher = WeightWatcher(pub.directory, [replica], telemetry=tel)
    pub.publish(_state(1))
    assert watcher.poll_once() == "installed"
    tel.finalize()
    text = telemetry_report.render(str(run))
    assert "== publish (weight hot-swap) ==" in text
    assert "publish_count" in text and "publish_installed" in text
    assert "swap latency" in text
    assert "published 1" in text and "installed 1" in text

    plain = tmp_path / "plain"
    tel2 = Telemetry(out_dir=str(plain))
    tel2.step(epoch=0, iter=0, loss=1.0, step_time=0.01)
    tel2.finalize()
    assert "== publish" not in telemetry_report.render(str(plain))


def test_hot_swap_lands_at_pipeline_drain_between_pairs(tmp_path, pool):
    """Round 14: with the pipelined worker, a weight flip queued while
    TWO dispatches are in flight lands only at the drain point between
    in-flight pairs — both outstanding batches answer bitwise on the old
    weights, the next dispatch on the new, zero recompiles (the A/B pin
    across a pipelined pair)."""
    import time as _t

    from cs744_ddp_tpu.ft import ChaosPlan

    pub_dir = str(tmp_path / "pub")
    pub = WeightPublisher(pub_dir, fingerprint={"model": "tiny"})
    pub.publish(_state(1))                            # v1
    # slow_replica stalls dispatch 1's ISSUE hook: while it sleeps,
    # dispatch 0 is already in flight, giving the main thread a window
    # to queue the v2 flip with both pipeline slots claimed.
    plan = ChaosPlan.parse(["slow_replica:1:0"])
    rep = EngineReplica(0, model="tiny", buckets=(2, 4), seed=0,
                        chaos=plan, slow_stall_s=1.0, pipeline=True)
    watcher = WeightWatcher(pub_dir, [rep])
    assert watcher.poll_once() == "installed"         # v1 before serving

    # Full-max-bucket requests: one per dispatch, deterministic numbering.
    futs = [rep.scheduler.submit(pool.images[4 * i:4 * i + 4], slo_ms=None)
            for i in range(3)]
    pub.publish(_state(2))                            # v2 on disk, unseen
    rep.start()
    try:
        deadline = _t.time() + 10.0
        while ("slow_replica", 1) not in plan.fired:
            assert _t.time() < deadline, "chaos stall never fired"
            _t.sleep(0.01)
        watcher.poll_once(wait=False)   # queue the flip mid-stall
        replies = [f.result(30.0) for f in futs]
    finally:
        rep.stop()

    # The in-flight pair answered on v1, the post-drain dispatch on v2.
    assert [r.status for r in replies] == ["ok"] * 3
    assert [r.model_version for r in replies] == [1, 1, 2]
    assert rep.engine.weights_version == 2
    # One bucket served three dispatches across the flip on ONE compiled
    # executable: the install swapped weights, never the program.
    assert set(rep.engine._exec) == {(4, "f32")}
    # The bitwise half, against reference engines fed each bundle
    # through the same install entry point.
    ref = InferenceEngine("tiny", buckets=(2, 4), seed=0)
    for v, r in zip((1, 1, 2), replies):
        _install_version(ref, pub_dir, v)
        imgs = pool.images[4 * replies.index(r):4 * replies.index(r) + 4]
        want, _, _ = ref.infer_counts(imgs)
        np.testing.assert_array_equal(r.logits, np.asarray(want))
