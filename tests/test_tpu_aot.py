"""Multi-chip TPU compilation, without TPU hardware: AOT compile-only.

``jax.experimental.topologies`` provides a deviceless v5e-8 topology, so CI
can compile the REAL 8-chip TPU programs (the thing the virtual CPU mesh
cannot check: TPU lowering, ICI collective selection, the compiled
collective schedule) and assert structure on the final HLO.

Notes on what TPU HLO shows (vs the GPU backend): XLA:GPU splits async
collectives into ``all-reduce-start/done`` pairs in the final module; the
TPU backend schedules collectives internally and typically keeps a fused
sync ``all-reduce`` op at this model scale, while splitting collectives it
chooses to overlap (the gather strategy's ``all-gather`` does appear as an
async start/done pair).  Overlap on TPU is the latency-hiding scheduler's
job.

These tests pin the COMPILED cost spectrum — the reference's pedagogical
point, which survives TPU compilation because the strategies' barrier
chains prevent the all-reduce combiner from equalizing the tiers
(strategies.py): per-param stays one collective per leaf, ddp collapses to
one fused variadic collective per ~25 MB bucket.
"""

import re
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cs744_ddp_tpu.models import vgg
from cs744_ddp_tpu.ops import sgd

# AOT-lowering full VGG-11 programs for a v5e-8 mesh costs minutes per test
# on a single CPU compile thread (the session fixture alone ~8 min) — far
# past the tier-1 sweep's budget; run the module with `-m slow`.
pytestmark = pytest.mark.slow
from cs744_ddp_tpu.parallel import get_strategy
from cs744_ddp_tpu.parallel.mesh import DATA_AXIS
from cs744_ddp_tpu.train import step as steplib

from tinynet import tiny_cnn


@pytest.fixture(scope="module")
def v5e8_mesh():
    try:
        from jax.experimental import topologies
        topo = topologies.get_topology_desc("v5e:2x4", platform="tpu")
    except Exception as e:  # no TPU compile-only client in this env
        pytest.skip(f"TPU AOT topology unavailable: {e}")
    return Mesh(np.array(topo.devices), (DATA_AXIS,))


def _lower_step(mesh, model, strategy, batch):
    init_fn, apply_fn = model
    state = steplib.init_train_state(init_fn, jax.random.PRNGKey(0))
    rep = NamedSharding(mesh, P())
    sharded = NamedSharding(mesh, P(DATA_AXIS))
    state_sds = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=rep), state)
    args = (state_sds,
            jax.ShapeDtypeStruct((2,), jnp.uint32, sharding=rep),
            jax.ShapeDtypeStruct((batch, 32, 32, 3), jnp.uint8,
                                 sharding=sharded),
            jax.ShapeDtypeStruct((batch,), jnp.int32, sharding=sharded))
    step = steplib.make_train_step(apply_fn, get_strategy(strategy), mesh,
                                   sgd.SGDConfig(), augment=True)
    return step.lower(*args)


def _compile_step(mesh, model, strategy, batch):
    return _lower_step(mesh, model, strategy, batch).compile().as_text()


def test_vgg11_ddp_compiles_for_v5e8_and_fuses(v5e8_mesh):
    """The flagship config (VGG-11, ddp) must compile for 8 real-topology
    v5e chips, and the compiled program must carry about bucket-count
    (37 MB grads / 25 MB = 2) all-reduces — DDP-grade fusion on TPU (+1
    margin for the step's own scalar-metric psum)."""
    txt = _compile_step(v5e8_mesh, vgg.VGG11(), "ddp", 256)
    n = len(re.findall(r" all-reduce\(", txt))
    assert 1 <= n <= 3, n


def test_vgg11_allreduce_keeps_per_leaf_collectives_on_tpu(v5e8_mesh):
    """Part 2b's deliberately-unfused cost model must SURVIVE TPU
    compilation: the barrier-chained per-param tier keeps (at least) one
    all-reduce per parameter leaf (34 for VGG-11+BN) — without the chain
    XLA's combiner would rewrite it into the ddp tier and erase the cost
    spectrum the reference exists to measure."""
    txt = _compile_step(v5e8_mesh, vgg.VGG11(), "allreduce", 256)
    n = len(re.findall(r" all-reduce\(", txt))
    assert n >= 34, n

    # And the spectrum is ordered: ddp strictly fewer collectives.
    txt_ddp = _compile_step(v5e8_mesh, vgg.VGG11(), "ddp", 256)
    assert len(re.findall(r" all-reduce\(", txt_ddp)) < n


def test_gather_strategy_keeps_two_phase_shape_on_tpu(v5e8_mesh):
    """Part 2a's deliberately-naive root-mediated pattern must SURVIVE TPU
    compilation as two dependent collective phases (gather, then
    mean-broadcast) — and the all-gather phase is scheduled async
    (start/done split), evidence XLA overlaps collectives it can."""
    txt = _compile_step(v5e8_mesh, tiny_cnn(), "gather", 64)
    assert len(re.findall(r"all-gather", txt)) >= 1
    assert len(re.findall(r"all-gather-start", txt)) >= 1  # async split
    assert len(re.findall(r" all-reduce\(", txt)) >= 1     # broadcast phase


def test_collective_chain_depth_pins_latency_shape(v5e8_mesh):
    """The tiers' LATENCY shape, statically (VERDICT r4 item 6): the number
    of collectives forced to run sequentially by data dependencies in the
    pre-optimization HLO, where the strategies' optimization_barrier chains
    are still visible.  Wall-clock can order gather vs allreduce on the CPU
    backend (tests/test_spectrum_wallclock.py) but not allreduce vs ddp
    (barriers are stripped there); this pins all three:

      gather    — 2 dependent collectives per leaf, leaf-chained: 2x34 = 68
                  (``/root/reference/src/Part 2a/main.py:117-127``)
      allreduce — 1 per leaf, leaf-chained: 34 (``Part 2b/main.py:116-119``)
      ddp       — 1 per ~25 MB bucket, buckets independent: 2
                  (``Part 3/main.py:61``)

    A regression that serializes the ddp buckets, de-fuses them (count
    tests above), or lets the combiner collapse a chained tier fails here
    even though the CPU backend cannot measure it."""
    from cs744_ddp_tpu.analysis import collective_chain_depth

    depth = {
        name: collective_chain_depth(
            _lower_step(v5e8_mesh, vgg.VGG11(), name, 256)
            .compiler_ir(dialect="hlo").as_hlo_text())
        for name in ("gather", "allreduce", "ddp")}
    # 34 = VGG-11's trainable leaves (the tier chains one psum per leaf);
    # a tight BAND rather than equality because toolchain bumps have moved
    # the count by the odd loss/metric psum the parser attributes to the
    # chain (VERDICT r5 item 5) — the regression this pins is the chain
    # COLLAPSING (fusion to a handful) or exploding, not +-2 bookkeeping.
    assert 34 <= depth["allreduce"] <= 36, depth
    assert depth["gather"] >= 2 * 34, depth
    # 2 buckets (37 MB / 25 MB) + margin of 1 for the loss/metric psum;
    # strictly below the per-leaf tier either way.
    assert depth["ddp"] <= 3, depth
    assert depth["ddp"] < depth["allreduce"] < depth["gather"], depth


@pytest.mark.slow  # compiles four big models for v5e-8 on one CPU thread
def test_large_zoo_models_compile_for_v5e8(v5e8_mesh):
    """vgg13 (10 BNs), vgg16 (13 BNs), vgg19 (16 BNs), resnet18 (20 BNs)
    and resnet34 (36 BNs) must compile for the 8-chip TPU topology.  Regression lock
    for the round-3 post-main-fusion SIGILL (every model beyond vgg11
    crashed the v5e compiler until the BN backward's fusion fence) — and
    since round 4 the lock covers BOTH fence regimes: every VGG compiles
    UNFENCED (the crash no longer reproduces and unfenced is faster
    there) while the ResNets compile FENCED (faster for them); a compiler
    regression on either path crashes this test loudly.
    models/layers.py::_bn_train_bwd has the full history."""
    from cs744_ddp_tpu.models import resnet

    txt = _compile_step(v5e8_mesh, vgg.VGG13(), "ddp", 64)
    assert " all-reduce(" in txt
    txt = _compile_step(v5e8_mesh, vgg.VGG16(), "ddp", 64)
    assert " all-reduce(" in txt
    txt = _compile_step(v5e8_mesh, vgg.VGG19(), "ddp", 64)
    assert " all-reduce(" in txt
    txt = _compile_step(v5e8_mesh, resnet.ResNet18(), "ddp", 64)
    assert " all-reduce(" in txt
    txt = _compile_step(v5e8_mesh, resnet.ResNet34(), "ddp", 64)
    assert " all-reduce(" in txt
