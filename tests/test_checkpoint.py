"""Checkpoint/resume: bitwise-exact continuation (beyond-parity subsystem).

The reference keeps training state only in memory (no torch.save/load —
SURVEY.md §5).  Here the full TrainState (params, BN running stats, SGD
momentum) persists per completed epoch, and resume is EXACT: the per-epoch
key is fold_in(seed, epoch) and the sampler never reshuffles (C6), so
[0..k) + restore + [k..n) must equal [0..n) in one run, bit for bit.
"""

import numpy as np

import jax

from cs744_ddp_tpu.data import cifar10
from cs744_ddp_tpu.train.loop import Trainer

from tinynet import tiny_cnn


def shrink(tr, n=256):
    tr.train_split = cifar10.Split(tr.train_split.images[:n],
                                   tr.train_split.labels[:n])
    tr.test_split = cifar10.Split(tr.test_split.images[:128],
                                  tr.test_split.labels[:128])


def make(tmp_path, mesh):
    tr = Trainer(model=tiny_cnn(), strategy="ddp", mesh=mesh,
                 global_batch=64, data_dir=str(tmp_path), augment=True,
                 limit_eval_batches=1, log=lambda s: None)
    shrink(tr)
    return tr


def test_resume_is_bitwise_exact(tmp_path, mesh4):
    ckpt = tmp_path / "ckpt"

    # Continuous 3-epoch run (no checkpointing).
    tr_ref = make(tmp_path, mesh4)
    tr_ref.run(3)

    # 2 epochs with checkpointing...
    tr_a = make(tmp_path, mesh4)
    tr_a.run(2, checkpoint_dir=str(ckpt))

    # ...then a FRESH process-equivalent Trainer resumes epoch 2.
    lines = []
    tr_b = make(tmp_path, mesh4)
    tr_b.log = lines.append
    tr_b.run(3, checkpoint_dir=str(ckpt))
    assert any("Resumed from checkpoint: epoch 2" in l for l in lines)

    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        tr_ref.state, tr_b.state)


def test_restore_errors_without_checkpoint(tmp_path, mesh4):
    import pytest
    from cs744_ddp_tpu.train.checkpoint import CheckpointManager
    mngr = CheckpointManager(str(tmp_path / "empty"))
    assert mngr.latest_epoch() is None
    tr = make(tmp_path, mesh4)
    with pytest.raises(FileNotFoundError):
        mngr.restore(tr.state)
    mngr.close()


def test_checkpoint_dir_rejects_foreign_config(tmp_path, mesh4):
    """Reusing a checkpoint dir under a different training config must fail
    loudly, not deep-fail in orbax or silently resume foreign state."""
    import pytest
    ckpt = str(tmp_path / "ckpt")
    tr = make(tmp_path, mesh4)
    tr.run(1, checkpoint_dir=ckpt)

    tr2 = Trainer(model=tiny_cnn(), strategy="allreduce", mesh=mesh4,
                  global_batch=64, data_dir=str(tmp_path), augment=True,
                  limit_eval_batches=1, log=lambda s: None)
    shrink(tr2)
    with pytest.raises(ValueError, match="different training config"):
        tr2.run(2, checkpoint_dir=ckpt)


def test_run_with_all_epochs_checkpointed_logs_and_exits(tmp_path, mesh4):
    ckpt = str(tmp_path / "ckpt")
    tr = make(tmp_path, mesh4)
    tr.run(1, checkpoint_dir=ckpt)
    lines = []
    tr2 = make(tmp_path, mesh4)
    tr2.log = lines.append
    tr2.run(1, checkpoint_dir=ckpt)
    assert any("nothing to run" in l for l in lines)


def test_checkpoint_dir_rejects_different_hyperparameters(tmp_path, mesh4):
    """Resume with a different lr must fail the config guard — a silent
    optimizer swap would break the bitwise-exact-resume contract."""
    import pytest
    from cs744_ddp_tpu.ops import sgd
    ckpt = str(tmp_path / "ckpt")
    tr = make(tmp_path, mesh4)
    tr.run(1, checkpoint_dir=ckpt)

    tr2 = Trainer(model=tiny_cnn(), strategy="ddp", mesh=mesh4,
                  global_batch=64, data_dir=str(tmp_path), augment=True,
                  sgd_cfg=sgd.SGDConfig(lr=0.001), limit_eval_batches=1,
                  log=lambda s: None)
    shrink(tr2)
    with pytest.raises(ValueError, match="different training config"):
        tr2.run(2, checkpoint_dir=ckpt)


def test_unstamped_checkpoint_dir_accepted_as_current_version(tmp_path,
                                                              mesh4):
    """Dirs written before the state_format_version stamp existed hold the
    version-2 structure (the 1->2 change predates the stamp), so a missing
    stamp must be accepted as the current version — a one-time migration —
    rather than refusing resume (ADVICE r4)."""
    import json
    import os
    ckpt = str(tmp_path / "ckpt")
    tr = make(tmp_path, mesh4)
    tr.run(1, checkpoint_dir=ckpt)
    state_after_1 = jax.tree.map(np.asarray, tr.state)

    # Strip the stamp, simulating a pre-stamp dir.
    cfg_path = os.path.join(ckpt, "trainer_config.json")
    with open(cfg_path) as f:
        cfg = json.load(f)
    del cfg["state_format_version"]
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)

    lines = []
    tr2 = make(tmp_path, mesh4)
    tr2.log = lines.append
    tr2.run(2, checkpoint_dir=ckpt)  # must resume, not raise
    # Resume actually happened (a silent fresh start would also train, so
    # the log line is the discriminating evidence) and training continued.
    assert any("Resumed from checkpoint: epoch 1" in l for l in lines), lines
    d = max(
        float(np.max(np.abs(a - np.asarray(b)))) if a.size else 0.0
        for a, b in zip(jax.tree.leaves(state_after_1),
                        jax.tree.leaves(jax.tree.map(np.asarray, tr2.state))))
    assert d > 0.0  # trained past the restored epoch
    # The one-time migration stamped the dir.
    with open(cfg_path) as f:
        assert json.load(f)["state_format_version"] == 2
