"""Checkpoint/resume: bitwise-exact continuation (beyond-parity subsystem).

The reference keeps training state only in memory (no torch.save/load —
SURVEY.md §5).  Here the full TrainState (params, BN running stats, SGD
momentum) persists per completed epoch, and resume is EXACT: the per-epoch
key is fold_in(seed, epoch) and the sampler never reshuffles (C6), so
[0..k) + restore + [k..n) must equal [0..n) in one run, bit for bit.
"""

import numpy as np

import jax

from cs744_ddp_tpu.data import cifar10
from cs744_ddp_tpu.train.loop import Trainer

from tinynet import tiny_cnn


def shrink(tr, n=256):
    tr.train_split = cifar10.Split(tr.train_split.images[:n],
                                   tr.train_split.labels[:n])
    tr.test_split = cifar10.Split(tr.test_split.images[:128],
                                  tr.test_split.labels[:128])


def make(tmp_path, mesh):
    tr = Trainer(model=tiny_cnn(), strategy="ddp", mesh=mesh,
                 global_batch=64, data_dir=str(tmp_path), augment=True,
                 limit_eval_batches=1, log=lambda s: None)
    shrink(tr)
    return tr


def test_resume_is_bitwise_exact(tmp_path, mesh4):
    ckpt = tmp_path / "ckpt"

    # Continuous 3-epoch run (no checkpointing).
    tr_ref = make(tmp_path, mesh4)
    tr_ref.run(3)

    # 2 epochs with checkpointing...
    tr_a = make(tmp_path, mesh4)
    tr_a.run(2, checkpoint_dir=str(ckpt))

    # ...then a FRESH process-equivalent Trainer resumes epoch 2.
    lines = []
    tr_b = make(tmp_path, mesh4)
    tr_b.log = lines.append
    tr_b.run(3, checkpoint_dir=str(ckpt))
    assert any("Resumed from checkpoint: epoch 2" in l for l in lines)

    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        tr_ref.state, tr_b.state)


def test_restore_errors_without_checkpoint(tmp_path, mesh4):
    import pytest
    from cs744_ddp_tpu.train.checkpoint import CheckpointManager
    mngr = CheckpointManager(str(tmp_path / "empty"))
    assert mngr.latest_epoch() is None
    tr = make(tmp_path, mesh4)
    with pytest.raises(FileNotFoundError):
        mngr.restore(tr.state)
    mngr.close()


def test_checkpoint_dir_rejects_foreign_config(tmp_path, mesh4):
    """Reusing a checkpoint dir under a different training config must fail
    loudly, not deep-fail in orbax or silently resume foreign state."""
    import pytest
    ckpt = str(tmp_path / "ckpt")
    tr = make(tmp_path, mesh4)
    tr.run(1, checkpoint_dir=ckpt)

    tr2 = Trainer(model=tiny_cnn(), strategy="allreduce", mesh=mesh4,
                  global_batch=64, data_dir=str(tmp_path), augment=True,
                  limit_eval_batches=1, log=lambda s: None)
    shrink(tr2)
    with pytest.raises(ValueError, match="different training config"):
        tr2.run(2, checkpoint_dir=ckpt)


def test_run_with_all_epochs_checkpointed_logs_and_exits(tmp_path, mesh4):
    ckpt = str(tmp_path / "ckpt")
    tr = make(tmp_path, mesh4)
    tr.run(1, checkpoint_dir=ckpt)
    lines = []
    tr2 = make(tmp_path, mesh4)
    tr2.log = lines.append
    tr2.run(1, checkpoint_dir=ckpt)
    assert any("nothing to run" in l for l in lines)


def test_checkpoint_dir_rejects_different_hyperparameters(tmp_path, mesh4):
    """Resume with a different lr must fail the config guard — a silent
    optimizer swap would break the bitwise-exact-resume contract."""
    import pytest
    from cs744_ddp_tpu.ops import sgd
    ckpt = str(tmp_path / "ckpt")
    tr = make(tmp_path, mesh4)
    tr.run(1, checkpoint_dir=ckpt)

    tr2 = Trainer(model=tiny_cnn(), strategy="ddp", mesh=mesh4,
                  global_batch=64, data_dir=str(tmp_path), augment=True,
                  sgd_cfg=sgd.SGDConfig(lr=0.001), limit_eval_batches=1,
                  log=lambda s: None)
    shrink(tr2)
    with pytest.raises(ValueError, match="different training config"):
        tr2.run(2, checkpoint_dir=ckpt)


def test_unstamped_checkpoint_dir_accepted_as_current_version(tmp_path,
                                                              mesh4):
    """Dirs written before the state_format_version stamp existed hold the
    version-2 structure (the 1->2 change predates the stamp).  For a
    stateless strategy that IS the current structure (the 2->3 bump only
    added ``SGDState.comm``, an empty pytree when stateless), so a missing
    stamp must be accepted — a one-time migration — rather than refusing
    resume (ADVICE r4)."""
    import json
    import os
    ckpt = str(tmp_path / "ckpt")
    tr = make(tmp_path, mesh4)
    tr.run(1, checkpoint_dir=ckpt)
    state_after_1 = jax.tree.map(np.asarray, tr.state)

    # Strip the stamp, simulating a pre-stamp dir.
    cfg_path = os.path.join(ckpt, "trainer_config.json")
    with open(cfg_path) as f:
        cfg = json.load(f)
    del cfg["state_format_version"]
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)

    lines = []
    tr2 = make(tmp_path, mesh4)
    tr2.log = lines.append
    tr2.run(2, checkpoint_dir=ckpt)  # must resume, not raise
    # Resume actually happened (a silent fresh start would also train, so
    # the log line is the discriminating evidence) and training continued.
    assert any("Resumed from checkpoint: epoch 1" in l for l in lines), lines
    d = max(
        float(np.max(np.abs(a - np.asarray(b)))) if a.size else 0.0
        for a, b in zip(jax.tree.leaves(state_after_1),
                        jax.tree.leaves(jax.tree.map(np.asarray, tr2.state))))
    assert d > 0.0  # trained past the restored epoch
    # The one-time migration stamped the dir as the CURRENT version (the
    # stateless v2 structure is leaf-for-leaf the v3 structure).
    from cs744_ddp_tpu.train.checkpoint import STATE_FORMAT_VERSION
    with open(cfg_path) as f:
        assert json.load(f)["state_format_version"] == STATE_FORMAT_VERSION


def test_unstamped_dir_rejected_for_stateful_strategy(tmp_path, mesh4):
    """The 2->3 migration is CONDITIONAL: a stateful (compressed) strategy
    stores error-feedback state in ``SGDState.comm``, so its structure is
    genuinely version 3 — an unstamped (v2-structured) dir must still be
    refused rather than deep-failing inside orbax on a structure
    mismatch."""
    import json
    import os
    import pytest
    ckpt = str(tmp_path / "ckpt")
    tr = Trainer(model=tiny_cnn(), strategy="compress-bf16", mesh=mesh4,
                 global_batch=64, data_dir=str(tmp_path), augment=True,
                 limit_eval_batches=1, log=lambda s: None)
    shrink(tr)
    tr.run(1, checkpoint_dir=ckpt)

    cfg_path = os.path.join(ckpt, "trainer_config.json")
    with open(cfg_path) as f:
        cfg = json.load(f)
    del cfg["state_format_version"]
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)

    tr2 = Trainer(model=tiny_cnn(), strategy="compress-bf16", mesh=mesh4,
                  global_batch=64, data_dir=str(tmp_path), augment=True,
                  limit_eval_batches=1, log=lambda s: None)
    shrink(tr2)
    with pytest.raises(ValueError, match="state-format version"):
        tr2.run(2, checkpoint_dir=ckpt)
    # A rejected resume never modifies the dir's metadata.
    with open(cfg_path) as f:
        assert "state_format_version" not in json.load(f)


# -- round 6: elastic metadata forward/backward compatibility ----------------
#
# Backward: checkpoints written BEFORE the elastic layer carry no topology
# metadata and must restore as world=1 with a one-time warning.  Forward:
# the round-6 sidecars must not break old-style (non-elastic) resume, and
# the elastic config guard relaxes exactly the world/global-batch keys.

def _elastic_make(tmp_path, world, *, ft=None, log=None):
    import cs744_ddp_tpu.train.loop as looplib
    from cs744_ddp_tpu.parallel import make_mesh
    assert looplib.WINDOW == 3, "callers must monkeypatch WINDOW first"
    return Trainer(model=tiny_cnn(), strategy="allreduce",
                   mesh=make_mesh(world), global_batch=64,
                   data_dir=str(tmp_path), seed=3, augment=True,
                   limit_train_batches=6, limit_eval_batches=1,
                   log=log or (lambda s: None), ft=ft, elastic="strong")


def test_pre_elastic_mid_epoch_checkpoint_resumes_world1_warns(
        tmp_path, monkeypatch):
    import json
    import os

    import pytest

    import cs744_ddp_tpu.train.loop as looplib
    from cs744_ddp_tpu.elastic import protocol as protolib
    from cs744_ddp_tpu.ft import ChaosPlan, FTConfig
    monkeypatch.setattr(looplib, "WINDOW", 3)

    ck = str(tmp_path / "ck")
    tr1 = _elastic_make(tmp_path, 1,
                        ft=FTConfig(chaos=ChaosPlan.parse(["preempt:3"])))
    tr1.run(1, checkpoint_dir=ck)
    assert tr1.preempted is True

    # Rewrite the mid-epoch sidecar into its pre-round-6 shape: resume
    # keys only, no world/global_batch/rank_keys.
    meta_path = os.path.join(ck, "mid_epoch_meta.json")
    with open(meta_path) as f:
        meta = json.load(f)
    order = meta["data_order"]
    meta["data_order"] = {k: order[k] for k in
                          ("seed", "epoch", "step", "reshuffle_each_epoch")}
    with open(meta_path, "w") as f:
        json.dump(meta, f)

    monkeypatch.setattr(protolib, "_warned_missing_world", False)
    lines = []
    tr2 = _elastic_make(tmp_path, 1, log=lines.append)
    with pytest.warns(UserWarning, match="no world size"):
        tr2.run(1, checkpoint_dir=ck)
    assert any("Resumed from mid-epoch checkpoint: epoch 0, step 3" in l
               for l in lines)
    assert tr2.resume_plan.old_world == 1          # the compat default
    assert tr2.resume_plan.start_step == 3

    # Bitwise vs a never-interrupted run of the same elastic config.
    tr0 = _elastic_make(tmp_path, 1)
    tr0.run(1)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        tr2.state, tr0.state)


def test_elastic_checkpoint_readable_by_non_elastic_trainer(tmp_path,
                                                            monkeypatch):
    """Forward direction: the round-6 epoch sidecar rides ALONGSIDE the
    state — an old-style (non-elastic) trainer of the same config resumes
    it without noticing."""
    import cs744_ddp_tpu.train.loop as looplib
    monkeypatch.setattr(looplib, "WINDOW", 3)

    ck = str(tmp_path / "ck")
    tr1 = _elastic_make(tmp_path, 1)
    tr1.run(1, checkpoint_dir=ck)

    from cs744_ddp_tpu.parallel import make_mesh
    lines = []
    tr2 = Trainer(model=tiny_cnn(), strategy="allreduce",
                  mesh=make_mesh(1), global_batch=64,
                  data_dir=str(tmp_path), seed=3, augment=True,
                  limit_train_batches=6, limit_eval_batches=1,
                  log=lines.append)
    tr2.run(2, checkpoint_dir=ck)                  # must resume, not raise
    assert any("Resumed from checkpoint: epoch 1" in l for l in lines)


def test_elastic_config_guard_frees_world_nonelastic_still_rejects(
        tmp_path, monkeypatch):
    import pytest

    import cs744_ddp_tpu.train.loop as looplib
    monkeypatch.setattr(looplib, "WINDOW", 3)

    ck = str(tmp_path / "ck")
    tr1 = _elastic_make(tmp_path, 2)
    tr1.run(1, checkpoint_dir=ck)

    # Elastic manager: a world change is exactly what resume is FOR.
    lines = []
    tr2 = _elastic_make(tmp_path, 1, log=lines.append)
    tr2.run(2, checkpoint_dir=ck)
    assert any("Resumed from checkpoint: epoch 1" in l for l in lines)

    # Non-elastic manager over the same dir: the world key is back in the
    # config equality, so the mismatch fails loudly.
    from cs744_ddp_tpu.parallel import make_mesh
    tr3 = Trainer(model=tiny_cnn(), strategy="allreduce",
                  mesh=make_mesh(1), global_batch=64,
                  data_dir=str(tmp_path), seed=3, augment=True,
                  limit_train_batches=6, limit_eval_batches=1,
                  log=lambda s: None)
    with pytest.raises(ValueError, match="different training config"):
        tr3.run(2, checkpoint_dir=ck)
