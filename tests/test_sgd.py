"""SGD parity vs torch.optim.SGD(lr=0.1, momentum=0.9, weight_decay=1e-4) —
the reference's exact optimizer (/root/reference/src/Part 1/main.py:114-115).
"""

import numpy as np
import torch

import jax
import jax.numpy as jnp

from cs744_ddp_tpu.ops import sgd


def test_sgd_matches_torch_over_many_steps():
    rng = np.random.default_rng(0)
    w0 = rng.normal(size=(7, 5)).astype(np.float32)
    b0 = rng.normal(size=(5,)).astype(np.float32)

    tw = torch.nn.Parameter(torch.from_numpy(w0.copy()))
    tb = torch.nn.Parameter(torch.from_numpy(b0.copy()))
    topt = torch.optim.SGD([tw, tb], lr=0.1, momentum=0.9, weight_decay=1e-4)

    params = {"w": jnp.asarray(w0), "b": jnp.asarray(b0)}
    state = sgd.init(params)
    cfg = sgd.SGDConfig(lr=0.1, momentum=0.9, weight_decay=1e-4)

    for step in range(10):
        gw = rng.normal(size=w0.shape).astype(np.float32)
        gb = rng.normal(size=b0.shape).astype(np.float32)
        topt.zero_grad()
        tw.grad = torch.from_numpy(gw.copy())
        tb.grad = torch.from_numpy(gb.copy())
        topt.step()
        params, state = sgd.update(
            params, {"w": jnp.asarray(gw), "b": jnp.asarray(gb)}, state, cfg)
        np.testing.assert_allclose(np.asarray(params["w"]),
                                   tw.detach().numpy(), atol=1e-5,
                                   err_msg=f"step {step} w")
        np.testing.assert_allclose(np.asarray(params["b"]),
                                   tb.detach().numpy(), atol=1e-5,
                                   err_msg=f"step {step} b")


def test_sgd_no_momentum_no_wd():
    params = {"w": jnp.ones((3,))}
    state = sgd.init(params)
    cfg = sgd.SGDConfig(lr=0.5, momentum=0.0, weight_decay=0.0)
    grads = {"w": jnp.full((3,), 2.0)}
    params, state = sgd.update(params, grads, state, cfg)
    np.testing.assert_allclose(np.asarray(params["w"]), 0.0)


def test_sgd_is_jittable():
    params = {"w": jnp.ones((4, 4))}
    state = sgd.init(params)
    jitted = jax.jit(lambda p, g, s: sgd.update(p, g, s))
    p2, s2 = jitted(params, {"w": jnp.ones((4, 4))}, state)
    assert p2["w"].shape == (4, 4)
