"""Benchmark-harness validation on the 8-virtual-device CPU mesh.

The real numbers come from the TPU run the driver performs (bench.py on the
bench host); what CI validates is the HARNESS: the strategy x model matrix
and the 1..N-device scaling sweep produce well-formed, internally-consistent
results (VERDICT r1 item 3).
"""

import json
import os

import numpy as np
import pytest

import bench
from cs744_ddp_tpu import models as model_zoo

from tinynet import tiny_cnn


def setup_module(module):
    model_zoo.register_model("tiny", tiny_cnn)


@pytest.mark.slow  # ~10 min: full matrix + sweep + convergence epochs
def test_bench_matrix_and_sweep_wellformed(tmp_path, monkeypatch):
    monkeypatch.setenv("CIFAR_DATA_DIR", str(tmp_path))
    # Shrink the synthetic dataset: the bench uses EPOCH-LENGTH windows, and
    # a 781-batch epoch per dispatch on the 1-core CPU mesh costs ~18 min of
    # wall-clock for zero extra coverage of the harness under test.
    from cs744_ddp_tpu.data import cifar10
    monkeypatch.setattr(cifar10, "TRAIN_SIZE", 64 * 12)
    monkeypatch.setattr(cifar10, "TEST_SIZE", 256)
    result = bench.run_bench(matrix=True, sweep=True, max_iters=8,
                             global_batch=64, models=("tiny",),
                             strategies=("allreduce", "ddp"),
                             deep_rows=(("tiny", "gather"),),
                             spectrum_deep_rows=(("tiny", "gather"),),
                             headline_model="tiny",
                             peak_batch_candidates=(8, 16),
                             serving_kwargs=dict(
                                 buckets=(2, 4, 8), loads=(50.0,),
                                 n_requests=20, startup_probe=False),
                             log=lambda s: None)
    # Driver contract head.
    assert result["metric"] == "cifar10_tiny_images_per_sec_per_chip"
    assert result["unit"] == "images/sec/chip"
    assert result["value"] > 0
    assert result["vs_baseline"] > 0
    assert result["num_devices"] == 8

    # Headline statistics: N runs with best/median/min, best == value.
    hs = result["headline_stats"]
    assert len(hs["runs"]) == bench.HEADLINE_RUNS
    assert hs["min"] <= hs["median"] <= hs["best"] == result["value"]

    # Strategy x model matrix: one positive entry per pair, plus the
    # deep-model rows appended beyond the cross (VERDICT r4 item 7; the
    # real run's deep_rows are vgg19/ddp and resnet34/ddp) and one bf16
    # row for the last deep pair at the parity batch.
    assert set(result["matrix"]) == {"tiny/allreduce", "tiny/ddp",
                                     "tiny/gather", "tiny/gather/bf16"}
    assert all(v["images_per_sec_per_chip"] > 0
               for v in result["matrix"].values())
    assert result["matrix"]["tiny/gather/bf16"]["precision"] == "bf16"

    # Peak entry: bf16 frontier config, well-formed and positive.
    assert result["peak"]["images_per_sec_per_chip"] > 0
    assert "bf16" in result["peak"]["config"]

    # Host-pipeline entry: chunked windowed --host-augment throughput,
    # tracked so the round-5 7.9x win cannot silently regress (BASELINE.md).
    hp = result["host_pipeline"]
    assert hp["images_per_sec_per_chip"] > 0
    assert hp["host_chunks"] >= 1
    # Chunk sweep covers the default K plus the 1/2/8 controls (K=1 is
    # round 5's whole-window staging), each a positive rate.
    assert set(hp["chunk_sweep"]) == {str(hp["host_chunks"]), "1", "2", "8"}
    assert all(v > 0 for v in hp["chunk_sweep"].values())
    # Link floor: the pure-device_put ceiling, both byte distributions
    # (real-entropy leg comes from the committed tests/assets fixture).
    lf = hp["link_floor"]
    assert lf["synthetic"]["floor_images_per_sec_per_chip"] > 0
    assert lf["real_entropy"]["floor_images_per_sec_per_chip"] > 0
    assert 0 < lf["real_entropy"]["unique_mib"] < lf["buffer_mib"]
    # Attached in-memory telemetry summary: the section trains real epochs,
    # so step events and host_augment/chunk_put/chunk_wait spans must be
    # there (chunk_put replaced prefetch_put for full batches in this PR).
    hts = hp["telemetry_summary"]
    assert hts["num_steps"] > 0
    assert "host_augment" in hts["spans"]
    assert "chunk_put" in hts["spans"]
    assert "chunk_wait" in hts["spans"]

    # Convergence entries: the reference's own correctness signal (VERDICT
    # r4 item 3).  At THIS test's shrunken 768-image scale the round-7
    # recalibrated task (data/cifar10.py) leaves accuracy near the chance
    # floor — too few samples per class/template to generalize — so here
    # only the SHAPE of the entries is checked (losses still fall).  The
    # graded LEARNING oracle runs at its calibrated 12.8k-image scale in
    # test_bench_convergence_oracle_graded below.
    conv = result["convergence"]
    assert conv["real_data"] is False   # tmp_path has no CIFAR pickles
    assert len(conv["per_epoch"]) == 3
    accs = [e["test_accuracy_pct"] for e in conv["per_epoch"]]
    losses = [e["train_loss_last"] for e in conv["per_epoch"]]
    assert all(0.0 <= a <= 100.0 for a in accs)
    assert losses[0] > losses[-1], losses  # train loss falls across epochs
    assert conv["test_accuracy_pct"] == accs[-1]
    assert conv["test_avg_loss"] > 0
    # Attached telemetry summary: 3 epochs x 12 batches of step events,
    # with steady-state percentiles ordered as percentiles must be.
    ts = conv["telemetry_summary"]
    assert ts["num_steps"] == len(conv["per_epoch"]) * 12
    if ts["num_steady_steps"]:
        stt = ts["steady_step_time_s"]
        assert stt["p50"] <= stt["p95"] <= stt["p99"] <= stt["max"]
    # Stable-lr companion: shape-checked only at this scale (see the
    # comment above conv; the >=2x-chance floor moved to the dedicated
    # oracle test at the calibrated dataset size).
    st = conv["stable_lr"]
    assert 0.0 <= st["test_accuracy_pct"] <= 100.0
    assert st["test_avg_loss"] >= 0 and st["train_loss_last"] >= 0

    # Serving section: ladder startup + per-bucket curve + open-loop
    # latency entry (full serving behavior is pinned in tests/test_serve.py;
    # here the subject is the section's shape inside the bench artifact).
    sv = result["serving"]
    assert sv["model"] == "tiny"
    assert set(sv["throughput_vs_bucket"]) == {"2", "4", "8"}
    for e in sv["throughput_vs_bucket"].values():
        assert e["images_per_sec"] > 0
        assert e["per_dispatch_ms"] > 0 and e["device_program_ms"] > 0
    assert sv["latency"]["50rps"]["completed"] > 0
    assert "serve_dispatch" in sv["telemetry_summary"]["spans"]

    # Hot-swap section (round 10): a steady row plus rolling/all-at-once
    # swap rows replaying the SAME trace while bundles land mid-stream.
    # Full swap behavior (A/B pin, torn rejection) is pinned in
    # tests/test_publish.py; here the subject is the section's shape and
    # its two CI contracts — every request answered and ZERO recompiles.
    hw = result["hotswap"]
    assert hw["model"] == "servenet" and hw["replicas"] == 2
    assert hw["steady"]["replies"] > 0 and hw["steady"]["unresolved"] == 0
    for name in ("rolling", "all_at_once"):
        row = hw[name]
        assert row["rolling"] is (name == "rolling")
        assert row["installs"] == row["publishes"] == 3
        assert row["installed_version"] == 3
        assert set(row["weights_versions"]) == {3}
        assert row["swap_samples"] == 3 * hw["replicas"]
        assert 0 < row["swap_ms_p50"] <= row["swap_ms_p99"] \
            <= row["swap_ms_max"]
        assert len(row["in_flight_at_publish"]) == 3
        assert row["recompiles"] == 0
        assert row["replies"] == hw["steady"]["replies"]
        assert row["unresolved"] == 0
        assert isinstance(row["goodput_dip_pct"], float)  # noise can be <0
    assert hw["zero_recompiles"] is True

    # Compression section (round 7): per-tier measured wall-clock, static
    # comm bytes from the audited lowering, and convergence delta vs the
    # uncompressed allreduce baseline.
    comp = result["compression"]
    assert comp["world"] == 8 and comp["baseline_tier"] == "allreduce"
    assert set(comp["per_tier"]) == set(bench.COMPRESSION_TIERS)
    for e in comp["per_tier"].values():
        assert e["wall_clock_s_best"] > 0
        assert e["images_per_sec_per_chip"] > 0
        assert e["comm_result_mib"] > 0
        assert 0.0 <= e["test_accuracy_pct"] <= 100.0
        assert -100.0 <= e["convergence_delta_pct"] <= 100.0
    ratio = {t: comp["per_tier"][t]["comm_ratio_vs_allreduce"]
             for t in comp["per_tier"]}
    # The contract floors, measured on the lowering (aux collectives — BN
    # pmeans, loss psum, int8's scale pmax — keep these just under the
    # pure-gradient 2x/4x; powersgd's analytic ratio on tiny is ~2.4x,
    # >=8x only on VGG-11-shaped leaves).
    assert ratio["allreduce"] == 1.0
    assert ratio["ddp"] >= 0.99 and ratio["overlap"] >= 0.99
    assert ratio["compress-bf16"] > 1.9
    assert ratio["compress-int8"] > 3.5
    assert ratio["powersgd"] > 1.9

    # Scaling sweep: 1,2,4,8 devices; WEAK scaling (constant per-chip
    # batch); efficiency is per-chip relative to the 1-device run and must
    # be finite/positive; 1-device eff == 1.
    sc = result["scaling"]
    assert sc["protocol"] == "weak scaling, 64 images/chip"
    assert set(sc["images_per_sec_per_chip"]) == {"1", "2", "4", "8"}
    eff = sc["efficiency_vs_1chip"]
    assert eff["1"] == 1.0
    assert all(v > 0 for v in eff.values())
    assert set(sc["mfu_vs_bf16_peak"]) == {"1", "2", "4", "8"}

    # Strong scaling: the reference's own protocol (global batch fixed),
    # reported alongside weak (ADVICE r3 item 4).
    st = sc["strong"]
    assert set(st["images_per_sec"]) == {"1", "2", "4", "8"}
    assert st["efficiency_vs_1chip"]["1"] == 1.0
    assert all(v > 0 for v in st["efficiency_vs_1chip"].values())

    # Spectrum: static collective stats from the v5e-8 AOT lowering (may be
    # absent only where the TPU AOT client is unavailable).
    if "spectrum" in result:
        per = result["spectrum"]["per_strategy"]
        assert set(per) == {"gather", "allreduce", "ddp"}
        # The tiers' cost shapes, exactly as strategies.py constructs them:
        # gather pays an all-gather per leaf; allreduce strictly more
        # collectives than ddp (fusion); gather's result bytes amplified by
        # world x vs the reduced tensors.
        assert per["gather"]["ops"]["all-gather"]["count"] >= 1
        assert per["allreduce"]["total_count"] > per["ddp"]["total_count"]
        assert per["gather"]["total_result_mib"] > \
            per["allreduce"]["total_result_mib"]
        # Deep-model rows (real run: resnet34 allreduce+ddp) ride in their
        # own sub-dict so per_strategy keeps its tier-only shape.
        deep = result["spectrum"]["deep_rows"]
        assert set(deep) == {"tiny/gather"}
        assert deep["tiny/gather"]["total_count"] >= 1
        assert deep["tiny/gather"]["grad_mib"] > 0

    # Emission contract: full payload (stdout line + sidecar) first, the
    # compact head LAST — the driver JSON-parses the final line of a
    # ~2000-byte stdout tail, which the full payload overflowed in rounds
    # 4/5 ("parsed": null in BENCH_r04/r05.json).
    import json
    sidecar = tmp_path / "BENCH_FULL.json"
    lines = []
    head = bench.emit_result(result, str(sidecar), out=lines.append)
    assert len(lines) == 2
    assert json.loads(lines[0]) == result                 # full, first
    assert json.loads(lines[1]) == head                   # head, LAST
    assert len(lines[1]) <= bench.HEAD_LINE_BUDGET
    assert head["full_payload_file"] == "BENCH_FULL.json"
    assert head["value"] == result["value"]
    assert head["headline_stats"] == result["headline_stats"]
    assert json.loads(sidecar.read_text()) == result      # auditable copy


@pytest.mark.slow  # ~3 min: 4 tiny-model epochs at the calibrated scale
def test_bench_convergence_oracle_graded(tmp_path, monkeypatch):
    """The CI learning floor, re-derived for the round-7 recalibrated
    synthetic task (satellite of the serving PR; data/cifar10.py knob
    comments + BASELINE.md "Synthetic-task recalibration (round 7)").

    Pinned at the reference's own Part-1 semantics — ONE worker,
    ``single`` strategy — because that is where the recalibration is
    defined: under this mesh's 8-way ddp the per-shard BN batch is 8 and
    the lr-0.1 trajectory sits at chance (measured 9.77/9.77/9.77 at
    global batch 64 and 14.1/11.7/15.6 at 256), an artifact of the
    virtual mesh, not of the task.  At the calibrated 12.8k-image scale
    the reference config must show a GRADED trajectory — rising epoch
    over epoch, above chance, below the label-noise ceiling (measured:
    16.02 / 32.03 / 34.57%, losses 2.2517 / 2.0924 / 1.9515) — and the
    stable-lr companion must clear 2.5x chance in one epoch (measured:
    50.00% single / 50.39% ddp).  Floors carry ~2x margin against
    seed/toolchain drift."""
    monkeypatch.setenv("CIFAR_DATA_DIR", str(tmp_path))
    from cs744_ddp_tpu.data import cifar10
    monkeypatch.setattr(cifar10, "TRAIN_SIZE", 64 * 200)
    monkeypatch.setattr(cifar10, "TEST_SIZE", 256)
    from cs744_ddp_tpu.ops import sgd as _sgd

    # Reference config (lr 0.1, SGD 0.1/0.9/1e-4 — Trainer default),
    # 3 epochs: the graded trajectory itself.
    tr = bench._make_trainer("tiny", "single", 1, global_batch=64,
                             data_dir=str(tmp_path), log=lambda s: None)
    assert tr.real_data is False
    accs, losses = [], []
    for ep in range(3):
        timers = tr.train_model(ep)
        _, _, acc = tr.test_model()
        accs.append(acc)
        losses.append(timers.losses[-1])
    # Graded: learning is under way but NOT saturated.
    assert accs[-1] > accs[0], accs          # rises across the window
    assert accs[-1] >= 20.0, accs            # >= 2x the 10% chance floor
    assert accs[-1] <= 90.0, accs            # below the ~91% noise ceiling
    assert losses[0] > losses[-1], losses    # train loss falls too

    # Stable-lr companion (bench.py's convergence section records the
    # same pair): decisively above chance after ONE epoch.
    tr2 = bench._make_trainer("tiny", "single", 1, global_batch=64,
                              data_dir=str(tmp_path), log=lambda s: None,
                              sgd_cfg=_sgd.SGDConfig(lr=0.01))
    tr2.train_model(0)
    _, _, acc2 = tr2.test_model()
    assert acc2 >= 25.0, acc2                # half the measured 50%


def test_matrix_pairs_prunes_world1_strategy_cross():
    models = ("vgg11", "resnet18")
    strategies = ("gather", "allreduce", "ddp")
    deep = (("vgg19", "ddp"), ("resnet34", "ddp"))
    # Multi-chip: the full cross plus the deep rows, in order.
    assert bench._matrix_pairs(8, models, strategies, deep) == \
        [(m, s) for m in models for s in strategies] + list(deep)
    # world=1: every strategy's sync is a no-op, so the cross is pruned to
    # one strategy per model (BASELINE.md "1-chip strategy matrix").
    assert bench._matrix_pairs(1, models, strategies, deep) == \
        [("vgg11", "ddp"), ("resnet18", "ddp"),
         ("vgg19", "ddp"), ("resnet34", "ddp")]
    # No "ddp" on offer -> the first offered strategy is kept; deep rows
    # already in the cross are not duplicated.
    assert bench._matrix_pairs(1, ("vgg11",), ("gather",),
                               (("vgg11", "gather"),)) == \
        [("vgg11", "gather")]


def test_emit_result_contract_and_head_budget(tmp_path, capsys):
    result = {"metric": "m", "value": 1.5, "unit": "u", "vs_baseline": 2.0,
              "num_devices": 8,
              "headline_stats": {"runs": [1.5], "best": 1.5},
              "tflops_per_sec": 0.5, "mfu_vs_bf16_peak": 0.01,
              "matrix": {"big": "x" * 4000}}   # bulk the head must exclude
    sidecar = tmp_path / "FULL.json"
    head = bench.emit_result(result, str(sidecar))   # default out=print
    cap = capsys.readouterr().out.strip().splitlines()
    assert len(cap) == 2
    assert json.loads(cap[0]) == result               # full payload first
    assert json.loads(cap[-1]) == head                # compact head LAST
    assert len(cap[-1]) <= bench.HEAD_LINE_BUDGET
    assert head["full_payload_file"] == "FULL.json"
    assert "matrix" not in head
    assert json.loads(sidecar.read_text()) == result  # auditable sidecar
    # A head that cannot fit the driver's tail capture must fail loudly
    # instead of reintroducing the r04/r05 parsed-null failure.
    huge = dict(result, metric="m" * 2 * bench.HEAD_LINE_BUDGET)
    with pytest.raises(RuntimeError, match="budget"):
        bench.emit_result(huge, str(sidecar), out=lambda s: None)


def test_emit_head_budget_worst_case_with_serving(tmp_path):
    """Satellite of the serving PR: a worst-case result — every head field
    at realistic maximal width PLUS a fat ``serving`` section — must still
    emit a FINAL stdout line within the driver budget that JSON-parses
    standalone.  Pins that growing the full payload (new sections) cannot
    regress the r04/r05 parsed-null failure: bulk rides in the sidecar, the
    head's size is a function of CONTRACT_KEYS alone."""
    serving = {
        "backend": "tpu", "model": "vgg11",
        "buckets": [1, 8, 32, 128, 256], "precision": "f32",
        "ladder_startup": {"startup_s": 123.4567, "per_bucket": {
            str(b): {"seconds": 23.4567, "source": "compile"}
            for b in (1, 8, 32, 128, 256)}, "warm": False},
        "throughput_vs_bucket": {str(b): {
            "per_dispatch_ms": 104.321, "device_program_ms": 4.321,
            "images_per_sec": 59259.26, "reps": 20}
            for b in (1, 8, 32, 128, 256)},
        "latency": {f"{rps}rps": {
            "n_requests": 200, "offered_rps": rps, "completed": 200,
            "rejected": 0, "latency_ms": {
                "p50": 105.123, "p95": 230.456, "p99": 480.789,
                "mean": 131.415, "max": 512.161}}
            for rps in (5.0, 20.0, 80.0)},
        "startup": {"method": "subprocess", "cold_s": 240.1234,
                    "warm_s": 3.4567, "warm_lt_half_cold": True},
        "telemetry_summary": {"spans": {"serve_dispatch": {
            "count": 999999, "total_s": 12345.6789}},
            "padding": "x" * 2000},
    }
    result = {
        "metric": "cifar10_vgg11_images_per_sec_per_chip",
        "value": 123456.78, "unit": "images/sec/chip",
        "vs_baseline": 3173.95, "num_devices": 256,
        "headline_stats": {"runs": [123456.78, 123400.12, 123399.99],
                           "best": 123456.78, "median": 123400.12,
                           "min": 123399.99},
        "tflops_per_sec": 123.45, "mfu_vs_bf16_peak": 0.6266,
        "serving": serving,
        "matrix": {"bulk": "y" * 4000},
    }
    lines = []
    head = bench.emit_result(result, str(tmp_path / "FULL.json"),
                             out=lines.append)
    final = lines[-1]
    assert len(final.encode()) <= bench.HEAD_LINE_BUDGET
    parsed = json.loads(final)               # standalone-parseable
    assert parsed == head
    assert parsed["value"] == result["value"]
    assert "serving" not in parsed           # bulk stays in the sidecar
    assert parsed["full_payload_file"] == "FULL.json"
    assert json.loads((tmp_path / "FULL.json").read_text())["serving"] \
        == serving


def test_emit_head_budget_with_committed_serving_load(tmp_path):
    """Rounds 9/10: the committed BENCH_FULL.json now carries the fat
    ``serving_load`` section (replica-scaling rows, goodput curve,
    overload telemetry summary) and the ``hotswap`` section (swap
    latency, in-flight samples, goodput dip).  Re-emitting that REAL
    artifact must still produce a final stdout line within the driver
    budget — the new sections ride in the sidecar, never the head."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(repo, "BENCH_FULL.json")) as f:
        result = json.load(f)
    assert "serving_load" in result
    assert "hotswap" in result
    # The committed swap rows honor the section's two CI contracts.
    assert result["hotswap"]["zero_recompiles"] is True
    for name in ("rolling", "all_at_once"):
        assert result["hotswap"][name]["unresolved"] == 0
    # Round 12: the tracing section honors ITS contracts — capacity
    # with tracing on within the 5% overhead budget, and the committed
    # two-process run reconstructed complete skew-corrected waterfalls.
    tracing = result["tracing"]
    assert tracing["capacity"]["within_budget"] is True
    assert tracing["capacity"]["overhead_frac"] <= 0.05
    two = tracing["two_process"]
    assert two["complete"] > 0
    assert any(p["skew_pairs"] > 0 for p in two["skew"].values())
    assert two["aggregate_wall_s"] < 10.0
    # Round 14: the dispatch-pipeline section honors ITS contracts —
    # pipelined capacity beats the committed round-9 figure, runtime
    # occupancy stays within the static two-slot bound, and the
    # bucket-8 dispatch tax shrank from the round-12 figure.
    pipe = result["pipeline"]
    assert pipe["capacity"]["beats_round9"] is True
    assert pipe["capacity"]["capacity_rps_on"] \
        > pipe["capacity"]["round9_capacity_rps"] == 441.6
    wf = pipe["waterfall"]
    assert wf["inflight_bound_ok"] is True
    assert wf["max_inflight"] <= 2
    b8 = wf["cost_prior"]["by_bucket"]["8"]["measured_over_prior"]
    assert b8 < 3.254          # the round-12 dispatch-tax figure
    # Round 20: the memory section honors ITS contracts — every zoo
    # program certified under the v5e budget, the compiled differential
    # clean (static >= XLA's temp+output floor, within band), and the
    # K-epoch planner table concrete and rising with the mesh.
    mem = result["memory"]
    assert mem["max_peak"]["peak_mib"] <= mem["budget_mib"]
    assert all(v <= mem["budget_mib"]
               for v in mem["peak_mib_by_program"].values())
    assert mem["compiled_check"]["clean"] is True
    assert mem["compiled_check"]["static_peak_mib"] \
        >= mem["compiled_check"]["compiled_floor_mib"]
    per_world = mem["planner"]["per_world"]
    ks = [per_world[w]["max_k"] for w in ("1", "2", "8")]
    assert ks == sorted(ks) and ks[0] > 0
    assert all(per_world[w]["mega_round_trips"] == 2 for w in per_world)
    lines = []
    head = bench.emit_result(result, str(tmp_path / "FULL.json"),
                             out=lines.append)
    final = lines[-1]
    assert len(final.encode()) <= bench.HEAD_LINE_BUDGET
    parsed = json.loads(final)
    assert parsed == head
    assert "serving_load" not in parsed
    assert "hotswap" not in parsed
    assert "tracing" not in parsed
    assert "pipeline" not in parsed
    assert "memory" not in parsed
    assert json.loads((tmp_path / "FULL.json").read_text()) == result


def test_bench_require_real_data_gate(tmp_path, monkeypatch):
    # No pickle batches under the data dir -> refuse before measuring.
    monkeypatch.setenv("CIFAR_DATA_DIR", str(tmp_path))
    with pytest.raises(SystemExit, match="require-real-data"):
        bench.main(["--require-real-data"])
    # The committed CIFAR fixture satisfies the gate; with run_bench
    # stubbed, main() emits per contract into --full-out.
    monkeypatch.setenv("CIFAR_DATA_DIR",
                       os.path.join(os.path.dirname(__file__), "assets"))
    monkeypatch.setattr(bench, "run_bench", lambda **kw: {
        "metric": "m", "value": 1.0, "unit": "u", "vs_baseline": 1.0,
        "num_devices": 1, "headline_stats": {"runs": [1.0]}})
    monkeypatch.setattr(bench, "_enable_compilation_cache", lambda: None)
    out = tmp_path / "SIDE.json"
    bench.main(["--require-real-data", "--full-out", str(out)])
    assert json.loads(out.read_text())["metric"] == "m"


def test_measure_link_floor_both_legs():
    """Fast harness check on the CPU mesh: both byte-distribution legs
    present and positive (the numbers only mean something on tpu — the
    backend label records that)."""
    lf = bench.measure_link_floor(lambda s: None, global_batch=64, ndev=8,
                                  trials=1)
    assert lf["backend"] == "cpu"
    assert lf["synthetic"]["floor_images_per_sec_per_chip"] > 0
    assert lf["synthetic"]["mib_per_s"] > 0
    real = lf["real_entropy"]   # committed tests/assets fixture
    assert real["floor_images_per_sec_per_chip"] > 0
    assert 0 < real["unique_mib"] < lf["buffer_mib"]


@pytest.mark.slow  # ~60s: two full-model cost analyses
def test_step_flops_per_image_is_world_invariant(tmp_path, mesh1, mesh8):
    """FLOPs/image must not depend on the mesh size: cost_analysis()
    reports the PER-DEVICE SPMD partition, so dividing by the global batch
    under-reports by ~world x (caught in round-3 review; on a real v5e-8
    this would have printed ~4% MFU instead of ~31%)."""
    from cs744_ddp_tpu.train.loop import Trainer

    def flops(mesh, strategy):
        tr = Trainer(model=tiny_cnn(), strategy=strategy, mesh=mesh,
                     global_batch=64, data_dir=str(tmp_path), augment=False,
                     log=lambda s: None)
        return tr.step_flops_per_image()

    f1 = flops(mesh1, "single")
    f8 = flops(mesh8, "ddp")
    if f1 is None or f8 is None:
        import pytest
        pytest.skip("backend offers no cost analysis")
    # Collectives/layout differ slightly between the programs; the bug this
    # pins was a factor-of-world (8x) error, far outside this band.
    assert 0.5 < f8 / f1 < 2.0, (f1, f8)


# -- CI artifact guard: committed BENCH_r*.json heads stay parseable ----------
#
# The driver captures bench.py's final stdout line as "parsed"; rounds 4/5
# shipped oversized heads the driver recorded as parsed:null (the failure
# emit_result now prevents).  Round 7 backfilled those two heads from the
# artifacts' own truncated tails + the round commits' BASELINE/VERDICT
# prose (the backfill is labeled in a "reconstructed" field), so the guard
# now holds unconditionally: EVERY committed round artifact must carry a
# parsed head with a non-null headline.


def test_committed_bench_artifacts_parse_with_headline():
    import glob
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    arts = sorted(glob.glob(os.path.join(repo, "BENCH_r*.json")))
    assert arts, "no committed BENCH_r*.json artifacts found"
    for path in arts:
        name = os.path.basename(path)
        with open(path) as f:
            art = json.load(f)                     # every artifact is JSON
        assert art["rc"] == 0, f"{name}: bench run failed"
        parsed = art.get("parsed")
        assert isinstance(parsed, dict), f"{name}: head did not parse"
        assert parsed.get("value"), f"{name}: null/zero headline value"
        assert parsed.get("metric"), f"{name}: missing headline metric"
    # The round-4/5 backfills carry their provenance.
    for name in ("BENCH_r04.json", "BENCH_r05.json"):
        with open(os.path.join(repo, name)) as f:
            head = json.load(f)["parsed"]
        assert "backfilled" in head["reconstructed"]
        assert head["headline_stats"]["best"] == head["value"]


def test_bench_full_sidecar_carries_elastic_section_slot():
    """BENCH_FULL.json (the bulk sidecar) parses and remains a dict — the
    run_elastic section merges there on the next bench run."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(repo, "BENCH_FULL.json")) as f:
        full = json.load(f)
    assert isinstance(full, dict) and full


# -- run_elastic: the elastic bench section is well-formed --------------------

def test_run_elastic_section_wellformed(tmp_path, monkeypatch):
    import cs744_ddp_tpu.train.loop as looplib
    from cs744_ddp_tpu.utils import metrics
    monkeypatch.setattr(looplib, "WINDOW", 3)
    monkeypatch.setattr(metrics, "WINDOW", 3)

    out = bench.run_elastic(lambda s: None, headline_model="tiny", ndev=2,
                            global_batch=64, data_dir=str(tmp_path),
                            max_iters=6)
    assert out["protocol"] == "strong"
    assert out["microshards"] == 4
    assert out["world"] == 2 and out["global_batch"] == 64

    sh = out["shrink"]
    assert (sh["from_world"], sh["to_world"]) == (2, 1)
    assert sh["death_step"] == 3                   # lim//2 on the WINDOW grid
    # Strong scaling: the step counter carries over, so only the
    # interrupted window is re-executed.
    assert sh["steps_lost"] == 0
    assert sh["coordinator_recovery_s"] >= 0
    assert sh["total_run_s"] > 0

    assert out["grow"]["to_world"] == 2
    assert out["grow"]["resume_run_s"] > 0

    dt = out["degraded_throughput"]
    assert dt["world1_images_per_sec"] > 0
    assert dt["world2_images_per_sec"] > 0
    assert dt["degraded_fraction"] > 0
