"""Serving-tier tests (round 9): continuous-batching SLO scheduler,
replica router, socket front-end (cs744_ddp_tpu/serve/) — all tier-1 CPU.

The pins, mirroring the ISSUE's acceptance bar:

* ``admit()`` is pure and deterministic — the same seeded trace replays
  to the identical plan (dispatches AND shed set), sheds the lowest tier
  earliest-to-miss first, and never sheds a high-tier request while a
  lower-tier batchmate could be deferred instead (the priority-inversion
  negative test).
* The virtual-time planners: continuous batching holds strictly lower
  p99 queue-wait than the micro-batcher's drain policy at matched load.
* The threaded scheduler accounts deadline misses (ok vs late vs shed)
  and backpressures with a QueueFull retry-after hint.
* The router places on the least-loaded live replica, falls through on
  QueueFull, and on replica death fails over every unfinished request —
  no accepted request is ever silently dropped (chaos ``replica_death``
  through real device-pinned engines).
* The socket front-end round-trips the wire protocol: served logits are
  BITWISE what the engine computes, overload replies carry the
  retry-after hint.
"""

import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

from cs744_ddp_tpu import models as model_zoo
from cs744_ddp_tpu.data import cifar10
from cs744_ddp_tpu.ft import ChaosPlan
from cs744_ddp_tpu.serve import (EngineReplica, FrontendClient,
                                 InferenceEngine, LoopbackClient, QueueFull,
                                 ReplicaRouter, ServiceModel, ServingFrontend,
                                 SLOScheduler, admit, make_request,
                                 plan_continuous, plan_drain,
                                 virtual_requests)
from cs744_ddp_tpu.serve.demo import synthetic_load_trace
from cs744_ddp_tpu.serve.frontend import (decode_reply, decode_request,
                                          encode_reply, encode_request)

from tinynet import tiny_cnn


def setup_module(module):
    model_zoo.register_model("tiny", tiny_cnn)


@pytest.fixture(scope="module")
def pool():
    return cifar10._synthetic_split(64, seed=5)


@pytest.fixture(scope="module")
def engine():
    model_zoo.register_model("tiny", tiny_cnn)
    return InferenceEngine("tiny", buckets=(2, 4, 8), seed=0)


# -- pure admission policy ----------------------------------------------------


def _vreq(n, tier, deadline, seq, t_arrival=0.0):
    reqs = virtual_requests([(t_arrival, n, tier, 0)])
    r = reqs[0]
    r.deadline = deadline
    r.seq = seq
    return r


def test_admit_determinism_over_seeded_trace():
    trace = synthetic_load_trace(300, offered_rps=800.0, seed=7)
    predict = {1: 0.001, 8: 0.004, 32: 0.012, 128: 0.04, 256: 0.07}.get
    buckets = (1, 8, 32, 128, 256)
    a = plan_continuous(virtual_requests(trace), buckets=buckets,
                        predict_s=predict)
    b = plan_continuous(virtual_requests(trace), buckets=buckets,
                        predict_s=predict)
    assert a == b                      # dispatches, records, shed set — all
    assert a["served"] + len(a["shed"]) == len(trace)


def test_admit_sheds_lowest_tier_earliest_miss_first():
    # Everyone predicted to miss, nobody deferrable: the shed order must
    # be lowest tier (largest tier number) first, earliest deadline first.
    pending = [_vreq(1, 0, 0.5, seq=1), _vreq(1, 1, 0.45, seq=2),
               _vreq(1, 1, 0.4, seq=3)]
    adm = admit(pending, 0.0, buckets=(4,), predict_s=lambda b: 1.0)
    assert adm.batch == ()
    assert [(r.seq, reason) for r, reason in adm.shed] == \
        [(3, "predicted_miss"), (2, "predicted_miss"), (1, "predicted_miss")]


def test_admit_sheds_already_late_with_reason():
    pending = [_vreq(1, 0, -1.0, seq=1), _vreq(1, 0, 10.0, seq=2)]
    adm = admit(pending, 0.0, buckets=(4,), predict_s=lambda b: 0.01)
    assert [r.seq for r in adm.batch] == [2]
    assert [(r.seq, reason) for r, reason in adm.shed] == [(1, "deadline")]
    # shed=False: late requests dispatch anyway.
    pending = [_vreq(1, 0, -1.0, seq=1)]
    adm = admit(pending, 0.0, buckets=(4,), predict_s=lambda b: 0.01,
                shed=False)
    assert [r.seq for r in adm.batch] == [1] and adm.shed == ()


def test_admit_defers_bulk_to_save_tight_slo():
    # A 20-image background request packs the batch into the slow 32
    # bucket and would drag the interactive request past its deadline.
    # admit() must DEFER the bulk (leave it queued — not shed) and
    # dispatch the tight request in the fast bucket.
    predict = {1: 0.01, 8: 0.02, 32: 0.5}.get
    tight = _vreq(1, 0, 0.1, seq=1)
    bulk = _vreq(20, 2, 10.0, seq=2)
    adm = admit([tight, bulk], 0.0, buckets=(1, 8, 32), predict_s=predict)
    assert adm.batch == (tight,)
    assert adm.bucket == 1
    assert adm.shed == ()              # deferred, not shed
    assert adm.predicted_done == pytest.approx(0.01)


def test_no_priority_inversion_under_overload():
    # Tiered overload: tier-0 traffic alone is schedulable by
    # construction (its 200ms SLO exceeds the 140ms worst case of one
    # in-flight dispatch plus its own — both <=70ms in this service
    # model), bulk tier-2 oversubscribes the ladder.  Whatever is shed,
    # it is never tier 0.
    trace = synthetic_load_trace(
        400, offered_rps=1500.0, seed=11,
        tiers=((0, 1, 200.0), (2, 9, 300.0)))
    predict = {1: 0.001, 8: 0.004, 32: 0.012, 128: 0.04, 256: 0.07}.get
    plan = plan_continuous(virtual_requests(trace),
                           buckets=(1, 8, 32, 128, 256), predict_s=predict)
    assert len(plan["shed"]) > 0       # genuinely overloaded
    assert all(tier == 2 for _trace, tier, _reason in plan["shed"])
    t0 = [rec for rec in plan["records"] if rec["tier"] == 0]
    assert t0 and all(rec["status"] == "ok" for rec in t0)


def test_continuous_beats_drain_p99_at_matched_load():
    trace = synthetic_load_trace(400, offered_rps=900.0, seed=3,
                                 tiers=((0, 1, 0),))   # no deadlines
    predict = {1: 0.001, 8: 0.004, 32: 0.012, 128: 0.04, 256: 0.07}.get
    buckets = (1, 8, 32, 128, 256)
    cont = plan_continuous(virtual_requests(trace), buckets=buckets,
                           predict_s=predict, shed=False)
    drain = plan_drain(virtual_requests(trace), buckets=buckets,
                       predict_s=predict)
    assert cont["served"] == drain["served"] == len(trace)
    assert cont["p99_wait_ms"] < drain["p99_wait_ms"]


def test_service_model_prior_and_ewma():
    svc = ServiceModel((2, 4, 8), anchor_s=1e-3)
    # Prior: anchored at the smallest bucket, scaled by weight (= size).
    assert svc.predict(2) == pytest.approx(1e-3)
    assert svc.predict(8) == pytest.approx(4e-3)
    # One observation re-anchors every bucket through the weight ratio.
    svc.observe(4, 0.010)
    assert svc.predict(4) == pytest.approx(0.010)
    assert svc.predict(8) == pytest.approx(0.020)
    # EWMA, not last-sample.
    svc.observe(4, 0.020)
    assert 0.010 < svc.predict(4) < 0.020
    snap = svc.snapshot()
    assert set(snap) == {2, 4, 8}
    with pytest.raises(ValueError, match="missing buckets"):
        ServiceModel((2, 4), weights={2: 1.0})


# -- threaded scheduler -------------------------------------------------------


class StubEngine:
    """Engine stand-in: fixed service sleep, zero logits, dispatch log."""

    def __init__(self, buckets=(1, 2, 4), service_s=0.0, fail_at=None):
        self.buckets = tuple(buckets)
        self.max_batch = self.buckets[-1]
        self.service_s = service_s
        self.fail_at = fail_at
        self.calls = []
        self.gate = None

    def infer_counts(self, images, labels=None, *, precision="f32",
                     trace_ids=None):
        if self.fail_at is not None and len(self.calls) >= self.fail_at:
            raise RuntimeError("stub engine exploded")
        self.calls.append(int(images.shape[0]))
        if self.gate is not None:
            self.gate.wait(5.0)
        if self.service_s:
            time.sleep(self.service_s)
        return np.zeros((images.shape[0], 10), np.float32), 0, 0


def _imgs(n):
    return np.zeros((n, 32, 32, 3), np.uint8)


def test_scheduler_deadline_miss_accounting():
    # shed=False so late requests are SERVED and reported late.
    eng = StubEngine(service_s=0.05)
    with SLOScheduler(eng, shed=False) as sched:
        late = sched.submit(_imgs(1), slo_ms=1.0)
        ok = sched.submit(_imgs(1), slo_ms=10_000.0)
        r_late, r_ok = late.result(5.0), ok.result(5.0)
    assert r_late.status == "late" and r_ok.status == "ok"
    assert r_ok.logits.shape == (1, 10)
    for r in (r_late, r_ok):
        assert r.queue_wait_ms >= 0.0
        assert r.latency_ms == pytest.approx(
            r.queue_wait_ms + r.service_ms, abs=1.0)


def test_scheduler_sheds_doomed_requests():
    eng = StubEngine(service_s=0.05)
    with SLOScheduler(eng, shed=True) as sched:
        gate_first = sched.submit(_imgs(1), slo_ms=10_000.0)
        doomed = sched.submit(_imgs(1), slo_ms=0.001)  # already late
        r = doomed.result(5.0)
    assert r.status == "shed" and r.reason in ("deadline", "predicted_miss")
    assert gate_first.result(5.0).status == "ok"


def test_scheduler_queuefull_retry_hint():
    # Unstarted scheduler: nothing drains, so the bounded queue fills and
    # the QueueFull carries a positive backlog-derived retry hint.
    eng = StubEngine(buckets=(1, 2, 4))
    sched = SLOScheduler(eng, max_queue_images=4)
    sched.submit(_imgs(4), slo_ms=None)
    with pytest.raises(QueueFull) as ei:
        sched.submit(_imgs(2), slo_ms=None)
    assert ei.value.retry_after_ms > 0.0
    assert sched.queue_depth() == 4


# -- router -------------------------------------------------------------------


class StubSched:
    """Bare scheduler stand-in for routing-policy tests."""

    class _Eng:
        max_batch = 8

    def __init__(self, replica, outstanding=0.0, alive=True, full=False):
        self.engine = self._Eng()
        self.replica = replica
        self.buckets = (8,)
        self.svc = ServiceModel((8,))
        self.alive = alive
        self.full = full
        self._outstanding = outstanding
        self.got = []
        self.on_death = None

    def outstanding_s(self):
        return self._outstanding

    def enqueue(self, req):
        if self.full:
            raise QueueFull(f"stub {self.replica} full",
                            retry_after_ms=10.0 * (self.replica + 1))
        self.got.append(req)
        return req.future


def test_router_routes_least_loaded_with_fallthrough():
    scheds = [StubSched(0, 0.3), StubSched(1, 0.1), StubSched(2, 0.2)]
    router = ReplicaRouter(scheds)
    router.submit(_imgs(1))
    assert [len(s.got) for s in scheds] == [0, 1, 0]
    # Least-loaded now full: falls through to the next by load.
    scheds[1].full = True
    router.submit(_imgs(1))
    assert [len(s.got) for s in scheds] == [0, 1, 1]
    # Everyone full: QueueFull with the SMALLEST hint across replicas.
    for s in scheds:
        s.full = True
    with pytest.raises(QueueFull) as ei:
        router.submit(_imgs(1))
    assert ei.value.retry_after_ms == pytest.approx(10.0)
    # Nobody alive: explicit error, not a hang.
    for s in scheds:
        s.full, s.alive = False, False
    with pytest.raises(RuntimeError, match="no live replicas"):
        router.submit(_imgs(1))


def test_router_ties_break_by_replica_index():
    scheds = [StubSched(0, 0.0), StubSched(1, 0.0)]
    router = ReplicaRouter(scheds)
    for _ in range(3):
        router.submit(_imgs(1))
    assert [len(s.got) for s in scheds] == [3, 0]


def test_router_failover_resolves_every_request():
    # Replica 0's engine dies on its FIRST dispatch while more requests
    # are queued behind it: every unfinished request (in-flight AND
    # queued) must fail over to replica 1 and resolve ok — zero silent
    # drops, zero errors.
    dead_eng = StubEngine(service_s=0.02, fail_at=0)
    live_eng = StubEngine(service_s=0.0)
    s0 = SLOScheduler(dead_eng, replica=0)
    s1 = SLOScheduler(live_eng, replica=1)
    router = ReplicaRouter([s0, s1])
    with router:
        futs = [router.submit(_imgs(1), slo_ms=None) for _ in range(10)]
        replies = [f.result(10.0) for f in futs]
    assert [r.status for r in replies] == ["ok"] * 10
    assert all(r.replica == 1 for r in replies)
    assert len({r.trace for r in replies}) == 10
    stats = router.stats()
    assert stats["failovers"] >= 1
    assert not s0.alive


def test_replica_death_chaos_failover_end_to_end(pool):
    # Real device-pinned engines; chaos kills replica 0 at its first
    # dispatch; the router fails over and every request still gets its
    # logits.  (``replica_death:0:0`` = dispatch 0 of replica 0.)
    model_zoo.register_model("tiny", tiny_cnn)
    chaos = ChaosPlan.parse(["replica_death:0:0"])
    replicas = [EngineReplica(i, model="tiny", buckets=(2, 4), seed=0,
                              chaos=chaos)
                for i in range(2)]
    router = ReplicaRouter(replicas)
    with router:
        futs = [router.submit(pool.images[i:i + 2], slo_ms=None)
                for i in range(8)]
        replies = [f.result(30.0) for f in futs]
        assert not replicas[0].alive and replicas[1].alive
    assert [r.status for r in replies] == ["ok"] * 8
    assert all(r.logits.shape == (2, 10) for r in replies)
    assert len({r.trace for r in replies}) == 8
    assert router.stats()["failovers"] >= 1


# -- wire protocol + socket e2e ----------------------------------------------


def test_slow_replica_chaos_stalls_but_serves(pool):
    # ``slow_replica:0:0`` stalls replica 0's first dispatch (a straggling
    # chip): the request is served — slower, never dropped — and the
    # stall shows up in the measured latency the router's EWMA feeds on.
    model_zoo.register_model("tiny", tiny_cnn)
    chaos = ChaosPlan.parse(["slow_replica:0:0"])
    replica = EngineReplica(0, model="tiny", buckets=(2,), seed=0,
                            chaos=chaos, slow_stall_s=0.15)
    router = ReplicaRouter([replica])
    with router:
        rep = router.submit(pool.images[:2], slo_ms=None).result(30.0)
    assert rep.status == "ok"
    assert ("slow_replica", 0) in chaos.fired
    assert rep.service_ms >= 150.0


def test_wire_codec_roundtrip(pool):
    imgs = pool.images[:3]
    payload = encode_request(7, imgs, tier=2, slo_ms=125.0)
    req_id, out, tier, slo = decode_request(payload)
    assert (req_id, tier, slo) == (7, 2, 125.0)
    assert np.array_equal(out, imgs)
    logits = np.arange(30, dtype=np.float32).reshape(3, 10)
    rep = decode_reply(encode_reply(7, {
        "status": "ok", "trace": 99, "logits": logits, "reason": "",
        "queue_wait_ms": 1.5, "service_ms": 2.5, "retry_after_ms": 0.0}))
    assert rep["status"] == "ok" and rep["trace"] == 99
    assert np.array_equal(rep["logits"], logits)
    assert rep["queue_wait_ms"] == 1.5 and rep["service_ms"] == 2.5


def test_socket_e2e_logits_bitwise(engine, pool):
    imgs = pool.images[:2]
    direct, _, _ = engine.infer_counts(imgs)
    with SLOScheduler(engine) as sched:
        with ServingFrontend(sched) as fe:
            with FrontendClient(fe.address, timeout=30.0) as client:
                rep = client.request(imgs, slo_ms=None)
    assert rep["status"] == "ok" and rep["trace"] > 0
    assert np.array_equal(rep["logits"], np.asarray(direct))


def test_socket_pipelined_out_of_order_replies(engine, pool):
    with SLOScheduler(engine) as sched:
        with ServingFrontend(sched) as fe:
            with FrontendClient(fe.address, timeout=30.0) as client:
                futs = [client.submit(pool.images[i:i + 1], slo_ms=None)
                        for i in range(6)]
                reps = [f.result(30.0) for f in futs]
    assert all(r["status"] == "ok" for r in reps)
    assert len({r["trace"] for r in reps}) == 6


class FullBackend:
    def submit(self, images, labels=None, *, tier=0, slo_ms=None):
        raise QueueFull("full", retry_after_ms=42.0)


def test_socket_overload_reply_carries_retry_hint():
    with ServingFrontend(FullBackend()) as fe:
        with FrontendClient(fe.address, timeout=10.0) as client:
            rep = client.request(_imgs(1))
    assert rep["status"] == "overload" and rep["reason"] == "queue_full"
    assert rep["retry_after_ms"] == pytest.approx(42.0)


def test_loopback_overload_is_reply_not_exception():
    client = LoopbackClient(FullBackend())
    rep = client.request(_imgs(1))
    assert rep["status"] == "overload"
    assert rep["retry_after_ms"] == pytest.approx(42.0)


def test_telemetry_report_slo_section(tmp_path, monkeypatch):
    """The scheduler's per-request gauges/counters render as the report's
    ``== slo ==`` section (tiered attainment, shed reasons); a run with
    no SLO signal renders without it — absent-safe for older runs."""
    import os
    from cs744_ddp_tpu.obs import Telemetry
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    monkeypatch.syspath_prepend(os.path.join(repo, "tools"))
    import telemetry_report

    served = tmp_path / "served"
    tel = Telemetry(out_dir=str(served))
    eng = StubEngine(service_s=0.01)
    with SLOScheduler(eng, telemetry=tel) as sched:
        ok = sched.submit(_imgs(1), tier=0, slo_ms=10_000.0)
        shed = sched.submit(_imgs(1), tier=2, slo_ms=0.001)
        ok.result(5.0), shed.result(5.0)
    tel.finalize()
    text = telemetry_report.render(str(served))
    assert "== slo (tiered attainment) ==" in text
    assert "tier 0" in text and "tier 2" in text
    assert "shed by reason" in text

    plain = tmp_path / "plain"
    tel2 = Telemetry(out_dir=str(plain))
    tel2.step(epoch=0, iter=0, loss=1.0, step_time=0.01)
    tel2.finalize()
    assert "== slo" not in telemetry_report.render(str(plain))


def test_make_request_validation():
    with pytest.raises(ValueError, match="empty"):
        make_request(_imgs(0))
    with pytest.raises(ValueError, match="exceeds the largest"):
        make_request(_imgs(9), max_batch=8)
    with pytest.raises(ValueError, match="labels shape"):
        make_request(_imgs(2), labels=np.zeros(3, np.int32))
    req = make_request(_imgs(2), slo_ms=None)
    assert req.deadline == float("inf") and isinstance(req.future, Future)


# -- dispatch pipeline (round 14) ---------------------------------------------


def test_admit_free_at_two_slot_semantics():
    """``admit(free_at=)`` — pipelined second-slot admission: predicted
    completions are measured from when the engine actually frees a slot,
    not the admission instant; ``None`` / a past ``free_at`` (idle
    pipeline) is the round-13 policy bit-for-bit; already-late shed is
    still judged against NOW."""
    svc = ServiceModel((2, 4), anchor_s=0.010)
    now = 1000.0
    r = _vreq(2, 0, now + 0.035, seq=0)
    base = admit([r], now, buckets=(2, 4), predict_s=svc.predict)
    idle = admit([r], now, buckets=(2, 4), predict_s=svc.predict,
                 free_at=now - 5.0)
    assert idle == base
    assert base.batch == (r,)
    assert base.predicted_done == pytest.approx(now + 0.010)
    # Second slot: the engine frees at now+20ms, so this batch completes
    # at now+30ms — still inside its deadline, admitted.
    busy = admit([r], now, buckets=(2, 4), predict_s=svc.predict,
                 free_at=now + 0.020)
    assert busy.batch == (r,)
    assert busy.predicted_done == pytest.approx(now + 0.030)
    # A deadline the idle slot makes but the busy slot cannot is a
    # predicted miss (nothing lower-priority to defer -> shed).
    tight = _vreq(2, 0, now + 0.012, seq=1)
    assert admit([tight], now, buckets=(2, 4),
                 predict_s=svc.predict).batch == (tight,)
    a = admit([tight], now, buckets=(2, 4), predict_s=svc.predict,
              free_at=now + 0.020)
    assert a.batch == ()
    assert [(req.seq, reason) for req, reason in a.shed] \
        == [(1, "predicted_miss")]
    # Already-late: shed as "deadline" vs NOW, free_at irrelevant.
    late = _vreq(2, 0, now - 1.0, seq=2)
    a2 = admit([late], now, buckets=(2, 4), predict_s=svc.predict,
               free_at=now + 0.020)
    assert [(req.seq, reason) for req, reason in a2.shed] \
        == [(2, "deadline")]


def test_scheduler_rejects_pipeline_without_async_engine():
    with pytest.raises(ValueError, match="infer_counts_async"):
        SLOScheduler(StubEngine(), pipeline=True)
    # Auto-detection: a bare infer_counts engine falls back to serial.
    assert SLOScheduler(StubEngine()).pipeline is False


def test_pipelined_bitwise_vs_serial_seeded_trace(pool):
    """Tentpole pin: the pipelined worker answers a mixed-bucket trace
    (ragged tail included) bitwise-identically to the serial round-13
    worker.  Batch composition may differ between the two runs — rows
    are batchmate-invariant (train=False BN, pinned in test_serve.py) —
    so the per-request logits must still match exactly."""
    sizes = [1, 3, 2, 4, 8, 5, 2, 1, 7, 3, 4, 6]

    def _serve(pipeline):
        rep = EngineReplica(0, model="tiny", buckets=(2, 4, 8), seed=0,
                            pipeline=pipeline)
        assert rep.scheduler.pipeline is pipeline
        futs, off = [], 0
        for n in sizes:
            futs.append(rep.scheduler.submit(pool.images[off:off + n],
                                             slo_ms=None))
            off += n
        with rep.scheduler:
            return [f.result(60.0) for f in futs]

    serial = _serve(False)
    piped = _serve(True)
    assert [r.status for r in serial] == ["ok"] * len(sizes)
    assert [r.status for r in piped] == ["ok"] * len(sizes)
    for a, b in zip(serial, piped):   # futures in submit order
        np.testing.assert_array_equal(a.logits, b.logits)
    # The accounting invariant survives the overlap: latency decomposes
    # into queue wait + service, with service the fence-to-fence window
    # of the request's own dispatch (not the overlapped wall clock).
    for r in piped:
        assert r.latency_ms == pytest.approx(
            r.queue_wait_ms + r.service_ms, abs=1.0)


def test_pipelined_occupancy_bound_and_span_causality(pool):
    """Runtime two-slot occupancy meets the static bound exactly, and
    the engine's async spans stay causally attributable: each
    ``serve_dispatch``/``serve_fetch`` span names exactly its batch's
    trace ids, and the dispatch spans are occupancy-honest — clipped to
    issue order, never overlapping."""
    from cs744_ddp_tpu.analysis import dispatch as dispatchlib
    from cs744_ddp_tpu.obs import Telemetry

    tel = Telemetry()           # in-memory recorder
    rep = EngineReplica(0, model="tiny", buckets=(2, 4), seed=0,
                        telemetry=tel, pipeline=True)
    # Full-max-bucket requests, submitted before the worker starts: each
    # dispatch carries exactly one request, and the queue holds several
    # dispatches at start so the second slot MUST fill.
    futs = [rep.scheduler.submit(pool.images[4 * i:4 * i + 4], slo_ms=None)
            for i in range(5)]
    with rep.scheduler:
        replies = [f.result(60.0) for f in futs]
    assert [r.status for r in replies] == ["ok"] * 5
    events = tel.records
    bound = dispatchlib.serving_inflight_bound()
    assert bound == 2
    assert dispatchlib.max_serving_inflight(events) == bound
    dspans = [e for e in events if e.get("kind") == "span"
              and e["name"] == "serve_dispatch"]
    fspans = [e for e in events if e.get("kind") == "span"
              and e["name"] == "serve_fetch"]
    assert len(dspans) == len(fspans) == 5
    want = [[r.trace] for r in replies]
    assert [d["traces"] for d in dspans] == want
    assert [f["traces"] for f in fspans] == want
    for prev, nxt in zip(dspans, dspans[1:]):
        assert nxt["t"] >= prev["t"] + prev["dur_s"] - 1e-6


def test_telemetry_report_pipeline_section(tmp_path, monkeypatch):
    """The pipelined worker's occupancy gauges and fault counter render
    as ``== dispatch pipeline ==``; a serial run renders without it —
    absent-safe for older runs."""
    import os
    from cs744_ddp_tpu.obs import Telemetry
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    monkeypatch.syspath_prepend(os.path.join(repo, "tools"))
    import telemetry_report

    run = tmp_path / "piped"
    tel = Telemetry(out_dir=str(run))
    for v in (1, 2, 2, 1, 0):
        tel.gauge("serve_inflight", v, replica=0)
    tel.counter("serve_dispatch_fault", bucket=4, replica=0,
                error="ChaosError")
    tel.finalize()
    text = telemetry_report.render(str(run))
    assert "== dispatch pipeline ==" in text
    assert "replica 0" in text and "max 2" in text
    assert "dispatch faults        1" in text

    plain = tmp_path / "plain"
    tel2 = Telemetry(out_dir=str(plain))
    tel2.step(epoch=0, iter=0, loss=1.0, step_time=0.01)
    tel2.finalize()
    assert "== dispatch pipeline" not in telemetry_report.render(str(plain))
