"""End-to-end training tests on the virtual 8-device CPU mesh.

The equivalence test is the one the reference's structure implies but never
writes down (SURVEY.md §4): strategies gather/allreduce/ddp must produce
fp-tolerance-equal parameters after N steps from identical init and shards.

A tiny conv net stands in for VGG-11 to keep CPU compiles fast — the
strategy/step/loop code under test is identical (full VGG runs in
tests/test_models.py and on the TPU bench).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cs744_ddp_tpu.data import cifar10
from cs744_ddp_tpu.ops import sgd
from cs744_ddp_tpu.ops.loss import cross_entropy
from cs744_ddp_tpu.train.loop import Trainer, _shard_batches

from tinynet import run_steps, tiny_cnn, tiny_cnn_nobn


def make_trainer(tmp_path, mesh, strategy, **kw):
    kw.setdefault("global_batch", 64)
    kw.setdefault("augment", False)  # determinism across strategies
    kw.setdefault("log", lambda s: None)
    kw.setdefault("model", tiny_cnn())
    return Trainer(strategy=strategy, mesh=mesh, data_dir=str(tmp_path), **kw)


def params_allclose(a, b, atol):
    flat_a, flat_b = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(flat_a) == len(flat_b)
    for x, y in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=atol)


def test_strategy_equivalence_after_steps(tmp_path, mesh8):
    """gather ≡ allreduce ≡ ddp: same params after 5 steps."""
    results = {}
    for strategy in ("gather", "allreduce", "ddp"):
        tr = make_trainer(tmp_path, mesh8, strategy)
        key = jax.random.PRNGKey(123)
        for it, (imgs, labs) in enumerate(_shard_batches(
                tr.train_split, tr.world, tr.global_batch, 0, shuffle=True)):
            if it >= 5:
                break
            x, y = tr._put(imgs, labs)
            tr.state, loss = tr.train_step(tr.state, key, x, y)
        results[strategy] = jax.block_until_ready(tr.state.params)
    # Tolerance: the three collective patterns sum in different orders
    # (stack+mean vs ring all-reduce vs bucketed all-reduce), so results
    # differ at fp32 rounding level, amplified by BN + lr=0.1 — exactly as
    # the reference's Gloo strategies would.  Bitwise equality is neither
    # achievable nor claimed.
    params_allclose(results["gather"], results["allreduce"], atol=5e-4)
    params_allclose(results["ddp"], results["allreduce"], atol=5e-4)


def test_single_matches_eight_way_ddp(tmp_path, mesh1, mesh8):
    """A world-1 run and an 8-way DDP run on the same global batch take the
    same parameter step, modulo BatchNorm: the 8-way run normalizes each
    shard with LOCAL batch stats (per-replica BN, reference semantics), so
    only the BN-free subtree is compared after step 1."""
    tr1 = make_trainer(tmp_path, mesh1, "single")
    tr8 = make_trainer(tmp_path, mesh8, "ddp")
    # Force identical init (same seed => already identical, but be explicit).
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), tr1.state.params, tr8.state.params)

    imgs, labs = next(_shard_batches(tr1.train_split, tr1.world, 64, 0,
                                     shuffle=True))
    x1, y1 = tr1._put(imgs, labs)
    tr1.state, _ = tr1.train_step(tr1.state, jax.random.PRNGKey(0), x1, y1)

    imgs8, labs8 = next(_shard_batches(tr8.train_split, tr8.world, 64, 0,
                                       shuffle=True))
    x8, y8 = tr8._put(imgs8, labs8)
    tr8.state, _ = tr8.train_step(tr8.state, jax.random.PRNGKey(0), x8, y8)

    # Different sampler world sizes shard the SAME seed-0 permutation
    # differently; global batch content is the first 64 entries either way.
    np.testing.assert_array_equal(np.sort(labs), np.sort(labs8))

    # fc gradient depends on BN output => compare conv weights only would
    # also differ through BN backward.  The directly comparable piece with
    # per-replica BN stats is the fc BIAS gradient (sum of dlogits), which
    # is batch-mean over the same examples in both runs... but dlogits pass
    # through BN too.  So: assert closeness loosely — per-replica BN at
    # shard size 8 vs 64 is a real (documented) semantic difference, and
    # this test pins it as BOUNDED, not zero.
    for xa, xb in zip(jax.tree.leaves(tr1.state.params),
                      jax.tree.leaves(tr8.state.params)):
        a, b = np.asarray(xa), np.asarray(xb)
        # Loose bound: per-replica BN stats (shard size 8 vs 64) are a real
        # semantic difference.  The TIGHT averaging oracle is the BN-free
        # test below — this bound once masked a grads×world bug, so it only
        # documents that BN noise stays bounded, nothing more.
        assert np.max(np.abs(a - b)) < 0.6, "divergence beyond BN-stat noise"


def test_single_matches_eight_way_ddp_bnfree_tight(tmp_path, mesh1, mesh8):
    """The REAL cross-world averaging oracle (VERDICT r1 item 5): with no
    BatchNorm there is no per-replica batch-stats semantic, so a 1-device
    run and an 8-way DDP run on the same global batch compute the same
    mathematics — the mean gradient over the global batch is invariant to
    how the batch is dealt across shards (the round-robin deal of batch b
    covers exactly permutation positions [b*64, (b+1)*64) in both worlds).
    Equality must hold to fp tolerance over several steps."""
    # lr=0.01: the default 0.1 makes the tiny net's trajectory unstable
    # (loss grows), and an unstable trajectory amplifies benign fp32
    # reassociation into O(1) parameter differences — the oracle needs
    # stable dynamics so only a REAL averaging bug can produce divergence.
    cfg = sgd.SGDConfig(lr=0.01)
    tr1 = make_trainer(tmp_path, mesh1, "single", model=tiny_cnn_nobn(),
                       sgd_cfg=cfg)
    tr8 = make_trainer(tmp_path, mesh8, "ddp", model=tiny_cnn_nobn(),
                       sgd_cfg=cfg)
    for tr in (tr1, tr8):
        run_steps(tr, 5)
    # fp32 reassociation (8-way psum vs one batch mean) only — no BN noise.
    params_allclose(tr1.state.params, tr8.state.params, atol=2e-5)
    params_allclose(tr1.state.opt_state.momentum,
                    tr8.state.opt_state.momentum, atol=2e-5)


def test_windowed_path_matches_per_step_path(tmp_path, mesh8):
    """A W-step compiled window must produce the same TrainState as W
    individual per-step calls (augment off so PRNG streams are moot)."""
    tr_win = make_trainer(tmp_path, mesh8, "ddp")
    tr_step = make_trainer(tmp_path, mesh8, "ddp")
    n_iters = 7
    # Shrink BOTH trainers to the same n_iters-batch epoch (the sampler
    # permutation depends on the dataset size, so the splits must match).
    for tr in (tr_win, tr_step):
        tr.train_split = cifar10.Split(
            tr.train_split.images[:64 * n_iters],
            tr.train_split.labels[:64 * n_iters])
    tr_win.train_model(0)

    key = jax.random.fold_in(jax.random.PRNGKey(tr_step.seed), 0)
    for it, (imgs, labs) in enumerate(_shard_batches(
            tr_step.train_split, tr_step.world, 64, 0, shuffle=True)):
        if it >= n_iters:
            break
        x, y = tr_step._put(imgs, labs)
        tr_step.state, _ = tr_step.train_step(
            tr_step.state, jax.random.fold_in(key, it), x, y)

    # Tolerance: scan vs unrolled dispatch compile to different programs,
    # so fp32 reassociation gives ~1e-5-level divergence over 7 steps.
    params_allclose(tr_win.state.params, tr_step.state.params, atol=1e-4)
    params_allclose(tr_win.state.opt_state.momentum,
                    tr_step.state.opt_state.momentum, atol=1e-4)
    # Running variance accumulates squared activations — more fp-sensitive.
    params_allclose(tr_win.state.bn_state, tr_step.state.bn_state, atol=1e-3)


def test_windowed_path_matches_per_step_path_with_augment(tmp_path, mesh4):
    """With the canonical PRNG fold order (batch index, then mesh position)
    the windowed and per-step paths must consume the SAME augmentation
    stream — this pins ADVICE r1's fold-order divergence as fixed."""
    tr_win = make_trainer(tmp_path, mesh4, "ddp", augment=True)
    tr_step = make_trainer(tmp_path, mesh4, "ddp", augment=True)
    n_iters = 4
    for tr in (tr_win, tr_step):
        tr.train_split = cifar10.Split(
            tr.train_split.images[:64 * n_iters],
            tr.train_split.labels[:64 * n_iters])
    tr_win.train_model(0)

    key = jax.random.fold_in(jax.random.PRNGKey(tr_step.seed), 0)
    for it, (imgs, labs) in enumerate(_shard_batches(
            tr_step.train_split, tr_step.world, 64, 0, shuffle=True)):
        if it >= n_iters:
            break
        x, y = tr_step._put(imgs, labs)
        tr_step.state, _ = tr_step.train_step(
            tr_step.state, jax.random.fold_in(key, it), x, y)

    # Same stream => same data => scan-vs-unrolled fp divergence only.
    params_allclose(tr_win.state.params, tr_step.state.params, atol=1e-4)


def test_ragged_tail_batch_is_trained(tmp_path, mesh8):
    """drop_last=False parity (VERDICT r2 item 4): the short final batch is
    trained — through its own compiled step at its true shape — and the
    windowed and per-step paths agree on it.

    208 examples / world 8 / global batch 64: per-rank 26 = 3*8 + 2, so the
    epoch is 3 full batches plus a ragged global tail of 16."""
    tr_win = make_trainer(tmp_path, mesh8, "ddp")
    tr_step = make_trainer(tmp_path, mesh8, "ddp", profile_phases=True)
    for tr in (tr_win, tr_step):
        tr.train_split = cifar10.Split(tr.train_split.images[:208],
                                       tr.train_split.labels[:208])
    t_win = tr_win.train_model(0)
    t_step = tr_step.train_model(0)
    # Printed count == trained count: ceil(26 / 8) = 4 iterations.
    assert t_win.iter_number - 1 == 4
    assert t_step.iter_number - 1 == 4
    # Both paths take the same parameter trajectory through the tail.
    params_allclose(tr_win.state.params, tr_step.state.params, atol=1e-4)
    # The tail actually MOVED the params: replay only the 3 full windows.
    tr_full = make_trainer(tmp_path, mesh8, "ddp")
    tr_full.train_split = cifar10.Split(tr_full.train_split.images[:208],
                                        tr_full.train_split.labels[:208])
    tr_full.limit_train_batches = 3
    tr_full.train_model(0)
    diffs = [float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
             for a, b in zip(jax.tree.leaves(tr_win.state.params),
                             jax.tree.leaves(tr_full.state.params))]
    assert max(diffs) > 1e-6, "tail step was a no-op"


def test_staging_cache_invalidates_on_split_replacement(tmp_path, mesh4):
    """Replacing test_split after an eval must restage (not reuse stale
    device arrays)."""
    tr = make_trainer(tmp_path, mesh4, "allreduce")
    tr.test_split = cifar10.Split(tr.test_split.images[:128],
                                  tr.test_split.labels[:128])
    _, correct_full, _ = tr.test_model()
    tr.test_split = cifar10.Split(tr.test_split.images[:64],
                                  tr.test_split.labels[:64])
    _, correct_small, _ = tr.test_model()
    assert correct_small <= 64  # would exceed 64 if stale staging were used


def test_loss_decreases_single_device(tmp_path, mesh1):
    """The reference's convergence oracle: running loss drops (SURVEY.md §4).
    Synthetic data is class-templated, so a few steps cut loss sharply."""
    tr = make_trainer(tmp_path, mesh1, "single", global_batch=64,
                      sgd_cfg=sgd.SGDConfig(lr=0.05))
    key = jax.random.PRNGKey(0)
    losses = []
    for it, (imgs, labs) in enumerate(_shard_batches(
            tr.train_split, 1, 64, 0, shuffle=True)):
        if it >= 30:
            break
        x, y = tr._put(imgs, labs)
        tr.state, loss = tr.train_step(tr.state, jax.random.fold_in(key, it),
                                       x, y)
        losses.append(float(loss))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.7, losses


def test_eval_counts_exact_over_full_test_set(tmp_path, mesh4):
    tr = make_trainer(tmp_path, mesh4, "allreduce", global_batch=64)
    # Shrink the test set for speed, with a ragged tail (not % 64).
    tr.test_split = cifar10.Split(tr.test_split.images[:200],
                                  tr.test_split.labels[:200])
    avg_loss, correct, acc = tr.test_model()
    assert 0 <= correct <= 200
    assert acc == pytest.approx(100.0 * correct / 200)
    assert avg_loss > 0

    # Cross-check against a direct (unsharded, unpadded) computation.
    from cs744_ddp_tpu.data import augment as aug
    from cs744_ddp_tpu.ops.loss import accuracy_counts
    x = aug.normalize(jnp.asarray(tr.test_split.images))
    logits, _ = tr.apply_fn(tr.state.params, tr.state.bn_state, x, train=False)
    expected_correct = int(accuracy_counts(logits,
                                           jnp.asarray(tr.test_split.labels)))
    assert correct == expected_correct
    expected_loss = float(cross_entropy(
        logits, jnp.asarray(tr.test_split.labels)))
    assert avg_loss == pytest.approx(expected_loss, abs=1e-5)


def test_trainer_run_prints_reference_schedule(tmp_path, mesh1):
    lines = []
    tr = make_trainer(tmp_path, mesh1, "single", global_batch=64,
                      log=lines.append)
    tr.test_split = cifar10.Split(tr.test_split.images[:64],
                                  tr.test_split.labels[:64])
    # ~25 iterations: one full window + part of the next.
    tr.train_split = cifar10.Split(tr.train_split.images[:64 * 25],
                                   tr.train_split.labels[:64 * 25])
    tr.run(epochs=1)
    text = "\n".join(lines)
    # Reference prints len(train_loader) = per-rank batch count
    # (Part 2a/main.py:46): ceil(50000 / 64) = 782 at construction time.
    assert "Size of training set is 782" in text
    assert "Training loss after 20 iterations is" in text
    assert "Training time after 1 epoch is" in text
    assert "Test set: Average loss:" in text
    # First window excluded from timing report (reference main.py:51).
    assert "Average Pass time in iter 20 is" not in text


def test_bf16_precision_trains_and_evaluates(tmp_path, mesh4):
    """Mixed-precision mode: master params stay f32, training converges on
    the synthetic split, and the eval path runs under bf16 activations."""
    tr = Trainer(model=tiny_cnn(), strategy="ddp", mesh=mesh4,
                 global_batch=64, data_dir=str(tmp_path), augment=False,
                 precision="bf16", log=lambda s: None)
    assert all(l.dtype == jnp.float32
               for l in jax.tree.leaves(tr.state.params))
    key = jax.random.PRNGKey(0)
    losses = []
    for it, (imgs, labs) in enumerate(_shard_batches(
            tr.train_split, 4, 64, 0, shuffle=True)):
        if it >= 30:
            break
        x, y = tr._put(imgs, labs)
        tr.state, loss = tr.train_step(tr.state, jax.random.fold_in(key, it),
                                       x, y)
        losses.append(float(loss))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.7, losses
    tr.test_split = cifar10.Split(tr.test_split.images[:128],
                                  tr.test_split.labels[:128])
    avg_loss, correct, acc = tr.test_model()
    assert np.isfinite(avg_loss) and 0 <= correct <= 128

    import pytest
    with pytest.raises(ValueError):
        Trainer(model=tiny_cnn(), strategy="ddp", mesh=mesh4,
                global_batch=64, data_dir=str(tmp_path),
                precision="fp16", log=lambda s: None)
