"""Unit tests for the HLO collective-stats parser behind bench.py's
``spectrum`` section (utils/hlo_stats.py)."""

from cs744_ddp_tpu.utils.hlo_stats import bytes_of_type, collective_stats

# Shapes/ops modeled on real v5e HLO text (layout/tiling annotations and
# tuple results included).
SAMPLE = """\
HloModule jit_step
%psum_invariant.54 = f32[8]{0:T(128)S(1)} all-reduce(%x), channel_id=1
%all-reduce.14 = (f32[512,10]{0,1:T(8,128)S(1)}, f32[8]{0:T(128)S(1)}) all-reduce(%a, %b), channel_id=2
%all-gather.15 = f32[24,3,3,8]{3,2,1,0:T(4,128)} all-gather(%p), dimensions={0}
%ags = (f32[1024]{0}, f32[8192]{0}) all-gather-start(%q), dimensions={0}
%agd = f32[8192]{0} all-gather-done(%ags)
%rss = (f32[1048576]{0}, f32[262144]{0}) reduce-scatter-start(%r)
%rsd = f32[262144]{0} reduce-scatter-done(%rss)
ROOT %tuple.90 = (f32[512,10]{0,1}, f32[3,3,3,8]{3,2,1,0}) tuple(%t, %u)
%custom-call.3 = f32[64]{0} custom-call(%all-gather.15), custom_call_target="x"
"""


def test_bytes_of_type():
    assert bytes_of_type("f32[512,10]{0,1:T(8,128)S(1)}") == 512 * 10 * 4
    assert bytes_of_type("(f32[8]{0}, bf16[8]{0})") == 8 * 4 + 8 * 2
    assert bytes_of_type("u32[]{:S(2)}") == 4          # scalar
    assert bytes_of_type("token[]") == 0               # unknown dtype skipped


def test_collective_stats_counts_and_bytes():
    s = collective_stats(SAMPLE)
    # all-reduce: two sync instances; bytes = 8*4 + (512*10*4 + 8*4).
    ar = s["ops"]["all-reduce"]
    assert ar["count"] == 2
    assert abs(ar["result_mib"] - (8 * 4 + 512 * 10 * 4 + 8 * 4) / 2**20) \
        < 0.01
    # all-gather: one sync + one async PAIR counted once; async bytes come
    # from the -done result only (the -start tuple holds source buffers).
    ag = s["ops"]["all-gather"]
    assert ag["count"] == 2
    assert abs(ag["result_mib"]
               - (24 * 3 * 3 * 8 * 4 + 8192 * 4) / 2**20) < 0.01
    # Async reduce-scatter pair: counted once, bytes from -done ONLY
    # (1.0 MiB output; counting the -start tuple's source buffers too
    # would read 5.0 MiB, and dropping -done would read 0 — both sides of
    # the convention are discriminated at this size).
    rs = s["ops"]["reduce-scatter"]
    assert rs["count"] == 1
    assert rs["result_mib"] == 1.0
    # tuple/custom-call lines (which merely REFERENCE collectives as
    # operands) are not collectives.
    assert s["total_count"] == 5


# A handcrafted module with a KNOWN collective dependency structure, in the
# pre-optimization print format (bare names, computation headers without
# arrows) collective_chain_depth is documented to consume:
#   chain: ar1 -> (through elementwise add) -> ar2 -> ag1   depth 3
#   parallel: ar_par (independent)                          depth 1
#   while body with one collective, called from main        contributes 1
DEPTH_SAMPLE = """\
HloModule jit_window

region_add.1 {
  lhs = f32[] parameter(0)
  rhs = f32[] parameter(1)
  ROOT add.r = f32[] add(lhs, rhs)
}

body.2 {
  bp = f32[8]{0} parameter(0)
  ar.body = f32[8]{0} all-reduce(bp), to_apply=region_add.1
  ROOT bt = f32[8]{0} add(ar.body, ar.body)
}

ENTRY main.3 {
  p0 = f32[8]{0} parameter(0)
  ar1 = f32[8]{0} all-reduce(p0), to_apply=region_add.1
  mid = f32[8]{0} add(ar1, p0)
  ar2 = f32[8]{0} all-reduce(mid), to_apply=region_add.1
  ag1 = f32[64]{0} all-gather(ar2), dimensions={0}
  ar_par = f32[8]{0} all-reduce(p0), to_apply=region_add.1
  w = f32[8]{0} while(p0), body=body.2, condition=region_add.1
  wdep = f32[8]{0} add(w, ag1)
  ROOT out = f32[64]{0} all-gather(wdep), dimensions={0}
}
"""


def test_collective_chain_depth_on_handcrafted_module():
    from cs744_ddp_tpu.utils.hlo_stats import collective_chain_depth
    # Longest chain: ar1 -> ar2 -> ag1 (3) then -> wdep -> ROOT out (4);
    # the while's body contributes its internal depth (1) to w, giving
    # w(1) -> wdep -> out(2) on that arm — the ar chain dominates.
    assert collective_chain_depth(DEPTH_SAMPLE) == 4


def test_collective_chain_depth_chain_feeding_collective_callee():
    from cs744_ddp_tpu.utils.hlo_stats import collective_chain_depth
    # A collective chain FEEDING a collective-bearing called computation:
    # ar1's result is the while's operand, and the while body runs its own
    # all-reduce, so the body's collective necessarily executes AFTER ar1 —
    # operand chain and callee internals compose to depth 1 + 1 = 2.
    # (Taking max(operand_chain, callee_depth) instead of their sum reads
    # this module as depth 1 — the undercount this fixture pins against.)
    txt = """\
region_add.1 {
  lhs = f32[] parameter(0)
  rhs = f32[] parameter(1)
  ROOT add.r = f32[] add(lhs, rhs)
}

cond.1 {
  cp = f32[8]{0} parameter(0)
  ROOT lt = pred[] constant(false)
}

body.1 {
  bp = f32[8]{0} parameter(0)
  ar.body = f32[8]{0} all-reduce(bp), to_apply=region_add.1
  ROOT bt = f32[8]{0} add(ar.body, ar.body)
}

ENTRY main.1 {
  p0 = f32[8]{0} parameter(0)
  ar1 = f32[8]{0} all-reduce(p0), to_apply=region_add.1
  w = f32[8]{0} while(ar1), body=body.1, condition=cond.1
  ROOT r = f32[8]{0} add(w, w)
}
"""
    assert collective_chain_depth(txt) == 2
    # Lengthening the feeding chain must lengthen the total the same way:
    # ar1 -> ar2 -> while(collective body) = 3.
    txt3 = txt.replace(
        "  w = f32[8]{0} while(ar1), body=body.1, condition=cond.1",
        "  ar2 = f32[8]{0} all-reduce(ar1), to_apply=region_add.1\n"
        "  w = f32[8]{0} while(ar2), body=body.1, condition=cond.1")
    assert collective_chain_depth(txt3) == 3


def test_collective_chain_depth_async_pairs_count_once():
    from cs744_ddp_tpu.utils.hlo_stats import collective_chain_depth
    txt = """\
ENTRY main {
  p0 = f32[8]{0} parameter(0)
  ags = (f32[8]{0}, f32[64]{0}) all-gather-start(p0), dimensions={0}
  agd = f32[64]{0} all-gather-done(ags)
  ar1 = f32[64]{0} all-reduce(agd)
  ROOT r = f32[64]{0} add(ar1, ar1)
}
"""
    # start counts 1, done 0 (one collective), then the dependent
    # all-reduce: depth 2 — an async pair must not count twice.
    assert collective_chain_depth(txt) == 2


def test_collective_chain_depth_ignores_metadata_and_strings():
    from cs744_ddp_tpu.utils.hlo_stats import collective_chain_depth
    # Poisoned fixture: metadata op_name/source_file tokens COLLIDE with the
    # instruction names ar1/ar2 (XLA records the originating jax op there,
    # and jaxpr-derived names routinely match instruction names).  Without
    # stripping annotations before reference extraction these fabricate
    # ar1 -> ar2 -> ar3 dependency edges and report depth 3; the real
    # module is three INDEPENDENT all-reduces (depth 1).  The quoted "}"
    # inside source_file additionally checks strings are removed before the
    # metadata block is matched.
    txt = """\
ENTRY %main.1 (p0: f32[8]) -> f32[8] {
  %p0 = f32[8]{0} parameter(0)
  %ar1 = f32[8]{0} all-reduce(%p0), channel_id=1, metadata={op_name="ar0" source_file="a}b.py" source_line=1}
  %ar2 = f32[8]{0} all-reduce(%p0), channel_id=2, metadata={op_name="jit(step)/ar1" source_file="loop.py" source_line=2}
  ROOT %ar3 = f32[8]{0} all-reduce(%p0), channel_id=3, metadata={op_name="ar2" source_line=3}
}
"""
    assert collective_chain_depth(txt) == 1
    # Structural references OUTSIDE metadata (to_apply=, body=) must still
    # resolve: the while body's internal collective feeds the chain.
    txt2 = """\
region_add.1 {
  lhs = f32[] parameter(0)
  rhs = f32[] parameter(1)
  ROOT add.r = f32[] add(lhs, rhs)
}

ENTRY main.2 {
  p0 = f32[8]{0} parameter(0)
  ar1 = f32[8]{0} all-reduce(p0), to_apply=region_add.1, metadata={op_name="ar2"}
  ROOT ar2 = f32[8]{0} all-reduce(ar1), to_apply=region_add.1
}
"""
    assert collective_chain_depth(txt2) == 2


def test_collective_chain_depth_optimized_print_sigils():
    from cs744_ddp_tpu.utils.hlo_stats import collective_chain_depth
    txt = """\
ENTRY %main.1 (p0: f32[8]) -> f32[8] {
  %p0 = f32[8]{0} parameter(0)
  %ar1 = f32[8]{0:T(128)} all-reduce(%p0), channel_id=1
  ROOT %ar2 = f32[8]{0:T(128)} all-reduce(%ar1), channel_id=2
}
"""
    assert collective_chain_depth(txt) == 2


# ---------------------------------------------------------------------------
# Committed fixtures (VERDICT r5 item 5): ONE module with a known collective
# structure rendered in BOTH print forms XLA emits — the optimized print
# (%-sigils, typed operands, layout/tiling annotations, metadata) and the
# pre-optimization print (bare names, no operand types).  The parsers feed
# bench.py's spectrum section, where a silent format mismatch reads as
# "zero collectives"; these pin absolute values AND sigil/bare agreement.
#
# Module structure (see the .hlo files):
#   chain  ar1 -> ar2 -> ar3(tuple) -> async all-gather pair   depth 4
#   plus an independent collective-permute and a while whose body holds an
#   async reduce-scatter pair (contributes depth 1 on its arm).
#   Counts: all-reduce 3, all-gather 1 (pair), reduce-scatter 1 (pair),
#   collective-permute 1 -> total 6.

def _fixture(name):
    import os
    path = os.path.join(os.path.dirname(__file__), "assets", "hlo", name)
    with open(path) as f:
        return f.read()


def test_hlo_fixture_stats_and_depth_both_print_forms():
    from cs744_ddp_tpu.utils.hlo_stats import (collective_chain_depth,
                                               collective_stats)
    sigil = _fixture("train_window_sigil.hlo")
    bare = _fixture("train_window_bare.hlo")

    s = collective_stats(sigil)
    assert s["ops"]["all-reduce"]["count"] == 3
    # ar1 + ar2 + tuple ar3 = (1024 + 1024 + 2*1024) f32 = 16 KiB -> 0.02.
    assert s["ops"]["all-reduce"]["result_mib"] == 0.02
    # Async pair counted once; bytes from the -done result (f32[8192]),
    # NOT the -start tuple (which also carries the source buffer).
    assert s["ops"]["all-gather"]["count"] == 1
    assert s["ops"]["all-gather"]["result_mib"] == 0.03
    assert s["ops"]["reduce-scatter"]["count"] == 1
    assert s["ops"]["collective-permute"]["count"] == 1
    assert s["total_count"] == 6

    # The bare pre-optimization print of the SAME module must parse to the
    # same stats — the sigil/type/layout decorations are presentation only.
    assert collective_stats(bare) == s

    # Depth: ar1 -> ar2 -> ar3 -> all-gather pair = 4 (the while-body
    # reduce-scatter arm and the lone collective-permute are shallower);
    # identical across print forms, and the sigil form's metadata
    # (op_name="ar3" etc.) must not fabricate extra edges.
    assert collective_chain_depth(sigil) == 4
    assert collective_chain_depth(bare) == 4
