"""Unit tests for the HLO collective-stats parser behind bench.py's
``spectrum`` section (utils/hlo_stats.py)."""

from cs744_ddp_tpu.utils.hlo_stats import bytes_of_type, collective_stats

# Shapes/ops modeled on real v5e HLO text (layout/tiling annotations and
# tuple results included).
SAMPLE = """\
HloModule jit_step
%psum_invariant.54 = f32[8]{0:T(128)S(1)} all-reduce(%x), channel_id=1
%all-reduce.14 = (f32[512,10]{0,1:T(8,128)S(1)}, f32[8]{0:T(128)S(1)}) all-reduce(%a, %b), channel_id=2
%all-gather.15 = f32[24,3,3,8]{3,2,1,0:T(4,128)} all-gather(%p), dimensions={0}
%ags = (f32[1024]{0}, f32[8192]{0}) all-gather-start(%q), dimensions={0}
%agd = f32[8192]{0} all-gather-done(%ags)
%rss = (f32[1048576]{0}, f32[262144]{0}) reduce-scatter-start(%r)
%rsd = f32[262144]{0} reduce-scatter-done(%rss)
ROOT %tuple.90 = (f32[512,10]{0,1}, f32[3,3,3,8]{3,2,1,0}) tuple(%t, %u)
%custom-call.3 = f32[64]{0} custom-call(%all-gather.15), custom_call_target="x"
"""


def test_bytes_of_type():
    assert bytes_of_type("f32[512,10]{0,1:T(8,128)S(1)}") == 512 * 10 * 4
    assert bytes_of_type("(f32[8]{0}, bf16[8]{0})") == 8 * 4 + 8 * 2
    assert bytes_of_type("u32[]{:S(2)}") == 4          # scalar
    assert bytes_of_type("token[]") == 0               # unknown dtype skipped


def test_collective_stats_counts_and_bytes():
    s = collective_stats(SAMPLE)
    # all-reduce: two sync instances; bytes = 8*4 + (512*10*4 + 8*4).
    ar = s["ops"]["all-reduce"]
    assert ar["count"] == 2
    assert abs(ar["result_mib"] - (8 * 4 + 512 * 10 * 4 + 8 * 4) / 2**20) \
        < 0.01
    # all-gather: one sync + one async PAIR counted once; async bytes come
    # from the -done result only (the -start tuple holds source buffers).
    ag = s["ops"]["all-gather"]
    assert ag["count"] == 2
    assert abs(ag["result_mib"]
               - (24 * 3 * 3 * 8 * 4 + 8192 * 4) / 2**20) < 0.01
    # Async reduce-scatter pair: counted once, bytes from -done ONLY
    # (1.0 MiB output; counting the -start tuple's source buffers too
    # would read 5.0 MiB, and dropping -done would read 0 — both sides of
    # the convention are discriminated at this size).
    rs = s["ops"]["reduce-scatter"]
    assert rs["count"] == 1
    assert rs["result_mib"] == 1.0
    # tuple/custom-call lines (which merely REFERENCE collectives as
    # operands) are not collectives.
    assert s["total_count"] == 5
