"""Gradient-sync strategy tests on the 8-virtual-device CPU mesh.

Covers: mathematical equivalence of the three strategies (same averaged
gradient — the property the reference's Parts 2a/2b/3 rely on but never
test), bucketing round-trips, and the collective patterns in the lowered HLO.
"""

import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
try:
    from jax import shard_map
except ImportError:                      # jax < 0.6: experimental namespace
    from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from cs744_ddp_tpu.parallel import bucketing, strategies
from cs744_ddp_tpu.parallel.mesh import DATA_AXIS
from cs744_ddp_tpu.train.step import _SHARD_MAP_KW


def tree_of_grads(key, scale=1.0):
    ks = jax.random.split(key, 4)
    return {
        "conv": [{"w": jax.random.normal(ks[0], (3, 3, 8, 16)) * scale,
                  "b": jax.random.normal(ks[1], (16,)) * scale}],
        "fc": {"w": jax.random.normal(ks[2], (32, 10)) * scale,
               "b": jax.random.normal(ks[3], (10,)) * scale},
    }


def run_strategy(mesh, strategy, grads_per_device):
    """Apply a strategy to per-device gradient pytrees; return the synced
    (replicated) result.  grads leaves have a leading device axis."""
    f = shard_map(lambda g: strategy(
        jax.tree.map(lambda a: a[0], g), DATA_AXIS),
        mesh=mesh, in_specs=(P(DATA_AXIS),), out_specs=P(),
        **_SHARD_MAP_KW)
    return jax.jit(f)(grads_per_device)


@pytest.fixture
def per_device_grads(mesh8):
    n = mesh8.devices.size
    keys = jax.random.split(jax.random.PRNGKey(7), n)
    trees = [tree_of_grads(k) for k in keys]
    return jax.tree.map(lambda *ls: jnp.stack(ls), *trees)


def test_all_strategies_compute_the_mean(mesh8, per_device_grads):
    expected = jax.tree.map(lambda a: jnp.mean(a, 0), per_device_grads)
    for name in ("gather", "allreduce", "ddp"):
        out = run_strategy(mesh8, strategies.get_strategy(name),
                           per_device_grads)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-6,
                err_msg=f"strategy {name}"),
            out, expected)


def test_local_strategy_is_identity():
    grads = tree_of_grads(jax.random.PRNGKey(0))
    out = strategies.local(grads, DATA_AXIS)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b)), out, grads)


def test_bucketing_plan_partitions_all_leaves():
    grads = tree_of_grads(jax.random.PRNGKey(3))
    n_leaves = len(jax.tree.leaves(grads))
    for bucket_bytes in (64, 4096, bucketing.DEFAULT_BUCKET_BYTES):
        plan = bucketing.make_plan(grads, bucket_bytes)
        covered = sorted(i for b in plan.buckets for i in b)
        assert covered == list(range(n_leaves))  # exact partition


def test_bucketing_respects_size_bound_and_reverse_order():
    grads = {"a": jnp.zeros((1000,)), "b": jnp.zeros((1000,)),
             "c": jnp.zeros((1000,))}
    plan = bucketing.make_plan(grads, bucket_bytes=4500)  # fits 1 leaf + change
    # 4000-byte leaves, 4500-byte cap -> one leaf per bucket.
    assert plan.num_buckets == 3
    # Reverse registration order: leaf index 2 ("c") first, like DDP.
    assert plan.buckets[0] == (2,)


def test_strategy_collective_patterns_in_stablehlo(mesh8):
    """The tiers must stay observably distinct pre-optimization: the
    per-param tier is a barrier-CHAINED sequence of per-leaf all-reduces
    (Part 2b's blocking loop — leaves-1 barriers), while the ddp tier
    groups leaves into buckets with barriers only BETWEEN buckets
    (Part 3's in-order comm stream).  The compiled-level distinctness (one
    collective per leaf vs per bucket on the v5e-8 lowering) is asserted
    in tests/test_tpu_aot.py — the CPU backend here strips barriers and
    fuses both tiers (test_ddp_wallclock_not_slower_than_allreduce pins
    that convergence)."""
    grads = tree_of_grads(jax.random.PRNGKey(1))
    stacked = jax.tree.map(lambda a: a[None].repeat(8, 0), grads)

    def counts(strategy):
        f = shard_map(lambda g: strategy(
            jax.tree.map(lambda a: a[0], g), DATA_AXIS),
            mesh=mesh8, in_specs=(P(DATA_AXIS),), out_specs=P(),
            **_SHARD_MAP_KW)
        hlo = jax.jit(f).lower(stacked).as_text()  # StableHLO MLIR
        return (len(re.findall(r"stablehlo\.all_reduce", hlo)),
                len(re.findall(r"stablehlo\.optimization_barrier", hlo)))

    n_ar, n_bar = counts(strategies.get_strategy("allreduce"))
    assert (n_ar, n_bar) == (4, 3)   # per leaf, sequentially chained

    n_ar, n_bar = counts(strategies.get_strategy("ddp"))
    assert (n_ar, n_bar) == (4, 0)   # all four leaves fit one 25MB bucket

    # Tiny buckets: one leaf per bucket -> chained like DDP's comm stream.
    n_ar, n_bar = counts(strategies.get_strategy("ddp", bucket_bytes=64))
    assert (n_ar, n_bar) == (4, 3)

    # gather_scatter: all-gather + all-reduce per leaf, chained.
    f = shard_map(lambda g: strategies.gather_scatter(
        jax.tree.map(lambda a: a[0], g), DATA_AXIS),
        mesh=mesh8, in_specs=(P(DATA_AXIS),), out_specs=P(),
        **_SHARD_MAP_KW)
    hlo = jax.jit(f).lower(stacked).as_text()
    assert len(re.findall(r"stablehlo\.all_gather", hlo)) == 4
    assert len(re.findall(r"stablehlo\.all_reduce", hlo)) == 4
    assert len(re.findall(r"stablehlo\.optimization_barrier", hlo)) == 3


def test_compiled_step_reaches_ddp_grade_fusion(mesh8):
    """On the CPU BACKEND (which strips optimization barriers), the whole
    compiled train step must carry at most bucket-count all-reduces for
    BOTH the ddp and the per-param strategy: XLA's all-reduce combiner
    delivers DDP-grade fusion — the capability torch gets from DDP's C++
    reducer.  On TPU the barrier chains keep the tiers distinct instead
    (tests/test_tpu_aot.py); pre-optimization structure is pinned in
    test_strategy_collective_patterns_in_stablehlo."""
    if tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 5):
        pytest.skip("this jax's CPU backend keeps optimization barriers, so "
                    "the all-reduce combiner never sees a fusable chain; the "
                    "fusion capability is pinned on newer toolchains only")
    from tinynet import tiny_cnn

    import jax.numpy as jnp
    from cs744_ddp_tpu.ops import sgd
    from cs744_ddp_tpu.train import step as steplib

    init_fn, apply_fn = tiny_cnn()
    state = steplib.init_train_state(init_fn, jax.random.PRNGKey(0))
    imgs = jnp.zeros((64, 32, 32, 3), jnp.uint8)
    labs = jnp.zeros((64,), jnp.int32)
    for name in ("allreduce", "ddp"):
        step = steplib.make_train_step(
            apply_fn, strategies.get_strategy(name), mesh8, sgd.SGDConfig(),
            augment=False)
        txt = step.lower(state, jax.random.PRNGKey(0), imgs, labs) \
                  .compile().as_text()
        n = len(re.findall(r" all-reduce\(", txt))
        assert 1 <= n <= 2, (name, n)  # 4 grad leaves -> <= 2 collectives


@pytest.mark.slow  # ~70s: ResNet-18 compile + timed steps on the CPU mesh
def test_ddp_wallclock_not_slower_than_allreduce(mesh8):
    """Part 3's capability claim, measured: the bucketed-fused tier must not
    lose to per-param all-reduce on a model with many parameter leaves
    (ResNet-18, ~60 leaves).  On this XLA version both compile to the same
    fused collective schedule, so this pins ddp step time <= allreduce
    step time as a wall-clock invariant (margin covers CI timer noise).

    The POSITIVE separation of all three tiers (gather > allreduce > ddp
    in ms/step) is measured where the collective patterns dominate —
    tools/bench_strategy_spectrum.py, a 122-leaf comm-bound model on this
    same 8-virtual-device mesh — and recorded in BASELINE.md ("Strategy
    cost spectrum"); this test only guards the non-regression direction."""
    import time

    import jax.numpy as jnp
    from cs744_ddp_tpu.models import resnet
    from cs744_ddp_tpu.ops import sgd
    from cs744_ddp_tpu.train import step as steplib

    init_fn, apply_fn = resnet.ResNet18()
    state = steplib.init_train_state(init_fn, jax.random.PRNGKey(0))
    imgs = jnp.zeros((32, 32, 32, 3), jnp.uint8)
    labs = jnp.zeros((32,), jnp.int32)

    # Compile and warm BOTH programs first, then INTERLEAVE the timed steps:
    # back-to-back A/B pairs cancel the load drift of a shared CI host that
    # sequential per-strategy timing is exposed to.
    steps, states = {}, {}
    for name in ("allreduce", "ddp"):
        step = steplib.make_train_step(
            apply_fn, strategies.get_strategy(name), mesh8, sgd.SGDConfig(),
            augment=False)
        s = state
        for i in range(2):
            s, loss = step(s, jax.random.PRNGKey(i), imgs, labs)
            float(loss)  # value fetch = completion fence
        steps[name], states[name] = step, s

    times = {"allreduce": [], "ddp": []}
    for i in range(9):
        for name in ("allreduce", "ddp"):
            t0 = time.time()
            states[name], loss = steps[name](
                states[name], jax.random.PRNGKey(i), imgs, labs)
            float(loss)  # value fetch = completion fence
            times[name].append(time.time() - t0)

    # Median over 9 interleaved pairs: robust to per-step scheduler spikes
    # (a single outlier cannot move the median) as well as slow drift.
    med = {k: sorted(v)[len(v) // 2] for k, v in times.items()}
    assert med["ddp"] <= med["allreduce"] * 1.5, med


def test_strategy_registry():
    assert set(strategies.STRATEGIES) == {
        "single", "gather", "allreduce", "ddp", "overlap",
        "compress-bf16", "compress-int8", "powersgd"}
    with pytest.raises(ValueError):
        strategies.get_strategy("zero_redundancy")
    assert strategies.get_strategy("powersgd").rank == \
        strategies.DEFAULT_COMPRESS_RANK
    assert strategies.get_strategy("powersgd", compress_rank=2).rank == 2
    with pytest.raises(ValueError):
        strategies.PowerSGD(rank=0)
    with pytest.raises(ValueError):
        strategies.CompressedPsum("fp4")


# -- round-7 tiers: overlapped ddp + compressed collectives -------------------

def run_stateful(mesh, strategy, grads_per_device, comm):
    """Apply a stateful strategy with its per-worker comm state threaded;
    returns (synced grads [replicated], new comm [stacked per worker])."""
    f = shard_map(
        lambda g, c: strategy(jax.tree.map(lambda a: a[0], g), DATA_AXIS,
                              comm=c),
        mesh=mesh, in_specs=(P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(P(), P(DATA_AXIS)), **_SHARD_MAP_KW)
    return jax.jit(f)(grads_per_device, comm)


def test_overlap_computes_the_mean(mesh8, per_device_grads):
    expected = jax.tree.map(lambda a: jnp.mean(a, 0), per_device_grads)
    out = run_strategy(mesh8, strategies.get_strategy("overlap"),
                       per_device_grads)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
        out, expected)


def test_overlapped_ddp_drops_the_barrier_chain(mesh8):
    """The overlap tier is the ddp bucket plan WITHOUT the inter-bucket
    optimization_barrier chain: at one leaf per bucket, ddp lowers
    leaves-1 barriers while overlap lowers ZERO — each bucket's psum is
    gated only by its own gradients (the StableHLO-level pin; the chain
    DEPTH contract lives in analysis/audit.py's overlap rule)."""
    grads = tree_of_grads(jax.random.PRNGKey(1))
    stacked = jax.tree.map(lambda a: a[None].repeat(8, 0), grads)

    def counts(strategy):
        f = shard_map(lambda g: strategy(
            jax.tree.map(lambda a: a[0], g), DATA_AXIS),
            mesh=mesh8, in_specs=(P(DATA_AXIS),), out_specs=P(),
            **_SHARD_MAP_KW)
        hlo = jax.jit(f).lower(stacked).as_text()  # StableHLO MLIR
        return (len(re.findall(r"stablehlo\.all_reduce", hlo)),
                len(re.findall(r"stablehlo\.optimization_barrier", hlo)))

    assert counts(strategies.get_strategy("ddp", bucket_bytes=64)) == (4, 3)
    assert counts(strategies.get_strategy("overlap",
                                          bucket_bytes=64)) == (4, 0)
    # One 25MB bucket: same fused collective count as ddp, still no chain.
    assert counts(strategies.get_strategy("overlap")) == (4, 0)


def test_compressed_bf16_error_feedback(mesh8, per_device_grads):
    """The bf16 tier's wire mean must track the true mean within bf16
    rounding, the residual must be EXACTLY the untransmitted part
    (v - bf16(v)), and carrying it forward must not let quantization
    error accumulate across steps (the EF-SGD property)."""
    strat = strategies.get_strategy("compress-bf16")
    assert strat.stateful and strat.name == "compress-bf16"
    local_like = jax.tree.map(lambda a: a[0], per_device_grads)
    comm = strat.init_comm(local_like, 8)
    expected = jax.tree.map(lambda a: jnp.mean(a, 0), per_device_grads)

    out, new_comm = run_stateful(mesh8, strat, per_device_grads, comm)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=0, atol=2e-2),
        out, expected)
    # Residual == what this worker failed to transmit, bitwise.
    jax.tree.map(
        lambda g, r: np.testing.assert_array_equal(
            np.asarray(r),
            np.asarray(g.astype(jnp.float32)
                       - g.astype(jnp.bfloat16).astype(jnp.float32))),
        per_device_grads, new_comm["residual"])

    # Constant grads, residuals carried: the time-average of the synced
    # outputs converges on the true mean instead of repeating one step's
    # rounding error.
    outs, comm_t = [out], new_comm
    for _ in range(3):
        o, comm_t = run_stateful(mesh8, strat, per_device_grads, comm_t)
        outs.append(o)
    leaves_e = jax.tree.leaves(expected)
    for i, le in enumerate(leaves_e):
        avg = np.mean([np.asarray(jax.tree.leaves(o)[i]) for o in outs],
                      axis=0)
        one = np.max(np.abs(np.asarray(jax.tree.leaves(outs[0])[i]) - le))
        assert np.max(np.abs(avg - np.asarray(le))) <= one + 1e-6


def test_compressed_int8_shared_scale_never_overflows(mesh8):
    """Every worker at +amax is the wire's worst case: a naive per-worker
    127 scale (or an unclipped round at scale amax*world/127) sums past
    int8's 127 and wraps the mean NEGATIVE.  The shared pmax'd scale with
    the clip at L = 127 // world keeps the sum bounded — identical grads
    come back exactly, sign preserved."""
    g = {"w": jnp.full((4, 4), 3.0, jnp.float32),
         "b": jnp.full((2,), -3.0, jnp.float32)}
    stacked = jax.tree.map(lambda a: a[None].repeat(8, 0), g)
    strat = strategies.get_strategy("compress-int8")
    comm = strat.init_comm(g, 8)
    out, new_comm = run_stateful(mesh8, strat, stacked, comm)
    # amax=3, L=15, scale=1/5: v/scale = +-15 on the nose -> exact.
    np.testing.assert_allclose(np.asarray(out["w"]), 3.0, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out["b"]), -3.0, rtol=1e-6)
    jax.tree.map(lambda r: np.testing.assert_allclose(
        np.asarray(r), 0.0, atol=1e-6), new_comm["residual"])

    # Mixed magnitudes still stay within quantization distance of the
    # true mean (one scale step = amax / (127 // world)).
    keys = jax.random.split(jax.random.PRNGKey(3), 8)
    rand = jax.tree.map(
        lambda a: jnp.stack([jax.random.normal(k, a.shape) for k in keys]),
        g)
    expected = jax.tree.map(lambda a: jnp.mean(a, 0), rand)
    out2, _ = run_stateful(mesh8, strat, rand,
                           strat.init_comm(g, 8))
    amax = max(float(jnp.max(jnp.abs(l))) for l in jax.tree.leaves(rand))
    step = amax / (127 // 8)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=0, atol=step),
        out2, expected)


def test_powersgd_rank1_reconstruction_and_determinism(mesh8):
    """A rank-1 matrix is inside the rank-4 subspace, so one power-iteration
    step reconstructs it to float precision (residual ~ 0); vector leaves
    ride the bf16 fallback; and the whole tier is deterministic — a fresh
    run from the same comm state is bitwise identical."""
    u = jax.random.normal(jax.random.PRNGKey(17), (24,))
    vv = jax.random.normal(jax.random.PRNGKey(18), (6,))
    g = {"w": jnp.outer(u, vv), "b": jnp.arange(6, dtype=jnp.float32)}
    stacked = jax.tree.map(lambda a: a[None].repeat(8, 0), g)
    strat = strategies.get_strategy("powersgd")
    assert strat._low_rank(g["w"].shape) and not strat._low_rank(
        g["b"].shape)
    comm = strat.init_comm(g, 8)
    assert set(comm) == {"residual", "q"}

    out1, comm1 = run_stateful(mesh8, strat, stacked, comm)
    np.testing.assert_allclose(np.asarray(out1["w"]), np.asarray(g["w"]),
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(comm1["residual"]["w"]), 0.0,
                               atol=1e-4)
    # bf16 fallback leaf: mean within bf16 rounding.
    np.testing.assert_allclose(np.asarray(out1["b"]), np.asarray(g["b"]),
                               rtol=2e-2, atol=1e-3)

    out2, comm2 = run_stateful(mesh8, strat, stacked, comm)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), out1, out2)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), comm1, comm2)


def test_reshard_comm_conserves_residual_mass():
    """Elastic world resize: the total undelivered error-feedback mass is
    invariant (2 -> 1 -> 3), and PowerSGD Q factors stay replicated."""
    comm = {
        "residual": {"w": jnp.asarray([[1.0, -2.0], [3.0, 0.5]])},
        "q": {"000": jnp.repeat(jnp.asarray([[1.0, 2.0]])[None], 2, 0)},
    }
    down = strategies.reshard_comm(comm, 1)
    np.testing.assert_allclose(np.asarray(down["residual"]["w"]),
                               [[4.0, -1.5]])
    up = strategies.reshard_comm(down, 3)
    assert up["residual"]["w"].shape == (3, 2)
    np.testing.assert_allclose(
        np.asarray(up["residual"]["w"]).sum(0), [4.0, -1.5], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(up["q"]["000"]),
                               np.repeat([[[1.0, 2.0]]], 3, 0))
