"""Gradient-sync strategy tests on the 8-virtual-device CPU mesh.

Covers: mathematical equivalence of the three strategies (same averaged
gradient — the property the reference's Parts 2a/2b/3 rely on but never
test), bucketing round-trips, and the collective patterns in the lowered HLO.
"""

import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
try:
    from jax import shard_map
except ImportError:                      # jax < 0.6: experimental namespace
    from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from cs744_ddp_tpu.parallel import bucketing, strategies
from cs744_ddp_tpu.parallel.mesh import DATA_AXIS
from cs744_ddp_tpu.train.step import _SHARD_MAP_KW


def tree_of_grads(key, scale=1.0):
    ks = jax.random.split(key, 4)
    return {
        "conv": [{"w": jax.random.normal(ks[0], (3, 3, 8, 16)) * scale,
                  "b": jax.random.normal(ks[1], (16,)) * scale}],
        "fc": {"w": jax.random.normal(ks[2], (32, 10)) * scale,
               "b": jax.random.normal(ks[3], (10,)) * scale},
    }


def run_strategy(mesh, strategy, grads_per_device):
    """Apply a strategy to per-device gradient pytrees; return the synced
    (replicated) result.  grads leaves have a leading device axis."""
    f = shard_map(lambda g: strategy(
        jax.tree.map(lambda a: a[0], g), DATA_AXIS),
        mesh=mesh, in_specs=(P(DATA_AXIS),), out_specs=P(),
        **_SHARD_MAP_KW)
    return jax.jit(f)(grads_per_device)


@pytest.fixture
def per_device_grads(mesh8):
    n = mesh8.devices.size
    keys = jax.random.split(jax.random.PRNGKey(7), n)
    trees = [tree_of_grads(k) for k in keys]
    return jax.tree.map(lambda *ls: jnp.stack(ls), *trees)


def test_all_strategies_compute_the_mean(mesh8, per_device_grads):
    expected = jax.tree.map(lambda a: jnp.mean(a, 0), per_device_grads)
    for name in ("gather", "allreduce", "ddp"):
        out = run_strategy(mesh8, strategies.get_strategy(name),
                           per_device_grads)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-6,
                err_msg=f"strategy {name}"),
            out, expected)


def test_local_strategy_is_identity():
    grads = tree_of_grads(jax.random.PRNGKey(0))
    out = strategies.local(grads, DATA_AXIS)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b)), out, grads)


def test_bucketing_plan_partitions_all_leaves():
    grads = tree_of_grads(jax.random.PRNGKey(3))
    n_leaves = len(jax.tree.leaves(grads))
    for bucket_bytes in (64, 4096, bucketing.DEFAULT_BUCKET_BYTES):
        plan = bucketing.make_plan(grads, bucket_bytes)
        covered = sorted(i for b in plan.buckets for i in b)
        assert covered == list(range(n_leaves))  # exact partition


def test_bucketing_respects_size_bound_and_reverse_order():
    grads = {"a": jnp.zeros((1000,)), "b": jnp.zeros((1000,)),
             "c": jnp.zeros((1000,))}
    plan = bucketing.make_plan(grads, bucket_bytes=4500)  # fits 1 leaf + change
    # 4000-byte leaves, 4500-byte cap -> one leaf per bucket.
    assert plan.num_buckets == 3
    # Reverse registration order: leaf index 2 ("c") first, like DDP.
    assert plan.buckets[0] == (2,)


def test_strategy_collective_patterns_in_stablehlo(mesh8):
    """The tiers must stay observably distinct pre-optimization: the
    per-param tier is a barrier-CHAINED sequence of per-leaf all-reduces
    (Part 2b's blocking loop — leaves-1 barriers), while the ddp tier
    groups leaves into buckets with barriers only BETWEEN buckets
    (Part 3's in-order comm stream).  The compiled-level distinctness (one
    collective per leaf vs per bucket on the v5e-8 lowering) is asserted
    in tests/test_tpu_aot.py — the CPU backend here strips barriers and
    fuses both tiers (test_ddp_wallclock_not_slower_than_allreduce pins
    that convergence)."""
    grads = tree_of_grads(jax.random.PRNGKey(1))
    stacked = jax.tree.map(lambda a: a[None].repeat(8, 0), grads)

    def counts(strategy):
        f = shard_map(lambda g: strategy(
            jax.tree.map(lambda a: a[0], g), DATA_AXIS),
            mesh=mesh8, in_specs=(P(DATA_AXIS),), out_specs=P(),
            **_SHARD_MAP_KW)
        hlo = jax.jit(f).lower(stacked).as_text()  # StableHLO MLIR
        return (len(re.findall(r"stablehlo\.all_reduce", hlo)),
                len(re.findall(r"stablehlo\.optimization_barrier", hlo)))

    n_ar, n_bar = counts(strategies.get_strategy("allreduce"))
    assert (n_ar, n_bar) == (4, 3)   # per leaf, sequentially chained

    n_ar, n_bar = counts(strategies.get_strategy("ddp"))
    assert (n_ar, n_bar) == (4, 0)   # all four leaves fit one 25MB bucket

    # Tiny buckets: one leaf per bucket -> chained like DDP's comm stream.
    n_ar, n_bar = counts(strategies.get_strategy("ddp", bucket_bytes=64))
    assert (n_ar, n_bar) == (4, 3)

    # gather_scatter: all-gather + all-reduce per leaf, chained.
    f = shard_map(lambda g: strategies.gather_scatter(
        jax.tree.map(lambda a: a[0], g), DATA_AXIS),
        mesh=mesh8, in_specs=(P(DATA_AXIS),), out_specs=P(),
        **_SHARD_MAP_KW)
    hlo = jax.jit(f).lower(stacked).as_text()
    assert len(re.findall(r"stablehlo\.all_gather", hlo)) == 4
    assert len(re.findall(r"stablehlo\.all_reduce", hlo)) == 4
    assert len(re.findall(r"stablehlo\.optimization_barrier", hlo)) == 3


def test_compiled_step_reaches_ddp_grade_fusion(mesh8):
    """On the CPU BACKEND (which strips optimization barriers), the whole
    compiled train step must carry at most bucket-count all-reduces for
    BOTH the ddp and the per-param strategy: XLA's all-reduce combiner
    delivers DDP-grade fusion — the capability torch gets from DDP's C++
    reducer.  On TPU the barrier chains keep the tiers distinct instead
    (tests/test_tpu_aot.py); pre-optimization structure is pinned in
    test_strategy_collective_patterns_in_stablehlo."""
    if tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 5):
        pytest.skip("this jax's CPU backend keeps optimization barriers, so "
                    "the all-reduce combiner never sees a fusable chain; the "
                    "fusion capability is pinned on newer toolchains only")
    from tinynet import tiny_cnn

    import jax.numpy as jnp
    from cs744_ddp_tpu.ops import sgd
    from cs744_ddp_tpu.train import step as steplib

    init_fn, apply_fn = tiny_cnn()
    state = steplib.init_train_state(init_fn, jax.random.PRNGKey(0))
    imgs = jnp.zeros((64, 32, 32, 3), jnp.uint8)
    labs = jnp.zeros((64,), jnp.int32)
    for name in ("allreduce", "ddp"):
        step = steplib.make_train_step(
            apply_fn, strategies.get_strategy(name), mesh8, sgd.SGDConfig(),
            augment=False)
        txt = step.lower(state, jax.random.PRNGKey(0), imgs, labs) \
                  .compile().as_text()
        n = len(re.findall(r" all-reduce\(", txt))
        assert 1 <= n <= 2, (name, n)  # 4 grad leaves -> <= 2 collectives


@pytest.mark.slow  # ~70s: ResNet-18 compile + timed steps on the CPU mesh
def test_ddp_wallclock_not_slower_than_allreduce(mesh8):
    """Part 3's capability claim, measured: the bucketed-fused tier must not
    lose to per-param all-reduce on a model with many parameter leaves
    (ResNet-18, ~60 leaves).  On this XLA version both compile to the same
    fused collective schedule, so this pins ddp step time <= allreduce
    step time as a wall-clock invariant (margin covers CI timer noise).

    The POSITIVE separation of all three tiers (gather > allreduce > ddp
    in ms/step) is measured where the collective patterns dominate —
    tools/bench_strategy_spectrum.py, a 122-leaf comm-bound model on this
    same 8-virtual-device mesh — and recorded in BASELINE.md ("Strategy
    cost spectrum"); this test only guards the non-regression direction."""
    import time

    import jax.numpy as jnp
    from cs744_ddp_tpu.models import resnet
    from cs744_ddp_tpu.ops import sgd
    from cs744_ddp_tpu.train import step as steplib

    init_fn, apply_fn = resnet.ResNet18()
    state = steplib.init_train_state(init_fn, jax.random.PRNGKey(0))
    imgs = jnp.zeros((32, 32, 32, 3), jnp.uint8)
    labs = jnp.zeros((32,), jnp.int32)

    # Compile and warm BOTH programs first, then INTERLEAVE the timed steps:
    # back-to-back A/B pairs cancel the load drift of a shared CI host that
    # sequential per-strategy timing is exposed to.
    steps, states = {}, {}
    for name in ("allreduce", "ddp"):
        step = steplib.make_train_step(
            apply_fn, strategies.get_strategy(name), mesh8, sgd.SGDConfig(),
            augment=False)
        s = state
        for i in range(2):
            s, loss = step(s, jax.random.PRNGKey(i), imgs, labs)
            float(loss)  # value fetch = completion fence
        steps[name], states[name] = step, s

    times = {"allreduce": [], "ddp": []}
    for i in range(9):
        for name in ("allreduce", "ddp"):
            t0 = time.time()
            states[name], loss = steps[name](
                states[name], jax.random.PRNGKey(i), imgs, labs)
            float(loss)  # value fetch = completion fence
            times[name].append(time.time() - t0)

    # Median over 9 interleaved pairs: robust to per-step scheduler spikes
    # (a single outlier cannot move the median) as well as slow drift.
    med = {k: sorted(v)[len(v) // 2] for k, v in times.items()}
    assert med["ddp"] <= med["allreduce"] * 1.5, med


def test_strategy_registry():
    assert set(strategies.STRATEGIES) == {"single", "gather", "allreduce",
                                          "ddp"}
    with pytest.raises(ValueError):
        strategies.get_strategy("zero_redundancy")
