"""Worker process for the 2-process rendezvous test (tests/test_multiprocess.py).

Reproduces the reference's launch model — one manually-launched OS process
per node, rank from the command line, rendezvous at a coordinator address
(``/root/reference/src/Part 2a/main.py:148-175``) — with the TPU-native
runtime: ``jax.distributed.initialize`` (via parallel.mesh), a mesh spanning
both processes' devices, and gloo cross-process CPU collectives.

Usage: mp_worker.py <process_id> <num_processes> <port> <outdir> [strategy]
The launcher must set JAX_PLATFORMS=cpu and
XLA_FLAGS=--xla_force_host_platform_device_count=4 in the environment.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=4")

import jax

jax.config.update("jax_platforms", "cpu")

N_STEPS = 3


def main() -> None:
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    outdir = sys.argv[4]
    strategy = sys.argv[5] if len(sys.argv) > 5 else "allreduce"
    tests_dir = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, tests_dir)                    # tinynet
    sys.path.insert(0, os.path.dirname(tests_dir))   # cs744_ddp_tpu

    from cs744_ddp_tpu.parallel import mesh as meshlib

    # The runtime under test: rendezvous BEFORE any backend use.
    meshlib.initialize_distributed("127.0.0.1", nproc, pid, port=port)
    assert jax.process_count() == nproc, jax.process_count()

    import numpy as np

    from cs744_ddp_tpu.data import cifar10
    from cs744_ddp_tpu.train.loop import Trainer
    from tinynet import run_steps, tiny_cnn

    log = lambda s: print(f"[proc {pid}] {s}", flush=True)
    tr = Trainer(model=tiny_cnn(), strategy=strategy, global_batch=64,
                 data_dir=os.path.join(outdir, "data"), augment=False,
                 log=log)
    assert tr.world == jax.device_count() == 4 * nproc

    # Losses are fully replicated -> locally readable on every process.
    losses = run_steps(tr, N_STEPS)

    # Also drive the eval path across the process-spanning mesh.
    tr.test_split = cifar10.Split(tr.test_split.images[:128],
                                  tr.test_split.labels[:128])
    avg_loss, correct, _ = tr.test_model()

    flat = jax.tree.leaves(tr.state.params)
    np.savez(os.path.join(outdir, f"params_{pid}.npz"),
             losses=np.asarray(losses, np.float64),
             eval_loss=np.float64(avg_loss), eval_correct=np.int64(correct),
             **{f"p{i}": np.asarray(leaf) for i, leaf in enumerate(flat)})
    log(f"done: losses={losses} eval={avg_loss:.4f}/{correct}")


if __name__ == "__main__":
    main()
