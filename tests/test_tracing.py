"""Distributed tracing + SLO alerting tests (round 12) — all tier-1 CPU.

The pins, mirroring the ISSUE's acceptance bar:

* Wire-protocol forward compat BOTH directions: extension-free frames
  are byte-identical to the pre-round-12 layout and decode everywhere;
  extended frames decode on the old 4-tuple surface with the extension
  dropped; unknown TLV tags are skipped by length; non-extension
  trailing bytes still fail decode (torn frames never pass silently).
* Cross-process aggregation: NTP-midpoint skew correction stays within
  the RTT/2 bound even under asymmetric path delays; torn tails and
  rotated event files degrade gracefully; a replica death leaves an
  ORPHANED (complete=False) but attributable waterfall.
* The alert engine's chaos drills fire EXACTLY their expected rule ids
  (slow_replica -> STRAGGLER+SLO_BURN; publish_torn -> PUBLISH_LAG;
  clean -> none), and replaying a log yields the live alert sequence.
* The acceptance scenario: one request served across two real OS
  processes reconstructs into a single skew-corrected waterfall whose
  stage sum is bounded by the client-measured latency.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from cs744_ddp_tpu import models as model_zoo
from cs744_ddp_tpu.data import cifar10
from cs744_ddp_tpu.ft import ChaosPlan
from cs744_ddp_tpu.obs import AlertEngine, Telemetry, TraceContext
from cs744_ddp_tpu.obs import aggregate
from cs744_ddp_tpu.obs.telemetry import read_events_jsonl
from cs744_ddp_tpu.obs.tracing import (EXT_MAGIC, TAG_TRACE, new_id,
                                       pack_ext, pack_trace, unpack_ext,
                                       unpack_ext_ex, unpack_trace)
from cs744_ddp_tpu.serve import (EngineReplica, LoopbackClient,
                                 ReplicaRouter, ServingFrontend)
from cs744_ddp_tpu.serve.frontend import (decode_reply, decode_request,
                                          decode_request_ex, encode_reply,
                                          encode_request)

from tinynet import tiny_cnn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def setup_module(module):
    model_zoo.register_model("tiny", tiny_cnn)


@pytest.fixture(scope="module")
def pool():
    return cifar10._synthetic_split(64, seed=5)


# -- trace context + wire extension codec -------------------------------------


def test_trace_context_lineage():
    root = TraceContext.new_root("client")
    assert root.trace_id and root.span_id and root.parent_span_id == 0
    child = root.child("frontend")
    assert child.trace_id == root.trace_id
    assert child.parent_span_id == root.span_id
    assert child.span_id not in (0, root.span_id)
    a = child.attrs()
    assert a == {"trace_id": child.trace_id, "span_id": child.span_id,
                 "parent_span_id": root.span_id, "origin": "frontend"}
    assert all(new_id() != 0 for _ in range(64))


def test_ext_block_skips_unknown_tags_and_tolerates_torn():
    ctx = TraceContext.new_root("client")
    blob = pack_ext({TAG_TRACE: pack_trace(ctx), 99: b"future-field"})
    fields = unpack_ext(blob)
    assert unpack_trace(fields[TAG_TRACE]) == ctx
    assert fields[99] == b"future-field"       # unknown tag carried by len
    # Torn mid-field: the partial trailing field is dropped, not fatal.
    assert TAG_TRACE not in unpack_ext(blob[:6])
    # Wrong magic/version degrades to "no extension", never raises.
    assert unpack_ext(b"\x00" + blob[1:]) == {}
    assert unpack_ext(b"") == {}


def test_ext_block_counts_skipped_and_torn():
    """Round 13: ``unpack_ext_ex`` COUNTS what forward-compat skipping
    silently tolerated — unknown tags (still carried) and dropped torn
    trailing fields — so the codec can surface cross-version drift."""
    ctx = TraceContext.new_root("client")
    blob = pack_ext({TAG_TRACE: pack_trace(ctx), 99: b"future-field"})
    fields, skipped, torn = unpack_ext_ex(blob)
    assert unpack_trace(fields[TAG_TRACE]) == ctx
    assert fields[99] == b"future-field" and (skipped, torn) == (1, 0)
    # Torn trailing field: dropped and counted; earlier fields survive.
    fields, skipped, torn = unpack_ext_ex(blob[:-1])
    assert TAG_TRACE in fields and 99 not in fields
    assert (skipped, torn) == (0, 1)
    # A clean all-known block counts nothing.
    clean = pack_ext({TAG_TRACE: pack_trace(ctx)})
    assert unpack_ext_ex(clean)[1:] == (0, 0)
    # Missing/unversioned blocks stay zero-count empty, never raising.
    assert unpack_ext_ex(b"") == ({}, 0, 0)
    assert unpack_ext_ex(b"\x00" + blob[1:]) == ({}, 0, 0)


def test_wire_ext_skipped_counter_emission(pool):
    """The decoders feed skip/torn counts into the ``wire_ext_skipped``
    telemetry counter, attributed per frame kind — and clean frames
    emit nothing."""
    root = TraceContext.new_root("client")
    traced = encode_request(4, pool.images[:2], tier=2, slo_ms=25.0,
                            ctx=root)

    def skips(tel):
        return [r for r in tel.records if r.get("kind") == "counter"
                and r.get("name") == "wire_ext_skipped"]

    tel = Telemetry()
    assert decode_request_ex(traced, tel)[4] == root
    assert skips(tel) == []                    # same-build frame: silent
    future = traced + pack_ext({7: b"xyz"})[2:]
    assert decode_request_ex(future, tel)[4] == root
    (rec,) = skips(tel)
    assert (rec["inc"], rec["unknown"], rec["torn"]) == (1, 1, 0)
    assert rec["frame"] == "request"
    # A torn trailing field on a reply counts on the reply side; the
    # known fields still decode.
    logits = np.arange(20, dtype=np.float32).reshape(2, 10)
    rep = {"status": "ok", "trace": 5, "logits": logits, "reason": "",
           "queue_wait_ms": 1.0, "service_ms": 2.0, "retry_after_ms": 0.0}
    timed = encode_reply(9, rep, t_recv=10.5, t_send=10.75)
    torn = timed + pack_ext({7: b"xyz"})[2:-1]
    tel2 = Telemetry()
    out = decode_reply(torn, tel2)
    assert (out["t_recv"], out["t_send"]) == (10.5, 10.75)
    (rec,) = skips(tel2)
    assert (rec["inc"], rec["unknown"], rec["torn"]) == (1, 0, 1)
    assert rec["frame"] == "reply"


def test_telemetry_report_wire_ext_section(tmp_path, monkeypatch, pool):
    """tools/telemetry_report surfaces the skip counts as a
    ``== wire extension skips ==`` section — absent on same-build runs."""
    monkeypatch.syspath_prepend(os.path.join(REPO, "tools"))
    import telemetry_report
    root = TraceContext.new_root("client")
    traced = encode_request(4, pool.images[:2], tier=2, slo_ms=25.0,
                            ctx=root)
    future = traced + pack_ext({7: b"xyz"})[2:]
    run = tmp_path / "run"
    tel = Telemetry(str(run))
    decode_request_ex(future, tel)
    tel.finalize()
    text = telemetry_report.render(str(run))
    assert "== wire extension skips ==" in text
    assert "request" in text and "unknown tags skipped 1" in text

    plain = tmp_path / "plain"
    tel2 = Telemetry(str(plain))
    tel2.step(epoch=0, iter=0, loss=1.0, step_time=0.01)
    tel2.finalize()
    assert "wire extension" not in telemetry_report.render(str(plain))


def test_wire_request_compat_both_directions(pool):
    imgs = pool.images[:2]
    # Direction 1: NEW encoder, tracing off -> byte-identical to the
    # pre-round-12 frame (zero wire cost), and ctx decodes as None.
    plain = encode_request(3, imgs, tier=1, slo_ms=50.0)
    assert plain == encode_request(3, imgs, tier=1, slo_ms=50.0, ctx=None)
    req_id, out, tier, slo, ctx = decode_request_ex(plain)
    assert (req_id, tier, slo, ctx) == (3, 1, 50.0, None)
    assert np.array_equal(out, imgs)
    # Direction 2: NEW traced frame on the OLD 4-tuple surface — the
    # extension is tolerated and dropped, images bitwise intact.
    root = TraceContext.new_root("client")
    traced = encode_request(4, imgs, tier=2, slo_ms=25.0, ctx=root)
    assert traced[:len(plain)] != plain        # different header fields
    req_id, out, tier, slo = decode_request(traced)
    assert (req_id, tier, slo) == (4, 2, 25.0)
    assert np.array_equal(out, imgs)
    # And the new surface recovers the full context.
    *_, ctx2 = decode_request_ex(traced)
    assert ctx2 == root
    # A future field rides along without breaking today's decoder.
    future = traced + pack_ext({7: b"xyz"})[2:]   # splice extra TLV
    assert decode_request_ex(future)[4] == root
    # Non-extension trailing garbage is a TORN frame: still fails.
    with pytest.raises(ValueError, match="not an extension block"):
        decode_request_ex(plain + b"garbage!")


def test_wire_reply_compat_both_directions():
    logits = np.arange(20, dtype=np.float32).reshape(2, 10)
    rep = {"status": "ok", "trace": 5, "logits": logits, "reason": "",
           "queue_wait_ms": 1.0, "service_ms": 2.0, "retry_after_ms": 0.0}
    plain = encode_reply(9, rep)
    out = decode_reply(plain)
    assert "t_recv" not in out and np.array_equal(out["logits"], logits)
    timed = encode_reply(9, rep, t_recv=10.5, t_send=10.75)
    assert timed[:len(plain)] == plain         # strictly trailing ext
    assert timed[len(plain)] == EXT_MAGIC
    out = decode_reply(timed)
    assert (out["t_recv"], out["t_send"]) == (10.5, 10.75)
    assert np.array_equal(out["logits"], logits)
    with pytest.raises(ValueError, match="not an extension block"):
        decode_reply(plain + b"\x00\x01")


# -- aggregation --------------------------------------------------------------


def _span(name, t, dur, ctx, **extra):
    return {"kind": "span", "name": name, "t": t, "dur_s": dur,
            **ctx.attrs(), **extra}


def _stream_pair(n=20, offset=5.0, d_req=0.001, d_rep=0.009):
    """Client+server streams with a KNOWN clock offset and asymmetric
    path delays: request leg ``d_req``, reply leg ``d_rep`` seconds."""
    client, server = [], []
    for i in range(n):
        root = TraceContext.new_root("client")
        t1 = 100.0 + i
        t2 = t1 + d_req + offset          # server clock
        t3 = t2 + 0.002
        t4 = (t3 - offset) + d_rep        # back on the client clock
        client.append(_span("trace_client", t1, t4 - t1, root))
        server.append(_span("frontend_request", t2, t3 - t2,
                            root.child("frontend")))
    return (aggregate.ProcessStream("client", client),
            aggregate.ProcessStream("server", server))


def test_skew_asymmetric_rtt_stays_within_bound():
    # NTP midpoint under ASYMMETRIC legs: the estimate is biased by
    # (d_req - d_rep)/2 but the reported rtt bound must still cover the
    # true offset — that inequality is the whole point of the bound.
    d_req, d_rep, offset = 0.001, 0.009, 5.0
    cli, srv = _stream_pair(offset=offset, d_req=d_req, d_rep=d_rep)
    est = aggregate.estimate_offsets([srv, cli])
    # Server (reference) pinned at zero; client estimated from all pairs.
    assert est["server"] == aggregate.ClockEstimate(0.0, 0.0, 0, True)
    c = est["client"]
    assert c.estimated and c.n_pairs == 20
    assert c.offset_s == pytest.approx(offset + (d_req - d_rep) / 2.0,
                                       abs=1e-9)
    assert abs(c.offset_s - offset) <= c.rtt_bound_s + 1e-12
    assert c.rtt_bound_s == pytest.approx((d_req + d_rep) / 2.0, abs=1e-9)
    # The merged spans land on ONE timeline: client span starts before
    # the server window it encloses, despite the 5s raw clock gap.
    report = aggregate.aggregate_streams([srv, cli])
    assert report["reference"] == "server"
    assert report["traces"] == 20 and report["orphaned"] == 20  # no stages
    traces = aggregate.merge_traces([srv, cli], est)
    for spans in traces.values():
        assert [s["name"] for s in spans] == ["trace_client",
                                              "frontend_request"]


def test_aggregate_rotated_and_torn_event_files(tmp_path):
    # One trace's spans split across a ROTATED generation and the live
    # file, with a torn half-written line at the tail: the reader counts
    # the bad line, and the waterfall still reconstructs COMPLETE.
    root = TraceContext.new_root("client")
    sched = root.child("sched")
    d = tmp_path / "server"
    d.mkdir()
    old = [_span("wire_decode", 1.0, 0.001, root.child("frontend")),
           _span("sched_queue", 1.001, 0.002, sched, trace=7, bucket=2)]
    new = [_span("serve_dispatch", 1.003, 0.004,
                 TraceContext(0, 0, 0, ""), traces=[7], bucket=2),
           _span("reply_encode", 1.008, 0.001, root.child("frontend"))]
    new[0].pop("trace_id")        # batch spans carry traces=, not trace_id
    (d / "events.1.jsonl").write_text(
        "\n".join(json.dumps(e) for e in old) + "\n")
    (d / "events.jsonl").write_text(
        "\n".join(json.dumps(e) for e in new) + "\n"
        + '{"kind": "span", "name": "torn')      # killed mid-write
    cli = tmp_path / "client"
    cli.mkdir()
    (cli / "events.jsonl").write_text(
        json.dumps(_span("trace_client", 0.999, 0.012, root, trace=7))
        + "\n")
    report = aggregate.aggregate_run_dirs([str(d), str(cli)])
    assert report["processes"]["server"]["bad_lines"] == 1
    assert report["traces"] == 1 and report["complete"] == 1
    (w,) = report["waterfalls"]
    assert w["complete"] and w["bucket"] == 2
    assert set(w["stages"]) == {"wire_decode", "queue_wait",
                                "device_compute", "reply_encode"}
    assert w["client_ms"] == pytest.approx(12.0)


def test_aggregate_directory_with_only_rotated_generations(tmp_path):
    """Round 13 satellite: a process killed right after rotation leaves a
    directory with ONLY ``events.N.jsonl`` generations — no live
    ``events.jsonl``.  The reader must still yield the generations
    oldest-first and the multi-directory merge must reconstruct the
    cross-process waterfall COMPLETE."""
    root = TraceContext.new_root("client")
    sched = root.child("sched")
    d = tmp_path / "server"
    d.mkdir()
    gen1 = [_span("wire_decode", 1.0, 0.001, root.child("frontend")),
            _span("sched_queue", 1.001, 0.002, sched, trace=7, bucket=2)]
    gen2 = [_span("serve_dispatch", 1.003, 0.004,
                  TraceContext(0, 0, 0, ""), traces=[7], bucket=2),
            _span("reply_encode", 1.008, 0.001, root.child("frontend"))]
    gen2[0].pop("trace_id")       # batch spans carry traces=, not trace_id
    # Rotation numbers count up from the most recent: .2 is OLDER than .1.
    (d / "events.2.jsonl").write_text(
        "\n".join(json.dumps(e) for e in gen1) + "\n")
    (d / "events.1.jsonl").write_text(
        "\n".join(json.dumps(e) for e in gen2) + "\n")
    # No live events.jsonl: the reader tolerates its absence and keeps
    # generation order.
    events, bad = read_events_jsonl(str(d / "events.jsonl"))
    assert bad == 0
    assert [e["name"] for e in events] == ["wire_decode", "sched_queue",
                                           "serve_dispatch", "reply_encode"]
    cli = tmp_path / "client"
    cli.mkdir()
    (cli / "events.jsonl").write_text(
        json.dumps(_span("trace_client", 0.999, 0.012, root, trace=7))
        + "\n")
    report = aggregate.aggregate_run_dirs([str(d), str(cli)])
    assert report["processes"]["server"]["bad_lines"] == 0
    assert report["traces"] == 1 and report["complete"] == 1
    (w,) = report["waterfalls"]
    assert w["complete"] and set(w["procs"]) == {"client", "server"}
    assert set(w["stages"]) == {"wire_decode", "queue_wait",
                                "device_compute", "reply_encode"}


def test_replica_death_leaves_attributable_orphan(pool):
    # Chaos kills the ONLY replica at dispatch 0: the request resolves
    # (error reply — no silent drop), and its trace renders as an
    # ORPHANED waterfall whose surviving spans still attribute the
    # origins that ran.  chaos_fired telemetry marks the injection.
    model_zoo.register_model("tiny", tiny_cnn)
    tel = Telemetry()
    chaos = ChaosPlan.parse(["replica_death:0:0"])
    replica = EngineReplica(0, model="tiny", buckets=(2,), seed=0,
                            chaos=chaos, telemetry=tel)
    router = ReplicaRouter([replica], telemetry=tel)
    with router:
        client = LoopbackClient(router, telemetry=tel)
        rep = client.request(pool.images[:2], slo_ms=None)
    assert rep["status"] == "error"
    assert ("replica_death", 0) in chaos.fired
    events = tel.records
    assert any(e.get("kind") == "counter" and e.get("name") == "chaos_fired"
               and e.get("site") == "replica_death" for e in events)
    report = aggregate.aggregate_streams(
        [aggregate.ProcessStream("proc", list(events))])
    assert report["complete"] == 0 and report["orphaned"] >= 1
    w = report["waterfalls"][0]
    assert not w["complete"]
    assert "device_compute" not in w["stages"]
    assert "client" in w["origins"]          # attributable to its hops


def test_loopback_trace_spans_one_process(pool):
    # Tracing through the in-process client: every hop parents under the
    # client root, per-request spans carry the batcher trace id, and the
    # stage sum is bounded by the client-measured round-trip.
    model_zoo.register_model("tiny", tiny_cnn)
    tel = Telemetry()
    replica = EngineReplica(0, model="tiny", buckets=(2,), seed=0,
                            telemetry=tel)
    replica.startup()
    router = ReplicaRouter([replica], telemetry=tel)
    with router:
        client = LoopbackClient(router, telemetry=tel)
        client.request(pool.images[:2], slo_ms=None)     # warm compile
        rep = client.request(pool.images[:2], slo_ms=None)
    assert rep["status"] == "ok"
    report = aggregate.aggregate_streams(
        [aggregate.ProcessStream("proc", list(tel.records))])
    complete = [w for w in report["waterfalls"] if w["complete"]]
    assert complete
    w = complete[-1]
    assert "device_compute" in w["stages"] and "queue_wait" in w["stages"]
    assert 0.0 < w["sum_ms"] <= w["client_ms"] + 0.1
    spans = [e for e in tel.records
             if e.get("kind") == "span" and e.get("trace_id")]
    child = next(e for e in spans if e["name"] == "sched_queue"
                 and e["trace_id"] == w["trace_id"])
    assert child["parent_span_id"] != 0          # parented, not floating
    assert child["origin"] == "sched"
    root = next(e for e in spans if e["name"] == "trace_client"
                and e["trace_id"] == w["trace_id"])
    assert root["parent_span_id"] == 0           # the client minted it


# -- alert engine chaos drills ------------------------------------------------


def _healthy_events(t0=0.0):
    evs = []
    for i in range(80):
        t = t0 + 0.05 * i
        evs.append({"kind": "gauge", "name": "serve_latency_ms", "t": t,
                    "value": 5.0, "met": True, "tier": 0})
        evs.append({"kind": "gauge", "name": "serve_queue_depth", "t": t,
                    "value": 4, "replica": i % 2})
        evs.append({"kind": "gauge", "name": "serve_service_ms", "t": t,
                    "value": 2.0 + (i % 2), "replica": i % 2})
    evs.append({"kind": "gauge", "name": "publish_version", "t": t0 + 4.0,
                "value": 3})
    evs.append({"kind": "gauge", "name": "installed_version",
                "t": t0 + 4.1, "value": 3})
    return evs


def test_alert_drill_clean_run_fires_nothing():
    eng = AlertEngine()
    eng.run(_healthy_events())
    assert eng.fired_rules() == []
    assert eng.summary() == {"fired": [], "by_rule": {}, "total": 0}


def test_alert_drill_slow_replica_exact_rules():
    # The slow_replica signature: one replica's service EWMA far above
    # its peer, every request late.  EXACTLY straggler + burn-rate fire
    # — not shed-rate, not queue-depth, not publish-lag.
    evs = []
    for i in range(70):
        t = 0.1 * i
        evs.append({"kind": "gauge", "name": "serve_service_ms", "t": t,
                    "value": 500.0 if i % 2 == 0 else 5.0,
                    "replica": i % 2})
        evs.append({"kind": "gauge", "name": "serve_latency_ms", "t": t,
                    "value": 400.0, "met": False, "tier": 0})
    eng = AlertEngine()
    eng.run(evs)
    assert eng.fired_rules() == ["SLO_BURN", "STRAGGLER"]
    burn = next(a for a in eng.alerts if a.rule == "SLO_BURN")
    assert burn.attrs["attainment"] == 0.0
    strag = next(a for a in eng.alerts if a.rule == "STRAGGLER")
    assert strag.attrs["replica"] == 0


def test_alert_drill_publish_torn_exact_rules():
    # The publish_torn signature: the watcher REJECTS a corrupt bundle
    # (crc) while serving stays healthy — publish-lag only.
    evs = _healthy_events()
    evs.append({"kind": "counter", "name": "publish_rejected", "t": 4.2,
                "inc": 1, "why": "crc"})
    eng = AlertEngine()
    eng.run(evs)
    assert eng.fired_rules() == ["PUBLISH_LAG"]
    (alert,) = [a for a in eng.alerts if a.rule == "PUBLISH_LAG"]
    assert alert.attrs == {"counter": "publish_rejected", "reason": "crc"}


def test_alert_publish_lag_is_time_driven_and_cooldown_event_time():
    # installed_version trailing publish_version for > publish_lag_s of
    # EVENT time trips the lag rule; the cooldown is event-time too, so
    # replaying the log reproduces the live alert count exactly.
    evs = [{"kind": "gauge", "name": "publish_version", "t": 0.0,
            "value": 2},
           {"kind": "gauge", "name": "installed_version", "t": 0.1,
            "value": 1}]
    evs += [{"kind": "gauge", "name": "serve_queue_depth", "t": t,
             "value": 1} for t in (2.0, 6.0, 7.0, 12.0)]
    live = AlertEngine(publish_lag_s=5.0, cooldown_s=5.0)
    fired = [a.rule for e in evs for a in live.observe(e)]
    assert fired == ["PUBLISH_LAG", "PUBLISH_LAG"]    # t=6 then t=12
    replay = AlertEngine(publish_lag_s=5.0, cooldown_s=5.0)
    replay.run(evs)
    assert [(a.rule, a.t) for a in replay.alerts] == \
        [(a.rule, a.t) for a in live.alerts]


def test_alert_live_tap_slow_replica_chaos(pool):
    # LIVE drill: real engines, chaos slow_replica stalls replica 0's
    # first dispatch, the engine rides the telemetry tap.  With shedding
    # off and an unmeetable SLO the drill fires exactly straggler +
    # burn-rate, and the alerts land in the event stream as kind=alert.
    model_zoo.register_model("tiny", tiny_cnn)
    tel = Telemetry()
    alerts = AlertEngine(tel, burn_window=4, straggler_min_steps=1,
                         cooldown_s=0.0)
    tel.add_tap(alerts.observe)
    chaos = ChaosPlan.parse(["slow_replica:0:0"])
    replicas = [EngineReplica(i, model="tiny", buckets=(2,), seed=0,
                              chaos=chaos, slow_stall_s=0.3, shed=False,
                              telemetry=tel)
                for i in range(2)]
    for r in replicas:
        r.startup()
    router = ReplicaRouter(replicas, telemetry=tel)
    with router:
        client = LoopbackClient(router, telemetry=tel)
        futs = [client.submit(pool.images[:2], slo_ms=0.01)
                for _ in range(6)]
        statuses = [f.result(30.0)["status"] for f in futs]
    assert statuses == ["late"] * 6            # served, never dropped
    assert ("slow_replica", 0) in chaos.fired
    assert alerts.fired_rules() == ["SLO_BURN", "STRAGGLER"]
    assert any(a.rule == "STRAGGLER" and a.attrs["replica"] == 0
               for a in alerts.alerts)
    assert any(e.get("kind") == "alert" and e.get("rule") == "SLO_BURN"
               for e in tel.records)


# -- two OS processes -> one waterfall (the acceptance scenario) --------------


def test_two_process_waterfall_acceptance(tmp_path):
    # A real second OS process (tools/serve_load.py) replays requests
    # over the socket; merging both processes' event files reconstructs
    # skew-corrected end-to-end waterfalls: pairs estimated, stages from
    # BOTH processes, stage sum bounded by the client's measured
    # round-trip (the residual is wire + scheduling gaps, never
    # negative beyond the skew bound).
    model_zoo.register_model("tiny", tiny_cnn)
    srv_dir, cli_dir = str(tmp_path / "server"), str(tmp_path / "client")
    stel = Telemetry(srv_dir)
    replica = EngineReplica(0, model="tiny", buckets=(2, 4), seed=0,
                            telemetry=stel)
    replica.startup()
    router = ReplicaRouter([replica], telemetry=stel)
    with router:
        with ServingFrontend(router, telemetry=stel) as fe:
            # Warm every bucket OUTSIDE the traced window so cold
            # compiles don't ride the measured waterfalls.
            warm = LoopbackClient(router)
            for b in (2, 4):
                warm.submit(np.zeros((b, 32, 32, 3), np.uint8),
                            slo_ms=None).result(60.0)
            proc = subprocess.run(
                [sys.executable,
                 os.path.join(REPO, "tools", "serve_load.py"), "replay",
                 "--port", str(fe.address[1]), "--rps", "40",
                 "--requests", "12", "--max-size", "4",
                 "--telemetry-out", cli_dir, "--timeout", "60"],
                capture_output=True, text=True, timeout=180)
    assert proc.returncode == 0, proc.stderr[-800:]
    stats = json.loads(proc.stdout.strip().splitlines()[-1])
    assert stats["replies"] == 12 and stats["unresolved"] == 0
    stel.finalize()
    report = aggregate.aggregate_run_dirs([srv_dir, cli_dir])
    assert report["reference"] == "server"
    cli = report["processes"]["client"]
    assert cli["skew_estimated"] and cli["skew_pairs"] >= 10
    assert report["complete"] >= 10
    spanning = [w for w in report["waterfalls"]
                if w["complete"] and set(w["procs"]) == {"client",
                                                         "server"}]
    assert spanning
    for w in spanning:
        assert "device_compute" in w["stages"]
        assert {"client", "frontend", "sched"} <= set(w["origins"])
        # Stage sum vs client-measured latency: sum <= client + skew
        # tolerance; the residual is the un-spanned wire/callback time.
        assert w["sum_ms"] <= w["client_ms"] + 2e3 * cli["rtt_bound_s"]
    res = report["client_minus_stages_ms"]
    assert res["p50"] > -2e3 * cli["rtt_bound_s"]
    assert res["p50"] < 250.0                 # sane on a loaded CI host


def test_trace_waterfall_cli_renders(tmp_path, monkeypatch):
    # tools/trace_waterfall.py over synthetic two-process dirs: human
    # rendering names the reference clock and the skew estimate, and
    # --json round-trips the report.
    monkeypatch.syspath_prepend(os.path.join(REPO, "tools"))
    import trace_waterfall
    cli, srv = _stream_pair(n=4)
    for name, stream in (("client", cli), ("server", srv)):
        d = tmp_path / name
        d.mkdir()
        (d / "events.jsonl").write_text(
            "\n".join(json.dumps(e) for e in stream.events) + "\n")
    out = []
    monkeypatch.setattr("builtins.print", lambda *a, **k: out.append(
        " ".join(str(x) for x in a)))
    rc = trace_waterfall.main([str(tmp_path / "server"),
                               str(tmp_path / "client")])
    assert rc == 0
    text = "\n".join(out)
    assert "reference clock" in text and "server" in text
    assert "offset" in text
    out.clear()
    assert trace_waterfall.main([str(tmp_path / "server"),
                                 str(tmp_path / "client"), "--json"]) == 0
    parsed = json.loads("\n".join(out))
    assert parsed["reference"] == "server"
    assert parsed["processes"]["client"]["skew_pairs"] == 4


def test_telemetry_report_waterfall_and_alert_sections(tmp_path,
                                                       monkeypatch):
    # The run report grows ``== waterfall ==`` and ``== alerts ==``
    # sections when the stream carries traced spans / alert records —
    # and stays absent-safe for pre-round-12 runs.
    monkeypatch.syspath_prepend(os.path.join(REPO, "tools"))
    import telemetry_report

    traced = tmp_path / "traced"
    tel = Telemetry(str(traced))
    root = TraceContext.new_root("client")
    t0 = time.time()
    tel.span_event("trace_client", t0, 0.010, **root.attrs())
    tel.span_event("sched_queue", t0 + 0.001, 0.002, trace=1,
                   **root.child("sched").attrs())
    tel.alert("SLO_BURN", "page", attainment=0.5)
    tel.finalize()
    text = telemetry_report.render(str(traced))
    assert "== waterfall (distributed traces, this stream) ==" in text
    assert "== alerts ==" in text
    assert "SLO_BURN" in text

    plain = tmp_path / "plain"
    tel2 = Telemetry(str(plain))
    tel2.step(epoch=0, iter=0, loss=1.0, step_time=0.01)
    tel2.finalize()
    text2 = telemetry_report.render(str(plain))
    assert "== waterfall" not in text2 and "== alerts" not in text2
