"""Static HBM liveness certifier + K-epoch feasibility planner (round 20).

* ``hlo_ir.type_bytes`` / ``result_bytes`` — structural byte sizes,
  tuple-recursive and layout/tiling-tolerant, pinned on a committed
  fixture and proven DIFFERENTIALLY against the legacy regex summer
  (``stats.bytes_of_type``) over every committed fixture.
* ``memlife.mem_report`` — the liveness sweep: peak bytes pinned by hand
  on the committed donated/undonated window pair; the donation delta IS
  the carried state bytes; while trip counts do not multiply the peak
  (steady-state model); donation must round-trip as an aliased-bytes
  equality.
* ``audit`` integration — the ``peak-memory`` rule fails a program over
  its ``hbm_budget_bytes`` contract and passes under it; every audited
  program carries ``peak_mib`` in its stats.
* Differential vs the executable — the static peak must never sit under
  XLA's ``memory_analysis()`` temp+output floor (checked on a REAL
  compiled window) and the synthetic unsound/unmoored paths fire.
* Runtime cross-check — a real windowed train run's ``memory`` gauge
  (live device bytes) stays under the window's static certificate.
* ``megaplan`` — the closed form unit-pinned against hand-computed
  slab/ring/state bytes; concrete vgg11 max-K at worlds 1/2/8 @ 16 GiB;
  monotone in budget, non-increasing in window padding.
* Repo self-checks — v5e literals single-sourced, fixture invariants
  hold, and both produce ``lint_graft --json``-shaped findings on
  seeded violations.
* ``tools/telemetry_report.py`` — the ``== memory ==`` section renders
  measured-vs-certified and stays absent for runs with no signal.
"""

import glob
import json
import os
import types

import pytest

from cs744_ddp_tpu import models as model_zoo
from cs744_ddp_tpu.analysis import audit as auditlib
from cs744_ddp_tpu.analysis import (costmodel, dispatch, hlo_ir, megaplan,
                                    memlife, stats)
from cs744_ddp_tpu.obs import Telemetry
from cs744_ddp_tpu.train.loop import Trainer

from tinynet import tiny_cnn

ASSETS = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "assets", "hlo")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DONATED = open(os.path.join(REPO, memlife.FIXTURE_DONATED)).read()
UNDONATED = open(os.path.join(REPO, memlife.FIXTURE_UNDONATED)).read()


# ---------------------------------------------------------------------------
# structural byte sizes (satellite: hlo_ir.type_bytes / result_bytes)
# ---------------------------------------------------------------------------

def test_type_bytes_pins():
    # Layout + tiling annotations are size-irrelevant and ignored.
    assert hlo_ir.type_bytes("f32[128,64]{1,0:T(8,128)}") == 128 * 64 * 4
    assert hlo_ir.type_bytes("u8[2,32,32,3]{3,2,1,0}") == 2 * 32 * 32 * 3
    assert hlo_ir.type_bytes("bf16[3,5]") == 30
    assert hlo_ir.type_bytes("f32[]") == 4
    # Size-less leaves contribute nothing.
    assert hlo_ir.type_bytes("token[]") == 0
    assert hlo_ir.type_bytes(None) == 0
    # Tuples recurse; nesting and scalar members included.
    assert hlo_ir.type_bytes("(f32[2,3], (s32[4], pred[]))") == 24 + 16 + 1


def test_result_bytes_fixture_pins():
    mod = hlo_ir.parse(
        open(os.path.join(ASSETS, "memlife_types.hlo")).read())
    by = {i.name: i for i in mod.entry_computation.instructions.values()}
    assert hlo_ir.result_bytes(by["big"]) == 32768
    assert hlo_ir.result_bytes(by["img"]) == 6144
    assert hlo_ir.result_bytes(by["half"]) == 30
    assert hlo_ir.result_bytes(by["tok"]) == 0
    assert hlo_ir.result_bytes(by["pair"]) == 41


def test_result_bytes_differential_vs_legacy():
    """Old == new on EVERY instruction of every committed fixture: the
    structural recursion and the legacy regex sum must agree, or one of
    them mis-sizes real lowerings."""
    total = 0
    for path in sorted(glob.glob(os.path.join(ASSETS, "*.hlo"))):
        mod = hlo_ir.parse(open(path).read())
        for ins in mod.instructions():
            assert hlo_ir.result_bytes(ins) == \
                stats.bytes_of_type(ins.result_type), \
                f"{os.path.basename(path)}:{ins.name} {ins.result_type}"
            total += 1
    assert total > 100   # the sweep actually covered the corpus


def test_dtype_bytes_single_copy():
    """stats aliases the canonical table — same object, not a fork."""
    assert stats._DTYPE_BYTES is hlo_ir.DTYPE_BYTES


# ---------------------------------------------------------------------------
# liveness sweep: hand-pinned peaks on the committed window pair
# ---------------------------------------------------------------------------
# Both fixtures: w0/m0 = f32[64,10] (2560 B each), i0 = s32[] (4 B) carried
# through a 4-trip while.  Donated: params 5124 + while spike (body fresh
# carry 5124 + cond pred/consts 4) = 10252.  Undonated: + a 5124 B
# carry-copy (XLA's copy-insertion for a live caller-held operand).

def test_liveness_pins_donated():
    rep = memlife.mem_report(DONATED, "fixture/donated")
    assert rep.peak_bytes == 10252
    assert rep.param_bytes == 5124
    assert rep.donated_bytes == 5124
    assert rep.carry_bytes == 5124
    assert rep.undonated_copy_bytes == 0
    assert rep.peak_mib == pytest.approx(10252 / 2**20)
    assert rep.top_sets and rep.top_sets[0]["live_bytes"] == 10252
    members = dict(rep.top_sets[0]["members"])
    assert members["w0"] == 2560 and members["i0"] == 4


def test_liveness_donation_delta_is_carry_bytes():
    """The tentpole's proof obligation: donated vs undonated twins differ
    by EXACTLY the carried state bytes — donation proven in bytes, not
    by attribute presence."""
    don = memlife.mem_report(DONATED, "fixture/donated")
    und = memlife.mem_report(UNDONATED, "fixture/undonated")
    assert und.peak_bytes == 15376
    assert und.undonated_copy_bytes == 5124
    assert und.peak_bytes - don.peak_bytes == und.undonated_copy_bytes


def test_liveness_steady_state_trip_invariance():
    """A while body's peak is charged ONCE (steady state): multiplying
    the trip count 100x must not move the static peak."""
    hot = DONATED.replace("constant(4)", "constant(400)")
    assert "constant(400)" in hot
    assert memlife.mem_report(hot, "hot").peak_bytes == \
        memlife.mem_report(DONATED, "don").peak_bytes


def test_donation_alias_equality():
    # The committed donated fixture round-trips: every donated param leaf
    # has a same-size output leaf to alias.
    mod = hlo_ir.parse(DONATED)
    assert memlife.donation_alias_findings(mod, "fixture/donated") == []
    # Seeded violation: donates an f32[8] but outputs only an f32[4] —
    # the donation cannot round-trip in place.
    bad = hlo_ir.parse("""\
HloModule bad_donor, buffer_donor={ (0, {}) }

ENTRY main {
  p = f32[8] parameter(0)
  ROOT s = f32[4] slice(p), slice={[0:4]}
}
""")
    msgs = memlife.donation_alias_findings(bad, "bad")
    assert msgs and "cannot round-trip" in msgs[0]


# ---------------------------------------------------------------------------
# audit integration: the peak-memory rule and the per-program stat
# ---------------------------------------------------------------------------

def test_audit_peak_memory_rule_budget():
    over = auditlib.audit_program(UNDONATED, auditlib.ProgramContract(
        name="mem/fixture", hbm_budget_bytes=10_000))
    assert over.rules["peak-memory"] == "fail"
    assert any(f.rule == "peak-memory" for f in over.findings)
    under = auditlib.audit_program(UNDONATED, auditlib.ProgramContract(
        name="mem/fixture", hbm_budget_bytes=2**20))
    assert under.rules["peak-memory"] == "pass"
    assert under.stats["peak_mib"] == pytest.approx(15376 / 2**20, abs=1e-3)


def test_audit_default_budget_is_chip_capacity():
    """hbm_budget_bytes=0 means the single-sourced v5e capacity — the
    fixture sits miles under it."""
    rep = auditlib.audit_program(DONATED, auditlib.ProgramContract(
        name="mem/fixture"))
    assert rep.rules["peak-memory"] == "pass"


# ---------------------------------------------------------------------------
# differential vs the executable: never under XLA's own accounting
# ---------------------------------------------------------------------------

def test_check_against_compiled_synthetic_paths():
    rep = memlife.mem_report(DONATED, "fixture/donated")
    # Unsound: compiled floor above the static peak.
    ms = types.SimpleNamespace(temp_size_in_bytes=20_000,
                               output_size_in_bytes=5_000,
                               argument_size_in_bytes=0)
    bad = memlife.check_against_compiled(rep, ms)
    assert bad and "UNDER the compiled floor" in bad[0]
    # Unmoored: windowed bound far beyond band x compiled total.
    ms2 = types.SimpleNamespace(temp_size_in_bytes=10,
                                output_size_in_bytes=10,
                                argument_size_in_bytes=10)
    loose = memlife.check_against_compiled(rep, ms2, windowed=True)
    assert loose and "unmoored" in loose[0]
    # Sane stats: clean.
    ms3 = types.SimpleNamespace(temp_size_in_bytes=5_000,
                                output_size_in_bytes=5_124,
                                argument_size_in_bytes=5_124)
    assert memlife.check_against_compiled(rep, ms3, windowed=True) == []


def test_static_bound_covers_real_compiled_window():
    """Lower AND compile the real train window; the static peak must
    clear ``memory_analysis()``'s temp+output floor and stay within the
    declared band — the certifier's soundness contract on a living
    executable, not just fixtures."""
    model_zoo.register_model("tiny", tiny_cnn)
    lowered, name = megaplan.lower_window(
        "tiny", world=4, window=3, global_batch=64)
    rep = memlife.mem_report(auditlib._hlo_text(lowered), name)
    ms = lowered.compile().memory_analysis()
    floor = ((getattr(ms, "temp_size_in_bytes", 0) or 0)
             + (getattr(ms, "output_size_in_bytes", 0) or 0))
    assert rep.peak_bytes >= floor
    assert memlife.check_against_compiled(rep, ms, windowed=True) == []


# ---------------------------------------------------------------------------
# runtime cross-check: measured residency under the certificate
# ---------------------------------------------------------------------------

def test_runtime_memory_gauge_under_certificate(tmp_path, mesh4):
    """A real windowed run's per-boundary ``memory`` gauge (live device
    bytes) must sit under the window program's static peak — the
    certificate bounds what the process actually holds."""
    model_zoo.register_model("tiny", tiny_cnn)
    tel = Telemetry()
    tr = Trainer(model=tiny_cnn(), strategy="ddp", mesh=mesh4,
                 global_batch=64, data_dir=str(tmp_path), augment=True,
                 limit_train_batches=9, limit_eval_batches=2,
                 log=lambda s: None, telemetry=tel)
    tr.train_model(0)
    gauges = [r["value"] for r in tel.records
              if r["kind"] == "gauge" and r["name"] == "memory"]
    assert gauges, "windowed path emitted no memory gauge"
    assert all("host_rss_peak_mib" in g for g in gauges)
    measured = max(g.get("device_live_mib", 0.0) for g in gauges)
    rep = megaplan.window_mem_report(
        "tiny", world=4, window=3, global_batch=64)
    assert 0 < measured <= rep.peak_bytes / 2**20, \
        f"measured {measured} MiB vs certified {rep.peak_mib} MiB"


# ---------------------------------------------------------------------------
# megaplan: closed form unit-pinned, concrete vgg11 K, monotone
# ---------------------------------------------------------------------------

def test_plan_k_epochs_hand_computed():
    """Every byte in the closed form pinned by hand: 1000 batches of 16
    per-chip CIFAR samples (3072 u8 + 4 label = 3076 B) -> 49,216,000 B
    slab; 1000 ring rows x 16 B + 4 B counter; 1 GiB budget."""
    assert megaplan.RING_ROW_BYTES == 16
    assert megaplan.ring_bytes_for_steps(1000) == 16_000
    assert megaplan.slab_bytes_per_epoch(1000, 4, 64, 4) == 49_216_000
    # Window padding: 999 batches pad up to 1000 at window 4.
    assert megaplan.slab_bytes_per_epoch(999, 4, 64, 4) == 49_216_000
    plan = megaplan.plan_k_epochs(
        model="tiny", world=4, window=4, global_batch=64, nbatches=1000,
        state_bytes=1_000_000, transient_bytes=500_000,
        hbm_budget_bytes=2**30)
    assert plan.fixed_bytes == 1_500_004
    assert plan.per_epoch_bytes == 49_232_000
    assert plan.max_k == (2**30 - 1_500_004) // 49_232_000 == 21
    assert plan.windowed_round_trips_per_epoch == \
        dispatch.epoch_round_trip_bound("window", 1000, 4,
                                        include_eval=True) == 251
    assert plan.mega_round_trips == 2
    assert plan.round_trips_saved == 21 * 251 - 2
    # Infeasible budgets report 0 with a reason, never negative K.
    broke = megaplan.plan_k_epochs(
        model="tiny", world=4, window=4, global_batch=64, nbatches=1000,
        state_bytes=2**31, hbm_budget_bytes=2**30)
    assert broke.max_k == 0 and broke.round_trips_saved == 0
    assert any("infeasible" in n for n in broke.notes)


def test_max_feasible_k_vgg11_concrete():
    """The acceptance numbers: vgg11 @ 16 GiB, window 4, global batch
    256 — concrete K per world, rising with the mesh (per-chip slab and
    transient shrink as the batch shards)."""
    ks = {w: megaplan.max_feasible_K("vgg11", w, 4, global_batch=256)
          for w in (1, 2, 8)}
    assert ks == {1: 105, 2: 215, 8: 873}


def test_max_feasible_k_monotone_in_budget_and_window():
    rep = megaplan.window_mem_report(
        "vgg11", world=8, window=4, global_batch=256)
    by_budget = [megaplan.max_feasible_K(
        "vgg11", 8, 4, gib * 2**30, global_batch=256, window_report=rep)
        for gib in (2, 4, 8, 16)]
    assert by_budget == sorted(by_budget)
    assert by_budget[0] > 0
    # Bigger windows pad the slab more: K never increases with window.
    by_window = [megaplan.plan_k_epochs(
        model="vgg11", world=8, window=w, global_batch=256,
        state_bytes=rep.param_bytes,
        transient_bytes=200 * 2**20).max_k
        for w in (1, 3, 4, 7, 16)]
    assert by_window == sorted(by_window, reverse=True)


# ---------------------------------------------------------------------------
# repo self-checks: single-sourced constants, fixture invariants
# ---------------------------------------------------------------------------

def _mini_repo(tmp_path, extra_py=None):
    """A minimal repo tree satisfying the single-source checker."""
    home = tmp_path / "cs744_ddp_tpu" / "analysis" / "costmodel.py"
    home.parent.mkdir(parents=True)
    home.write_text("V5E_BF16_PEAK_FLOPS = 197e12\n"
                    "V5E_HBM_BYTES_PER_S = 819e9\n"
                    "V5E_ICI_BYTES_PER_S = 200e9\n"
                    "V5E_HBM_CAPACITY_BYTES = 16 * 2**30\n")
    for rel, text in (extra_py or {}).items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text)
    return str(tmp_path)


def test_constants_single_source_repo_and_seeded(tmp_path):
    # The real repo is clean (also enforced by lint_graft + cli).
    assert memlife.check_constants_single_source(REPO) == []
    # Seeded duplicate literal and capacity reassignment both fire.
    root = _mini_repo(tmp_path, {
        "cs744_ddp_tpu/fork.py":
            "PEAK = 197e12\nV5E_HBM_CAPACITY_BYTES = 8 * 2**30\n"})
    findings = memlife.check_constants_single_source(root)
    assert {f.rule for f in findings} == {"memory-constants"}
    msgs = "\n".join(f.message for f in findings)
    assert "197e12" in msgs and "reassigned" in msgs
    # Findings carry the lint_graft --json shape (rule/path/line/message).
    f = findings[0]
    json.dumps({"rule": f.rule, "file": f.path, "line": f.line,
                "message": f.message})
    assert f.line > 0


def test_fixture_invariants_repo_and_seeded(tmp_path):
    assert memlife.check_fixture_invariants(REPO) == []
    # Missing fixtures -> findings, not a crash.
    missing = memlife.check_fixture_invariants(str(tmp_path))
    assert len(missing) == 2
    assert all(f.rule == "memory-fixture" for f in missing)
    # Seeded drift: both files undonated -> the donation delta no longer
    # equals the carried bytes, the invariant breaks loudly.
    for rel in (memlife.FIXTURE_DONATED, memlife.FIXTURE_UNDONATED):
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(UNDONATED)
    assert memlife.check_fixture_invariants(str(tmp_path)) != []


def test_check_memory_composes_both(tmp_path):
    assert memlife.check_memory(REPO) == []
    # A broken tree surfaces findings from BOTH halves through the one
    # entry point lint_graft/cli call.
    root = _mini_repo(tmp_path, {"cs744_ddp_tpu/fork.py": "X = 819e9\n"})
    rules = {f.rule for f in memlife.check_memory(root)}
    assert rules == {"memory-constants", "memory-fixture"}


# ---------------------------------------------------------------------------
# telemetry report: the == memory == section
# ---------------------------------------------------------------------------

def test_telemetry_report_memory_section(tmp_path, monkeypatch):
    monkeypatch.syspath_prepend(os.path.join(REPO, "tools"))
    import telemetry_report
    events = [
        {"kind": "gauge", "name": "memory", "t": 1.0, "epoch": 0,
         "value": {"host_rss_peak_mib": 512.3, "device_live_mib": 17.9,
                   "device_live_arrays": 42}},
        {"kind": "gauge", "name": "memory", "t": 2.0, "epoch": 1,
         "value": {"host_rss_peak_mib": 530.0, "device_live_mib": 18.1,
                   "device_live_arrays": 40}},
    ]
    (tmp_path / "events.jsonl").write_text(
        "".join(json.dumps(e) + "\n" for e in events))
    (tmp_path / "manifest.json").write_text(json.dumps({
        "model": "tiny",
        "audit": {"clean": True, "n_programs": 1, "n_findings": 0,
                  "n_waived": 0,
                  "programs": {"train/window/ddp": {
                      "rules": {"peak-memory": "pass"},
                      "chain_depth": 1, "peak_mib": 18.214}},
                  "findings": [], "waived": []},
    }))
    out = telemetry_report.render(str(tmp_path))
    assert "== memory (measured vs certified) ==" in out
    assert "max      18.10 MiB" in out
    assert "train/window/ddp" in out
    assert "measured within certificate" in out
    # Over-certificate measurement flips the verdict line.
    (tmp_path / "events.jsonl").write_text(json.dumps({
        "kind": "gauge", "name": "memory", "t": 1.0,
        "value": {"device_live_mib": 99.0}}) + "\n")
    assert "EXCEEDS the certified peak" in \
        telemetry_report.render(str(tmp_path))
    # Absent-safe: no gauges, no audit record -> no section.
    (tmp_path / "events.jsonl").write_text("")
    (tmp_path / "manifest.json").write_text(json.dumps({"model": "tiny"}))
    assert "== memory" not in telemetry_report.render(str(tmp_path))
