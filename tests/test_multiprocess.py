"""Multi-process (multi-controller) correctness — VERDICT r1 item 2.

The reference's defining launch model is N separate OS processes
rendezvousing at a coordinator (``/root/reference/src/Part 2a/main.py:
148-153`` and the ``--rank`` CLI ``:156-175``).  Here: two real OS
processes, 4 virtual CPU devices each, ``jax.distributed.initialize`` over
localhost, gloo cross-process collectives, running the SAME Trainer code
the single-controller path uses — then the parent asserts

  * both processes hold identical parameters after N allreduce steps
    (the replicated-state invariant across controller boundaries), and
  * those parameters match an in-process single-controller run of the
    identical configuration on the 8-virtual-device mesh (the
    multi-controller path computes the same mathematics).
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import jax

from cs744_ddp_tpu.data import native
from cs744_ddp_tpu.train.loop import Trainer

from mp_worker import N_STEPS
from tinynet import run_steps, tiny_cnn

_TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO_ROOT = os.path.dirname(_TESTS_DIR)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.parametrize("strategy", ["gather", "allreduce", "ddp"])
def test_two_process_rendezvous_matches_single_controller(tmp_path, mesh8,
                                                          strategy):
    # Pre-build the native library so the workers don't race the first build.
    native.load_library()

    env = {**os.environ,
           "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=4"}
    port = _free_port()
    script = os.path.join(_TESTS_DIR, "mp_worker.py")
    procs = [subprocess.Popen(
        [sys.executable, script, str(i), "2", str(port), str(tmp_path),
         strategy],
        env=env, cwd=_REPO_ROOT,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(2)]
    try:
        outs = [p.communicate(timeout=600)[0].decode() for p in procs]
    finally:
        for p in procs:  # never leak hung workers (e.g. a dead rendezvous)
            if p.poll() is None:
                p.kill()
                p.wait()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker {p.args} failed:\n{out}"

    d0 = np.load(tmp_path / "params_0.npz")
    d1 = np.load(tmp_path / "params_1.npz")
    assert set(d0.files) == set(d1.files)

    # (1) Cross-process consistency: the replicated state is identical on
    # both controllers (gloo's reduction gives every process the same sum).
    for k in d0.files:
        np.testing.assert_allclose(d0[k], d1[k], rtol=0, atol=1e-6,
                                   err_msg=f"process disagreement on {k}")

    # (2) Single-controller equivalence: the same config in THIS process on
    # the 8-virtual-device mesh takes the same steps.
    tr = Trainer(model=tiny_cnn(), strategy=strategy, global_batch=64,
                 data_dir=str(tmp_path / "data"), augment=False,
                 mesh=mesh8, log=lambda s: None)
    losses = run_steps(tr, N_STEPS)

    np.testing.assert_allclose(np.asarray(losses, np.float64), d0["losses"],
                               atol=1e-5)
    flat = jax.tree.leaves(tr.state.params)
    assert len(flat) == sum(1 for k in d0.files if k.startswith("p"))
    for i, leaf in enumerate(flat):
        np.testing.assert_allclose(
            np.asarray(leaf), d0[f"p{i}"], atol=1e-5,
            err_msg=f"single- vs multi-controller divergence on leaf {i}")
