"""Elastic training (elastic/): world-resize resume with pinned math.

The load-bearing pin is the strong-scaling CI trajectory: the microshard
window's update is a pure function of the GLOBAL batch, so training the
same config at world 1, 2 and 4 must produce BITWISE-identical states —
that is the invariant every shrink/grow recovery in test_ft.py rides.
Around it: the resume planner (weak/strong translation, shrink ladder,
forward/backward metadata compat), the canonical-order sampler invariance
the planner assumes (rank r of world w deals positions ``r::w`` of ONE
permutation, torch wrap-pad tiling included), and the straggler detector.
"""

import warnings

import numpy as np

import jax
import jax.numpy as jnp
import pytest

import cs744_ddp_tpu.train.loop as looplib
from cs744_ddp_tpu.data import sharding
from cs744_ddp_tpu.elastic import (PROTOCOLS, StragglerDetector, flat_meta,
                                   make_elastic_train_window, plan_resume,
                                   plan_shrink, rank_data_keys,
                                   tree_combine_mean, validate_rank_keys,
                                   world_of)
from cs744_ddp_tpu.elastic import protocol as protolib
from cs744_ddp_tpu.parallel import make_mesh
from cs744_ddp_tpu.train.loop import Trainer

from tinynet import tiny_cnn


# -- resume planner -----------------------------------------------------------

def test_flat_meta_accepts_both_sidecar_shapes():
    nested = {"epoch": 1, "step": 5,
              "data_order": {"seed": 3, "world": 2, "rank_keys": [7, 8]}}
    flat = {"epoch": 1, "step": 5, "seed": 3, "world": 2,
            "rank_keys": [7, 8]}
    assert flat_meta(nested) == flat
    assert flat_meta(flat) == flat
    assert flat_meta(None) == {}
    assert flat_meta({}) == {}


def test_world_of_missing_world_defaults_to_1_warns_once(monkeypatch):
    monkeypatch.setattr(protolib, "_warned_missing_world", False)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        assert world_of({"epoch": 0}) == 1     # pre-round-6 checkpoint
        assert world_of(None) == 1
    msgs = [str(w.message) for w in rec]
    assert len(msgs) == 1                      # one-time, not per call
    assert "no world size" in msgs[0]
    assert world_of({"world": 4}) == 4         # recorded world wins, no warn


def test_plan_resume_strong_step_is_world_invariant():
    meta = {"world": 4, "global_batch": 256, "epoch": 2, "step": 37,
            "protocol": "strong"}
    for m in (1, 2, 4):
        plan = plan_resume(meta, m, microshards=4)
        assert plan.protocol == "strong"
        assert (plan.old_world, plan.new_world) == (4, m)
        assert plan.start_epoch == 2
        assert plan.start_step == 37           # batch b is batch b at any M
        assert plan.examples_replayed == 0
        assert plan.steps_lost == 0
        assert plan.new_global_batch == 256    # pinned


def test_plan_resume_strong_divisibility_errors():
    meta = {"world": 4, "global_batch": 256, "step": 10}
    with pytest.raises(ValueError, match="not divisible by new world"):
        plan_resume(meta, 3, protocol="strong", microshards=4)
    with pytest.raises(ValueError, match="global batch 250 not divisible"):
        plan_resume({"world": 2, "global_batch": 250, "step": 1}, 2,
                    protocol="strong", microshards=4)
    with pytest.raises(ValueError, match="unknown elastic protocol"):
        plan_resume(meta, 2, protocol="superlinear")
    with pytest.raises(ValueError, match="new world must be >= 1"):
        plan_resume(meta, 0)
    with pytest.raises(ValueError, match="no global_batch"):
        plan_resume({"world": 2, "step": 1}, 2)


def test_plan_resume_weak_replays_the_floor_remainder():
    # 4 ranks x 64/chip = gb 256; 10 steps done = 2560 examples.  At
    # world 3 (gb 192): 2560 // 192 = 13 steps, 64 examples re-processed.
    meta = {"world": 4, "global_batch": 256, "epoch": 0, "step": 10,
            "protocol": "weak"}
    plan = plan_resume(meta, 3)
    assert plan.new_global_batch == 192        # per-chip 64 pinned
    assert plan.start_step == 13
    assert plan.examples_replayed == 2560 - 13 * 192 == 64
    assert plan.steps_lost == 10 - (13 * 192) // 256 == 1
    # Growing 4 -> 8 doubles gb; 2560 // 512 = 5 steps, zero remainder.
    plan = plan_resume(meta, 8)
    assert (plan.new_global_batch, plan.start_step) == (512, 5)
    assert plan.examples_replayed == 0
    assert plan.steps_lost == 0


def test_plan_shrink_walks_the_geometry_down():
    # Strong scaling at microshards=4: 4 -> 2 (3 doesn't divide 4) -> 1.
    assert plan_shrink(4, 64, microshards=4) == 2
    assert plan_shrink(2, 64, microshards=4) == 1
    # Without the microshard constraint 4 -> 3 when the batch allows it;
    # 64 doesn't divide by 3, so that geometry lands on 2.
    assert plan_shrink(4, 60) == 3
    assert plan_shrink(4, 64) == 2
    with pytest.raises(ValueError, match="cannot shrink below world 1"):
        plan_shrink(1, 64)


def test_rank_keys_validate_and_catch_dataset_drift():
    meta = {"world": 2, "seed": 3, "epoch": 0,
            "rank_keys": list(rank_data_keys(256, 2, seed=3))}
    validate_rank_keys(meta, 256)              # same dataset/seed: ok
    validate_rank_keys({"world": 2}, 256)      # pre-round-6 meta: no-op
    with pytest.raises(ValueError, match="data-order keys do not match"):
        validate_rank_keys(meta, 300)          # dataset changed underneath
    with pytest.raises(ValueError, match="data-order keys do not match"):
        validate_rank_keys({**meta, "seed": 4}, 256)
    # The nested mid-epoch shape validates identically.
    validate_rank_keys({"data_order": meta}, 256)


# -- sampler invariance (the seam the planner rides) --------------------------

@pytest.mark.parametrize("n", [10, 197, 256])
def test_rank_streams_deal_from_one_canonical_order(n):
    """For EVERY world size, interleaving the per-rank streams recovers the
    wrap-padded canonical permutation — the invariant that makes consumed
    examples world-independent (includes non-divisible worlds, e.g. the
    4 -> 3 shrink geometry)."""
    for w in range(1, 9):
        mat = sharding.global_epoch_indices(n, w, seed=3)
        total = mat.size
        want = sharding.canonical_epoch_order(n, seed=3, pad_to=total)
        np.testing.assert_array_equal(mat.T.ravel(), want)


def test_wrap_pad_tiles_like_torch_beyond_2n():
    # total > 2n: torch tiles the whole list ceil(total/n) times.
    perm = np.array([4, 1, 3, 0, 2])
    got = sharding._wrap_pad(perm, 13)
    np.testing.assert_array_equal(got, np.tile(perm, 3)[:13])
    np.testing.assert_array_equal(sharding._wrap_pad(perm, 3), perm[:3])


def test_resize_preserves_epoch_order_4_to_3():
    """The shrink case the ladder exercises: after a 4 -> 3 resize the
    canonical order is untouched (pure function of seed/epoch, never of
    world), and under the never-reshuffle quirk (C6) it is also untouched
    across epochs — so batch b covers positions [b*B, (b+1)*B) before AND
    after the resize."""
    n, B = 197, 12                       # 12 divides at worlds 1,2,3,4,6
    before = sharding.canonical_epoch_order(n, seed=3, epoch=0)
    after = sharding.canonical_epoch_order(n, seed=3, epoch=1)
    np.testing.assert_array_equal(before, after)   # C6: no set_epoch
    padded = sharding.canonical_epoch_order(n, seed=3, pad_to=16 * B)
    for w in (1, 2, 3, 4, 6):
        mat = sharding.global_epoch_indices(n, w, seed=3)
        stream = mat.T.ravel()
        for b in range(stream.size // B):
            np.testing.assert_array_equal(
                np.sort(stream[b * B:(b + 1) * B]),
                np.sort(padded[b * B:(b + 1) * B]))


# -- the fixed combine tree ---------------------------------------------------

def test_tree_combine_mean_matches_mean_with_pinned_order():
    x = jnp.asarray(np.random.default_rng(0).normal(size=(4, 3, 2)),
                    jnp.float32)
    got = tree_combine_mean(x)
    # The pinned order is exactly ((x0+x1)+(x2+x3))/4 — assert bitwise.
    want = ((x[0] + x[1]) + (x[2] + x[3])) / 4
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    np.testing.assert_allclose(np.asarray(got), np.mean(x, axis=0),
                               rtol=1e-6)
    # s=1 degenerates to the identity (the world == microshards case).
    np.testing.assert_array_equal(np.asarray(tree_combine_mean(x[:1])),
                                  np.asarray(x[0]))


def test_tree_combine_mean_rejects_non_power_of_two():
    with pytest.raises(ValueError, match="power-of-two"):
        tree_combine_mean(jnp.zeros((3, 2)))


# -- straggler detection ------------------------------------------------------

def test_straggler_detector_flags_only_the_outlier():
    det = StragglerDetector(4, min_steps=3)
    for _ in range(2):
        for r in range(4):
            det.observe(r, 0.1)
        assert det.check() == []               # min_steps not reached
    for r in range(4):
        det.observe(r, 2.0 if r == 2 else 0.1)
    assert det.check() == [2]
    assert det.flag_counts == {2: 1}
    assert det.ewma(2) > det.ewma(0)
    s = det.summary()
    assert s["world"] == 4 and s["flag_counts"] == {"2": 1}


def test_straggler_detector_world1_never_flags():
    det = StragglerDetector(1, min_steps=1)
    for _ in range(5):
        det.observe(0, 9.9)
    assert det.check() == []                   # no peers to lag behind


def test_straggler_detector_validates():
    with pytest.raises(ValueError, match="world"):
        StragglerDetector(0)
    with pytest.raises(ValueError, match="threshold"):
        StragglerDetector(2, threshold=1.0)
    with pytest.raises(ValueError, match="out of range"):
        StragglerDetector(2).observe(2, 0.1)


# -- config validation --------------------------------------------------------

def test_window_factory_validates_geometry(mesh4):
    _, apply_fn = tiny_cnn()
    with pytest.raises(ValueError, match="power of two"):
        make_elastic_train_window(apply_fn, mesh4, microshards=6)
    with pytest.raises(ValueError, match="not divisible by world"):
        make_elastic_train_window(apply_fn, mesh4, microshards=2)
    with pytest.raises(ValueError, match="on-device"):
        make_elastic_train_window(apply_fn, mesh4, microshards=4,
                                  augment="host")


def test_trainer_validates_elastic_config(tmp_path, mesh4):
    with pytest.raises(ValueError, match="protocol must be one of"):
        Trainer(model=tiny_cnn(), mesh=mesh4, global_batch=64,
                data_dir=str(tmp_path), log=lambda s: None,
                elastic="superlinear")
    with pytest.raises(ValueError, match="not divisible by microshards"):
        # world 1 passes the generic world-divisibility check, so the
        # elastic-specific microshard check is what fires.
        Trainer(model=tiny_cnn(), mesh=make_mesh(1), global_batch=50,
                data_dir=str(tmp_path), log=lambda s: None,
                elastic="strong")
    with pytest.raises(ValueError, match="device-side"):
        Trainer(model=tiny_cnn(), mesh=mesh4, global_batch=64,
                data_dir=str(tmp_path), log=lambda s: None,
                host_augment=True, elastic="strong")
    assert "weak" in PROTOCOLS and "strong" in PROTOCOLS


# -- THE CI PIN: strong scaling is bitwise world-invariant at 1 -> 2 -> 4 -----

def _elastic_trainer(tmp_path, world, **kw):
    kw.setdefault("limit_train_batches", 6)
    kw.setdefault("strategy", "allreduce")
    return Trainer(model=tiny_cnn(), mesh=make_mesh(world),
                   global_batch=64, data_dir=str(tmp_path), seed=3,
                   augment=True, limit_eval_batches=1, log=lambda s: None,
                   elastic="strong", **kw)


def _host_state(tr):
    return jax.tree.map(lambda a: np.asarray(jax.device_get(a)), tr.state)


@pytest.fixture
def small_window(monkeypatch):
    monkeypatch.setattr(looplib, "WINDOW", 3)


def test_strong_scaling_trajectory_bitwise_identical_1_2_4(tmp_path,
                                                           small_window):
    """ISSUE round 6 acceptance: the SAME config (global batch 64, seed 3,
    2 epochs) trained at world 1, 2 and 4 on the CPU virtual mesh ends in
    bitwise-identical TrainStates.  This pins the one residual assumption
    of the microshard window — XLA lowers the runtime-trip-count loop body
    identically whether a rank runs 4, 2 or 1 iterations."""
    states = {}
    for w in (1, 2, 4):
        tr = _elastic_trainer(tmp_path, w)
        if w == 4:
            # Checkpointing must not disturb the pinned stream, and the
            # epoch sidecar must carry the round-6 topology metadata.
            ckpt = str(tmp_path / "ckpt4")
            tr.run(2, checkpoint_dir=ckpt)
            from cs744_ddp_tpu.train.checkpoint import read_epoch_meta
            meta = read_epoch_meta(ckpt)
            assert meta["world"] == 4
            assert meta["global_batch"] == 64
            assert meta["protocol"] == "strong"
            assert meta["microshards"] == 4
            assert len(meta["rank_keys"]) == 4
            assert meta["rank_keys"] == list(rank_data_keys(
                len(tr.train_split.labels), 4, seed=3))
        else:
            tr.run(2)
        states[w] = _host_state(tr)
    la, lb, lc = (jax.tree.leaves(states[w]) for w in (1, 2, 4))
    assert len(la) == len(lb) == len(lc)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(x, y, err_msg="world 1 vs 2")
    for x, y in zip(la, lc):
        np.testing.assert_array_equal(x, y, err_msg="world 1 vs 4")


def test_elastic_shrink_2_to_1_reshards_compressed_residuals(tmp_path,
                                                             small_window):
    """Round-7: EF residual state survives an elastic 2 -> 1 shrink.  The
    on-disk comm stack is (2, ...); the resumed world-1 trainer absorbs it
    sum-conserving (strategies.reshard_comm), so no quantization error
    recorded before the shrink is lost — bitwise: the absorbed stack IS
    the old stack's axis-0 sum."""
    ck = str(tmp_path / "ck_shrink")
    tr2 = _elastic_trainer(tmp_path, 2, strategy="compress-bf16",
                           limit_train_batches=3)
    # The strong-elastic window replaces the strategy's reduction with the
    # pinned-order combine (that's the world-invariance pin above), so EF
    # residuals do not ACCRUE during elastic training; what this test owns
    # is the carry: plant a distinct per-worker residual stack and require
    # the elastic run to thread it through every window unchanged
    # (sgd.update), checkpoint it, and reshard it on the world-1 resume.
    comm = jax.device_get(tr2.state.opt_state.comm)
    planted = jax.tree.map(
        lambda l: (np.arange(l.size, dtype=l.dtype).reshape(l.shape) / 64.0
                   + np.arange(1, 3, dtype=l.dtype).reshape(
                       (2,) + (1,) * (l.ndim - 1))),
        comm["residual"])
    tr2.state = tr2._commit_state(tr2.state._replace(
        opt_state=tr2.state.opt_state._replace(
            comm={"residual": planted})))
    tr2.run(1, checkpoint_dir=ck)
    r2 = [np.asarray(l) for l in jax.tree.leaves(
        jax.device_get(tr2.state.opt_state.comm)["residual"])]
    assert all(l.shape[0] == 2 for l in r2)
    for got, want in zip(r2, jax.tree.leaves(planted)):
        np.testing.assert_array_equal(got, want)   # carried, not mutated

    # Epoch 0 is already checkpointed, so run(1) on the world-1 trainer
    # restores + absorbs the state and trains nothing — the absorbed comm
    # is exactly what the resume handed the next epoch.
    tr1 = _elastic_trainer(tmp_path, 1, strategy="compress-bf16",
                           limit_train_batches=3)
    tr1.run(1, checkpoint_dir=ck)
    r1 = [np.asarray(l) for l in jax.tree.leaves(
        jax.device_get(tr1.state.opt_state.comm)["residual"])]
    assert all(l.shape[0] == 1 for l in r1)
    for old, new in zip(r2, r1):
        np.testing.assert_array_equal(old.sum(axis=0), new[0])
    # Params/momentum are world-invariant and restore bitwise.
    np.testing.assert_array_equal(
        np.asarray(jax.tree.leaves(tr2.state.params)[0]),
        np.asarray(jax.tree.leaves(tr1.state.params)[0]))
