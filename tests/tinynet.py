"""Tiny conv net shared by the CPU-mesh tests and the multi-process worker.

conv(3->8) + BN + relu + pool(4x) + fc: exercises every layer kind the real
models use, while keeping CPU compiles fast.  The strategy/step/loop code
under test is identical to what VGG/ResNet run (full models are covered by
tests/test_models.py and the TPU bench).
"""

import jax
import jax.numpy as jnp

from cs744_ddp_tpu.models import layers
from cs744_ddp_tpu.train.loop import _shard_batches


def run_steps(trainer, n_steps, *, epoch=0, base_key=0):
    """Drive `n_steps` per-step train_step calls with the canonical step-key
    convention (fold the iteration index into the base key; the step folds
    the mesh position itself).  Shared by every cross-path equivalence
    oracle so they all compare the same computation.  Returns the losses."""
    key = jax.random.PRNGKey(base_key)
    losses = []
    for it, (imgs, labs) in enumerate(_shard_batches(
            trainer.train_split, trainer.world, trainer.global_batch, epoch,
            shuffle=True)):
        if it >= n_steps:
            break
        x, y = trainer._put(imgs, labs)
        trainer.state, loss = trainer.train_step(
            trainer.state, jax.random.fold_in(key, it), x, y)
        losses.append(float(loss))  # value fetch = completion fence
    return losses


def tiny_cnn():
    def init_fn(key, dtype=jnp.float32):
        k1, k2 = jax.random.split(key)
        params = {"conv": layers.conv2d_init(k1, 3, 8, 3, dtype)}
        params["bn"], bn_state = layers.batchnorm_init(8, dtype)
        params["fc"] = layers.linear_init(k2, 8 * 8 * 8, 10, dtype)
        return params, {"bn": bn_state}

    def apply_fn(params, state, x, *, train):
        y = layers.conv2d_apply(params["conv"], x)
        y, new_bn = layers.batchnorm_apply(params["bn"], state["bn"], y,
                                           train=train)
        y = layers.relu(y)
        y = layers.maxpool2x2(layers.maxpool2x2(y))  # 32 -> 8
        y = y.reshape(y.shape[0], -1)
        return layers.linear_apply(params["fc"], y), {"bn": new_bn}

    return init_fn, apply_fn


def tiny_cnn_nobn():
    """BN-free variant: with no batch statistics, a 1-device run and an
    N-device data-parallel run on the same global batch are mathematically
    identical — the tight cross-world averaging oracle."""

    def init_fn(key, dtype=jnp.float32):
        k1, k2 = jax.random.split(key)
        params = {"conv": layers.conv2d_init(k1, 3, 8, 3, dtype),
                  "fc": layers.linear_init(k2, 8 * 8 * 8, 10, dtype)}
        return params, {}

    def apply_fn(params, state, x, *, train):
        del train
        y = layers.conv2d_apply(params["conv"], x)
        y = layers.relu(y)
        y = layers.maxpool2x2(layers.maxpool2x2(y))  # 32 -> 8
        y = y.reshape(y.shape[0], -1)
        return layers.linear_apply(params["fc"], y), state

    return init_fn, apply_fn
