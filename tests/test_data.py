"""Data pipeline tests: sampler sharding semantics, augmentation, loading."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cs744_ddp_tpu.data import augment, cifar10, sharding


class TestShardedSampler:
    def test_disjoint_cover_equal_counts(self):
        """Shards must disjointly cover all 50k examples with equal counts
        (SURVEY.md §4: 'disjoint cover of 50k examples')."""
        n, world = 50_000, 4
        all_idx = [sharding.ShardedSampler(n, world, r).epoch_indices()
                   for r in range(world)]
        assert all(len(ix) == 12_500 for ix in all_idx)
        union = np.concatenate(all_idx)
        assert len(np.unique(union)) == n

    def test_padding_wraps_like_torch(self):
        n, world = 10, 4   # ceil(10/4)=3 -> total 12, 2 wrapped
        all_idx = [sharding.ShardedSampler(n, world, r, shuffle=False)
                   .epoch_indices() for r in range(world)]
        flat = np.stack(all_idx).T.reshape(-1)  # undo round-robin deal
        np.testing.assert_array_equal(flat, np.r_[np.arange(10), [0, 1]])

    def test_no_reshuffle_across_epochs_by_default(self):
        """Reference never calls sampler.set_epoch -> same permutation every
        epoch (SURVEY.md C6)."""
        s = sharding.ShardedSampler(1000, 2, 0)
        np.testing.assert_array_equal(s.epoch_indices(0), s.epoch_indices(5))
        s2 = sharding.ShardedSampler(1000, 2, 0, reshuffle_each_epoch=True)
        assert not np.array_equal(s2.epoch_indices(0), s2.epoch_indices(1))

    def test_global_matrix_matches_per_rank(self):
        mat = sharding.global_epoch_indices(100, 4)
        for r in range(4):
            np.testing.assert_array_equal(
                mat[r], sharding.ShardedSampler(100, 4, r).epoch_indices())


class TestAugment:
    def test_normalize_stats(self):
        img = np.full((1, 32, 32, 3), 128, np.uint8)
        out = np.asarray(augment.normalize(jnp.asarray(img)))
        expected = (128 / 255.0 - cifar10.MEAN) / cifar10.STD
        np.testing.assert_allclose(out[0, 0, 0], expected, atol=1e-6)

    def test_augment_shapes_and_determinism(self):
        imgs = np.random.default_rng(0).integers(
            0, 256, (8, 32, 32, 3)).astype(np.uint8)
        key = jax.random.PRNGKey(0)
        a = augment.augment(key, jnp.asarray(imgs))
        b = augment.augment(key, jnp.asarray(imgs))
        assert a.shape == (8, 32, 32, 3)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        c = augment.augment(jax.random.PRNGKey(1), jnp.asarray(imgs))
        assert not np.array_equal(np.asarray(a), np.asarray(c))

    def test_matmul_formulation_equals_gather_formulation(self):
        """The MXU one-hot-matmul crop/flip must be BIT-identical to the
        dynamic_slice gather formulation (uint8 is exact in bf16)."""
        imgs = np.random.default_rng(9).integers(
            0, 256, (32, 32, 32, 3)).astype(np.uint8)
        for seed in (0, 1, 2):
            key = jax.random.PRNGKey(seed)
            a = np.asarray(augment.augment(key, jnp.asarray(imgs)))
            b = np.asarray(augment.augment_gather(key, jnp.asarray(imgs)))
            np.testing.assert_array_equal(a, b)

    def test_augment_is_crop_of_padded(self):
        """With an all-ones image, any crop/flip output normalizes the same
        nonzero constant inside, zeros (padding) possibly at borders."""
        imgs = np.full((4, 32, 32, 3), 255, np.uint8)
        out = np.asarray(augment.augment(jax.random.PRNGKey(3),
                                         jnp.asarray(imgs)))
        interior = out[:, 8:24, 8:24, :]  # never touches pad for offsets<=8
        expected = (1.0 - cifar10.MEAN) / cifar10.STD
        np.testing.assert_allclose(
            interior, np.broadcast_to(expected, interior.shape), atol=1e-5)


class TestCifar10:
    def test_synthetic_fallback_shapes(self, tmp_path):
        train, test, real = cifar10.load(str(tmp_path))
        assert not real
        assert train.images.shape == (50_000, 32, 32, 3)
        assert train.images.dtype == np.uint8
        assert test.labels.shape == (10_000,)
        assert train.labels.min() >= 0 and train.labels.max() <= 9

    def test_synthetic_is_deterministic(self, tmp_path):
        t1, _, _ = cifar10.load(str(tmp_path))
        t2, _, _ = cifar10.load(str(tmp_path))
        np.testing.assert_array_equal(t1.images, t2.images)

    def test_real_pickle_loader(self, tmp_path):
        """Write a fake cifar-10-batches-py dir in the on-disk format."""
        import pickle
        d = tmp_path / "cifar-10-batches-py"
        d.mkdir()
        rng = np.random.default_rng(0)
        for i in range(1, 6):
            data = rng.integers(0, 256, (100, 3072)).astype(np.uint8)
            with open(d / f"data_batch_{i}", "wb") as f:
                pickle.dump({b"data": data,
                             b"labels": list(rng.integers(0, 10, 100))}, f)
        with open(d / "test_batch", "wb") as f:
            pickle.dump({b"data": rng.integers(0, 256, (50, 3072)).astype(
                np.uint8), b"labels": list(rng.integers(0, 10, 50))}, f)
        train, test, real = cifar10.load(str(tmp_path))
        assert real
        assert train.images.shape == (500, 32, 32, 3)
        assert test.images.shape == (50, 32, 32, 3)


class TestRealFormatFixture:
    """The real-CIFAR loading path, byte-level (VERDICT r4 item 8).

    No egress means real-CIFAR accuracy can't be demonstrated here
    (BASELINE.md), but the loader's bytes -> NHWC -> normalize path is
    verified end-to-end against a COMMITTED fixture in the genuine
    cifar-10-batches-py format (tools/make_cifar_fixture.py: bytes keys,
    protocol-2 pickles, planar R/G/B rows) with independently computed
    expectations — the same decode torchvision performs on the real files
    (``/root/reference/src/Part 1/main.py:94-103``)."""

    @pytest.fixture(scope="class")
    def assets_dir(self):
        import os
        d = os.path.join(os.path.dirname(__file__), "assets")
        if not os.path.isdir(os.path.join(d, "cifar-10-batches-py")):
            pytest.skip("fixture assets not present")
        return d

    def test_loader_selects_real_data_with_expected_shapes(self, assets_dir):
        train, test, real = cifar10.load(assets_dir)
        assert real is True
        assert train.images.shape == (5 * 64, 32, 32, 3)
        assert train.images.dtype == np.uint8
        assert train.labels.shape == (5 * 64,)
        assert test.images.shape == (64, 32, 32, 3)
        assert set(np.unique(test.labels)) == set(range(10))

    def test_bytes_to_nhwc_against_independent_decode(self, assets_dir):
        """Every byte: images[n, r, c, ch] == raw[n, 1024*ch + 32*r + c]
        (the CIFAR spec's planar layout), decoded here with plain pickle +
        integer indexing, sharing no code with the loader."""
        import os
        import pickle
        train, test, _ = cifar10.load(assets_dir)
        for file_idx, name in ((1, "data_batch_2"), (None, "test_batch")):
            with open(os.path.join(assets_dir, "cifar-10-batches-py",
                                   name), "rb") as f:
                raw = pickle.load(f, encoding="bytes")
            split = test if file_idx is None else train
            base = 0 if file_idx is None else file_idx * 64
            want = raw[b"data"].reshape(64, 3, 32, 32)
            for n in (0, 7, 63):
                for r, c, ch in ((0, 0, 0), (31, 31, 2), (13, 5, 1)):
                    assert split.images[base + n, r, c, ch] == \
                        want[n, ch, r, c]
            # And the full tensor, vectorized.
            np.testing.assert_array_equal(
                split.images[base:base + 64], want.transpose(0, 2, 3, 1))
            np.testing.assert_array_equal(
                split.labels[base:base + 64],
                np.asarray(raw[b"labels"], np.int32))

    def test_normalize_matches_reference_constants(self, assets_dir):
        """Device normalize on fixture bytes == (x/255 - mean)/std with the
        reference's literal constants (``Part 1/main.py:82-83``)."""
        train, _, _ = cifar10.load(assets_dir)
        x = train.images[:8]
        got = np.asarray(augment.normalize(jnp.asarray(x)))
        mean = np.array([125.3, 123.0, 113.9], np.float32) / 255.0
        std = np.array([63.0, 62.1, 66.7], np.float32) / 255.0
        want = (x.astype(np.float32) / 255.0 - mean) / std
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)

    def test_trainer_end_to_end_on_real_format_data(self, assets_dir, mesh4):
        """A Trainer pointed at the fixture takes the REAL-data path
        (real_data=True) and completes a train+eval epoch on it."""
        from cs744_ddp_tpu.train.loop import Trainer
        from tinynet import tiny_cnn
        tr = Trainer(model=tiny_cnn(), strategy="ddp", mesh=mesh4,
                     global_batch=64, data_dir=assets_dir, augment=True,
                     limit_train_batches=3, limit_eval_batches=1,
                     log=lambda s: None)
        assert tr.real_data is True
        timers = tr.train_model(0)
        assert np.isfinite(timers.losses).all()
        avg_loss, correct, acc = tr.test_model()
        assert np.isfinite(avg_loss) and 0 <= acc <= 100


def test_fixture_assets_match_generator():
    """The committed fixture bytes must be exactly what
    tools/make_cifar_fixture.py generates (deterministic seed): a drifted
    regeneration or a hand-edited asset would silently decouple the
    byte-level loader tests from the documented generator."""
    import os
    import sys
    import tempfile
    tools = os.path.join(os.path.dirname(__file__), os.pardir, "tools")
    sys.path.insert(0, tools)
    try:
        import make_cifar_fixture
    finally:
        sys.path.remove(tools)
    committed = os.path.join(os.path.dirname(__file__), "assets",
                             "cifar-10-batches-py")
    if not os.path.isdir(committed):
        pytest.skip("fixture assets not present")
    with tempfile.TemporaryDirectory() as tmp:
        fresh = make_cifar_fixture.main(tmp)
        for name in [f"data_batch_{i}" for i in range(1, 6)] + ["test_batch"]:
            with open(os.path.join(fresh, name), "rb") as f:
                want = f.read()
            with open(os.path.join(committed, name), "rb") as f:
                got = f.read()
            assert got == want, f"{name} diverges from the generator"
