"""Data pipeline tests: sampler sharding semantics, augmentation, loading."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cs744_ddp_tpu.data import augment, cifar10, sharding


class TestShardedSampler:
    def test_disjoint_cover_equal_counts(self):
        """Shards must disjointly cover all 50k examples with equal counts
        (SURVEY.md §4: 'disjoint cover of 50k examples')."""
        n, world = 50_000, 4
        all_idx = [sharding.ShardedSampler(n, world, r).epoch_indices()
                   for r in range(world)]
        assert all(len(ix) == 12_500 for ix in all_idx)
        union = np.concatenate(all_idx)
        assert len(np.unique(union)) == n

    def test_padding_wraps_like_torch(self):
        n, world = 10, 4   # ceil(10/4)=3 -> total 12, 2 wrapped
        all_idx = [sharding.ShardedSampler(n, world, r, shuffle=False)
                   .epoch_indices() for r in range(world)]
        flat = np.stack(all_idx).T.reshape(-1)  # undo round-robin deal
        np.testing.assert_array_equal(flat, np.r_[np.arange(10), [0, 1]])

    def test_no_reshuffle_across_epochs_by_default(self):
        """Reference never calls sampler.set_epoch -> same permutation every
        epoch (SURVEY.md C6)."""
        s = sharding.ShardedSampler(1000, 2, 0)
        np.testing.assert_array_equal(s.epoch_indices(0), s.epoch_indices(5))
        s2 = sharding.ShardedSampler(1000, 2, 0, reshuffle_each_epoch=True)
        assert not np.array_equal(s2.epoch_indices(0), s2.epoch_indices(1))

    def test_global_matrix_matches_per_rank(self):
        mat = sharding.global_epoch_indices(100, 4)
        for r in range(4):
            np.testing.assert_array_equal(
                mat[r], sharding.ShardedSampler(100, 4, r).epoch_indices())


class TestAugment:
    def test_normalize_stats(self):
        img = np.full((1, 32, 32, 3), 128, np.uint8)
        out = np.asarray(augment.normalize(jnp.asarray(img)))
        expected = (128 / 255.0 - cifar10.MEAN) / cifar10.STD
        np.testing.assert_allclose(out[0, 0, 0], expected, atol=1e-6)

    def test_augment_shapes_and_determinism(self):
        imgs = np.random.default_rng(0).integers(
            0, 256, (8, 32, 32, 3)).astype(np.uint8)
        key = jax.random.PRNGKey(0)
        a = augment.augment(key, jnp.asarray(imgs))
        b = augment.augment(key, jnp.asarray(imgs))
        assert a.shape == (8, 32, 32, 3)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        c = augment.augment(jax.random.PRNGKey(1), jnp.asarray(imgs))
        assert not np.array_equal(np.asarray(a), np.asarray(c))

    def test_matmul_formulation_equals_gather_formulation(self):
        """The MXU one-hot-matmul crop/flip must be BIT-identical to the
        dynamic_slice gather formulation (uint8 is exact in bf16)."""
        imgs = np.random.default_rng(9).integers(
            0, 256, (32, 32, 32, 3)).astype(np.uint8)
        for seed in (0, 1, 2):
            key = jax.random.PRNGKey(seed)
            a = np.asarray(augment.augment(key, jnp.asarray(imgs)))
            b = np.asarray(augment.augment_gather(key, jnp.asarray(imgs)))
            np.testing.assert_array_equal(a, b)

    def test_augment_is_crop_of_padded(self):
        """With an all-ones image, any crop/flip output normalizes the same
        nonzero constant inside, zeros (padding) possibly at borders."""
        imgs = np.full((4, 32, 32, 3), 255, np.uint8)
        out = np.asarray(augment.augment(jax.random.PRNGKey(3),
                                         jnp.asarray(imgs)))
        interior = out[:, 8:24, 8:24, :]  # never touches pad for offsets<=8
        expected = (1.0 - cifar10.MEAN) / cifar10.STD
        np.testing.assert_allclose(
            interior, np.broadcast_to(expected, interior.shape), atol=1e-5)


class TestCifar10:
    def test_synthetic_fallback_shapes(self, tmp_path):
        train, test, real = cifar10.load(str(tmp_path))
        assert not real
        assert train.images.shape == (50_000, 32, 32, 3)
        assert train.images.dtype == np.uint8
        assert test.labels.shape == (10_000,)
        assert train.labels.min() >= 0 and train.labels.max() <= 9

    def test_synthetic_is_deterministic(self, tmp_path):
        t1, _, _ = cifar10.load(str(tmp_path))
        t2, _, _ = cifar10.load(str(tmp_path))
        np.testing.assert_array_equal(t1.images, t2.images)

    def test_real_pickle_loader(self, tmp_path):
        """Write a fake cifar-10-batches-py dir in the on-disk format."""
        import pickle
        d = tmp_path / "cifar-10-batches-py"
        d.mkdir()
        rng = np.random.default_rng(0)
        for i in range(1, 6):
            data = rng.integers(0, 256, (100, 3072)).astype(np.uint8)
            with open(d / f"data_batch_{i}", "wb") as f:
                pickle.dump({b"data": data,
                             b"labels": list(rng.integers(0, 10, 100))}, f)
        with open(d / "test_batch", "wb") as f:
            pickle.dump({b"data": rng.integers(0, 256, (50, 3072)).astype(
                np.uint8), b"labels": list(rng.integers(0, 10, 50))}, f)
        train, test, real = cifar10.load(str(tmp_path))
        assert real
        assert train.images.shape == (500, 32, 32, 3)
        assert test.images.shape == (50, 32, 32, 3)
