"""End-to-end equivalence against the actual torch reference stack.

Every *piece* of this framework is parity-tested against torch in isolation
(layers, SGD, CE, transplanted-weights forward).  This test is the one the
reference's structure implies but never writes down at the INTEGRATION level
(VERDICT r2 item 3): identical init, identical data order, augmentation off,
then N >= 50 training steps of

  * the reference's semantics in torch — zero_grad -> forward -> CE ->
    backward -> SGD(0.1, 0.9, 1e-4) step, eager, train-mode BN
    (``/root/reference/src/Part 1/main.py:17-58``), vs
  * this framework's real path — ``Trainer.train_model``'s compiled windowed
    scan, including the ragged final batch's own compiled step,

and the loss trajectories and final parameters must agree to fp tolerance.
Any integration-level semantic drift — batch order, BN update order or
momentum, gradient scaling, normalization constants, loss accounting —
shows up here as an O(1e-1) divergence; fp32 backend differences (XLA vs
ATen conv algorithms) stay orders of magnitude below the tolerances.

The equivalence runs use lr=0.01 (the reference's other hyperparameters —
momentum 0.9, weight decay 1e-4, CE loss, per-batch SGD — unchanged): at
the reference's lr=0.1 this batch-32 configuration is UNSTABLE (running
loss swings past 11), and an unstable trajectory amplifies benign fp32
backend rounding exponentially until no tolerance separates real drift
from chaos — the same reasoning as the BN-free averaging oracle in
test_train_e2e.py.  lr-scaling correctness itself is pinned against torch
in test_sgd.py, so nothing is lost by choosing stable dynamics here.
"""

import numpy as np
import pytest
import torch
import torch.nn as nn

import jax
import jax.numpy as jnp

from cs744_ddp_tpu.data import cifar10
from cs744_ddp_tpu.ops import sgd
from cs744_ddp_tpu.parallel import mesh as meshlib
from cs744_ddp_tpu.train.loop import Trainer, _shard_batches
from cs744_ddp_tpu.train.step import TrainState

from test_models import torch_vgg11

# 10 full batches of 32 plus a ragged tail of 16 per epoch; 5 epochs = 55
# steps >= the 50 the equivalence bar asks for.  Batch 32 keeps the torch
# side ~1 s/step on this 1-core host.
BATCH = 32
N_EXAMPLES = 32 * 10 + 16
EPOCHS = 5
LR = 0.01   # stable dynamics — see module docstring


def transplant_from_torch(tmodel) -> tuple:
    """Copy a torch VGG-11's weights/buffers into our pytree layout
    (the machinery of test_models.py's transplanted-forward parity test)."""
    convs = [m for m in tmodel.layers if isinstance(m, nn.Conv2d)]
    bns = [m for m in tmodel.layers if isinstance(m, nn.BatchNorm2d)]
    params = {
        "conv": [
            {"w": jnp.asarray(c.weight.detach().numpy().transpose(2, 3, 1, 0)),
             "b": jnp.asarray(c.bias.detach().numpy())} for c in convs],
        "bn": [
            {"gamma": jnp.asarray(b.weight.detach().numpy()),
             "beta": jnp.asarray(b.bias.detach().numpy())} for b in bns],
        "fc1": {"w": jnp.asarray(tmodel.fc1.weight.detach().numpy().T),
                "b": jnp.asarray(tmodel.fc1.bias.detach().numpy())},
    }
    state = {"bn": [
        {"mean": jnp.asarray(b.running_mean.numpy()),
         "var": jnp.asarray(b.running_var.numpy())} for b in bns]}
    return params, state


def normalize_np(u8: np.ndarray) -> np.ndarray:
    """ToTensor + Normalize with the reference's channel stats
    (``Part 1/main.py:82-89``), NHWC f32."""
    return ((u8.astype(np.float32) / 255.0) - cifar10.MEAN) / cifar10.STD


def run_torch_reference(tmodel, split, epochs: int):
    """The reference's train_model loop, eager torch, on our shard order."""
    opt = torch.optim.SGD(tmodel.parameters(), lr=LR, momentum=0.9,
                          weight_decay=1e-4)
    lossfn = nn.CrossEntropyLoss()
    tmodel.train()
    losses = []
    for epoch in range(epochs):
        for imgs, labs in _shard_batches(split, 1, BATCH, epoch,
                                         shuffle=True):
            x = torch.from_numpy(
                np.ascontiguousarray(normalize_np(imgs).transpose(0, 3, 1, 2)))
            y = torch.from_numpy(labs.astype(np.int64))
            opt.zero_grad()
            loss = lossfn(tmodel(x), y)
            loss.backward()
            opt.step()
            losses.append(float(loss.detach()))
    return losses


@pytest.mark.slow  # ~20 min: 55 full VGG-11 steps on both stacks, CPU
def test_trainer_matches_torch_reference_stack(tmp_path, mesh1):
    torch.manual_seed(0)
    tmodel = torch_vgg11()

    tr = Trainer(model="vgg11", strategy="single", mesh=mesh1,
                 global_batch=BATCH, data_dir=str(tmp_path), augment=False,
                 sgd_cfg=sgd.SGDConfig(lr=LR), log=lambda s: None)
    split = cifar10.Split(tr.train_split.images[:N_EXAMPLES],
                          tr.train_split.labels[:N_EXAMPLES])
    tr.train_split = split

    # Identical init: transplant the torch model's seed-0 weights.
    params, bn_state = transplant_from_torch(tmodel)
    tr.state = meshlib.put_global_tree(
        TrainState(params, bn_state, sgd.init(params)),
        meshlib.replicated(mesh1))

    ours = []
    for epoch in range(EPOCHS):
        ours.extend(tr.train_model(epoch).losses)

    theirs = run_torch_reference(tmodel, split, EPOCHS)

    assert len(ours) == len(theirs) == EPOCHS * 11  # incl. ragged tails

    # Loss trajectories agree step for step.  Backend fp differences (XLA
    # vs ATen conv algorithms) compound linearly through 55 stable steps;
    # integration-level semantic drift would be orders of magnitude above
    # this bound.
    np.testing.assert_allclose(ours, theirs, atol=0.02, rtol=0.02)

    # Final parameters agree leaf for leaf.
    final_theirs, final_bn_theirs = transplant_from_torch(tmodel)
    for a, b in zip(jax.tree.leaves(tr.state.params),
                    jax.tree.leaves(final_theirs)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=0.02)
    # BN running MEANS integrated the same batch statistics.  The bound is
    # a gross-drift guard only: a semantic error (state not threaded
    # through the windowed scan, wrong momentum, update order) leaves the
    # means near init (0) or integrated on the wrong schedule — O(1) error
    # against magnitudes of 0.2-2 here — while honest backend fp drift
    # measured <= 0.073 across all layers on jax >= 0.5 and <= 0.27 (14/128
    # channels past 0.15, losses and params still within their bounds) on
    # jax 0.4.37's CPU conv algorithms.  Running VARIANCES are not
    # asserted: they are second-order statistics of activations that this
    # 55-step run trains to memorization (final loss ~2e-4), where benign
    # fp drift amplifies to ~60% relative on near-dead channels; the BN
    # update rule itself (biased/unbiased, momentum 0.1) is pinned
    # element-exactly against torch.nn.BatchNorm2d in test_layers.py.
    for ours_layer, theirs_layer in zip(tr.state.bn_state["bn"],
                                        final_bn_theirs["bn"]):
        np.testing.assert_allclose(np.asarray(ours_layer["mean"]),
                                   np.asarray(theirs_layer["mean"]),
                                   atol=0.35)
