"""Serving fast-path tests (cs744_ddp_tpu/serve/) on the CPU backend.

The central pin is the ISSUE's acceptance bar: bucketed serving output is
BITWISE-identical (f32) to an unpadded direct forward at the exact request
size, including ragged fills — with ``train=False`` BatchNorm every row is
computed independently of its batchmates, so padding must be a pure layout
detail.  Around it: the batching policy's determinism under a seeded trace
(the pure ``plan_batches`` replay), the threaded micro-batcher returning
each request its own rows, the warm-start executable-cache roundtrip, the
staged-ingest slot-reuse safety, and the telemetry-off path touching the
recorder not at all.
"""

import threading
import time

import numpy as np
import pytest

from cs744_ddp_tpu import models as model_zoo
from cs744_ddp_tpu.data import cifar10
from cs744_ddp_tpu.obs import NULL
from cs744_ddp_tpu.serve import (InferenceEngine, MicroBatcher, QueueFull,
                                 StagedIngest, coalesce,
                                 executable_serialization_supported,
                                 plan_batches)
from cs744_ddp_tpu.serve.batcher import smallest_bucket
from cs744_ddp_tpu.serve.demo import parse_buckets, synthetic_trace

from tinynet import tiny_cnn


def setup_module(module):
    model_zoo.register_model("tiny", tiny_cnn)


@pytest.fixture(scope="module")
def pool():
    return cifar10._synthetic_split(64, seed=3)


@pytest.fixture(scope="module")
def engine():
    model_zoo.register_model("tiny", tiny_cnn)
    return InferenceEngine("tiny", buckets=(2, 4, 8), seed=0)


# -- ladder shape -------------------------------------------------------------

def test_bucket_for_edges(engine):
    assert engine.bucket_for(1) == 2
    assert engine.bucket_for(2) == 2
    assert engine.bucket_for(3) == 4
    assert engine.bucket_for(8) == 8
    assert engine.max_batch == 8
    with pytest.raises(ValueError, match="at least one"):
        engine.bucket_for(0)
    with pytest.raises(ValueError, match="exceeds the largest"):
        engine.bucket_for(9)


def test_engine_validates_config():
    with pytest.raises(ValueError, match="strictly increasing"):
        InferenceEngine("tiny", buckets=(4, 2))
    with pytest.raises(ValueError, match="strictly increasing"):
        InferenceEngine("tiny", buckets=(2, 2, 4))
    with pytest.raises(ValueError, match="at least one bucket"):
        InferenceEngine("tiny", buckets=())
    with pytest.raises(ValueError, match="unknown precision"):
        InferenceEngine("tiny", buckets=(2,), precisions=("f16",))


# -- bitwise equivalence (the acceptance pin) ---------------------------------

def test_bucketed_output_bitwise_equals_direct_forward(engine, pool):
    """Every ragged fill of every bucket: the engine's sliced logits must be
    BITWISE-identical f32 to jit-compiling the same forward at the exact
    request size with no padding.

    n=1 is excluded from the bitwise leg: XLA specializes batch-1 codegen
    (different instruction order, last-ulp drift vs every batch>=2 program
    — measured on this CPU backend), so the DIRECT program is the outlier
    there, not the padding; the singleton case is pinned separately via
    composition invariance below."""
    import jax
    direct = jax.jit(engine._forward["f32"])
    for n in (2, 3, 5, 7, 8):
        imgs = pool.images[:n]
        labs = pool.labels[:n]
        logits, loss, correct = engine.infer_counts(imgs, labs)
        d_logits, d_loss, d_correct = direct(
            engine.params, engine.bn_state, imgs,
            np.asarray(labs, np.int32))
        assert logits.shape == (n, 10) and logits.dtype == np.float32
        assert np.array_equal(logits, np.asarray(d_logits)), \
            f"bucketed logits differ from direct forward at n={n}"
        # The masked counts: pad rows carry label -1 and contribute zero.
        # correct is an integer count (exact); loss_sum's reduction tree
        # differs between bucket sizes, so it is float-close, not bitwise.
        assert correct == int(d_correct)
        assert loss == pytest.approx(float(d_loss), rel=1e-6)


def test_request_rows_are_batchmate_invariant(engine, pool):
    """A request's logits rows are BITWISE-independent of what rides (or
    pads) alongside it — the property that makes bucketed serving exact
    at every fill, including n=1."""
    import jax
    # Same bucket program, different fill/pad composition.
    solo = engine.infer(pool.images[:1])
    paired = engine.infer(pool.images[:2])[:1]
    assert np.array_equal(solo, paired)
    full = engine.infer(np.concatenate([pool.images[:5],
                                        pool.images[20:23]]))[:5]
    assert np.array_equal(engine.infer(pool.images[:5]), full)
    # The singleton still matches the batch-1 direct program float-close
    # (see the bitwise test's docstring for why not bitwise).
    direct = jax.jit(engine._forward["f32"])
    d_logits, _, _ = direct(engine.params, engine.bn_state,
                            pool.images[:1], np.full((1,), -1, np.int32))
    np.testing.assert_allclose(solo, np.asarray(d_logits), rtol=1e-5)


def test_staging_and_plain_copy_paths_identical(engine, pool):
    """use_staging=False (padded np copy) must produce the same staged
    bytes, hence bitwise-identical logits, as the arena path."""
    plain = InferenceEngine("tiny", buckets=(2, 4, 8), seed=0,
                            use_staging=False)
    for n in (1, 3, 6):
        a = engine.infer(pool.images[:n])
        b = plain.infer(pool.images[:n])
        assert np.array_equal(a, b)


def test_unlabeled_request_counts_are_zero(engine, pool):
    logits, loss, correct = engine.infer_counts(pool.images[:3])
    assert logits.shape == (3, 10)
    assert loss == 0.0 and correct == 0


# -- batching policy (pure functions) -----------------------------------------

def test_coalesce_prefix_selection():
    assert coalesce([1, 2, 4], 8) == (3, 7)
    assert coalesce([1, 2, 4, 2], 8) == (3, 7)   # 4th would overflow
    assert coalesce([8, 1], 8) == (1, 8)
    assert coalesce([], 8) == (0, 0)
    # FIFO atomicity: an oversized head blocks the prefix entirely rather
    # than being skipped around (requests are never reordered or split).
    assert coalesce([9, 1], 8) == (0, 0)


def test_smallest_bucket():
    assert smallest_bucket((2, 4, 8), 3) == 4
    assert smallest_bucket((2, 4, 8), 8) == 8
    with pytest.raises(ValueError, match="exceed"):
        smallest_bucket((2, 4, 8), 9)


def test_plan_batches_deterministic_and_policy_sound():
    buckets = (2, 4, 8)
    max_wait = 0.004
    trace = synthetic_trace(48, offered_rps=300.0, seed=5,
                            size_choices=(1, 1, 2, 4, 8))
    plan = plan_batches(trace, buckets, max_wait)
    # Determinism: the same seeded trace replans to the same batches.
    assert plan == plan_batches(trace, buckets, max_wait)
    assert plan != plan_batches(trace, buckets, max_wait * 4)

    # Coverage: every request rides exactly once, in FIFO order.
    ridden = [i for b in plan for i in b["requests"]]
    assert ridden == list(range(len(trace)))
    for b in plan:
        # The recorded totals are consistent and fit the chosen bucket,
        # which is the smallest covering one.
        assert b["images"] == sum(trace[i][1] for i in b["requests"])
        assert b["bucket"] == smallest_bucket(buckets, b["images"])
        # No dispatch is released before its requests arrive, and none
        # later than the oldest request's deadline.
        first_t = trace[b["requests"][0]][0]
        last_t = max(trace[i][0] for i in b["requests"])
        assert last_t <= b["t"] + 1e-9
        assert b["t"] <= first_t + max_wait + 1e-9


def test_plan_batches_zero_wait_degenerates_to_per_request():
    trace = synthetic_trace(16, offered_rps=50.0, seed=2,
                            size_choices=(1, 2))
    plan = plan_batches(trace, (2, 4), 0.0)
    # Distinct arrival stamps + zero wait: nothing ever coalesces.
    assert len(plan) == len(trace)
    assert all(len(b["requests"]) == 1 for b in plan)


def test_plan_batches_rejects_oversized_request():
    with pytest.raises(ValueError, match="exceeds the largest"):
        plan_batches([(0.0, 9)], (2, 4, 8), 0.01)


def test_synthetic_trace_seeded():
    a = synthetic_trace(20, offered_rps=30.0, seed=4)
    assert a == synthetic_trace(20, offered_rps=30.0, seed=4)
    assert a != synthetic_trace(20, offered_rps=30.0, seed=5)
    assert a[0][0] == 0.0
    assert all(t1 <= t2 for (t1, _), (t2, _) in zip(a, a[1:]))


def test_parse_buckets():
    assert parse_buckets("8,1,32") == (1, 8, 32)
    assert parse_buckets("4,4") == (4,)


# -- threaded micro-batcher ---------------------------------------------------

def test_microbatcher_returns_each_request_its_own_rows(engine, pool):
    """Futures resolve to the submitting request's exact logits rows —
    bitwise equal to serving each request alone."""
    rng = np.random.default_rng(0)
    sizes = [1, 3, 2, 8, 1, 4, 5, 2]
    reqs = [pool.images[rng.integers(0, len(pool.images), size=s)]
            for s in sizes]
    with MicroBatcher(engine, max_wait_ms=2.0) as mb:
        futs = [mb.submit(imgs) for imgs in reqs]
        outs = [f.result(timeout=30) for f in futs]
    for imgs, out in zip(reqs, outs):
        assert out.shape == (len(imgs), 10)
        assert np.array_equal(out, engine.infer(imgs))


def test_microbatcher_lifecycle_and_bounds(engine, pool):
    mb = MicroBatcher(engine)
    with pytest.raises(RuntimeError, match="not running"):
        mb.submit(pool.images[:1])
    with mb:
        with pytest.raises(ValueError, match="exceeds the largest"):
            mb.submit(pool.images[:9])   # > max_batch, before enqueue
    with pytest.raises(RuntimeError, match="already started"):
        mb.start() and mb.start()


class _GatedEngine:
    """Engine stub whose dispatch blocks on an event: makes queue-pressure
    tests deterministic (the worker is provably busy while we fill)."""

    buckets = (8,)
    max_batch = 8
    telemetry = NULL

    def __init__(self):
        self.gate = threading.Event()
        self.calls = []

    def infer_counts(self, images, labels, precision="f32"):
        self.gate.wait(timeout=30)
        self.calls.append(len(images))
        return np.zeros((len(images), 10), np.float32), 0.0, 0


def test_microbatcher_bounded_queue_rejects():
    eng = _GatedEngine()
    with MicroBatcher(eng, max_wait_ms=0.0, max_queue_images=8) as mb:
        first = mb.submit(np.zeros((8, 32, 32, 3), np.uint8))
        # The worker owns the first batch (blocked at the gate); the queue
        # itself now has room for exactly one more full bucket.
        deadline = time.time() + 5
        while time.time() < deadline:
            with mb._cond:
                if not mb._pending:
                    break
            time.sleep(0.001)
        second = mb.submit(np.zeros((8, 32, 32, 3), np.uint8))
        with pytest.raises(QueueFull) as ei:
            mb.submit(np.zeros((1, 32, 32, 3), np.uint8))
        # Backpressure hint: queue depth x service EWMA (10 ms prior
        # before the first dispatch completes), never a bare reject.
        assert ei.value.retry_after_ms > 0.0
        eng.gate.set()
        first.result(timeout=30)
        second.result(timeout=30)
    assert eng.calls == [8, 8]


class _FailingEngine:
    buckets = (4,)
    max_batch = 4
    telemetry = NULL

    def infer_counts(self, images, labels, precision="f32"):
        raise RuntimeError("device fell over")


def test_microbatcher_propagates_engine_failure():
    with MicroBatcher(_FailingEngine(), max_wait_ms=0.0) as mb:
        fut = mb.submit(np.zeros((2, 32, 32, 3), np.uint8))
        with pytest.raises(RuntimeError, match="fell over"):
            fut.result(timeout=30)


# -- warm-start executable cache ----------------------------------------------

@pytest.mark.skipif(not executable_serialization_supported(),
                    reason="jax lacks serialize_executable")
def test_executable_cache_roundtrip(tmp_path, pool):
    """Cold startup compiles + saves; a fresh engine on the same dir loads
    every rung from cache and serves bitwise-identical logits."""
    cold = InferenceEngine("tiny", buckets=(2, 4), seed=0,
                           cache_dir=str(tmp_path))
    r_cold = cold.startup()
    assert not r_cold["warm"]
    assert all(v["source"] == "compile"
               for v in r_cold["per_bucket"].values())

    warm = InferenceEngine("tiny", buckets=(2, 4), seed=0,
                           cache_dir=str(tmp_path))
    r_warm = warm.startup()
    assert r_warm["warm"]
    assert all(v["source"] == "cache"
               for v in r_warm["per_bucket"].values())
    assert r_warm["executable_cache"]["hits"] == 2
    assert r_warm["startup_s"] < r_cold["startup_s"]
    for n in (1, 3):
        assert np.array_equal(cold.infer(pool.images[:n]),
                              warm.infer(pool.images[:n]))


@pytest.mark.skipif(not executable_serialization_supported(),
                    reason="jax lacks serialize_executable")
def test_executable_cache_treats_garbage_as_miss(tmp_path):
    from cs744_ddp_tpu.serve.cache import ExecutableCache, cache_key
    cache = ExecutableCache(str(tmp_path))
    key = cache_key(bucket=2, model="x")
    with open(cache._path(key), "wb") as f:
        f.write(b"not a pickle")
    assert cache.load(key) is None
    assert cache.stats()["misses"] == 1


def test_cache_key_is_stable_and_field_sensitive():
    from cs744_ddp_tpu.serve.cache import cache_key
    assert cache_key(a=1, b="x") == cache_key(b="x", a=1)
    assert cache_key(a=1) != cache_key(a=2)


# -- staged ingest ------------------------------------------------------------

def test_staged_ingest_roundtrip_and_slot_reuse(pool):
    """Staged rows match the source with zeroed pads, and results staged
    earlier survive the arena cycling through all its slots."""
    ing = StagedIngest(8, nslots=2)
    batches = [pool.images[i * 8:i * 8 + n]
               for i, n in enumerate((3, 8, 5))]   # > nslots stages
    handles = [ing.stage(b, 8) for b in batches]
    for src, h in zip(batches, handles):
        got = np.asarray(h)
        assert got.shape == (8, 32, 32, 3)
        assert np.array_equal(got[:len(src)], src)
        assert not got[len(src):].any()   # deterministic zero padding


def test_staged_ingest_bounds(pool):
    ing = StagedIngest(8)
    with pytest.raises(ValueError, match="cannot stage"):
        ing.stage(pool.images[:0], 8)
    with pytest.raises(ValueError, match="cannot stage"):
        ing.stage(pool.images[:9], 8)
    with pytest.raises(ValueError, match="cannot stage"):
        ing.stage(pool.images[:4], 16)   # bucket beyond the arena


# -- telemetry-off path -------------------------------------------------------

class _ExplodingRecorder:
    """enabled=False recorder whose every method call fails the test: the
    disabled serving path must never touch the recorder (the NULL path's
    zero-allocation contract)."""

    enabled = False

    def __getattr__(self, name):
        raise AssertionError(
            f"telemetry.{name} touched with telemetry disabled")


def test_disabled_telemetry_is_never_touched(pool):
    eng = InferenceEngine("tiny", buckets=(2, 4), seed=0,
                          telemetry=_ExplodingRecorder())
    eng.startup()
    eng.infer_counts(pool.images[:3], pool.labels[:3])
    with MicroBatcher(eng, max_wait_ms=1.0) as mb:
        futs = [mb.submit(pool.images[:2]) for _ in range(4)]
        for f in futs:
            f.result(timeout=30)
    # And the shared NULL singleton holds no per-call state at all.
    assert not hasattr(NULL, "records")
    assert NULL.counter_totals() == {}


# -- end-to-end demo / cli ----------------------------------------------------

def _report_module(monkeypatch):
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    monkeypatch.syspath_prepend(os.path.join(repo, "tools"))
    import telemetry_report
    return telemetry_report


def test_cli_serve_demo_end_to_end(capsys, tmp_path, monkeypatch):
    import json

    from cs744_ddp_tpu import cli
    cli.main(["--serve-demo", "--model", "tiny", "--serve-buckets", "2,4",
              "--serve-requests", "12", "--serve-load", "300",
              "--serve-max-wait-ms", "2", "--serve-seed", "1",
              "--telemetry-out", str(tmp_path)])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert set(out) == {"startup", "demo"}
    assert set(out["startup"]["per_bucket"]) == {"2", "4"}
    demo = out["demo"]["300rps"]
    assert demo["completed"] + demo["rejected"] == 12
    assert demo["completed"] > 0 and "latency_ms" in demo
    # The run directory carries the serving manifest + events; the report
    # tool renders it (serving section present exactly when serve gauges
    # exist — tools/telemetry_report.py).
    tr = _report_module(monkeypatch)
    text = tr.render(str(tmp_path))
    assert "== serving ==" in text
    assert "request latency by bucket" in text
    man = json.loads((tmp_path / "manifest.json").read_text())
    assert man["mode"] == "serve"
    assert "compilation_cache" in man


def test_report_tolerates_run_without_serving_events(tmp_path, monkeypatch):
    """A plain training-run directory renders with no serving section."""
    from cs744_ddp_tpu.obs import Telemetry
    tr = _report_module(monkeypatch)
    tel = Telemetry(out_dir=str(tmp_path))
    tel.write_manifest({"model": "tiny"})
    tel.step(epoch=0, iter=0, loss=1.0, step_time=0.01)
    tel.finalize()
    assert "== serving ==" not in tr.render(str(tmp_path))
