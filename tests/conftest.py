"""Test configuration: run JAX on CPU with 8 virtual devices.

This replaces the reference's "4 real VMs + Gloo" test environment
(SURVEY.md §4): the same Mesh/shard_map code paths run unmodified on
8 fake CPU devices, so every distributed strategy is exercised without
TPU hardware.  Must set env vars BEFORE jax is imported anywhere.
"""

import os

# Force CPU: the driver environment presets JAX_PLATFORMS=axon (real TPU),
# and jax is already imported at interpreter startup by a site hook, so the
# env var route is too late — use jax.config (backends are still lazy).
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.devices()[0].platform == "cpu", (
    "tests must run on the virtual-device CPU backend")

# Persist XLA compilations (same cache bench.py uses): saves ~4 min of
# repeated CPU-backend compiles across suite runs.  The deviceless TPU AOT
# client cannot DESERIALIZE cache entries (jax warns and recompiles — hence
# the filter); everything else hits.
import warnings  # noqa: E402

warnings.filterwarnings(
    "ignore", message="Error reading persistent compilation cache entry")
from cs744_ddp_tpu.utils.compcache import \
    enable_persistent_compilation_cache  # noqa: E402

enable_persistent_compilation_cache(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from cs744_ddp_tpu.parallel import make_mesh
    assert len(jax.devices()) >= 8, "need 8 virtual devices"
    return make_mesh(8)


@pytest.fixture(scope="session")
def mesh4():
    from cs744_ddp_tpu.parallel import make_mesh
    return make_mesh(4)


@pytest.fixture(scope="session")
def mesh1():
    from cs744_ddp_tpu.parallel import make_mesh
    return make_mesh(1)
