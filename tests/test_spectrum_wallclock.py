"""POSITIVE strategy-spectrum separation in CI (VERDICT r3 item 3a).

The reference's entire pedagogical point is the ordering
gather (Part 2a) > allreduce (Part 2b) > ddp (Part 3) in per-step cost
(``/root/reference/src/Part 2a/main.py:117-127`` vs ``Part 2b/main.py:
116-119`` vs ``Part 3/main.py:61``).  tests/test_strategies.py pins the
structural distinction (HLO patterns) and a one-directional bound (ddp must
not lose); this test asserts the POSITIVE wall-clock separation, so a
regression that equalized the tiers — e.g. a barrier-chain change letting
XLA's all-reduce combiner merge the per-param tier — fails CI.

Measured where the collective patterns dominate: a shrunken variant of the
comm-bound MLP from tools/bench_strategy_spectrum.py (many small leaves, 1
example per device) on the 8-virtual-device CPU mesh; the full-size tool
run is what BASELINE.md records (gather 3,110 > allreduce 2,068 > ddp
1,430 ms/step, a 1.5x gap for the asserted pair).

Noise discipline — this host is ONE core timesliced across 8 virtual
devices, so external load inflates steps by 2x+ in bursts: samples are
single steps, rounds are INTERLEAVED across tiers, and the compared
statistic is the MIN over rounds (contention is strictly one-sided, the
same convention as the bench's best-of-N — an early median-based version
of this test flaked twice under full-suite load, once even inverting the
ordering when a burst landed on gather's quiet slot).

Only gather > allreduce is asserted: the allreduce-vs-ddp separation does
NOT survive the CPU backend reliably — it strips the optimization-barrier
chains, so the per-param and bucketed tiers' compiled forms converge
there (strategies.py module docstring; observed inverted under full-suite
load).  That ordering is pinned where it is real: structurally on the TPU
lowering (tests/test_tpu_aot.py — per-leaf vs per-bucket collective
counts) and in bench.py's static `spectrum` section.
"""

import os
import sys
import time

import numpy as np

import jax

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))
import bench_strategy_spectrum as spectool  # noqa: E402

from cs744_ddp_tpu.ops import sgd
from cs744_ddp_tpu.parallel import get_strategy, mesh as meshlib
from cs744_ddp_tpu.train import step as steplib

ROUNDS = 5


def test_spectrum_ordering_gather_above_allreduce(mesh8, monkeypatch):
    # Half-depth MLP (62 leaves): the separation is structural (2
    # sequential collectives per leaf vs 1), so fewer/smaller leaves keep
    # the ratio while making 5 interleaved rounds affordable in CI.
    monkeypatch.setattr(spectool, "LAYERS", [3072] + [512] * 30 + [10])
    state = steplib.init_train_state(spectool.mlp_init, jax.random.PRNGKey(0))
    state = meshlib.put_global_tree(state, meshlib.replicated(mesh8))

    batch = 8  # 1 example/device: per-step cost ~ the collective pattern
    rng = np.random.default_rng(0)
    images = jax.device_put(
        rng.integers(0, 256, (batch, 32, 32, 3)).astype(np.uint8),
        meshlib.batch_sharding(mesh8))
    labels = jax.device_put(
        rng.integers(0, 10, (batch,)).astype(np.int32),
        meshlib.batch_sharding(mesh8))
    key = jax.random.PRNGKey(1)

    # Only the two tiers whose ordering IS asserted get compiled and
    # stepped (ddp's separation lives on the TPU lowering, module
    # docstring — benchmarking it here was unasserted dead cost).
    steps, states = {}, {}
    for name in ("gather", "allreduce"):
        steps[name] = steplib.make_train_step(
            spectool.mlp_apply, get_strategy(name), mesh8, sgd.SGDConfig(),
            augment=False)
        s, loss = steps[name](state, key, images, labels)  # compile+warmup
        float(loss)
        states[name] = s

    samples = {name: [] for name in steps}
    for _ in range(ROUNDS):
        for name, step in steps.items():   # interleaved: contention is
            s = states[name]               # shared across tiers per round
            t0 = time.time()
            s, loss = step(s, key, images, labels)
            float(loss)                    # value fetch = completion fence
            samples[name].append(time.time() - t0)
            states[name] = s

    best = {name: min(v) for name, v in samples.items()}
    assert best["gather"] > 1.1 * best["allreduce"], (best, samples)


def test_compressed_tiers_never_lose_on_measured_comm_bytes(mesh8,
                                                            monkeypatch):
    """Round-7 byte ladder, MEASURED on the lowering (collective RESULT
    bytes from the pre-optimization HLO, analysis/stats.py — the same
    accounting --audit-zoo certifies): no compressed tier may ever carry
    more all-reduce traffic than the per-param f32 tier, and the declared
    ratios hold with margin — bf16 ~2x, int8 ~4x, powersgd far below on
    the MLP's (3072,512)/(512,512) leaves.  Wall-clock can't separate the
    tiers on the one-core CPU mesh (docstring above); bytes can."""
    from cs744_ddp_tpu.analysis import stats
    monkeypatch.setattr(spectool, "LAYERS", [3072] + [512] * 6 + [10])

    batch = 8
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, (batch, 32, 32, 3)).astype(np.uint8)
    labels = rng.integers(0, 10, (batch,)).astype(np.int32)
    key = jax.random.PRNGKey(1)

    def ar_bytes(name):
        strat = get_strategy(name)
        state = steplib.init_train_state(
            spectool.mlp_init, jax.random.PRNGKey(0), strat, 8)
        step = steplib.make_train_step(spectool.mlp_apply, strat, mesh8,
                                       sgd.SGDConfig(), augment=False)
        hlo = step.lower(state, key, images, labels).compiler_ir(
            dialect="hlo").as_hlo_text()
        return stats.collective_bytes(hlo).get("all-reduce", 0)

    f32 = ar_bytes("allreduce")
    measured = {t: ar_bytes(t)
                for t in ("compress-bf16", "compress-int8", "powersgd")}
    # The satellite's one-directional floor: never lose to per-param f32.
    for tier, got in measured.items():
        assert got < f32, (tier, got, f32)
    # And the contract ratios, with headroom for the non-gradient aux
    # collectives (loss psum; int8's packed shared-scale pmax).
    assert measured["compress-bf16"] <= 0.55 * f32, (measured, f32)
    assert measured["compress-int8"] <= 0.30 * f32, (measured, f32)
    # rank 4 on (3072,512): 4*(m+n) floats vs m*n — order-of-magnitude.
    assert measured["powersgd"] <= 0.20 * f32, (measured, f32)
