"""Fault-tolerance layer (ft/) tests: the deterministic chaos harness, the
supervised staging pipeline, the non-finite step guard, preemption-safe
mid-epoch resume, and the atomic-artifact/truncated-telemetry satellites.

The load-bearing pins are BITWISE: every recovery path that promises to
preserve the training stream (producer restart, degraded staging, checksum
repair, put retry, mid-epoch resume) must leave the final TrainState
byte-identical to an undisturbed run of the SAME program configuration.
Guard-on vs guard-off runs compile different step programs (XLA fuses them
differently, ~1e-10 apart), so no test compares across that boundary.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np

import jax
import pytest

import cs744_ddp_tpu.train.loop as looplib
from cs744_ddp_tpu.data import cifar10
from cs744_ddp_tpu.elastic import ElasticCoordinator
from cs744_ddp_tpu.ft import (NULL_CHAOS, PUBLISH_SITES, RANK_SITES, SITES,
                              ChaosPlan, FTConfig, NonFiniteError, NullChaos,
                              RankDeathError, StagingStalled, Watchdog,
                              batch_checksums, call_with_retry,
                              verify_checksums)
from cs744_ddp_tpu.parallel import make_mesh
from cs744_ddp_tpu.obs.telemetry import atomic_write_json, read_events_jsonl
from cs744_ddp_tpu.train.checkpoint import CheckpointManager
from cs744_ddp_tpu.train.loop import Trainer

from tinynet import tiny_cnn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- chaos plan ---------------------------------------------------------------

def test_chaos_parse_specs_and_empty():
    plan = ChaosPlan.parse(["put_fail:2", "corrupt_slot:3:7"])
    assert plan.enabled
    assert plan.spec() == [
        {"site": "put_fail", "step": 2, "seed": 0},
        {"site": "corrupt_slot", "step": 3, "seed": 7}]
    # Empty/None parse to the stateless disabled singleton, not a plan.
    assert ChaosPlan.parse(None) is NULL_CHAOS
    assert ChaosPlan.parse([]) is NULL_CHAOS


def test_chaos_parse_rejects_bad_specs():
    with pytest.raises(ValueError, match="SITE:step"):
        ChaosPlan.parse(["put_fail"])
    with pytest.raises(ValueError, match="integers"):
        ChaosPlan.parse(["put_fail:x"])
    with pytest.raises(ValueError, match="unknown chaos site"):
        ChaosPlan.parse(["meteor_strike:3"])
    with pytest.raises(ValueError, match=">= 0"):
        ChaosPlan.parse(["put_fail:-1"])


def test_chaos_fire_is_one_shot_and_recorded():
    plan = ChaosPlan.parse(["producer_crash:4"])
    assert not plan.fire("producer_crash", 3)
    assert plan.fire("producer_crash", 4)
    assert not plan.fire("producer_crash", 4)      # one-shot
    assert not plan.fire("put_fail", 4)            # other sites unaffected
    assert plan.fired == [("producer_crash", 4)]


def test_chaos_fire_range_and_reached():
    plan = ChaosPlan.parse(["put_fail:5", "preempt:3"])
    assert not plan.fire_range("put_fail", 0, 5)   # half-open: 5 excluded
    assert plan.fire_range("put_fail", 5, 8)
    assert not plan.fire_range("put_fail", 5, 8)
    assert not plan.fire_reached("preempt", 2)
    assert plan.fire_reached("preempt", 7)         # >= the planned step
    assert not plan.fire_reached("preempt", 7)
    assert plan.fired == [("put_fail", 5), ("preempt", 3)]


def test_chaos_steps_lists_planned_not_fired():
    plan = ChaosPlan.parse(["put_fail:1", "put_fail:9", "preempt:2"])
    assert plan.steps("put_fail") == (1, 9)
    plan.fire("put_fail", 1)
    assert plan.steps("put_fail") == (1, 9)        # fired entries stay listed


def test_chaos_rng_deterministic_in_seed_site_step():
    a = ChaosPlan.parse(["corrupt_slot:3:7"]).rng("corrupt_slot", 3)
    b = ChaosPlan.parse(["corrupt_slot:3:7"]).rng("corrupt_slot", 3)
    c = ChaosPlan.parse(["corrupt_slot:3:8"]).rng("corrupt_slot", 3)
    xs, ys, zs = (r.integers(0, 2**31, size=16) for r in (a, b, c))
    np.testing.assert_array_equal(xs, ys)
    assert not np.array_equal(xs, zs)


def test_chaos_fire_thread_safe_exactly_once():
    plan = ChaosPlan.parse(["producer_crash:0"])
    hits, barrier = [], threading.Barrier(8)

    def race():
        barrier.wait()
        if plan.fire("producer_crash", 0):
            hits.append(1)

    threads = [threading.Thread(target=race) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(hits) == 1


def test_null_chaos_is_stateless_and_all_false():
    assert NullChaos.__slots__ == ()
    assert NULL_CHAOS.enabled is False
    with pytest.raises(AttributeError):
        NULL_CHAOS.fired = []                      # no state can ever attach
    assert NULL_CHAOS.fire("producer_crash", 0) is False
    assert NULL_CHAOS.fire_range("put_fail", 0, 10) is False
    assert NULL_CHAOS.fire_reached("preempt", 10) is False
    assert NULL_CHAOS.steps("corrupt_slot") == ()
    assert NULL_CHAOS.spec() == []


def test_trainer_without_ft_compiles_no_supervision(tmp_path, mesh4):
    """ft=None is the zero-cost path: the chaos hook is the disabled
    singleton and none of the supervision/guard machinery is armed."""
    tr = Trainer(model=tiny_cnn(), strategy="allreduce", mesh=mesh4,
                 global_batch=64, data_dir=str(tmp_path), augment=True,
                 host_augment=True, log=lambda s: None)
    assert tr.chaos is NULL_CHAOS
    assert tr._supervise is False
    assert tr._guard_on is False
    assert tr._verify_chunks is False
    assert tr.staging_degraded is False


def test_chaos_nonfinite_requires_guard(tmp_path, mesh4):
    with pytest.raises(ValueError, match="nonfinite"):
        Trainer(model=tiny_cnn(), strategy="allreduce", mesh=mesh4,
                global_batch=64, data_dir=str(tmp_path), augment=True,
                host_augment=True, log=lambda s: None,
                ft=FTConfig(chaos=ChaosPlan.parse(["nonfinite_grad:1"])))


# -- supervision primitives ---------------------------------------------------

def test_watchdog_fires_once_detection_only():
    fired = []
    with Watchdog(0.02, on_timeout=fired.append) as wd:
        time.sleep(0.15)                           # body overruns but runs on
        body_done = True
    assert body_done and wd.fired and len(fired) == 1
    assert fired[0] >= 0.02


def test_watchdog_quiet_when_body_is_fast():
    fired = []
    with Watchdog(5.0, on_timeout=fired.append) as wd:
        pass
    assert not wd.fired and fired == []
    with Watchdog(None, on_timeout=fired.append):  # disabled deadline
        pass
    assert fired == []


def test_call_with_retry_backoff_and_callback_order():
    calls, retries, naps = [], [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError(f"transient {len(calls)}")
        return "ok"

    out = call_with_retry(flaky, attempts=4, backoff_base_s=0.05,
                          on_retry=lambda a, e: retries.append((a, str(e))),
                          sleep=naps.append)
    assert out == "ok" and len(calls) == 3
    assert retries == [(0, "transient 1"), (1, "transient 2")]
    assert naps == [0.05, 0.1]                     # base * 2**attempt


def test_call_with_retry_final_failure_propagates():
    with pytest.raises(OSError, match="always"):
        call_with_retry(lambda: (_ for _ in ()).throw(OSError("always")),
                        attempts=3, backoff_base_s=0.0, sleep=lambda s: None)
    with pytest.raises(ValueError, match="attempts"):
        call_with_retry(lambda: 1, attempts=0, backoff_base_s=0.0)


def test_checksums_detect_single_flipped_byte():
    rows = [np.arange(64, dtype=np.uint8).reshape(8, 8) for _ in range(3)]
    sums = batch_checksums(rows)
    assert verify_checksums(rows, sums) == []
    rows[1][3, 4] ^= 0x40
    assert verify_checksums(rows, sums) == [1]
    rows[1][3, 4] ^= 0x40                          # repair restores the sum
    assert verify_checksums(rows, sums) == []


# -- atomic artifact writes (satellite: kill-mid-write) -----------------------

def test_atomic_write_json_survives_sigkill_mid_write(tmp_path):
    """A process SIGKILLed at the worst instant — partial temp file written,
    atomic replace not yet reached — must leave the previous artifact
    intact and parseable (this is the window os.replace protects)."""
    path = tmp_path / "artifact.json"
    script = tmp_path / "killer.py"
    script.write_text(textwrap.dedent(f"""\
        import os, signal, sys
        sys.path.insert(0, {REPO!r})
        from cs744_ddp_tpu.obs.telemetry import atomic_write_json
        path = sys.argv[1]
        atomic_write_json(path, {{"generation": 0, "complete": True}})
        # Second write: die at the worst instant — the temp file holds a
        # torn half-document, the replace has not happened.
        tmp = f"{{path}}.{{os.getpid()}}.tmp"
        with open(tmp, "w") as f:
            f.write('{{"generation": 1, "comp')
            f.flush()
            os.fsync(f.fileno())
            os.kill(os.getpid(), signal.SIGKILL)
        """))
    proc = subprocess.run([sys.executable, str(script), str(path)],
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == -signal.SIGKILL, proc.stderr
    with open(path) as f:
        assert json.load(f) == {"generation": 0, "complete": True}
    # The orphaned temp file must not confuse a later writer.
    atomic_write_json(str(path), {"generation": 2})
    with open(path) as f:
        assert json.load(f) == {"generation": 2}


def test_atomic_write_json_cleans_tmp_on_serialization_error(tmp_path):
    path = str(tmp_path / "artifact.json")
    atomic_write_json(path, {"v": 0})
    with pytest.raises(TypeError):
        # Non-string keys raise MID-dump, after partial bytes hit the temp
        # file; the artifact must keep its previous content and the temp
        # file must be cleaned up.
        atomic_write_json(path, {"v": 1, ("bad", "key"): 2})
    with open(path) as f:
        assert json.load(f) == {"v": 0}
    assert [p for p in os.listdir(tmp_path) if p.endswith(".tmp")] == []


# -- truncated telemetry (satellite: report tolerates killed runs) ------------

def test_read_events_jsonl_tolerates_truncated_tail(tmp_path):
    p = str(tmp_path / "events.jsonl")
    with open(p, "w") as f:
        f.write(json.dumps({"kind": "step", "iter": 1}) + "\n")
        f.write(json.dumps({"kind": "counter", "name": "c"}) + "\n")
        f.write('{"kind": "step", "it')            # run killed mid-write
    warns = []
    events, n_bad = read_events_jsonl(p, warn=warns.append)
    assert [e["kind"] for e in events] == ["step", "counter"]
    assert n_bad == 1
    assert len(warns) == 1 and "undecodable" in warns[0]
    # Missing file: empty, not an error (a run killed before any event).
    assert read_events_jsonl(str(tmp_path / "absent.jsonl")) == ([], 0)


def test_telemetry_report_surfaces_truncated_lines(tmp_path, monkeypatch):
    from cs744_ddp_tpu.obs.telemetry import Telemetry
    monkeypatch.syspath_prepend(os.path.join(REPO, "tools"))
    import telemetry_report

    d = str(tmp_path / "run")
    tel = Telemetry(d)
    tel.write_manifest({"model": "tiny", "strategy": "ddp", "world_size": 4,
                        "global_batch": 64})
    for i in range(1, 6):
        tel.step(epoch=0, iter=i, loss=1.0 / i, step_time=0.01, steady=i > 2)
    with open(os.path.join(d, "events.jsonl"), "a") as f:
        f.write('{"kind": "step", "epoch": 0, "iter": 6, "los')  # torn tail
    text = telemetry_report.render(d)
    assert "!! 1 undecodable event line(s) skipped" in text
    assert "5 (3 steady)" in text                  # good lines still counted


# -- integration: the chaos matrix -------------------------------------------
#
# tiny_cnn on the 4-device CPU mesh, 7 batches of 64 with WINDOW=3 (windows
# at 3/6, final batch through the absolute window grid).  Synthetic CIFAR-10
# is deterministic, so one clean reference state serves every bitwise pin.

LIMIT = 7

_CLEAN_STATE = {}


def _trainer(tmp_path, mesh4, *, ft=None, limit=LIMIT, log=None,
             strategy="allreduce"):
    return Trainer(model=tiny_cnn(), strategy=strategy, mesh=mesh4,
                   global_batch=64, data_dir=str(tmp_path), augment=True,
                   host_augment=True, limit_train_batches=limit,
                   log=log or (lambda s: None), ft=ft)


def _host_state(tr):
    return jax.tree.map(lambda a: np.asarray(jax.device_get(a)), tr.state)


def _clean_state(tmp_path, mesh4, limit=LIMIT):
    assert looplib.WINDOW == 3, "callers must monkeypatch WINDOW first"
    if limit not in _CLEAN_STATE:
        tr = _trainer(tmp_path, mesh4, limit=limit)
        tr.train_model(0)
        _CLEAN_STATE[limit] = _host_state(tr)
    return _CLEAN_STATE[limit]


def _assert_bitwise(state_a, state_b):
    la, lb = jax.tree.leaves(state_a), jax.tree.leaves(state_b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.fixture
def small_window(monkeypatch):
    monkeypatch.setattr(looplib, "WINDOW", 3)


def test_producer_crash_restart_is_bitwise(tmp_path, mesh4, small_window):
    clean = _clean_state(tmp_path, mesh4)
    plan = ChaosPlan.parse(["producer_crash:4"])
    tr = _trainer(tmp_path, mesh4, ft=FTConfig(chaos=plan))
    tr.train_model(0)
    assert plan.fired == [("producer_crash", 4)]
    assert tr.producer_failures == 1
    assert tr.staging_degraded is False            # one restart sufficed
    _assert_bitwise(_host_state(tr), clean)


def test_producer_double_crash_degrades_bitwise(tmp_path, mesh4,
                                                small_window):
    clean = _clean_state(tmp_path, mesh4)
    # The restarted producer hits the second entry at the same step: the
    # restart budget (1) is exhausted and staging degrades to synchronous
    # per-batch puts — overlap lost, stream unchanged.
    plan = ChaosPlan.parse(["producer_crash:2", "producer_crash:2"])
    lines = []
    tr = _trainer(tmp_path, mesh4, ft=FTConfig(chaos=plan), log=lines.append)
    tr.train_model(0)
    assert tr.producer_failures == 2
    assert tr.staging_degraded is True
    assert any("degrading to synchronous" in ln for ln in lines)
    _assert_bitwise(_host_state(tr), clean)


def test_degraded_staging_mode_is_bitwise(tmp_path, mesh4, small_window):
    clean = _clean_state(tmp_path, mesh4)
    tr = _trainer(tmp_path, mesh4, ft=FTConfig(degrade_staging=True))
    assert tr.staging_degraded is True
    tr.train_model(0)
    assert tr.producer_failures == 0
    _assert_bitwise(_host_state(tr), clean)


def test_corrupt_slot_detected_repaired_bitwise(tmp_path, mesh4,
                                                small_window):
    clean = _clean_state(tmp_path, mesh4)
    plan = ChaosPlan.parse(["corrupt_slot:3"])
    lines = []
    tr = _trainer(tmp_path, mesh4, ft=FTConfig(chaos=plan), log=lines.append)
    assert tr._verify_chunks is True               # auto-on with this site
    tr.train_model(0)
    assert ("corrupt_slot", 3) in plan.fired
    assert any("staged batch 3 failed its checksum" in ln for ln in lines)
    _assert_bitwise(_host_state(tr), clean)


def test_put_fail_retried_bitwise(tmp_path, mesh4, small_window):
    clean = _clean_state(tmp_path, mesh4)
    plan = ChaosPlan.parse(["put_fail:2"])
    lines = []
    tr = _trainer(tmp_path, mesh4,
                  ft=FTConfig(chaos=plan, backoff_base_s=0.001),
                  log=lines.append)
    tr.train_model(0)
    assert ("put_fail", 2) in plan.fired
    assert any("retrying with backoff" in ln for ln in lines)
    assert tr.producer_failures == 0               # retry absorbed the fault
    _assert_bitwise(_host_state(tr), clean)


def test_put_delay_trips_watchdog_bitwise(tmp_path, mesh4, small_window):
    clean = _clean_state(tmp_path, mesh4)
    plan = ChaosPlan.parse(["put_delay:2"])
    lines = []
    tr = _trainer(tmp_path, mesh4,
                  ft=FTConfig(chaos=plan, put_timeout_s=0.05),
                  log=lines.append)
    tr.train_model(0)
    assert ("put_delay", 2) in plan.fired
    # Detection-only: the watchdog logs the overrun, the put completes.
    assert any("watchdog deadline" in ln for ln in lines)
    _assert_bitwise(_host_state(tr), clean)


def test_stall_deadline_raises_staging_stalled(tmp_path, mesh4):
    tr = _trainer(tmp_path, mesh4, ft=FTConfig())

    def wedged_fill(emit):
        emit("first")
        time.sleep(1.6)                            # producer alive but stuck

    it = tr._prefetch_iter(wedged_fill, stall_timeout_s=0.1)
    assert next(it) == "first"
    with pytest.raises(StagingStalled, match="deadline"):
        next(it)
    it.close()


# -- integration: non-finite step guard ---------------------------------------

def test_nonfinite_skip_counts_and_keeps_params_finite(tmp_path, mesh4,
                                                       small_window):
    plan = ChaosPlan.parse(["nonfinite_grad:2"])
    tr = _trainer(tmp_path, mesh4,
                  ft=FTConfig(nonfinite="skip", chaos=plan))
    timers = tr.train_model(0)
    assert ("nonfinite_grad", 2) in plan.fired
    assert tr.nonfinite_skipped == 1
    assert tr.nonfinite_restored == 0
    assert np.isfinite(timers.losses).all()        # bad update never applied
    for leaf in jax.tree.leaves(_host_state(tr)):
        assert np.isfinite(leaf).all()


def test_nonfinite_halt_raises_before_applying(tmp_path, mesh4,
                                               small_window):
    tr = _trainer(tmp_path, mesh4,
                  ft=FTConfig(nonfinite="halt",
                              chaos=ChaosPlan.parse(["nonfinite_grad:2"])))
    with pytest.raises(NonFiniteError, match="policy=halt"):
        tr.train_model(0)


def test_nonfinite_restore_rolls_back_and_continues(tmp_path, mesh4,
                                                    small_window):
    plan = ChaosPlan.parse(["nonfinite_grad:2"])
    lines = []
    tr = _trainer(tmp_path, mesh4,
                  ft=FTConfig(nonfinite="restore", chaos=plan),
                  log=lines.append)
    tr.train_model(0)
    assert tr.nonfinite_restored == 1
    assert any("rolled back" in ln for ln in lines)
    for leaf in jax.tree.leaves(_host_state(tr)):
        assert np.isfinite(leaf).all()


# -- integration: preemption-safe mid-epoch resume ----------------------------

def test_chaos_preempt_without_checkpoint_dir_raises(tmp_path, mesh4,
                                                     small_window):
    tr = _trainer(tmp_path, mesh4,
                  ft=FTConfig(chaos=ChaosPlan.parse(["preempt:0"])))
    with pytest.raises(RuntimeError, match="chaos preempt requires"):
        tr.train_model(0)                          # no guard installed


def test_chaos_preempt_mid_epoch_resume_is_bitwise(tmp_path, mesh4,
                                                   small_window):
    """The tentpole pin: SIGTERM at a step boundary -> emergency mid-epoch
    checkpoint -> a fresh process-equivalent Trainer resumes from that
    exact step -> the finished epoch is bitwise identical to one that was
    never interrupted."""
    ck = str(tmp_path / "ck")
    lines = []

    def small_eval(tr):
        tr.test_split = cifar10.Split(tr.test_split.images[:64],
                                      tr.test_split.labels[:64])
        return tr

    # Interrupted run: injected SIGTERM once progress reaches step 5 —
    # the boundary poll sees it at trained=6 (WINDOW=3 grid).
    tr1 = small_eval(_trainer(
        tmp_path, mesh4, log=lines.append,
        ft=FTConfig(chaos=ChaosPlan.parse(["preempt:5"]))))
    tr1.run(1, checkpoint_dir=ck)
    assert tr1.preempted is True
    assert any("emergency checkpoint saved" in ln for ln in lines)

    peek = CheckpointManager(ck)
    assert peek.latest_mid_epoch() == (0, 6)
    assert peek.latest_epoch() is None
    peek.close()

    # Resume (no chaos): picks up at epoch 0 step 6, finishes the epoch.
    tr2 = small_eval(_trainer(tmp_path, mesh4, log=lines.append))
    tr2.run(1, checkpoint_dir=ck)
    assert tr2.preempted is False
    assert any("Resumed from mid-epoch checkpoint: epoch 0, step 6" in ln
               for ln in lines)

    # Uninterrupted reference with the same program configuration.
    tr0 = small_eval(_trainer(tmp_path, mesh4))
    tr0.run(1)
    _assert_bitwise(_host_state(tr2), _host_state(tr0))

    # The completed epoch checkpoint outranks — and clears — the mid-epoch
    # emergency save (a later run must not rewind into the epoch).
    peek = CheckpointManager(ck)
    assert peek.latest_epoch() == 0
    assert peek.latest_mid_epoch() is None
    peek.close()


def test_preempt_resume_carries_compressed_residuals_bitwise(
        tmp_path, mesh4, small_window):
    """Round-7 pin: the error-feedback residual stack (opt_state.comm) is
    part of the checkpointed TrainState — a preemption while residuals
    are NONZERO resumes bitwise, including the rest of the epoch whose
    arithmetic depends on the carried residuals."""
    ck = str(tmp_path / "ck_comp")
    lines = []

    def small_eval(tr):
        tr.test_split = cifar10.Split(tr.test_split.images[:64],
                                      tr.test_split.labels[:64])
        return tr

    # Preempt EARLY (boundary poll at trained=3 on the WINDOW=3 grid): on
    # this synthetic task the net later collapses to zero grads and the
    # bf16 residuals decay to EXACT zero, which would make the
    # nonzero-residual assertion below vacuous.
    tr1 = small_eval(_trainer(
        tmp_path, mesh4, strategy="compress-bf16", log=lines.append,
        ft=FTConfig(chaos=ChaosPlan.parse(["preempt:2"]))))
    tr1.run(1, checkpoint_dir=ck)
    assert tr1.preempted is True
    comm = jax.device_get(tr1.state.opt_state.comm)
    assert any(np.any(np.asarray(l)) for l in jax.tree.leaves(comm)), \
        "preempted too late: every EF residual already decayed to zero"

    # Resume (no chaos) and finish; compare against never-interrupted.
    tr2 = small_eval(_trainer(tmp_path, mesh4, strategy="compress-bf16",
                              log=lines.append))
    tr2.run(1, checkpoint_dir=ck)
    assert any("Resumed from mid-epoch checkpoint" in ln for ln in lines)
    tr0 = small_eval(_trainer(tmp_path, mesh4, strategy="compress-bf16"))
    tr0.run(1)
    # _assert_bitwise spans the WHOLE TrainState, comm residuals included.
    _assert_bitwise(_host_state(tr2), _host_state(tr0))
    assert jax.tree.leaves(tr2.state.opt_state.comm)[0].shape[0] == 4


CHILD_SCRIPT = """\
import os
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
import sys
repo, tests_dir, ck, data = sys.argv[1:5]
sys.path.insert(0, repo)
sys.path.insert(0, tests_dir)
import jax
jax.config.update("jax_platforms", "cpu")
from cs744_ddp_tpu.utils.compcache import enable_persistent_compilation_cache
enable_persistent_compilation_cache(repo)
import cs744_ddp_tpu.train.loop as looplib
looplib.WINDOW = 3
from cs744_ddp_tpu.data import cifar10
from cs744_ddp_tpu.parallel import make_mesh
from tinynet import tiny_cnn
tr = looplib.Trainer(model=tiny_cnn(), strategy="allreduce",
                     mesh=make_mesh(4), global_batch=64, data_dir=data,
                     augment=True, host_augment=True, limit_train_batches=45,
                     log=lambda s: print(s, flush=True))
tr.test_split = cifar10.Split(tr.test_split.images[:64],
                              tr.test_split.labels[:64])
tr.run(1, checkpoint_dir=ck)
print("CHILD_PREEMPTED" if tr.preempted else "CHILD_COMPLETED", flush=True)
"""


def test_sigterm_subprocess_emergency_checkpoint_and_resume(
        tmp_path, mesh4, small_window):
    """End-to-end preemption exactly as a pod scheduler delivers it: a REAL
    SIGTERM to a separate training process mid-epoch.  The child finishes
    its in-flight step, writes the emergency checkpoint and exits cleanly;
    resuming from its checkpoint dir completes the epoch bitwise identical
    to a never-interrupted run."""
    ck = str(tmp_path / "ck")
    script = tmp_path / "child.py"
    script.write_text(CHILD_SCRIPT)
    proc = subprocess.Popen(
        [sys.executable, str(script), REPO, os.path.dirname(__file__),
         ck, str(tmp_path)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    reaper = threading.Timer(420, proc.kill)       # hang backstop only
    reaper.start()
    signaled = False
    lines = []
    try:
        for line in proc.stdout:
            lines.append(line)
            if not signaled and "Training loss after 20 iterations" in line:
                proc.send_signal(signal.SIGTERM)   # mid-epoch, mid-training
                signaled = True
        proc.wait(timeout=120)
    finally:
        reaper.cancel()
    out = "".join(lines)
    assert signaled, f"child never reached iteration 20:\n{out}"
    assert proc.returncode == 0, out               # clean exit, not a kill
    assert "emergency checkpoint saved" in out
    assert "CHILD_PREEMPTED" in out

    peek = CheckpointManager(ck)
    mid = peek.latest_mid_epoch()
    peek.close()
    assert mid is not None and mid[0] == 0 and 20 < mid[1] <= 45

    def small_eval(tr):
        tr.test_split = cifar10.Split(tr.test_split.images[:64],
                                      tr.test_split.labels[:64])
        return tr

    lines2 = []
    tr2 = small_eval(_trainer(tmp_path, mesh4, limit=45, log=lines2.append))
    tr2.run(1, checkpoint_dir=ck)
    assert any("Resumed from mid-epoch checkpoint" in ln for ln in lines2)

    tr0 = small_eval(_trainer(tmp_path, mesh4, limit=45))
    tr0.run(1)
    _assert_bitwise(_host_state(tr2), _host_state(tr0))


# -- integration: rank-level chaos + the elastic degradation ladder -----------
#
# New round-6 sites: rank_death / slow_rank target a RANK (the spec's third
# field), coordinator_loss targets the coordinator's recovery progress.
# Every recovery that promises to preserve the stream stays BITWISE.

def test_chaos_rank_sites_target_ranks_one_shot():
    assert RANK_SITES == ("rank_death", "slow_rank")
    assert "coordinator_loss" in SITES
    plan = ChaosPlan.parse(["rank_death:3:1", "slow_rank:5:2",
                            "coordinator_loss:0"])
    # The third field is the target rank, carried in the seed slot.
    assert plan.seed_of("rank_death", 3) == 1
    assert plan.seed_of("slow_rank", 5) == 2
    assert plan.fire_reached("rank_death", 4)      # >= planned step
    assert not plan.fire_reached("rank_death", 9)  # one-shot
    assert plan.fire_reached("coordinator_loss", 0)
    err = RankDeathError(1, 0, 3)
    assert (err.rank, err.epoch, err.step) == (1, 0, 3)


def _small_eval(tr):
    tr.test_split = cifar10.Split(tr.test_split.images[:64],
                                  tr.test_split.labels[:64])
    return tr


def test_rank_death_emergency_checkpoint_same_world_resume_bitwise(
        tmp_path, mesh4, small_window):
    """Rank death mid-epoch -> emergency mid-epoch checkpoint (with the
    round-6 topology metadata) -> a same-world resume finishes the epoch
    bitwise identical to an undisturbed run (the coordinator's retry rung
    is exactly this plain resume)."""
    clean = _clean_state(tmp_path, mesh4)
    ck = str(tmp_path / "ck_rd")
    plan = ChaosPlan.parse(["rank_death:3:1"])
    lines = []
    tr = _small_eval(_trainer(tmp_path, mesh4, ft=FTConfig(chaos=plan),
                              log=lines.append))
    tr.run(1, checkpoint_dir=ck)
    assert tr.rank_death == (1, 0, 3)
    assert ("rank_death", 3) in plan.fired
    assert any("Rank 1 died at epoch 0 step 3" in ln for ln in lines)

    from cs744_ddp_tpu.elastic import flat_meta
    from cs744_ddp_tpu.train.checkpoint import read_mid_epoch_meta
    meta = flat_meta(read_mid_epoch_meta(ck))
    assert meta["world"] == 4 and meta["step"] == 3
    assert len(meta["rank_keys"]) == 4

    lines2 = []
    tr2 = _small_eval(_trainer(tmp_path, mesh4, log=lines2.append))
    tr2.run(1, checkpoint_dir=ck)
    assert any("Resumed from mid-epoch checkpoint: epoch 0, step 3" in ln
               for ln in lines2)
    assert tr2.rank_death is None
    _assert_bitwise(_host_state(tr2), clean)


def _elastic_trainer(tmp_path, world, *, ft=None, log=None, limit=6):
    return Trainer(model=tiny_cnn(), strategy="allreduce",
                   mesh=make_mesh(world), global_batch=64,
                   data_dir=str(tmp_path), seed=3, augment=True,
                   limit_train_batches=limit, limit_eval_batches=1,
                   log=log or (lambda s: None), ft=ft, elastic="strong")


def test_rank_death_ladder_shrinks_and_recovery_is_bitwise(tmp_path,
                                                           small_window):
    """ISSUE round 6 acceptance: a chaos-injected mid-epoch rank death at
    world 2 drives the coordinator down the ladder (emergency checkpoint ->
    shrink -> resume at world 1), and the recovered run's final state is
    BITWISE equal to a fault-free run at the target world — the strong-
    scaling world-invariance pin cashed in as a recovery guarantee."""
    tr0 = _elastic_trainer(tmp_path, 1)            # fault-free world-1 ref
    tr0.run(1)

    plan = ChaosPlan.parse(["rank_death:3:1"])
    lines = []
    coord = ElasticCoordinator(
        lambda w: _elastic_trainer(tmp_path, w, ft=FTConfig(chaos=plan),
                                   log=lines.append),
        world=2, global_batch=64, microshards=4, chaos=plan,
        log=lines.append)
    tr = coord.run(1, str(tmp_path / "ck_ladder"))

    assert [e["kind"] for e in coord.events] == ["shrink"]
    assert any("shrinking world 2 -> 1" in ln for ln in lines)
    rep = coord.report()
    assert rep["world"] == 1 and rep["degraded"] is True
    assert rep["generation"] == 1 and len(rep["members"]) == 1
    plan_r = tr.resume_plan
    assert (plan_r.old_world, plan_r.new_world) == (2, 1)
    assert plan_r.start_step == 3                  # strong: step carries
    assert plan_r.examples_replayed == 0
    _assert_bitwise(_host_state(tr), _host_state(tr0))


def test_coordinator_loss_rederives_membership_from_disk_bitwise(
        tmp_path, small_window):
    """The coordinator_loss site drops the in-memory membership mid-
    recovery; the coordinator must re-derive it from checkpoint metadata
    alone and still land the same bitwise-pinned shrink."""
    tr0 = _elastic_trainer(tmp_path, 1)
    tr0.run(1)

    plan = ChaosPlan.parse(["rank_death:3:1", "coordinator_loss:0"])
    lines = []
    coord = ElasticCoordinator(
        lambda w: _elastic_trainer(tmp_path, w, ft=FTConfig(chaos=plan),
                                   log=lines.append),
        world=2, global_batch=64, microshards=4, chaos=plan,
        log=lines.append)
    tr = coord.run(1, str(tmp_path / "ck_closs"))

    assert any("re-deriving from checkpoint metadata" in ln for ln in lines)
    assert ("coordinator_loss", 0) in plan.fired
    assert [e["kind"] for e in coord.events] == ["shrink"]
    assert coord.report()["world"] == 1
    _assert_bitwise(_host_state(tr), _host_state(tr0))


def test_slow_rank_flags_straggler_and_stream_unchanged(tmp_path, mesh4,
                                                        small_window):
    """slow_rank injects a real stall attributed to one rank's step-time
    gauge: the detector must flag exactly that rank, and the training
    stream must be untouched (detection-only, bitwise pin)."""
    clean = _clean_state(tmp_path, mesh4)
    plan = ChaosPlan.parse(["slow_rank:3:2"])
    lines = []
    tr = _trainer(tmp_path, mesh4,
                  ft=FTConfig(chaos=plan, slow_rank_stall_s=2.0),
                  log=lines.append)
    tr.train_model(0)
    assert ("slow_rank", 3) in plan.fired
    assert any("rank 2 straggling" in ln for ln in lines)
    assert tr._straggler.flag_counts.get(2, 0) >= 1
    assert tr.rank_death is None
    _assert_bitwise(_host_state(tr), clean)


# -- publish/hot-swap chaos sites (round 10) ----------------------------------


def test_chaos_publish_and_swap_sites_one_shot_seeded():
    assert PUBLISH_SITES == ("publish_torn", "publish_stale")
    assert "swap_mid_batch" in SITES
    assert all(s in SITES for s in PUBLISH_SITES)
    plan = ChaosPlan.parse(["publish_torn:1:7", "publish_stale:2",
                            "swap_mid_batch:4:1"])
    # The third field targets a replica for swap_mid_batch — carried in
    # the seed slot, same convention as the rank/replica sites.
    assert plan.seed_of("swap_mid_batch", 4) == 1
    assert not plan.fire("publish_torn", 0)
    assert plan.fire("publish_torn", 1)
    assert not plan.fire("publish_torn", 1)            # one-shot
    assert plan.fire("publish_stale", 2)
    assert plan.fired == [("publish_torn", 1), ("publish_stale", 2)]
    # Torn-byte offsets are deterministic in (seed, site, step).
    a = ChaosPlan.parse(["publish_torn:1:7"]).rng("publish_torn", 1)
    b = ChaosPlan.parse(["publish_torn:1:7"]).rng("publish_torn", 1)
    np.testing.assert_array_equal(a.integers(0, 2**31, size=8),
                                  b.integers(0, 2**31, size=8))


def _publish_stack(tmp_path, chaos):
    """Minimal publish->serve loop: one publisher, one CPU replica, one
    watcher (probes attached) — the recovery-pin fixture for the three
    round-10 chaos sites."""
    from cs744_ddp_tpu import models as model_zoo
    from cs744_ddp_tpu.publish import WeightPublisher, WeightWatcher
    from cs744_ddp_tpu.serve import EngineReplica
    model_zoo.register_model("tiny", tiny_cnn)
    pub = WeightPublisher(str(tmp_path / "pub"), chaos=chaos,
                          fingerprint={"model": "tiny"})
    replica = EngineReplica(0, model="tiny", buckets=(2,), seed=0,
                            chaos=chaos)
    replica.startup()
    watcher = WeightWatcher(pub.directory, [replica])
    return pub, replica, watcher


def _tiny_state(seed):
    from cs744_ddp_tpu.train.step import init_train_state
    init_fn, _ = tiny_cnn()
    return init_train_state(init_fn, jax.random.PRNGKey(seed))


def test_publish_torn_rejected_by_crc_old_version_serves(tmp_path):
    """publish_torn recovery pin: the torn bundle (seeded payload bytes
    flipped after the atomic rename) is rejected at crc-verify time and
    the previously installed version keeps serving bitwise-unchanged."""
    plan = ChaosPlan.parse(["publish_torn:1"])
    pub, replica, watcher = _publish_stack(tmp_path, plan)
    assert pub.publish(_tiny_state(1))["torn"] is False
    assert watcher.poll_once() == "installed"
    imgs = cifar10._synthetic_split(8, seed=5).images[:2]
    before, _, _ = replica.engine.infer_counts(imgs)
    rec = pub.publish(_tiny_state(2))
    assert rec["torn"] is True and ("publish_torn", 1) in plan.fired
    assert watcher.poll_once() == "rejected"
    rep = watcher.report()
    assert rep["rejected"] == 1 and rep["installed_version"] == 1
    assert replica.engine.weights_version == 1
    after, _, _ = replica.engine.infer_counts(imgs)
    np.testing.assert_array_equal(np.asarray(after), np.asarray(before))


def test_publish_stale_skipped_current_version_keeps_serving(tmp_path):
    """publish_stale recovery pin: a duplicate publisher re-announcing an
    already-installed version is skipped — never re-installed, never an
    error, the current version keeps serving."""
    plan = ChaosPlan.parse(["publish_stale:1"])
    pub, replica, watcher = _publish_stack(tmp_path, plan)
    assert pub.publish(_tiny_state(1))["version"] == 1
    assert watcher.poll_once() == "installed"
    rec = pub.publish(_tiny_state(2))
    assert rec["stale"] is True and rec["version"] == 1
    assert rec["file"].endswith(".dup.ccwb")
    assert ("publish_stale", 1) in plan.fired
    assert watcher.poll_once() == "stale"
    rep = watcher.report()
    assert rep["stale"] == 1 and rep["installed_version"] == 1
    assert replica.engine.weights_version == 1


def test_swap_mid_batch_probe_never_mixes_weights(tmp_path):
    """swap_mid_batch recovery pin: chaos fires the watcher's poll from
    INSIDE dispatch 1's hook on the scheduler worker thread; the racing
    dispatch is answered ENTIRELY by the old weights (the flip lands at
    the next loop boundary) and the next dispatch by the new — a batch
    never sees mixed weights, and every reply's model_version tag says
    which model computed it."""
    plan = ChaosPlan.parse(["swap_mid_batch:1:0"])
    pub, replica, watcher = _publish_stack(tmp_path, plan)
    pub.publish(_tiny_state(1))
    assert watcher.poll_once() == "installed"
    imgs = cifar10._synthetic_split(8, seed=5).images[:2]
    replica.start()
    try:
        r0 = replica.scheduler.submit(imgs, slo_ms=None).result(30.0)
        pub.publish(_tiny_state(2))   # v2 on disk; only the probe polls
        r1 = replica.scheduler.submit(imgs, slo_ms=None).result(30.0)
        r2 = replica.scheduler.submit(imgs, slo_ms=None).result(30.0)
    finally:
        replica.stop()
    assert ("swap_mid_batch", 1) in plan.fired
    assert (r0.model_version, r1.model_version, r2.model_version) == (1, 1, 2)
    np.testing.assert_array_equal(r1.logits, r0.logits)   # old model, whole batch
    assert not np.array_equal(r2.logits, r1.logits)       # new model after flip


# -- round 14: completion-side chaos (dispatch_fault) -------------------------


def test_chaos_dispatch_fault_site_registered_one_shot():
    from cs744_ddp_tpu.ft.chaos import REPLICA_SITES
    assert "dispatch_fault" in SITES
    assert "dispatch_fault" in REPLICA_SITES
    plan = ChaosPlan.parse(["dispatch_fault:1:0"])
    assert plan.seed_of("dispatch_fault", 1) == 0   # third field = replica
    assert plan.fire("dispatch_fault", 1)
    assert not plan.fire("dispatch_fault", 1)       # one-shot
    assert plan.fired == [("dispatch_fault", 1)]


def test_dispatch_fault_isolated_bitwise_recovery_pipelined_vs_serial():
    """dispatch_fault recovery pin: the chaos site discards dispatch 1's
    device result at its completion fence (with the pipelined worker,
    while dispatch 2 is already in flight).  Both workers isolate the
    fault — dispatch 1's request gets an explicit error reply, every
    neighbour resolves ok on the SAME weights, the worker survives —
    and the non-faulted replies are bitwise-identical between the
    pipelined and serial paths."""
    from cs744_ddp_tpu import models as model_zoo
    from cs744_ddp_tpu.serve import EngineReplica
    model_zoo.register_model("tiny", tiny_cnn)
    pool = cifar10._synthetic_split(16, seed=5)

    def _serve(pipeline):
        plan = ChaosPlan.parse(["dispatch_fault:1:0"])
        rep = EngineReplica(0, model="tiny", buckets=(2, 4), seed=0,
                            chaos=plan, pipeline=pipeline)
        # Full-max-bucket requests submitted before the worker starts:
        # each dispatch carries exactly one request, so the faulted
        # dispatch number maps deterministically to one reply.
        futs = [rep.scheduler.submit(pool.images[4 * i:4 * i + 4],
                                     slo_ms=None)
                for i in range(4)]
        rep.start()
        try:
            replies = [f.result(30.0) for f in futs]
        finally:
            rep.stop()
        return plan, replies

    plan_p, piped = _serve(True)
    plan_s, serial = _serve(False)
    for plan, replies in ((plan_p, piped), (plan_s, serial)):
        assert [r.status for r in replies] == ["ok", "error", "ok", "ok"]
        assert plan.fired == [("dispatch_fault", 1)]    # fired exactly once
        assert "ChaosError" in replies[1].reason
        assert replies[1].logits is None
        # Old weights keep serving around the fault: one version tag.
        assert {r.model_version for r in replies} == {0}
    for a, b in zip(serial, piped):
        if a.status == "ok":
            np.testing.assert_array_equal(a.logits, b.logits)
