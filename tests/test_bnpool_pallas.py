"""Numerics pin for the Pallas fused BN->ReLU->MaxPool backward.

The kernel is a recorded NEGATIVE perf result (see the module docstring:
0.75x/0.91x vs the XLA composition on v5e) kept as working evidence and
scaffolding; this test keeps it CORRECT so the evidence stays live.  The
CPU CI runs the kernels in Pallas interpret mode — same math, no TPU.

The oracle is plain jax autodiff through the SAME forward math
(``_fwd_impl``'s double-rounded y), which makes the expected equality
exact in f32: routing, gating and reductions all coincide.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from jax.experimental.pallas import tpu as pltpu

from cs744_ddp_tpu.ops import bnpool_pallas as bp

# The interpret-mode context manager these tests run the kernels under is
# not present on every jax in the support window (absent on this
# container's build); without it there is no way to execute a TPU Pallas
# kernel on the CPU CI, so the numerics pin only runs where it exists.
pytestmark = pytest.mark.skipif(
    not hasattr(pltpu, "force_tpu_interpret_mode"),
    reason="jax.experimental.pallas.tpu lacks force_tpu_interpret_mode "
           "on this toolchain")


def _ref_chain(x, gamma, beta):
    """Autodiff oracle mirroring _fwd_impl bit for bit."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, (0, 1, 2))
    if x.dtype == jnp.bfloat16:
        var = jnp.maximum(
            jnp.mean(jnp.square(xf), (0, 1, 2)) - jnp.square(mean), 0.0)
    else:
        var = jnp.mean(jnp.square(xf - mean), (0, 1, 2))
    inv = lax.rsqrt(var + bp.BN_EPS)
    xhat = (xf - mean) * inv
    xhat_act = xhat.astype(x.dtype).astype(jnp.float32)
    z = (xhat_act * gamma + beta).astype(x.dtype)
    y = jnp.maximum(z, jnp.zeros((), x.dtype))
    return lax.reduce_window(y, -jnp.inf, lax.max, (1, 2, 2, 1),
                             (1, 2, 2, 1), "VALID")


@pytest.mark.parametrize("shape", [(16, 32, 32, 64), (8, 16, 16, 128),
                                   (4, 8, 8, 64)])
def test_fused_backward_matches_autodiff_f32(shape):
    N, H, W, C = shape
    k1, k2, k3, k4 = jax.random.split(jax.random.PRNGKey(0), 4)
    x = jax.random.normal(k1, shape) * 2 + 0.3
    # Inject exact ties (quantized values) so first-match routing is hit.
    x = jnp.where(jax.random.bernoulli(k4, 0.3, shape),
                  jnp.round(x * 2) / 2, x)
    gamma = jax.random.normal(k2, (C,)) * 0.5 + 1.0
    beta = jax.random.normal(k3, (C,)) * 0.2
    w = jax.random.normal(jax.random.PRNGKey(9), (N, H // 2, W // 2, C))

    def loss_fused(x, g, b):
        p, _, _ = bp.bn_relu_pool(x, g, b)
        return jnp.sum(p * w)

    def loss_ref(x, g, b):
        return jnp.sum(_ref_chain(x, g, b) * w)

    with pltpu.force_tpu_interpret_mode():
        got = jax.grad(loss_fused, argnums=(0, 1, 2))(x, gamma, beta)
    want = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(x, gamma, beta)
    for g, r, name in zip(got, want, ("dx", "dgamma", "dbeta")):
        # f32-reduction-order differences only (chunked-sequential sums
        # in the kernel vs the oracle's pairwise reductions).
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=5e-4, atol=1e-4, err_msg=name)

    # Forward parity is bitwise (same math, same rounding).
    with pltpu.force_tpu_interpret_mode():
        pf, mean_f, var_f = jax.jit(bp.bn_relu_pool)(x, gamma, beta)
    np.testing.assert_array_equal(np.asarray(pf),
                                  np.asarray(jax.jit(_ref_chain)(
                                      x, gamma, beta)))


def test_fused_backward_bf16_routing_flips_are_rare_and_tie_shaped():
    """bf16 dx may differ from the autodiff oracle ONLY at routing flips
    between window elements within a couple of bf16 ulps (excess-
    precision/double-rounding ties — module docstring); the flip fraction
    must stay tiny and every flip site must be a genuine near-tie."""
    shape = (16, 32, 32, 64)
    N, H, W, C = shape
    k1, k2, k3, k4 = jax.random.split(jax.random.PRNGKey(1), 4)
    x = (jax.random.normal(k1, shape) * 2 + 0.3)
    x = jnp.where(jax.random.bernoulli(k4, 0.3, shape),
                  jnp.round(x * 2) / 2, x).astype(jnp.bfloat16)
    gamma = jax.random.normal(k2, (C,)) * 0.5 + 1.0
    beta = jax.random.normal(k3, (C,)) * 0.2
    w = jax.random.normal(jax.random.PRNGKey(9), (N, H // 2, W // 2, C))

    def loss_fused(x, g, b):
        p, _, _ = bp.bn_relu_pool(x, g, b)
        return jnp.sum(p.astype(jnp.float32) * w)

    def loss_ref(x, g, b):
        return jnp.sum(_ref_chain(x, g, b).astype(jnp.float32) * w)

    with pltpu.force_tpu_interpret_mode():
        dx = jax.grad(loss_fused)(x, gamma, beta)
    dref = jax.jit(jax.grad(loss_ref))(x, gamma, beta)
    d = np.abs(np.asarray(dx, np.float32) - np.asarray(dref, np.float32))
    flip_sites = np.argwhere(d > 0.05)
    # Tiny fraction of elements...
    assert len(flip_sites) <= 2e-4 * d.size, len(flip_sites)
    # ...and every site sits in a window whose top-2 values are within a
    # couple of bf16 ulps (i.e. it IS a tie flip, not a routing bug).
    xf = np.asarray(x, np.float32)
    mean = xf.mean((0, 1, 2))
    var = np.maximum((xf ** 2).mean((0, 1, 2)) - mean ** 2, 0.0)
    inv = 1.0 / np.sqrt(var + bp.BN_EPS)
    xhat_act = np.asarray(jnp.asarray((xf - mean) * inv
                                      ).astype(jnp.bfloat16), np.float32)
    z = np.asarray(jnp.asarray(xhat_act * np.asarray(gamma)
                               + np.asarray(beta)).astype(jnp.bfloat16),
                   np.float32)
    y = np.maximum(z, 0.0)
    for (n, h, wq, c) in flip_sites[:64]:
        win = y[n, (h // 2) * 2:(h // 2) * 2 + 2,
                (wq // 2) * 2:(wq // 2) * 2 + 2, c].reshape(-1)
        top2 = np.sort(win)[-2:]
        rel = abs(top2[1] - top2[0]) / (abs(top2[1]) + 1e-9)
        assert rel < 2e-2, (tuple(int(v) for v in (n, h, wq, c)),
                            win.tolist())
