"""Model zoo tests: shapes, parameter counts, full-model torch parity.

Parameter count oracle: the reference VGG-11 variant (10 classes, 512->10
head) has 9,231,114 parameters (SURVEY.md §4 cites ~9.2M).
"""

import numpy as np
import pytest
import torch
import torch.nn as nn

import jax
import jax.numpy as jnp

from cs744_ddp_tpu.models import get_model, resnet, vgg


def n_params(tree):
    return sum(int(np.prod(l.shape)) for l in jax.tree.leaves(tree))


def torch_vgg11():
    """The reference's _VGG('VGG11') rebuilt verbatim-semantics in torch
    (reference /root/reference/src/Part 1/model.py:11-46)."""
    cfg = [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"]
    layers_, in_ch = [], 3
    for c in cfg:
        if c == "M":
            layers_.append(nn.MaxPool2d(2, 2))
        else:
            layers_ += [nn.Conv2d(in_ch, c, 3, 1, 1, bias=True),
                        nn.BatchNorm2d(c), nn.ReLU(inplace=True)]
            in_ch = c
    features = nn.Sequential(*layers_)

    class VGG(nn.Module):
        def __init__(self):
            super().__init__()
            self.layers = features
            self.fc1 = nn.Linear(512, 10)

        def forward(self, x):
            y = self.layers(x)
            return self.fc1(y.view(y.size(0), -1))

    return VGG()


def test_vgg11_param_count_matches_torch():
    params, state = vgg.init(jax.random.PRNGKey(0), "VGG11")
    tmodel = torch_vgg11()
    torch_count = sum(p.numel() for p in tmodel.parameters())
    assert n_params(params) == torch_count == 9_231_114
    # BN running stats count, too (state tree).
    torch_buffers = sum(b.numel() for n, b in tmodel.named_buffers()
                        if "running" in n)
    assert n_params(state) == torch_buffers


@pytest.mark.parametrize("name,expected_convs",
                         [("VGG11", 8), ("VGG13", 10), ("VGG16", 13),
                          ("VGG19", 16)])
def test_vgg_family_structure(name, expected_convs):
    params, state = vgg.init(jax.random.PRNGKey(0), name)
    assert len(params["conv"]) == expected_convs
    assert len(state["bn"]) == expected_convs
    logits, new_state = vgg.apply(params, state,
                                  jnp.zeros((2, 32, 32, 3)), train=True,
                                  name=name)
    assert logits.shape == (2, 10)


def test_vgg11_forward_matches_torch_with_transplanted_weights():
    """Transplant torch weights into our pytree; logits must agree."""
    torch.manual_seed(0)
    tmodel = torch_vgg11().eval()
    params, state = vgg.init(jax.random.PRNGKey(0), "VGG11")

    convs = [m for m in tmodel.layers if isinstance(m, nn.Conv2d)]
    bns = [m for m in tmodel.layers if isinstance(m, nn.BatchNorm2d)]
    params["conv"] = [
        {"w": jnp.asarray(c.weight.detach().numpy().transpose(2, 3, 1, 0)),
         "b": jnp.asarray(c.bias.detach().numpy())} for c in convs]
    params["bn"] = [
        {"gamma": jnp.asarray(b.weight.detach().numpy()),
         "beta": jnp.asarray(b.bias.detach().numpy())} for b in bns]
    state["bn"] = [
        {"mean": jnp.asarray(b.running_mean.numpy()),
         "var": jnp.asarray(b.running_var.numpy())} for b in bns]
    params["fc1"] = {"w": jnp.asarray(tmodel.fc1.weight.detach().numpy().T),
                     "b": jnp.asarray(tmodel.fc1.bias.detach().numpy())}

    x = np.random.default_rng(0).normal(
        scale=1.0, size=(4, 32, 32, 3)).astype(np.float32)
    ours, _ = vgg.apply(params, state, jnp.asarray(x), train=False)
    theirs = tmodel(torch.from_numpy(x.transpose(0, 3, 1, 2))).detach().numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-4)


def torch_resnet_cifar(counts=(2, 2, 2, 2)):
    """The standard CIFAR BasicBlock ResNet (3x3 stem, no maxpool, 10-class
    head) rebuilt in torch, mirroring models/resnet.py's architecture spec;
    ``counts`` are blocks per stage ((2,2,2,2)=18, (3,4,6,3)=34)."""

    class Block(nn.Module):
        def __init__(self, cin, cout, stride):
            super().__init__()
            self.conv1 = nn.Conv2d(cin, cout, 3, stride, 1, bias=False)
            self.bn1 = nn.BatchNorm2d(cout)
            self.conv2 = nn.Conv2d(cout, cout, 3, 1, 1, bias=False)
            self.bn2 = nn.BatchNorm2d(cout)
            self.down = None
            if stride != 1 or cin != cout:
                self.down = nn.Sequential(
                    nn.Conv2d(cin, cout, 1, stride, 0, bias=False),
                    nn.BatchNorm2d(cout))

        def forward(self, x):
            y = torch.relu(self.bn1(self.conv1(x)))
            y = self.bn2(self.conv2(y))
            sc = self.down(x) if self.down is not None else x
            return torch.relu(y + sc)

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.stem_conv = nn.Conv2d(3, 64, 3, 1, 1, bias=False)
            self.stem_bn = nn.BatchNorm2d(64)
            blocks, cin = [], 64
            for (width, stage_stride), nblocks in zip(
                    ((64, 1), (128, 2), (256, 2), (512, 2)), counts):
                for b in range(nblocks):
                    blocks.append(Block(cin, width,
                                        stage_stride if b == 0 else 1))
                    cin = width
            self.blocks = nn.ModuleList(blocks)
            self.fc = nn.Linear(512, 10)

        def forward(self, x):
            y = torch.relu(self.stem_bn(self.stem_conv(x)))
            for blk in self.blocks:
                y = blk(y)
            y = y.mean(dim=(2, 3))
            return self.fc(y)

    return Net()


def _conv_w(c):
    return jnp.asarray(c.weight.detach().numpy().transpose(2, 3, 1, 0))


def _bn_p(b):
    return ({"gamma": jnp.asarray(b.weight.detach().numpy()),
             "beta": jnp.asarray(b.bias.detach().numpy())},
            {"mean": jnp.asarray(b.running_mean.numpy()),
             "var": jnp.asarray(b.running_var.numpy())})


@pytest.mark.parametrize("name,counts", [("ResNet18", (2, 2, 2, 2)),
                                         ("ResNet34", (3, 4, 6, 3))])
def test_resnet_forward_matches_torch_with_transplanted_weights(name, counts):
    """Transplant a torch CIFAR-ResNet's weights into our pytree; logits
    must agree — the full-model forward parity VGG already has
    (residual adds, strided downsampling, global average pool included)."""
    torch.manual_seed(0)
    tmodel = torch_resnet_cifar(counts).eval()
    params, state = resnet.init(jax.random.PRNGKey(0), name)

    params["stem_conv"] = {"w": _conv_w(tmodel.stem_conv)}
    params["stem_bn"], state["stem_bn"] = _bn_p(tmodel.stem_bn)
    for i, blk in enumerate(tmodel.blocks):
        bp, bs = params["blocks"][i], state["blocks"][i]
        bp["conv1"] = {"w": _conv_w(blk.conv1)}
        bp["bn1"], bs["bn1"] = _bn_p(blk.bn1)
        bp["conv2"] = {"w": _conv_w(blk.conv2)}
        bp["bn2"], bs["bn2"] = _bn_p(blk.bn2)
        if blk.down is not None:
            bp["down_conv"] = {"w": _conv_w(blk.down[0])}
            bp["down_bn"], bs["down_bn"] = _bn_p(blk.down[1])
        else:
            assert "down_conv" not in bp  # architecture agreement
    params["fc"] = {"w": jnp.asarray(tmodel.fc.weight.detach().numpy().T),
                    "b": jnp.asarray(tmodel.fc.bias.detach().numpy())}

    x = np.random.default_rng(1).normal(size=(4, 32, 32, 3)).astype(np.float32)
    ours, _ = resnet.apply(params, state, jnp.asarray(x), train=False,
                           name=name)
    theirs = tmodel(torch.from_numpy(x.transpose(0, 3, 1, 2))).detach().numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-4)

    # Same count, leaf for leaf (transplant covered every parameter).
    torch_count = sum(p.numel() for p in tmodel.parameters())
    assert n_params(params) == torch_count


def test_resnet18_shapes_and_count():
    params, state = resnet.init(jax.random.PRNGKey(0))
    # CIFAR ResNet-18 (3x3 stem, 10-class head): 11,173,962 params.
    assert n_params(params) == 11_173_962
    logits, ns = resnet.apply(params, state, jnp.zeros((2, 32, 32, 3)),
                              train=True)
    assert logits.shape == (2, 10)


def test_get_model_registry():
    for name in ("vgg11", "vgg16", "resnet18", "resnet34"):
        init_fn, apply_fn = get_model(name)
        params, state = init_fn(jax.random.PRNGKey(1))
        logits, _ = apply_fn(params, state, jnp.zeros((1, 32, 32, 3)),
                             train=False)
        assert logits.shape == (1, 10)
    with pytest.raises(ValueError):
        get_model("alexnet")
