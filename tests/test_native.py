"""Native C++ loader (native/fastloader.cpp) vs NumPy/JAX references."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cs744_ddp_tpu.data import augment as jaug
from cs744_ddp_tpu.data import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native lib unavailable (no g++?)")


def test_gather_matches_numpy():
    rng = np.random.default_rng(0)
    ds = rng.integers(0, 256, (100, 32, 32, 3)).astype(np.uint8)
    idx = rng.integers(0, 100, 37)
    np.testing.assert_array_equal(native.gather(ds, idx), ds[idx])


def test_normalize_matches_device_path():
    rng = np.random.default_rng(1)
    imgs = rng.integers(0, 256, (5, 32, 32, 3)).astype(np.uint8)
    ours = native.normalize(imgs)
    ref = np.asarray(jaug.normalize(jnp.asarray(imgs)))
    np.testing.assert_allclose(ours, ref, atol=1e-6)


def test_augment_matches_python_reference():
    """C++ crop/flip/normalize == the pure-NumPy fallback, elementwise."""
    rng = np.random.default_rng(2)
    imgs = rng.integers(0, 256, (16, 32, 32, 3)).astype(np.uint8)
    offsets = rng.integers(0, 9, (16, 2)).astype(np.int32)
    flips = rng.integers(0, 2, 16).astype(np.uint8)

    got = native.augment(imgs, offsets, flips)

    padded = np.pad(imgs, ((0, 0), (4, 4), (4, 4), (0, 0)))
    from cs744_ddp_tpu.data.cifar10 import MEAN, STD
    for i in range(16):
        oy, ox = offsets[i]
        crop = padded[i, oy:oy + 32, ox:ox + 32]
        if flips[i]:
            crop = crop[:, ::-1]
        expected = (crop.astype(np.float32) / 255.0 - MEAN) / STD
        np.testing.assert_allclose(got[i], expected, atol=1e-5,
                                   err_msg=f"image {i}")


def test_zero_offset_center_no_flip_is_identity_crop():
    imgs = np.arange(32 * 32 * 3, dtype=np.uint8).reshape(1, 32, 32, 3)
    offsets = np.full((1, 2), 4, np.int32)  # offset 4 == no shift
    flips = np.zeros(1, np.uint8)
    got = native.augment(imgs, offsets, flips)
    ref = np.asarray(jaug.normalize(jnp.asarray(imgs)))
    np.testing.assert_allclose(got, ref, atol=1e-6)
