"""Native C++ loader (native/fastloader.cpp) vs NumPy/JAX references."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cs744_ddp_tpu.data import augment as jaug
from cs744_ddp_tpu.data import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native lib unavailable (no g++?)")


def test_gather_matches_numpy():
    rng = np.random.default_rng(0)
    ds = rng.integers(0, 256, (100, 32, 32, 3)).astype(np.uint8)
    idx = rng.integers(0, 100, 37)
    np.testing.assert_array_equal(native.gather(ds, idx), ds[idx])


def test_normalize_matches_device_path():
    rng = np.random.default_rng(1)
    imgs = rng.integers(0, 256, (5, 32, 32, 3)).astype(np.uint8)
    ours = native.normalize(imgs)
    ref = np.asarray(jaug.normalize(jnp.asarray(imgs)))
    np.testing.assert_allclose(ours, ref, atol=1e-6)


def test_augment_matches_python_reference():
    """C++ crop/flip/normalize == the pure-NumPy fallback, elementwise."""
    rng = np.random.default_rng(2)
    imgs = rng.integers(0, 256, (16, 32, 32, 3)).astype(np.uint8)
    offsets = rng.integers(0, 9, (16, 2)).astype(np.int32)
    flips = rng.integers(0, 2, 16).astype(np.uint8)

    got = native.augment(imgs, offsets, flips)

    padded = np.pad(imgs, ((0, 0), (4, 4), (4, 4), (0, 0)))
    from cs744_ddp_tpu.data.cifar10 import MEAN, STD
    for i in range(16):
        oy, ox = offsets[i]
        crop = padded[i, oy:oy + 32, ox:ox + 32]
        if flips[i]:
            crop = crop[:, ::-1]
        expected = (crop.astype(np.float32) / 255.0 - MEAN) / STD
        np.testing.assert_allclose(got[i], expected, atol=1e-5,
                                   err_msg=f"image {i}")


def test_zero_offset_center_no_flip_is_identity_crop():
    imgs = np.arange(32 * 32 * 3, dtype=np.uint8).reshape(1, 32, 32, 3)
    offsets = np.full((1, 2), 4, np.int32)  # offset 4 == no shift
    flips = np.zeros(1, np.uint8)
    got = native.augment(imgs, offsets, flips)
    ref = np.asarray(jaug.normalize(jnp.asarray(imgs)))
    np.testing.assert_allclose(got, ref, atol=1e-6)


def _rand_aug_inputs(seed, n_dataset=100, n=37):
    rng = np.random.default_rng(seed)
    ds = rng.integers(0, 256, (n_dataset, 32, 32, 3)).astype(np.uint8)
    idx = rng.integers(0, n_dataset, n).astype(np.int64)
    offsets = rng.integers(0, 9, (n, 2)).astype(np.int32)
    flips = rng.integers(0, 2, n).astype(np.uint8)
    return ds, idx, offsets, flips


def test_gather_augment_u8_fuses_gather_then_augment():
    """The v3 fused kernel == gather followed by augment_u8, elementwise
    (the chunked staging path's bit-identity rests on this)."""
    ds, idx, offsets, flips = _rand_aug_inputs(3)
    fused = native.gather_augment_u8(ds, idx, offsets, flips)
    staged = native.augment_u8(ds[idx], offsets, flips)
    np.testing.assert_array_equal(fused, staged)


def test_out_params_write_in_place_without_copy():
    """gather / augment_u8 / gather_augment_u8 must fill the caller's
    buffer (an arena row) and return the SAME object."""
    ds, idx, offsets, flips = _rand_aug_inputs(4)
    n = len(idx)
    for fn, expect in (
            (lambda o: native.gather(ds, idx, out=o), ds[idx]),
            (lambda o: native.augment_u8(ds[idx], offsets, flips, out=o),
             native.augment_u8(ds[idx], offsets, flips)),
            (lambda o: native.gather_augment_u8(ds, idx, offsets, flips,
                                                out=o),
             native.augment_u8(ds[idx], offsets, flips))):
        out = np.full((n, 32, 32, 3), 0xAB, np.uint8)
        ret = fn(out)
        assert ret is out
        np.testing.assert_array_equal(out, expect)


def test_out_param_validation_rejects_bad_buffers():
    ds, idx, offsets, flips = _rand_aug_inputs(5)
    n = len(idx)
    with pytest.raises(ValueError, match="uint8"):
        native.gather(ds, idx, out=np.empty((n, 32, 32, 3), np.float32))
    with pytest.raises(ValueError, match="uint8"):
        native.gather_augment_u8(ds, idx, offsets, flips,
                                 out=np.empty((n + 1, 32, 32, 3), np.uint8))
    strided = np.empty((n, 32, 32, 6), np.uint8)[..., ::2]
    with pytest.raises(ValueError, match="contiguous"):
        native.augment_u8(ds[idx], offsets, flips, out=strided)


def test_fallback_paths_match_native(monkeypatch):
    """With the C++ library simulated absent, the NumPy fallbacks of the
    v3 surface (gather/augment_u8/gather_augment_u8, out= included) must
    produce the same bytes the native kernels do."""
    ds, idx, offsets, flips = _rand_aug_inputs(6)
    want_fused = native.gather_augment_u8(ds, idx, offsets, flips)
    want_gather = native.gather(ds, idx)

    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_load_attempted", True)
    assert native.load_library() is None
    np.testing.assert_array_equal(
        native.gather_augment_u8(ds, idx, offsets, flips), want_fused)
    np.testing.assert_array_equal(native.gather(ds, idx), want_gather)
    out = np.empty((len(idx), 32, 32, 3), np.uint8)
    assert native.gather(ds, idx, out=out) is out
    np.testing.assert_array_equal(out, want_gather)
    out2 = np.empty((len(idx), 32, 32, 3), np.uint8)
    assert native.gather_augment_u8(ds, idx, offsets, flips, out=out2) is out2
    np.testing.assert_array_equal(out2, want_fused)


class _FakeHandle:
    def __init__(self, log, tag):
        self._log, self._tag = log, tag

    def block_until_ready(self):
        self._log.append(self._tag)


def test_staging_arena_round_robin_and_transfer_fence():
    arena = native.StagingArena(3, chunk_batches=2, batch=4)
    assert arena.nslots == 3
    assert arena.chunk_batches == 2
    log = []
    slots = []
    for tag in range(3):
        slot, buf = arena.acquire()
        slots.append(slot)
        assert buf is arena.buffer(slot)
        assert buf.shape == (2, 4, 32, 32, 3) and buf.dtype == np.uint8
        arena.retire(slot, _FakeHandle(log, tag))
    assert slots == [0, 1, 2]
    assert log == []          # nothing fenced yet: all slots were fresh
    # Second cycle: each acquire must wait on that slot's pending transfer
    # exactly once, in round-robin order.
    for tag in range(3):
        slot, _ = arena.acquire()
        assert slot == tag
    assert log == [0, 1, 2]
    # Fences are one-shot: re-acquiring without a retire does not re-wait.
    for _ in range(3):
        arena.acquire()
    assert log == [0, 1, 2]


def test_staging_arena_needs_two_slots():
    with pytest.raises(ValueError, match="2 slots"):
        native.StagingArena(1, chunk_batches=1, batch=4)


def test_staging_arena_rows_are_64_byte_aligned():
    """Aliasing by jax's CPU client is decided per buffer by 64-byte
    alignment; heap-recycled np.empty blocks come back at MIXED alignments
    mid-suite (measured: slots [no,no,no,YES,YES,no] in one arena), which
    made a single-slot probe unsound.  Rows are force-aligned so all slots
    behave identically."""
    for cap in (1, 2, 5):
        arena = native.StagingArena(3, chunk_batches=cap, batch=4)
        for s in range(arena.nslots):
            buf = arena.buffer(s)
            assert buf.ctypes.data % 64 == 0
            assert buf.flags["C_CONTIGUOUS"]
            assert buf.shape == (cap, 4, 32, 32, 3)
