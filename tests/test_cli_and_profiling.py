"""CLI end-to-end + split-phase profiling mode (VERDICT r1 item 7).

The reference's two reporting/launch surfaces that round 1 left untested:

  * the fwd/bwd phase split (``/root/reference/src/Part 1/main.py:28-57``):
    forward and backward+sync+step timed separately, averaged per
    20-iteration window, first window excluded;
  * the argparse CLI (``Part 2a/main.py:156-175``) driving a full
    train+eval run.
"""

import numpy as np

import jax

from cs744_ddp_tpu import cli
from cs744_ddp_tpu.data import cifar10
from cs744_ddp_tpu.train.loop import Trainer

from tinynet import tiny_cnn


def test_profile_phases_reports_fwd_bwd_split(tmp_path, mesh4):
    """profile_phases mode must print Forward/Backward Pass lines from the
    second window on (warmup window excluded), and run the same number of
    iterations as the windowed path would."""
    lines = []
    tr = Trainer(model=tiny_cnn(), strategy="allreduce", mesh=mesh4,
                 global_batch=64, data_dir=str(tmp_path), augment=False,
                 profile_phases=True, log=lines.append)
    tr.train_split = cifar10.Split(tr.train_split.images[:64 * 45],
                                   tr.train_split.labels[:64 * 45])
    timers = tr.train_model(0)
    text = "\n".join(lines)
    assert "Training loss after 20 iterations is" in text
    assert "Training loss after 40 iterations is" in text
    # Warmup window skipped from the TIMING report (loss still printed).
    assert "Forward Pass time in iter 20 is" not in text
    assert "Average Pass time in iter 20 is" not in text
    # Second window reports all three phase lines.
    assert "Forward Pass time in iter 40 is" in text
    assert "Backward Pass time in iter 40 is" in text
    assert "Average Pass time in iter 40 is" in text
    # Steady-state samples exist and the phases are sane in the mean.
    # NOTE the bound is a CEILING, not a subset check: on this tiny model
    # both timers are dispatch-dominated (fwd-only and full-step cost about
    # the same per call, and individual pairs invert under scheduler
    # noise), so mean(fwd) < mean(step) does NOT hold reliably here.  What
    # this protects is grosser breakage: the two programs being swapped or
    # the fwd timer degenerating (e.g. timing multiple steps).
    assert len(timers.steady_step_times) == 45 - 20
    assert len(timers.steady_forward_times) == 45 - 20
    assert (np.mean(timers.steady_forward_times)
            <= 1.1 * np.mean(timers.steady_step_times))


def test_phase_split_windowed_orders_fwd_below_bwd(tmp_path, mesh4):
    """The window-amortized phase split (VERDICT r3 item 4) must show the
    reference's structure POSITIVELY — forward strictly cheaper than
    backward+sync+step — because dispatch cost is amortized over the
    window (the per-step mode above can only assert a ceiling: its timers
    are dispatch-dominated by construction).  Backward of conv+BN+fc is
    ~2x forward, so the margin is generous."""
    tr = Trainer(model=tiny_cnn(), strategy="allreduce", mesh=mesh4,
                 global_batch=64, data_dir=str(tmp_path), augment=True,
                 log=lambda s: None)
    state_before = jax.tree.map(lambda a: np.asarray(a).copy(),
                                tr.state.params)
    # Two trials with across-trial min aggregation — the SAME statistic
    # tools/perf_phase_split.py reports; a lone within-trial slope can
    # invert under full-suite host load (measure_phase_split docstring),
    # so asserting on it would flake.
    best = {}
    for _ in range(2):
        split = tr.measure_phase_split(window_iters=10, windows=3)
        assert set(split["window_totals_ms"]) == \
            {"fwd_10", "fwd_5", "step_10", "step_5"}
        assert all(v > 0 for v in split["window_totals_ms"].values())
        for k, v in split["window_totals_ms"].items():
            best[k] = min(best.get(k, float("inf")), v)
    fwd = (best["fwd_10"] - best["fwd_5"]) / 5
    step = (best["step_10"] - best["step_5"]) / 5
    assert fwd > 0, best
    assert step - fwd > fwd, best          # backward strictly > forward
    # Measurement must not perturb the training trajectory.
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), b), tr.state.params, state_before)


def test_phase_split_rejects_host_augment(tmp_path, mesh4):
    """measure_phase_split times the compiled windowed path; on a
    host_augment trainer it would silently measure a pipeline that
    trainer never trains with, so it must refuse (same contract as
    steady_state_throughput)."""
    import pytest

    tr = Trainer(model=tiny_cnn(), strategy="allreduce", mesh=mesh4,
                 global_batch=64, data_dir=str(tmp_path), augment=True,
                 host_augment=True, log=lambda s: None)
    with pytest.raises(ValueError, match="host_augment"):
        tr.measure_phase_split(window_iters=4)


def test_host_augment_trains_deterministically(tmp_path, mesh4):
    """--host-augment (VERDICT r2 weak #7): the C++ host pipeline feeds
    preprocessed f32 batches through the per-batch path; training works,
    converges on the synthetic split, and is run-to-run deterministic."""
    def run():
        tr = Trainer(model=tiny_cnn(), strategy="allreduce", mesh=mesh4,
                     global_batch=64, data_dir=str(tmp_path), augment=True,
                     host_augment=True, limit_train_batches=25,
                     log=lambda s: None)
        timers = tr.train_model(0)
        return timers.losses, tr.state

    losses_a, state_a = run()
    losses_b, state_b = run()
    assert len(losses_a) == 25
    # Convergence oracle (synthetic data is class-templated).
    assert np.mean(losses_a[-5:]) < np.mean(losses_a[:5])
    # Host RNG stream is counter-based in (seed, epoch, it): bitwise rerun.
    assert losses_a == losses_b
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), state_a.params, state_b.params)


def test_host_augment_prefetch_matches_serial_stream(tmp_path, mesh4):
    """The double-buffered pipeline (VERDICT r3 item 6) must yield a stream
    BIT-IDENTICAL to serial per-batch preparation — the counter-based host
    RNG makes prefetch order-insensitive — including the ragged tail."""
    from cs744_ddp_tpu.train.loop import _shard_batches

    tr = Trainer(model=tiny_cnn(), strategy="allreduce", mesh=mesh4,
                 global_batch=64, data_dir=str(tmp_path), augment=True,
                 host_augment=True, log=lambda s: None)
    # 200 examples / world 4 -> 3 full global batches + ragged tail of 8.
    tr.train_split = cifar10.Split(tr.train_split.images[:200],
                                   tr.train_split.labels[:200])
    serial = []
    for it, (imgs, labs) in enumerate(_shard_batches(
            tr.train_split, tr.world, tr.global_batch, 0, shuffle=True)):
        serial.append((it, *tr._put_host_augmented(imgs, labs, 0, it)))
    prefetched = list(tr._iter_host_batches(0))
    assert [p[0] for p in prefetched] == [s[0] for s in serial] == [0, 1, 2, 3]
    for (_, xs, ys), (_, xp, yp) in zip(serial, prefetched):
        np.testing.assert_array_equal(np.asarray(xs), np.asarray(xp))
        np.testing.assert_array_equal(np.asarray(ys), np.asarray(yp))


def test_host_augment_prefetch_respects_limit(tmp_path, mesh4):
    """The producer thread must STOP at limit_train_batches (not merely
    filter), and an abandoned consumer must not wedge the producer."""
    tr = Trainer(model=tiny_cnn(), strategy="allreduce", mesh=mesh4,
                 global_batch=64, data_dir=str(tmp_path), augment=True,
                 host_augment=True, limit_train_batches=2,
                 log=lambda s: None)
    assert [p[0] for p in tr._iter_host_batches(0)] == [0, 1]
    # Early abandonment: closing the generator mid-stream joins the thread.
    gen = tr._iter_host_batches(0)
    next(gen)
    gen.close()   # must not hang


def test_host_augment_trains_the_ragged_tail(tmp_path, mesh4):
    """host_augment's per-batch path must train the short final batch too
    (f32 tail shapes flow through _warm_per_step_tail_shapes and the host
    pipeline): 200 examples / world 4 / batch 64 -> per-rank 50 = 3*16 + 2,
    i.e. 3 full batches plus a ragged global tail of 8."""
    tr = Trainer(model=tiny_cnn(), strategy="allreduce", mesh=mesh4,
                 global_batch=64, data_dir=str(tmp_path), augment=True,
                 host_augment=True, log=lambda s: None)
    tr.train_split = cifar10.Split(tr.train_split.images[:200],
                                   tr.train_split.labels[:200])
    timers = tr.train_model(0)
    assert timers.iter_number - 1 == 4  # ceil(50 / 16)
    assert all(np.isfinite(l) for l in timers.losses)


def test_profile_phases_honors_reshuffle_and_limit(tmp_path, mesh4):
    """The per-step path must forward reshuffle_each_epoch (ADVICE r1) and
    respect limit_train_batches."""
    seen = []
    tr = Trainer(model=tiny_cnn(), strategy="allreduce", mesh=mesh4,
                 global_batch=64, data_dir=str(tmp_path), augment=False,
                 profile_phases=True, reshuffle_each_epoch=True,
                 limit_train_batches=3, log=seen.append)
    t0 = tr.train_model(0)
    t1 = tr.train_model(1)
    assert t0.iter_number - 1 == 3  # limit respected
    # Reshuffled epochs see different batches -> different loss sequences.
    # (Losses also differ because params moved; the REAL reshuffle check is
    # sharding-level, tests/test_data.py — this pins the flag reaches the
    # sampler without error.)
    assert t1.iter_number - 1 == 3


def test_cli_end_to_end_smoke(tmp_path, capsys, mesh4):
    """Drive main([...]) with the reference's knobs end to end on a tiny
    bounded run: the full print schedule must appear on stdout."""
    cli.main(["--strategy", "ddp", "--model", "vgg11",
              "--batch-size", "64", "--num-devices", "4",
              "--epochs", "1", "--data-dir", str(tmp_path),
              "--limit-train-batches", "3", "--limit-eval-batches", "2",
              "--no-augment"])
    out = capsys.readouterr().out
    assert "Size of training set is 782" in out
    assert "Size of test set is" in out
    assert "Training time after 1 epoch is" in out
    assert "Test set: Average loss:" in out
    # Accuracy denominator reflects the eval cap (2 batches x 64).
    assert "/128 (" in out


def test_cli_rejects_unknown_strategy(tmp_path):
    import pytest
    with pytest.raises(SystemExit):
        cli.main(["--strategy", "zero_redundancy"])


def test_cli_require_real_data_refuses_synthetic_fallback(tmp_path):
    """--require-real-data must fail loudly BEFORE any training when the
    data dir holds no CIFAR-10 pickle batches — never silently train on
    the synthetic stand-in (VERDICT r5 item 7)."""
    import pytest
    with pytest.raises(SystemExit, match="require-real-data") as ei:
        cli.main(["--require-real-data", "--data-dir", str(tmp_path),
                  "--epochs", "1"])
    assert "cifar-10-batches-py" in str(ei.value)


def test_profile_dir_writes_xplane_trace(tmp_path, mesh4):
    """--profile-dir must capture a jax.profiler trace of the first epoch."""
    import glob
    import os

    tr = Trainer(model=tiny_cnn(), strategy="allreduce", mesh=mesh4,
                 global_batch=64, data_dir=str(tmp_path), augment=False,
                 limit_train_batches=2, limit_eval_batches=1,
                 log=lambda s: None)
    tr.run(1, profile_dir=str(tmp_path / "trace"))
    found = glob.glob(str(tmp_path / "trace" / "**" / "*.xplane.pb"),
                      recursive=True)
    assert found, os.listdir(tmp_path / "trace")


def test_host_augment_windowed_matches_per_step_path(tmp_path, mesh4):
    """The chunked windowed host-augment path (VERDICT r4 item 5; chunked
    staging round 6) must consume a stream BIT-IDENTICAL to the per-step
    path's (counter-based host RNG, absolute iteration indices) and produce
    the same TrainState to scan-vs-unrolled fp tolerance — including the
    ragged tail."""
    from cs744_ddp_tpu.train.loop import _shard_batches

    def make():
        tr = Trainer(model=tiny_cnn(), strategy="allreduce", mesh=mesh4,
                     global_batch=64, data_dir=str(tmp_path), augment=True,
                     host_augment=True, log=lambda s: None)
        # 200 examples / world 4 -> 3 full batches + ragged tail of 8.
        tr.train_split = cifar10.Split(tr.train_split.images[:200],
                                       tr.train_split.labels[:200])
        return tr

    # Stream bit-identity: staged uint8 chunk buffers carry the SAME
    # crop/flip stream as the per-step f32 path (same counter-based RNG,
    # absolute indices) — pinned both as u8-vs-u8 equality and as
    # normalize(u8) ~ f32 equivalence — plus the tail.  3 full batches fit
    # one chunk (capacity ceil(20/4)=5), closed by the window boundary.
    from cs744_ddp_tpu.data import cifar10 as c10
    tr = make()
    serial_u8, serial_f32, serial_y = [], [], []
    for it, (imgs, labs) in enumerate(_shard_batches(
            tr.train_split, tr.world, tr.global_batch, 0, shuffle=True)):
        serial_u8.append(tr._host_transform_u8(imgs, len(labs), 0, it))
        serial_f32.append(tr._host_transform(imgs, len(labs), 0, it))
        serial_y.append(labs)
    emitted = list(tr._iter_host_window_chunks(0))
    kinds = [k for k, _ in emitted]
    assert kinds == ["chunk", "tail"]  # 3 full batches in one chunk + tail
    k, xw, yw, last = emitted[0][1]
    assert k == 3 and last is True
    xw = np.asarray(xw)
    assert xw.dtype == np.uint8
    np.testing.assert_array_equal(xw, np.stack(serial_u8[:3]))
    np.testing.assert_array_equal(np.asarray(yw),
                                  np.stack(serial_y[:3]).astype(np.int32))
    # The two formats are the same transform: device-normalize of the u8
    # crop == the C++ f32 product (fp association differs, nothing else).
    np.testing.assert_allclose(
        (xw[0].astype(np.float32) / 255.0 - c10.MEAN) / c10.STD,
        serial_f32[0], rtol=0, atol=1e-5)
    _, xt, yt = emitted[1][1]
    np.testing.assert_array_equal(np.asarray(xt), serial_f32[3])

    # State equivalence: windowed train_model vs the per-step path.
    tr_win, tr_step = make(), make()
    tr_win.train_model(0)
    tr_step._train_model_per_step(0)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=1e-4),
        tr_win.state.params, tr_step.state.params)


def test_host_augment_chunked_stream_and_k1_degenerate(tmp_path, mesh4,
                                                       monkeypatch):
    """Multi-chunk staging: with WINDOW=3 and host_chunks=2 (chunk capacity
    2) a 7-full-batch epoch must emit chunks 2,1 | 2,1 | 1 with ``last``
    flags closing each window, the concatenated chunk stream must equal the
    serial u8 stream (checked AFTER exhausting the producer, so every arena
    slot has been reused/retired before any buffer is read — the aliasing
    regression this arrangement exists to force), and training must match
    the K=1 degenerate path (round 5's whole-window staging) bit-for-bit
    in its loss stream."""
    import cs744_ddp_tpu.train.loop as looplib
    from cs744_ddp_tpu.train.loop import _shard_batches

    monkeypatch.setattr(looplib, "WINDOW", 3)

    def make(chunks):
        tr = Trainer(model=tiny_cnn(), strategy="allreduce", mesh=mesh4,
                     global_batch=64, data_dir=str(tmp_path), augment=True,
                     host_augment=True, host_chunks=chunks,
                     log=lambda s: None)
        # 456 examples / world 4 -> 7 full batches + ragged tail of 8.
        tr.train_split = cifar10.Split(tr.train_split.images[:456],
                                       tr.train_split.labels[:456])
        return tr

    tr = make(2)
    assert tr._chunk_cap() == 2
    assert tr._chunk_plan(3) == [2, 1] and tr._chunk_plan(1) == [1]
    serial_u8, serial_y = [], []
    for it, (imgs, labs) in enumerate(_shard_batches(
            tr.train_split, tr.world, tr.global_batch, 0, shuffle=True)):
        if imgs.shape[0] == tr.global_batch:
            serial_u8.append(tr._host_transform_u8(imgs, len(labs), 0, it))
            serial_y.append(labs)
    emitted = list(tr._iter_host_window_chunks(0))   # producer fully drained
    assert [k for k, _ in emitted] == ["chunk"] * 5 + ["tail"]
    sizes = [p[0] for k, p in emitted if k == "chunk"]
    lasts = [p[3] for k, p in emitted if k == "chunk"]
    assert sizes == [2, 1, 2, 1, 1]
    assert lasts == [False, True, False, True, True]
    got_x = np.concatenate([np.asarray(p[1]) for k, p in emitted
                            if k == "chunk"])
    got_y = np.concatenate([np.asarray(p[2]) for k, p in emitted
                            if k == "chunk"])
    np.testing.assert_array_equal(got_x, np.stack(serial_u8))
    np.testing.assert_array_equal(got_y,
                                  np.stack(serial_y).astype(np.int32))

    # K=2 vs the K=1 degenerate case: identical loss stream and params.
    tr_k2, tr_k1 = make(2), make(1)
    t2 = tr_k2.train_model(0)
    t1 = tr_k1.train_model(0)
    assert t2.losses == t1.losses
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)),
        tr_k2.state.params, tr_k1.state.params)


def test_host_augment_chunked_arena_reuse_keeps_stream_intact(tmp_path,
                                                              mesh4,
                                                              monkeypatch):
    """Force HEAVY arena slot reuse (WINDOW=2, host_chunks=2 -> 1-batch
    chunks, 6 slots, 9 full batches -> every slot rewritten) and pin that
    a full training epoch still matches the K=1 whole-window path
    bit-for-bit.  This is the regression lock for the backend-aliasing
    hazard: jax's CPU client can alias committed numpy buffers into device
    arrays (native.StagingArena docstring), so a slot rewritten before its
    chunk was consumed would corrupt the stream — the Trainer's aliasing
    probe + private-copy fallback is what this test proves out."""
    import cs744_ddp_tpu.train.loop as looplib

    monkeypatch.setattr(looplib, "WINDOW", 2)

    def make(chunks):
        tr = Trainer(model=tiny_cnn(), strategy="allreduce", mesh=mesh4,
                     global_batch=64, data_dir=str(tmp_path), augment=True,
                     host_augment=True, host_chunks=chunks,
                     log=lambda s: None)
        # 576 = 9 full global batches exactly (no tail).
        tr.train_split = cifar10.Split(tr.train_split.images[:576],
                                       tr.train_split.labels[:576])
        return tr

    tr_c = make(2)
    t_c = tr_c.train_model(0)
    arena = tr_c._staging_arena
    assert arena is not None and arena.nslots == 6  # 9 chunks > 6 slots
    t_1 = make(1).train_model(0)
    assert t_c.losses == t_1.losses


def test_host_augment_windowed_respects_limit_and_close(tmp_path, mesh4):
    """The chunked producer must STOP at limit_train_batches (emitting a
    window-closing chunk of exactly that many batches) and an abandoned
    consumer must not wedge a producer that is BLOCKED on a full queue."""
    msgs = []
    tr = Trainer(model=tiny_cnn(), strategy="allreduce", mesh=mesh4,
                 global_batch=64, data_dir=str(tmp_path), augment=True,
                 host_augment=True, limit_train_batches=2,
                 log=msgs.append)
    emitted = list(tr._iter_host_window_chunks(0))
    assert [k for k, _ in emitted] == ["chunk"]
    k, _, _, last = emitted[0][1]
    assert k == 2 and last is True  # exactly limit batches, window closed
    assert tr._host_window_shapes() == {2}

    # Early abandonment with the producer genuinely mid-stream: no limit,
    # so the full 781-batch epoch keeps the producer blocked in safe_put
    # on the bounded chunk queue when close() fires — the stop-event path,
    # not a join of an already-dead thread.
    tr.limit_train_batches = None
    gen = tr._iter_host_window_chunks(0)
    next(gen)
    gen.close()   # must not hang
    assert not any("did not exit" in m for m in msgs), msgs
