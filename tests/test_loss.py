"""Cross-entropy parity vs torch.nn.CrossEntropyLoss (reference criterion,
/root/reference/src/Part 1/main.py:110)."""

import numpy as np
import torch

import jax.numpy as jnp

from cs744_ddp_tpu.ops.loss import accuracy_counts, cross_entropy


def test_cross_entropy_matches_torch():
    rng = np.random.default_rng(0)
    logits = rng.normal(size=(16, 10)).astype(np.float32) * 3
    labels = rng.integers(0, 10, size=16).astype(np.int64)
    ours = float(cross_entropy(jnp.asarray(logits),
                               jnp.asarray(labels.astype(np.int32))))
    theirs = float(torch.nn.CrossEntropyLoss()(
        torch.from_numpy(logits), torch.from_numpy(labels)))
    assert abs(ours - theirs) < 1e-5


def test_accuracy_counts():
    logits = jnp.asarray([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]])
    labels = jnp.asarray([1, 0, 0])
    assert int(accuracy_counts(logits, labels)) == 2
