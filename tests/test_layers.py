"""Layer-level parity tests against PyTorch (CPU).

The reference model is torch.nn modules (/root/reference/src/Part 1/model.py);
these tests pin our functional layers to the same math: conv/linear forward
agreement under weight transplant, BatchNorm train/eval semantics including
running-stat updates, and torch-default init distributions.
"""

import numpy as np
import pytest
import torch
import torch.nn as nn

import jax
import jax.numpy as jnp

from cs744_ddp_tpu.models import layers


def test_conv2d_matches_torch():
    torch.manual_seed(0)
    tconv = nn.Conv2d(3, 8, 3, stride=1, padding=1, bias=True)
    x = np.random.default_rng(0).normal(size=(2, 5, 5, 3)).astype(np.float32)

    params = {
        # torch weight OIHW -> our HWIO
        "w": jnp.asarray(tconv.weight.detach().numpy().transpose(2, 3, 1, 0)),
        "b": jnp.asarray(tconv.bias.detach().numpy()),
    }
    ours = layers.conv2d_apply(params, jnp.asarray(x))
    theirs = tconv(torch.from_numpy(x.transpose(0, 3, 1, 2)))
    theirs = theirs.detach().numpy().transpose(0, 2, 3, 1)
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=1e-5)


def test_linear_matches_torch():
    torch.manual_seed(1)
    tl = nn.Linear(16, 10)
    x = np.random.default_rng(1).normal(size=(4, 16)).astype(np.float32)
    params = {"w": jnp.asarray(tl.weight.detach().numpy().T),
              "b": jnp.asarray(tl.bias.detach().numpy())}
    ours = layers.linear_apply(params, jnp.asarray(x))
    theirs = tl(torch.from_numpy(x)).detach().numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=1e-5)


def test_batchnorm_train_and_eval_match_torch():
    torch.manual_seed(2)
    tbn = nn.BatchNorm2d(4)
    x = np.random.default_rng(2).normal(size=(3, 6, 6, 4)).astype(np.float32)
    params = {"gamma": jnp.ones(4), "beta": jnp.zeros(4)}
    state = {"mean": jnp.zeros(4), "var": jnp.ones(4)}

    # Two training steps: outputs AND running-stat trajectories must agree.
    tx = torch.from_numpy(x.transpose(0, 3, 1, 2))
    for _ in range(2):
        ours, state = layers.batchnorm_apply(params, state, jnp.asarray(x),
                                             train=True)
        theirs = tbn(tx).detach().numpy().transpose(0, 2, 3, 1)
        np.testing.assert_allclose(np.asarray(ours), theirs, atol=1e-5)
    np.testing.assert_allclose(np.asarray(state["mean"]),
                               tbn.running_mean.numpy(), atol=1e-5)
    np.testing.assert_allclose(np.asarray(state["var"]),
                               tbn.running_var.numpy(), atol=1e-5)

    # Eval mode uses running stats.
    tbn.eval()
    ours_eval, _ = layers.batchnorm_apply(params, state, jnp.asarray(x),
                                          train=False)
    theirs_eval = tbn(tx).detach().numpy().transpose(0, 2, 3, 1)
    np.testing.assert_allclose(np.asarray(ours_eval), theirs_eval, atol=1e-5)


def test_maxpool_matches_torch():
    x = np.random.default_rng(3).normal(size=(2, 8, 8, 3)).astype(np.float32)
    ours = layers.maxpool2x2(jnp.asarray(x))
    theirs = nn.MaxPool2d(2, 2)(torch.from_numpy(x.transpose(0, 3, 1, 2)))
    np.testing.assert_allclose(np.asarray(ours),
                               theirs.numpy().transpose(0, 2, 3, 1), atol=1e-6)


def test_torch_default_init_bounds():
    """Conv/linear init must be U(-1/sqrt(fan_in), 1/sqrt(fan_in))."""
    key = jax.random.PRNGKey(0)
    p = layers.conv2d_init(key, 16, 32, 3)
    bound = 1.0 / np.sqrt(16 * 9)
    w = np.asarray(p["w"])
    assert w.min() >= -bound and w.max() <= bound
    # A uniform on [-b,b] has std b/sqrt(3); check within 5%.
    assert abs(w.std() - bound / np.sqrt(3)) < 0.05 * bound
    assert np.asarray(p["b"]).min() >= -bound

    p = layers.linear_init(key, 512, 10)
    bound = 1.0 / np.sqrt(512)
    w = np.asarray(p["w"])
    assert w.min() >= -bound and w.max() <= bound
