"""Layer-level parity tests against PyTorch (CPU).

The reference model is torch.nn modules (/root/reference/src/Part 1/model.py);
these tests pin our functional layers to the same math: conv/linear forward
agreement under weight transplant, BatchNorm train/eval semantics including
running-stat updates, and torch-default init distributions.
"""

import numpy as np
import pytest
import torch
import torch.nn as nn

import jax
import jax.numpy as jnp

from cs744_ddp_tpu.models import layers


def test_conv2d_matches_torch():
    torch.manual_seed(0)
    tconv = nn.Conv2d(3, 8, 3, stride=1, padding=1, bias=True)
    x = np.random.default_rng(0).normal(size=(2, 5, 5, 3)).astype(np.float32)

    params = {
        # torch weight OIHW -> our HWIO
        "w": jnp.asarray(tconv.weight.detach().numpy().transpose(2, 3, 1, 0)),
        "b": jnp.asarray(tconv.bias.detach().numpy()),
    }
    ours = layers.conv2d_apply(params, jnp.asarray(x))
    theirs = tconv(torch.from_numpy(x.transpose(0, 3, 1, 2)))
    theirs = theirs.detach().numpy().transpose(0, 2, 3, 1)
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=1e-5)


def test_linear_matches_torch():
    torch.manual_seed(1)
    tl = nn.Linear(16, 10)
    x = np.random.default_rng(1).normal(size=(4, 16)).astype(np.float32)
    params = {"w": jnp.asarray(tl.weight.detach().numpy().T),
              "b": jnp.asarray(tl.bias.detach().numpy())}
    ours = layers.linear_apply(params, jnp.asarray(x))
    theirs = tl(torch.from_numpy(x)).detach().numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=1e-5)


def test_batchnorm_train_and_eval_match_torch():
    torch.manual_seed(2)
    tbn = nn.BatchNorm2d(4)
    x = np.random.default_rng(2).normal(size=(3, 6, 6, 4)).astype(np.float32)
    params = {"gamma": jnp.ones(4), "beta": jnp.zeros(4)}
    state = {"mean": jnp.zeros(4), "var": jnp.ones(4)}

    # Two training steps: outputs AND running-stat trajectories must agree.
    tx = torch.from_numpy(x.transpose(0, 3, 1, 2))
    for _ in range(2):
        ours, state = layers.batchnorm_apply(params, state, jnp.asarray(x),
                                             train=True)
        theirs = tbn(tx).detach().numpy().transpose(0, 2, 3, 1)
        np.testing.assert_allclose(np.asarray(ours), theirs, atol=1e-5)
    np.testing.assert_allclose(np.asarray(state["mean"]),
                               tbn.running_mean.numpy(), atol=1e-5)
    np.testing.assert_allclose(np.asarray(state["var"]),
                               tbn.running_var.numpy(), atol=1e-5)

    # Eval mode uses running stats.
    tbn.eval()
    ours_eval, _ = layers.batchnorm_apply(params, state, jnp.asarray(x),
                                          train=False)
    theirs_eval = tbn(tx).detach().numpy().transpose(0, 2, 3, 1)
    np.testing.assert_allclose(np.asarray(ours_eval), theirs_eval, atol=1e-5)


def test_maxpool_matches_torch():
    x = np.random.default_rng(3).normal(size=(2, 8, 8, 3)).astype(np.float32)
    ours = layers.maxpool2x2(jnp.asarray(x))
    theirs = nn.MaxPool2d(2, 2)(torch.from_numpy(x.transpose(0, 3, 1, 2)))
    np.testing.assert_allclose(np.asarray(ours),
                               theirs.numpy().transpose(0, 2, 3, 1), atol=1e-6)


def test_maxpool_gradient_matches_torch_including_ties():
    """maxpool2x2's backward (XLA's native select-and-scatter — the
    deliberately-kept implementation, see the layers.py docstring for the
    measured negative results of replacing it) must route gradient to the
    FIRST maximal window element like torch — exercised with heavy ties
    (quantized values and all-equal windows, the post-ReLU all-zeros
    case)."""
    rng = np.random.default_rng(7)
    # Quantize to force frequent within-window ties; add all-zero windows.
    x = np.round(rng.normal(size=(3, 8, 8, 5)).astype(np.float32) * 2) / 2
    x[0, :2, :2, :] = 0.0
    dy = rng.normal(size=(3, 4, 4, 5)).astype(np.float32)

    def loss(a):
        return jnp.sum(layers.maxpool2x2(a) * jnp.asarray(dy))

    ours = np.asarray(jax.grad(loss)(jnp.asarray(x)))

    tx = torch.from_numpy(x.transpose(0, 3, 1, 2)).requires_grad_(True)
    ty = nn.MaxPool2d(2, 2)(tx)
    ty.backward(torch.from_numpy(dy.transpose(0, 3, 1, 2)))
    theirs = tx.grad.numpy().transpose(0, 2, 3, 1)
    np.testing.assert_array_equal(ours, theirs)


def test_torch_default_init_bounds():
    """Conv/linear init must be U(-1/sqrt(fan_in), 1/sqrt(fan_in))."""
    key = jax.random.PRNGKey(0)
    p = layers.conv2d_init(key, 16, 32, 3)
    bound = 1.0 / np.sqrt(16 * 9)
    w = np.asarray(p["w"])
    assert w.min() >= -bound and w.max() <= bound
    # A uniform on [-b,b] has std b/sqrt(3); check within 5%.
    assert abs(w.std() - bound / np.sqrt(3)) < 0.05 * bound
    assert np.asarray(p["b"]).min() >= -bound

    p = layers.linear_init(key, 512, 10)
    bound = 1.0 / np.sqrt(512)
    w = np.asarray(p["w"])
    assert w.min() >= -bound and w.max() <= bound


def test_batchnorm_fused_vjp_matches_autodiff():
    """The custom_vjp BN backward (closed-form fused gradient) must equal
    autodiff through a straightforward two-pass BN implementation, for all
    of dx, dgamma, dbeta — and the backward must also match torch's."""
    import torch

    key = jax.random.PRNGKey(11)
    x = jax.random.normal(key, (8, 5, 5, 6), jnp.float32) * 2.0 + 0.3
    gamma = jnp.linspace(0.5, 1.5, 6)
    beta = jnp.linspace(-0.2, 0.2, 6)
    dy = jax.random.normal(jax.random.fold_in(key, 1), x.shape)

    def fused(x, g, b):
        y, _, _ = layers._bn_train_norm(x, g, b)
        return jnp.vdot(y, dy)

    def ref(x, g, b):
        mean = jnp.mean(x, (0, 1, 2))
        var = jnp.mean(jnp.square(x - mean), (0, 1, 2))
        y = (x - mean) * jax.lax.rsqrt(var + layers.BN_EPS) * g + b
        return jnp.vdot(y, dy)

    g1 = jax.grad(fused, argnums=(0, 1, 2))(x, gamma, beta)
    g2 = jax.grad(ref, argnums=(0, 1, 2))(x, gamma, beta)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)

    # Torch cross-check of the same cotangent contraction.
    xt = torch.tensor(np.asarray(x).transpose(0, 3, 1, 2),
                      requires_grad=True)
    bn = torch.nn.BatchNorm2d(6, eps=layers.BN_EPS)
    with torch.no_grad():
        bn.weight.copy_(torch.tensor(np.asarray(gamma)))
        bn.bias.copy_(torch.tensor(np.asarray(beta)))
    out = bn(xt)
    out.backward(torch.tensor(np.asarray(dy).transpose(0, 3, 1, 2)))
    np.testing.assert_allclose(
        np.asarray(g1[0]), xt.grad.numpy().transpose(0, 2, 3, 1),
        rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(g1[1]), bn.weight.grad.numpy(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(g1[2]), bn.bias.grad.numpy(),
                               rtol=1e-4, atol=1e-5)


def test_layers_follow_activation_dtype():
    """bf16 activations must flow through conv/linear/pool in bf16 (master
    params stay f32), while BN statistics stay f32 internally."""
    key = jax.random.PRNGKey(0)
    p = layers.conv2d_init(key, 3, 8, 3)
    x = jnp.zeros((2, 8, 8, 3), jnp.bfloat16)
    y = layers.conv2d_apply(p, x)
    assert y.dtype == jnp.bfloat16
    assert p["w"].dtype == jnp.float32

    bp, bs = layers.batchnorm_init(8)
    yb, ns = layers.batchnorm_apply(bp, bs, y + 1.0, train=True)
    assert yb.dtype == jnp.bfloat16
    assert ns["mean"].dtype == jnp.float32 and ns["var"].dtype == jnp.float32

    lp = layers.linear_init(key, 8, 4)
    yl = layers.linear_apply(lp, yb.reshape(2, -1)[:, :8])
    assert yl.dtype == jnp.bfloat16

    assert layers.maxpool2x2(yb).dtype == jnp.bfloat16


def test_batchnorm_vjp_mean_var_cotangents_exact():
    """Differentiating THROUGH the mean/var outputs (e.g. a statistics
    regularizer) must produce the exact gradient, not silent zeros."""
    key = jax.random.PRNGKey(5)
    x = jax.random.normal(key, (4, 3, 3, 2)) * 1.5 + 0.2
    gamma = jnp.ones((2,))
    beta = jnp.zeros((2,))

    def fused(x):
        y, mean, var = layers._bn_train_norm(x, gamma, beta)
        return jnp.sum(y) + 3.0 * jnp.sum(mean) + 0.5 * jnp.sum(var)

    def ref(x):
        mean = jnp.mean(x, (0, 1, 2))
        var = jnp.mean(jnp.square(x - mean), (0, 1, 2))
        y = (x - mean) * jax.lax.rsqrt(var + layers.BN_EPS) * gamma + beta
        return jnp.sum(y) + 3.0 * jnp.sum(mean) + 0.5 * jnp.sum(var)

    np.testing.assert_allclose(np.asarray(jax.grad(fused)(x)),
                               np.asarray(jax.grad(ref)(x)),
                               rtol=1e-5, atol=1e-6)


def test_bf16_onepass_bn_stats_match_centered():
    """bf16 mode's ONE-PASS batch statistics (E[x^2]-mean^2, f32
    accumulation — layers._bn_train_fwd_impl) must stay within bf16-input
    rounding of the centered two-pass form for the magnitudes this
    workload produces (post-conv/post-BN activations, |mean|/std = O(1)).
    Guards the documented bf16 deviation (BASELINE.md) against drifting
    into the catastrophic-cancellation regime unnoticed."""
    rng = np.random.default_rng(0)
    # Representative magnitudes incl. a shifted-mean channel (mean ~ 8x
    # std) — still far from the |mean|/std >> 1 cancellation regime.
    base = rng.normal(size=(64, 8, 8, 16)).astype(np.float32)
    base[..., 3] = base[..., 3] * 0.5 + 4.0
    x16 = jnp.asarray(base, jnp.bfloat16)

    y16, _, m16, v16, _ = jax.jit(layers._bn_train_fwd_impl)(
        x16, jnp.ones((16,)), jnp.zeros((16,)))

    # Oracle: centered two-pass stats over the SAME bf16-rounded values.
    xf = np.asarray(x16, np.float64)
    mean = xf.mean((0, 1, 2))
    var = ((xf - mean) ** 2).mean((0, 1, 2))
    np.testing.assert_allclose(np.asarray(m16), mean, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(v16), var, rtol=1e-3, atol=1e-4)
    assert y16.dtype == jnp.bfloat16
    # f32 path keeps the centered formulation (its own f64 oracle over the
    # UNrounded input).
    y32, _, m32, v32, _ = jax.jit(layers._bn_train_fwd_impl)(
        jnp.asarray(base), jnp.ones((16,)), jnp.zeros((16,)))
    b64 = base.astype(np.float64)
    var32 = ((b64 - b64.mean((0, 1, 2))) ** 2).mean((0, 1, 2))
    np.testing.assert_allclose(np.asarray(v32), var32, rtol=1e-5)
