"""Static-analysis subsystem tests (cs744_ddp_tpu/analysis/).

Four layers, each pinned here:

* ``hlo_ir``   — the structural HLO parser: round-trips every committed
  fixture in tests/assets/hlo/ and agrees DIFFERENTIALLY with the legacy
  regex implementation (kept in utils/hlo_stats as the oracle) on both
  print forms, called computations, async pairs and metadata-poisoned
  modules.
* ``audit``    — the rule engine: every rule catches a deliberately
  seeded violation AND passes the real shipped-program zoo (tiny model,
  4-device CPU mesh) — the acceptance bar is a CLEAN audit of every
  program this repo dispatches, with the strategy depth ladder
  (ddp < allreduce < gather) certified on the lowered programs.
* ``pylint_rules`` / ``tools/lint_graft.py`` — the AST lint: each rule
  fires on a synthetic violation, waivers suppress, and the repo itself
  lints clean (tier-1 gate).
* thread-safety regressions the lint's ``lock-ownership`` rule found
  (MicroBatcher.start) and the Watchdog cancel-vs-fire race, locked in
  behaviorally.
* round-13 whole-program verification — the lock-order deadlock
  detector (``lockgraph``: repo graph certified acyclic on the declared
  partial order, ``*_locked`` caller-holds verified), wire-protocol
  schema conformance (``wire_schema`` against the ``serve/wire.py``
  table, including a deliberately mismatched encoder fixture and the
  corruption sweep), and the static host-round-trip certifier
  (``dispatch``: closed-form bounds matched EXACTLY against the live
  ``host_round_trips`` counter on all three dispatch paths), folded
  into one tier-1 gate (``test_repo_static_verification``).
"""

import glob
import json
import os
import threading
import time
import types

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cs744_ddp_tpu import models as model_zoo
from cs744_ddp_tpu.analysis import audit as auditlib
from cs744_ddp_tpu.analysis import dispatch as dispatchlib
from cs744_ddp_tpu.analysis import (hlo_ir, lockgraph, memlife,
                                    pylint_rules, stats, wire_schema)
from cs744_ddp_tpu.obs import Telemetry
from cs744_ddp_tpu.serve import wire
from cs744_ddp_tpu.train.loop import Trainer
from cs744_ddp_tpu.utils import hlo_stats as legacy

from tinynet import tiny_cnn

ASSETS = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "assets", "hlo")
FIXTURES = sorted(glob.glob(os.path.join(ASSETS, "*.hlo")))
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def setup_module(module):
    model_zoo.register_model("tiny", tiny_cnn)


def _read(path: str) -> str:
    with open(path) as fh:
        return fh.read()


# ---------------------------------------------------------------------------
# hlo_ir: parser round-trip + differential vs the legacy regex oracle
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("path", FIXTURES, ids=os.path.basename)
def test_parser_round_trip(path):
    """parse -> to_text -> parse preserves the accounting-relevant
    structure on every committed fixture (both print forms)."""
    txt = _read(path)
    mod = hlo_ir.parse(txt)
    rt = hlo_ir.parse(mod.to_text())
    assert stats.collective_stats(rt) == stats.collective_stats(mod)
    assert (stats.collective_chain_depth(rt)
            == stats.collective_chain_depth(mod))
    assert rt.donated_param_count() == mod.donated_param_count()
    assert set(rt.computations) == set(mod.computations)


@pytest.mark.parametrize("path", FIXTURES, ids=os.path.basename)
def test_differential_ir_vs_legacy_regex(path):
    """The IR implementation must agree with the legacy regex oracle on
    every committed fixture — the adapter contract of utils/hlo_stats."""
    txt = _read(path)
    assert stats.collective_stats(txt) == legacy.legacy_collective_stats(txt)
    assert (stats.collective_chain_depth(txt)
            == legacy.legacy_collective_chain_depth(txt))
    assert stats.bytes_of_type("(f32[64,10]{1,0}, bf16[3]{0}, token[])") \
        == legacy.legacy_bytes_of_type(
            "(f32[64,10]{1,0}, bf16[3]{0}, token[])")


# Pinned per-fixture numbers: a parser regression that silently changes
# the accounting (rather than erroring) fails here even if old == new.
_FIXTURE_PINS = {
    "train_window_bare.hlo": {"total": 6, "depth": 4},
    "train_window_sigil.hlo": {"total": 6, "depth": 4},
    # Collective inside a fused computation, a called computation and a
    # custom-call's called_computations; depth SUMS operand chains with
    # callee-internal depth across fusion -> call -> custom-call.
    "called_comp.hlo": {"total": 3, "depth": 4,
                        "counts": {"all-reduce": 3}},
    # Async start/done pairs counted once each (start: count, done:
    # bytes), chained all-reduce -> all-gather.
    "async_pair.hlo": {"total": 2, "depth": 2, "mib": 0.07,
                       "counts": {"all-reduce": 1, "all-gather": 1}},
    # op_name strings naming other instructions, braces and escaped
    # quotes inside source_file paths: none of it may poison the graph.
    "metadata_heavy.hlo": {"total": 2, "depth": 2,
                           "counts": {"all-reduce": 2}},
}


@pytest.mark.parametrize("name", sorted(_FIXTURE_PINS), ids=str)
def test_fixture_pins(name):
    txt = _read(os.path.join(ASSETS, name))
    pin = _FIXTURE_PINS[name]
    s = stats.collective_stats(txt)
    assert s["total_count"] == pin["total"], s
    assert stats.collective_chain_depth(txt) == pin["depth"]
    if "counts" in pin:
        assert {op: e["count"] for op, e in s["ops"].items()} \
            == pin["counts"], s
    if "mib" in pin:
        assert s["total_result_mib"] == pin["mib"], s


def test_parser_called_computations():
    mod = hlo_ir.parse(_read(os.path.join(ASSETS, "called_comp.hlo")))
    entry = mod.computations["main"]
    assert mod.entry == "main"
    assert list(entry.instructions["fus"].called) == ["fused_reduce"]
    assert list(entry.instructions["c"].called) == ["helper_call"]
    assert list(entry.instructions["cc"].called) == ["helper_call"]
    assert entry.instructions["cc"].attr("custom_call_target") \
        == '"my_target"'
    assert entry.root.name == "out"
    # Bodies referenced by while show up too (the host-sync rule's input).
    sig = hlo_ir.parse(_read(os.path.join(ASSETS,
                                          "train_window_sigil.hlo")))
    w = sig.computations["main.4"].instructions["w"]
    assert sorted(w.called) == ["wbody.2", "wcond.3"]


def test_parser_donation_header():
    txt = ("HloModule donate, buffer_donor={ (0, {}), (1, {}) }, "
           "entry_computation_layout={(f32[4]{0},f32[4]{0})->f32[4]{0}}\n"
           "\n"
           "ENTRY main {\n"
           "  p0 = f32[4] parameter(0)\n"
           "  p1 = f32[4] parameter(1)\n"
           "  ROOT s = f32[4] add(p0, p1)\n"
           "}\n")
    assert hlo_ir.parse(txt).donated_param_count() == 2
    bare = txt.replace("buffer_donor={ (0, {}), (1, {}) }, ", "")
    assert hlo_ir.parse(bare).donated_param_count() == 0


# ---------------------------------------------------------------------------
# audit: every rule catches a seeded violation (positive) and stays quiet
# on conforming programs (negative)
# ---------------------------------------------------------------------------

_CHAIN3 = """\
HloModule chain3

radd {
  x = f32[] parameter(0)
  y = f32[] parameter(1)
  ROOT s = f32[] add(x, y)
}

ENTRY main {
  p = f32[64] parameter(0)
  a1 = f32[64] all-reduce(p), channel_id=1, to_apply=radd
  a2 = f32[64] all-reduce(a1), channel_id=2, to_apply=radd
  a3 = f32[64] all-reduce(a2), channel_id=3, to_apply=radd
  ROOT o = f32[64] add(a3, a3)
}
"""


def _contract(**kw):
    kw.setdefault("name", "t/prog")
    return auditlib.ProgramContract(**kw)


def _rules_of(report):
    return {r for r, v in report.rules.items() if v == "fail"}


def test_rule_collective_contract_seeded():
    # single/world-1 programs must be collective-free.
    r = auditlib.audit_program(_CHAIN3, _contract(strategy="single"))
    assert _rules_of(r) == {"collective-contract"}
    # ddp with fewer buckets than leaves must NOT serialize per leaf:
    # a 3-deep chain against nbuckets=1/nleaves=3 is the fusion win lost.
    r = auditlib.audit_program(_CHAIN3, _contract(
        strategy="ddp", world=4, nleaves=3, nbuckets=1))
    assert _rules_of(r) == {"collective-contract"}
    assert "fusion win lost" in r.findings[0].message
    # gather needs all-gathers; an all-reduce-only program fails.
    r = auditlib.audit_program(_CHAIN3, _contract(
        strategy="gather", world=4, nleaves=2))
    assert _rules_of(r) == {"collective-contract"}


def test_rule_collective_contract_conforming():
    # The same chain IS a conforming per-param allreduce tier.
    r = auditlib.audit_program(_CHAIN3, _contract(
        strategy="allreduce", world=4, nleaves=3))
    assert r.passed, r.findings
    assert r.stats["collectives"] == {"all-reduce": 3}
    assert r.stats["chain_depth"] == 3
    # And a genuinely collective-free program audits clean as single.
    clean = ("HloModule empty\n\nENTRY main {\n"
             "  ROOT p = f32[4] parameter(0)\n}\n")
    assert auditlib.audit_program(clean, _contract(strategy="single")).passed


_WIRE = """\
HloModule wire

radd {
  x = DT[] parameter(0)
  y = DT[] parameter(1)
  ROOT s = DT[] add(x, y)
}

ENTRY main {
  p = DT[64] parameter(0)
  q = DT[64] parameter(1)
  a1 = DT[64] all-reduce(p), channel_id=1, to_apply=radd
  a2 = DT[64] all-reduce(q), channel_id=2, to_apply=radd
  ROOT o = DT[64] add(a1, a2)
}
"""


def test_rule_overlap_contract_seeded():
    # A 3-deep post-backward chain is exactly what the overlap tier must
    # NOT lower — same fused count as ddp, but fully serialized.
    r = auditlib.audit_program(_CHAIN3, _contract(
        strategy="overlap", world=4, nleaves=3, nbuckets=3))
    assert _rules_of(r) == {"collective-contract"}
    assert "must not chain" in r.findings[0].message
    # Two INDEPENDENT all-reduces (chain depth 1) conform.
    r = auditlib.audit_program(_WIRE.replace("DT", "f32"), _contract(
        strategy="overlap", world=4, nleaves=2, nbuckets=2))
    assert r.passed, r.findings
    # Fewer reduces than buckets: a bucket went unsynced.
    assert not auditlib.audit_program(
        _WIRE.replace("DT", "f32"), _contract(
            strategy="overlap", world=4, nleaves=3, nbuckets=3)).passed


_GATED = """\
HloModule gated

radd {
  x = f32[] parameter(0)
  y = f32[] parameter(1)
  ROOT s = f32[] add(x, y)
}

ENTRY main {
  a = f32[8,8] parameter(0)
  b = f32[8,8] parameter(1)
  d1 = f32[8,8] dot(a, b), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  d2 = f32[8,8] dot(b, a), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  SRC
  ar = f32[8,8] all-reduce(red), channel_id=1, to_apply=radd
  ROOT o = f32[8,8] add(ar, SINK)
}
"""


def test_rule_overlap_dot_cone_seeded():
    """The overlap tier's scheduling evidence: at least one collective's
    operand cone must exclude part of the backward — a collective gated
    on EVERY dot cannot have been issued early."""
    allgated = (_GATED.replace("SRC", "red = f32[8,8] add(d1, d2)")
                .replace("SINK", "ar"))
    r = auditlib.audit_program(allgated, _contract(
        strategy="overlap", world=4, nleaves=1, nbuckets=1))
    assert _rules_of(r) == {"collective-contract"}
    assert "operand cone" in r.findings[0].message
    # The same program with the reduce gated on d1 only: d2 is outside
    # the cone, so the collective COULD overlap it — conforming.
    partial = (_GATED.replace("SRC", "red = f32[8,8] add(d1, d1)")
               .replace("SINK", "d2"))
    assert auditlib.audit_program(partial, _contract(
        strategy="overlap", world=4, nleaves=1, nbuckets=1)).passed


def test_rule_compressed_bytes_seeded():
    c2 = dict(strategy="compress-bf16", world=4, nleaves=2,
              param_bytes=512, compress_ratio=2.0)
    # An uncompressed f32 wire (512 B) against the 2x contract: caught.
    r = auditlib.audit_program(_WIRE.replace("DT", "f32"), _contract(**c2))
    assert _rules_of(r) == {"collective-contract"}
    assert "compression is not real" in r.findings[0].message
    # The genuine bf16 wire (256 B = param_bytes/2): conforming.
    assert auditlib.audit_program(_WIRE.replace("DT", "bf16"),
                                  _contract(**c2)).passed, "bf16 wire"
    # int8 contract (4x): bf16 wire fails, s8 wire (128 B) passes.
    c4 = dict(c2, strategy="compress-int8", compress_ratio=4.0)
    assert not auditlib.audit_program(_WIRE.replace("DT", "bf16"),
                                      _contract(**c4)).passed
    assert auditlib.audit_program(_WIRE.replace("DT", "s8"),
                                  _contract(**c4)).passed
    # Declared aux allowance (BN pmeans, int8 scale pmax) is excluded
    # from the gradient wire before the ratio is enforced.
    assert auditlib.audit_program(
        _WIRE.replace("DT", "f32"),
        _contract(**dict(c2, aux_bytes=256))).passed
    # Every leaf must still be reduced.
    assert not auditlib.audit_program(
        _WIRE.replace("DT", "bf16"),
        _contract(**dict(c2, nleaves=3))).passed


_LEAK = """\
HloModule leak

ENTRY main {
  a = bf16[8,8] parameter(0)
  b = bf16[8,8] parameter(1)
  ROOT d = DT[8,8] dot(a, b), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}
"""


def test_rule_dtype_leak():
    bad = auditlib.audit_program(_LEAK.replace("DT", "f32"),
                                 _contract(precision="bf16"))
    assert _rules_of(bad) == {"dtype-leak"}
    assert "dot" in bad.findings[0].message
    ok = auditlib.audit_program(_LEAK.replace("DT", "bf16"),
                                _contract(precision="bf16"))
    assert ok.passed, ok.findings
    # An f32-declared program may dot in f32 — the rule is bf16-only.
    assert auditlib.audit_program(_LEAK.replace("DT", "f32"),
                                  _contract(precision="f32")).passed


def test_rule_donation():
    # Both donated params need a same-size output leaf to alias (round 20:
    # donation is checked as aliased-bytes equality, not just leaf count).
    donated = ("HloModule m, buffer_donor={ (0, {}), (1, {}) }\n\n"
               "ENTRY main {\n  p0 = f32[4] parameter(0)\n"
               "  p1 = f32[4] parameter(1)\n"
               "  s = f32[4] add(p0, p1)\n"
               "  d = f32[4] multiply(p0, p1)\n"
               "  ROOT t = (f32[4], f32[4]) tuple(s, d)\n}\n")
    undonated = ("HloModule m\n\nENTRY main {\n"
                 "  p0 = f32[4] parameter(0)\n"
                 "  p1 = f32[4] parameter(1)\n"
                 "  ROOT s = f32[4] add(p0, p1)\n}\n")
    bad = auditlib.audit_program(undonated, _contract(
        donates_state=True, n_state_leaves=2))
    assert _rules_of(bad) == {"donation"}
    ok = auditlib.audit_program(donated, _contract(
        donates_state=True, n_state_leaves=2))
    assert ok.passed, ok.findings
    assert ok.stats["donated"] == 2
    # More state leaves than donated entries: still a miss.
    assert not auditlib.audit_program(donated, _contract(
        donates_state=True, n_state_leaves=3)).passed


_HOST_SYNC = """\
HloModule host_sync

wbody {
  p = f32[4] parameter(0)
  cb = f32[4] custom-call(p), custom_call_target="xla_ffi_python_cpu_callback"
  ROOT r = f32[4] add(cb, cb)
}

wcond {
  q = f32[4] parameter(0)
  ROOT lt = pred[] constant(false)
}

ENTRY main {
  a = f32[4] parameter(0)
  w = f32[4] while(a), body=wbody, condition=wcond
  ROOT out = f32[4] add(w, w)
}
"""


def test_rule_host_sync_hlo():
    bad = auditlib.audit_program(_HOST_SYNC, _contract())
    assert _rules_of(bad) == {"host-sync"}
    assert "wbody" in bad.findings[0].message
    # The same callback OUTSIDE any while body is legal (one-shot host
    # call at dispatch, not one per scanned step).
    flat = _HOST_SYNC.replace(
        "w = f32[4] while(a), body=wbody, condition=wcond",
        'w = f32[4] custom-call(a), custom_call_target='
        '"xla_ffi_python_cpu_callback"')
    assert auditlib.audit_program(flat, _contract()).passed


def test_rule_host_sync_jaxpr():
    clean_hlo = ("HloModule m\n\nENTRY main {\n"
                 "  ROOT p = f32[4] parameter(0)\n}\n")

    def cb(x):
        return np.asarray(x)

    def body_with_callback(xs):
        def step(c, x):
            y = jax.pure_callback(
                cb, jax.ShapeDtypeStruct(x.shape, x.dtype), x)
            return c + jnp.sum(y), None
        out, _ = jax.lax.scan(step, 0.0, xs)
        return out

    bad_jaxpr = jax.make_jaxpr(body_with_callback)(jnp.ones((3, 2)))
    bad = auditlib.audit_program(clean_hlo, _contract(), jaxpr=bad_jaxpr)
    assert _rules_of(bad) == {"host-sync"}
    assert "callback" in bad.findings[0].message

    def body_plain(xs):
        def step(c, x):
            return c + jnp.sum(x), None
        out, _ = jax.lax.scan(step, 0.0, xs)
        return out

    ok_jaxpr = jax.make_jaxpr(body_plain)(jnp.ones((3, 2)))
    assert auditlib.audit_program(clean_hlo, _contract(),
                                  jaxpr=ok_jaxpr).passed


_BAKED = """\
HloModule baked

ENTRY main {{
  c = f32[{N}]{{0}} constant({{...}})
  p = f32[{N}]{{0}} parameter(0)
  ROOT o = f32[{N}]{{0}} add(c, p)
}}
"""


def test_rule_baked_constants():
    big = _BAKED.format(N=400000)    # 1.6 MB > the 1 MiB default
    bad = auditlib.audit_program(big, _contract())
    assert _rules_of(bad) == {"baked-constants"}
    assert "1600000 bytes" in bad.findings[0].message
    # Under the threshold (or with a raised contract limit): clean.
    assert auditlib.audit_program(_BAKED.format(N=1000),
                                  _contract()).passed
    assert auditlib.audit_program(big, _contract(
        max_constant_bytes=1 << 21)).passed


def test_waivers():
    c = _contract(name="train/step/ddp", strategy="ddp", world=4,
                  nleaves=3, nbuckets=1)
    # Global waiver: finding moves to waived, program passes, rule is
    # recorded as waived (still visible in the manifest).
    r = auditlib.audit_program(_CHAIN3, c, waive=("collective-contract",))
    assert r.passed and r.waived
    assert r.rules["collective-contract"] == "waived"
    # Glob-scoped waiver only applies to matching program names.
    r = auditlib.audit_program(_CHAIN3, c,
                               waive=("collective-contract@serve/*",))
    assert not r.passed
    r = auditlib.audit_program(_CHAIN3, c,
                               waive=("collective-contract@train/*",))
    assert r.passed


def test_certify_ladder_seeded():
    ladder, findings = auditlib._certify_ladder(
        {"gather": 2, "allreduce": 6, "ddp": 1}, nleaves=6, nbuckets=1,
        program="strategy-ladder")
    assert len(findings) == 1 and "gather" in findings[0].message
    _, findings = auditlib._certify_ladder(
        {"gather": 12, "allreduce": 6, "ddp": 6}, nleaves=6, nbuckets=1,
        program="strategy-ladder")
    assert len(findings) == 1 and "ddp" in findings[0].message
    _, findings = auditlib._certify_ladder(
        {"gather": 12, "allreduce": 6, "ddp": 1}, nleaves=6, nbuckets=1,
        program="strategy-ladder")
    assert not findings


# ---------------------------------------------------------------------------
# audit: the real program zoo must be CLEAN (the PR's acceptance bar)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def zoo():
    model_zoo.register_model("tiny", tiny_cnn)
    return auditlib.audit_zoo(model="tiny", global_batch=64, window=3,
                              serve_buckets=(2,), num_devices=4,
                              collect_hlo=True)


def test_zoo_audits_clean(zoo):
    assert zoo.clean, "\n".join(zoo.format_lines())
    # 8 strategies x 3 train paths + eval + 1 serving bucket.
    assert len(zoo.reports) == 26
    names = {r.program for r in zoo.reports}
    assert "train/window/ddp" in names and "eval/window" in names
    assert "serve/b2/f32" in names
    assert "train/window/overlap" in names
    assert "train/window/compress-int8" in names
    assert "train/window/powersgd" in names


def test_zoo_depth_ladder(zoo):
    """The paper's cost ordering, certified on the lowered programs:
    bucketed ddp strictly shallower than per-param allreduce, which is
    strictly shallower than the two-phase gather tier."""
    lad = zoo.ladder
    assert lad["ddp"] < lad["allreduce"] < lad["gather"], lad
    assert lad["single"] == 0
    # tiny_cnn: 6 param leaves, one ~25 MB bucket — the depths are the
    # tiers' defining shape (2/leaf, 1/leaf, 1/bucket).
    assert lad["gather"] == 2 * lad["allreduce"]
    assert lad["ddp"] == 1
    # Round-7 tiers, recorded informatively alongside the certified trio:
    # overlap never chains (depth 1 regardless of bucket count); the
    # compressed tiers chain per leaf like allreduce (+1 for int8's
    # shared-scale pmax); powersgd's two-psum leaves sit deepest.
    assert lad["overlap"] == 1
    assert lad["compress-bf16"] == lad["allreduce"]
    assert lad["compress-int8"] == lad["allreduce"] + 1
    assert lad["powersgd"] >= lad["allreduce"]


def test_zoo_summary_shape(zoo):
    s = zoo.summary()
    assert s["clean"] and s["n_findings"] == 0
    assert s["n_programs"] == len(zoo.reports)
    assert set(s["programs"]["train/window/ddp"]["rules"]) \
        == set(auditlib.RULES)
    lines = zoo.format_lines()
    assert lines[-1].startswith("[audit] CLEAN")
    json.dumps(s)   # manifest-ready: JSON-serializable as-is


def test_zoo_bf16_clean():
    """The bf16 window program carries no f32 dot/conv leak — the
    dtype-leak rule passes on the real mixed-precision lowering."""
    res = auditlib.audit_zoo(model="tiny", global_batch=64, window=3,
                             precision="bf16", strategies=("ddp",),
                             paths=("window",), include_eval=False,
                             num_devices=4)
    assert res.clean, "\n".join(res.format_lines())
    assert res.reports[0].rules["dtype-leak"] == "pass"


# ---------------------------------------------------------------------------
# CLI wiring: --audit strict exit codes, manifest recording
# ---------------------------------------------------------------------------

def test_cli_audit_zoo_strict_clean(capsys):
    from cs744_ddp_tpu import cli
    cli.main(["--audit-zoo", "--audit", "strict", "--model", "tiny",
              "--batch-size", "64", "--num-devices", "4",
              "--serve-buckets", "2"])
    out = capsys.readouterr().out
    assert "[audit] CLEAN" in out
    assert "[audit] strategy depth ladder" in out


def test_cli_audit_strict_exits_2_on_finding(capsys):
    from cs744_ddp_tpu import cli
    from cs744_ddp_tpu.obs import NULL
    dirty = auditlib.AuditResult(reports=[auditlib.audit_program(
        _CHAIN3, _contract(strategy="single"))])
    assert not dirty.clean
    args = types.SimpleNamespace(audit="strict")
    with pytest.raises(SystemExit) as exc:
        cli._apply_audit(args, NULL, dirty)
    assert exc.value.code == 2
    # warn mode reports the same findings but never exits.
    args.audit = "warn"
    cli._apply_audit(args, NULL, dirty)
    assert "DIRTY" in capsys.readouterr().out


def test_record_audit_disabled_recorder_untouched():
    class Exploding:
        enabled = False

        def __getattr__(self, name):
            raise AssertionError(f"telemetry.{name} touched while disabled")

    res = auditlib.AuditResult(reports=[auditlib.audit_program(
        _CHAIN3, _contract(strategy="allreduce", world=4, nleaves=3))])
    auditlib.record_audit(Exploding(), res)   # must not raise


def test_record_audit_merges_into_manifest(tmp_path):
    from cs744_ddp_tpu.obs import Telemetry
    tel = Telemetry(str(tmp_path))
    tel.write_manifest({"model": "tiny", "mode": "test"})
    res = auditlib.AuditResult(reports=[auditlib.audit_program(
        _CHAIN3, _contract(strategy="allreduce", world=4, nleaves=3))])
    auditlib.record_audit(tel, res)
    manifest = json.loads((tmp_path / "manifest.json").read_text())
    assert manifest["model"] == "tiny"          # merged, not clobbered
    assert manifest["audit"]["clean"] is True
    assert manifest["audit"]["programs"]["t/prog"]["chain_depth"] == 3
    tel.finalize()


def test_telemetry_report_renders_audit(tmp_path, monkeypatch):
    monkeypatch.syspath_prepend(os.path.join(REPO, "tools"))
    import telemetry_report
    (tmp_path / "events.jsonl").write_text("")
    (tmp_path / "manifest.json").write_text(json.dumps({
        "model": "tiny",
        "audit": {"clean": False, "n_programs": 2, "n_findings": 1,
                  "n_waived": 0,
                  "programs": {
                      "train/window/ddp": {
                          "rules": {"collective-contract": "pass"},
                          "chain_depth": 1},
                      "train/step/single": {
                          "rules": {"collective-contract": "fail"},
                          "chain_depth": 3}},
                  "findings": [{"rule": "collective-contract",
                                "program": "train/step/single",
                                "message": "expected collective-free"}],
                  "waived": [],
                  "ladder": {"ddp": 1, "allreduce": 6, "gather": 12}},
    }))
    out = telemetry_report.render(str(tmp_path))
    assert "== program audit ==" in out
    assert "DIRTY: 2 programs, 1 findings" in out
    assert "FAIL collective-contract" in out
    assert "strategy depth ladder" in out
    # Tolerant when absent: a run with no audit record renders without
    # the section (older manifests unchanged).
    (tmp_path / "manifest.json").write_text(json.dumps({"model": "tiny"}))
    assert "program audit" not in telemetry_report.render(str(tmp_path))


# ---------------------------------------------------------------------------
# AST lint: each rule fires on a seeded violation; waivers suppress;
# the repo itself is clean
# ---------------------------------------------------------------------------

_SRC_UNFENCED = """\
import time

class T:
    def run(self, x):
        t0 = time.time()
        loss = self.train_window(x)
        return time.time() - t0
"""

_SRC_FENCED = """\
import time
import numpy as np

class T:
    def run(self, x):
        t0 = time.time()
        loss = np.asarray(self.train_window(x))
        return time.time() - t0
"""


def test_lint_unfenced_timing():
    bad = pylint_rules.lint_source(_SRC_UNFENCED, "bad.py")
    assert [f.rule for f in bad] == ["unfenced-timing"]
    assert bad[0].line == 6
    # A fence WRAPPING the dispatch synchronizes where it returns.
    assert pylint_rules.lint_source(_SRC_FENCED, "ok.py") == []
    # Timing with no dispatch inside is plain host timing: out of scope.
    host_only = _SRC_UNFENCED.replace("self.train_window(x)", "len(x)")
    assert pylint_rules.lint_source(host_only, "ok.py") == []
    # Round-7 overlap scheduling: timing a PER-BUCKET dispatch loop is the
    # same hazard — the loop queues every bucket's collective and the
    # timer stops before any of them ran.  The rule must see through the
    # loop nesting (bench.run_compression and the overlap tier's bucket
    # walk are in the default lint targets).
    bucketed = _SRC_UNFENCED.replace(
        "loss = self.train_window(x)",
        "for b in x:\n            loss = self.train_step(b)")
    bad = pylint_rules.lint_source(bucketed, "bad.py")
    assert [f.rule for f in bad] == ["unfenced-timing"]


_SRC_THREAD_JNP = """\
import threading
import jax.numpy as jnp

def worker(q):
    q.put(jnp.ones(3))

def start(q):
    return threading.Thread(target=worker, args=(q,)).start()
"""


def test_lint_thread_jnp():
    bad = pylint_rules.lint_source(_SRC_THREAD_JNP, "bad.py")
    assert [f.rule for f in bad] == ["thread-jnp"]
    ok = _SRC_THREAD_JNP.replace("jnp.ones(3)", "[1, 2, 3]")
    assert pylint_rules.lint_source(ok, "ok.py") == []
    # The same jnp use OUTSIDE any thread entry is fine.
    no_thread = _SRC_THREAD_JNP.replace("threading.Thread(target=worker, "
                                        "args=(q,)).start()", "worker")
    assert pylint_rules.lint_source(no_thread, "ok.py") == []


_SRC_UNLOCKED = """\
import threading

class Q:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def put(self, x):
        with self._lock:
            self._items.append(x)

    def drain(self):
        self._items = []
"""


def test_lint_lock_ownership():
    bad = pylint_rules.lint_source(_SRC_UNLOCKED, "bad.py")
    assert [f.rule for f in bad] == ["lock-ownership"]
    assert bad[0].line == 13
    assert "drain" in bad[0].message
    ok = _SRC_UNLOCKED.replace(
        "    def drain(self):\n        self._items = []",
        "    def drain(self):\n        with self._lock:\n"
        "            self._items = []")
    assert pylint_rules.lint_source(ok, "ok.py") == []


def test_lint_waivers():
    waived = _SRC_UNLOCKED.replace(
        "    def drain(self):\n        self._items = []",
        "    def drain(self):\n"
        "        self._items = []   # lint: ok(lock-ownership)")
    assert pylint_rules.lint_source(waived, "w.py") == []
    generic = _SRC_UNLOCKED.replace(
        "    def drain(self):\n        self._items = []",
        "    def drain(self):\n        self._items = []   # lint: ok")
    assert pylint_rules.lint_source(generic, "w.py") == []
    # A waiver for a DIFFERENT rule does not suppress.
    wrong = _SRC_UNLOCKED.replace(
        "    def drain(self):\n        self._items = []",
        "    def drain(self):\n"
        "        self._items = []   # lint: ok(thread-jnp)")
    assert [f.rule for f in pylint_rules.lint_source(wrong, "w.py")] \
        == ["lock-ownership"]


_SRC_SPAN_BARE = """\
def emit(tel, t0, ctx):
    tel.span_event("sched_queue", t0, 0.01, bucket=4)
"""

_SRC_SPAN_SPLAT = """\
def emit(tel, t0, ctx):
    tel.span_event("sched_queue", t0, 0.01, bucket=4, **ctx.attrs())
"""


def test_lint_span_hygiene_traced_names():
    # A distributed-trace span without its join keys is invisible to the
    # cross-process aggregation — the rule catches the emit site.
    bad = pylint_rules.lint_source(_SRC_SPAN_BARE, "bad.py")
    assert [f.rule for f in bad] == ["span-hygiene"]
    assert "sched_queue" in bad[0].message
    # **ctx.attrs() splat satisfies it; so does an explicit trace_id=.
    assert pylint_rules.lint_source(_SRC_SPAN_SPLAT, "ok.py") == []
    explicit = _SRC_SPAN_BARE.replace("bucket=4", "trace_id=tid")
    assert pylint_rules.lint_source(explicit, "ok.py") == []
    # Splatting a LOCAL assigned from .attrs() counts too (the frontend
    # builds attrs dicts before adding reply fields).
    via_var = ("def emit(tel, t0, ctx):\n"
               "    attrs = ctx.attrs()\n"
               "    attrs['status'] = 'ok'\n"
               "    tel.span_event('frontend_request', t0, 0.01, **attrs)\n")
    assert pylint_rules.lint_source(via_var, "ok.py") == []
    # Non-traced span names are out of scope entirely.
    other = _SRC_SPAN_BARE.replace("sched_queue", "host_augment")
    assert pylint_rules.lint_source(other, "ok.py") == []


def test_lint_span_hygiene_batch_names_and_waiver():
    # Batch-level engine spans cover a whole dispatch: they need the
    # member batcher trace ids (traces=) instead of one trace_id.
    bad = ("def emit(tel, t0):\n"
           "    tel.span_event('serve_dispatch', t0, 0.01, bucket=8)\n")
    finds = pylint_rules.lint_source(bad, "bad.py")
    assert [f.rule for f in finds] == ["span-hygiene"]
    assert "traces=" in finds[0].message
    ok = bad.replace("bucket=8", "traces=list(ids)")
    assert pylint_rules.lint_source(ok, "ok.py") == []
    waived = bad.replace(
        "bucket=8)", "bucket=8)  # lint: ok(span-hygiene)")
    assert pylint_rules.lint_source(waived, "w.py") == []


def test_repo_lints_clean():
    """Tier-1 gate: the shipped tree carries none of the four hazards
    (same check tools/lint_graft.py runs standalone)."""
    targets = [os.path.join(REPO, t) for t in pylint_rules.DEFAULT_TARGETS]
    findings = pylint_rules.lint_paths(targets)
    assert findings == [], "\n".join(
        f"{f.path}:{f.line}: [{f.rule}] {f.message}" for f in findings)


def test_lint_graft_cli(tmp_path, monkeypatch, capsys):
    monkeypatch.syspath_prepend(os.path.join(REPO, "tools"))
    import lint_graft
    bad = tmp_path / "bad.py"
    bad.write_text(_SRC_UNLOCKED)
    assert lint_graft.main([str(bad)]) == 1
    out = capsys.readouterr().out
    assert "[lock-ownership]" in out and "1 finding(s)" in out
    ok = tmp_path / "ok.py"
    ok.write_text("x = 1\n")
    assert lint_graft.main([str(ok)]) == 0
    assert "lint_graft: clean" in capsys.readouterr().out


def test_lint_graft_cli_json(tmp_path, monkeypatch, capsys):
    """--json emits a machine-readable findings array (CI annotation)
    with exit codes unchanged: 1 on findings, 0 clean."""
    monkeypatch.syspath_prepend(os.path.join(REPO, "tools"))
    import lint_graft
    bad = tmp_path / "bad.py"
    bad.write_text(_SRC_UNLOCKED)
    assert lint_graft.main(["--json", str(bad)]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert len(payload) == 1
    (f,) = payload
    assert set(f) == {"rule", "file", "line", "message"}
    assert f["rule"] == "lock-ownership" and f["line"] == 13
    assert f["file"].endswith("bad.py") and "drain" in f["message"]
    ok = tmp_path / "ok.py"
    ok.write_text("x = 1\n")
    assert lint_graft.main(["--json", str(ok)]) == 0
    assert json.loads(capsys.readouterr().out) == []


# ---------------------------------------------------------------------------
# Thread-safety regressions (satellite 2): the lock-ownership findings,
# fixed and locked in behaviorally
# ---------------------------------------------------------------------------

def test_microbatcher_lifecycle_locked():
    """start() historically wrote _stop/_worker without the condition —
    racing _enqueue's locked reads.  Now the whole transition happens
    under self._cond and the assertion-mode check enforces it."""
    from cs744_ddp_tpu.serve import InferenceEngine, MicroBatcher
    model_zoo.register_model("tiny", tiny_cnn)
    eng = InferenceEngine("tiny", buckets=(2, 4), seed=0)
    eng.startup()
    mb = MicroBatcher(eng, max_wait_ms=1.0)
    # The ownership assertion itself: outside the lock it trips, under
    # the lock it passes (the worker/enqueue paths call it while locked).
    with pytest.raises(AssertionError, match="without holding"):
        mb._assert_owned()
    with mb._cond:
        mb._assert_owned()
    with mb:
        with pytest.raises(RuntimeError, match="already started"):
            mb.start()
        rng = np.random.default_rng(0)
        img = rng.integers(0, 256, (2, 32, 32, 3), dtype=np.uint8)
        assert mb.submit(img).result(timeout=30).shape == (2, 10)
    # Stopped and drained: the queue rejects, and a restart works.
    with pytest.raises(RuntimeError, match="not running"):
        mb.submit(img)
    with mb:
        assert mb.submit(img).result(timeout=30).shape == (2, 10)


def test_watchdog_cancel_vs_fire_race():
    """Timer.cancel does not wait for an in-flight callback: a watchdog
    whose body already completed must NEVER count a timeout afterwards.
    __exit__ marks it cancelled under the lock; a late _fire is inert."""
    from cs744_ddp_tpu.ft.supervisor import Watchdog
    fired = []
    wd = Watchdog(10.0, on_timeout=fired.append)
    with wd:
        pass
    # Simulate the in-flight timer thread firing AFTER __exit__.
    wd._fire()
    assert not wd.fired and fired == []
    # The genuine-timeout path still works and fires exactly once.
    with Watchdog(0.005, on_timeout=fired.append) as wd2:
        deadline = time.time() + 5.0
        while not wd2.fired and time.time() < deadline:
            time.sleep(0.005)
    assert wd2.fired and len(fired) == 1
    wd2._fire()           # late duplicate after exit: still inert
    assert len(fired) == 1


_SRC_DECLARED = """\
import threading

class Coord:
    _lock_owned = ("world", "members")

    def __init__(self):
        self._lock = threading.Lock()
        self.world = 4
        self.members = (0, 1, 2, 3)

    def shrink(self):
        self.world = 1
"""


def test_lint_lock_owned_declaration_guards_from_first_write():
    """A class-level ``_lock_owned`` tuple declares attributes lock-owned
    even when NO locked write is in view — a new method mutating them
    unlocked fails before any locked counterpart exists (the elastic
    coordinator's membership contract)."""
    bad = pylint_rules.lint_source(_SRC_DECLARED, "bad.py")
    assert [f.rule for f in bad] == ["lock-ownership"]
    assert "shrink" in bad[0].message and "world" in bad[0].message
    ok = _SRC_DECLARED.replace(
        "    def shrink(self):\n        self.world = 1",
        "    def shrink(self):\n        with self._lock:\n"
        "            self.world = 1")
    assert pylint_rules.lint_source(ok, "ok.py") == []
    # Undeclared attributes keep the heuristic-only semantics: a write
    # that is never locked anywhere is not flagged.
    free = _SRC_DECLARED.replace('("world", "members")', '("members",)')
    assert pylint_rules.lint_source(free, "free.py") == []
    # __init__ stays exempt (construction happens-before sharing), and
    # non-literal declaration elements are ignored, not crashed on.
    dynamic = _SRC_DECLARED.replace('("world", "members")',
                                    '("members",) + EXTRA')
    assert pylint_rules.lint_source(
        "EXTRA = ()\n" + dynamic, "dyn.py") == []


def test_lint_lock_owned_declaration_needs_a_lock():
    # Without a lock attribute the rule (and the declaration) is inert.
    no_lock = "class C:\n    _lock_owned = ('x',)\n" \
              "    def f(self):\n        self.x = 1\n"
    assert pylint_rules.lint_source(no_lock, "n.py") == []


_SRC_ROUTER = """\
import threading

class Router:
    _lock_owned = ("_routed", "_failovers")

    def __init__(self):
        self._lock = threading.Lock()
        self._routed = 0
        self._failovers = 0

    def submit(self):
        with self._lock:
            self._routed += 1

    def _handle_death(self):
        self._failovers += 1
"""


def test_lint_lock_owned_covers_router_shape():
    """Round 9: the serving router's failover counter is bumped from a
    scheduler worker thread, not the caller's — an unlocked write in the
    death handler is exactly the race the declaration must catch."""
    bad = pylint_rules.lint_source(_SRC_ROUTER, "bad.py")
    assert [f.rule for f in bad] == ["lock-ownership"]
    assert "_handle_death" in bad[0].message \
        and "_failovers" in bad[0].message
    ok = _SRC_ROUTER.replace(
        "    def _handle_death(self):\n        self._failovers += 1",
        "    def _handle_death(self):\n        with self._lock:\n"
        "            self._failovers += 1")
    assert pylint_rules.lint_source(ok, "ok.py") == []


def test_serving_tier_declares_lock_ownership():
    """The live router/scheduler/frontend classes carry ``_lock_owned``
    declarations, so the repo-wide lint gate (test_repo_lints_clean)
    guards their mutable state from first write — not only after a
    locked counterpart exists somewhere."""
    from cs744_ddp_tpu.serve.frontend import FrontendClient, ServingFrontend
    from cs744_ddp_tpu.serve.router import ReplicaRouter
    from cs744_ddp_tpu.serve.scheduler import ServiceModel, SLOScheduler
    assert set(ReplicaRouter._lock_owned) >= {"_routed", "_failovers"}
    assert set(SLOScheduler._lock_owned) >= {"_pending", "_inflight",
                                             "_dead", "_stop"}
    assert set(ServiceModel._lock_owned) >= {"_ewma"}
    assert set(ServingFrontend._lock_owned) >= {"_conns", "_running"}
    assert set(FrontendClient._lock_owned) >= {"_futs", "_next_id"}


def test_zoo_shrunk_world_audits_clean():
    """Round 6: the program set the elastic ladder degrades INTO (world 2
    and the world-1 synchronous fallback) certifies against the same cost
    contracts as the full mesh — ``--audit-zoo`` passes for shrunk worlds."""
    for ndev in (2, 1):
        res = auditlib.audit_zoo(model="tiny", global_batch=64, window=3,
                                 strategies=("ddp",), paths=("window",),
                                 include_eval=False, num_devices=ndev)
        assert res.clean, "\n".join(res.format_lines())


# ---------------------------------------------------------------------------
# Round 13, analyzer 1: lock-order deadlock detector (analysis/lockgraph)
# ---------------------------------------------------------------------------

def _fmt(findings):
    return "\n".join(f"{f.path}:{f.line}: [{f.rule}] {f.message}"
                     for f in findings)


def test_repo_lock_graph_certified():
    """The whole-package lock graph is acyclic, every edge descends the
    declared partial order, and the known cross-subsystem edges are
    actually SEEN (an analyzer that went blind would pass vacuously)."""
    graph = lockgraph.build_repo_graph(REPO)
    assert lockgraph.check_graph(graph) == [], _fmt(lockgraph.check_graph(graph))
    # The five cross-object edges the threaded subsystems really take.
    for edge in (("WeightWatcher._lock", "SLOScheduler._cond"),
                 ("WeightWatcher._lock", "Telemetry._lock"),
                 ("AlertEngine._lock", "Telemetry._lock"),
                 ("MicroBatcher._cond", "Telemetry._lock"),
                 ("SLOScheduler._cond", "ServiceModel._lock")):
        assert edge in graph.edges, sorted(graph.edges)
    # Every lock the package owns has a declared rank, and every edge
    # descends it — the certificate BASELINE.md records.
    order = lockgraph.certified_order(graph)
    assert set(order) == graph.nodes
    for src, dst in graph.edges:
        assert order.index(src) < order.index(dst), (src, dst)
    summary = lockgraph.graph_summary(graph)
    json.dumps(summary)   # manifest/--verify-static ready
    assert summary["certified_order"] == order
    assert lockgraph.check_locks(REPO) == []


_SRC_ABBA = """\
import threading

class A:
    def __init__(self, peer):
        self._lock = threading.Lock()
        self.peer = peer

    def ping(self):
        with self._lock:
            self.peer.poke()

    def poked(self):
        with self._lock:
            pass

class B:
    def __init__(self, peer):
        self._lock = threading.Lock()
        self.peer = peer

    def poke(self):
        with self._lock:
            self.peer.poked()
"""


def test_lockgraph_detects_abba_cycle():
    """The seeded positive fixture: A holds its lock calling into B,
    B holds its lock calling back into A — the classic ABBA shape the
    detector exists for.  Both the cycle and the order violation fire."""
    finds = lockgraph.check_source(_SRC_ABBA, "abba.py",
                                   order=("A._lock", "B._lock"))
    rules = sorted(f.rule for f in finds)
    assert "lock-cycle" in rules and "lock-order-violation" in rules
    # With no declared order the edges are undeclared, and the cycle
    # still fires — acyclicity does not depend on the order table.
    finds = lockgraph.check_source(_SRC_ABBA, "abba.py", order=())
    rules = sorted(f.rule for f in finds)
    assert "lock-cycle" in rules and "lock-order-undeclared" in rules
    # Cutting the back-edge (B no longer calls into A) clears it.
    acyclic = _SRC_ABBA.replace("            self.peer.poked()",
                                "            pass")
    assert lockgraph.check_source(acyclic, "ok.py",
                                  order=("A._lock", "B._lock")) == []


_SRC_CALLER_HOLDS = """\
import threading

class W:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = []

    def _drain_locked(self):
        self.items = []

    def good(self):
        with self._lock:
            self._drain_locked()

    def also_good_locked(self):
        self._drain_locked()

    def bad(self):
        self._drain_locked()
"""


def test_lockgraph_caller_holds_verification():
    """What makes the lint's *_locked exemption sound: every call site
    of a *_locked method must hold the class lock (directly, or by being
    *_locked itself).  An unlocked call is the seeded violation."""
    finds = lockgraph.check_source(_SRC_CALLER_HOLDS, "w.py", order=())
    assert [f.rule for f in finds] == ["lock-caller-holds"]
    assert "bad" in finds[0].message and "_drain_locked" in finds[0].message
    fixed = _SRC_CALLER_HOLDS.replace(
        "    def bad(self):\n        self._drain_locked()",
        "    def bad(self):\n        with self._lock:\n"
        "            self._drain_locked()")
    assert lockgraph.check_source(fixed, "w.py", order=()) == []


def test_lockgraph_cross_object_locked_call():
    src = _SRC_CALLER_HOLDS.replace(
        "    def bad(self):\n        self._drain_locked()",
        "    def bad(self):\n        pass") + """\

class Z:
    def __init__(self, w):
        self._lock = threading.Lock()
        self.w = w

    def steal(self):
        self.w._drain_locked()
"""
    finds = lockgraph.check_source(src, "z.py", order=())
    assert [f.rule for f in finds] == ["lock-cross-locked-call"]
    assert "Z.steal" in finds[0].message


def test_lockgraph_consistent_order_is_clean():
    src = """\
import threading

class Outer:
    def __init__(self, tel):
        self._lock = threading.Lock()
        self.tel = tel

    def tick(self):
        with self._lock:
            self.tel.bump()

class Inner:
    def __init__(self):
        self._lock = threading.Lock()

    def bump(self):
        with self._lock:
            pass
"""
    assert lockgraph.check_source(
        src, "ok.py", order=("Outer._lock", "Inner._lock")) == []
    # The same edge against the INVERTED declaration is a violation.
    finds = lockgraph.check_source(
        src, "bad.py", order=("Inner._lock", "Outer._lock"))
    assert [f.rule for f in finds] == ["lock-order-violation"]


# ---------------------------------------------------------------------------
# Round 13, satellite 1: the lint holding idioms that replaced waivers
# ---------------------------------------------------------------------------

_SRC_CONDACQ = """\
import threading

class P:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0

    def bump(self):
        with self._lock:
            self.n += 1

    def poll(self):
        if not self._lock.acquire(blocking=False):
            return
        try:
            self.n += 1
        finally:
            self._lock.release()
"""


def test_lint_conditional_acquire_idiom():
    """The watcher's non-blocking poll: after a conditional
    ``.acquire()`` whose failure arm bails, the rest of the block runs
    held — no waiver needed.  A write BEFORE the acquire still races."""
    assert pylint_rules.lint_source(_SRC_CONDACQ, "ok.py") == []
    bad = _SRC_CONDACQ.replace(
        "    def poll(self):\n"
        "        if not self._lock.acquire(blocking=False):",
        "    def poll(self):\n"
        "        self.n += 1\n"
        "        if not self._lock.acquire(blocking=False):")
    finds = pylint_rules.lint_source(bad, "bad.py")
    assert [f.rule for f in finds] == ["lock-ownership"]
    assert "poll" in finds[0].message


_SRC_LOCKED_SUFFIX = """\
import threading

class S:
    def __init__(self):
        self._lock = threading.Lock()
        self.gen = 0

    def install(self):
        with self._lock:
            self.gen += 1
            self._reset_locked()

    def _reset_locked(self):
        self.gen = 0
"""


def test_lint_locked_suffix_idiom():
    """A ``*_locked`` method's body runs under the caller's lock by
    contract — the lint trusts the suffix (no waiver), and lockgraph
    verifies every call site (previous tests).  Without the suffix the
    same write is flagged."""
    assert pylint_rules.lint_source(_SRC_LOCKED_SUFFIX, "ok.py") == []
    assert lockgraph.check_source(_SRC_LOCKED_SUFFIX, "ok.py",
                                  order=()) == []
    bad = _SRC_LOCKED_SUFFIX.replace("_reset_locked", "_reset")
    finds = pylint_rules.lint_source(bad, "bad.py")
    assert [f.rule for f in finds] == ["lock-ownership"]
    assert "_reset" in finds[0].message


def test_no_lock_ownership_waivers_left():
    """Satellite 1's acceptance bar: the idioms above replaced every
    ``# lint: ok(lock-ownership)`` waiver in the tree."""
    hits = []
    for dirpath, dirnames, filenames in os.walk(
            os.path.join(REPO, "cs744_ddp_tpu")):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in filenames:
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            if "lint: ok(lock-ownership)" in _read(path):
                hits.append(path)
    assert hits == []


# ---------------------------------------------------------------------------
# Round 13, analyzer 2: wire-protocol schema conformance (wire_schema)
# ---------------------------------------------------------------------------

def test_repo_wire_schema_conformance():
    """Every pack/unpack site in the covered modules agrees with the
    serve/wire.py table, the live constants match it, and the schema
    summary is manifest-ready."""
    finds = wire_schema.check_wire(REPO)
    assert finds == [], _fmt(finds)
    assert wire.verify_runtime() == []
    summary = wire.schema_summary()
    json.dumps(summary)
    assert [f["fmt"] for f in summary["frames"]] == ["<IBBdH", "<IBBQdddiH"]
    assert {f["name"] for f in summary["frames"]} == {"request", "reply"}


_SRC_BAD_ENCODER = """\
import struct

_LEN = struct.Struct("<I")
_REQ = struct.Struct("<IBBdI")
"""


def test_wire_detects_mismatched_encoder():
    """The deliberately mismatched encoder: _REQ widened its count field
    (H -> I) without touching the schema table — the drift one peer
    ships and the other cannot parse."""
    finds = wire_schema.check_source(_SRC_BAD_ENCODER, "enc.py")
    assert [f.rule for f in finds] == ["wire-format-mismatch"]
    assert "_REQ" in finds[0].message and "<IBBdH" in finds[0].message
    fixed = _SRC_BAD_ENCODER.replace("<IBBdI", "<IBBdH")
    assert wire_schema.check_source(fixed, "enc.py") == []


def test_wire_detects_unregistered_and_tag_drift():
    src = ("import struct\n"
           "_SNEAK = struct.Struct(\"<QQ\")\n"
           "n = struct.calcsize(\"<QQ\")\n"
           "TAG_TRACE = 9\n"
           "TAG_NEW = 1\n"
           "TAG_DUP = 1\n")
    rules = sorted(f.rule for f in wire_schema.check_source(src, "m.py"))
    assert rules == ["wire-tag-dup", "wire-tag-mismatch",
                     "wire-unregistered-format", "wire-unregistered-format",
                     "wire-unregistered-tag", "wire-unregistered-tag"]


def test_wire_ext_parser_total_static_and_dynamic():
    """The optional-extension parser must be TOTAL — statically (no
    raise, every unpack length-guarded) and dynamically (exhaustive
    truncation + byte-flip sweep over the live function)."""
    raising = ("def unpack_ext(buf):\n"
               "    if len(buf) < 2:\n"
               "        raise ValueError('short')\n"
               "    return {}\n")
    finds = wire_schema.check_ext_parser_total(raising, "t.py")
    assert [f.rule for f in finds] == ["wire-ext-raise"]
    unguarded = ("def unpack_ext(buf):\n"
                 "    tag, n = _TLV_HEAD.unpack_from(buf, 0)\n"
                 "    return {tag: n}\n")
    finds = wire_schema.check_ext_parser_total(unguarded, "t.py")
    assert [f.rule for f in finds] == ["wire-ext-unguarded"]
    assert wire_schema.ext_parse_corruption_sweep() == []


# ---------------------------------------------------------------------------
# Round 13, analyzer 3: static host-round-trip certifier (dispatch)
# ---------------------------------------------------------------------------

def test_round_trip_closed_form():
    b = dispatchlib.epoch_round_trip_bound
    assert b("step", 25) == 25
    assert b("step", 25, include_eval=True) == 26
    assert b("window", 25, 20) == 2
    assert b("window", 25, 20, include_eval=True) == 3
    assert b("window", 25, 5) == 5
    assert b("host_window", 7, 3, tail_batch=True) == 4
    assert b("eval", 2) == 1 and b("eval", 0) == 0
    with pytest.raises(ValueError, match="bad bound query"):
        b("window", 5)             # windowed path needs a window
    with pytest.raises(ValueError, match="bad bound query"):
        b("step", -1)
    with pytest.raises(ValueError, match="unknown dispatch path"):
        b("warp", 5)


def test_dispatch_seeded_violations():
    """Each certificate rule catches its seeded regression: a windowed
    program that lowered straight-line, one scanning a different window
    than the trainer dispatches, and one that stopped donating."""
    flat = dispatchlib.ProgramCert("train/window/ddp", "window", (), 3)
    assert [f.rule for f in dispatchlib.check_cert(flat)] \
        == ["dispatch-no-scan"]
    drift = dispatchlib.ProgramCert("train/window/ddp", "window", (4,), 3)
    assert [f.rule for f in dispatchlib.check_cert(drift, expect_window=3)] \
        == ["dispatch-window-mismatch"]
    bounce = dispatchlib.ProgramCert("train/window/ddp", "window", (3,), 0)
    assert [f.rule for f in dispatchlib.check_cert(bounce, expect_window=3)] \
        == ["dispatch-donation-zero"]
    good = dispatchlib.ProgramCert("train/window/ddp", "window", (3, 4), 3)
    assert dispatchlib.check_cert(good, expect_window=3) == []
    assert good.window == 4 and flat.window is None


def test_zoo_dispatch_certificate(zoo):
    """The certificate over the real lowered zoo: every windowed program
    scans the dispatched window and donates; the closed-form bounds are
    recorded per program."""
    cert = dispatchlib.certify_zoo(zoo, window=3, nbatches=25)
    assert cert["clean"], json.dumps(cert["findings"], indent=2)
    progs = cert["programs"]
    assert set(progs) == set(zoo.hlo)
    win = progs["train/window/ddp"]
    assert win["path"] == "window" and win["donated"] > 0
    assert win["epoch_round_trips"] == dispatchlib.epoch_round_trip_bound(
        "window", 25, 3, include_eval=True) == 10
    assert progs["train/step/ddp"]["epoch_round_trips"] == 26
    assert progs["eval/window"]["path"] == "eval"
    assert "epoch_round_trips" not in progs["eval/window"]
    assert progs["serve/b2/f32"]["path"] == "serve"
    json.dumps(cert)
    with pytest.raises(ValueError, match="collect_hlo"):
        dispatchlib.certify_zoo(types.SimpleNamespace(hlo={}),
                                window=3, nbatches=25)


def _trip_trainer(tmp_path, mesh4, telemetry, **kw):
    return Trainer(model=tiny_cnn(), strategy="ddp", mesh=mesh4,
                   global_batch=64, data_dir=str(tmp_path), augment=False,
                   limit_train_batches=25, limit_eval_batches=2,
                   log=lambda s: None, telemetry=telemetry, **kw)


def test_static_round_trip_bound_matches_runtime_exactly(tmp_path, mesh4):
    """ISSUE 13's acceptance bar: the static closed form equals the live
    ``host_round_trips`` counter EXACTLY on all three dispatch paths —
    ring-buffer windowed, plain windowed, and per-step."""
    from cs744_ddp_tpu.utils.metrics import WINDOW
    nbatches = 25
    windowed = dispatchlib.epoch_round_trip_bound(
        "window", nbatches, WINDOW, include_eval=True)

    tel = Telemetry()
    tr = _trip_trainer(tmp_path, mesh4, tel, metrics_ring=WINDOW)
    tr.train_model(0)
    tr.test_model()
    assert dispatchlib.total_runtime_trips(tel.records) == windowed == 3
    assert dispatchlib.count_runtime_trips(tel.records) \
        == {"window_drain": 2, "eval": 1}

    tel = Telemetry()
    tr = _trip_trainer(tmp_path, mesh4, tel, metrics_ring=0)
    tr.train_model(0)
    tr.test_model()
    assert dispatchlib.total_runtime_trips(tel.records) == windowed == 3
    assert dispatchlib.count_runtime_trips(tel.records) \
        == {"window_fetch": 2, "eval": 1}

    tel = Telemetry()
    tr = _trip_trainer(tmp_path, mesh4, tel, profile_phases=True)
    tr.train_model(0)
    tr.test_model()
    per_step = dispatchlib.epoch_round_trip_bound(
        "step", nbatches, include_eval=True)
    assert dispatchlib.total_runtime_trips(tel.records) == per_step == 26
    sites = dispatchlib.count_runtime_trips(tel.records)
    assert sites["step_fetch"] == 25 and sites["eval"] == 1


# ---------------------------------------------------------------------------
# Round 13 tentpole gate: the one tier-1 test CI pins everything on
# ---------------------------------------------------------------------------

def test_repo_static_verification(zoo):
    """Folds --audit-zoo, the repo lints, and the whole-program
    analyzers (lock order, wire schema, memory single-source + fixture
    invariants) into one gate — what ``--verify-static`` runs from the
    CLI, asserted here as a tier-1 test."""
    findings = pylint_rules.lint_paths(
        [os.path.join(REPO, t) for t in pylint_rules.DEFAULT_TARGETS])
    findings += lockgraph.check_locks(REPO)
    findings += wire_schema.check_wire(REPO)
    findings += memlife.check_memory(REPO)
    assert findings == [], _fmt(findings)
    assert zoo.clean, "\n".join(zoo.format_lines())
    cert = dispatchlib.certify_zoo(zoo, window=3, nbatches=25)
    assert cert["clean"], json.dumps(cert["findings"], indent=2)


# ---------------------------------------------------------------------------
# Round 14: fused-ingest edge rule, async-dispatch lint, serving-scan cert
# ---------------------------------------------------------------------------

_U8_RUNG = """\
HloModule rung

ENTRY main {
  img = u8[8,32,32,3] parameter(0)
  w = f32[3072,10] parameter(1)
  f = f32[8,32,32,3] convert(img)
  r = f32[8,3072] reshape(f)
  ROOT d = f32[8,10] dot(r, w), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}
"""


def test_rule_ingest_edge_seeded():
    # A fused rung: u8 image at the edge, in-program convert -> clean.
    r = auditlib.audit_program(_U8_RUNG, _contract(u8_edge=True))
    assert r.passed, r.findings
    # Float image-shaped entry parameter: the normalize left the program
    # and the wire pays 4x.
    leaked = _U8_RUNG.replace("img = u8[8,32,32,3] parameter(0)",
                              "img = f32[8,32,32,3] parameter(0)") \
                     .replace("f = f32[8,32,32,3] convert(img)",
                              "f = f32[8,32,32,3] negate(img)")
    r = auditlib.audit_program(leaked, _contract(u8_edge=True))
    assert _rules_of(r) == {"ingest-edge"}
    assert "4x transfer" in r.findings[0].message
    # u8 image parameter but no in-program float convert: the program
    # never normalizes on device.
    raw = _U8_RUNG.replace("f = f32[8,32,32,3] convert(img)",
                           "f = f32[8,32,32,3] iota(), iota_dimension=0")
    r = auditlib.audit_program(raw, _contract(u8_edge=True))
    assert _rules_of(r) == {"ingest-edge"}
    assert "never normalizes" in r.findings[0].message
    # The rule is contract-gated: without u8_edge the same float-edge
    # program is a legitimate training lowering.
    assert auditlib.audit_program(leaked, _contract()).passed


_SRC_ASYNC_UNFENCED = """\
import time

class T:
    def run(self, x):
        t0 = time.time()
        h = self.infer_counts_async(x)
        return time.time() - t0
"""


def test_lint_unfenced_timing_async_dispatch():
    # issue-without-complete inside a timing window: the timer stops
    # before the device ran anything.
    bad = pylint_rules.lint_source(_SRC_ASYNC_UNFENCED, "bad.py")
    assert [f.rule for f in bad] == ["unfenced-timing"]
    # complete() IS the fence for the async path.
    fenced = _SRC_ASYNC_UNFENCED.replace(
        "h = self.infer_counts_async(x)",
        "h = self.infer_counts_async(x)\n        out = self.complete(h)")
    assert pylint_rules.lint_source(fenced, "ok.py") == []


def test_cert_serving_rung_straight_line():
    # The static half of the two-in-flight bound: a serving rung that
    # lowers to a scan would host-sync inside the program.
    cert = dispatchlib.ProgramCert(program="serve/b8/f32", path="serve",
                                   scan_trips=(3,), donated=0)
    rules = [f.rule for f in dispatchlib.check_cert(cert)]
    assert rules == ["dispatch-serving-scan"]
    clean = dispatchlib.ProgramCert(program="serve/b8/f32", path="serve",
                                    scan_trips=(), donated=0)
    assert dispatchlib.check_cert(clean) == []
    # Static bound == scheduler constant == arena depth.
    from cs744_ddp_tpu.serve import PIPELINE_SLOTS
    assert dispatchlib.serving_inflight_bound() == PIPELINE_SLOTS == 2
    # Runtime half: occupancy scan over telemetry gauge records.
    recs = [{"kind": "gauge", "name": "serve_inflight", "value": v}
            for v in (1, 2, 1, 0)]
    recs.append({"kind": "gauge", "name": "other", "value": 9})
    assert dispatchlib.max_serving_inflight(recs) == 2
    assert dispatchlib.max_serving_inflight([]) == 0
