"""Structured telemetry subsystem (obs/): recorder, summary math, wiring.

Covers the whole contract the subsystem makes:

  * ``percentile`` / ``summarize_events`` against hand-computed values;
  * JSONL schema round-trip through a file-backed run directory
    (manifest.json / events.jsonl / summary.json);
  * span nesting, thread-local span stacks (the host-augment producer
    thread), and error capture;
  * the disabled path: ``NULL`` makes ZERO file writes and cannot
    accumulate per-step state (``__slots__ = ()``);
  * ``WindowedTimers`` emits step events ALONGSIDE the reference-parity
    print schedule, never instead of it;
  * Trainer wiring: manifest fields, compile_warmup/eval spans, collective
    counters, epoch gauges, host-augment pipeline spans and queue gauge;
  * the CLI ``--telemetry-out`` flag end to end, with the summary
    recomputed from the raw events and compared to summary.json;
  * the native-loader failure path surfacing in ``load_error()`` (what the
    manifest records);
  * tools/telemetry_report.py rendering, including the interrupted-run
    (no summary.json) recompute path.
"""

import builtins
import json
import os
import re
import threading

import pytest

from cs744_ddp_tpu import cli
from cs744_ddp_tpu.obs import (NULL, NullTelemetry, Telemetry, git_sha,
                               percentile, read_run, summarize_events)
from cs744_ddp_tpu.obs.telemetry import _NULL_SPAN
from cs744_ddp_tpu.train.loop import Trainer
from cs744_ddp_tpu.utils.metrics import WindowedTimers

from tinynet import tiny_cnn


# -- percentile / summary math ------------------------------------------------

def test_percentile_hand_computed():
    xs = [4.0, 9.0, 1.0, 6.0, 10.0, 3.0, 7.0, 2.0, 8.0, 5.0]  # shuffled 1..10
    assert percentile(xs, 50) == pytest.approx(5.5)
    assert percentile(xs, 95) == pytest.approx(9.55)
    assert percentile(xs, 99) == pytest.approx(9.91)
    assert percentile(xs, 0) == 1.0
    assert percentile(xs, 100) == 10.0
    assert percentile([7.25], 95) == 7.25          # single sample
    with pytest.raises(ValueError):
        percentile([], 50)


def test_summarize_events_hand_computed():
    steady = [i / 1000.0 for i in range(1, 11)]    # 1..10 ms
    events = []
    for i, t in enumerate(steady):
        events.append({"kind": "step", "epoch": 0, "iter": i + 21,
                       "loss": float(i), "step_time_s": t, "steady": True})
    # Warmup steps: counted in num_steps and losses, NOT in steady stats.
    events.append({"kind": "step", "epoch": 0, "iter": 1, "loss": 99.0,
                   "step_time_s": 5.0, "steady": False})
    events.append({"kind": "span", "name": "host_augment", "dur_s": 0.5})
    events.append({"kind": "span", "name": "host_augment", "dur_s": 0.25})
    events.append({"kind": "counter", "name": "c", "inc": 2, "total": 2})
    events.append({"kind": "counter", "name": "c", "inc": 3, "total": 5})

    s = summarize_events(events, global_batch=64, note="extra-field")
    assert s["num_events"] == len(events)
    assert s["num_steps"] == 11
    assert s["num_steady_steps"] == 10
    stt = s["steady_step_time_s"]
    assert stt["p50"] == pytest.approx(0.0055)
    assert stt["p95"] == pytest.approx(0.00955)
    assert stt["p99"] == pytest.approx(0.00991)
    assert stt["min"] == 0.001 and stt["max"] == 0.010
    assert stt["mean"] == pytest.approx(sum(steady) / 10)
    assert s["steady_images_per_sec"] == \
        pytest.approx(64 * 10 / sum(steady))
    assert s["final_loss"] == 99.0                 # last step RECORDED
    assert s["mean_loss"] == pytest.approx((sum(range(10)) + 99.0) / 11)
    assert s["spans"]["host_augment"] == {"count": 2, "total_s": 0.75}
    assert s["counters"]["c"] == 5                 # final total, not the sum
    assert s["global_batch"] == 64 and s["note"] == "extra-field"


# -- recorder: file round-trip, spans, null path ------------------------------

def test_file_backed_round_trip(tmp_path):
    d = str(tmp_path / "run")
    tel = Telemetry(d)
    tel.write_manifest({"model": "tiny", "strategy": "ddp"})
    tel.step(epoch=0, iter=1, loss=2.5, step_time=0.01, steady=False)
    tel.step(epoch=0, iter=2, loss=1.5, step_time=0.02,
             forward_time=0.008, steady=True)
    tel.gauge("queue_depth", 3, window=1)
    tel.counter("bytes", inc=10)
    tel.counter("bytes", inc=5)
    with tel.span("eval"):
        pass
    summary = tel.finalize(global_batch=8)

    manifest, events, read_summary = read_run(d)
    assert manifest["schema_version"] == 1
    assert manifest["model"] == "tiny" and manifest["strategy"] == "ddp"
    assert "created_at" in manifest
    assert read_summary == summary

    # One JSON object per line, schema keys per kind.
    with open(os.path.join(d, "events.jsonl")) as f:
        lines = [json.loads(l) for l in f]
    assert lines == events
    by_kind = {}
    for e in events:
        by_kind.setdefault(e["kind"], []).append(e)
    assert {k: len(v) for k, v in by_kind.items()} == \
        {"step": 2, "gauge": 1, "counter": 2, "span": 1}
    for e in by_kind["step"]:
        assert {"t", "epoch", "iter", "loss", "step_time_s",
                "steady"} <= e.keys()
    assert by_kind["step"][1]["forward_time_s"] == 0.008
    assert by_kind["gauge"][0] == {"kind": "gauge", "name": "queue_depth",
                                   "t": by_kind["gauge"][0]["t"], "value": 3,
                                   "window": 1}
    assert [c["total"] for c in by_kind["counter"]] == [10, 15]
    assert {"name", "t", "dur_s", "depth"} <= by_kind["span"][0].keys()

    assert summary["num_steps"] == 2 and summary["num_steady_steps"] == 1
    assert summary["counters"] == {"bytes": 15}
    assert summary["steady_step_time_s"]["p50"] == 0.02


def test_span_nesting_and_thread_local_stack():
    tel = Telemetry()                               # in-memory
    with tel.span("outer"):
        # Producer-thread spans must not inherit the main thread's stack.
        def worker():
            with tel.span("worker"):
                pass
        t = threading.Thread(target=worker)
        t.start()
        t.join()
        with tel.span("inner", window=3):
            pass
    recs = {r["name"]: r for r in tel.records if r["kind"] == "span"}
    assert recs["worker"]["depth"] == 0
    assert "parent" not in recs["worker"]
    assert recs["inner"]["depth"] == 1
    assert recs["inner"]["parent"] == "outer"
    assert recs["inner"]["window"] == 3            # attrs pass through
    assert recs["outer"]["depth"] == 0
    assert all(r["dur_s"] >= 0 for r in recs.values())


def test_span_records_error_and_reraises():
    tel = Telemetry()
    with pytest.raises(ValueError):
        with tel.span("boom"):
            raise ValueError("x")
    (rec,) = tel.records
    assert rec["error"] == "ValueError"


def test_null_recorder_makes_no_writes_and_holds_no_state(monkeypatch):
    assert isinstance(NULL, NullTelemetry)
    assert NULL.enabled is False
    assert NullTelemetry.__slots__ == ()
    # No attribute can ever be attached -> per-step state CANNOT grow.
    with pytest.raises(AttributeError):
        NULL.records = []

    opened = []
    real_open = builtins.open
    monkeypatch.setattr(builtins, "open",
                        lambda *a, **k: (opened.append(a),
                                         real_open(*a, **k))[1])
    for _ in range(50):
        NULL.step(epoch=0, iter=1, loss=1.0, step_time=0.1)
        NULL.gauge("g", 1)
        NULL.counter("c")
        with NULL.span("s"):
            pass
    NULL.write_manifest({"model": "x"})
    assert NULL.finalize(global_batch=64) is None
    assert opened == []                            # zero file writes
    # The span context manager is a shared singleton — no per-call alloc.
    assert NULL.span("a") is NULL.span("b") is _NULL_SPAN
    # The chunked-staging spans ride the same path: attrs must not force
    # an allocation either (the producer thread calls these per chunk).
    assert NULL.span("chunk_put", batches=3, last=True) is _NULL_SPAN
    assert NULL.span("chunk_wait") is _NULL_SPAN
    NULL.gauge("window_chunks_pending", 2)         # still zero writes
    assert opened == []


def test_git_sha_returns_repo_head():
    sha = git_sha(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert sha is None or re.fullmatch(r"[0-9a-f]{40}", sha)
    assert git_sha("/") is None or isinstance(git_sha("/"), str)


# -- WindowedTimers: events alongside the parity prints -----------------------

def test_windowed_timers_emit_alongside_unchanged_prints():
    def drive(timers):
        for i in range(45):
            timers.record(0.5 + i, 0.01, 0.004)
        timers.record(99.0, 0.20, steady=False)    # ragged-tail sample

    plain_lines, tel_lines = [], []
    drive(WindowedTimers(plain_lines.append))
    tel = Telemetry()
    drive(WindowedTimers(tel_lines.append, telemetry=tel, epoch=2))

    # The parity surface: the print schedule is IDENTICAL with telemetry on.
    assert tel_lines == plain_lines
    assert any("Training loss after 20 iterations is" in l
               for l in plain_lines)

    steps = [r for r in tel.records if r["kind"] == "step"]
    assert len(steps) == 46
    assert [s["iter"] for s in steps] == list(range(1, 47))
    assert all(s["epoch"] == 2 for s in steps)
    # Steady flag mirrors the timers' own warmup/steady rules exactly.
    assert all(not s["steady"] for s in steps[:20])
    assert all(s["steady"] for s in steps[20:45])
    assert not steps[45]["steady"]
    assert steps[0]["forward_time_s"] == 0.004
    assert "forward_time_s" not in steps[45]


# -- Trainer wiring -----------------------------------------------------------

def _normalize(lines):
    """Blank out wall-clock values — the only nondeterministic content in
    the reference print schedule (loss lines are seed-deterministic)."""
    return [re.sub(r"is [0-9.e+-]+$", "is <t>", l) if "time" in l else l
            for l in lines]


def test_trainer_stdout_parity_and_event_stream(tmp_path, mesh4):
    def run(telemetry):
        lines = []
        tr = Trainer(model=tiny_cnn(), strategy="allreduce", mesh=mesh4,
                     global_batch=64, data_dir=str(tmp_path), augment=False,
                     limit_train_batches=25, limit_eval_batches=2,
                     log=lines.append, telemetry=telemetry)
        tr.run(1)
        return lines

    plain = run(NULL)
    tel = Telemetry()
    instrumented = run(tel)
    # Byte-identical print schedule modulo wall-clock values.
    assert _normalize(instrumented) == _normalize(plain)

    recs = tel.records
    steps = [r for r in recs if r["kind"] == "step"]
    assert len(steps) == 25
    assert [s["iter"] for s in steps] == list(range(1, 26))
    span_names = {r["name"] for r in recs if r["kind"] == "span"}
    assert "compile_warmup" in span_names
    assert "eval" in span_names
    gauge_names = {r["name"] for r in recs if r["kind"] == "gauge"}
    assert "epoch_time_s" in gauge_names
    # Static collective telemetry from the lowered step (emitted once).
    counter_names = {r["name"] for r in recs if r["kind"] == "counter"}
    assert any(n.startswith("collective_") for n in counter_names) or \
        "collective_stats_error" in gauge_names

    man = tel.manifest
    assert man["strategy"] == "allreduce"
    assert man["world_size"] == 4
    assert man["global_batch"] == 64
    assert set(man["native_loader"]) == {"available", "error"}
    for key in ("model", "jax_version", "backend", "device_kind",
                "precision", "git_sha", "seed"):
        assert key in man

    summary = tel.finalize(global_batch=64)
    assert summary["num_steps"] == 25
    assert 0 < summary["num_steady_steps"] <= 5    # beyond the warmup window
    stt = summary["steady_step_time_s"]
    assert stt["min"] <= stt["p50"] <= stt["p95"] <= stt["p99"] <= stt["max"]


def test_trainer_host_augment_pipeline_telemetry(tmp_path, mesh4):
    tel = Telemetry()
    tr = Trainer(model=tiny_cnn(), strategy="allreduce", mesh=mesh4,
                 global_batch=64, data_dir=str(tmp_path), augment=True,
                 host_augment=True, limit_train_batches=4,
                 log=lambda s: None, telemetry=tel)
    tr.train_model(0)
    spans = [r for r in tel.records if r["kind"] == "span"]
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)
    # Producer-thread work is visible: the stochastic transform and the
    # per-chunk device puts (chunk_put superseded prefetch_put for staged
    # full batches when staging went chunked; prefetch_put remains on the
    # per-step tail path only).
    assert by_name["host_augment"]
    assert by_name["chunk_put"]
    assert all(s["batches"] >= 1 for s in by_name["chunk_put"])
    assert any(s["last"] for s in by_name["chunk_put"])  # window boundary
    # The producer thread has its own span stack: these are top-level.
    assert all(s["depth"] == 0 for s in by_name["host_augment"])
    assert all(s["depth"] == 0 for s in by_name["chunk_put"])
    # Consumer-side stall probe + pipeline gauges.
    assert by_name["chunk_wait"]
    depths = [r["value"] for r in tel.records
              if r["kind"] == "gauge" and r["name"] == "prefetch_queue_depth"]
    assert depths and all(d >= 0 for d in depths)
    pending = [r["value"] for r in tel.records
               if r["kind"] == "gauge" and r["name"] == "window_chunks_pending"]
    assert pending and all(p >= 1 for p in pending)


# -- CLI end to end -----------------------------------------------------------

def test_cli_telemetry_out_end_to_end(tmp_path, capsys, mesh4):
    """The acceptance path: a --telemetry-out run writes all three
    artifacts; the summary is exactly recomputable from the raw events; the
    reference-parity stdout schedule is unchanged."""
    out = str(tmp_path / "tel")
    cli.main(["--strategy", "ddp", "--model", "vgg11",
              "--batch-size", "64", "--num-devices", "4",
              "--epochs", "1", "--data-dir", str(tmp_path),
              "--limit-train-batches", "3", "--limit-eval-batches", "2",
              "--no-augment", "--telemetry-out", out])
    stdout = capsys.readouterr().out
    # The parity schedule — same asserts as the non-telemetry smoke test.
    assert "Size of training set is 782" in stdout
    assert "Training time after 1 epoch is" in stdout
    assert "Test set: Average loss:" in stdout
    assert out not in stdout                       # recorder prints nothing

    assert sorted(os.listdir(out)) == ["events.jsonl", "manifest.json",
                                       "summary.json"]
    manifest, events, summary = read_run(out)
    assert manifest["model"] == "vgg11"
    assert manifest["strategy"] == "ddp"
    assert manifest["world_size"] == 4
    assert manifest["global_batch"] == 64
    assert manifest["schema_version"] == 1

    kinds = {e["kind"] for e in events}
    assert kinds <= {"step", "span", "gauge", "counter"}
    steps = [e for e in events if e["kind"] == "step"]
    assert [s["iter"] for s in steps] == [1, 2, 3]
    assert all(s["epoch"] == 0 for s in steps)

    # summary.json is a pure function of the event log — recompute and
    # compare EXACTLY (percentile math included).
    assert summarize_events(events, global_batch=64) == summary


# -- native loader failure path (what the manifest surfaces) ------------------

def test_native_load_error_is_captured_and_warned(monkeypatch, tmp_path):
    from cs744_ddp_tpu.data import native
    monkeypatch.setattr(native, "_SO_PATH", str(tmp_path / "nope.so"))
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_load_attempted", False)
    monkeypatch.setattr(native, "_load_error", None)
    with pytest.warns(RuntimeWarning, match="native host loader unavailable"):
        assert native.load_library(build=False) is None
    assert native.available() is False
    assert "OSError" in native.load_error()
    # NumPy fallback still serves the data path while degraded.
    import numpy as np
    ds = np.arange(2 * 32 * 32 * 3, dtype=np.uint8).reshape(2, 32, 32, 3)
    np.testing.assert_array_equal(native.gather(ds, np.array([1, 0])),
                                  ds[[1, 0]])


# -- report tool --------------------------------------------------------------

def _make_run_dir(tmp_path):
    d = str(tmp_path / "run")
    tel = Telemetry(d)
    tel.write_manifest({"model": "tiny", "strategy": "ddp", "world_size": 4,
                        "global_batch": 64,
                        "native_loader": {"available": True, "error": None}})
    for i in range(1, 24):
        tel.step(epoch=0, iter=i, loss=2.0 / i, step_time=0.01,
                 steady=i > 20)
    tel.gauge("prefetch_queue_depth", 2)
    tel.counter("collective_all-reduce_count", 34)
    with tel.span("eval"):
        pass
    tel.finalize(global_batch=64)
    return d


def test_telemetry_report_renders_run_dir(tmp_path, monkeypatch, capsys):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    monkeypatch.syspath_prepend(os.path.join(repo, "tools"))
    import telemetry_report

    d = _make_run_dir(tmp_path)
    text = telemetry_report.render(d)
    assert "== run manifest ==" in text
    assert "tiny" in text and "ddp" in text
    assert "native_loader" in text and "available" in text
    assert "23 (3 steady)" in text
    assert "eval" in text
    assert "collective_all-reduce_count" in text
    assert "prefetch_queue_depth" in text

    assert telemetry_report.main([d, "--json"]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["num_steps"] == 23

    # Interrupted run: no summary.json — the report recomputes from events.
    os.remove(os.path.join(d, "summary.json"))
    text = telemetry_report.render(d)
    assert "23 (3 steady)" in text
    assert telemetry_report.main([d, "--json"]) == 0
    reparsed = json.loads(capsys.readouterr().out)
    assert reparsed["num_steady_steps"] == 3
    assert reparsed["global_batch"] == 64          # pulled from the manifest
