"""Time isolated pieces of the train step to find the fixed per-step cost.

Builder's tool (see tools/perf_attribution.py).  The tunneled TPU backend
has ~90 ms per-dispatch latency, so each piece is measured INSIDE one
compiled program: ``lax.scan`` chains K iterations of the piece (outputs
feed the carry so nothing is DCE'd), and the per-iteration time is the
fenced dispatch time / K, with the scan's own overhead calibrated out by a
null scan.  Headline config: VGG-11, f32, batch 256, one chip.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

K = 100     # scan iterations per dispatch
R = 3       # dispatches (first excluded as warmup)


def bench(make_scanned, *args):
    import jax
    import numpy as np
    fn = jax.jit(make_scanned)
    out = fn(*args)
    np.asarray(jax.tree.leaves(out)[0])          # compile+warm fence
    times = []
    for _ in range(R):
        t0 = time.time()
        out = fn(*args)
        np.asarray(jax.tree.leaves(out)[0])      # value-fetch fence
        times.append(time.time() - t0)
    return min(times) / K * 1e3


def main():
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax import lax

    from cs744_ddp_tpu.data import augment as aug
    from cs744_ddp_tpu.models import vgg
    from cs744_ddp_tpu.ops import sgd
    from cs744_ddp_tpu.ops.loss import cross_entropy
    from cs744_ddp_tpu.utils.compcache import \
        enable_persistent_compilation_cache

    enable_persistent_compilation_cache(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    B = 256
    params, bn_state = vgg.init(jax.random.PRNGKey(0), "VGG11")
    opt = sgd.init(params)
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.integers(0, 256, (B, 32, 32, 3)), jnp.uint8)
    labels = jnp.asarray(rng.integers(0, 10, (B,)), jnp.int32)
    x = jnp.asarray(rng.normal(size=(B, 32, 32, 3)), jnp.float32)
    key = jax.random.PRNGKey(1)

    def scan_of(body, carry):
        def scanned(carry, *consts):
            def one(c, i):
                return body(c, i, *consts), ()
            c, _ = lax.scan(one, carry, jnp.arange(K))
            return c
        return scanned, carry

    def null_body(c, i):
        return c + 1.0

    def full_body(carry, i, images, labels):
        params, bn_state, opt = carry
        k = jax.random.fold_in(key, i)
        xx = aug.augment(k, images)

        def loss_fn(p):
            logits, nb = vgg.apply(p, bn_state, xx, train=True)
            return cross_entropy(logits, labels), nb

        (loss, nb), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        np_, no = sgd.update(params, grads, opt, sgd.SGDConfig())
        return (np_, nb, no)

    def fwd_bwd_body(carry, i, xx, labels):
        params, bn_state = carry

        def loss_fn(p):
            logits, nb = vgg.apply(p, bn_state, xx, train=True)
            return cross_entropy(logits, labels), nb

        (loss, nb), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        # feed a scaled grad back so the chain is sequential, magnitude ~0
        params = jax.tree.map(lambda p, g: p + 0.0 * g, params, grads)
        return (params, nb)

    def fwd_body(carry, i, xx, labels):
        params, bn_state = carry
        logits, nb = vgg.apply(params, bn_state, xx, train=True)
        return (jax.tree.map(
            lambda p: p + 0.0 * jnp.sum(logits), params), nb)

    def sgd_body(carry, i, grads):
        params, opt = carry
        np_, no = sgd.update(params, grads, opt, sgd.SGDConfig())
        return (np_, no)

    def aug_body(carry, i, images):
        k = jax.random.fold_in(key, i)
        xx = aug.augment(k, images)
        return carry + jnp.sum(xx)

    grads = jax.jit(lambda p, s, xx, y: jax.grad(
        lambda pp: cross_entropy(vgg.apply(pp, s, xx, train=True)[0], y))(p))(
        params, bn_state, x, labels)
    jax.block_until_ready(grads)

    null_ms = bench(*scan_of(null_body, jnp.float32(0.0)))
    print(f"null scan        {null_ms:7.3f} ms/iter")

    fn, carry = scan_of(full_body, (params, bn_state, opt))
    print(f"full step        {bench(fn, carry, images, labels) - null_ms:7.3f} ms/iter")
    fn, carry = scan_of(fwd_bwd_body, (params, bn_state))
    print(f"fwd+bwd          {bench(fn, carry, x, labels) - null_ms:7.3f} ms/iter")
    fn, carry = scan_of(fwd_body, (params, bn_state))
    print(f"fwd (train BN)   {bench(fn, carry, x, labels) - null_ms:7.3f} ms/iter")
    fn, carry = scan_of(sgd_body, (params, opt))
    print(f"sgd update       {bench(fn, carry, grads) - null_ms:7.3f} ms/iter")
    fn, carry = scan_of(aug_body, jnp.float32(0.0))
    print(f"augment          {bench(fn, carry, images) - null_ms:7.3f} ms/iter")


if __name__ == "__main__":
    main()
