"""Reproduce the BASELINE.md forward/backward split artifact.

The reference times forward and backward+sync+step separately
(``/root/reference/src/Part 1/main.py:33-43``).  On the tunneled TPU
backend a per-step timer measures ~100 ms of dispatch latency, so the
honest split is ``Trainer.measure_phase_split``'s two-window-size slope
(see its docstring).  This tool runs the committed table's measurement
configuration (VGG-11, f32, batch 256, W=100, 3 interleaved windows),
prints one JSON line per trial to stderr, and emits the across-trials
slope (mins over every trial's window totals) as the final stdout line —
the statistic BASELINE.md records.

Run:  python tools/perf_phase_split.py [--model vgg11] [--trials 3]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="vgg11")
    p.add_argument("--global-batch", type=int, default=256)
    p.add_argument("--window-iters", type=int, default=100)
    p.add_argument("--windows", type=int, default=3)
    # 3 trials: the tunnel's per-dispatch latency wobbles by tens of ms,
    # and a single wobble among one trial's six window totals visibly
    # skews a lone within-trial slope (observed); three trials of mins
    # pin the across-trials slope to ~1% of the perf_pieces cross-check.
    p.add_argument("--trials", type=int, default=3)
    args = p.parse_args(argv)
    if args.trials < 1:
        p.error("--trials must be >= 1")

    from cs744_ddp_tpu.train.loop import Trainer
    from cs744_ddp_tpu.utils.compcache import \
        enable_persistent_compilation_cache

    enable_persistent_compilation_cache(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    trainer = Trainer(model=args.model, strategy="single", num_devices=1,
                      global_batch=args.global_batch,
                      data_dir=os.environ.get("CIFAR_DATA_DIR", "./data"),
                      log=lambda s: None)
    best = {}
    w = half = None
    for _ in range(args.trials):
        split = trainer.measure_phase_split(
            window_iters=args.window_iters, windows=args.windows)
        w, half = split["window_iters"], split["window_iters"] // 2
        for k, v in split["window_totals_ms"].items():
            best[k] = min(best.get(k, float("inf")), v)
        print(json.dumps({k: round(v, 4) if isinstance(v, float) else v
                          for k, v in split.items() if k != "window_totals_ms"},
                         ), file=sys.stderr)
    # Across-trials slope: mins over every trial's windows — one contended
    # half-window min within a single trial cannot skew this estimate.
    span = w - half
    fwd = (best[f"fwd_{w}"] - best[f"fwd_{half}"]) / span
    step = (best[f"step_{w}"] - best[f"step_{half}"]) / span
    print(json.dumps({"model": args.model, "protocol":
                      f"two-size slope W={w}/{half}, "
                      f"best of {args.trials}x{args.windows} windows",
                      "forward_ms_per_iter": round(fwd, 4),
                      "backward_ms_per_iter": round(step - fwd, 4),
                      "step_ms_per_iter": round(step, 4)}))


if __name__ == "__main__":
    main()
