"""Merge N telemetry run directories into cross-process latency waterfalls.

Each process in a traced serving run (``--trace`` on the server, the
client, the load generator) writes spans carrying ``trace_id`` /
``span_id`` / ``parent_span_id`` into its OWN ``events.jsonl``.  This
tool stitches them back together (``cs744_ddp_tpu/obs/aggregate.py``):
clock skew per process via the NTP midpoint method (error bounded by
half the measured round trip), per-request stage waterfalls (wire
decode, queue wait, admit deferral, staging, device compute, fetch,
reply encode), per-stage p50/p99 attribution, critical-path shares, and
— with ``--prior-flops`` — the device-compute stage measured against
the HLO cost-model prior.

Pure python over jsonl: safe to run on a machine with no jax installed.

Run:  python tools/trace_waterfall.py RUN_DIR [RUN_DIR ...]
          [--json] [--reference NAME] [--max-waterfalls N]
          [--prior-flops FILE.json]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cs744_ddp_tpu.obs import aggregate as agg  # noqa: E402

_BAR_WIDTH = 40


def _bars(stages: dict) -> list:
    """One waterfall's stages as proportional ASCII bars."""
    total = sum(stages.values()) or 1e-9
    lines = []
    for stage in agg.STAGE_ORDER:
        if stage not in stages:
            continue
        ms = stages[stage]
        n = max(1, int(round(_BAR_WIDTH * ms / total)))
        lines.append(f"    {stage:<16} {'#' * n:<{_BAR_WIDTH}} "
                     f"{ms:9.3f} ms")
    return lines


def render(report: dict) -> str:
    lines = ["cross-process trace waterfall", ""]
    lines.append("== processes ==")
    for name, p in sorted(report["processes"].items()):
        if name == report.get("reference"):
            skew = "reference clock"
        elif p["skew_estimated"]:
            skew = (f"offset {p['clock_offset_s'] * 1e3:+.3f} ms "
                    f"(+/- {p['rtt_bound_s'] * 1e3:.3f} ms, "
                    f"{p['skew_pairs']} pairs)")
        else:
            skew = "no skew estimate (no matched request pairs)"
        bad = f"  !! {p['bad_lines']} bad lines" if p["bad_lines"] else ""
        lines.append(f"  {name:<20} {p['events']:>7} events  {skew}{bad}")
    lines.append("")

    lines.append(f"== traces ==")
    lines.append(f"  reconstructed          {report['traces']} "
                 f"({report['complete']} complete, "
                 f"{report['orphaned']} orphaned/partial)")
    res = report.get("client_minus_stages_ms")
    if res:
        lines.append(f"  client - stage sum     p50 {res['p50']:+.3f} ms  "
                     f"p99 {res['p99']:+.3f} ms "
                     f"(wire + scheduling residual)")
    lines.append("")

    if report["stage_ms"]:
        lines.append("== stage attribution (all traces) ==")
        for stage, a in report["stage_ms"].items():
            lines.append(f"  {stage:<16} x{a['count']:<6} "
                         f"p50 {a['p50']:8.3f} ms  "
                         f"p99 {a['p99']:8.3f} ms  "
                         f"mean {a['mean']:8.3f} ms")
        dom = report["critical_path"].get("dominant")
        if dom:
            share = report["critical_path"]["share"].get(dom)
            lines.append(f"  critical path          {dom} "
                         f"({share:.0%} of stage time)")
        lines.append("")

    prior = report.get("cost_prior")
    if prior:
        lines.append("== device compute vs cost-model prior ==")
        for b, rec in prior["by_bucket"].items():
            ratio = rec["measured_over_prior"]
            lines.append(f"  bucket {b:<6} measured p50 "
                         f"{rec['measured_ms_p50']:8.3f} ms  prior "
                         f"{rec['prior_ms']:8.3f} ms  ratio "
                         f"{ratio if ratio is not None else '-'}")
        lines.append("")

    for w in report["waterfalls"]:
        flag = "" if w["complete"] else "  [incomplete]"
        who = ",".join(w.get("procs", []))
        lines.append(f"== waterfall trace {w['trace_id']:#x}{flag} "
                     f"({who}) ==")
        lines.extend(_bars(w["stages"]))
        tail = [f"stage sum {w['sum_ms']:.3f} ms"]
        if w.get("frontend_ms") is not None:
            tail.append(f"server window {w['frontend_ms']:.3f} ms")
        if w.get("client_ms") is not None:
            tail.append(f"client round-trip {w['client_ms']:.3f} ms")
        lines.append("    " + "  |  ".join(tail))
        lines.append("")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="merge N telemetry run dirs into cross-process "
                    "request waterfalls")
    p.add_argument("run_dirs", nargs="+",
                   help="telemetry run directories (one per process)")
    p.add_argument("--json", action="store_true",
                   help="emit the aggregation report as JSON")
    p.add_argument("--reference", default=None,
                   help="stream name (dir basename) whose clock is the "
                        "reference; default: the first with server spans")
    p.add_argument("--max-waterfalls", type=int, default=8,
                   help="individual waterfalls to render (default 8)")
    p.add_argument("--prior-flops", default=None, metavar="FILE.json",
                   help="json {bucket: flops} from the HLO cost model; "
                        "joins device compute against the analytic prior")
    args = p.parse_args(argv)
    for d in args.run_dirs:
        if not os.path.isdir(d):
            p.error(f"not a directory: {d}")
    prior = None
    if args.prior_flops:
        with open(args.prior_flops, encoding="utf-8") as f:
            prior = {int(k): float(v) for k, v in json.load(f).items()}
    report = agg.aggregate_run_dirs(
        args.run_dirs,
        warn=lambda msg: print(f"warning: {msg}", file=sys.stderr),
        reference=args.reference, prior_flops=prior,
        max_waterfalls=args.max_waterfalls)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(render(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
