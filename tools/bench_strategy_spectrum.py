"""Measure the three gradient-sync tiers' wall-clock cost spectrum.

The reference exists to show gather/scatter-via-root (Part 2a) is slower
than per-param all-reduce (Part 2b) is slower than bucketed-fused DDP
(Part 3).  On one TPU chip the collectives are trivial (world=1) and on the
CPU unit-test mesh VGG's compute drowns the comm — so this tool measures the
tiers where their *communication* patterns dominate: a parameter-heavy,
compute-light MLP (the gradient pytree is ~50 MB across many leaves) on an
8-virtual-device CPU mesh with a tiny per-device batch.  There the per-step
cost is essentially the collective pattern itself:

  * gather:    2 sequential collectives per leaf, world x gather traffic
  * allreduce: 1 all-reduce per leaf, barrier-chained
  * ddp:       1 fused variadic all-reduce per ~25 MB bucket

Run:  python tools/bench_strategy_spectrum.py [--steps 10]
Results are recorded in BASELINE.md ("Strategy cost spectrum").
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_DEVICES = 8
# Deep and narrow: ~17M params (~66 MB f32) spread over 122 leaves — the
# shape of the reference's point.  VGG-11+BN has 34 grad tensors; what DDP's
# bucketing buys is FEWER COLLECTIVE LAUNCHES over many tensors, so the
# spectrum needs a many-leaf pytree to be visible in wall-clock.
LAYERS = [3072] + [512] * 60 + [10]


def mlp_init(key):
    import jax
    import jax.numpy as jnp
    params = {"w": [], "b": []}
    for din, dout in zip(LAYERS[:-1], LAYERS[1:]):
        key, sub = jax.random.split(key)
        params["w"].append(
            jax.random.normal(sub, (din, dout), jnp.float32) / jnp.sqrt(din))
        params["b"].append(jnp.zeros((dout,), jnp.float32))
    return params, {}


def mlp_apply(params, state, x, *, train):
    import jax.numpy as jnp
    del train
    x = x.reshape(x.shape[0], -1)
    for i, (w, b) in enumerate(zip(params["w"], params["b"])):
        x = x @ w + b
        if i < len(params["w"]) - 1:
            x = jnp.maximum(x, 0)
    return x, state


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--batch-per-device", type=int, default=1)
    args = p.parse_args(argv)

    import __graft_entry__ as ge
    ge._ensure_devices(N_DEVICES)

    import numpy as np
    import jax

    from cs744_ddp_tpu.ops import sgd
    from cs744_ddp_tpu.parallel import get_strategy, mesh as meshlib
    from cs744_ddp_tpu.train import step as steplib

    mesh = meshlib.make_mesh(N_DEVICES)
    state = steplib.init_train_state(mlp_init, jax.random.PRNGKey(0))
    state = meshlib.put_global_tree(state, meshlib.replicated(mesh))

    batch = args.batch_per_device * N_DEVICES
    rng = np.random.default_rng(0)
    images = jax.device_put(
        rng.integers(0, 256, (batch, 32, 32, 3)).astype(np.uint8),
        meshlib.batch_sharding(mesh))
    labels = jax.device_put(
        rng.integers(0, 10, (batch,)).astype(np.int32),
        meshlib.batch_sharding(mesh))
    key = jax.random.PRNGKey(1)

    result = {}
    for name in ("gather", "allreduce", "ddp"):
        step = steplib.make_train_step(
            mlp_apply, get_strategy(name), mesh, sgd.SGDConfig(),
            augment=False)
        s, loss = step(state, key, images, labels)   # compile + warmup
        float(loss)
        t0 = time.time()
        for _ in range(args.steps):
            s, loss = step(s, key, images, labels)
        float(loss)                                  # value-fetch fence
        per_step_ms = (time.time() - t0) / args.steps * 1e3
        result[name] = round(per_step_ms, 2)
        print(f"{name:10s} {per_step_ms:9.2f} ms/step", file=sys.stderr)

    nleaves = len(jax.tree.leaves(state.params))
    print(json.dumps({"config": f"mlp-60x512-{nleaves}leaves/"
                                f"world{N_DEVICES}/batch{batch}/cpu-mesh",
                      "ms_per_step": result}))


if __name__ == "__main__":
    main()
