"""Host->device link-goodput floor for the chunked staging path.

Builder's tool: runs ``bench.measure_link_floor`` standalone — pure
``put_global`` of WINDOW-sized uint8 staging buffers (the exact
shape/sharding train/loop.py's producer ships) on (a) the synthetic split's
compressible bytes and (b) real-entropy CIFAR-10 bytes from the committed
``tests/assets`` fixture, tiled.  The floor is the images/sec/chip CEILING
for the host-augment pipeline on this backend; BASELINE.md's host-pipeline
target is stated as a fraction of it (VERDICT r5 item 3).

Run on the bench host: ``python tools/perf_link_floor.py [global_batch]``.
The same measurement rides inside every full bench run
(``bench.py`` -> ``host_pipeline.link_floor``); this wrapper exists for
iterating on the staging path without paying for a full bench.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax

    import bench

    global_batch = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    ndev = len(jax.devices())
    floor = bench.measure_link_floor(
        lambda s: print(s, file=sys.stderr),
        global_batch=global_batch, ndev=ndev)
    print(json.dumps(floor, indent=2))


if __name__ == "__main__":
    main()
