"""Attribute the backward pass: full VGG vs BN-free vs per-stage truncation.

Builder's tool.  Scanned-K measurement (see perf_pieces.py) of
value_and_grad over model variants at the headline config, to locate the
fwd+bwd time (measured ~2.7 ms/iter vs ~0.53 ms fwd-only).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

K = 100


def main():
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax import lax

    from cs744_ddp_tpu.models import vgg, layers
    from cs744_ddp_tpu.ops.loss import cross_entropy
    from cs744_ddp_tpu.utils.compcache import \
        enable_persistent_compilation_cache

    enable_persistent_compilation_cache(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    B = 256
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(B, 32, 32, 3)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 10, (B,)), jnp.int32)

    def bench_scan(body, carry, *consts):
        def scanned(carry, *cs):
            def one(c, i):
                return body(c, i, *cs), ()
            c, _ = lax.scan(one, carry, jnp.arange(K))
            return c
        fn = jax.jit(scanned)
        out = fn(carry, *consts)
        np.asarray(jax.tree.leaves(out)[0])
        ts = []
        for _ in range(3):
            t0 = time.time()
            out = fn(carry, *consts)
            np.asarray(jax.tree.leaves(out)[0])
            ts.append(time.time() - t0)
        return min(ts) / K * 1e3

    null = bench_scan(lambda c, i: c + 1.0, jnp.float32(0))
    print(f"null               {null:7.3f} ms")

    def apply_nobn(params, state, xx, *, train):
        # VGG-11 with BN replaced by identity (same convs/pools/fc).
        cfg = vgg.CFG["VGG11"]
        i = 0
        h = xx
        for c in cfg:
            if c == "M":
                h = layers.maxpool2x2(h)
            else:
                h = layers.conv2d_apply(params["conv"][i], h)
                h = layers.relu(h)
                i += 1
        h = h.reshape(h.shape[0], -1)
        return layers.linear_apply(params["fc1"], h), state

    variants = {}
    params, bn_state = vgg.init(jax.random.PRNGKey(0), "VGG11")
    variants["full vgg11"] = (vgg.apply, params, bn_state)
    variants["no-BN vgg11"] = (apply_nobn, params, bn_state)

    for name, (apply_fn, p0, s0) in variants.items():
        def gbody(carry, i, xx, labels, apply_fn=apply_fn, s0=s0):
            p = carry

            def loss_fn(pp):
                logits, _ = apply_fn(pp, s0, xx, train=True)
                return cross_entropy(logits, labels)

            g = jax.grad(loss_fn)(p)
            return jax.tree.map(lambda a, b: a + 0.0 * b, p, g)

        t = bench_scan(gbody, p0, x, labels) - null
        print(f"grad {name:14s} {t:7.3f} ms")

        def fbody(carry, i, xx, labels, apply_fn=apply_fn, s0=s0):
            p = carry
            logits, _ = apply_fn(p, s0, xx, train=True)
            return jax.tree.map(
                lambda a: a + 0.0 * jnp.sum(logits), p)

        t = bench_scan(fbody, p0, x, labels) - null
        print(f"fwd  {name:14s} {t:7.3f} ms")


if __name__ == "__main__":
    main()
