"""Render a telemetry run directory (obs/) into a human summary.

A ``--telemetry-out`` run leaves three artifacts: ``manifest.json`` (the run
header), ``events.jsonl`` (per-step events, spans, gauges, counters) and
``summary.json`` (steady-state percentiles).  This tool prints them as one
readable report — run header, step-time table, span totals, counters and
the last value of every gauge — recomputing the summary from the raw events
when ``summary.json`` is missing (interrupted runs).

Run:  python tools/telemetry_report.py <run-dir> [--json]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cs744_ddp_tpu.obs import read_run, summarize_events  # noqa: E402
from cs744_ddp_tpu.obs.telemetry import (percentile,  # noqa: E402
                                         read_events_jsonl)


def _fmt_ms(seconds: float) -> str:
    return f"{seconds * 1e3:.2f} ms"


def _serving_lines(events) -> list:
    """Serving-path rendering (serve/ + --serve-demo runs): the queue-depth
    trace and per-bucket client-latency percentiles, both rebuilt from raw
    gauge events (``queue_depth``; ``serve_latency_ms`` with its ``bucket``
    attr).  Returns [] for runs with no serving events — training-run
    reports are unchanged."""
    depth, lat = [], {}
    for e in events:
        if e.get("kind") != "gauge":
            continue
        if e.get("name") == "queue_depth":
            depth.append(e["value"])
        elif e.get("name") == "serve_latency_ms":
            lat.setdefault(e.get("bucket", "?"), []).append(e["value"])
    if not depth and not lat:
        return []
    lines = ["== serving =="]
    if depth:
        lines.append(f"  queue_depth (images)   samples {len(depth)}  "
                     f"max {max(depth)}  "
                     f"mean {sum(depth) / len(depth):.1f}  "
                     f"last {depth[-1]}")
    if lat:
        lines.append("  request latency by bucket (client-side, "
                     "enqueue -> logits):")
        for b in sorted(lat, key=str):
            v = lat[b]
            lines.append(f"    bucket {b!s:<6} x{len(v):<6} "
                         f"p50 {percentile(v, 50):8.2f} ms  "
                         f"p95 {percentile(v, 95):8.2f} ms  "
                         f"p99 {percentile(v, 99):8.2f} ms")
    lines.append("")
    return lines


def _elastic_lines(events, manifest) -> list:
    """Elastic-mode rendering (``--elastic`` runs): per-rank step-time
    percentiles from the raw ``rank_step_time_s`` gauges, straggler flags,
    and the rank-death count — the report-side face of the round-6
    world-resize layer.  Returns [] for runs with no elastic signal —
    non-elastic reports are unchanged."""
    per, flags, deaths = {}, {}, 0
    for e in events:
        kind, name = e.get("kind"), e.get("name")
        if kind == "gauge" and name == "rank_step_time_s":
            per.setdefault(e.get("rank", "?"), []).append(e["value"])
        elif kind == "counter" and name == "straggler_flagged":
            flags[e.get("rank", "?")] = \
                flags.get(e.get("rank", "?"), 0) + e.get("inc", 1)
        elif kind == "counter" and name == "rank_deaths":
            deaths = e["total"]
    cfg = (manifest or {}).get("elastic")
    if not per and not flags and not deaths and not cfg:
        return []
    lines = ["== elastic =="]
    if cfg:
        proto = cfg.get("protocol")
        ms = cfg.get("microshards")
        lines.append(f"  protocol               {proto}"
                     + (f" (microshards {ms})" if ms else ""))
    if per:
        lines.append("  per-rank step time (window-boundary attribution):")
        for r in sorted(per, key=str):
            v = per[r]
            mark = f"  straggler x{flags[r]}" if r in flags else ""
            lines.append(f"    rank {r!s:<4} x{len(v):<6} "
                         f"p50 {_fmt_ms(percentile(v, 50)):>12}  "
                         f"max {_fmt_ms(max(v)):>12}{mark}")
    if deaths:
        lines.append(f"  rank deaths            {deaths}")
    lines.append("")
    return lines


def _audit_lines(manifest) -> list:
    """Program-audit rendering (``--audit`` runs write
    ``manifest["audit"]`` via analysis/audit.py's ``record_audit``):
    verdict, per-program rule grid and any findings.  Returns [] when the
    manifest carries no audit record — older runs render unchanged."""
    audit = (manifest or {}).get("audit")
    if not isinstance(audit, dict):
        return []
    lines = ["== program audit =="]
    verdict = "CLEAN" if audit.get("clean") else "DIRTY"
    lines.append(f"  {verdict}: {audit.get('n_programs', 0)} programs, "
                 f"{audit.get('n_findings', 0)} findings, "
                 f"{audit.get('n_waived', 0)} waived")
    for prog, rec in sorted((audit.get("programs") or {}).items()):
        rules = rec.get("rules") or {}
        failed = sorted(r for r, v in rules.items() if v == "fail")
        waived = sorted(r for r, v in rules.items() if v == "waived")
        status = "FAIL " + ",".join(failed) if failed else "pass"
        if waived:
            status += f"  (waived {','.join(waived)})"
        depth = rec.get("chain_depth")
        lines.append(f"  {prog:<28} depth {depth!s:<4} {status}")
    for f in audit.get("findings") or []:
        lines.append(f"    !! {f.get('program')}: [{f.get('rule')}] "
                     f"{f.get('message')}")
    ladder = audit.get("ladder")
    if ladder:
        lines.append(f"  strategy depth ladder    {ladder}")
    lines.append("")
    return lines


def render(out_dir: str) -> str:
    manifest, events, summary = read_run(out_dir)
    # A preempted/killed run legitimately truncates the final event line;
    # count and surface it rather than failing the report (the report may
    # be the only diagnostic artifact such a run leaves).
    _, n_bad = read_events_jsonl(
        os.path.join(out_dir, "events.jsonl"),
        warn=lambda msg: print(f"warning: {msg}", file=sys.stderr))
    if summary is None:
        # Interrupted run: recompute from the raw events so a partial run
        # still renders (the report may be the only diagnostic artifact).
        gb = (manifest or {}).get("global_batch")
        summary = summarize_events(events, global_batch=gb)
    lines = [f"telemetry run: {out_dir}", ""]
    if n_bad:
        lines.append(f"  !! {n_bad} undecodable event line(s) skipped "
                     f"(run killed mid-write?)")
        lines.append("")

    if manifest:
        lines.append("== run manifest ==")
        order = ["model", "strategy", "world_size", "global_batch",
                 "precision", "augment", "host_augment", "jax_version",
                 "backend", "device_kind", "git_sha"]
        for k in order:
            if k in manifest:
                lines.append(f"  {k:<22} {manifest[k]}")
        native = manifest.get("native_loader")
        if native is not None:
            status = "available" if native.get("available") else \
                f"UNAVAILABLE ({native.get('error')})"
            lines.append(f"  {'native_loader':<22} {status}")
        lines.append("")

    lines.append("== steady-state steps ==")
    lines.append(f"  steps recorded         {summary.get('num_steps', 0)} "
                 f"({summary.get('num_steady_steps', 0)} steady)")
    st = summary.get("steady_step_time_s")
    if st:
        for q in ("p50", "p95", "p99", "mean", "min", "max"):
            lines.append(f"  step time {q:<12} {_fmt_ms(st[q])}")
    ips = summary.get("steady_images_per_sec")
    if ips:
        lines.append(f"  images/sec             {ips:,.0f}")
    if "final_loss" in summary:
        lines.append(f"  final loss             {summary['final_loss']:.4f}")
    lines.append("")

    if summary.get("spans"):
        lines.append("== spans (total wall clock) ==")
        for name, agg in sorted(summary["spans"].items(),
                                key=lambda kv: -kv[1]["total_s"]):
            lines.append(f"  {name:<22} x{agg['count']:<5} "
                         f"{_fmt_ms(agg['total_s'])}")
        lines.append("")

    if summary.get("counters"):
        lines.append("== counters (final) ==")
        for name, total in sorted(summary["counters"].items()):
            lines.append(f"  {name:<34} {total}")
        lines.append("")

    lines.extend(_serving_lines(events))
    lines.extend(_elastic_lines(events, manifest))
    lines.extend(_audit_lines(manifest))

    gauges = {}
    for e in events:
        if e.get("kind") == "gauge":
            gauges[e["name"]] = e["value"]   # last write wins
    if gauges:
        lines.append("== gauges (last value) ==")
        for name, value in sorted(gauges.items()):
            lines.append(f"  {name:<22} {value}")
        lines.append("")

    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="render a --telemetry-out run directory")
    p.add_argument("run_dir", help="directory holding manifest.json / "
                                   "events.jsonl / summary.json")
    p.add_argument("--json", action="store_true",
                   help="emit the (re)computed summary as JSON instead of "
                        "the human table")
    args = p.parse_args(argv)
    if not os.path.isdir(args.run_dir):
        p.error(f"not a directory: {args.run_dir}")
    if args.json:
        manifest, events, summary = read_run(args.run_dir)
        if summary is None:
            summary = summarize_events(
                events, global_batch=(manifest or {}).get("global_batch"))
        print(json.dumps(summary, indent=2))
    else:
        print(render(args.run_dir))
    return 0


if __name__ == "__main__":
    sys.exit(main())
