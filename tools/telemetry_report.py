"""Render a telemetry run directory (obs/) into a human summary.

A ``--telemetry-out`` run leaves three artifacts: ``manifest.json`` (the run
header), ``events.jsonl`` (per-step events, spans, gauges, counters) and
``summary.json`` (steady-state percentiles).  This tool prints them as one
readable report — run header, step-time table, span totals, counters and
the last value of every gauge — recomputing the summary from the raw events
when ``summary.json`` is missing (interrupted runs).

Run:  python tools/telemetry_report.py <run-dir> [--json]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cs744_ddp_tpu.obs import read_run, summarize_events  # noqa: E402
from cs744_ddp_tpu.obs.telemetry import (percentile,  # noqa: E402
                                         read_events_jsonl)


def _fmt_ms(seconds: float) -> str:
    return f"{seconds * 1e3:.2f} ms"


def _serving_lines(events) -> list:
    """Serving-path rendering (serve/ + --serve-demo runs): the queue-depth
    trace and per-bucket client-latency percentiles, both rebuilt from raw
    gauge events (``queue_depth``; ``serve_latency_ms`` with its ``bucket``
    attr).  Returns [] for runs with no serving events — training-run
    reports are unchanged."""
    depth, lat = [], {}
    for e in events:
        if e.get("kind") != "gauge":
            continue
        if e.get("name") == "queue_depth":
            depth.append(e["value"])
        elif e.get("name") == "serve_latency_ms":
            lat.setdefault(e.get("bucket", "?"), []).append(e["value"])
    if not depth and not lat:
        return []
    lines = ["== serving =="]
    if depth:
        lines.append(f"  queue_depth (images)   samples {len(depth)}  "
                     f"max {max(depth)}  "
                     f"mean {sum(depth) / len(depth):.1f}  "
                     f"last {depth[-1]}")
    if lat:
        lines.append("  request latency by bucket (client-side, "
                     "enqueue -> logits):")
        for b in sorted(lat, key=str):
            v = lat[b]
            lines.append(f"    bucket {b!s:<6} x{len(v):<6} "
                         f"p50 {percentile(v, 50):8.2f} ms  "
                         f"p95 {percentile(v, 95):8.2f} ms  "
                         f"p99 {percentile(v, 99):8.2f} ms")
    lines.append("")
    return lines


def _wire_ext_lines(events) -> list:
    """Wire extension-block health: unknown TLV tags skipped and torn
    trailing fields dropped by the codec (``wire_ext_skipped`` counter,
    per frame kind).  Non-zero numbers mean a peer on a different
    protocol build is talking to this process — the cross-version drift
    signal ROADMAP item 1 needs.  Returns [] when no frame ever skipped
    a field — same-build runs are unchanged."""
    per = {}
    for e in events:
        if e.get("kind") == "counter" and e.get("name") == "wire_ext_skipped":
            key = e.get("frame", "?")
            unknown, torn = per.get(key, (0, 0))
            per[key] = (unknown + e.get("unknown", 0),
                        torn + e.get("torn", 0))
    if not per:
        return []
    lines = ["== wire extension skips =="]
    for frame in sorted(per):
        unknown, torn = per[frame]
        lines.append(f"  {frame:<10} unknown tags skipped {unknown:<6} "
                     f"torn fields dropped {torn}")
    lines.append("")
    return lines


def _elastic_lines(events, manifest) -> list:
    """Elastic-mode rendering (``--elastic`` runs): per-rank step-time
    percentiles from the raw ``rank_step_time_s`` gauges, straggler flags,
    and the rank-death count — the report-side face of the round-6
    world-resize layer.  Returns [] for runs with no elastic signal —
    non-elastic reports are unchanged."""
    per, flags, deaths = {}, {}, 0
    for e in events:
        kind, name = e.get("kind"), e.get("name")
        if kind == "gauge" and name == "rank_step_time_s":
            per.setdefault(e.get("rank", "?"), []).append(e["value"])
        elif kind == "counter" and name == "straggler_flagged":
            flags[e.get("rank", "?")] = \
                flags.get(e.get("rank", "?"), 0) + e.get("inc", 1)
        elif kind == "counter" and name == "rank_deaths":
            deaths = e["total"]
    cfg = (manifest or {}).get("elastic")
    if not per and not flags and not deaths and not cfg:
        return []
    lines = ["== elastic =="]
    if cfg:
        proto = cfg.get("protocol")
        ms = cfg.get("microshards")
        lines.append(f"  protocol               {proto}"
                     + (f" (microshards {ms})" if ms else ""))
    if per:
        lines.append("  per-rank step time (window-boundary attribution):")
        for r in sorted(per, key=str):
            v = per[r]
            mark = f"  straggler x{flags[r]}" if r in flags else ""
            lines.append(f"    rank {r!s:<4} x{len(v):<6} "
                         f"p50 {_fmt_ms(percentile(v, 50)):>12}  "
                         f"max {_fmt_ms(max(v)):>12}{mark}")
    if deaths:
        lines.append(f"  rank deaths            {deaths}")
    lines.append("")
    return lines


def _audit_lines(manifest) -> list:
    """Program-audit rendering (``--audit`` runs write
    ``manifest["audit"]`` via analysis/audit.py's ``record_audit``):
    verdict, per-program rule grid and any findings.  Returns [] when the
    manifest carries no audit record — older runs render unchanged."""
    audit = (manifest or {}).get("audit")
    if not isinstance(audit, dict):
        return []
    lines = ["== program audit =="]
    verdict = "CLEAN" if audit.get("clean") else "DIRTY"
    lines.append(f"  {verdict}: {audit.get('n_programs', 0)} programs, "
                 f"{audit.get('n_findings', 0)} findings, "
                 f"{audit.get('n_waived', 0)} waived")
    for prog, rec in sorted((audit.get("programs") or {}).items()):
        rules = rec.get("rules") or {}
        failed = sorted(r for r, v in rules.items() if v == "fail")
        waived = sorted(r for r, v in rules.items() if v == "waived")
        status = "FAIL " + ",".join(failed) if failed else "pass"
        if waived:
            status += f"  (waived {','.join(waived)})"
        depth = rec.get("chain_depth")
        lines.append(f"  {prog:<28} depth {depth!s:<4} {status}")
    for f in audit.get("findings") or []:
        lines.append(f"    !! {f.get('program')}: [{f.get('rule')}] "
                     f"{f.get('message')}")
    ladder = audit.get("ladder")
    if ladder:
        lines.append(f"  strategy depth ladder    {ladder}")
    lines.append("")
    return lines


def _attribution_lines(manifest) -> list:
    """Cost-model attribution rendering (round 8: ``--audit-zoo`` with a
    telemetry dir records ``manifest["attribution"]`` via
    analysis/audit.record_attribution): per-program analytic
    FLOPs/HBM/wire with the roofline verdict, the measured MFU join when
    present, and overlap's exposed-comm bound vs ddp.  Returns [] when
    the manifest carries no attribution record — older runs render
    unchanged."""
    attr = (manifest or {}).get("attribution")
    if not isinstance(attr, dict):
        return []
    lines = ["== attribution (static cost model) =="]
    progs = attr.get("programs") or {}
    if progs:
        lines.append(f"  {'program':<28} {'gflops':>9} {'hbm_mib':>9} "
                     f"{'wire_mib':>9}  bound      comm/compute")
        for name, rec in sorted(progs.items()):
            ratio = rec.get("comm_compute_ratio")
            lines.append(
                f"  {name:<28} {rec.get('gflops', 0):>9} "
                f"{rec.get('hbm_mib', 0):>9} {rec.get('wire_mib', 0):>9}  "
                f"{rec.get('roofline_bound', '?'):<9}  "
                f"{ratio if ratio is not None else '-'}")
    measured = attr.get("measured")
    if isinstance(measured, dict):
        lines.append(f"  measured join          {measured.get('program')}: "
                     f"{measured.get('images_per_sec_per_chip')} img/s/chip, "
                     f"mfu {measured.get('mfu_vs_bf16_peak')}, "
                     f"{measured.get('roofline_bound')}-bound")
    ov = attr.get("overlap_vs_ddp")
    if isinstance(ov, dict):
        lines.append(f"  overlap exposed comm   <= "
                     f"{ov.get('overlap_exposed_bytes_upper_bound')} B vs "
                     f"ddp chained {ov.get('ddp_chained_bytes')} B "
                     f"(hiding ratio >= {ov.get('hiding_ratio_lower_bound')})")
    lines.append("")
    return lines


def _memory_lines(events, manifest) -> list:
    """Memory rendering (round 20): the runtime ``memory`` gauges that
    ``train/loop.emit_memory_gauges`` records at window/epoch boundaries
    (peak host RSS, live device bytes via ``jax.live_arrays``) joined
    against the static peak-HBM certificate the audit attaches per
    program (``peak_mib`` from analysis/memlife.py).  A measured device
    residency above the fattest certified peak means the liveness model
    missed a buffer — the same inequality tier-1 pins.  Returns [] for
    runs with neither signal — older runs render unchanged."""
    rss, live_mib, live_n = [], [], []
    for e in events:
        if e.get("kind") != "gauge" or e.get("name") != "memory":
            continue
        v = e.get("value")
        if not isinstance(v, dict):
            continue
        if "host_rss_peak_mib" in v:
            rss.append(v["host_rss_peak_mib"])
        if "device_live_mib" in v:
            live_mib.append(v["device_live_mib"])
        if "device_live_arrays" in v:
            live_n.append(v["device_live_arrays"])
    certified = {}
    for prog, rec in (((manifest or {}).get("audit") or {})
                      .get("programs") or {}).items():
        if isinstance(rec, dict) and rec.get("peak_mib") is not None:
            certified[prog] = rec["peak_mib"]
    if not rss and not live_mib and not certified:
        return []
    lines = ["== memory (measured vs certified) =="]
    if live_mib:
        lines.append(f"  device live (gauge)    x{len(live_mib):<6} "
                     f"max {max(live_mib):10.2f} MiB  "
                     f"last {live_mib[-1]:10.2f} MiB"
                     + (f"  ({live_n[-1]} arrays)" if live_n else ""))
    if rss:
        lines.append(f"  host RSS peak          x{len(rss):<6} "
                     f"max {max(rss):10.1f} MiB")
    if certified:
        fattest = max(certified, key=certified.get)
        lines.append(f"  certified peak (max)   {certified[fattest]:10.3f} "
                     f"MiB  ({fattest}, static liveness bound)")
        if live_mib:
            if max(live_mib) <= certified[fattest]:
                lines.append(f"  verdict                measured within "
                             f"certificate (headroom "
                             f"{certified[fattest] - max(live_mib):.2f} MiB)")
            else:
                lines.append(f"  !! measured device residency "
                             f"{max(live_mib):.2f} MiB EXCEEDS the "
                             f"certified peak — liveness model missed "
                             f"a buffer")
    lines.append("")
    return lines


def _trace_lines(events) -> list:
    """Serving-causality rendering (round 8): per-request trace ids ride
    the enqueue -> batch -> dispatch -> fetch spans, and two per-request
    gauges split client latency into queue wait vs service time.  Returns
    [] for runs with no trace signal — older runs render unchanged."""
    trace_reqs = set()
    dispatch_spans = 0
    dispatch_traced = 0
    qw, svc = [], []
    for e in events:
        kind, name = e.get("kind"), e.get("name")
        if kind == "span" and name == "serve_enqueue" and "trace" in e:
            trace_reqs.add(e["trace"])
        elif kind == "span" and name == "serve_dispatch":
            dispatch_spans += 1
            if e.get("traces"):
                dispatch_traced += 1
        elif kind == "gauge" and name == "serve_queue_wait_ms":
            qw.append(e["value"])
        elif kind == "gauge" and name == "serve_service_ms":
            svc.append(e["value"])
    if not trace_reqs and not qw and not svc:
        return []
    lines = ["== traces (request causality) =="]
    if trace_reqs:
        lines.append(f"  traced requests        {len(trace_reqs)}")
    if dispatch_spans:
        lines.append(f"  dispatch spans         {dispatch_spans} "
                     f"({dispatch_traced} carrying trace ids)")
    for label, v in (("queue wait", qw), ("service time", svc)):
        if v:
            lines.append(f"  {label:<12} x{len(v):<6} "
                         f"p50 {percentile(v, 50):8.2f} ms  "
                         f"p95 {percentile(v, 95):8.2f} ms  "
                         f"mean {sum(v) / len(v):8.2f} ms")
    lines.append("")
    return lines


def _slo_lines(events) -> list:
    """SLO scheduling rendering (round 9): per-tier attainment from the
    scheduler's ``serve_latency_ms`` gauges (``tier``/``met`` attrs),
    shed counts by tier/reason, failovers, and per-replica utilization.
    Returns [] for runs with no SLO signal — older runs render
    unchanged."""
    tiers = {}
    shed_reasons = {}
    failovers = 0
    deaths = 0
    util = {}
    for e in events:
        kind, name = e.get("kind"), e.get("name")
        if kind == "gauge" and name == "serve_latency_ms" and "met" in e \
                and "tier" in e:
            agg = tiers.setdefault(e["tier"], {"served": 0, "met": 0,
                                               "shed": 0})
            agg["served"] += 1
            agg["met"] += 1 if e["met"] else 0
        elif kind == "counter" and name == "serve_shed":
            if "tier" in e:
                agg = tiers.setdefault(e["tier"], {"served": 0, "met": 0,
                                                   "shed": 0})
                agg["shed"] += int(e.get("inc", 1))
            reason = str(e.get("reason", "unknown"))
            shed_reasons[reason] = shed_reasons.get(reason, 0) \
                + int(e.get("inc", 1))
        elif kind == "counter" and name == "serve_failover":
            failovers += int(e.get("inc", 1))
        elif kind == "counter" and name == "replica_death":
            deaths += int(e.get("inc", 1))
        elif kind == "gauge" and name == "replica_util" and "replica" in e:
            util[e["replica"]] = e["value"]
    if not tiers and not shed_reasons and not util:
        return []
    lines = ["== slo (tiered attainment) =="]
    for tier in sorted(tiers):
        agg = tiers[tier]
        offered = agg["served"] + agg["shed"]
        att = agg["met"] / offered if offered else 0.0
        lines.append(f"  tier {tier!s:<4} served {agg['served']:<6} "
                     f"met {agg['met']:<6} late "
                     f"{agg['served'] - agg['met']:<5} "
                     f"shed {agg['shed']:<5} attainment {att:7.2%}")
    if shed_reasons:
        detail = ", ".join(f"{r} {n}" for r, n in sorted(shed_reasons.items()))
        lines.append(f"  shed by reason         {detail}")
    if deaths or failovers:
        lines.append(f"  replica deaths         {deaths} "
                     f"({failovers} requests failed over)")
    if util:
        detail = "  ".join(f"r{k} {v:.2f}" for k, v in sorted(util.items()))
        lines.append(f"  replica utilization    {detail}")
    lines.append("")
    return lines


def _publish_lines(events) -> list:
    """Weight hot-swap rendering (round 10, ``publish/``): publish/install
    counters from both sides of the pipeline (publisher counts, installs,
    crc/signature rejections, stale skips), per-replica swap-latency
    percentiles from the watcher's ``swap_ms`` gauges, and the last
    published vs installed version.  Returns [] for runs with no publish
    signal — older runs render unchanged."""
    counts = {}
    swap_ms = []
    published = installed = None
    for e in events:
        kind, name = e.get("kind"), e.get("name")
        if kind == "counter" and name in (
                "publish_count", "publish_installed", "publish_rejected",
                "publish_stale_skipped", "publish_chaos_injected",
                "weights_installed"):
            counts[name] = e["total"]
        elif kind == "gauge" and name == "swap_ms":
            swap_ms.append(e["value"])
        elif kind == "gauge" and name == "publish_version":
            published = e["value"]
        elif kind == "gauge" and name == "installed_version":
            installed = e["value"]
    if not counts and not swap_ms and published is None \
            and installed is None:
        return []
    lines = ["== publish (weight hot-swap) =="]
    for name in ("publish_count", "publish_installed", "publish_rejected",
                 "publish_stale_skipped", "publish_chaos_injected",
                 "weights_installed"):
        if name in counts:
            lines.append(f"  {name:<22} {counts[name]}")
    if published is not None or installed is not None:
        lines.append(f"  version                published {published}  "
                     f"installed {installed}")
    if swap_ms:
        lines.append(f"  swap latency x{len(swap_ms):<6} "
                     f"p50 {percentile(swap_ms, 50):8.2f} ms  "
                     f"p99 {percentile(swap_ms, 99):8.2f} ms  "
                     f"max {max(swap_ms):8.2f} ms")
    lines.append("")
    return lines


def _pipeline_lines(events) -> list:
    """Dispatch-pipeline rendering (round 14): the scheduler's
    ``serve_inflight`` gauge traces per-replica pipeline occupancy (0..
    ``PIPELINE_SLOTS``) after every issue/completion — the occupancy
    distribution says how often batch N+1 actually overlapped batch N.
    ``serve_dispatch_fault`` counts completion-side faults that were
    isolated to one batch (explicit error replies, worker survived).
    Returns [] for runs with no pipeline signal — serial-mode and older
    runs render unchanged."""
    occ = {}
    faults = 0
    for e in events:
        kind, name = e.get("kind"), e.get("name")
        if kind == "gauge" and name == "serve_inflight":
            per = occ.setdefault(e.get("replica", "?"), {})
            v = int(e.get("value", 0))
            per[v] = per.get(v, 0) + 1
        elif kind == "counter" and name == "serve_dispatch_fault":
            faults += int(e.get("inc", 1))
    if not occ and not faults:
        return []
    lines = ["== dispatch pipeline =="]
    for replica in sorted(occ, key=str):
        per = occ[replica]
        n = sum(per.values())
        detail = "  ".join(f"{d} slots {per[d] / n:.0%}"
                           for d in sorted(per))
        lines.append(f"  replica {replica!s:<4} occupancy x{n:<6} "
                     f"max {max(per)}  {detail}")
    if faults:
        lines.append(f"  dispatch faults        {faults} "
                     f"(isolated: error replies, worker survived)")
    lines.append("")
    return lines


def _waterfall_lines(out_dir: str, events) -> list:
    """Distributed-trace rendering (round 12, ``obs/aggregate.py``): when
    the run carries ``trace_id``-stamped spans, reconstruct this one
    stream's request waterfalls (single-process view — use
    tools/trace_waterfall.py across N run dirs for the skew-corrected
    cross-process merge).  Returns [] for untraced runs."""
    if not any(e.get("kind") == "span" and e.get("trace_id")
               for e in events):
        return []
    from cs744_ddp_tpu.obs import aggregate as agg
    rep = agg.aggregate_streams(
        [agg.ProcessStream(os.path.basename(os.path.normpath(out_dir))
                           or out_dir, events)])
    lines = ["== waterfall (distributed traces, this stream) =="]
    lines.append(f"  traces                 {rep['traces']} "
                 f"({rep['complete']} complete, {rep['orphaned']} "
                 f"orphaned/partial)")
    for stage, a in rep["stage_ms"].items():
        lines.append(f"  {stage:<16} x{a['count']:<6} "
                     f"p50 {a['p50']:8.2f} ms  p99 {a['p99']:8.2f} ms")
    dom = rep["critical_path"].get("dominant")
    if dom:
        share = rep["critical_path"]["share"].get(dom)
        lines.append(f"  critical path          {dom} "
                     f"({share:.0%} of stage time)")
    lines.append("")
    return lines


def _alert_lines(events) -> list:
    """Alert-engine rendering (round 12, ``obs/alerts.py``): structured
    ``kind: alert`` events grouped by deterministic rule id.  Returns []
    for runs with no alerts — quiet runs render unchanged."""
    by_rule = {}
    for e in events:
        if e.get("kind") != "alert":
            continue
        agg = by_rule.setdefault(e.get("rule", "?"), {
            "count": 0, "severity": e.get("severity", "?"),
            "first_t": e.get("t")})
        agg["count"] += 1
        agg["last_t"] = e.get("t")
    if not by_rule:
        return []
    lines = ["== alerts =="]
    for rule, agg in sorted(by_rule.items()):
        span_s = (agg["last_t"] or 0) - (agg["first_t"] or 0)
        lines.append(f"  {rule:<14} [{agg['severity']}]  x{agg['count']:<5}"
                     f" over {span_s:.1f} s")
    lines.append("")
    return lines


def render(out_dir: str) -> str:
    manifest, events, summary = read_run(out_dir)
    # A preempted/killed run legitimately truncates the final event line;
    # count and surface it rather than failing the report (the report may
    # be the only diagnostic artifact such a run leaves).
    _, n_bad = read_events_jsonl(
        os.path.join(out_dir, "events.jsonl"),
        warn=lambda msg: print(f"warning: {msg}", file=sys.stderr))
    if summary is None:
        # Interrupted run: recompute from the raw events so a partial run
        # still renders (the report may be the only diagnostic artifact).
        gb = (manifest or {}).get("global_batch")
        summary = summarize_events(events, global_batch=gb)
    lines = [f"telemetry run: {out_dir}", ""]
    if n_bad:
        lines.append(f"  !! {n_bad} undecodable event line(s) skipped "
                     f"(run killed mid-write?)")
        lines.append("")

    if manifest:
        lines.append("== run manifest ==")
        order = ["model", "strategy", "world_size", "global_batch",
                 "precision", "augment", "host_augment", "jax_version",
                 "backend", "device_kind", "git_sha"]
        for k in order:
            if k in manifest:
                lines.append(f"  {k:<22} {manifest[k]}")
        native = manifest.get("native_loader")
        if native is not None:
            status = "available" if native.get("available") else \
                f"UNAVAILABLE ({native.get('error')})"
            lines.append(f"  {'native_loader':<22} {status}")
        lines.append("")

    lines.append("== steady-state steps ==")
    lines.append(f"  steps recorded         {summary.get('num_steps', 0)} "
                 f"({summary.get('num_steady_steps', 0)} steady)")
    st = summary.get("steady_step_time_s")
    if st:
        for q in ("p50", "p95", "p99", "mean", "min", "max"):
            lines.append(f"  step time {q:<12} {_fmt_ms(st[q])}")
    ips = summary.get("steady_images_per_sec")
    if ips:
        lines.append(f"  images/sec             {ips:,.0f}")
    if "final_loss" in summary:
        lines.append(f"  final loss             {summary['final_loss']:.4f}")
    lines.append("")

    if summary.get("spans"):
        lines.append("== spans (total wall clock) ==")
        for name, agg in sorted(summary["spans"].items(),
                                key=lambda kv: -kv[1]["total_s"]):
            lines.append(f"  {name:<22} x{agg['count']:<5} "
                         f"{_fmt_ms(agg['total_s'])}")
        lines.append("")

    if summary.get("counters"):
        lines.append("== counters (final) ==")
        for name, total in sorted(summary["counters"].items()):
            lines.append(f"  {name:<34} {total}")
        lines.append("")

    lines.extend(_wire_ext_lines(events))

    lines.extend(_serving_lines(events))
    lines.extend(_elastic_lines(events, manifest))
    lines.extend(_audit_lines(manifest))
    lines.extend(_attribution_lines(manifest))
    lines.extend(_memory_lines(events, manifest))
    lines.extend(_trace_lines(events))
    lines.extend(_slo_lines(events))
    lines.extend(_publish_lines(events))
    lines.extend(_pipeline_lines(events))
    lines.extend(_waterfall_lines(out_dir, events))
    lines.extend(_alert_lines(events))

    gauges = {}
    for e in events:
        if e.get("kind") == "gauge":
            gauges[e["name"]] = e["value"]   # last write wins
    if gauges:
        lines.append("== gauges (last value) ==")
        for name, value in sorted(gauges.items()):
            lines.append(f"  {name:<22} {value}")
        lines.append("")

    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="render a --telemetry-out run directory")
    p.add_argument("run_dir", help="directory holding manifest.json / "
                                   "events.jsonl / summary.json")
    p.add_argument("--json", action="store_true",
                   help="emit the (re)computed summary as JSON instead of "
                        "the human table")
    args = p.parse_args(argv)
    if not os.path.isdir(args.run_dir):
        p.error(f"not a directory: {args.run_dir}")
    if args.json:
        manifest, events, summary = read_run(args.run_dir)
        if summary is None:
            summary = summarize_events(
                events, global_batch=(manifest or {}).get("global_batch"))
        print(json.dumps(summary, indent=2))
    else:
        print(render(args.run_dir))
    return 0


if __name__ == "__main__":
    sys.exit(main())
