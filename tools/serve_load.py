"""Standalone serving load-trace generator + open-loop replay client.

Two subcommands:

* ``gen``    — write a seeded tiered load trace (``serve/demo.py``
  ``synthetic_load_trace``) as JSON: ``{"trace": [[t_s, n_images, tier,
  slo_ms], ...], "meta": {...}}``.  Deterministic in (seed, rps,
  requests), so a committed trace file IS the workload.
* ``replay`` — replay a trace file open-loop over the wire protocol
  against a running ``--serve-frontend`` server (or ``gen`` + replay in
  one shot with ``--rps``), printing the goodput/SLO-attainment stats
  sheet as one JSON line.  Requests are submitted at their scheduled
  arrival times regardless of completion — offered load is the
  independent variable.

Run:  python tools/serve_load.py gen --requests 2000 --rps 1000 \
          --seed 0 -o trace.json
      python tools/serve_load.py replay trace.json --port 7447
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cs744_ddp_tpu.serve import demo  # noqa: E402
from cs744_ddp_tpu.serve.frontend import FrontendClient  # noqa: E402


def _parse_tiers(spec):
    """``tier:weight:slo_ms`` triples -> the tiers mixture tuple."""
    if not spec:
        return demo.DEFAULT_TIERS
    tiers = []
    for s in spec:
        tier, weight, slo = s.split(":")
        tiers.append((int(tier), float(weight), float(slo)))
    return tuple(tiers)


def gen_trace(args) -> dict:
    sizes = demo.SIZE_CHOICES
    if args.max_size is not None:
        sizes = tuple(s for s in sizes if s <= args.max_size)
    trace = demo.synthetic_load_trace(
        args.requests, offered_rps=args.rps, seed=args.seed,
        size_choices=sizes, tiers=_parse_tiers(args.tier))
    return {
        "trace": [[round(t, 9), n, tier, slo] for t, n, tier, slo in trace],
        "meta": {"requests": args.requests, "offered_rps": args.rps,
                 "seed": args.seed,
                 "tiers": [list(t) for t in _parse_tiers(args.tier)]},
    }


def cmd_gen(args) -> int:
    doc = gen_trace(args)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f)
        print(f"wrote {len(doc['trace'])} requests to {args.out}")
    else:
        print(json.dumps(doc))
    return 0


def cmd_replay(args) -> int:
    if args.trace:
        with open(args.trace) as f:
            doc = json.load(f)
        trace = [tuple(row) for row in doc["trace"]]
        seed = int(doc.get("meta", {}).get("seed", args.seed))
    else:
        if args.rps is None:
            raise SystemExit("replay needs a trace file or --rps")
        doc = gen_trace(args)
        trace = [tuple(row) for row in doc["trace"]]
        seed = args.seed
    pool = demo.request_pool(seed=123)
    # --telemetry-out makes this CLIENT process one stream of a
    # distributed trace: each request gets a root TraceContext riding
    # the wire extension, and the client-side ``trace_client`` spans
    # land in our own events.jsonl for tools/trace_waterfall.py to
    # skew-correct against the server's stream.
    telemetry = None
    if args.telemetry_out:
        from cs744_ddp_tpu.obs import Telemetry
        telemetry = Telemetry(args.telemetry_out)
    try:
        with FrontendClient((args.host, args.port), timeout=args.timeout,
                            telemetry=telemetry) as client:
            stats = demo.replay_load(client, trace, pool=pool, seed=seed,
                                     drain_timeout_s=args.timeout)
    finally:
        if telemetry is not None:
            telemetry.finalize()
    print(json.dumps(stats))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="seeded serving load-trace generator + open-loop "
                    "replay client (wire protocol)")
    sub = p.add_subparsers(dest="cmd", required=True)

    g = sub.add_parser("gen", help="generate a seeded tiered load trace")
    g.add_argument("--requests", type=int, default=1000)
    g.add_argument("--rps", type=float, default=500.0,
                   help="offered load, requests/sec")
    g.add_argument("--seed", type=int, default=0)
    g.add_argument("--tier", action="append", default=None,
                   metavar="TIER:WEIGHT:SLO_MS",
                   help="tier mixture entry (repeatable; default "
                        "0:2:75 1:5:200 2:3:600)")
    g.add_argument("--max-size", type=int, default=None, metavar="N",
                   help="cap request sizes at N images (match the "
                        "server's largest bucket)")
    g.add_argument("-o", "--out", default=None,
                   help="trace file (default: print one JSON line)")
    g.set_defaults(fn=cmd_gen)

    r = sub.add_parser("replay", help="replay a trace against a running "
                                      "--serve-frontend server")
    r.add_argument("trace", nargs="?", default=None,
                   help="trace file from gen (omit to generate inline "
                        "with --rps/--requests)")
    r.add_argument("--host", default="127.0.0.1")
    r.add_argument("--port", type=int, required=True)
    r.add_argument("--requests", type=int, default=1000)
    r.add_argument("--rps", type=float, default=None)
    r.add_argument("--seed", type=int, default=0)
    r.add_argument("--tier", action="append", default=None,
                   metavar="TIER:WEIGHT:SLO_MS")
    r.add_argument("--max-size", type=int, default=None, metavar="N")
    r.add_argument("--timeout", type=float, default=120.0,
                   help="drain timeout seconds")
    r.add_argument("--telemetry-out", default=None, metavar="DIR",
                   help="write client-side trace spans (events.jsonl) "
                        "here; enables distributed tracing on every "
                        "request via the wire extension")
    r.set_defaults(fn=cmd_replay)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
