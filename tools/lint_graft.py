#!/usr/bin/env python
"""Project lint runner: AST rules from cs744_ddp_tpu/analysis/pylint_rules.

Enforces the repo's concurrency/measurement invariants statically:
un-fenced timing around device dispatches, jnp on producer/batcher
threads, shared-state writes outside the owning lock, and
distributed-trace spans emitted without their join keys
(span-hygiene).  Exits nonzero on any finding, so it slots into CI
as-is; tests/test_analysis.py runs the same check as a tier-1 test.

    python tools/lint_graft.py              # lint the default targets
    python tools/lint_graft.py serve ft     # lint specific paths

Waive a line with ``# lint: ok`` or ``# lint: ok(rule-name)``.
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

from cs744_ddp_tpu.analysis.pylint_rules import (DEFAULT_TARGETS,  # noqa: E402
                                                 lint_paths)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        "lint_graft", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*",
                    help="files/directories to lint (default: "
                         + ", ".join(DEFAULT_TARGETS) + ")")
    args = ap.parse_args(argv)
    paths = args.paths or [os.path.join(_REPO_ROOT, t)
                           for t in DEFAULT_TARGETS]
    findings = lint_paths(paths)
    for f in findings:
        print(f"{os.path.relpath(f.path, _REPO_ROOT)}:{f.line}: "
              f"[{f.rule}] {f.message}")
    if findings:
        print(f"{len(findings)} finding(s)")
        return 1
    print("lint_graft: clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
