#!/usr/bin/env python
"""Project lint runner: the AST rules from cs744_ddp_tpu/analysis.

Enforces the repo's concurrency/measurement invariants statically:
un-fenced timing around device dispatches, jnp on producer/batcher
threads, shared-state writes outside the owning lock, and
distributed-trace spans emitted without their join keys
(span-hygiene).  A default (path-less) run also certifies the
whole-program analyzers: the lock-order deadlock detector
(analysis/lockgraph — acyclic acquisition graph on the declared
partial order, *_locked caller-holds verified), wire-protocol
schema conformance (analysis/wire_schema — every struct format/TLV
tag against serve/wire.py, encoder/decoder symmetry, total
extension parsing), and the memory self-checks (analysis/memlife —
the v5e roofline/capacity literals stay single-sourced in
analysis/costmodel.py, and the committed fixture pair keeps proving
the donation delta in bytes).  Exits nonzero on any finding, so it
slots into CI as-is; tests/test_analysis.py runs the same checks as
a tier-1 test.

    python tools/lint_graft.py              # lint + lockgraph + wire
    python tools/lint_graft.py serve ft     # lint specific paths only
    python tools/lint_graft.py --json       # machine-readable findings
    python tools/lint_graft.py --dispatch   # + static dispatch certifier
                                            #   (lowers the zoo: slow,
                                            #   needs jax)

Waive a lint line with ``# lint: ok`` or ``# lint: ok(rule-name)``;
the whole-program analyzers take no waivers — fix the source or the
declared order/schema table.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

from cs744_ddp_tpu.analysis.pylint_rules import (DEFAULT_TARGETS,  # noqa: E402
                                                 lint_paths)


def _dispatch_findings():
    """Lower a small zoo and run the static round-trip certifier over
    it.  Import-gated: only the --dispatch path touches jax."""
    from cs744_ddp_tpu.analysis import audit, dispatch
    from cs744_ddp_tpu.analysis.pylint_rules import LintFinding
    result = audit.audit_zoo(global_batch=64, window=4,
                             strategies=("single", "ddp"),
                             collect_hlo=True)
    cert = dispatch.certify_zoo(result, window=4, nbatches=25)
    return [LintFinding(f["rule"], f["program"], 0, f["message"])
            for f in cert["findings"]]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        "lint_graft", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*",
                    help="files/directories to lint (default: "
                         + ", ".join(DEFAULT_TARGETS)
                         + ", plus the whole-program analyzers)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as a JSON array of "
                         "{rule, file, line, message} (CI diff "
                         "annotation); exit codes unchanged")
    ap.add_argument("--dispatch", action="store_true",
                    help="also run the static dispatch certifier over a "
                         "lowered zoo (slow; requires jax)")
    args = ap.parse_args(argv)
    if args.paths:
        paths = args.paths
        findings = lint_paths(paths)
    else:
        from cs744_ddp_tpu.analysis import lockgraph, memlife, wire_schema
        findings = lint_paths([os.path.join(_REPO_ROOT, t)
                               for t in DEFAULT_TARGETS])
        findings += lockgraph.check_locks(_REPO_ROOT)
        findings += wire_schema.check_wire(_REPO_ROOT)
        findings += memlife.check_memory(_REPO_ROOT)
    if args.dispatch:
        findings += _dispatch_findings()

    def rel(path: str) -> str:
        return (os.path.relpath(path, _REPO_ROOT)
                if os.path.isabs(path) else path)

    if args.as_json:
        print(json.dumps([{"rule": f.rule, "file": rel(f.path),
                           "line": f.line, "message": f.message}
                          for f in findings], indent=2))
        return 1 if findings else 0
    for f in findings:
        print(f"{rel(f.path)}:{f.line}: [{f.rule}] {f.message}")
    if findings:
        print(f"{len(findings)} finding(s)")
        return 1
    print("lint_graft: clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
