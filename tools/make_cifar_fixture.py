"""Generate the committed real-format CIFAR-10 test fixture.

Writes ``tests/assets/cifar-10-batches-py/`` in the EXACT on-disk format of
the real dataset the reference downloads via torchvision
(``/root/reference/src/Part 1/main.py:94-103``): one pickled dict per batch
file with ``b"data"`` — uint8 ``[N, 3072]``, each row the R plane then G
then B, each plane row-major 32x32 — and ``b"labels"`` — a plain list of
ints.  Keys are bytes and the pickle is protocol 2, matching what
``pickle.load(..., encoding="bytes")`` sees on the genuine (Python-2-era)
files.

This host has no egress (BASELINE.md: real-CIFAR *accuracy* remains
unverifiable), so the loader's bytes -> NHWC -> normalize path is instead
pinned at the byte level against this fixture (tests/test_data.py;
VERDICT r4 item 8).  64 images per file keeps the committed assets small
while covering every class.

Regenerate (deterministic, seed fixed): ``python tools/make_cifar_fixture.py``
"""

import os
import pickle

import numpy as np

N_PER_FILE = 64


def make_batch(rng: np.random.Generator, batch_label: bytes):
    """One batch dict in the genuine format (bytes keys, planar rows)."""
    data = rng.integers(0, 256, size=(N_PER_FILE, 3072), dtype=np.uint8)
    labels = [int(x) for x in rng.integers(0, 10, size=N_PER_FILE)]
    # Cover all 10 classes regardless of the draw (the fixture doubles as
    # an eval-path asset; empty classes would weaken it).
    labels[:10] = list(range(10))
    return {
        b"batch_label": batch_label,
        b"labels": labels,
        b"data": data,
        b"filenames": [b"fixture_%05d.png" % i for i in range(N_PER_FILE)],
    }


def main(out_root: str | None = None) -> str:
    here = os.path.dirname(os.path.abspath(__file__))
    out = out_root or os.path.join(here, os.pardir, "tests", "assets")
    batch_dir = os.path.join(out, "cifar-10-batches-py")
    os.makedirs(batch_dir, exist_ok=True)
    rng = np.random.default_rng(20260731)
    for i in range(1, 6):
        with open(os.path.join(batch_dir, f"data_batch_{i}"), "wb") as f:
            pickle.dump(make_batch(rng, b"training batch %d of 5" % i), f,
                        protocol=2)
    with open(os.path.join(batch_dir, "test_batch"), "wb") as f:
        pickle.dump(make_batch(rng, b"testing batch 1 of 1"), f, protocol=2)
    return batch_dir


if __name__ == "__main__":
    print(main())
