"""Measure the reference stack's throughput on this host: torch CPU VGG-11,
batch 256, SGD(0.1, 0.9, 1e-4) — the reference's exact training config
(/root/reference/src/Part 1/main.py:110-115) on synthetic data.

This supplies the vs_baseline denominator for bench.py, since the reference
publishes no numbers (BASELINE.json "published": {}).  Run:
    python tools/bench_torch_baseline.py [iters]
"""

import sys
import time

import numpy as np
import torch
import torch.nn as nn


def build_vgg11():
    cfg = [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"]
    layers, in_ch = [], 3
    for c in cfg:
        if c == "M":
            layers.append(nn.MaxPool2d(2, 2))
        else:
            layers += [nn.Conv2d(in_ch, c, 3, 1, 1, bias=True),
                       nn.BatchNorm2d(c), nn.ReLU(inplace=True)]
            in_ch = c

    class VGG(nn.Module):
        def __init__(self):
            super().__init__()
            self.layers = nn.Sequential(*layers)
            self.fc1 = nn.Linear(512, 10)

        def forward(self, x):
            y = self.layers(x)
            return self.fc1(y.view(y.size(0), -1))

    return VGG()


def main():
    iters = int(sys.argv[1]) if len(sys.argv) > 1 else 5
    torch.manual_seed(0)
    torch.set_num_threads(4)  # reference: Part 1/main.py:11
    model = build_vgg11()
    opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9,
                          weight_decay=1e-4)
    crit = nn.CrossEntropyLoss()
    rng = np.random.default_rng(0)
    x = torch.from_numpy(rng.normal(size=(256, 3, 32, 32)).astype(np.float32))
    y = torch.from_numpy(rng.integers(0, 10, 256).astype(np.int64))

    # warmup
    opt.zero_grad()
    crit(model(x), y).backward()
    opt.step()

    t0 = time.time()
    for _ in range(iters):
        opt.zero_grad()
        loss = crit(model(x), y)
        loss.backward()
        opt.step()
    dt = (time.time() - t0) / iters
    print(f"torch CPU VGG-11 batch 256: {dt:.3f} s/iter, "
          f"{256 / dt:.1f} images/sec")


if __name__ == "__main__":
    main()
