"""Perf attribution experiments for the VGG-11/f32/batch-256 headline config.

Times steady-state throughput of controlled variants on the real chip to
attribute the gap to the v5e ceiling (VERDICT r2 weak #2): augmentation,
BatchNorm, precision, batch size.  Not part of the bench contract — a
builder's tool; results inform BASELINE.md and optimization work.

Run (on the TPU chip): python tools/perf_attribution.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def throughput(**kw):
    """(img/s, MFU fields) for one variant.  MFU arithmetic lives in
    analysis/costmodel.mfu_fields — the one copy of the v5e peak constant
    (round 8); this tool only measures."""
    from cs744_ddp_tpu.analysis.costmodel import mfu_fields
    from cs744_ddp_tpu.train.loop import Trainer
    defaults = dict(model="vgg11", strategy="single", num_devices=1,
                    global_batch=256, data_dir="./data", log=lambda s: None)
    defaults.update(kw)
    tr = Trainer(**defaults)
    _, ips = tr.steady_state_throughput(max_iters=100)
    return ips, mfu_fields(ips, tr.step_flops_per_image())


def main():
    from cs744_ddp_tpu.utils.compcache import \
        enable_persistent_compilation_cache
    enable_persistent_compilation_cache(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    results = {}
    experiments = [
        ("baseline_f32_b256", {}),
        ("no_augment", {"augment": False}),
        ("bf16_b256", {"precision": "bf16"}),
        ("f32_b1024", {"global_batch": 1024}),
        ("bf16_b1024", {"global_batch": 1024, "precision": "bf16"}),
        ("bf16_b2048", {"global_batch": 2048, "precision": "bf16"}),
        ("bf16_b4096", {"global_batch": 4096, "precision": "bf16"}),
    ]
    for name, kw in experiments:
        t0 = time.time()
        ips, mfu = throughput(**kw)
        results[name] = {"images_per_sec": round(ips, 1), **mfu}
        print(f"{name:22s} {ips:10.1f} img/s  "
              f"mfu {mfu.get('mfu_vs_bf16_peak', '-')}  "
              f"(wall {time.time()-t0:.0f}s)", file=sys.stderr)
    print(json.dumps(results))


if __name__ == "__main__":
    main()
