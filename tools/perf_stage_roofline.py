"""Per-stage ISOLATED-OP roofline probe for the VGG-11 train step — with
a measured validity limit, kept on the record (VERDICT r3 item 1):

    Isolation is only honest for tensors LARGER than VMEM.  For stages
    whose activations fit (everything past 32x32x64 at batch 256), the
    measurement scan keeps the tensor VMEM-resident across iterations and
    the measured time lands BELOW the analytic HBM bound — not a
    measurement error but a different memory system than the real step,
    where the tensor round-trips HBM between layers.  Round 4 therefore
    attributes the whole step from per-op profiler traces instead
    (BASELINE.md "Single-chip performance work"); this tool remains valid
    for the >VMEM stage-0 ops (where it confirmed pool backward at ~100%
    of its bandwidth bound, and BN backward between its 3-pass and 5-pass
    formulations) and as the recorded methodological negative result.

Each stage's forward and backward is measured in isolation on the chip and
compared against its compute bound (197 TFLOP/s v5e bf16 peak — f32 convs
run bf16 multiply passes at JAX's default precision) and its HBM bandwidth
bound (~819 GB/s v5e).

Method: scanned-K measurement (see tools/perf_pieces.py — the tunneled
backend's ~100 ms dispatch cost demands in-program repetition), with the
carry threaded through each iteration's input (`x + 0.0*f(y)` — float
semantics forbid XLA from folding 0*x, so the chain is sequential and
nothing is DCE'd or hoisted).  Backward = (fwd+bwd) − fwd, both measured.

Bytes model (f32=4, bf16=2 bytes/elem), minimum HBM traffic:
  conv fwd : read x, w       ; write y
  conv bwd : read dy, x, w   ; write dx, dw
  bn   fwd : read x (2 passes: centered stats, then normalize); write y
  bn   bwd : read xhat, dy (x2: two fused reduction+apply passes); write dx
  pool fwd : read x; write y (y is 1/4 of x)
  pool bwd : read x, dy; write dx   (select-and-scatter re-derives argmax)

Run:  python tools/perf_stage_roofline.py [--precision f32] [--batch 256]
Results recorded in BASELINE.md ("Per-stage roofline").
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cs744_ddp_tpu.analysis.costmodel import (  # noqa: E402
    V5E_BF16_PEAK_FLOPS as V5E_PEAK_FLOPS,
    V5E_HBM_BYTES_PER_S as V5E_HBM_BYTES)

R = 3            # timed dispatches (min taken; first extra dispatch warms)
TARGET_MS = 300  # device work per dispatch: >> the ~±10 ms dispatch jitter

# VGG-11 conv stages at 32x32 input: (H=W, Cin, Cout); pool after stages
# marked in POOL_AFTER (reference model.py:3-8, cfg 'VGG11').
STAGES = [(32, 3, 64), (16, 64, 128), (8, 128, 256), (8, 256, 256),
          (4, 256, 512), (4, 512, 512), (2, 512, 512), (2, 512, 512)]
POOL_AFTER = {0, 1, 3, 5, 7}


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--precision", choices=("f32", "bf16"), default="f32")
    args = p.parse_args(argv)

    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax import lax

    from cs744_ddp_tpu.models import layers
    from cs744_ddp_tpu.utils.compcache import \
        enable_persistent_compilation_cache

    enable_persistent_compilation_cache(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    B = args.batch
    dtype = jnp.bfloat16 if args.precision == "bf16" else jnp.float32
    esize = 2 if args.precision == "bf16" else 4
    rng = np.random.default_rng(0)

    def bench_total(body, carry, k, *consts):
        """min-of-R TOTAL seconds for a K-iteration scan of `body`.

        The program returns a SCALAR reduction of the final carry: fetching
        the carry itself would drag megabytes through the tunnel per fence
        (a 67 MB activation takes seconds at tunnel bandwidth and its
        variance swamped the measurement in the first version of this
        tool); the scalar still transitively fences the whole chain."""
        def scanned(carry, *cs):
            def one(c, i):
                return body(c, i, *cs), ()
            c, _ = lax.scan(one, carry, jnp.arange(k))
            return jnp.mean(c.astype(jnp.float32))
        fn = jax.jit(scanned)
        np.asarray(fn(carry, *consts))               # compile+warm fence
        ts = []
        for _ in range(R):
            t0 = time.time()
            out = fn(carry, *consts)
            np.asarray(out)                          # value-fetch fence
            ts.append(time.time() - t0)
        return min(ts)

    # One dispatch's fixed cost (the ~100 ms tunnel tax): a trivial scan.
    null_total = bench_total(lambda c, i: c + 1.0, jnp.float32(0), 50)

    def bench_body(body, carry, est_roof_ms, *consts):
        """Per-iteration ms, K sized so device work is ~TARGET_MS per
        dispatch (the dispatch jitter is then a few % of signal), minus
        the dispatch's fixed cost."""
        k = int(min(max(TARGET_MS / max(est_roof_ms, 1e-3), 100), 20000))
        total = bench_total(body, carry, k, *consts)
        return max(total - null_total, 0.0) / k * 1e3

    def report(name, measured_ms, flops, bytes_):
        t_flops = flops / V5E_PEAK_FLOPS * 1e3
        t_bytes = bytes_ / V5E_HBM_BYTES * 1e3
        roof = max(t_flops, t_bytes)
        bound = "MXU" if t_flops >= t_bytes else "HBM"
        print(json.dumps({
            "stage": name, "measured_ms": round(measured_ms, 4),
            "compute_ms": round(t_flops, 4), "hbm_ms": round(t_bytes, 4),
            "roofline_ms": round(roof, 4), "bound": bound,
            "pct_of_roofline": round(100 * roof / measured_ms, 1)
            if measured_ms > 0 else None}))
        return measured_ms, roof

    totals = {"measured": 0.0, "roof": 0.0}

    for si, (H, Cin, Cout) in enumerate(STAGES):
        x = jnp.asarray(rng.normal(size=(B, H, H, Cin)), dtype)
        conv_p = {k: v for k, v in layers.conv2d_init(
            jax.random.PRNGKey(si), Cin, Cout).items()}
        dy = jnp.asarray(rng.normal(size=(B, H, H, Cout)), dtype)

        def conv_fwd(c, i, x, w, b):
            y = layers.conv2d_apply({"w": w, "b": b}, c)
            return x + 0.0 * jnp.mean(y)          # sequential, no DCE

        def conv_fwd_bwd(c, i, x, w, b, dy):
            def f(xx, ww):
                return layers.conv2d_apply({"w": ww, "b": b}, xx)
            y, vjp = jax.vjp(f, c, w)
            dx, dw = vjp(dy)
            return x + 0.0 * (jnp.mean(y) + jnp.mean(dx) + jnp.mean(dw))

        nhw = B * H * H
        wbytes = 9 * Cin * Cout * 4               # master weights stay f32
        f_flops = 2 * nhw * 9 * Cin * Cout
        f_bytes = nhw * Cin * esize + wbytes + nhw * Cout * esize
        b_flops = 2 * f_flops                     # dx conv + dw correlation
        b_bytes = (nhw * Cout * esize + nhw * Cin * esize + wbytes
                   + nhw * Cin * esize + wbytes)
        est_f = max(f_flops / V5E_PEAK_FLOPS, f_bytes / V5E_HBM_BYTES) * 1e3
        est_b = max(b_flops / V5E_PEAK_FLOPS, b_bytes / V5E_HBM_BYTES) * 1e3
        t_f = bench_body(conv_fwd, x, est_f, x, conv_p["w"], conv_p["b"])
        t_fb = bench_body(conv_fwd_bwd, x, est_f + est_b, x, conv_p["w"],
                          conv_p["b"], dy)
        m, r = report(f"conv{si} {H}x{H} {Cin}->{Cout} fwd", t_f,
                      f_flops, f_bytes)
        totals["measured"] += m
        totals["roof"] += r
        m, r = report(f"conv{si} {H}x{H} {Cin}->{Cout} bwd", t_fb - t_f,
                      b_flops, b_bytes)
        totals["measured"] += m
        totals["roof"] += r

        # BatchNorm after every conv.
        bn_p, _ = layers.batchnorm_init(Cout)

        def bn_fwd(c, i, dy_unused, g, b):
            y, _, _ = layers._bn_train_norm(c, g, b)
            return c + 0.0 * jnp.mean(y)

        def bn_fwd_bwd(c, i, dy, g, b):
            def f(xx):
                y, m_, v_ = layers._bn_train_norm(xx, g, b)
                return y
            y, vjp = jax.vjp(f, c)
            (dx,) = vjp(dy)
            return c + 0.0 * (jnp.mean(y) + jnp.mean(dx))

        act = jnp.asarray(rng.normal(size=(B, H, H, Cout)), dtype)
        abytes = B * H * H * Cout * esize
        # fwd: read x twice (centered stats), write y = 3 passes.
        # bwd: the dx formula depends on full-batch sums, so the minimum
        # is pass 1 read (xhat, dy) + pass 2 read (xhat, dy) + write dx
        # = 5 activation passes (matching the bytes model above).
        est_bn_f = 3 * abytes / V5E_HBM_BYTES * 1e3
        est_bn_b = 5 * abytes / V5E_HBM_BYTES * 1e3
        t_f = bench_body(bn_fwd, act, est_bn_f, dy, bn_p["gamma"],
                         bn_p["beta"])
        t_fb = bench_body(bn_fwd_bwd, act, est_bn_f + est_bn_b, dy,
                          bn_p["gamma"], bn_p["beta"])
        m, r = report(f"bn{si} ({Cout}ch @{H}) fwd", t_f,
                      0, 3 * abytes)
        totals["measured"] += m
        totals["roof"] += r
        m, r = report(f"bn{si} ({Cout}ch @{H}) bwd", t_fb - t_f,
                      0, 5 * abytes)
        totals["measured"] += m
        totals["roof"] += r

        if si in POOL_AFTER:
            def pool_fwd(c, i):
                y = layers.maxpool2x2(c)
                return c + 0.0 * jnp.mean(y)

            def pool_fwd_bwd(c, i, dyp):
                y, vjp = jax.vjp(layers.maxpool2x2, c)
                (dx,) = vjp(dyp)
                return c + 0.0 * (jnp.mean(y) + jnp.mean(dx))

            dyp = jnp.asarray(
                rng.normal(size=(B, H // 2, H // 2, Cout)), dtype)
            est_p = 1.25 * abytes / V5E_HBM_BYTES * 1e3
            t_f = bench_body(pool_fwd, act, est_p)
            t_fb = bench_body(pool_fwd_bwd, act, 3 * est_p, dyp)
            m, r = report(f"pool{si} ({Cout}ch @{H}) fwd", t_f,
                          0, abytes + abytes // 4)
            totals["measured"] += m
            totals["roof"] += r
            m, r = report(f"pool{si} ({Cout}ch @{H}) bwd", t_fb - t_f,
                          0, 2 * abytes + abytes // 4)
            totals["measured"] += m
            totals["roof"] += r

    print(json.dumps({
        "stage": "TOTAL (conv+bn+pool, fwd+bwd)",
        "measured_ms": round(totals["measured"], 3),
        "roofline_ms": round(totals["roof"], 3),
        "pct_of_roofline": round(
            100 * totals["roof"] / totals["measured"], 1),
        "batch": B, "precision": args.precision}))


if __name__ == "__main__":
    main()
