"""Per-time-slice TensorCore/DMA occupancy account from an XPlane trace.

VERDICT r4 item 1 asked whether the step leaves recoverable idle time —
the additive (no-overlap) roofline in BASELINE.md conceded ~45% of the
bf16 step to *un-overlapped* memory time, which would make DMA/compute
overlap the obvious lever (microbatch pipelining etc.).  This tool answers
from the trace the framework already collects (``--profile-dir``):

  * window span of the LAST ``jit_window`` module dispatch (steady state:
    earlier dispatches carry compile/warmup),
  * TensorCore busy = union of leaf "XLA Ops" events (the ``while`` scan
    wrapper, ``*-start`` markers excluded) — on TPU this line is the
    serialized TC execution, so window − union is TRUE TC idle,
  * DMA busy = union of "Async XLA Ops" events (async copies overlapped
    by the scheduler),
  * recoverable := both-idle + TC-idle-during-DMA — the only time any
    scheduling change (pipelining, reordering, prefetching) could win,
  * TC busy split MXU-class vs other: each event name is mapped into the
    freshly compiled window HLO (same config + persistent compilation
    cache => same module) and classed MXU if its fusion's computation
    contains a ``convolution(`` / `` dot(`` — giving the kernel-efficiency
    ceiling: were every non-conv op free, the step could not run faster
    than the conv-fusion time.

Run (on the TPU chip):
  python tools/perf_occupancy.py                     # bf16/b1536 peak config
  python tools/perf_occupancy.py --precision f32 --global-batch 256
"""

import argparse
import collections
import glob
import json
import os
import re

import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")


def build_mxu_map(model, global_batch, precision, window):
    """{instruction_name: True if its computation runs on the MXU} from the
    compiled window program's final HLO text."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from cs744_ddp_tpu.models import get_model
    from cs744_ddp_tpu.ops import sgd
    from cs744_ddp_tpu.parallel import get_strategy, mesh as meshlib
    from cs744_ddp_tpu.train import step as steplib

    mesh = meshlib.make_mesh(1)
    init_fn, apply_fn = get_model(model)
    state = steplib.init_train_state(init_fn, jax.random.PRNGKey(0))
    state = meshlib.put_global_tree(state, meshlib.replicated(mesh))
    win = steplib.make_train_window(
        apply_fn, get_strategy("single"), mesh, sgd.SGDConfig(),
        augment=True,
        compute_dtype=jnp.bfloat16 if precision == "bf16" else None)
    from jax.sharding import NamedSharding, PartitionSpec as P
    esh = NamedSharding(mesh, P(None, meshlib.DATA_AXIS))
    nb = window
    args = (state, jax.random.PRNGKey(0),
            jax.ShapeDtypeStruct((nb, global_batch, 32, 32, 3), jnp.uint8,
                                 sharding=esh),
            jax.ShapeDtypeStruct((nb, global_batch), jnp.int32, sharding=esh),
            jnp.int32(0), jnp.zeros((window,), jnp.int8))
    txt = win.lower(*args).compile().as_text()

    # Computations containing MXU work.
    comp_mxu = {}
    cur = None
    for line in txt.splitlines():
        m = re.match(r"^\s*(?:ENTRY\s+)?%?([\w.\-]+)\s*(?:\([^)]*\))?\s*"
                     r"(?:->[^{]*)?\{\s*$", line)
        if m and line.rstrip().endswith("{") and "=" not in line:
            cur = m.group(1)
            comp_mxu.setdefault(cur, False)
            continue
        if cur and (" convolution(" in line or " dot(" in line):
            comp_mxu[cur] = True
    # Instructions: direct convs are MXU; fusions inherit their called
    # computation's class.
    instr_mxu = {}
    for line in txt.splitlines():
        m = re.match(r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=", line)
        if not m:
            continue
        name = m.group(1)
        instr_mxu.setdefault(name, False)  # every instruction classifies
        if " convolution(" in line or " dot(" in line:
            instr_mxu[name] = True
        cm = re.search(r"calls=%?([\w.\-]+)", line)
        if cm:
            instr_mxu[name] = instr_mxu.get(name, False) or \
                comp_mxu.get(cm.group(1), False)
    return instr_mxu


def union(intervals):
    intervals = sorted(intervals)
    out = []
    for s, t in intervals:
        if out and s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], t)
        else:
            out.append([s, t])
    return out


def span(intervals):
    return sum(t - s for s, t in intervals)


def intersect(a, b):
    """Total overlap between two interval unions."""
    i = j = tot = 0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        t = min(a[i][1], b[j][1])
        if t > s:
            tot += t - s
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return tot


def complement(intervals, t0, t1):
    out = []
    prev = t0
    for s, t in intervals:
        if s > prev:
            out.append([prev, s])
        prev = max(prev, t)
    if t1 > prev:
        out.append([prev, t1])
    return out


def analyze(trace_file, mxu_map, window_iters):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2
    xs = xplane_pb2.XSpace()
    with open(trace_file, "rb") as f:
        xs.ParseFromString(f.read())
    tpu = [p for p in xs.planes if p.name == "/device:TPU:0"][0]
    md = tpu.event_metadata
    lines = {l.name: l for l in tpu.lines}
    wins = [e for e in lines["XLA Modules"].events
            if "window" in md[e.metadata_id].name]
    if not wins:
        raise RuntimeError("no jit_window module event in trace")
    w = wins[-1]
    t0, t1 = w.offset_ps, w.offset_ps + w.duration_ps

    tc, per_op = [], collections.Counter()
    mxu_time = other_time = unknown_time = 0
    for e in lines["XLA Ops"].events:
        if not (t0 <= e.offset_ps < t1):
            continue
        name = md[e.metadata_id].name
        inst = re.match(r"%?([\w.\-]+)\s*=", name)
        inst = inst.group(1) if inst else name
        op = re.search(r"=\s*[^=]*?\s([a-z][\w\-]*)\(", name)
        op = op.group(1) if op else "?"
        if op in ("while", "copy-start", "async-start", "all-reduce-start"):
            continue  # containers/markers, not TC execution time
        tc.append([e.offset_ps, e.offset_ps + e.duration_ps])
        per_op[(inst, op)] += e.duration_ps
        if op in ("convolution", "dot"):
            mxu_time += e.duration_ps
        elif inst in mxu_map:
            if mxu_map[inst]:
                mxu_time += e.duration_ps
            else:
                other_time += e.duration_ps
        else:
            unknown_time += e.duration_ps

    dma = [[e.offset_ps, e.offset_ps + e.duration_ps]
           for e in lines["Async XLA Ops"].events
           if t0 <= e.offset_ps < t1]

    tc_u, dma_u = union(tc), union(dma)
    tc_idle = complement(tc_u, t0, t1)
    win_ps = t1 - t0
    tc_busy = span(tc_u)
    idle_during_dma = intersect(tc_idle, dma_u)
    both_idle = span(tc_idle) - idle_during_dma
    top = [{"op": f"{i} [{o}]", "ms": round(d / 1e9, 3),
            "class": ("mxu" if (o in ("convolution", "dot")
                                or mxu_map.get(i, False)) else "other")}
           for (i, o), d in per_op.most_common(12)]
    return {
        "window_ms": round(win_ps / 1e9, 3),
        "iters": window_iters,
        "per_iter_ms": round(win_ps / 1e9 / window_iters, 3),
        "tc_busy_ms": round(tc_busy / 1e9, 3),
        "tc_busy_pct": round(100 * tc_busy / win_ps, 2),
        "dma_busy_ms": round(span(dma_u) / 1e9, 3),
        "dma_busy_pct": round(100 * span(dma_u) / win_ps, 2),
        "tc_idle_during_dma_ms": round(idle_during_dma / 1e9, 3),
        "both_idle_ms": round(both_idle / 1e9, 3),
        "recoverable_pct": round(
            100 * (idle_during_dma + both_idle) / win_ps, 2),
        "tc_mxu_class_ms": round(mxu_time / 1e9, 3),
        "tc_other_class_ms": round(other_time / 1e9, 3),
        "tc_unclassified_ms": round(unknown_time / 1e9, 3),
        "mxu_class_pct_of_busy": round(100 * mxu_time / max(tc_busy, 1), 2),
        "top_ops": top,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="vgg11")
    ap.add_argument("--global-batch", type=int, default=1536)
    ap.add_argument("--precision", default="bf16")
    ap.add_argument("--window", type=int, default=20)
    ap.add_argument("--trace", help="existing .xplane.pb (skip measurement)")
    args = ap.parse_args()

    from cs744_ddp_tpu.utils.compcache import \
        enable_persistent_compilation_cache
    enable_persistent_compilation_cache(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    mxu_map = build_mxu_map(args.model, args.global_batch, args.precision,
                            args.window)
    trace = args.trace
    if trace is None:
        import jax
        from cs744_ddp_tpu.data import cifar10
        from cs744_ddp_tpu.train.loop import Trainer
        # Size the synthetic epoch to exactly two full windows so the LAST
        # window dispatch has args.window iterations (per_iter_ms correct).
        cifar10.TRAIN_SIZE = 2 * args.window * args.global_batch
        tr = Trainer(model=args.model, strategy="single", num_devices=1,
                     global_batch=args.global_batch,
                     precision=args.precision,
                     data_dir=tempfile.mkdtemp(), log=lambda s: None,
                     limit_train_batches=2 * args.window)
        tr.train_model(0)  # compile/warm outside the trace
        prof = tempfile.mkdtemp(prefix="occupancy_")
        with jax.profiler.trace(prof):
            tr.train_model(0)
        traces = glob.glob(prof + "/**/*.xplane.pb", recursive=True)
        trace = traces[0]
    result = {"config": f"{args.model}/{args.precision}/"
                        f"b{args.global_batch}/W{args.window}",
              **analyze(trace, mxu_map, args.window)}
    print(json.dumps(result))


if __name__ == "__main__":
    main()
