"""Functional NN layer primitives with PyTorch-default initialization.

The reference model zoo (``/root/reference/src/Part 1/model.py``) is built from
``nn.Conv2d(3x3, pad=1, bias=True)`` + ``nn.BatchNorm2d`` + ``nn.ReLU`` blocks
with ``nn.MaxPool2d(2,2)`` and a final ``nn.Linear``.  This module supplies the
same primitives as pure functions over parameter pytrees — the TPU-idiomatic
formulation: arrays are NHWC (XLA:TPU's preferred conv layout), every apply is
traceable/jittable, and state (BatchNorm running stats) is threaded explicitly.

Initialization matches PyTorch defaults exactly so that loss curves are
comparable to the reference:

  * Conv2d / Linear weight: ``kaiming_uniform_(a=sqrt(5))`` which reduces to
    ``U(-1/sqrt(fan_in), 1/sqrt(fan_in))``.
  * Conv2d / Linear bias:   ``U(-1/sqrt(fan_in), 1/sqrt(fan_in))``.
  * BatchNorm: gamma=1, beta=0, running_mean=0, running_var=1.

(see torch.nn.modules.conv/linear reset_parameters; verified against torch in
tests/test_layers.py).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax


Params = Dict[str, Any]
State = Dict[str, Any]

# BatchNorm constants matching torch.nn.BatchNorm2d defaults.
BN_MOMENTUM = 0.1
BN_EPS = 1e-5


def _torch_uniform(key: jax.Array, shape: Tuple[int, ...], bound: float,
                   dtype=jnp.float32) -> jax.Array:
    return jax.random.uniform(key, shape, dtype, minval=-bound, maxval=bound)


# ---------------------------------------------------------------------------
# Conv2d (3x3/anything, NHWC activations, HWIO weights)
# ---------------------------------------------------------------------------

def conv2d_init(key: jax.Array, in_ch: int, out_ch: int, ksize: int = 3,
                dtype=jnp.float32, *, bias: bool = True) -> Params:
    """PyTorch-default conv init. Weight stored HWIO (TPU-native layout).

    ``bias=False`` matches ``nn.Conv2d(..., bias=False)`` — used by ResNet
    blocks where a BatchNorm immediately follows.
    """
    wkey, bkey = jax.random.split(key)
    fan_in = in_ch * ksize * ksize
    bound = 1.0 / math.sqrt(fan_in)
    p = {"w": _torch_uniform(wkey, (ksize, ksize, in_ch, out_ch), bound, dtype)}
    if bias:
        p["b"] = _torch_uniform(bkey, (out_ch,), bound, dtype)
    return p


def conv2d_apply(params: Params, x: jax.Array, stride: int = 1,
                 padding: int = 1) -> jax.Array:
    """x: [N,H,W,C] -> [N,H',W',out_ch].

    Compute dtype follows the ACTIVATION: master weights stay f32 and are
    cast to x.dtype here (a no-op for f32 x), so feeding bf16 activations
    runs the conv natively on the MXU (bf16 multiply, f32 accumulate)
    without a separate low-precision parameter copy."""
    y = lax.conv_general_dilated(
        x, params["w"].astype(x.dtype),
        window_strides=(stride, stride),
        padding=[(padding, padding), (padding, padding)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    if "b" in params:
        y = y + params["b"].astype(y.dtype)
    return y


# ---------------------------------------------------------------------------
# Linear
# ---------------------------------------------------------------------------

def linear_init(key: jax.Array, in_features: int, out_features: int,
                dtype=jnp.float32) -> Params:
    wkey, bkey = jax.random.split(key)
    bound = 1.0 / math.sqrt(in_features)
    # Stored [in, out] so apply is x @ w (no transpose on the MXU).
    return {
        "w": _torch_uniform(wkey, (in_features, out_features), bound, dtype),
        "b": _torch_uniform(bkey, (out_features,), bound, dtype),
    }


def linear_apply(params: Params, x: jax.Array) -> jax.Array:
    # Master weights f32, compute in the activation dtype (see conv2d_apply).
    return x @ params["w"].astype(x.dtype) + params["b"].astype(x.dtype)


# ---------------------------------------------------------------------------
# BatchNorm2d (torch semantics)
# ---------------------------------------------------------------------------

def batchnorm_init(num_features: int, dtype=jnp.float32) -> Tuple[Params, State]:
    params = {
        "gamma": jnp.ones((num_features,), dtype),
        "beta": jnp.zeros((num_features,), dtype),
    }
    state = {
        "mean": jnp.zeros((num_features,), dtype),
        "var": jnp.ones((num_features,), dtype),
    }
    return params, state


def _make_bn_train_norm(fence: bool):
    """Build the fused-backward BN normalizer; ``fence`` selects whether
    the backward ends in an ``optimization_barrier`` (see _bn_train_bwd).
    Two instances exist because custom_vjp rules are bound per function
    object — the fence choice must be made at trace time, per model."""

    @jax.custom_vjp
    def bn_train_norm(x: jax.Array, gamma: jax.Array, beta: jax.Array):
        y, _, mean, var, _ = _bn_train_fwd_impl(x, gamma, beta)
        return y, mean, var

    bn_train_norm.defvjp(_bn_train_fwd,
                         partial(_bn_train_bwd, fence=fence))
    return bn_train_norm


def _bn_train_fwd_impl(x, gamma, beta):
    xf = x.astype(jnp.float32)
    axes = (0, 1, 2)
    mean = jnp.mean(xf, axes)
    if x.dtype == jnp.bfloat16:
        # bf16 mode: ONE-PASS statistics (sum and sum-of-squares in the
        # same read), clamped at zero.  The centered form's extra full
        # activation pass was the single largest cost bucket of the bf16
        # peak step (profiled round 4: the convert_reduce stats fusions
        # were ~26% of step time; one-pass measured +3.9% whole-step).
        # Numerically safe HERE because accumulation is f32 and post-BN/
        # post-conv activations have |mean|/std = O(1) — the catastrophic-
        # cancellation regime (|mean|/std >> 1) that rules one-pass out
        # for the f32 parity path cannot arise from bf16 inputs of this
        # magnitude.  bf16 mode is already a documented deviation
        # (BASELINE.md); the f32 path below keeps torch-parity centered
        # two-pass semantics.
        var = jnp.maximum(
            jnp.mean(jnp.square(xf), axes) - jnp.square(mean), 0.0)
    else:
        var = jnp.mean(jnp.square(xf - mean), axes)  # biased, centered
    inv = lax.rsqrt(var + BN_EPS)
    xhat = (xf - mean) * inv
    y = (xhat * gamma + beta).astype(x.dtype)
    return y, xhat, mean, var, inv


def _bn_train_fwd(x, gamma, beta):
    y, xhat, mean, var, inv = _bn_train_fwd_impl(x, gamma, beta)
    # The activation-sized residual is stored in the ACTIVATION dtype: in
    # bf16 mode that halves the dominant backward-pass HBM traffic, and the
    # backward's reductions still accumulate in f32.
    return (y, mean, var), (xhat.astype(x.dtype), inv, gamma)


def _bn_train_bwd(res, cts, *, fence: bool = True):
    """The closed-form fused BN backward (two passes over the activation).

    Forward computes CENTERED two-pass statistics in f32 (the one-pass
    E[x^2]-E[x]^2 form cancels catastrophically for large mean/std ratios
    — and torch's BatchNorm2d is centered, so parity demands it); this
    backward uses the closed-form BN gradient instead of letting autodiff
    differentiate through the statistics chain, which materializes several
    extra activation-sized intermediates — BN is HBM-bandwidth-bound, so
    passes are the cost that matters on TPU.

    The mean/var outputs feed only the (non-differentiated) running-stats
    update — torch likewise treats running stats as statistics, outside
    the autograd graph — so their cotangents are normally zero (exact
    terms are still applied below)."""
    xhat_stored, inv, gamma = res
    in_dtype = xhat_stored.dtype
    xhat = xhat_stored.astype(jnp.float32)
    dy = cts[0].astype(jnp.float32)
    axes = (0, 1, 2)
    n = xhat.shape[0] * xhat.shape[1] * xhat.shape[2]
    sum_dy = jnp.sum(dy, axes)
    sum_dy_xhat = jnp.sum(dy * xhat, axes)
    dx = (gamma * inv / n) * (n * dy - sum_dy - xhat * sum_dy_xhat)
    # Exact cotangent terms for the mean/var outputs (normally literal
    # zeros — they feed only the non-differentiated running-stats update,
    # and XLA folds the zero contributions — but a future loss term
    # touching the statistics gets CORRECT gradients, not silent zeros):
    # d mean / d x_i = 1/n;  d var / d x_i = 2 (x_i - mean) / n.
    ct_mean = cts[1].astype(jnp.float32)
    ct_var = cts[2].astype(jnp.float32)
    dx = dx + ct_mean / n + (2.0 / n) * ct_var * (xhat / inv)
    # Fusion fence history and policy.  Round 3: XLA:TPU's post-main-
    # fusion pass SIGILLed compiling models with more than ~8 of these
    # custom backward blocks inside shard_map (vgg13/16/19 and resnet18
    # all crashed; vgg11 — exactly 8 BNs — compiled), so the barrier was
    # mandatory armor.  Round 4: the crash no longer reproduces on the
    # current toolchain (probed unfenced at batch 256: vgg13/19 and
    # resnet18/34; vgg16 is locked by the AOT compile test, which builds
    # every VGG unfenced), which turns the fence into a pure
    # compiler-SCHEDULING choice
    # — the barrier is numerically an identity, and the CPU backend
    # strips it.  Measured per family on v5e (BASELINE.md round 4):
    # unfenced wins for VGGs (+6.9/+14.1/+9.5% for vgg11/13/19, so
    # models/vgg.py passes fence=False), fenced wins for ResNets
    # (resnet18 +7% fenced — capping fusion clusters at the BN boundary
    # schedules the deep residual graph better; models/resnet.py keeps
    # the default).  The AOT tests compile both regimes, so a compiler
    # regression on either path fails CI loudly.
    if not fence:
        return (dx.astype(in_dtype), sum_dy_xhat, sum_dy)
    return lax.optimization_barrier(
        (dx.astype(in_dtype), sum_dy_xhat, sum_dy))


_bn_train_norm = _make_bn_train_norm(fence=True)
_bn_train_norm_unfenced = _make_bn_train_norm(fence=False)


def batchnorm_apply(params: Params, state: State, x: jax.Array, *,
                    train: bool, fence: bool = True
                    ) -> Tuple[jax.Array, State]:
    """Torch-parity BatchNorm over NHWC.

    ``fence`` selects the fenced (default) or unfenced backward — a
    compiler-scheduling choice with identical numerics (the barrier is
    semantically an identity); measured winners per model family are
    recorded in _bn_train_bwd.

    Training normalizes with the *biased* batch variance and updates running
    stats with the *unbiased* variance (torch.nn.BatchNorm2d semantics,
    momentum=0.1).  In the data-parallel setting the batch stats are the
    *local shard's* stats — matching the reference, where each replica's BN
    sees only its own shard (SURVEY.md §7 "BatchNorm semantics in DP").

    Statistics and normalization math always run in f32 — summing tens of
    thousands of bf16 activations per channel would lose the mean — and the
    result is cast back to the activation dtype (no-op for f32).
    """
    if train:
        norm = _bn_train_norm if fence else _bn_train_norm_unfenced
        y, mean, var = norm(x, params["gamma"], params["beta"])
        n = x.shape[0] * x.shape[1] * x.shape[2]
        unbiased = var * (n / max(n - 1, 1))
        new_state = {
            "mean": (1 - BN_MOMENTUM) * state["mean"] + BN_MOMENTUM * mean,
            "var": (1 - BN_MOMENTUM) * state["var"] + BN_MOMENTUM * unbiased,
        }
        return y, new_state

    xf = x.astype(jnp.float32)
    inv = lax.rsqrt(state["var"] + BN_EPS)
    y = (xf - state["mean"]) * inv * params["gamma"] + params["beta"]
    return y.astype(x.dtype), state


# ---------------------------------------------------------------------------
# MaxPool 2x2/2 (reference model.py:16: MaxPool2d(kernel_size=2, stride=2))
# ---------------------------------------------------------------------------

def maxpool2x2(x: jax.Array) -> jax.Array:
    """Non-overlapping 2x2/2 max pool.

    Deliberately the plain ``reduce_window`` whose autodiff backward is
    XLA's ``select-and-scatter``: it profiles at ~12% of the VGG-11 train
    step on v5e, but both jnp-level replacements tried in round 3 (6-D
    block-view transpose masks; stride-2 corner slices with contiguous
    interleave-reshapes) measured 20-25% SLOWER end-to-end — stride-2
    spatial access fights the (8,128) tiling harder than the native
    scatter does.  Round 4 additionally tried a fully fused custom-vjp
    BN->relu->pool BACKWARD (pool scatter + relu gate + both BN
    reductions in one formula, derived from the saved BN xhat — halving
    the nominal activation passes) in two formulations: strided
    slice/stack masks and slice-free 6-D broadcast masks with a priority-
    score tie-break.  Both were ~15% slower WHOLE-STEP than this native
    path (91.9k -> 77-78k img/s on v5e) despite moving fewer bytes —
    XLA's select-and-scatter plus its fusion choices beat jnp-level
    window masks on this hardware every time it has been tried.  Gradient
    tie-breaking (first maximal element per window, torch's convention)
    is pinned in tests/test_layers.py.
    """
    return lax.reduce_window(
        x, -jnp.inf, lax.max,
        window_dimensions=(1, 2, 2, 1),
        window_strides=(1, 2, 2, 1),
        padding="VALID",
    )


def relu(x: jax.Array) -> jax.Array:
    return jnp.maximum(x, 0)
