"""Functional NN layer primitives with PyTorch-default initialization.

The reference model zoo (``/root/reference/src/Part 1/model.py``) is built from
``nn.Conv2d(3x3, pad=1, bias=True)`` + ``nn.BatchNorm2d`` + ``nn.ReLU`` blocks
with ``nn.MaxPool2d(2,2)`` and a final ``nn.Linear``.  This module supplies the
same primitives as pure functions over parameter pytrees — the TPU-idiomatic
formulation: arrays are NHWC (XLA:TPU's preferred conv layout), every apply is
traceable/jittable, and state (BatchNorm running stats) is threaded explicitly.

Initialization matches PyTorch defaults exactly so that loss curves are
comparable to the reference:

  * Conv2d / Linear weight: ``kaiming_uniform_(a=sqrt(5))`` which reduces to
    ``U(-1/sqrt(fan_in), 1/sqrt(fan_in))``.
  * Conv2d / Linear bias:   ``U(-1/sqrt(fan_in), 1/sqrt(fan_in))``.
  * BatchNorm: gamma=1, beta=0, running_mean=0, running_var=1.

(see torch.nn.modules.conv/linear reset_parameters; verified against torch in
tests/test_layers.py).
"""

from __future__ import annotations

import math
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

Params = Dict[str, Any]
State = Dict[str, Any]

# BatchNorm constants matching torch.nn.BatchNorm2d defaults.
BN_MOMENTUM = 0.1
BN_EPS = 1e-5


def _torch_uniform(key: jax.Array, shape: Tuple[int, ...], bound: float,
                   dtype=jnp.float32) -> jax.Array:
    return jax.random.uniform(key, shape, dtype, minval=-bound, maxval=bound)


# ---------------------------------------------------------------------------
# Conv2d (3x3/anything, NHWC activations, HWIO weights)
# ---------------------------------------------------------------------------

def conv2d_init(key: jax.Array, in_ch: int, out_ch: int, ksize: int = 3,
                dtype=jnp.float32, *, bias: bool = True) -> Params:
    """PyTorch-default conv init. Weight stored HWIO (TPU-native layout).

    ``bias=False`` matches ``nn.Conv2d(..., bias=False)`` — used by ResNet
    blocks where a BatchNorm immediately follows.
    """
    wkey, bkey = jax.random.split(key)
    fan_in = in_ch * ksize * ksize
    bound = 1.0 / math.sqrt(fan_in)
    p = {"w": _torch_uniform(wkey, (ksize, ksize, in_ch, out_ch), bound, dtype)}
    if bias:
        p["b"] = _torch_uniform(bkey, (out_ch,), bound, dtype)
    return p


def conv2d_apply(params: Params, x: jax.Array, stride: int = 1,
                 padding: int = 1) -> jax.Array:
    """x: [N,H,W,C] -> [N,H',W',out_ch]."""
    y = lax.conv_general_dilated(
        x, params["w"],
        window_strides=(stride, stride),
        padding=[(padding, padding), (padding, padding)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    if "b" in params:
        y = y + params["b"]
    return y


# ---------------------------------------------------------------------------
# Linear
# ---------------------------------------------------------------------------

def linear_init(key: jax.Array, in_features: int, out_features: int,
                dtype=jnp.float32) -> Params:
    wkey, bkey = jax.random.split(key)
    bound = 1.0 / math.sqrt(in_features)
    # Stored [in, out] so apply is x @ w (no transpose on the MXU).
    return {
        "w": _torch_uniform(wkey, (in_features, out_features), bound, dtype),
        "b": _torch_uniform(bkey, (out_features,), bound, dtype),
    }


def linear_apply(params: Params, x: jax.Array) -> jax.Array:
    return x @ params["w"] + params["b"]


# ---------------------------------------------------------------------------
# BatchNorm2d (torch semantics)
# ---------------------------------------------------------------------------

def batchnorm_init(num_features: int, dtype=jnp.float32) -> Tuple[Params, State]:
    params = {
        "gamma": jnp.ones((num_features,), dtype),
        "beta": jnp.zeros((num_features,), dtype),
    }
    state = {
        "mean": jnp.zeros((num_features,), dtype),
        "var": jnp.ones((num_features,), dtype),
    }
    return params, state


def batchnorm_apply(params: Params, state: State, x: jax.Array, *,
                    train: bool) -> Tuple[jax.Array, State]:
    """Torch-parity BatchNorm over NHWC.

    Training normalizes with the *biased* batch variance and updates running
    stats with the *unbiased* variance (torch.nn.BatchNorm2d semantics,
    momentum=0.1).  In the data-parallel setting the batch stats are the
    *local shard's* stats — matching the reference, where each replica's BN
    sees only its own shard (SURVEY.md §7 "BatchNorm semantics in DP").
    """
    if train:
        axes = (0, 1, 2)
        mean = jnp.mean(x, axes)
        var = jnp.mean(jnp.square(x - mean), axes)  # biased
        n = x.shape[0] * x.shape[1] * x.shape[2]
        unbiased = var * (n / max(n - 1, 1))
        new_state = {
            "mean": (1 - BN_MOMENTUM) * state["mean"] + BN_MOMENTUM * mean,
            "var": (1 - BN_MOMENTUM) * state["var"] + BN_MOMENTUM * unbiased,
        }
        use_mean, use_var = mean, var
    else:
        new_state = state
        use_mean, use_var = state["mean"], state["var"]

    inv = lax.rsqrt(use_var + BN_EPS)
    y = (x - use_mean) * inv * params["gamma"] + params["beta"]
    return y, new_state


# ---------------------------------------------------------------------------
# MaxPool 2x2/2 (reference model.py:16: MaxPool2d(kernel_size=2, stride=2))
# ---------------------------------------------------------------------------

def maxpool2x2(x: jax.Array) -> jax.Array:
    return lax.reduce_window(
        x, -jnp.inf, lax.max,
        window_dimensions=(1, 2, 2, 1),
        window_strides=(1, 2, 2, 1),
        padding="VALID",
    )


def relu(x: jax.Array) -> jax.Array:
    return jnp.maximum(x, 0)
