"""Model zoo: VGG-11/13/16/19 (reference parity) + ResNet-18/34 (stress)."""

from . import resnet, vgg

# User-registered factories (name -> () -> (init_fn, apply_fn)); lets tests
# and downstream users plug models into the CLI/bench without editing here.
_CUSTOM = {}


def register_model(name: str, factory) -> None:
    """Register ``factory() -> (init_fn, apply_fn)`` under ``name``."""
    _CUSTOM[name.lower()] = factory


def get_model(name: str):
    """Return (init_fn, apply_fn) for a model name used by the CLI/bench.

    ``vgg11`` matches the reference's only model
    (``/root/reference/src/Part 1/model.py:49-50``); ``resnet18`` is the
    BASELINE.json scaling stress config.
    """
    name = name.lower()
    if name in _CUSTOM:
        return _CUSTOM[name]()
    if name in ("vgg11", "vgg13", "vgg16", "vgg19"):
        return vgg.make(name.upper())
    if name in ("resnet18", "resnet-18"):
        return resnet.make("ResNet18")
    if name in ("resnet34", "resnet-34"):
        return resnet.make("ResNet34")
    raise ValueError(f"unknown model {name!r}; expected vgg11/13/16/19, "
                     f"resnet18/34, or one of {sorted(_CUSTOM) or '(none)'}")
