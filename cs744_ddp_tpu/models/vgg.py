"""Config-table-driven VGG family for 32x32x3 inputs, 10 classes.

Capability parity with the reference model zoo
(``/root/reference/src/Part 1/model.py:3-50``): VGG-11/13/16/19 built from
3x3 conv (pad 1, bias) + BatchNorm + ReLU blocks with 2x2/2 max-pool at 'M'
markers, then a flatten-512 -> Linear(512, 10) head.  Here the model is a pure
function pair (init/apply) over parameter & state pytrees — jit/grad/shard_map
compose over it directly, and activations are NHWC for XLA:TPU.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from . import layers

CFG = {
    "VGG11": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "VGG13": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
              512, 512, "M"],
    "VGG16": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
              "M", 512, 512, 512, "M"],
    "VGG19": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
              512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}

NUM_CLASSES = 10


def init(key: jax.Array, name: str = "VGG11",
         dtype=jnp.float32) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Build (params, state) pytrees for the named VGG config."""
    cfg = CFG[name]
    conv_params = []
    bn_params = []
    bn_state = []
    in_ch = 3
    for layer_cfg in cfg:
        if layer_cfg == "M":
            continue
        key, sub = jax.random.split(key)
        conv_params.append(layers.conv2d_init(sub, in_ch, layer_cfg, 3, dtype))
        bp, bs = layers.batchnorm_init(layer_cfg, dtype)
        bn_params.append(bp)
        bn_state.append(bs)
        in_ch = layer_cfg
    key, sub = jax.random.split(key)
    params = {
        "conv": conv_params,
        "bn": bn_params,
        "fc1": layers.linear_init(sub, 512, NUM_CLASSES, dtype),
    }
    state = {"bn": bn_state}
    return params, state


def apply(params: Dict[str, Any], state: Dict[str, Any], x: jax.Array, *,
          train: bool, name: str = "VGG11") -> Tuple[jax.Array, Dict[str, Any]]:
    """x: [N,32,32,3] NHWC -> logits [N,10], new state."""
    cfg = CFG[name]
    # BN backward fusion fence OFF for the whole VGG family: the round-3
    # v5e compiler SIGILL that originally forced it no longer reproduces
    # on the current toolchain (probed: vgg13/19 + resnet18/34 all AOT-
    # compile unfenced at batch 256), and the per-model A/B on the chip
    # measures unfenced VGGs consistently faster — vgg11 +6.9%, vgg13
    # +14.1%, vgg19 +9.5% whole-step (BASELINE.md round 4).  The barrier
    # is numerically an identity, so this is purely a compiler-scheduling
    # choice; ResNets keep the fence (it WINS there, resnet18 +7% fenced —
    # models/resnet.py), and the AOT compile tests cover both regimes.
    fence = False
    new_bn_state = []
    i = 0
    for layer_cfg in cfg:
        if layer_cfg == "M":
            x = layers.maxpool2x2(x)
        else:
            x = layers.conv2d_apply(params["conv"][i], x)
            x, ns = layers.batchnorm_apply(params["bn"][i], state["bn"][i], x,
                                           train=train, fence=fence)
            new_bn_state.append(ns)
            x = layers.relu(x)
            i += 1
    # After 5 pools: [N,1,1,512] -> flatten 512 (reference model.py:43-45).
    x = x.reshape(x.shape[0], -1)
    logits = layers.linear_apply(params["fc1"], x)
    return logits, {"bn": new_bn_state}


def make(name: str = "VGG11"):
    """Return (init_fn, apply_fn) closed over the config name."""
    def init_fn(key, dtype=jnp.float32):
        return init(key, name, dtype)

    def apply_fn(params, state, x, *, train):
        return apply(params, state, x, train=train, name=name)

    return init_fn, apply_fn


def VGG11():
    return make("VGG11")


def VGG13():
    return make("VGG13")


def VGG16():
    return make("VGG16")


def VGG19():
    return make("VGG19")
