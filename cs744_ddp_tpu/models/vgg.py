"""Config-table-driven VGG family for 32x32x3 inputs, 10 classes.

Capability parity with the reference model zoo
(``/root/reference/src/Part 1/model.py:3-50``): VGG-11/13/16/19 built from
3x3 conv (pad 1, bias) + BatchNorm + ReLU blocks with 2x2/2 max-pool at 'M'
markers, then a flatten-512 -> Linear(512, 10) head.  Here the model is a pure
function pair (init/apply) over parameter & state pytrees — jit/grad/shard_map
compose over it directly, and activations are NHWC for XLA:TPU.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from . import layers

CFG = {
    "VGG11": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "VGG13": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
              512, 512, "M"],
    "VGG16": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
              "M", 512, 512, 512, "M"],
    "VGG19": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
              512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}

NUM_CLASSES = 10


def init(key: jax.Array, name: str = "VGG11",
         dtype=jnp.float32) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Build (params, state) pytrees for the named VGG config."""
    cfg = CFG[name]
    conv_params = []
    bn_params = []
    bn_state = []
    in_ch = 3
    for layer_cfg in cfg:
        if layer_cfg == "M":
            continue
        key, sub = jax.random.split(key)
        conv_params.append(layers.conv2d_init(sub, in_ch, layer_cfg, 3, dtype))
        bp, bs = layers.batchnorm_init(layer_cfg, dtype)
        bn_params.append(bp)
        bn_state.append(bs)
        in_ch = layer_cfg
    key, sub = jax.random.split(key)
    params = {
        "conv": conv_params,
        "bn": bn_params,
        "fc1": layers.linear_init(sub, 512, NUM_CLASSES, dtype),
    }
    state = {"bn": bn_state}
    return params, state


def apply(params: Dict[str, Any], state: Dict[str, Any], x: jax.Array, *,
          train: bool, name: str = "VGG11") -> Tuple[jax.Array, Dict[str, Any]]:
    """x: [N,32,32,3] NHWC -> logits [N,10], new state."""
    cfg = CFG[name]
    # BN backward fusion fence: required above ~8 BN layers (the v5e
    # compiler SIGILLs — layers._bn_train_bwd), but VGG-11 sits exactly at
    # the threshold and measures +6.9% whole-step throughput unfenced
    # (BASELINE.md round 4; the barrier is numerically an identity, so
    # this is purely a compiler-scheduling choice).  Deeper configs keep
    # the fence; the AOT compile tests cover both regimes.
    n_bn = sum(1 for c in cfg if c != "M")
    fence = n_bn > 8
    new_bn_state = []
    i = 0
    for layer_cfg in cfg:
        if layer_cfg == "M":
            x = layers.maxpool2x2(x)
        else:
            x = layers.conv2d_apply(params["conv"][i], x)
            x, ns = layers.batchnorm_apply(params["bn"][i], state["bn"][i], x,
                                           train=train, fence=fence)
            new_bn_state.append(ns)
            x = layers.relu(x)
            i += 1
    # After 5 pools: [N,1,1,512] -> flatten 512 (reference model.py:43-45).
    x = x.reshape(x.shape[0], -1)
    logits = layers.linear_apply(params["fc1"], x)
    return logits, {"bn": new_bn_state}


def make(name: str = "VGG11"):
    """Return (init_fn, apply_fn) closed over the config name."""
    def init_fn(key, dtype=jnp.float32):
        return init(key, name, dtype)

    def apply_fn(params, state, x, *, train):
        return apply(params, state, x, train=train, name=name)

    return init_fn, apply_fn


def VGG11():
    return make("VGG11")


def VGG13():
    return make("VGG13")


def VGG16():
    return make("VGG16")


def VGG19():
    return make("VGG19")
