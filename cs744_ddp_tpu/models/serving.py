"""The fused-ingest serving forward: u8 at the program edge.

Every serving ladder rung (``serve/engine.py``) runs this forward: the
wire format (uint8 CIFAR rows) is the PROGRAM's input dtype, and the
u8 -> float normalize (``data/augment.normalize``) happens inside XLA —
the same transfer-compact idiom the training window uses.  Keeping the
builder here (not inline in the engine) makes the fused forward a named,
versioned artifact:

* the audit's ``ingest-edge`` rule certifies each lowered rung against
  this contract (u8 image parameter, in-program convert, no float image
  constants baked);
* ``INGEST_VERSION`` is folded into the engine's executable cache key,
  so warm-start caches never resurrect an executable compiled against a
  different ingest scheme (ROADMAP: shared-ladder cache keys must
  version the fused forward).

The forward masks pad rows by the label = -1 convention
(``train/step.py::masked_eval_counts``), so serving and eval accounting
share one definition; with ``train=False`` BatchNorm every row is
independent of its batchmates, which is what makes bucket padding
bitwise-invisible.
"""

from __future__ import annotations

#: Identity of the fused-ingest forward, folded into executable cache
#: keys.  Bump whenever the program edge changes (dtype, normalize,
#: masking): a stale warm-start hit across schemes would silently serve
#: wrong math.
INGEST_VERSION = "fused-u8-v1"


def make_u8_forward(apply_fn, compute_dtype=None):
    """Build ``forward(params, bn_state, images_u8, labels)`` ->
    ``(logits f32, loss_sum, correct)`` with the normalize fused at the
    program edge.

    ``compute_dtype`` casts the normalized activations (bf16 compute);
    logits always come back f32 so downstream comparison/accounting is
    precision-independent.
    """
    import jax.numpy as jnp

    from ..data import augment as aug
    from ..train.step import masked_eval_counts, maybe_cast

    def forward(params, bn_state, images_u8, labels):
        x = maybe_cast(aug.normalize(images_u8), compute_dtype)
        logits, _ = apply_fn(params, bn_state, x, train=False)
        logits = logits.astype(jnp.float32)
        loss_sum, correct = masked_eval_counts(logits, labels)
        return logits, loss_sum, correct

    return forward
