"""ResNet-18/34 (CIFAR-10 variants) — the scaling stress configs.

BASELINE.json config #5 calls for "ResNet-18 / CIFAR-10 8-worker allreduce
(scaling stress beyond coursework)".  This is the standard CIFAR-adapted
BasicBlock ResNet: a 3x3 stem (no 7x7/maxpool — inputs are 32x32), four
stages of BasicBlocks at widths (64,128,256,512) with strides (1,2,2,2),
global average pool, Linear(512,10).  ResNet-18 has (2,2,2,2) blocks per
stage; ResNet-34 has (3,4,6,3) — the next rung of the same family for
deeper stress runs.  Same functional (init, apply) contract as models.vgg.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from . import layers

STAGES = ((64, 1), (128, 2), (256, 2), (512, 2))
BLOCK_COUNTS = {"ResNet18": (2, 2, 2, 2), "ResNet34": (3, 4, 6, 3)}
NUM_CLASSES = 10


def _block_init(key, in_ch, out_ch, stride, dtype):
    k1, k2, k3 = jax.random.split(key, 3)
    p: Dict[str, Any] = {}
    s: Dict[str, Any] = {}
    p["conv1"] = layers.conv2d_init(k1, in_ch, out_ch, 3, dtype, bias=False)
    p["bn1"], s["bn1"] = layers.batchnorm_init(out_ch, dtype)
    p["conv2"] = layers.conv2d_init(k2, out_ch, out_ch, 3, dtype, bias=False)
    p["bn2"], s["bn2"] = layers.batchnorm_init(out_ch, dtype)
    if stride != 1 or in_ch != out_ch:
        p["down_conv"] = layers.conv2d_init(k3, in_ch, out_ch, 1, dtype, bias=False)
        p["down_bn"], s["down_bn"] = layers.batchnorm_init(out_ch, dtype)
    return p, s


def _block_apply(p, s, x, stride, *, train):
    # ResNets keep the default FENCED BN backward: unlike the VGGs (where
    # removing the fence measures +7-14%, models/vgg.py), the fence WINS
    # here — resnet18 measured 25,840 img/s fenced vs 23,942 unfenced on
    # v5e (capping fusion clusters at the BN boundary evidently schedules
    # the 20-BN residual graph better).  Numerics are identical either
    # way; see layers._bn_train_bwd.
    ns: Dict[str, Any] = {}
    y = layers.conv2d_apply(p["conv1"], x, stride=stride, padding=1)
    y, ns["bn1"] = layers.batchnorm_apply(p["bn1"], s["bn1"], y, train=train)
    y = layers.relu(y)
    y = layers.conv2d_apply(p["conv2"], y, stride=1, padding=1)
    y, ns["bn2"] = layers.batchnorm_apply(p["bn2"], s["bn2"], y, train=train)
    if "down_conv" in p:
        sc = layers.conv2d_apply(p["down_conv"], x, stride=stride, padding=0)
        sc, ns["down_bn"] = layers.batchnorm_apply(p["down_bn"], s["down_bn"],
                                                   sc, train=train)
    else:
        sc = x
    return layers.relu(y + sc), ns


def init(key: jax.Array, name: str = "ResNet18",
         dtype=jnp.float32) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    counts = BLOCK_COUNTS[name]
    key, sub = jax.random.split(key)
    params: Dict[str, Any] = {
        "stem_conv": layers.conv2d_init(sub, 3, 64, 3, dtype, bias=False)}
    state: Dict[str, Any] = {}
    params["stem_bn"], state["stem_bn"] = layers.batchnorm_init(64, dtype)

    in_ch = 64
    blocks_p, blocks_s = [], []
    for (width, stage_stride), nblocks in zip(STAGES, counts):
        for b in range(nblocks):
            stride = stage_stride if b == 0 else 1
            key, sub = jax.random.split(key)
            bp, bs = _block_init(sub, in_ch, width, stride, dtype)
            blocks_p.append(bp)
            blocks_s.append(bs)
            in_ch = width
    params["blocks"] = blocks_p
    state["blocks"] = blocks_s

    key, sub = jax.random.split(key)
    params["fc"] = layers.linear_init(sub, 512, NUM_CLASSES, dtype)
    return params, state


def apply(params, state, x: jax.Array, *, train: bool,
          name: str = "ResNet18") -> Tuple[jax.Array, Dict[str, Any]]:
    """x: [N,32,32,3] -> logits [N,10], new state."""
    counts = BLOCK_COUNTS[name]
    new_state: Dict[str, Any] = {}
    y = layers.conv2d_apply(params["stem_conv"], x, stride=1, padding=1)
    y, new_state["stem_bn"] = layers.batchnorm_apply(
        params["stem_bn"], state["stem_bn"], y, train=train)
    y = layers.relu(y)

    new_blocks = []
    i = 0
    for (width, stage_stride), nblocks in zip(STAGES, counts):
        for b in range(nblocks):
            stride = stage_stride if b == 0 else 1
            y, ns = _block_apply(params["blocks"][i], state["blocks"][i], y,
                                 stride, train=train)
            new_blocks.append(ns)
            i += 1
    new_state["blocks"] = new_blocks

    y = jnp.mean(y, axis=(1, 2))  # global average pool -> [N,512]
    logits = layers.linear_apply(params["fc"], y)
    return logits, new_state


def make(name: str = "ResNet18"):
    def init_fn(key, dtype=jnp.float32):
        return init(key, name, dtype)

    def apply_fn(p, s, x, *, train):
        return apply(p, s, x, train=train, name=name)

    return init_fn, apply_fn


def ResNet18():
    return make("ResNet18")


def ResNet34():
    return make("ResNet34")
