"""Numerical ops: loss, optimizer, and (optional) Pallas kernels."""

from .loss import accuracy_counts, cross_entropy  # noqa: F401
from .sgd import SGDConfig, SGDState              # noqa: F401
from . import sgd                                  # noqa: F401
