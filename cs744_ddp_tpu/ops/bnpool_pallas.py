"""Fused BN->ReLU->MaxPool2x2 with a Pallas TPU backward.

**Status: measured NEGATIVE result — correct, tested, NOT wired into the
model zoo.**  On the v5e chip (scan-amortized fwd+grad A/B vs the plain
XLA composition, 2026-07-31):

    bf16 [1536,32,32,64]: fused 7.86 ms/iter vs XLA 5.92 — 0.75x
    f32  [256,32,32,64]:  fused 3.13 ms/iter vs XLA 2.84 — 0.91x

(First formulation — whole-block intermediates — was 9.6 ms and hit
Mosaic's 16 MB scoped-VMEM limit at 2 MiB blocks; the committed version
streams chunks through a fori_loop, which recovered 1.8 ms but not the
gap.)  The lesson recorded so it is not retried: this chain is NOT
HBM-bound in any implementation — its single-pass traffic bound (~0.9 ms
at bf16/b1536) is unreachable because the routed-scatter formulation
costs ~30 VPU ops/element (routing compares, first-match masks, selects,
dtype round-trips), making it VPU-bound at ~6x the DMA time, while XLA's
four separate kernels each do a few ops/element and together finish in
~3.4 ms.  Combined with rounds 3-4's four jnp-level fusion attempts (all
~15% slower whole-step, models/layers.py::maxpool2x2), the conclusion is
now implementation-family-independent: XLA's native select-and-scatter +
split BN backward is the right lowering for this chain on this hardware.

Why it was built (round 5): the occupancy account (BASELINE.md,
tools/perf_occupancy.py) shows the TensorCore 99.9% busy — the remaining
MFU gap is in-kernel, and the dominant opportunity was the pool-preceded
BN block's BACKWARD: XLA executes it as four separate kernels
(select-and-scatter, relu-mask fusion, two BN-backward fusions) that
together re-read the stage-0 activation ~10x (2.63 ms/iter = 19.5% of the
bf16/b1536 step).  Pallas writes the memory schedule directly, which is
the one lever the jnp-level attempts lacked — the hypothesis was wrong
for an interesting reason (VPU cost, not memory schedule), which is why
the module stays: working evidence, reusable scaffolding (lane-merged
pooling layout, chunked-streaming grid pattern), numerics pinned by
tests/test_bnpool_pallas.py.

The backward is TWO Pallas passes over the residual (the minimum for
BatchNorm, whose dx needs the global sums):

  phase 1: recompute pool routing + relu gate from xhat, reduce
           sum(dy) and sum(dy*xhat) per channel         (reads xhat, dP)
  phase 2: dx = (gamma*inv/n)(n*dy - sum_dy - xhat*sum_dy_xhat),
           scattered back through the same routing      (reads again, writes dx)

Layout strategy (the whole trick): a [B,H,W,C] block is viewed as
[B, H/2, 2, W/2, 2C] — the H-split is a major-dim split (free) and the
W-pair MERGES INTO THE LANE DIMENSION (2C = 128 lanes exactly for the
C=64 stage this kernel targets; C>=128 stages use multiples).  Window
partners become lane-half slices, so the routing/scatter needs ZERO
sublane relayouts — the formulation error that made earlier attempts
slow (and made Mosaic spill registers when tried as stacks/reshapes).

Semantics match the unfused path exactly in f32; in bf16 the routing can
differ at ~1e-4 of elements where XLA's excess-precision pooling
(compare-before-rounding under --xla_allow_excess_precision) or the
residual's double rounding distinguishes values within 1-2 bf16 ulps —
the op is exactly consistent with ITS OWN forward (built from the same
rounded residual), pinned by the test:

  * pool gradient goes to the FIRST maximal element in row-major window
    order (torch's convention, XLA's select-and-scatter behavior —
    pinned in tests/test_layers.py);
  * relu gate is (pre-relu > 0), i.e. no gradient at exactly 0 (torch);
  * reductions accumulate in f32 regardless of the activation dtype;
  * the routing is recomputed from Z = gamma*xhat + beta, sharing the
    BN residual — relu destroyed negative Z, but wherever relu clipped,
    the gate zeroes the gradient, so recomputation is exact.

Forward stays plain XLA (it fuses into the producing conv); only the
backward is Pallas.  Reference chain being replaced:
``/root/reference/src/Part 1/model.py`` Conv->BN->ReLU->MaxPool blocks.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# The BN semantics this op must match are DEFINED in models/layers.py —
# share its constants/statistics so a future tuning there cannot silently
# diverge from this fused variant.
from ..models.layers import BN_EPS, _bn_train_fwd_impl

# VMEM budget per xhat block (the DMA granularity).  Compute streams the
# block in _CHUNK_ROWS-row chunks, so the block size is bounded by the
# VMEM the pipeline's double-buffered inputs + output occupy, not by the
# kernels' live intermediates.
_BLOCK_BYTES = 2 * 1024 * 1024


def _halves(x, c):
    """Lane halves of a [..., 2C] value: (even-column, odd-column)."""
    return x[..., :c], x[..., c:]


def _routed(xh5, dp, gamma2, beta2, c, act_dtype):
    """Per-quadrant routed+gated gradients and xhat quadrants.

    xh5: [B,H/2,2,W/2,2C] f32 (lane-merged view of xhat)
    dp:  [B,H/2,W/2,C]    f32 (pool output grad)
    Returns (dyq, xq): 4-tuples in row-major window order 00,01,10,11.

    The max/tie comparisons and the relu gate run on values ROUNDED to
    ``act_dtype`` — the dtype the forward's pool actually compared in —
    then upcast to f32 for the compare itself (the VPU has no bf16
    compare; upcasting is injective, so tie semantics are identical).
    bf16 routing thus matches the unfused path except where
    bf16(bf16(xhat)*gamma+beta) double-rounds differently from the
    forward's single rounding (a ~1-ulp tie flip that moves dP to an
    equal-valued window element).
    """
    x0, x1 = xh5[:, :, 0], xh5[:, :, 1]            # [B,H/2,W/2,2C]
    z0 = (x0 * gamma2 + beta2).astype(act_dtype).astype(jnp.float32)
    z1 = (x1 * gamma2 + beta2).astype(act_dtype).astype(jnp.float32)
    zero = jnp.zeros((), jnp.float32)
    y0 = jnp.maximum(z0, zero)
    y1 = jnp.maximum(z1, zero)
    a, b = _halves(y0, c)                          # window row 0
    cc, d = _halves(y1, c)                         # window row 1
    wmax = jnp.maximum(jnp.maximum(a, b), jnp.maximum(cc, d))
    hit_a = a == wmax
    hit_b = (b == wmax) & ~hit_a
    hit_c = (cc == wmax) & ~hit_a & ~hit_b
    hit_d = (d == wmax) & ~hit_a & ~hit_b & ~hit_c
    za, zb = _halves(z0, c)
    zc, zd = _halves(z1, c)
    dyq = (jnp.where(hit_a & (za > zero), dp, 0.0),
           jnp.where(hit_b & (zb > zero), dp, 0.0),
           jnp.where(hit_c & (zc > zero), dp, 0.0),
           jnp.where(hit_d & (zd > zero), dp, 0.0))
    xa, xb = _halves(x0, c)
    xc, xd = _halves(x1, c)
    return dyq, (xa, xb, xc, xd)


# Rows of the block processed per inner-loop iteration: the kernels hold
# ~12 chunk-sized f32 intermediates live, so the CHUNK bounds the vreg
# working set while the BLOCK (DMA granularity) stays large.
_CHUNK_ROWS = 4


def _sums_kernel(xhat_ref, dp_ref, gamma2_ref, beta2_ref, out_ref, *, c,
                 chunk_rows):
    """Phase 1: accumulate [2,C] = (sum_dy, sum_dy_xhat) over the grid,
    streaming the block through chunk_rows-row chunks.

    ASSUMES sequential grid execution: ``out_ref`` carries the running
    accumulator from step to step (init at program 0, += after), so the
    ``pallas_call`` must pin ``dimension_semantics=("arbitrary",)`` — on
    megacore TPUs (v4/v5p) a parallel grid dimension would be split across
    cores and the read-modify-write would race."""
    bn = xhat_ref.shape[0]
    gamma2, beta2 = gamma2_ref[:], beta2_ref[:]
    act = xhat_ref.dtype

    def chunk(i, acc):
        r = i * chunk_rows
        xh5 = xhat_ref[pl.ds(r, chunk_rows)].astype(jnp.float32)
        dp = dp_ref[pl.ds(r, chunk_rows)].astype(jnp.float32)
        dyq, xq = _routed(xh5, dp, gamma2, beta2, c, act)
        dy_tot = dyq[0] + dyq[1] + dyq[2] + dyq[3]
        dyx_tot = (dyq[0] * xq[0] + dyq[1] * xq[1]
                   + dyq[2] * xq[2] + dyq[3] * xq[3])
        return acc + jnp.stack([jnp.sum(dy_tot.reshape(-1, c), axis=0),
                                jnp.sum(dyx_tot.reshape(-1, c), axis=0)])

    acc = jax.lax.fori_loop(0, bn // chunk_rows, chunk,
                            jnp.zeros((2, c), jnp.float32))

    @pl.when(pl.program_id(0) == 0)
    def _():
        out_ref[:] = acc

    @pl.when(pl.program_id(0) != 0)
    def _():
        out_ref[:] += acc


def _dx_kernel(xhat_ref, dp_ref, gamma2_ref, beta2_ref, inv2_ref,
               sums2_ref, dx_ref, *, c, n, chunk_rows):
    """Phase 2: dx through the same routing, streamed in chunks.
    ``n`` = N*H*W, the BN reduction count (static)."""
    bn = xhat_ref.shape[0]
    gamma2, beta2 = gamma2_ref[:], beta2_ref[:]
    act = xhat_ref.dtype
    sum_dy2 = sums2_ref[0, :]                       # [2C], duplicated
    sum_dy_xhat2 = sums2_ref[1, :]
    scale2 = gamma2 * inv2_ref[:] * (1.0 / n)

    def chunk(i, carry):
        r = i * chunk_rows
        xh5 = xhat_ref[pl.ds(r, chunk_rows)].astype(jnp.float32)
        dp = dp_ref[pl.ds(r, chunk_rows)].astype(jnp.float32)
        dyq, xq = _routed(xh5, dp, gamma2, beta2, c, act)
        # dx per window row, built in the lane-merged [.., 2C] domain so
        # the store back through the free reshape needs no relayout.
        dz0 = jnp.concatenate([dyq[0], dyq[1]], axis=-1)
        dz1 = jnp.concatenate([dyq[2], dyq[3]], axis=-1)
        xh0 = jnp.concatenate([xq[0], xq[1]], axis=-1)
        xh1 = jnp.concatenate([xq[2], xq[3]], axis=-1)
        dx0 = scale2 * (n * dz0 - sum_dy2 - xh0 * sum_dy_xhat2)
        dx1 = scale2 * (n * dz1 - sum_dy2 - xh1 * sum_dy_xhat2)
        dx_ref[pl.ds(r, chunk_rows)] = jnp.stack(
            [dx0, dx1], axis=2).astype(dx_ref.dtype)
        return carry

    jax.lax.fori_loop(0, bn // chunk_rows, chunk, 0)


def _blk(shape, itemsize):
    """Batch-rows per block for a [N,H,W,C] residual: as many rows as
    keep the xhat block within _BLOCK_BYTES."""
    n, h, w, c = shape
    return max(1, min(n, _BLOCK_BYTES // (h * w * c * itemsize)))


def _dup(v):
    """[C] -> [2C] channel vector for the lane-merged domain."""
    return jnp.concatenate([v.astype(jnp.float32)] * 2)


def _pallas_backward(xhat, dp, gamma, beta, inv, out_dtype):
    """(dx, sum_dy, sum_dy_xhat) via the two-phase Pallas kernels."""
    n_, h, w, c = xhat.shape
    bn = _blk(xhat.shape, xhat.dtype.itemsize)
    while n_ % bn:
        bn -= 1
    chunk_rows = min(_CHUNK_ROWS, bn)
    while bn % chunk_rows:
        chunk_rows -= 1
    grid = (n_ // bn,)
    gamma2, beta2, inv2 = _dup(gamma), _dup(beta), _dup(inv)
    # The lane-merged view (free: row-major linearization is unchanged);
    # last two dims (W/2, 2C) tile the VPU exactly at C=64.
    xh5 = xhat.reshape(n_, h // 2, 2, w // 2, 2 * c)

    xh_spec = pl.BlockSpec((bn, h // 2, 2, w // 2, 2 * c),
                           lambda i: (i, 0, 0, 0, 0),
                           memory_space=pltpu.VMEM)
    dp_spec = pl.BlockSpec((bn, h // 2, w // 2, c), lambda i: (i, 0, 0, 0),
                           memory_space=pltpu.VMEM)
    ch_spec = pl.BlockSpec((2 * c,), lambda i: (0,),
                           memory_space=pltpu.VMEM)
    sums_spec = pl.BlockSpec((2, c), lambda i: (0, 0),
                             memory_space=pltpu.VMEM)

    # The sums kernel ACCUMULATES into out_ref across grid steps (phase-1
    # reduction), which is only sound if the grid executes sequentially on
    # one core: "arbitrary" semantics pin that, keeping megacore chips
    # (v4/v5p, which otherwise split a parallel grid across two cores with
    # separate out_ref instances) from racing the read-modify-write.
    sums = pl.pallas_call(
        partial(_sums_kernel, c=c, chunk_rows=chunk_rows),
        grid=grid,
        in_specs=[xh_spec, dp_spec, ch_spec, ch_spec],
        out_specs=sums_spec,
        out_shape=jax.ShapeDtypeStruct((2, c), jnp.float32),
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("arbitrary",)),
    )(xh5, dp, gamma2, beta2)

    sums2 = jnp.concatenate([sums, sums], axis=1)   # [2, 2C]
    dx5 = pl.pallas_call(
        partial(_dx_kernel, c=c, n=float(n_ * h * w),
                chunk_rows=chunk_rows),
        grid=grid,
        in_specs=[xh_spec, dp_spec, ch_spec, ch_spec, ch_spec,
                  pl.BlockSpec((2, 2 * c), lambda i: (0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=xh_spec,
        out_shape=jax.ShapeDtypeStruct((n_, h // 2, 2, w // 2, 2 * c),
                                       out_dtype),
    )(xh5, dp, gamma2, beta2, inv2, sums2)
    return dx5.reshape(n_, h, w, c), sums[0], sums[1]


def _fwd_impl(x, gamma, beta):
    """Plain-XLA forward: BN (centered or one-pass per dtype, matching
    models/layers.py semantics) -> relu -> 2x2 maxpool.

    Z is computed FROM THE ROUNDED RESIDUAL (xhat cast to the activation
    dtype and back) so the backward's routing reconstruction —
    act(f32(act(xhat)) * gamma + beta) — is BIT-IDENTICAL to what the
    forward's pool compared: the fused op is exactly consistent with its
    own gradient.  In f32 the casts are identity (the parity path is
    unchanged); in bf16 the output moves by <= 1 ulp vs the unfused
    composition (bf16 mode is already a documented deviation)."""
    if x.shape[1] % 2 or x.shape[2] % 2:
        raise ValueError(
            f"bn_relu_pool requires even H and W (2x2/2 pool windows; the "
            f"backward's lane-merged layout assumes no truncated rows), "
            f"got {x.shape}")
    # Statistics from the ONE shared BN implementation (centered two-pass
    # f32 / one-pass bf16 per models/layers.py); its y is discarded — the
    # fused op rebuilds z from the ROUNDED xhat below — and DCE'd by XLA.
    _, xhat, mean, var, inv = _bn_train_fwd_impl(x, gamma, beta)
    xhat_act = xhat.astype(x.dtype).astype(jnp.float32)
    z = (xhat_act * gamma + beta).astype(x.dtype)
    y = jnp.maximum(z, jnp.zeros((), x.dtype))
    pooled = lax.reduce_window(y, -jnp.inf, lax.max,
                               window_dimensions=(1, 2, 2, 1),
                               window_strides=(1, 2, 2, 1), padding="VALID")
    return pooled, xhat, mean, var, inv


@jax.custom_vjp
def bn_relu_pool(x, gamma, beta):
    """(pooled, mean, var) with the fused Pallas backward."""
    pooled, _, mean, var, _ = _fwd_impl(x, gamma, beta)
    return pooled, mean, var


def _bn_relu_pool_fwd(x, gamma, beta):
    pooled, xhat, mean, var, inv = _fwd_impl(x, gamma, beta)
    # Residual in the activation dtype (halves backward HBM traffic in
    # bf16 mode, same policy as models/layers.py::_bn_train_fwd).
    return (pooled, mean, var), (xhat.astype(x.dtype), inv, gamma, beta)


def _bn_relu_pool_bwd(res, cts):
    xhat_stored, inv, gamma, beta = res
    in_dtype = xhat_stored.dtype
    dp = cts[0]
    dx, sum_dy, sum_dy_xhat = _pallas_backward(
        xhat_stored, dp, gamma, beta, inv, in_dtype)
    # Exact cotangent terms for the mean/var outputs (normally zero: they
    # feed only the running-stats update — same policy as
    # models/layers.py::_bn_train_bwd, where XLA folds the zeros away).
    n = xhat_stored.shape[0] * xhat_stored.shape[1] * xhat_stored.shape[2]
    ct_mean = cts[1].astype(jnp.float32)
    ct_var = cts[2].astype(jnp.float32)
    dx = (dx.astype(jnp.float32) + ct_mean / n
          + (2.0 / n) * ct_var * (xhat_stored.astype(jnp.float32) / inv)
          ).astype(in_dtype)
    return dx, sum_dy_xhat, sum_dy


bn_relu_pool.defvjp(_bn_relu_pool_fwd, _bn_relu_pool_bwd)
