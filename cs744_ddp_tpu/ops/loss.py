"""Cross-entropy loss with torch.nn.CrossEntropyLoss semantics.

The reference uses ``torch.nn.CrossEntropyLoss()`` (mean reduction) as the
training and evaluation criterion (``/root/reference/src/Part 1/main.py:110``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean over batch of -log softmax(logits)[label].

    logits: [N, C] float; labels: [N] int.  Computed via log-sum-exp for
    stability (identical math to torch's CrossEntropyLoss mean reduction).
    Always reduced in f32 so bf16 compute mode keeps a full-precision loss.
    """
    logits = logits.astype(jnp.float32)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return jnp.mean(logz - picked)


def accuracy_counts(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Number of correct argmax predictions (reference main.py:69-71)."""
    return jnp.sum(jnp.argmax(logits, axis=-1) == labels)
