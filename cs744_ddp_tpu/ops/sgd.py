"""SGD with PyTorch semantics: L2 weight decay folded into the gradient,
then classic (non-Nesterov) momentum.

The reference trains with ``optim.SGD(lr=0.1, momentum=0.9, weight_decay=1e-4)``
(``/root/reference/src/Part 1/main.py:114-115``).  PyTorch's update is:

    g = grad + wd * p
    v = mu * v + g          (v initialized to g on the first step)
    p = p - lr * v

Since the velocity buffer starts at zero, ``mu*0 + g == g`` and a single
formula covers the first step too.  This differs from optax's
decoupled/trace variants, so it is implemented exactly, as a pure
jit-friendly pytree transform (SURVEY.md §7 "PyTorch SGD parity").
Verified against torch.optim.SGD in tests/test_sgd.py.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp


class SGDState(NamedTuple):
    momentum: Any          # pytree like params; velocity buffers
    # Gradient-sync communication state (None for stateless strategies):
    # the compressed tiers' error-feedback residuals and PowerSGD Q
    # factors (parallel/strategies.py), stacked per worker on a leading
    # mesh axis.  It rides in the optimizer state so checkpoints carry it
    # (bitwise preemption resume) and the windowed programs donate it; the
    # SGD update itself never touches it — the strategy writes it via
    # train/step.py's apply_strategy threading.
    comm: Any = None


class SGDConfig(NamedTuple):
    lr: float = 0.1
    momentum: float = 0.9
    weight_decay: float = 1e-4


def init(params: Any) -> SGDState:
    zeros = jax.tree.map(jnp.zeros_like, params)
    return SGDState(momentum=zeros)


def update(params: Any, grads: Any, state: SGDState,
           cfg: SGDConfig = SGDConfig()) -> tuple[Any, SGDState]:
    """One SGD step; returns (new_params, new_state). Pure and jittable."""
    d = jax.tree.map(lambda p, g: g + cfg.weight_decay * p, params, grads)
    new_vel = jax.tree.map(lambda v, dd: cfg.momentum * v + dd,
                           state.momentum, d)
    new_params = jax.tree.map(lambda p, v: p - cfg.lr * v, params, new_vel)
    return new_params, SGDState(momentum=new_vel, comm=state.comm)
