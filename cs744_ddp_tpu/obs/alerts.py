"""Streaming SLO alert engine over telemetry gauges/counters/spans.

A single-pass rules evaluator: feed it telemetry event records (live,
as a ``Telemetry`` tap, or post-hoc over a drained event list) and it
fires structured alerts with DETERMINISTIC rule ids — chaos drills pin
"exactly these rules fired" against ``fired_rules()`` / the
``summary["alerts"]`` roll-up, so a new false positive is a test
failure, not a dashboard shrug.

Built-in rules (id -> severity):

* ``SLO_BURN`` (page)   — SLO attainment over the sliding window of the
  last ``burn_window`` request outcomes (``serve_latency_ms`` gauges'
  ``met`` flag; sheds count as misses) dropped below
  ``burn_threshold``.
* ``SHED_RATE`` (warn)  — shed fraction over the same window above
  ``shed_threshold``.
* ``QUEUE_DEPTH`` (warn) — ``serve_queue_depth`` gauge above the high
  watermark.
* ``STRAGGLER`` (warn)  — one replica's EWMA service time exceeds the
  peer median by ``straggler_threshold``x (rides
  ``elastic.StragglerDetector`` over ``serve_service_ms`` gauges'
  ``replica`` attr).
* ``PUBLISH_LAG`` (warn) — the weight watcher fell behind the
  publisher: a ``publish_rejected``/``publish_stale_skipped`` counter,
  or ``installed_version`` still trailing
  ``publish_version``/``publish_latest_seen`` more than
  ``publish_lag_s`` after the publish.
* ``NONFINITE`` (page)  — more than ``nonfinite_max`` non-finite train
  steps (``nonfinite_skipped``/``nonfinite_restored`` counters).

Each rule re-fires at most once per ``cooldown_s`` of EVENT time (not
wall time), so replaying a log yields the same alert sequence as the
live run that produced it.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, NamedTuple, Optional

from .telemetry import NULL

# rule id -> severity (the full deterministic rule table).
RULES: Dict[str, str] = {
    "SLO_BURN": "page",
    "SHED_RATE": "warn",
    "QUEUE_DEPTH": "warn",
    "STRAGGLER": "warn",
    "PUBLISH_LAG": "warn",
    "NONFINITE": "page",
}


class Alert(NamedTuple):
    rule: str
    severity: str
    t: float
    attrs: Dict[str, Any]


class AlertEngine:
    """Single-pass rules evaluator; attach live with
    ``telemetry.add_tap(engine.observe)`` or replay with ``run()``."""

    def __init__(self, telemetry=NULL, *,
                 burn_window: int = 64, burn_threshold: float = 0.7,
                 shed_threshold: float = 0.5, queue_depth_high: int = 256,
                 straggler_threshold: float = 2.0,
                 straggler_min_steps: int = 3,
                 publish_lag_s: float = 5.0, nonfinite_max: int = 0,
                 cooldown_s: float = 5.0):
        self._tel = telemetry
        self.burn_window = int(burn_window)
        self.burn_threshold = float(burn_threshold)
        self.shed_threshold = float(shed_threshold)
        self.queue_depth_high = int(queue_depth_high)
        self.straggler_threshold = float(straggler_threshold)
        self.straggler_min_steps = int(straggler_min_steps)
        self.publish_lag_s = float(publish_lag_s)
        self.nonfinite_max = int(nonfinite_max)
        self.cooldown_s = float(cooldown_s)
        self.alerts: List[Alert] = []
        # RLock: firing goes through telemetry.alert(), whose tap fan-out
        # re-enters observe() on the same thread with the alert record.
        self._lock = threading.RLock()
        self._last_fire: Dict[str, float] = {}
        self._window: List[str] = []      # outcomes: "met"/"late"/"shed"
        self._detector = None             # lazily-built StragglerDetector
        self._nonfinite = 0.0
        self._published: Optional[float] = None   # newest published version
        self._published_t = 0.0
        self._installed: Optional[float] = None

    # -- firing --------------------------------------------------------------

    def _fire(self, rule: str, t: float, fired: List[Alert],
              **attrs) -> None:
        last = self._last_fire.get(rule)
        if last is not None and t - last < self.cooldown_s:
            return
        self._last_fire[rule] = t
        alert = Alert(rule, RULES[rule], t, attrs)
        self.alerts.append(alert)
        fired.append(alert)
        self._tel.alert(rule, RULES[rule], **attrs)

    # -- rule evaluation -----------------------------------------------------

    def _outcome(self, outcome: str, t: float, fired: List[Alert],
                 **attrs) -> None:
        self._window.append(outcome)
        if len(self._window) > self.burn_window:
            del self._window[:len(self._window) - self.burn_window]
        if len(self._window) < self.burn_window:
            return
        met = sum(1 for o in self._window if o == "met")
        shed = sum(1 for o in self._window if o == "shed")
        attainment = met / len(self._window)
        if attainment < self.burn_threshold:
            self._fire("SLO_BURN", t, fired, attainment=round(attainment, 4),
                       window=len(self._window), **attrs)
        if shed / len(self._window) > self.shed_threshold:
            self._fire("SHED_RATE", t, fired,
                       shed_rate=round(shed / len(self._window), 4),
                       window=len(self._window), **attrs)

    def _observe_straggler(self, replica: int, service_s: float,
                           t: float, fired: List[Alert]) -> None:
        # Lazy: ``elastic`` pulls jax at package import; report-only
        # consumers of obs/ must stay pure-python until a serve stream
        # (which has jax loaded anyway) actually feeds replica gauges.
        from ..elastic.straggler import StragglerDetector
        det = self._detector
        if det is None or replica >= det.world:
            grown = StragglerDetector(
                replica + 1 if det is None else max(det.world, replica + 1),
                threshold=self.straggler_threshold,
                min_steps=self.straggler_min_steps)
            if det is not None:   # transplant EWMA state into the wider one
                grown._ewma[:det.world] = det._ewma
                grown._count[:det.world] = det._count
                grown.flag_counts = det.flag_counts
            det = self._detector = grown
        det.observe(replica, service_s)
        for r in det.check():
            self._fire("STRAGGLER", t, fired, replica=r,
                       ewma_s=round(det.ewma(r) or 0.0, 4))

    def _observe_publish(self, t: float, fired: List[Alert]) -> None:
        if self._published is None:
            return
        if self._installed is not None and \
                self._installed >= self._published:
            return
        if t - self._published_t > self.publish_lag_s:
            self._fire("PUBLISH_LAG", t, fired,
                       published=self._published,
                       installed=self._installed,
                       lag_s=round(t - self._published_t, 3))

    # -- the streaming entry point -------------------------------------------

    def observe(self, event: Dict[str, Any]) -> List[Alert]:
        """Feed one telemetry record; returns alerts fired by it.
        Usable directly as a ``Telemetry`` tap."""
        kind = event.get("kind")
        if kind == "alert":      # our own emissions echo back via the tap
            return []
        fired: List[Alert] = []
        t = float(event.get("t", 0.0))
        name = event.get("name")
        with self._lock:
            if kind == "gauge":
                if name == "serve_latency_ms" and "met" in event:
                    self._outcome("met" if event["met"] else "late", t,
                                  fired, tier=event.get("tier"))
                elif name == "serve_queue_depth":
                    if event.get("value", 0) > self.queue_depth_high:
                        self._fire("QUEUE_DEPTH", t, fired,
                                   depth=event["value"],
                                   high=self.queue_depth_high)
                elif name == "serve_service_ms" and "replica" in event:
                    self._observe_straggler(int(event["replica"]),
                                            event["value"] / 1e3, t, fired)
                elif name in ("publish_version", "publish_latest_seen"):
                    v = float(event["value"])
                    if self._published is None or v > self._published:
                        self._published, self._published_t = v, t
                elif name == "installed_version":
                    self._installed = float(event["value"])
            elif kind == "counter":
                if name == "serve_shed":
                    for _ in range(int(event.get("inc", 1))):
                        self._outcome("shed", t, fired,
                                      tier=event.get("tier"))
                elif name in ("publish_rejected", "publish_stale_skipped"):
                    self._fire("PUBLISH_LAG", t, fired, counter=name,
                               reason=event.get("why"))
                elif name in ("nonfinite_skipped", "nonfinite_restored"):
                    self._nonfinite += float(event.get("inc", 1))
                    if self._nonfinite > self.nonfinite_max:
                        self._fire("NONFINITE", t, fired,
                                   count=self._nonfinite)
            # Publish lag is time-driven: ANY event advancing the clock
            # can trip it once the watcher trails long enough.
            self._observe_publish(t, fired)
        return fired

    def run(self, events) -> List[Alert]:
        """Replay an event list through the rules; returns ALL alerts
        fired during the pass (deterministic in the event order)."""
        for e in events:
            self.observe(e)
        return list(self.alerts)

    # -- reporting -----------------------------------------------------------

    def fired_rules(self) -> List[str]:
        """Sorted unique rule ids that fired — the chaos-drill pin."""
        return sorted({a.rule for a in self.alerts})

    def summary(self) -> Dict[str, Any]:
        by_rule: Dict[str, Dict[str, Any]] = {}
        for a in self.alerts:
            agg = by_rule.setdefault(a.rule, {
                "count": 0, "severity": a.severity, "first_t": a.t})
            agg["count"] += 1
            agg["last_attrs"] = a.attrs
        return {"fired": self.fired_rules(), "by_rule": by_rule,
                "total": len(self.alerts)}
