"""Join analytic :class:`~cs744_ddp_tpu.analysis.costmodel.CostReport`\\ s
with measured wall-clock (ISSUE 8 tentpole b).

The cost model says what a program MUST do (flops, HBM bytes, wire
bytes); a measured per-dispatch time says what it DID.  The join yields:

- **MFU** — achieved flops/s over the bf16 peak (per chip: shard_map
  reports are per-device, so ``flops / measured_s`` is already per-chip).
- **Roofline side** — whether the analytic compute time or the analytic
  HBM time dominates, plus the utilization ceiling that side imposes.
- **Comm/compute ratio** — serial wire seconds per compute second, the
  static version of the paper's sync-cost spectrum.
- **Exposed-comm bound** — for the ``overlap`` strategy: with a chain
  depth of 1, at most the LARGEST collective is exposed; ``ddp``'s
  barrier-chained plan pays the full sum (round-7 ladder, measured here
  against the same ICI model).
- **HBM residency** (round 20) — when the caller supplies the program's
  static liveness certificate (:func:`analysis.memlife.mem_report`),
  the record also carries the certified peak and its headroom against
  the chip capacity, so one attribution row answers both "how fast" and
  "does it fit".
"""

from __future__ import annotations

from typing import Dict, Optional

from ..analysis.costmodel import (CostReport, V5E_BF16_PEAK_FLOPS,
                                  V5E_HBM_BYTES_PER_S,
                                  V5E_HBM_CAPACITY_BYTES,
                                  V5E_ICI_BYTES_PER_S, mfu_fields)

__all__ = ["attribute", "overlap_vs_ddp", "mfu_fields"]


def attribute(report: CostReport, *, measured_s: Optional[float] = None,
              mem_report=None,
              peak_flops: float = V5E_BF16_PEAK_FLOPS,
              hbm_bytes_per_s: float = V5E_HBM_BYTES_PER_S,
              hbm_capacity_bytes: int = V5E_HBM_CAPACITY_BYTES,
              ici_bytes_per_s: float = V5E_ICI_BYTES_PER_S) -> Dict:
    """Attribution record for one program; ``measured_s`` (per-dispatch
    seconds, same per-device scope as the report) adds the measured-join
    fields, otherwise the record is purely analytic.  ``mem_report``
    (an :class:`analysis.memlife.MemReport` for the SAME program) adds
    the certified peak-residency fields."""
    compute_s = report.flops / peak_flops
    hbm_s = report.hbm_bytes / hbm_bytes_per_s
    comm_s = report.wire_bytes / ici_bytes_per_s
    denom = max(compute_s, hbm_s)
    out = {
        "program": report.name,
        "gflops": round(report.flops / 1e9, 4),
        "hbm_mib": round(report.hbm_bytes / 2**20, 3),
        "wire_mib": round(report.wire_bytes / 2**20, 4),
        "analytic_compute_s": compute_s,
        "analytic_hbm_s": hbm_s,
        "analytic_comm_s": comm_s,
        "roofline_bound": "compute" if compute_s >= hbm_s else "bandwidth",
        # The MFU ceiling the dominant roofline side permits: 1.0 when
        # compute-bound, compute_s/hbm_s when the HBM wall caps it.
        "mfu_roofline_ceiling": round(compute_s / denom, 4) if denom else None,
        "comm_compute_ratio": (round(comm_s / compute_s, 4)
                               if compute_s else None),
        "arithmetic_intensity": (round(report.arithmetic_intensity, 2)
                                 if report.hbm_bytes else None),
    }
    if measured_s:
        achieved = report.flops / measured_s
        out["measured_s"] = round(measured_s, 6)
        out["achieved_tflops_per_sec"] = round(achieved / 1e12, 4)
        out["mfu_vs_bf16_peak"] = round(achieved / peak_flops, 6)
    if mem_report is not None:
        peak = int(mem_report.peak_bytes)
        out["peak_hbm_mib"] = round(peak / 2**20, 3)
        out["hbm_headroom_mib"] = round(
            (hbm_capacity_bytes - peak) / 2**20, 3)
        out["hbm_capacity_utilization"] = round(
            peak / hbm_capacity_bytes, 6) if hbm_capacity_bytes else None
    return out


def overlap_vs_ddp(overlap_report: CostReport, ddp_report: CostReport, *,
                   ici_bytes_per_s: float = V5E_ICI_BYTES_PER_S) -> Dict:
    """Exposed-comm upper bound of the un-chained ``overlap`` plan vs the
    serial cost of ``ddp``'s chained bucket plan (per scanned step: uses
    the static per-instruction collective sizes, not loop-weighted
    totals)."""
    exposed = (max(overlap_report.collective_sizes)
               if overlap_report.collective_sizes else 0)
    chained = sum(ddp_report.collective_sizes)
    exposed_s = exposed / ici_bytes_per_s
    chained_s = chained / ici_bytes_per_s
    return {
        "overlap_exposed_bytes_upper_bound": exposed,
        "ddp_chained_bytes": chained,
        "overlap_exposed_comm_s_upper_bound": exposed_s,
        "ddp_chained_comm_s": chained_s,
        "hiding_ratio_lower_bound": (round(chained_s / exposed_s, 2)
                                     if exposed_s else None),
    }
