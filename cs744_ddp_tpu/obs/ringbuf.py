"""Device-resident metric ring buffer (ISSUE 8 tentpole a).

A fixed-capacity f32 ring of shape ``(capacity, N_METRICS)`` plus an i32
write counter, carried through the windowed training scan as part of the
donated carry.  Every scanned step writes one row via
``lax.dynamic_update_slice``; the host fetches the whole buffer ONCE per
window (a single ``np.asarray`` = one device round-trip) and reconstructs
per-step rows — including absolute step indices — from the ``marker``
column, instead of syncing per step.

Columns (see :data:`METRICS`):

- ``loss``         — the per-step scalar loss, bitwise-identical to what
                     the non-ring path stacks into the scan's ys (the
                     ring only observes; it never perturbs the math).
- ``grad_sqnorm``  — global post-sync gradient sqnorm (sum over leaves of
                     ``sum(g*g)``), replicated so the write is identical
                     on every shard.
- ``ok``           — the non-finite guard verdict (1.0 = applied); 1.0
                     when the guard is off.
- ``marker``       — the absolute batch index as f32.  Exact for indices
                     < 2**24, checked at drain; a run long enough to
                     break that would overflow the epoch counter first.

The write counter counts TOTAL writes (it is not reduced mod capacity on
device), so the host can detect overwrite and handle wraparound without a
second fetch.  ``capacity`` must be >= the largest window length or rows
would be overwritten before the drain — validated by the Trainer.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

METRICS = ("loss", "grad_sqnorm", "ok", "marker")
N_METRICS = len(METRICS)
DEFAULT_CAPACITY = 64          # >= WINDOW (20) with slack for ragged tails
_MARKER_EXACT = float(2 ** 24)  # largest exactly-representable f32 int


def make_ring(capacity: int = DEFAULT_CAPACITY):
    """Fresh (buffer, write-counter) pair.  Plain jnp arrays: the caller's
    jit placement (replicated specs in the shard_map builds) commits them;
    imported lazily so host-only consumers never pull in jax."""
    import jax.numpy as jnp
    if capacity < 1:
        raise ValueError(f"ring capacity must be >= 1, got {capacity}")
    return (jnp.zeros((capacity, N_METRICS), jnp.float32),
            jnp.zeros((), jnp.int32))


def ring_write(ring, values):
    """Write one row (a length-``N_METRICS`` tuple of scalars, any real
    dtype) at the current slot; returns the advanced ring.  Traced inside
    the scan body — one dynamic-update-slice, no host sync."""
    import jax.numpy as jnp
    from jax import lax
    buf, count = ring
    if len(values) != N_METRICS:
        raise ValueError(f"expected {N_METRICS} metrics, got {len(values)}")
    row = jnp.stack([jnp.asarray(v, jnp.float32).reshape(())
                     for v in values]).reshape(1, N_METRICS)
    slot = lax.rem(count, jnp.int32(buf.shape[0]))
    return (lax.dynamic_update_slice(buf, row, (slot, jnp.int32(0))),
            count + jnp.int32(1))


def drain_rows(buf_host, writes_total: int, count: int) -> np.ndarray:
    """Last ``count`` written rows in write order, from a host copy of the
    buffer.  ``writes_total`` is the host-tracked cumulative write count
    (tracking it host-side keeps the drain at exactly one device fetch —
    the buffer itself).  Handles wraparound; refuses overwritten reads."""
    buf = np.asarray(buf_host)
    cap = buf.shape[0]
    if count > cap:
        raise ValueError(
            f"drain of {count} rows exceeds ring capacity {cap}: rows were "
            "overwritten before the drain (raise --metrics-ring)")
    if count > writes_total:
        raise ValueError(
            f"drain of {count} rows exceeds total writes {writes_total}")
    idx = np.arange(writes_total - count, writes_total) % cap
    return buf[idx]


def marker_steps(rows: np.ndarray) -> np.ndarray:
    """Absolute step indices from the marker column, validated exact."""
    markers = rows[:, METRICS.index("marker")]
    if markers.size and float(np.max(markers)) >= _MARKER_EXACT:
        raise ValueError("ring marker exceeded exact-f32 integer range")
    return markers.astype(np.int64)


def split_columns(rows: np.ndarray) -> Tuple[np.ndarray, ...]:
    """(loss, grad_sqnorm, ok, steps) column views of drained rows."""
    return (rows[:, 0], rows[:, 1], rows[:, 2], marker_steps(rows))
