"""Structured telemetry: per-step events, spans, gauges, run manifest.

The reference's only observability surface is stdout (the 20-iteration
windowed prints, ``/root/reference/src/Part 1/main.py:28-57``).  This package
adds a machine-readable layer BESIDE that surface — never instead of it: a
JSONL event log plus a run manifest and an end-of-run summary, written only
when the caller opts in (``--telemetry-out``).  Disabled is the default and
costs nothing: ``NULL`` is a stateless no-op recorder and every hot call
site guards on ``telemetry.enabled``.
"""

from .alerts import RULES as ALERT_RULES
from .alerts import Alert, AlertEngine
from .telemetry import (NULL, NullTelemetry, Telemetry, git_sha, percentile,
                        read_run, summarize_events)
from .tracing import TraceContext

__all__ = ["ALERT_RULES", "Alert", "AlertEngine", "NULL", "NullTelemetry",
           "Telemetry", "TraceContext", "git_sha", "percentile", "read_run",
           "summarize_events"]
