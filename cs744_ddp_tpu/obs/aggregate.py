"""Cross-process trace aggregation: merge, skew-correct, waterfall.

Each process in a traced serving run writes spans into its OWN
``events.jsonl`` (rotation-aware, torn-tail tolerant — the reader is
``read_events_jsonl``).  This module stitches those per-process streams
back into per-request **latency waterfalls**:

1. **merge** — group every span carrying a ``trace_id`` attribute
   (stamped from ``TraceContext.attrs()``) across all streams.
2. **clock skew** — processes have independent clocks.  For every trace
   observed by both a client (``trace_client`` span: t1..t4 on the
   client clock) and the server (``frontend_request`` span: t2..t3 on
   the server clock) the NTP midpoint method gives the server-minus-
   client offset ``((t2-t1)+(t3-t4))/2`` with error bounded by half the
   round-trip residual ``rtt = (t4-t1)-(t3-t2)``.  The per-process
   offset is the MEDIAN over all matched pairs, applied to every
   timestamp from that process before reconstruction.
3. **waterfall** — per request, the ordered stage durations: wire
   decode, queue wait, admit deferral, staging, device compute, fetch,
   reply encode, plus the frontend window and the client-measured
   round-trip; per-batch engine spans (``serve_dispatch``/
   ``serve_fetch``/``serve_stage``) are joined to requests through the
   batcher trace id each carries in its ``traces`` attribute.  A trace
   whose process died mid-request (chaos ``replica_death``) renders as
   ``complete: False`` with whatever stages its surviving spans attest.

The device-compute stage optionally joins a COST-MODEL PRIOR
(``analysis/costmodel.py`` flop counts per bucket): a single rate
``k = sum(f*m)/sum(f*f)`` is least-squares fitted across buckets and the
per-bucket predicted-vs-measured ratio reported, so a bucket whose
measured time diverges from its flop share stands out.

Everything here is pure python over dicts — report-only tooling must
not pull jax/numpy (same rule as ``telemetry.percentile``).
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

from .telemetry import percentile, read_events_jsonl

# Span names the serve path emits (the aggregation contract; the
# span-hygiene lint rule in analysis/pylint_rules.py pins emit sites).
CLIENT_SPAN = "trace_client"          # client round-trip, t1..t4
FRONTEND_SPAN = "frontend_request"    # server window, t_recv..t_send
STAGE_SPANS = ("wire_decode", "sched_queue", "sched_defer", "serve_stage",
               "serve_dispatch", "serve_fetch", "reply_encode")
# Stage display order in a waterfall (request wall-clock order).
STAGE_ORDER = ("wire_decode", "queue_wait", "admit_defer", "staging",
               "device_compute", "fetch", "reply_encode")
_SPAN_TO_STAGE = {"wire_decode": "wire_decode", "sched_queue": "queue_wait",
                  "sched_defer": "admit_defer", "serve_stage": "staging",
                  "serve_dispatch": "device_compute", "serve_fetch": "fetch",
                  "reply_encode": "reply_encode"}
# Batch-level engine spans join requests via their ``traces`` attr.
_BATCH_SPANS = ("serve_stage", "serve_dispatch", "serve_fetch")


class ProcessStream(NamedTuple):
    """One process's event stream plus its read health."""
    name: str
    events: List[Dict[str, Any]]
    n_bad: int = 0


class ClockEstimate(NamedTuple):
    """Per-process clock offset onto the reference clock."""
    offset_s: float         # ADD to this process's timestamps
    rtt_bound_s: float      # |error| <= rtt/2 (median matched pair)
    n_pairs: int
    estimated: bool         # False -> no matched pairs, offset fell to 0


def load_streams(run_dirs: Sequence[str], warn=None) -> List[ProcessStream]:
    """Read N run directories (rotated + torn-tail tolerant) into
    named streams; the stream name is the directory basename."""
    streams = []
    for d in run_dirs:
        events, n_bad = read_events_jsonl(os.path.join(d, "events.jsonl"),
                                          warn=warn)
        streams.append(ProcessStream(os.path.basename(os.path.normpath(d))
                                     or d, events, n_bad))
    return streams


def _traced_spans(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    return [e for e in events
            if e.get("kind") == "span" and e.get("trace_id")]


def _windows(events, name) -> Dict[int, Tuple[float, float]]:
    """trace_id -> (t_start, t_end) for the given span name."""
    out = {}
    for e in _traced_spans(events):
        if e.get("name") == name:
            out[e["trace_id"]] = (e["t"], e["t"] + e.get("dur_s", 0.0))
    return out


def estimate_offsets(streams: Sequence[ProcessStream],
                     reference: Optional[str] = None
                     ) -> Dict[str, ClockEstimate]:
    """Per-stream clock offsets onto the reference stream's clock.

    The reference defaults to the first stream that carries
    ``frontend_request`` spans (the server — the hub every client pairs
    with).  A stream with no matched request/reply pairs against the
    reference keeps offset 0 with ``estimated=False``.
    """
    by_name = {s.name: s for s in streams}
    if reference is None:
        reference = next((s.name for s in streams
                          if _windows(s.events, FRONTEND_SPAN)), None)
        if reference is None and streams:
            reference = streams[0].name
    ref = by_name.get(reference)
    out: Dict[str, ClockEstimate] = {}
    ref_server = _windows(ref.events, FRONTEND_SPAN) if ref else {}
    ref_client = _windows(ref.events, CLIENT_SPAN) if ref else {}
    for s in streams:
        if ref is None or s.name == reference:
            out[s.name] = ClockEstimate(0.0, 0.0, 0, s.name == reference)
            continue
        offsets, rtts = [], []
        # This stream is the client, the reference the server ...
        mine_c = _windows(s.events, CLIENT_SPAN)
        for tid, (t1, t4) in mine_c.items():
            if tid in ref_server:
                t2, t3 = ref_server[tid]
                offsets.append(((t2 - t1) + (t3 - t4)) / 2.0)
                rtts.append((t4 - t1) - (t3 - t2))
        # ... or the reference is the client and this stream the server.
        mine_s = _windows(s.events, FRONTEND_SPAN)
        for tid, (t2, t3) in mine_s.items():
            if tid in ref_client:
                t1, t4 = ref_client[tid]
                offsets.append(-(((t2 - t1) + (t3 - t4)) / 2.0))
                rtts.append((t4 - t1) - (t3 - t2))
        if offsets:
            out[s.name] = ClockEstimate(
                percentile(offsets, 50),
                max(0.0, percentile(rtts, 50)) / 2.0,
                len(offsets), True)
        else:
            out[s.name] = ClockEstimate(0.0, 0.0, 0, False)
    return out


def merge_traces(streams: Sequence[ProcessStream],
                 offsets: Optional[Dict[str, ClockEstimate]] = None
                 ) -> Dict[int, List[Dict[str, Any]]]:
    """Group skew-corrected spans by trace_id.  Each returned span is a
    COPY with ``t`` shifted onto the reference clock and a ``proc``
    field naming its source stream."""
    offsets = offsets if offsets is not None else estimate_offsets(streams)
    traces: Dict[int, List[Dict[str, Any]]] = {}
    for s in streams:
        off = offsets.get(s.name, ClockEstimate(0.0, 0.0, 0, False)).offset_s
        for e in _traced_spans(s.events):
            rec = dict(e)
            rec["t"] = e["t"] + off
            rec["proc"] = s.name
            traces.setdefault(e["trace_id"], []).append(rec)
    for spans in traces.values():
        spans.sort(key=lambda r: r["t"])
    return traces


def batch_span_index(streams: Sequence[ProcessStream],
                     offsets: Optional[Dict[str, ClockEstimate]] = None
                     ) -> Dict[Any, List[Dict[str, Any]]]:
    """Batcher-trace-id -> skew-corrected batch-level engine spans.
    ``serve_stage``/``serve_dispatch``/``serve_fetch`` cover a whole
    bucket dispatch, so they carry the member requests' batcher trace
    ids in a ``traces`` attribute instead of one ``trace_id``."""
    offsets = offsets if offsets is not None else estimate_offsets(streams)
    index: Dict[Any, List[Dict[str, Any]]] = {}
    for s in streams:
        off = offsets.get(s.name, ClockEstimate(0.0, 0.0, 0, False)).offset_s
        for e in s.events:
            if e.get("kind") != "span" or e.get("name") not in _BATCH_SPANS:
                continue
            rec = dict(e)
            rec["t"] = e.get("t", 0.0) + off
            rec["proc"] = s.name
            for bt in (e.get("traces") or ()):
                index.setdefault(bt, []).append(rec)
    return index


def _build_waterfall(spans: List[Dict[str, Any]]) -> Dict[str, Any]:
    """One trace's spans -> one waterfall dict (pure, single trace)."""
    stages: Dict[str, float] = {}
    batch: Dict[str, Dict[str, Any]] = {}
    client_ms = frontend_ms = None
    bucket = None
    batcher_trace = None
    procs, origins = set(), set()
    for e in spans:
        procs.add(e.get("proc", "?"))
        if e.get("origin"):
            origins.add(e["origin"])
        name = e.get("name")
        dur_ms = e.get("dur_s", 0.0) * 1e3
        if name == CLIENT_SPAN:
            client_ms = dur_ms
        elif name == FRONTEND_SPAN:
            frontend_ms = dur_ms
        elif name in _BATCH_SPANS:
            batch[name] = e
            if e.get("bucket") is not None:
                bucket = e["bucket"]
        elif name in _SPAN_TO_STAGE:
            stage = _SPAN_TO_STAGE[name]
            stages[stage] = stages.get(stage, 0.0) + dur_ms
            if e.get("trace") is not None:
                batcher_trace = e["trace"]
    # Per-request spans carry the batcher trace id; batch-level engine
    # spans were pre-joined by the caller (their ``traces`` attr).
    for name, e in batch.items():
        stage = _SPAN_TO_STAGE[name]
        stages[stage] = stages.get(stage, 0.0) + e.get("dur_s", 0.0) * 1e3
    ordered = {s: round(stages[s], 3) for s in STAGE_ORDER if s in stages}
    total = sum(ordered.values())
    # Complete = the client saw a reply AND the device ran the request.
    complete = client_ms is not None and "device_compute" in ordered
    out: Dict[str, Any] = {
        "trace_id": spans[0]["trace_id"] if spans else 0,
        "complete": complete,
        "stages": ordered,
        "sum_ms": round(total, 3),
        "procs": sorted(procs),
        "origins": sorted(origins),
        "n_spans": len(spans),
    }
    if bucket is not None:
        out["bucket"] = bucket
    if batcher_trace is not None:
        out["trace"] = batcher_trace
    if frontend_ms is not None:
        out["frontend_ms"] = round(frontend_ms, 3)
        out["server_residual_ms"] = round(frontend_ms - total, 3)
    if client_ms is not None:
        out["client_ms"] = round(client_ms, 3)
        # wire + skew residual: client round-trip minus the server window
        if frontend_ms is not None:
            out["wire_ms"] = round(client_ms - frontend_ms, 3)
    return out


def build_waterfalls(traces: Dict[int, List[Dict[str, Any]]],
                     batch_index: Optional[Dict[Any, List[Dict[str, Any]]]]
                     = None) -> List[Dict[str, Any]]:
    """All traces -> waterfalls, joining batch-level engine spans to each
    member request via the batcher trace id its per-request spans carry
    (``trace`` attribute on ``sched_queue``/``trace_client``/...)."""
    batch_index = batch_index or {}
    waterfalls = []
    for tid, spans in sorted(traces.items()):
        bt = next((e.get("trace") for e in spans
                   if e.get("trace") is not None), None)
        joined = list(spans)
        if bt is not None:
            joined += batch_index.get(bt, [])
        waterfalls.append(_build_waterfall(joined))
    return waterfalls


def fit_cost_prior(waterfalls: List[Dict[str, Any]],
                   prior_flops: Dict[int, float]) -> Optional[Dict[str, Any]]:
    """Least-squares one-rate fit of measured device-compute time against
    the cost model's per-bucket flop counts: ``ms ~= k * flops``.  The
    per-bucket predicted/measured ratio flags buckets whose measured
    time diverges from their flop share."""
    by_bucket: Dict[int, List[float]] = {}
    for w in waterfalls:
        b = w.get("bucket")
        ms = w["stages"].get("device_compute")
        if b in prior_flops and ms is not None:
            by_bucket.setdefault(b, []).append(ms)
    if not by_bucket:
        return None
    med = {b: percentile(v, 50) for b, v in by_bucket.items()}
    sfm = sum(prior_flops[b] * m for b, m in med.items())
    sff = sum(prior_flops[b] ** 2 for b in med)
    k = sfm / sff if sff else 0.0
    buckets = {}
    for b, m in sorted(med.items()):
        pred = k * prior_flops[b]
        buckets[str(b)] = {
            "measured_ms_p50": round(m, 3),
            "prior_ms": round(pred, 3),
            "measured_over_prior": round(m / pred, 3) if pred else None,
            "n": len(by_bucket[b]),
        }
    return {"rate_ms_per_flop": k, "by_bucket": buckets}


def aggregate_streams(streams: Sequence[ProcessStream], *,
                      reference: Optional[str] = None,
                      prior_flops: Optional[Dict[int, float]] = None,
                      max_waterfalls: int = 8) -> Dict[str, Any]:
    """The full aggregation: streams -> skew estimates, waterfalls,
    per-stage p50/p99 attribution, critical-path shares, residuals."""
    offsets = estimate_offsets(streams, reference=reference)
    traces = merge_traces(streams, offsets)
    waterfalls = build_waterfalls(traces, batch_span_index(streams, offsets))
    complete = [w for w in waterfalls if w["complete"]]
    stage_ms: Dict[str, List[float]] = {}
    for w in waterfalls:
        for s, ms in w["stages"].items():
            stage_ms.setdefault(s, []).append(ms)
    attribution = {
        s: {"p50": round(percentile(v, 50), 3),
            "p99": round(percentile(v, 99), 3),
            "mean": round(sum(v) / len(v), 3), "count": len(v)}
        for s, v in ((s, stage_ms[s]) for s in STAGE_ORDER if s in stage_ms)}
    # Critical-path share: per complete waterfall, each stage's fraction
    # of the stage sum (stages are sequential per request, so the "path"
    # is the whole chain; the share says which link dominates).
    shares: Dict[str, List[float]] = {}
    for w in complete:
        total = w["sum_ms"] or 1e-9
        for s, ms in w["stages"].items():
            shares.setdefault(s, []).append(ms / total)
    critical = {s: round(sum(v) / len(v), 4)
                for s, v in ((s, shares[s])
                             for s in STAGE_ORDER if s in shares)}
    dominant = max(critical.items(), key=lambda kv: kv[1])[0] \
        if critical else None
    residuals = [w["client_ms"] - w["sum_ms"] for w in complete
                 if w.get("client_ms") is not None]
    # The reference stream's estimate is the only (estimated, 0-pair) one.
    ref_name = next((n for n, c in offsets.items()
                     if c.estimated and c.n_pairs == 0), None)
    out: Dict[str, Any] = {
        "reference": ref_name,
        "processes": {
            s.name: {
                "events": len(s.events), "bad_lines": s.n_bad,
                "clock_offset_s": round(offsets[s.name].offset_s, 6),
                "rtt_bound_s": round(offsets[s.name].rtt_bound_s, 6),
                "skew_pairs": offsets[s.name].n_pairs,
                "skew_estimated": offsets[s.name].estimated,
            } for s in streams},
        "traces": len(waterfalls),
        "complete": len(complete),
        "orphaned": len(waterfalls) - len(complete),
        "stage_ms": attribution,
        "critical_path": {"share": critical, "dominant": dominant},
        # Complete waterfalls first: the sample should show reconstructed
        # requests, not a page of shed/orphaned stubs.
        "waterfalls": sorted(
            waterfalls, key=lambda w: (not w["complete"], -w["n_spans"])
        )[:max_waterfalls],
    }
    if residuals:
        out["client_minus_stages_ms"] = {
            "p50": round(percentile(residuals, 50), 3),
            "p99": round(percentile(residuals, 99), 3)}
    if prior_flops:
        prior = fit_cost_prior(waterfalls, prior_flops)
        if prior is not None:
            out["cost_prior"] = prior
    return out


def aggregate_run_dirs(run_dirs: Sequence[str], *, warn=None,
                       **kwargs) -> Dict[str, Any]:
    """Convenience wrapper: N telemetry run dirs -> aggregation report."""
    return aggregate_streams(load_streams(run_dirs, warn=warn), **kwargs)
