"""The telemetry recorder: JSONL events, wall-clock spans, manifest, summary.

Three primitives, one file format:

* **events**  — per-step records (``kind: "step"``): loss, step/forward wall
  time, the steady flag (first 20-iteration window and ragged-tail dispatches
  excluded, mirroring ``WindowedTimers``), epoch and iteration number.
* **spans**   — named wall-clock regions (``kind: "span"``): host augment,
  prefetch put, eval, compile/warmup, checkpoint save.  Spans nest; each
  record carries its depth and parent name.  The span stack is thread-local
  because the host-augment producer runs on its own thread.
* **gauges/counters** — point-in-time values (``kind: "gauge"``) and
  monotonic tallies (``kind: "counter"``): prefetch queue depth, native-
  loader status, device ``memory_stats()``, collective op counts/bytes.

A run directory holds three files: ``manifest.json`` (the run header,
written once at trainer construction), ``events.jsonl`` (one JSON object per
line, append-only), and ``summary.json`` (steady-state percentiles, written
by ``finalize()``).  Construct with ``out_dir=None`` for an in-memory
recorder (bench sections) — same API, events kept in ``.records``.

The DISABLED path is ``NULL``: a stateless singleton whose methods do
nothing and whose ``span()`` returns a shared no-op context manager, so a
run without ``--telemetry-out`` performs zero file writes and zero per-step
allocations (guard hot call sites on ``telemetry.enabled`` so even the
argument dicts are never built).
"""

from __future__ import annotations

import json
import os
import subprocess
import threading
import time
from typing import Any, Dict, IO, List, Optional, Tuple

_SCHEMA_VERSION = 1


def atomic_write_json(path: str, obj, indent: Optional[int] = 2) -> None:
    """Complete-or-absent JSON write: dump to a unique temp file in the
    same directory, then ``os.replace`` into place.  A crash or preemption
    signal mid-write leaves either the previous file or the new one —
    never a torn half-document (pinned by tests/test_ft.py with a
    kill-mid-write subprocess)."""
    tmp = f"{path}.{os.getpid()}.tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(obj, f, indent=indent, default=str)
            f.write("\n")
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _rotated_paths(path: str) -> List[str]:
    """The rotated set behind ``path``, OLDEST FIRST: ``events.N.jsonl``
    down to ``events.1.jsonl`` (rotation keeps the numbering contiguous,
    so the scan stops at the first hole)."""
    base, ext = os.path.splitext(path)
    found = []
    n = 1
    while os.path.exists(f"{base}.{n}{ext}"):
        found.append(f"{base}.{n}{ext}")
        n += 1
    return list(reversed(found))


def read_events_jsonl(path: str,
                      warn=None) -> Tuple[List[Dict[str, Any]], int]:
    """Read an events.jsonl -> (events, n_bad), INCLUDING any rotated
    predecessors (``events.N.jsonl`` ... ``events.1.jsonl``, oldest
    first — size-aware rotation, round 8).  A run killed mid-write
    (preemption is a NORMAL exit path for this codebase) legitimately
    leaves a truncated final line; undecodable lines are counted and
    reported through ``warn`` (callable, e.g. ``log``) instead of failing
    the whole report."""
    events: List[Dict[str, Any]] = []
    n_bad = 0

    def _read_one(p: str) -> None:
        nonlocal n_bad
        with open(p) as f:
            for lineno, line in enumerate(f, 1):
                if not line.strip():
                    continue
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError:
                    n_bad += 1
                    if warn is not None:
                        warn(f"{p}:{lineno}: undecodable event line "
                             f"(truncated write?) — skipped")

    for p in _rotated_paths(path):
        _read_one(p)
    if os.path.exists(path):
        _read_one(path)
    return events, n_bad


def percentile(values: List[float], q: float) -> float:
    """Linear-interpolation percentile of an UNSORTED sample, q in [0, 100].

    Matches numpy's default ("linear") method: sorted [1..10] gives
    p50 = 5.5, p95 = 9.55, p99 = 9.91.  Pure-python on purpose — the
    summary path must not pull jax/numpy into report-only tooling.
    """
    if not values:
        raise ValueError("percentile of empty sample")
    xs = sorted(values)
    if len(xs) == 1:
        return float(xs[0])
    rank = (q / 100.0) * (len(xs) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(xs) - 1)
    if xs[lo] == xs[hi]:
        # Exact, not interpolated: a*(1-f) + a*f can drift a ulp, which
        # breaks p50 <= p95 <= p99 monotonicity on repeated samples.
        return float(xs[lo])
    frac = rank - lo
    return float(xs[lo] * (1.0 - frac) + xs[hi] * frac)


def git_sha(cwd: Optional[str] = None) -> Optional[str]:
    """Current commit sha, or None outside a git checkout."""
    try:
        out = subprocess.run(["git", "rev-parse", "HEAD"], cwd=cwd,
                             capture_output=True, text=True, timeout=10)
    except (OSError, subprocess.TimeoutExpired):
        return None
    sha = out.stdout.strip()
    return sha if out.returncode == 0 and sha else None


class _NullSpan:
    """Shared no-op context manager — one instance for the whole process."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class NullTelemetry:
    """The disabled recorder: every method is a no-op, ``enabled`` is False.

    Stateless (``__slots__ = ()``): recording through it cannot grow any
    per-step list, and it never touches the filesystem.  Hot call sites
    should still guard on ``.enabled`` so argument construction is skipped
    too.
    """
    __slots__ = ()
    enabled = False

    def step(self, **fields) -> None:
        pass

    def gauge(self, name: str, value, **attrs) -> None:
        pass

    def counter(self, name: str, inc=1, **attrs) -> None:
        pass

    def span(self, name: str, **attrs):
        return _NULL_SPAN

    def span_event(self, name: str, t0: float, dur_s: float,
                   **attrs) -> None:
        pass

    def alert(self, rule: str, severity: str, **attrs) -> None:
        pass

    def add_tap(self, fn) -> None:
        pass

    def counter_totals(self) -> Dict[str, float]:
        return {}

    def write_manifest(self, fields: Dict[str, Any]) -> None:
        pass

    def update_manifest(self, fields: Dict[str, Any]) -> None:
        pass

    def finalize(self, **extra) -> Optional[Dict[str, Any]]:
        return None


NULL = NullTelemetry()


class _Span:
    __slots__ = ("_tel", "name", "attrs", "t0")

    def __init__(self, tel: "Telemetry", name: str, attrs: Dict[str, Any]):
        self._tel = tel
        self.name = name
        self.attrs = attrs
        self.t0 = 0.0

    def __enter__(self):
        self._tel._push(self.name)
        self.t0 = time.time()
        return self

    def __exit__(self, exc_type, *exc):
        dur = time.time() - self.t0
        parent, depth = self._tel._pop()
        rec = {"kind": "span", "name": self.name, "t": self.t0,
               "dur_s": dur, "depth": depth}
        if parent is not None:
            rec["parent"] = parent
        if exc_type is not None:
            rec["error"] = exc_type.__name__
        if self.attrs:
            rec.update(self.attrs)
        self._tel._emit(rec)
        return False


class Telemetry:
    """The enabled recorder.  ``out_dir=None`` keeps events in memory."""

    enabled = True

    def __init__(self, out_dir: Optional[str] = None, *,
                 rotate_bytes: int = 64 * 2 ** 20, rotate_keep: int = 3):
        """``rotate_bytes`` caps the live ``events.jsonl``: past it the
        file rotates to ``events.1.jsonl`` (older generations shift up,
        at most ``rotate_keep`` kept) so a multi-hour run cannot grow the
        log unbounded.  0 disables rotation.  The 64 MiB default is far
        above any CI run — short runs never rotate (the run-directory
        listing stays exactly its three files)."""
        self.out_dir = out_dir
        self.records: List[Dict[str, Any]] = []  # in-memory mirror when no dir
        self.manifest: Optional[Dict[str, Any]] = None
        self.summary: Optional[Dict[str, Any]] = None
        self._fh: Optional[IO[str]] = None
        self._lock = threading.Lock()  # producer thread emits spans too
        self._tls = threading.local()
        self._counters: Dict[str, float] = {}
        self._taps: List = []   # live record observers (alert engine)
        if rotate_keep < 1:
            raise ValueError(f"rotate_keep must be >= 1, got {rotate_keep}")
        self._rotate_bytes = int(rotate_bytes)
        self._rotate_keep = int(rotate_keep)
        self._events_path: Optional[str] = None
        self._event_bytes = 0
        if out_dir is not None:
            os.makedirs(out_dir, exist_ok=True)
            self._events_path = os.path.join(out_dir, "events.jsonl")
            if os.path.exists(self._events_path):   # append to a prior run
                self._event_bytes = os.path.getsize(self._events_path)
            self._fh = open(self._events_path, "a", buffering=1)

    # -- span stack (per thread) -------------------------------------------

    def _stack(self) -> List[str]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _push(self, name: str) -> None:
        self._stack().append(name)

    def _pop(self) -> Tuple[Optional[str], int]:
        st = self._stack()
        st.pop()
        return (st[-1] if st else None), len(st)

    # -- emission -----------------------------------------------------------

    def _emit(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            if self._fh is not None:
                line = json.dumps(rec) + "\n"
                self._fh.write(line)
                self._event_bytes += len(line)
                if self._rotate_bytes and \
                        self._event_bytes >= self._rotate_bytes:
                    self._rotate_locked()
            else:
                self.records.append(rec)
        # Taps run OUTSIDE the writer lock: a tap that emits (the alert
        # engine firing through ``alert()``) re-enters ``_emit`` on the
        # same thread, which would deadlock under the held lock.
        for tap in self._taps:
            tap(rec)

    def add_tap(self, fn) -> None:
        """Register a live record observer, called once per emitted
        record (after it is written).  Taps must be fast and must not
        raise — the serve path runs through them."""
        self._taps.append(fn)

    def _rotate_locked(self) -> None:
        """Shift the rotated generations up one slot (dropping the one
        past ``rotate_keep``) and reopen a fresh live file.  Caller holds
        the lock; every move is an ``os.replace`` so a crash mid-rotation
        leaves whole files, never torn ones."""
        self._fh.close()
        base, ext = os.path.splitext(self._events_path)
        oldest = f"{base}.{self._rotate_keep}{ext}"
        if os.path.exists(oldest):
            os.unlink(oldest)
        for k in range(self._rotate_keep - 1, 0, -1):
            src = f"{base}.{k}{ext}"
            if os.path.exists(src):
                os.replace(src, f"{base}.{k + 1}{ext}")
        os.replace(self._events_path, f"{base}.1{ext}")
        self._fh = open(self._events_path, "a", buffering=1)
        self._event_bytes = 0

    def step(self, *, epoch: int, iter: int, loss: float, step_time: float,
             forward_time: Optional[float] = None, steady: bool = True,
             **extra) -> None:
        rec = {"kind": "step", "t": time.time(), "epoch": epoch, "iter": iter,
               "loss": float(loss), "step_time_s": float(step_time),
               "steady": bool(steady)}
        if forward_time is not None:
            rec["forward_time_s"] = float(forward_time)
        if extra:
            rec.update(extra)
        self._emit(rec)

    def gauge(self, name: str, value, **attrs) -> None:
        rec = {"kind": "gauge", "name": name, "t": time.time(),
               "value": value}
        if attrs:
            rec.update(attrs)
        self._emit(rec)

    def counter(self, name: str, inc=1, **attrs) -> None:
        with self._lock:
            total = self._counters.get(name, 0) + inc
            self._counters[name] = total
        rec = {"kind": "counter", "name": name, "t": time.time(),
               "inc": inc, "total": total}
        if attrs:
            rec.update(attrs)
        self._emit(rec)

    def span(self, name: str, **attrs) -> _Span:
        return _Span(self, name, attrs)

    def span_event(self, name: str, t0: float, dur_s: float,
                   **attrs) -> None:
        """Record an ALREADY-MEASURED interval as a span event.  Unlike
        ``span()`` (a context manager bound to one thread's span stack)
        this suits asynchronous intervals whose endpoints live on
        different threads or came off the wire — a client round-trip, a
        queue wait — so depth is 0 and parenting comes from the caller's
        trace attrs, not the thread-local stack."""
        rec = {"kind": "span", "name": name, "t": float(t0),
               "dur_s": float(dur_s), "depth": 0}
        if attrs:
            rec.update(attrs)
        self._emit(rec)

    def alert(self, rule: str, severity: str, **attrs) -> None:
        """Record a structured alert event (``kind: "alert"``) — the
        ``obs/alerts.py`` rules engine emits these; ``summarize_events``
        rolls them up under ``summary["alerts"]``."""
        rec = {"kind": "alert", "rule": rule, "severity": severity,
               "t": time.time()}
        if attrs:
            rec.update(attrs)
        self._emit(rec)

    def counter_totals(self) -> Dict[str, float]:
        """Current counter totals (a copy) without draining the event log
        — live introspection for the serving demo's bucket histogram."""
        with self._lock:
            return dict(self._counters)

    # -- run header / footer -------------------------------------------------

    def write_manifest(self, fields: Dict[str, Any]) -> None:
        man = {"schema_version": _SCHEMA_VERSION, "created_at": time.time()}
        man.update(fields)
        self.manifest = man
        if self.out_dir is not None:
            atomic_write_json(os.path.join(self.out_dir, "manifest.json"),
                              man)

    def update_manifest(self, fields: Dict[str, Any]) -> None:
        """Merge ``fields`` into the manifest and rewrite it — for facts
        only known at the END of a run (compilation-cache hit/miss
        counts) joining a header written at construction."""
        man = dict(self.manifest) if self.manifest else \
            {"schema_version": _SCHEMA_VERSION, "created_at": time.time()}
        man.update(fields)
        self.manifest = man
        if self.out_dir is not None:
            atomic_write_json(os.path.join(self.out_dir, "manifest.json"),
                              man)

    def finalize(self, **extra) -> Dict[str, Any]:
        """Compute the steady-state summary; write ``summary.json`` if the
        recorder is file-backed.  Safe to call once at the end of a run —
        also closes the event log."""
        events = self._drain_events()
        summary = summarize_events(events, **extra)
        self.summary = summary
        if self.out_dir is not None:
            atomic_write_json(os.path.join(self.out_dir, "summary.json"),
                              summary)
            with self._lock:
                if self._fh is not None:
                    self._fh.close()
                    self._fh = None
        return summary

    def _drain_events(self) -> List[Dict[str, Any]]:
        if self.out_dir is None:
            return list(self.records)
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
        events, _ = read_events_jsonl(
            os.path.join(self.out_dir, "events.jsonl"))
        return events


def summarize_events(events: List[Dict[str, Any]],
                     global_batch: Optional[int] = None,
                     **extra) -> Dict[str, Any]:
    """Steady-state summary of an event list: step-time percentiles,
    throughput, span totals, final counter values."""
    steps = [e for e in events if e.get("kind") == "step"]
    steady = [e["step_time_s"] for e in steps if e.get("steady")]
    spans: Dict[str, Dict[str, float]] = {}
    for e in events:
        if e.get("kind") == "span":
            agg = spans.setdefault(e["name"], {"count": 0, "total_s": 0.0})
            agg["count"] += 1
            agg["total_s"] += e.get("dur_s", 0.0)
    counters: Dict[str, float] = {}
    for e in events:
        if e.get("kind") == "counter":
            counters[e["name"]] = e["total"]
    gauges: Dict[str, Any] = {}
    for e in events:
        if e.get("kind") == "gauge":
            gauges[e["name"]] = e["value"]   # last write wins
    # Per-rank step-time aggregation (elastic runs emit one
    # ``rank_step_time_s`` gauge per rank per window boundary).
    ranks: Dict[str, Dict[str, float]] = {}
    for e in events:
        if e.get("kind") == "gauge" and e.get("name") == "rank_step_time_s" \
                and "rank" in e:
            agg = ranks.setdefault(str(e["rank"]), {
                "count": 0, "total_s": 0.0, "max_s": 0.0})
            agg["count"] += 1
            agg["total_s"] += e["value"]
            agg["max_s"] = max(agg["max_s"], e["value"])
    for agg in ranks.values():
        agg["mean_s"] = agg["total_s"] / agg["count"]

    summary: Dict[str, Any] = {
        "schema_version": _SCHEMA_VERSION,
        "num_events": len(events),
        "num_steps": len(steps),
        "num_steady_steps": len(steady),
        "spans": spans,
        "counters": counters,
        "gauges": gauges,
    }
    if ranks:
        summary["ranks"] = ranks
    # Serving latency split (round 8): the per-request queue-wait vs
    # service-time gauges the micro-batcher emits, aggregated so SLO
    # reading needs only the summary.
    qw = [e["value"] for e in events if e.get("kind") == "gauge"
          and e.get("name") == "serve_queue_wait_ms"]
    svc = [e["value"] for e in events if e.get("kind") == "gauge"
           and e.get("name") == "serve_service_ms"]
    if qw or svc:
        def _pct(vals):
            if not vals:
                return None
            return {"p50": percentile(vals, 50),
                    "p95": percentile(vals, 95),
                    "mean": sum(vals) / len(vals)}
        summary["serving_latency_split"] = {
            "requests": max(len(qw), len(svc)),
            "queue_wait_ms": _pct(qw),
            "service_ms": _pct(svc),
        }
    # SLO attainment by tier (round 9): the scheduler's per-request
    # ``serve_latency_ms`` gauges carry ``tier``/``met`` attrs and its
    # shed decisions are ``serve_shed`` counter events with
    # ``tier``/``reason`` — aggregated so the report's ``== slo ==``
    # section reads only the summary.
    slo_tiers: Dict[str, Dict[str, int]] = {}
    for e in events:
        if e.get("kind") == "gauge" and e.get("name") == "serve_latency_ms" \
                and "met" in e and "tier" in e:
            agg = slo_tiers.setdefault(str(e["tier"]),
                                       {"served": 0, "met": 0, "shed": 0})
            agg["served"] += 1
            agg["met"] += 1 if e["met"] else 0
    shed_reasons: Dict[str, int] = {}
    for e in events:
        if e.get("kind") == "counter" and e.get("name") == "serve_shed":
            if "tier" in e:
                agg = slo_tiers.setdefault(str(e["tier"]),
                                           {"served": 0, "met": 0, "shed": 0})
                agg["shed"] += int(e.get("inc", 1))
            reason = str(e.get("reason", "unknown"))
            shed_reasons[reason] = shed_reasons.get(reason, 0) \
                + int(e.get("inc", 1))
    if slo_tiers:
        for agg in slo_tiers.values():
            offered = agg["served"] + agg["shed"]
            agg["late"] = agg["served"] - agg["met"]
            agg["attainment"] = round(agg["met"] / offered, 4) \
                if offered else None
        replica_util = {
            str(e["replica"]): e["value"] for e in events
            if e.get("kind") == "gauge" and e.get("name") == "replica_util"
            and "replica" in e}
        summary["slo"] = {"by_tier": slo_tiers,
                          "shed_by_reason": shed_reasons}
        if replica_util:
            summary["slo"]["replica_util"] = replica_util
    # Alert roll-up (round 12): structured ``kind: "alert"`` events from
    # the obs/alerts.py rules engine, grouped by deterministic rule id so
    # chaos drills can pin exactly which rules fired from the summary.
    alerts: Dict[str, Dict[str, Any]] = {}
    for e in events:
        if e.get("kind") == "alert":
            agg = alerts.setdefault(str(e.get("rule", "unknown")), {
                "count": 0, "severity": str(e.get("severity", "warn"))})
            agg["count"] += 1
    if alerts:
        summary["alerts"] = alerts
    if steps:
        summary["final_loss"] = steps[-1]["loss"]
        summary["mean_loss"] = sum(s["loss"] for s in steps) / len(steps)
    if steady:
        summary["steady_step_time_s"] = {
            "p50": percentile(steady, 50),
            "p95": percentile(steady, 95),
            "p99": percentile(steady, 99),
            "mean": sum(steady) / len(steady),
            "min": min(steady),
            "max": max(steady),
        }
        if global_batch:
            summary["steady_images_per_sec"] = (
                global_batch * len(steady) / sum(steady))
    if global_batch:
        summary["global_batch"] = global_batch
    if extra:
        summary.update(extra)
    return summary


def read_run(out_dir: str) -> Tuple[Optional[Dict[str, Any]],
                                    List[Dict[str, Any]],
                                    Optional[Dict[str, Any]]]:
    """Load a run directory -> (manifest, events, summary); missing files
    come back as None / empty list so partial runs still render."""
    def _load(name):
        path = os.path.join(out_dir, name)
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return json.load(f)

    manifest = _load("manifest.json")
    summary = _load("summary.json")
    events, _ = read_events_jsonl(os.path.join(out_dir, "events.jsonl"))
    return manifest, events, summary
