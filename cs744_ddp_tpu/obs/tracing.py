"""Cross-process trace context + the wire-extension codec (round 12).

A request that crosses process boundaries (client -> frontend ->
scheduler -> replica engine) leaves spans in EACH process's own
``events.jsonl``.  To stitch those into one end-to-end waterfall
(``obs/aggregate.py``) every hop needs a shared identity:

* ``trace_id``        — one 64-bit id for the whole request, minted by
  whichever process sees it first (usually the client).
* ``span_id``         — this hop's own 64-bit id.
* ``parent_span_id``  — the upstream hop's ``span_id`` (0 at the root),
  giving the aggregator the parent/child edges without any global state.
* ``origin``          — a short producer tag (``client``, ``frontend``,
  ``sched``, ...) so orphaned spans remain attributable when a process
  dies mid-request (chaos ``replica_death``).

On the wire the context rides in an OPTIONAL TRAILING EXTENSION BLOCK
appended after the fixed-layout body of the length-prefixed frames
(``serve/frontend.py``).  The block is magic-byte + version gated and
TLV-encoded, so old peers (which validate ``len(body)`` against the
fixed layout only up to the declared payload) never see it, and new
peers skip unknown tags by length — the forward-compat path future
fields ride on.  Encoding with ``ctx=None`` is byte-identical to the
pre-round-12 format: tracing off costs zero wire bytes.
"""

from __future__ import annotations

import random
import struct
from typing import Dict, NamedTuple, Optional, Tuple

# Process-local id source.  SystemRandom: fork-safe and collision-free
# across the N OS processes whose logs the aggregator later merges —
# a seeded RNG would mint the SAME ids in every worker.
_ID_RNG = random.SystemRandom()


def new_id() -> int:
    """A nonzero random 64-bit id (0 is reserved for "no parent")."""
    while True:
        v = _ID_RNG.getrandbits(64)
        if v:
            return v


class TraceContext(NamedTuple):
    """One hop's identity inside a distributed trace (immutable)."""

    trace_id: int
    span_id: int
    parent_span_id: int = 0
    origin: str = ""

    @classmethod
    def new_root(cls, origin: str) -> "TraceContext":
        """Fresh trace: new trace_id, new span_id, no parent."""
        return cls(new_id(), new_id(), 0, origin)

    def child(self, origin: str) -> "TraceContext":
        """The next hop: same trace, new span, parented on this span."""
        return TraceContext(self.trace_id, new_id(), self.span_id, origin)

    def attrs(self) -> Dict[str, object]:
        """Span attributes for ``Telemetry.span``/``span_event`` — the
        join keys ``obs/aggregate.py`` groups and parents by."""
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_span_id": self.parent_span_id,
                "origin": self.origin}


# -- wire extension block ---------------------------------------------------
#
#   magic u8 (0xE1) | version u8 (1) | repeated { tag u8 | len u16 LE |
#   payload[len] }
#
# Unknown tags are skipped by length (forward compat); an unknown
# version or a torn block degrades to "no extension" rather than a
# decode error — tracing must never break serving.

EXT_MAGIC = 0xE1
EXT_VERSION = 1

TAG_TRACE = 1           # <QQQ> trace/span/parent ids + origin utf-8
TAG_SERVER_TIMES = 2    # <dd> t_recv, t_send on the server's clock

_EXT_HEAD = struct.Struct("<BB")
_TLV_HEAD = struct.Struct("<BH")
_TRACE_IDS = struct.Struct("<QQQ")
_TIMES = struct.Struct("<dd")


def pack_ext(fields: Dict[int, bytes]) -> bytes:
    """Encode a tag->payload map as one extension block ('' if empty)."""
    if not fields:
        return b""
    parts = [_EXT_HEAD.pack(EXT_MAGIC, EXT_VERSION)]
    for tag, payload in sorted(fields.items()):
        if len(payload) > 0xFFFF:
            raise ValueError(f"extension field {tag} too large")
        parts.append(_TLV_HEAD.pack(tag, len(payload)))
        parts.append(payload)
    return b"".join(parts)


# Tags this build understands; anything else is a forward-compat skip.
KNOWN_TAGS = frozenset({TAG_TRACE, TAG_SERVER_TIMES})


def unpack_ext_ex(buf: bytes) -> Tuple[Dict[int, bytes], int, int]:
    """Decode an extension block -> ``(fields, skipped_unknown, torn)``.
    Unknown tags are still CARRIED in ``fields`` (skipped by length,
    uninterpreted — a relay must not strip a newer peer's data) but
    counted, as is a torn trailing field (dropped).  A missing or
    unversioned block yields ``({}, 0, 0)``.  Never raises — tracing
    must never break serving."""
    if len(buf) < _EXT_HEAD.size:
        return {}, 0, 0
    magic, version = _EXT_HEAD.unpack_from(buf, 0)
    if magic != EXT_MAGIC or version != EXT_VERSION:
        return {}, 0, 0
    fields: Dict[int, bytes] = {}
    skipped = torn = 0
    off = _EXT_HEAD.size
    while off + _TLV_HEAD.size <= len(buf):
        tag, n = _TLV_HEAD.unpack_from(buf, off)
        off += _TLV_HEAD.size
        if off + n > len(buf):    # torn trailing field — drop it
            torn += 1
            break
        if tag not in KNOWN_TAGS:
            skipped += 1
        fields[tag] = buf[off:off + n]
        off += n
    return fields, skipped, torn


def unpack_ext(buf: bytes) -> Dict[int, bytes]:
    """Decode an extension block, skipping unknown tags; a missing,
    unversioned, or torn block yields ``{}`` (never raises)."""
    return unpack_ext_ex(buf)[0]


def pack_trace(ctx: TraceContext) -> bytes:
    origin = ctx.origin.encode("utf-8")[:255]
    return _TRACE_IDS.pack(ctx.trace_id, ctx.span_id,
                           ctx.parent_span_id) + origin


def unpack_trace(payload: bytes) -> Optional[TraceContext]:
    if len(payload) < _TRACE_IDS.size:
        return None
    trace_id, span_id, parent = _TRACE_IDS.unpack_from(payload, 0)
    if not trace_id:
        return None
    origin = payload[_TRACE_IDS.size:].decode("utf-8", "replace")
    return TraceContext(trace_id, span_id, parent, origin)


def pack_server_times(t_recv: float, t_send: float) -> bytes:
    return _TIMES.pack(t_recv, t_send)


def unpack_server_times(payload: bytes) -> Optional[Tuple[float, float]]:
    if len(payload) < _TIMES.size:
        return None
    return _TIMES.unpack_from(payload, 0)  # type: ignore[return-value]
