"""CLI — the reference's argparse surface plus strategy/model selection.

Reference flags (``/root/reference/src/Part 2a/main.py:156-175``):
``--master`` (coordinator IP, required there), ``--num-nodes``, ``--rank``,
``--epochs`` (default 1); port 6585 and global batch 256 hardcoded.  Here the
same knobs exist (with modern aliases), plus:

  * ``--strategy {single,gather,allreduce,ddp,overlap,compress-bf16,
    compress-int8,powersgd}`` selects the gradient-sync strategy: the
    Part-1/2a/2b/3 reference equivalents plus the round-7 extensions
    (overlapped bucketed DDP and the compressed collectives —
    error-feedback bf16/int8 quantization and PowerSGD low-rank);
  * ``--model {vgg11,resnet18}`` selects the model (resnet18 = the
    BASELINE.json stress config);
  * ``--num-devices`` restricts the mesh (e.g. to compare 1 vs 8 chips).

Run: ``python -m cs744_ddp_tpu.cli --strategy ddp --epochs 1``
"""

from __future__ import annotations

import argparse

from .ft import FTConfig, ChaosPlan, guard as ftguard
from .obs import NULL, Telemetry
from .utils import compcache
from .ops import sgd
from .parallel import mesh as meshlib
from .train.loop import GLOBAL_BATCH, Trainer


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("cs744_ddp_tpu")
    p.add_argument("--master", "--coordinator", dest="master", default=None,
                   help="coordinator address for multi-host runs "
                        "(reference --master)")
    p.add_argument("--num-nodes", "--num-processes", dest="num_nodes",
                   type=int, default=1,
                   help="number of host processes (reference --num-nodes)")
    p.add_argument("--rank", "--process-id", dest="rank", type=int, default=0,
                   help="this process's id (reference --rank)")
    p.add_argument("--epochs", type=int, default=1,
                   help="epochs to run (reference default 1)")
    p.add_argument("--strategy", default="allreduce",
                   choices=["single", "gather", "allreduce", "ddp",
                            "overlap", "compress-bf16", "compress-int8",
                            "powersgd"],
                   help="gradient sync strategy: Part 1/2a/2b/3 equivalents "
                        "(single/gather/allreduce/ddp) plus overlapped "
                        "bucketed DDP (overlap) and the compressed "
                        "collectives (compress-bf16/compress-int8 with "
                        "error feedback, powersgd low-rank)")
    p.add_argument("--compress-rank", type=int, default=None,
                   help="PowerSGD approximation rank (default 4); only "
                        "meaningful with --strategy powersgd")
    p.add_argument("--model", default="vgg11",
                   help="vgg11/13/16/19, resnet18/34, or any name "
                        "registered via models.register_model (validated "
                        "by the model zoo, not argparse, so plugged-in "
                        "models work everywhere the built-ins do)")
    p.add_argument("--batch-size", type=int, default=GLOBAL_BATCH,
                   help="GLOBAL batch (divided across workers, as in the "
                        "reference: Part 2a/main.py:22)")
    p.add_argument("--num-devices", type=int, default=None,
                   help="use only the first N local devices")
    p.add_argument("--data-dir", default="./data")
    p.add_argument("--require-real-data", action="store_true",
                   help="fail loudly if --data-dir holds no real CIFAR-10 "
                        "pickle batches instead of silently training on the "
                        "deterministic synthetic fallback (the right mode "
                        "for any run whose accuracy numbers will be read "
                        "as CIFAR-10 results)")
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight-decay", type=float, default=1e-4)
    p.add_argument("--no-augment", action="store_true")
    p.add_argument("--host-augment", action="store_true",
                   help="run the train transform in the C++ host pipeline "
                        "(data/native.py, the reference's DataLoader-worker "
                        "model), staged as uint8 window buffers and "
                        "dispatched as scanned windows (per-batch f32 under "
                        "--profile-phases); default keeps the transform "
                        "fused on device")
    p.add_argument("--precision", default="f32", choices=["f32", "bf16"],
                   help="compute precision: f32 = reference parity; bf16 = "
                        "mixed precision (f32 master weights/optimizer/BN "
                        "stats/loss, bf16 matmul+conv — the MXU native mode)")
    p.add_argument("--profile-phases", action="store_true",
                   help="additionally time a forward-only program to report "
                        "the reference's fwd/bwd split. NOTE: this per-step "
                        "mode pays per-call dispatch latency (large on "
                        "remote/tunneled TPU backends), so phase times can "
                        "dwarf the fused windowed step time the default "
                        "mode reports; use --profile-dir for a real trace")
    p.add_argument("--limit-train-batches", type=int, default=None,
                   help="cap train iterations per epoch (smoke runs/benches)")
    p.add_argument("--limit-eval-batches", type=int, default=None,
                   help="cap evaluation batches (smoke runs/benches)")
    p.add_argument("--port", type=int, default=6585,
                   help="coordinator port (reference hardcodes 6585)")
    p.add_argument("--profile-dir", default=None,
                   help="capture a jax.profiler trace of the first trained "
                        "epoch (XPlane, TensorBoard/Perfetto-viewable) - the "
                        "superset of the print-based timers (SURVEY.md §5)")
    p.add_argument("--checkpoint-dir", default=None,
                   help="save TrainState after each epoch and auto-resume "
                        "from the latest checkpoint (beyond-parity: the "
                        "reference has no checkpointing)")
    p.add_argument("--publish-dir", default=None,
                   help="publish the serving weights (params + BN stats) "
                        "as a versioned crc-checksummed bundle into this "
                        "directory every --publish-every completed epochs; "
                        "a serving process started with "
                        "--serve-publish-dir on the same directory "
                        "hot-swaps each version between dispatches with "
                        "zero recompiles (publish/)")
    p.add_argument("--publish-every", type=int, default=1, metavar="K",
                   help="publish every K completed epochs (default 1); "
                        "only meaningful with --publish-dir")
    p.add_argument("--metrics-ring", type=int, default=None, metavar="N",
                   help="device-resident metric ring capacity for the "
                        "windowed train paths (obs/ringbuf.py): per-step "
                        "loss/grad-norm/ok rows are written on device and "
                        "drained ONCE per window instead of per step. "
                        "Default on (capacity 64); 0 disables (per-step "
                        "fetch of stacked window losses); N >= 20 sets "
                        "the capacity")
    p.add_argument("--telemetry-out", default=None,
                   help="write structured run telemetry to this directory: "
                        "manifest.json (run header), events.jsonl (per-step "
                        "events, spans, gauges) and summary.json (steady-"
                        "state percentiles); render with "
                        "tools/telemetry_report.py. Off by default (zero "
                        "overhead); the stdout print schedule is unchanged "
                        "either way")
    ft = p.add_argument_group(
        "fault tolerance (ft/)",
        "preemption-safe resume, supervised staging, non-finite guard and "
        "the deterministic chaos harness; all off by default (the hot path "
        "pays nothing)")
    ft.add_argument("--nonfinite", default="off", choices=ftguard.POLICIES,
                    help="per-step finiteness guard on loss + global grad "
                         "norm: halt = raise (the bad update is never "
                         "applied), skip = keep prior params and continue, "
                         "restore = roll back to the last checkpoint "
                         "snapshot; off (default) compiles no guard at all")
    ft.add_argument("--chaos", action="append", default=None,
                    metavar="SITE:step[:seed]",
                    help="inject a deterministic fault once at the given "
                         "step (repeatable); sites: producer_crash, "
                         "put_delay, put_fail, corrupt_slot, nonfinite_grad "
                         "(requires --nonfinite != off), preempt (requires "
                         "--checkpoint-dir). Rank-level sites (the third "
                         "field is the target RANK, not a seed — "
                         "SITE:step:rank): rank_death, slow_rank; "
                         "coordinator_loss fires on recovery progress "
                         "(requires --elastic). Replica-level sites (third "
                         "field is the target REPLICA, step counts its own "
                         "dispatches): replica_death, slow_replica, and "
                         "swap_mid_batch (a pending publish races a live "
                         "dispatch: the racing dispatch is answered by the "
                         "OLD weights, the next by the new) "
                         "(requires --serve-frontend). Publish-level sites "
                         "(step counts the publisher's own publishes, "
                         "third field is a payload seed): publish_torn "
                         "(bundle corrupted after rename — rejected on "
                         "crc, old version keeps serving), publish_stale "
                         "(re-announces the previous version — skipped) "
                         "(require --publish-dir)")
    ft.add_argument("--ft-put-timeout", type=float, default=30.0,
                    metavar="SECONDS",
                    help="watchdog deadline on each staged chunk device_put")
    ft.add_argument("--ft-put-retries", type=int, default=3,
                    help="attempts per chunk device_put (exponential "
                         "backoff between attempts)")
    ft.add_argument("--ft-stall-timeout", type=float, default=120.0,
                    metavar="SECONDS",
                    help="consumer-side staging stall deadline; exceeding "
                         "it triggers producer restart, then degraded "
                         "synchronous staging (stream bit-identical)")
    ft.add_argument("--ft-verify-chunks", action="store_true",
                    help="checksum every staged batch at fill time and "
                         "re-stage any row whose bytes changed by transfer "
                         "time (auto-enabled by corrupt_slot chaos)")
    el = p.add_argument_group(
        "elastic (elastic/)",
        "checkpoint-based world-resize resume: a run interrupted at "
        "world=N resumes at world=M with re-sharded data order; rank-level "
        "chaos drives the retry -> shrink -> single-rank degradation "
        "ladder (requires --checkpoint-dir)")
    el.add_argument("--elastic", default="off",
                    choices=["off", "weak", "strong"],
                    help="weak = pinned per-chip batch (global batch scales "
                         "with the world; deterministic, example-measured "
                         "resume); strong = pinned global batch re-bucketed "
                         "across the world with bitwise world-invariant "
                         "math (microshard window, elastic/step_elastic.py)")
    el.add_argument("--resume-world", type=int, default=None, metavar="M",
                    help="run/resume at world size M (overrides "
                         "--num-devices): checkpointed progress from any "
                         "previous world is re-planned onto M under the "
                         "--elastic protocol")
    sv = p.add_argument_group(
        "serving (serve/)",
        "single-chip inference: AOT bucket ladder + micro-batching + "
        "warm-start executable cache; --serve-demo replays a seeded "
        "open-loop request trace and prints the stats sheet as one JSON "
        "line instead of training")
    sv.add_argument("--serve-demo", action="store_true",
                    help="serve mode: build the executable ladder for "
                         "--model, replay the seeded synthetic request "
                         "trace at each --serve-load, print startup + "
                         "latency/throughput JSON")
    sv.add_argument("--serve-buckets", default="1,8,32,128,256",
                    help="comma list of batch buckets for the AOT ladder")
    sv.add_argument("--serve-precision", default="f32",
                    choices=["f32", "bf16"])
    sv.add_argument("--serve-requests", type=int, default=200,
                    help="requests per offered-load replay")
    sv.add_argument("--serve-load", action="append", type=float,
                    default=None, metavar="RPS",
                    help="offered load in requests/sec (repeatable; "
                         "default one replay at 20 rps)")
    sv.add_argument("--serve-max-wait-ms", type=float, default=5.0,
                    help="micro-batcher deadline: max time the oldest "
                         "queued request waits before dispatch")
    sv.add_argument("--serve-cache-dir", default=None,
                    help="warm-start executable cache directory (a "
                         "restarted server loads serialized executables "
                         "instead of compiling)")
    sv.add_argument("--serve-seed", type=int, default=0,
                    help="seed for the synthetic request trace AND the "
                         "demo model init")
    sv.add_argument("--serve-frontend", action="store_true",
                    help="serve mode: start --serve-replicas device-pinned "
                         "engine replicas behind the least-loaded router "
                         "and the socket front-end, replay the seeded "
                         "TIERED trace over a real socket at each "
                         "--serve-load, print goodput/SLO-attainment JSON")
    sv.add_argument("--serve-replicas", type=int, default=1, metavar="N",
                    help="engine replicas, one per mesh device "
                         "(round-robin when N exceeds the device count)")
    sv.add_argument("--serve-slo-ms", type=float, default=None,
                    metavar="MS",
                    help="flatten the trace to ONE tier with this SLO "
                         "(default: the 3-tier 75/200/600 ms mixture)")
    sv.add_argument("--serve-port", type=int, default=0, metavar="PORT",
                    help="front-end TCP port (0 = ephemeral; the bound "
                         "address is in the output JSON — tools/"
                         "serve_load.py replays against it)")
    sv.add_argument("--serve-pipeline", default="on", choices=["on", "off"],
                    help="double-buffered dispatch pipeline in each "
                         "replica's scheduler: stage + issue batch N+1 "
                         "while batch N computes (off = the serial "
                         "dispatch-fence-reply loop, exactly the round-13 "
                         "path; only with --serve-frontend)")
    sv.add_argument("--serve-shed", default="on", choices=["on", "off"],
                    help="deadline-aware load shedding in the scheduler "
                         "(off = serve everything, late replies included "
                         "— the no-shed ablation)")
    sv.add_argument("--serve-publish-dir", default=None, metavar="DIR",
                    help="watch DIR for published weight bundles (a "
                         "--publish-dir training run's output) and "
                         "hot-swap every replica to each new version "
                         "between dispatches — zero restarts, zero "
                         "recompiles; replies carry the serving "
                         "model_version (only with --serve-frontend)")
    sv.add_argument("--serve-publish-poll-ms", type=float, default=50.0,
                    metavar="MS",
                    help="publish-directory poll interval for "
                         "--serve-publish-dir (default 50 ms)")
    sv.add_argument("--serve-trace-client", default=None, metavar="DIR",
                    help="write the in-process load client's distributed-"
                         "trace spans (events.jsonl) to DIR — a second "
                         "stream for tools/trace_waterfall.py; server "
                         "spans ride --telemetry-out (only with "
                         "--serve-frontend)")
    sv.add_argument("--serve-alerts", default="on", choices=["on", "off"],
                    help="attach the streaming SLO alert engine "
                         "(obs/alerts.py) to the server telemetry; the "
                         "fired-rule summary lands in the manifest and "
                         "the output JSON (default on; needs "
                         "--telemetry-out)")
    au = p.add_argument_group(
        "static analysis (analysis/)",
        "HLO/jaxpr program audit: certify each compiled program's cost "
        "shape (collective contract per strategy, dtype leaks, donation "
        "misses, host syncs in loop bodies, baked constants) before any "
        "step runs; results land in the telemetry manifest")
    au.add_argument("--audit", default="off",
                    choices=["off", "warn", "strict"],
                    help="audit the programs this run will dispatch "
                         "(train: the configured strategy's step/window/"
                         "host-window + eval; serve: the bucket ladder). "
                         "warn prints findings and continues; strict "
                         "exits 2 on any unwaived finding")
    au.add_argument("--audit-zoo", action="store_true",
                    help="audit the FULL program zoo (all 8 strategies x "
                         "3 train paths, eval, the serving ladder at "
                         "--serve-buckets) and exit without training; "
                         "combine with --audit strict for the CI gate")
    au.add_argument("--audit-waive", action="append", default=None,
                    metavar="RULE[@GLOB]",
                    help="waive an audit rule, optionally only for "
                         "programs matching a glob, e.g. "
                         "baked-constants@serve/* (repeatable); waived "
                         "findings are reported but don't fail strict")
    au.add_argument("--verify-static", action="store_true",
                    help="run the whole-repo static verification gate "
                         "and exit: repo lints, the lock-order deadlock "
                         "detector (certified acquisition order), wire-"
                         "protocol schema conformance against serve/"
                         "wire.py, the full program-zoo audit (incl. the "
                         "peak-HBM liveness certificate vs the v5e "
                         "budget), and the static host-round-trip "
                         "certificate; prints a JSON summary, exits 2 "
                         "on any finding")
    return p


def ft_config_from_args(args) -> "FTConfig | None":
    """FTConfig when any ft surface is requested, else None (the Trainer's
    ft=None fast path — no supervision wrappers, no guard compiled)."""
    defaults = (args.nonfinite == "off" and not args.chaos
                and args.ft_put_timeout == 30.0 and args.ft_put_retries == 3
                and args.ft_stall_timeout == 120.0
                and not args.ft_verify_chunks)
    if defaults:
        return None
    return FTConfig(
        nonfinite=args.nonfinite,
        chaos=ChaosPlan.parse(args.chaos),
        put_timeout_s=args.ft_put_timeout,
        put_retries=args.ft_put_retries,
        stall_timeout_s=args.ft_stall_timeout,
        verify_chunks=args.ft_verify_chunks,
    )


def _apply_audit(args, telemetry, result) -> None:
    """Shared --audit plumbing: print the report, record it in the run
    manifest (enabled recorders only — see analysis.audit.record_audit),
    exit 2 under strict when any unwaived finding remains."""
    from .analysis import audit as auditlib

    for line in result.format_lines():
        print(line)
    auditlib.record_audit(telemetry, result)
    if args.audit == "strict" and not result.clean:
        raise SystemExit(2)


def audit_main(args, telemetry) -> None:
    """--audit-zoo: certify the full shipped-program matrix and exit.
    With an enabled recorder the same lowerings also get a static
    cost-model attribution pass (analysis/costmodel) recorded under
    manifest["attribution"] — audit and attribution read ONE set of
    programs, so they cannot drift."""
    from .analysis import audit as auditlib
    from .serve import demo

    collect = getattr(telemetry, "enabled", False)
    result = auditlib.audit_zoo(
        model=args.model, global_batch=args.batch_size,
        precision=args.precision,
        serve_buckets=demo.parse_buckets(args.serve_buckets),
        serve_precision=args.serve_precision, serve_swap_recert=True,
        num_devices=args.num_devices, waive=args.audit_waive or (),
        metrics_ring=args.metrics_ring != 0, collect_hlo=collect)
    if collect:
        auditlib.record_attribution(
            telemetry, auditlib.zoo_attribution(result))
    _apply_audit(args, telemetry, result)


def verify_static_main(args, telemetry) -> None:
    """--verify-static: one gate over every static analyzer.  Repo lints
    + lock-order deadlock detection + wire schema conformance run first
    (pure AST, fast); then the full zoo is lowered once and shared by
    the program audit and the host-round-trip certificate.  The summary
    lands on stdout as JSON (and in the manifest for enabled recorders);
    any finding anywhere exits 2 — this is the CI front door
    tests/test_analysis.py::test_repo_static_verification pins."""
    import json
    import os

    from .analysis import audit as auditlib
    from .analysis import costmodel, memlife
    from .analysis import dispatch as dispatchlib
    from .analysis import lockgraph, wire_schema
    from .analysis.pylint_rules import DEFAULT_TARGETS, lint_paths
    from .serve import demo, wire
    from .utils.metrics import WINDOW

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings = lint_paths([os.path.join(repo, t) for t in DEFAULT_TARGETS])
    graph = lockgraph.build_repo_graph(repo)
    findings += lockgraph.check_graph(graph)
    findings += wire_schema.check_wire(repo)
    result = auditlib.audit_zoo(
        model=args.model, global_batch=args.batch_size,
        precision=args.precision,
        serve_buckets=demo.parse_buckets(args.serve_buckets),
        serve_precision=args.serve_precision,
        num_devices=args.num_devices, waive=args.audit_waive or (),
        metrics_ring=args.metrics_ring != 0, collect_hlo=True)
    cert = dispatchlib.certify_zoo(result, window=4,
                                   nbatches=WINDOW + WINDOW // 4,
                                   include_eval=True)
    findings += memlife.check_memory(repo)
    for f in findings:
        print(f"[verify-static] {f.rule}: {f.path}:{f.line} {f.message}")
    for line in result.format_lines():
        print(line)
    peaks = {r.program: r.stats.get("peak_mib", 0.0)
             for r in result.reports}
    fattest = max(peaks, key=peaks.get) if peaks else None
    summary = {
        "clean": (not findings and result.clean and cert["clean"]),
        "lint_findings": len(findings),
        "lock_graph": lockgraph.graph_summary(graph),
        "wire_schema": wire.schema_summary(),
        "audit": {"clean": result.clean, "n_programs": len(result.reports),
                  "n_findings": len(result.findings())},
        "dispatch": cert,
        # Compact memory certificate: the zoo-wide peak vs the
        # single-sourced per-chip budget (the peak-memory audit rule is
        # what fails "clean"; this entry is the headline number).
        "memory": {
            "budget_mib": round(
                costmodel.V5E_HBM_CAPACITY_BYTES / 2**20, 1),
            "max_peak_mib": max(peaks.values(), default=0.0),
            "max_peak_program": fattest,
        },
    }
    print(json.dumps(summary))
    auditlib.record_audit(telemetry, result)
    if getattr(telemetry, "enabled", False):
        telemetry.update_manifest({"verify_static": {
            k: summary[k] for k in ("clean", "lint_findings", "audit")}})
    if not summary["clean"]:
        raise SystemExit(2)


def elastic_main(args, telemetry) -> None:
    """--elastic: train under the ElasticCoordinator's degradation ladder.
    The coordinator rebuilds the trainer at each membership generation;
    ``--resume-world M`` starts (or resumes a checkpointed run) at world M.
    Requires --checkpoint-dir — recovery and resize both go through the
    emergency checkpoint protocol."""
    import json

    from .elastic import ElasticCoordinator
    from .ft import NULL_CHAOS

    if args.checkpoint_dir is None:
        raise SystemExit("--elastic requires --checkpoint-dir (recovery "
                         "and world-resize resume go through checkpoints)")
    world = args.resume_world or args.num_devices or \
        meshlib.make_mesh(None).devices.size
    ft = ft_config_from_args(args)
    # ONE chaos plan shared by trainer and coordinator: entries are
    # one-shot across membership generations, so an injected fault fires
    # in exactly one generation.
    chaos = ft.chaos if ft is not None else NULL_CHAOS

    def make_trainer(w: int) -> Trainer:
        return Trainer(
            model=args.model, strategy=args.strategy, num_devices=w,
            compress_rank=args.compress_rank,
            global_batch=args.batch_size, data_dir=args.data_dir,
            augment=not args.no_augment, precision=args.precision,
            sgd_cfg=sgd.SGDConfig(lr=args.lr, momentum=args.momentum,
                                  weight_decay=args.weight_decay),
            limit_train_batches=args.limit_train_batches,
            limit_eval_batches=args.limit_eval_batches,
            metrics_ring=args.metrics_ring,
            telemetry=telemetry, ft=ft, elastic=args.elastic)

    coord = ElasticCoordinator(
        make_trainer, world=world, global_batch=args.batch_size,
        protocol=args.elastic, chaos=chaos)
    coord.run(args.epochs, checkpoint_dir=args.checkpoint_dir)
    report = coord.report()
    telemetry.update_manifest({"elastic_report": report})
    print("elastic report: " + json.dumps(report))


def serve_frontend_main(args, telemetry) -> None:
    """--serve-frontend: replicated serving tier end-to-end — N
    device-pinned engine replicas behind the least-loaded router and the
    socket front-end; replay the seeded tiered trace over a REAL socket
    at each offered load, print ONE JSON line (startup + per-load
    goodput/attainment stats)."""
    import json

    import jax

    from .ft import NULL_CHAOS
    from .serve import demo
    from .serve.frontend import FrontendClient, ServingFrontend
    from .serve.replica import EngineReplica
    from .serve.router import ReplicaRouter

    ft = ft_config_from_args(args)
    chaos = ft.chaos if ft is not None else NULL_CHAOS
    buckets = demo.parse_buckets(args.serve_buckets)
    shed = args.serve_shed == "on"
    pipeline = args.serve_pipeline == "on"
    alerts = None
    if telemetry.enabled and args.serve_alerts == "on":
        from .obs import AlertEngine
        alerts = AlertEngine(telemetry)
        telemetry.add_tap(alerts.observe)
    client_tel = None
    if args.serve_trace_client is not None:
        client_tel = Telemetry(args.serve_trace_client)
        client_tel.write_manifest({"mode": "serve-frontend-client"})
    devices = jax.devices()
    replicas = [
        EngineReplica(i, args.model, device=devices[i % len(devices)],
                      buckets=buckets, precision=args.serve_precision,
                      seed=args.serve_seed, telemetry=telemetry,
                      cache_dir=args.serve_cache_dir, chaos=chaos,
                      shed=shed, pipeline=pipeline)
        for i in range(max(1, args.serve_replicas))]
    telemetry.write_manifest({
        "mode": "serve-frontend", "model": args.model,
        "buckets": list(buckets), "precision": args.serve_precision,
        "replicas": len(replicas), "shed": shed, "pipeline": pipeline,
        "slo_ms": args.serve_slo_ms,
        "requests": args.serve_requests, "seed": args.serve_seed,
        "chaos": chaos.spec() if chaos.enabled else [],
    })
    startup = {f"replica{r.index}": r.startup() for r in replicas}
    tiers = demo.DEFAULT_TIERS if args.serve_slo_ms is None \
        else ((0, 1, float(args.serve_slo_ms)),)
    router = ReplicaRouter(replicas, telemetry=telemetry)
    watcher = None
    if args.serve_publish_dir is not None:
        from .publish import WeightWatcher
        watcher = WeightWatcher(
            args.serve_publish_dir, replicas, telemetry=telemetry,
            chaos=chaos,
            poll_interval_s=args.serve_publish_poll_ms / 1e3)
    stats = {}
    sizes = tuple(s for s in demo.SIZE_CHOICES if s <= buckets[-1])
    address = None
    with router:
        if watcher is not None:
            watcher.start()
        frontend = ServingFrontend(router, port=args.serve_port,
                                   telemetry=telemetry)
        try:
            with frontend:
                address = frontend.address
                pool = demo.request_pool()
                for rps in (args.serve_load or [20.0]):
                    trace = demo.synthetic_load_trace(
                        args.serve_requests, offered_rps=rps,
                        seed=args.serve_seed, size_choices=sizes, tiers=tiers)
                    with FrontendClient(frontend.address,
                                        telemetry=client_tel) as client:
                        stats[f"{rps:g}rps"] = demo.replay_load(
                            client, trace, pool=pool, seed=args.serve_seed)
        finally:
            if watcher is not None:
                watcher.stop()
            if client_tel is not None:
                client_tel.finalize()
    out = {"address": list(address), "startup": startup,
           "router": router.stats(), "load": stats}
    if watcher is not None:
        out["publish"] = watcher.report()
    if alerts is not None:
        out["alerts"] = alerts.summary()
    if telemetry.enabled:
        telemetry.update_manifest({"router": router.stats()})
        if watcher is not None:
            telemetry.update_manifest({"publish": watcher.report()})
        if alerts is not None:
            telemetry.update_manifest({"alerts": alerts.summary()})
    print(json.dumps(out))


def serve_main(args, telemetry) -> None:
    """--serve-demo: build the ladder, replay the seeded trace at each
    offered load, print ONE JSON line (startup report + per-load stats)."""
    import json

    from .serve import InferenceEngine, demo

    buckets = demo.parse_buckets(args.serve_buckets)
    engine = InferenceEngine(
        args.model, buckets=buckets, precisions=(args.serve_precision,),
        cache_dir=args.serve_cache_dir, seed=args.serve_seed,
        telemetry=telemetry)
    telemetry.write_manifest({
        "mode": "serve", "model": args.model, "buckets": list(buckets),
        "precision": args.serve_precision,
        "max_wait_ms": args.serve_max_wait_ms,
        "requests": args.serve_requests, "seed": args.serve_seed,
    })
    if args.audit != "off":
        from .analysis import audit as auditlib
        result = auditlib.AuditResult(reports=auditlib.audit_serving(
            engine=engine, precision=args.serve_precision,
            waive=args.audit_waive or ()))
        _apply_audit(args, telemetry, result)
    startup = engine.startup()
    loads = args.serve_load or [20.0]
    stats = {}
    for rps in loads:
        stats[f"{rps:g}rps"] = demo.run_demo(
            engine, n_requests=args.serve_requests, offered_rps=rps,
            seed=args.serve_seed, max_wait_ms=args.serve_max_wait_ms,
            precision=args.serve_precision)
    print(json.dumps({"startup": startup, "demo": stats}))


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    # Persistent XLA compilation cache, unconditionally (previously only
    # bench/tests opted in): repeated CLI runs of the same config skip
    # multi-second XLA compiles; hit/miss counts land in the manifest.
    compcache.enable_persistent_compilation_cache(compcache.repo_root())
    if args.require_real_data:
        from .data import cifar10
        if not cifar10.has_real_data(args.data_dir):
            raise SystemExit(
                f"--require-real-data: no CIFAR-10 pickle batches under "
                f"{args.data_dir!r} (expected "
                f"{args.data_dir}/cifar-10-batches-py/data_batch_*); "
                "refusing to fall back to the synthetic stand-in")
    meshlib.initialize_distributed(args.master, args.num_nodes, args.rank,
                                   port=args.port)
    telemetry = (Telemetry(args.telemetry_out)
                 if args.telemetry_out is not None else NULL)
    if args.verify_static:
        try:
            verify_static_main(args, telemetry)
        finally:
            telemetry.update_manifest(
                {"compilation_cache": compcache.cache_stats()})
            telemetry.finalize()
        return
    if args.audit_zoo:
        try:
            audit_main(args, telemetry)
        finally:
            telemetry.update_manifest(
                {"compilation_cache": compcache.cache_stats()})
            telemetry.finalize()
        return
    if args.serve_frontend:
        try:
            serve_frontend_main(args, telemetry)
        finally:
            telemetry.update_manifest(
                {"compilation_cache": compcache.cache_stats()})
            telemetry.finalize()
        return
    if args.serve_demo:
        try:
            serve_main(args, telemetry)
        finally:
            telemetry.update_manifest(
                {"compilation_cache": compcache.cache_stats()})
            telemetry.finalize()
        return
    if args.resume_world is not None and args.elastic == "off":
        raise SystemExit("--resume-world requires --elastic (weak|strong): "
                         "without a declared protocol there is no defined "
                         "mapping of saved progress onto a new world size")
    if args.elastic != "off":
        try:
            elastic_main(args, telemetry)
        finally:
            telemetry.update_manifest(
                {"compilation_cache": compcache.cache_stats()})
            telemetry.finalize(global_batch=args.batch_size)
        return
    trainer = Trainer(
        model=args.model,
        strategy=args.strategy,
        num_devices=args.num_devices,
        compress_rank=args.compress_rank,
        global_batch=args.batch_size,
        data_dir=args.data_dir,
        augment=not args.no_augment,
        precision=args.precision,
        sgd_cfg=sgd.SGDConfig(lr=args.lr, momentum=args.momentum,
                              weight_decay=args.weight_decay),
        profile_phases=args.profile_phases,
        host_augment=args.host_augment,
        limit_train_batches=args.limit_train_batches,
        limit_eval_batches=args.limit_eval_batches,
        metrics_ring=args.metrics_ring,
        telemetry=telemetry,
        ft=ft_config_from_args(args),
    )
    try:
        if args.audit != "off":
            # Certify the programs THIS run dispatches (configured
            # strategy's three train paths + eval) before any step runs;
            # strict exits 2 with nothing trained.  After the Trainer's
            # manifest write so the audit record merges instead of being
            # clobbered.
            from .analysis import audit as auditlib
            _apply_audit(args, telemetry, auditlib.audit_zoo(
                model=args.model, global_batch=args.batch_size,
                precision=args.precision,
                strategies=(args.strategy,),
                num_devices=args.num_devices,
                waive=args.audit_waive or (),
                metrics_ring=bool(trainer.metrics_ring)))
        trainer.run(args.epochs, checkpoint_dir=args.checkpoint_dir,
                    profile_dir=args.profile_dir,
                    publish_dir=args.publish_dir,
                    publish_every=args.publish_every)
    finally:
        # summary.json even on an interrupted run — partial runs are the
        # ones whose artifact is most needed.  Cache hit/miss tallies are
        # only final once every compile has happened, hence manifest
        # UPDATE here rather than a field at construction.
        telemetry.update_manifest(
            {"compilation_cache": compcache.cache_stats()})
        telemetry.finalize(global_batch=args.batch_size)


if __name__ == "__main__":
    main()
