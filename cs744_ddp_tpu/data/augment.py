"""Augmentation: pad-4 random crop + horizontal flip + channel normalization.

Reference train transform (``/root/reference/src/Part 1/main.py:84-89``):
RandomCrop(32, padding=4) -> RandomHorizontalFlip -> ToTensor -> Normalize;
test transform is ToTensor -> Normalize only (``:91-93``).

TPU-first design: augmentation runs *on device, inside the jitted train step*,
on the uint8 batch — shifting work off the (single-core) host and letting XLA
fuse normalize into the first conv.  The same ops also run under vmap on CPU.
A native C++ host-side pipeline (cs744_ddp_tpu.data.native) provides the
torchvision-DataLoader-equivalent path for host-side preprocessing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .cifar10 import MEAN, STD

# NOTE: the stat constants stay NumPy at module scope on purpose — creating
# jnp arrays at import time would initialize the JAX backend before
# jax.distributed.initialize() runs (multi-host bootstrap, parallel/mesh.py).
# Inside jit they constant-fold identically.


def normalize(images_u8: jax.Array) -> jax.Array:
    """uint8 [.,32,32,3] -> float32, (x/255 - mean)/std (ToTensor+Normalize)."""
    x = images_u8.astype(jnp.float32) / 255.0
    return (x - MEAN) / STD


def _crop_one(img: jax.Array, off: jax.Array) -> jax.Array:
    """img: [40,40,3] padded; off: [2] int32 in [0,8]."""
    return jax.lax.dynamic_slice(img, (off[0], off[1], jnp.int32(0)),
                                 (32, 32, 3))


def augment_gather(key: jax.Array, images_u8: jax.Array) -> jax.Array:
    """Reference formulation: vmap'd dynamic_slice crop (lowers to gathers —
    fine on CPU, slow on TPU; kept as the semantics oracle for tests)."""
    n = images_u8.shape[0]
    kc, kf = jax.random.split(key)
    offs = jax.random.randint(kc, (n, 2), 0, 9, dtype=jnp.int32)
    flips = jax.random.bernoulli(kf, 0.5, (n,))

    padded = jnp.pad(images_u8, ((0, 0), (4, 4), (4, 4), (0, 0)))
    cropped = jax.vmap(_crop_one)(padded, offs)
    flipped = jnp.where(flips[:, None, None, None],
                        cropped[:, :, ::-1, :], cropped)
    return normalize(flipped)


def augment(key: jax.Array, images_u8: jax.Array) -> jax.Array:
    """Random pad-4 crop + hflip + normalize. images_u8: [N,32,32,3] uint8.

    TPU-native formulation: the per-example crop/flip is expressed as two
    batched ONE-HOT MATMULS (row-select, then column-select with the flip
    folded into the column one-hot), so the whole augmentation rides the MXU
    instead of lowering to per-example gathers.  One-hot selection sums pick
    exactly one term, and uint8 values (<=255) are exact in bfloat16, so the
    result is bit-identical to the gather formulation (tests/test_data.py).

    Round-3 negative result: a ``take_along_axis`` (gather) variant
    microbenchmarked ~25% cheaper in a standalone scan, but measured ~5%
    SLOWER for the WHOLE train step in A/B (83-85k vs 88-89k img/s at the
    headline config) — in-step, XLA fuses the one-hot matmuls with their
    neighbors better than the gathers.  Standalone microbenchmarks of
    fusion-sensitive ops mislead on TPU; A/B the full step.

    Per-example randomness comes from a single fold of the step key —
    deterministic given (seed, step), independent of device count.
    """
    n = images_u8.shape[0]
    kc, kf = jax.random.split(key)
    offs = jax.random.randint(kc, (n, 2), 0, 9, dtype=jnp.int32)
    flips = jax.random.bernoulli(kf, 0.5, (n,))

    padded = jnp.pad(images_u8, ((0, 0), (4, 4), (4, 4), (0, 0)))
    pads = padded.astype(jnp.bfloat16)

    # Row selector R[n, i, h] = 1 iff h == i + oy[n]       ([N,32,40])
    i32 = jnp.arange(32, dtype=jnp.int32)
    h40 = jnp.arange(40, dtype=jnp.int32)
    rows = (i32[None, :, None] + offs[:, 0][:, None, None]) == h40[None, None, :]
    # Column selector C[n, w, j] = 1 iff w == ox[n] + (31-j if flip else j)
    j32 = jnp.where(flips[:, None], 31 - i32[None, :], i32[None, :])
    target = j32 + offs[:, 1][:, None]                   # [N,32] source col
    cols = h40[None, :, None] == target[:, None, :]      # [N,40,32]

    r = rows.astype(jnp.bfloat16)
    c = cols.astype(jnp.bfloat16)
    # [N,32,40] @ [N,40,40,3] -> [N,32,40,3]; then cols: -> [N,32,32,3]
    picked_rows = jnp.einsum("nih,nhwc->niwc", r, pads)
    cropped = jnp.einsum("niwc,nwj->nijc", picked_rows, c)
    return normalize(cropped.astype(jnp.uint8))
