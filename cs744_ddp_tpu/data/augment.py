"""Augmentation: pad-4 random crop + horizontal flip + channel normalization.

Reference train transform (``/root/reference/src/Part 1/main.py:84-89``):
RandomCrop(32, padding=4) -> RandomHorizontalFlip -> ToTensor -> Normalize;
test transform is ToTensor -> Normalize only (``:91-93``).

TPU-first design: augmentation runs *on device, inside the jitted train step*,
on the uint8 batch — shifting work off the (single-core) host and letting XLA
fuse normalize into the first conv.  The same ops also run under vmap on CPU.
A native C++ host-side pipeline (cs744_ddp_tpu.data.native) provides the
torchvision-DataLoader-equivalent path for host-side preprocessing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .cifar10 import MEAN, STD

# NOTE: the stat constants stay NumPy at module scope on purpose — creating
# jnp arrays at import time would initialize the JAX backend before
# jax.distributed.initialize() runs (multi-host bootstrap, parallel/mesh.py).
# Inside jit they constant-fold identically.


def normalize(images_u8: jax.Array) -> jax.Array:
    """uint8 [.,32,32,3] -> float32, (x/255 - mean)/std (ToTensor+Normalize)."""
    x = images_u8.astype(jnp.float32) / 255.0
    return (x - MEAN) / STD


def _crop_one(img: jax.Array, off: jax.Array) -> jax.Array:
    """img: [40,40,3] padded; off: [2] int32 in [0,8]."""
    return jax.lax.dynamic_slice(img, (off[0], off[1], jnp.int32(0)),
                                 (32, 32, 3))


def augment(key: jax.Array, images_u8: jax.Array) -> jax.Array:
    """Random pad-4 crop + hflip + normalize. images_u8: [N,32,32,3] uint8.

    Per-example randomness comes from a single fold of the step key —
    deterministic given (seed, step), independent of device count.
    """
    n = images_u8.shape[0]
    kc, kf = jax.random.split(key)
    offs = jax.random.randint(kc, (n, 2), 0, 9, dtype=jnp.int32)
    flips = jax.random.bernoulli(kf, 0.5, (n,))

    padded = jnp.pad(images_u8, ((0, 0), (4, 4), (4, 4), (0, 0)))
    cropped = jax.vmap(_crop_one)(padded, offs)
    flipped = jnp.where(flips[:, None, None, None],
                        cropped[:, :, ::-1, :], cropped)
    return normalize(flipped)
