"""CIFAR-10 loading (host side, NumPy) with a deterministic synthetic fallback.

The reference loads CIFAR-10 via ``torchvision.datasets.CIFAR10(download=True)``
(``/root/reference/src/Part 1/main.py:94-103``).  This environment has no
network egress, so:

  * if the standard python-pickle batches (``cifar-10-batches-py``) exist under
    ``data_dir`` they are loaded (bit-identical to torchvision's arrays, but
    kept NHWC uint8 — the TPU-friendly layout);
  * otherwise a *deterministic, learnable* synthetic stand-in with the same
    shapes/dtypes/cardinalities (50k train / 10k test, 32x32x3 uint8,
    10 classes) is generated, so every train/eval/bench path exercises the
    real pipeline.

Channel normalization stats match the reference exactly
(mean=[125.3,123.0,113.9]/255, std=[63.0,62.1,66.7]/255 —
``/root/reference/src/Part 1/main.py:82-83``).
"""

from __future__ import annotations

import os
import pickle
from typing import NamedTuple, Tuple

import numpy as np

MEAN = np.array([125.3, 123.0, 113.9], np.float32) / 255.0
STD = np.array([63.0, 62.1, 66.7], np.float32) / 255.0

TRAIN_SIZE = 50_000
TEST_SIZE = 10_000
NUM_CLASSES = 10


class Split(NamedTuple):
    images: np.ndarray  # [N,32,32,3] uint8
    labels: np.ndarray  # [N] int32


def _load_pickle_batches(batch_dir: str, names) -> Split:
    imgs, labs = [], []
    for name in names:
        with open(os.path.join(batch_dir, name), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        data = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        imgs.append(np.ascontiguousarray(data, np.uint8))
        labs.append(np.asarray(d[b"labels"], np.int32))
    return Split(np.concatenate(imgs), np.concatenate(labs))


def _class_templates() -> np.ndarray:
    """Fixed low-frequency per-class templates, shared by BOTH splits (so a
    model trained on the train split generalizes to the test split)."""
    rng = np.random.default_rng(42)
    small = rng.uniform(40, 215, size=(NUM_CLASSES, 4, 4, 3)).astype(np.float32)
    return np.repeat(np.repeat(small, 8, axis=1), 8, axis=2)


def _synthetic_split(n: int, seed: int) -> Split:
    """Class-templated noisy images: trivially learnable, fully deterministic.

    Each class c gets a fixed low-frequency template (shared across splits);
    a sample is 0.75*template + 0.25*noise quantized to uint8 — enough signal
    that a CNN's loss drops fast (the convergence oracle of SURVEY.md §4),
    enough noise that it is not memorizable from one example.
    """
    rng = np.random.default_rng(seed)
    templates = _class_templates()
    labels = rng.integers(0, NUM_CLASSES, size=n).astype(np.int32)
    noise = rng.uniform(0, 255, size=(n, 32, 32, 3)).astype(np.float32)
    images = 0.75 * templates[labels] + 0.25 * noise
    return Split(np.clip(images, 0, 255).astype(np.uint8), labels)


def has_real_data(data_dir: str = "./data") -> bool:
    """Would ``load`` find the real python-pickle batches here?  The ONE
    check both ``--require-real-data`` surfaces (cli.py, bench.py) share
    with the loader, so the flag can never disagree with what ``load``
    actually does."""
    return os.path.isdir(os.path.join(data_dir, "cifar-10-batches-py"))


def load(data_dir: str = "./data") -> Tuple[Split, Split, bool]:
    """Return (train, test, is_real)."""
    batch_dir = os.path.join(data_dir, "cifar-10-batches-py")
    if os.path.isdir(batch_dir):
        train = _load_pickle_batches(
            batch_dir, [f"data_batch_{i}" for i in range(1, 6)])
        test = _load_pickle_batches(batch_dir, ["test_batch"])
        return train, test, True
    return (_synthetic_split(TRAIN_SIZE, seed=0),
            _synthetic_split(TEST_SIZE, seed=1), False)
