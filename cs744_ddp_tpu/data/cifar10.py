"""CIFAR-10 loading (host side, NumPy) with a deterministic synthetic fallback.

The reference loads CIFAR-10 via ``torchvision.datasets.CIFAR10(download=True)``
(``/root/reference/src/Part 1/main.py:94-103``).  This environment has no
network egress, so:

  * if the standard python-pickle batches (``cifar-10-batches-py``) exist under
    ``data_dir`` they are loaded (bit-identical to torchvision's arrays, but
    kept NHWC uint8 — the TPU-friendly layout);
  * otherwise a *deterministic, learnable* synthetic stand-in with the same
    shapes/dtypes/cardinalities (50k train / 10k test, 32x32x3 uint8,
    10 classes) is generated, so every train/eval/bench path exercises the
    real pipeline.

Channel normalization stats match the reference exactly
(mean=[125.3,123.0,113.9]/255, std=[63.0,62.1,66.7]/255 —
``/root/reference/src/Part 1/main.py:82-83``).
"""

from __future__ import annotations

import functools
import os
import pickle
from typing import NamedTuple, Tuple

import numpy as np

MEAN = np.array([125.3, 123.0, 113.9], np.float32) / 255.0
STD = np.array([63.0, 62.1, 66.7], np.float32) / 255.0

TRAIN_SIZE = 50_000
TEST_SIZE = 10_000
NUM_CLASSES = 10


class Split(NamedTuple):
    images: np.ndarray  # [N,32,32,3] uint8
    labels: np.ndarray  # [N] int32


def _load_pickle_batches(batch_dir: str, names) -> Split:
    imgs, labs = [], []
    for name in names:
        with open(os.path.join(batch_dir, name), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        data = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        imgs.append(np.ascontiguousarray(data, np.uint8))
        labs.append(np.asarray(d[b"labels"], np.int32))
    return Split(np.concatenate(imgs), np.concatenate(labs))


# Synthetic-task difficulty knobs, recalibrated (round 7) so the REFERENCE
# config (VGG-11, lr 0.1) shows a GRADED multi-epoch trajectory on the
# stand-in — neither the frozen-at-19.7% collapse round 5 measured (the old
# single-template/low-noise task pushed the first lr-0.1 step so far the net
# died at ln(10) loss) nor instant 100% (one epoch used to saturate, making
# a 3-epoch trajectory uninformative).  See BASELINE.md "Synthetic-task
# recalibration (round 7)" for the measured before/after trajectories.
_TEMPLATES_PER_CLASS = 3   # intra-class variety: one template is memorizable
_NOISE = 0.7               # per-pixel uniform noise fraction of the mix
_SHARED = 0.55             # inter-class template correlation (harder margins)
_CONTRAST = 0.5            # post-mix contrast toward mid-gray: shrinks the
#                            normalized input scale, which is THE knob that
#                            keeps the first lr-0.1 step from killing the
#                            net (measured on the CI tiny model: contrast
#                            1.0 -> frozen at exactly ln(10) loss even at
#                            full 50k scale; 0.5 -> stable graded learning)
_LABEL_NOISE = 0.1         # fraction of labels resampled uniformly: caps
#                            attainable accuracy below saturation


def _class_templates() -> np.ndarray:
    """Fixed low-frequency templates, shared by BOTH splits (so a model
    trained on the train split generalizes to the test split).

    [NUM_CLASSES, _TEMPLATES_PER_CLASS, 32, 32, 3]: every template is a
    blend of one GLOBAL base pattern (weight ``_SHARED`` — inter-class
    correlation, so classes are not linearly-separable blobs far apart),
    a per-class pattern, and a per-template variant (intra-class variety)."""
    rng = np.random.default_rng(42)
    base = rng.uniform(40, 215, size=(1, 1, 4, 4, 3)).astype(np.float32)
    cls = rng.uniform(40, 215,
                      size=(NUM_CLASSES, 1, 4, 4, 3)).astype(np.float32)
    var = rng.uniform(40, 215,
                      size=(NUM_CLASSES, _TEMPLATES_PER_CLASS, 4, 4, 3)
                      ).astype(np.float32)
    small = _SHARED * base + (1 - _SHARED) * (0.65 * cls + 0.35 * var)
    return np.repeat(np.repeat(small, 8, axis=2), 8, axis=3)


@functools.lru_cache(maxsize=8)
def _synthetic_split(n: int, seed: int) -> Split:
    """Class-templated noisy images: deterministic, learnable, NOT trivial.

    A sample draws one of its class's templates, mixes in ``_NOISE``
    uniform noise, pulls the result toward mid-gray by ``_CONTRAST``, and
    with probability ``_LABEL_NOISE`` carries a uniformly-resampled label.
    Calibrated (see knob comments above) so reference-config training
    rises epoch over epoch while staying between the 10% chance floor and
    saturation — the shape a convergence ORACLE needs to detect both a
    broken step (stuck at chance) and a degenerate task (instant 100%).

    Memoized: generating the full 50k split costs ~4 s of pure numpy, and
    multi-trainer processes (bench sections, the elastic coordinator's
    shrink/resume ladder) would otherwise pay it per Trainer.  The cached
    arrays are shared across callers and therefore read-only; consumers
    that need to mutate must copy."""
    rng = np.random.default_rng(seed)
    templates = _class_templates()
    labels = rng.integers(0, NUM_CLASSES, size=n).astype(np.int32)
    tidx = rng.integers(0, _TEMPLATES_PER_CLASS, size=n)
    noise = rng.uniform(0, 255, size=(n, 32, 32, 3)).astype(np.float32)
    images = (1 - _NOISE) * templates[labels, tidx] + _NOISE * noise
    images = 127.5 + _CONTRAST * (images - 127.5)
    if _LABEL_NOISE:
        flip = rng.random(n) < _LABEL_NOISE
        labels = np.where(flip, rng.integers(0, NUM_CLASSES, size=n),
                          labels).astype(np.int32)
    images = np.clip(images, 0, 255).astype(np.uint8)
    images.setflags(write=False)
    labels.setflags(write=False)
    return Split(images, labels)


def has_real_data(data_dir: str = "./data") -> bool:
    """Would ``load`` find the real python-pickle batches here?  The ONE
    check both ``--require-real-data`` surfaces (cli.py, bench.py) share
    with the loader, so the flag can never disagree with what ``load``
    actually does."""
    return os.path.isdir(os.path.join(data_dir, "cifar-10-batches-py"))


def load(data_dir: str = "./data") -> Tuple[Split, Split, bool]:
    """Return (train, test, is_real)."""
    batch_dir = os.path.join(data_dir, "cifar-10-batches-py")
    if os.path.isdir(batch_dir):
        train = _load_pickle_batches(
            batch_dir, [f"data_batch_{i}" for i in range(1, 6)])
        test = _load_pickle_batches(batch_dir, ["test_batch"])
        return train, test, True
    return (_synthetic_split(TRAIN_SIZE, seed=0),
            _synthetic_split(TEST_SIZE, seed=1), False)
