"""Data pipeline: CIFAR-10 loading, sharding, augmentation, prefetch."""

from . import augment, cifar10, sharding          # noqa: F401
from .cifar10 import Split, load                   # noqa: F401
from .sharding import ShardedSampler, global_epoch_indices  # noqa: F401
