"""Dataset sharding — the DistributedSampler equivalent.

Reference semantics (``/root/reference/src/Part 2a/main.py:38-44``):
``DistributedSampler(training_set, num_replicas=size, rank=rank)`` with
``shuffle=False`` on the loader; per-worker batch = global 256 / world_size
(``:22``).  Two load-bearing quirks preserved here (SURVEY.md C6):

  * ``set_epoch`` is never called, so the shard permutation is IDENTICAL every
    epoch (seed-0 shuffle, once).  ``reshuffle_each_epoch=True`` opts out.
  * the test set is NOT sharded — evaluation covers the full 10k set.

Like torch's DistributedSampler, the index list is padded (by wrapping) to a
multiple of world_size and dealt round-robin: rank r takes indices
``perm[r::world]``.
"""

from __future__ import annotations

import numpy as np


def _wrap_pad(perm: np.ndarray, total: int) -> np.ndarray:
    """Pad ``perm`` to ``total`` by wrapping, exactly as torch's
    DistributedSampler does — including the degenerate case where the
    padding EXCEEDS the dataset (total > 2n, e.g. a tiny split resharded
    onto a large world): torch tiles the whole index list
    (``(indices * ceil(pad/len))[:pad]``), and so must we.  The previous
    single-concatenate wrap silently produced a SHORT list there, which
    would desynchronize rank streams after a world resize — the elastic
    resume planner depends on this order being a pure function of
    (seed, epoch), never of world size."""
    if total <= perm.shape[0]:
        return perm[:total]
    reps = -(-total // perm.shape[0])  # ceil
    return np.concatenate([perm] * reps)[:total]


def canonical_epoch_order(n: int, *, seed: int = 0, shuffle: bool = True,
                          epoch: int = 0, reshuffle_each_epoch: bool = False,
                          pad_to: int | None = None) -> np.ndarray:
    """The world-INVARIANT canonical example order for ``epoch``.

    This is the permutation every ``ShardedSampler`` deals from: rank r of
    world w takes positions ``r::w`` of this order (after wrap-padding), so
    the column-major flatten of ``global_epoch_indices(n, w)`` equals a
    prefix of this array FOR EVERY w (pinned by tests/test_elastic.py).
    That invariance is the seam elastic resume rides: global batch b covers
    canonical positions [b*B, (b+1)*B) regardless of world size, so a
    checkpoint taken at world=N can be resumed at world=M without
    re-deriving which examples were consumed.
    """
    if shuffle:
        s = seed + (epoch if reshuffle_each_epoch else 0)
        perm = np.random.default_rng(s).permutation(n)
    else:
        perm = np.arange(n)
    if pad_to is not None:
        perm = _wrap_pad(perm, pad_to)
    return perm


class ShardedSampler:
    """Per-rank epoch index streams over a dataset of ``n`` examples."""

    def __init__(self, n: int, world: int, rank: int, *, seed: int = 0,
                 shuffle: bool = True, reshuffle_each_epoch: bool = False):
        if not (0 <= rank < world):
            raise ValueError(f"rank {rank} out of range for world {world}")
        self.n = n
        self.world = world
        self.rank = rank
        self.seed = seed
        self.shuffle = shuffle
        self.reshuffle_each_epoch = reshuffle_each_epoch
        self.num_samples = -(-n // world)  # ceil
        self.total = self.num_samples * world

    def epoch_indices(self, epoch: int = 0) -> np.ndarray:
        """Indices this rank processes in ``epoch`` (len == num_samples)."""
        # Reference never reshuffles (no set_epoch); epoch enters the
        # seed only when explicitly requested.  The wrap-pad (torch
        # semantics, tiled for world > 2n) happens on the CANONICAL order,
        # so rank streams for every world size deal from one permutation.
        perm = canonical_epoch_order(
            self.n, seed=self.seed, shuffle=self.shuffle, epoch=epoch,
            reshuffle_each_epoch=self.reshuffle_each_epoch,
            pad_to=self.total)
        return perm[self.rank:: self.world]


def global_epoch_indices(n: int, world: int, *, seed: int = 0,
                         shuffle: bool = True, epoch: int = 0,
                         reshuffle_each_epoch: bool = False) -> np.ndarray:
    """[world, num_samples] index matrix — the SPMD view of the sampler.

    Row r equals ``ShardedSampler(n, world, r).epoch_indices(epoch)``; a host
    that feeds all local devices slices its rows from this.  Column b of the
    matrix is global batch b's composition, matching the reference's
    per-worker loaders exactly.
    """
    samplers = [ShardedSampler(n, world, r, seed=seed, shuffle=shuffle,
                               reshuffle_each_epoch=reshuffle_each_epoch)
                for r in range(world)]
    return np.stack([s.epoch_indices(epoch) for s in samplers])
