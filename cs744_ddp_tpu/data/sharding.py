"""Dataset sharding — the DistributedSampler equivalent.

Reference semantics (``/root/reference/src/Part 2a/main.py:38-44``):
``DistributedSampler(training_set, num_replicas=size, rank=rank)`` with
``shuffle=False`` on the loader; per-worker batch = global 256 / world_size
(``:22``).  Two load-bearing quirks preserved here (SURVEY.md C6):

  * ``set_epoch`` is never called, so the shard permutation is IDENTICAL every
    epoch (seed-0 shuffle, once).  ``reshuffle_each_epoch=True`` opts out.
  * the test set is NOT sharded — evaluation covers the full 10k set.

Like torch's DistributedSampler, the index list is padded (by wrapping) to a
multiple of world_size and dealt round-robin: rank r takes indices
``perm[r::world]``.
"""

from __future__ import annotations

import numpy as np


class ShardedSampler:
    """Per-rank epoch index streams over a dataset of ``n`` examples."""

    def __init__(self, n: int, world: int, rank: int, *, seed: int = 0,
                 shuffle: bool = True, reshuffle_each_epoch: bool = False):
        if not (0 <= rank < world):
            raise ValueError(f"rank {rank} out of range for world {world}")
        self.n = n
        self.world = world
        self.rank = rank
        self.seed = seed
        self.shuffle = shuffle
        self.reshuffle_each_epoch = reshuffle_each_epoch
        self.num_samples = -(-n // world)  # ceil
        self.total = self.num_samples * world

    def epoch_indices(self, epoch: int = 0) -> np.ndarray:
        """Indices this rank processes in ``epoch`` (len == num_samples)."""
        if self.shuffle:
            # Reference never reshuffles (no set_epoch); epoch enters the
            # seed only when explicitly requested.
            s = self.seed + (epoch if self.reshuffle_each_epoch else 0)
            perm = np.random.default_rng(s).permutation(self.n)
        else:
            perm = np.arange(self.n)
        if self.total > self.n:  # pad by wrapping, as torch does
            perm = np.concatenate([perm, perm[: self.total - self.n]])
        return perm[self.rank:: self.world]


def global_epoch_indices(n: int, world: int, *, seed: int = 0,
                         shuffle: bool = True, epoch: int = 0,
                         reshuffle_each_epoch: bool = False) -> np.ndarray:
    """[world, num_samples] index matrix — the SPMD view of the sampler.

    Row r equals ``ShardedSampler(n, world, r).epoch_indices(epoch)``; a host
    that feeds all local devices slices its rows from this.  Column b of the
    matrix is global batch b's composition, matching the reference's
    per-worker loaders exactly.
    """
    samplers = [ShardedSampler(n, world, r, seed=seed, shuffle=shuffle,
                               reshuffle_each_epoch=reshuffle_each_epoch)
                for r in range(world)]
    return np.stack([s.epoch_indices(epoch) for s in samplers])
