"""ctypes bindings for the native host-side loader (native/fastloader.cpp).

The reference's host data path is native library code (torchvision C
transforms + DataLoader worker processes, /root/reference/src/Part 1/
main.py:96-101).  This is its equivalent here: threaded batch gather and
augmentation in C++.  The library auto-builds on first use (g++, ~2s) and
every entry point has a NumPy fallback, so the framework never hard-depends
on the toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import warnings
from typing import Optional

import numpy as np

from .cifar10 import MEAN, STD

_EXPECTED_VERSION = 3

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "build", "libfastloader.so")

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False
# Why the native path is off, when it is ("" while unattempted/loaded).
# Surfaced in the telemetry manifest (obs/) so a silently-degraded run —
# NumPy fallback where the C++ pipeline was expected — is diagnosable from
# the run artifact; also warned ONCE at load time rather than swallowed.
_load_error: Optional[str] = None


def _nthreads() -> int:
    return max(1, os.cpu_count() or 1)


def load_library(build: bool = True) -> Optional[ctypes.CDLL]:
    """Load (building if needed) libfastloader.so; None when unavailable."""
    global _lib, _load_attempted, _load_error
    if _lib is not None or _load_attempted:
        return _lib
    _load_attempted = True
    try:
        if build:
            # `make` is a cheap no-op when the .so is current, and rebuilds
            # a STALE one (the version assert below would otherwise fail
            # after every source change and silently drop to the fallback).
            # A FAILED build (no toolchain on this host) is non-fatal: a
            # prebuilt current .so must still load.
            try:
                subprocess.run(["make", "-C", _NATIVE_DIR, "-s"], check=True,
                               capture_output=True, timeout=120)
            except Exception:
                pass
        lib = ctypes.CDLL(_SO_PATH)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        f32p = ctypes.POINTER(ctypes.c_float)
        lib.fl_gather_u8.argtypes = [u8p, i64p, ctypes.c_int, u8p,
                                     ctypes.c_int]
        lib.fl_augment_f32.argtypes = [u8p, ctypes.c_int, i32p, u8p, f32p,
                                       f32p, f32p, ctypes.c_int]
        lib.fl_augment_u8.argtypes = [u8p, ctypes.c_int, i32p, u8p, u8p,
                                      ctypes.c_int]
        lib.fl_gather_augment_u8.argtypes = [u8p, i64p, ctypes.c_int, i32p,
                                             u8p, u8p, ctypes.c_int]
        lib.fl_normalize_f32.argtypes = [u8p, ctypes.c_int, f32p, f32p, f32p,
                                         ctypes.c_int]
        lib.fl_version.restype = ctypes.c_int
        version = lib.fl_version()
        if version != _EXPECTED_VERSION:
            raise RuntimeError(
                f"libfastloader ABI version {version} != expected "
                f"{_EXPECTED_VERSION} (stale build?)")
        _lib = lib
    except Exception as e:
        _lib = None
        _load_error = f"{type(e).__name__}: {e}"
        warnings.warn(
            f"native host loader unavailable ({_load_error}); falling back "
            f"to the NumPy data path — expect slower host-side "
            f"gather/augment", RuntimeWarning, stacklevel=2)
    return _lib


def available() -> bool:
    """True when the native library loaded (attempting the load if needed);
    when False, ``load_error()`` says why."""
    return load_library() is not None


def load_error() -> Optional[str]:
    """Why the native library is unavailable (None while it is loaded or
    the load has not been attempted yet)."""
    return _load_error


def _ptr(a: np.ndarray, ct):
    return a.ctypes.data_as(ctypes.POINTER(ct))


_MEAN32 = np.ascontiguousarray(MEAN, np.float32)
_STD32 = np.ascontiguousarray(STD, np.float32)


def gather(dataset: np.ndarray, indices: np.ndarray,
           out: Optional[np.ndarray] = None) -> np.ndarray:
    """out[i] = dataset[indices[i]] for a [N,32,32,3] uint8 dataset.

    ``out`` (uint8 [n,32,32,3], contiguous) receives the rows in place
    (arena staging, same contract as ``augment_u8``)."""
    lib = load_library()
    if lib is None:
        if out is None:
            return dataset[indices]
        _check_out(out, len(indices))[...] = dataset[indices]
        return out
    dataset = np.ascontiguousarray(dataset)
    idx = np.ascontiguousarray(indices, np.int64)
    out = np.empty((len(idx), 32, 32, 3), np.uint8) if out is None \
        else _check_out(out, len(idx))
    lib.fl_gather_u8(_ptr(dataset, ctypes.c_uint8), _ptr(idx, ctypes.c_int64),
                     len(idx), _ptr(out, ctypes.c_uint8), _nthreads())
    return out


def augment(images: np.ndarray, offsets: np.ndarray, flips: np.ndarray
            ) -> np.ndarray:
    """Pad-4 crop + flip + normalize; images [N,32,32,3] u8 -> f32.

    offsets: [N,2] int32 in [0,8]; flips: [N] bool/uint8.
    """
    n = len(images)
    images = np.ascontiguousarray(images)
    offsets = np.ascontiguousarray(offsets, np.int32)
    flips = np.ascontiguousarray(flips, np.uint8)
    lib = load_library()
    out = np.empty((n, 32, 32, 3), np.float32)
    if lib is None:
        padded = np.pad(images, ((0, 0), (4, 4), (4, 4), (0, 0)))
        for i in range(n):
            oy, ox = offsets[i]
            crop = padded[i, oy:oy + 32, ox:ox + 32]
            if flips[i]:
                crop = crop[:, ::-1]
            out[i] = (crop.astype(np.float32) / 255.0 - MEAN) / STD
        return out
    lib.fl_augment_f32(_ptr(images, ctypes.c_uint8), n,
                       _ptr(offsets, ctypes.c_int32),
                       _ptr(flips, ctypes.c_uint8),
                       _ptr(_MEAN32, ctypes.c_float),
                       _ptr(_STD32, ctypes.c_float),
                       _ptr(out, ctypes.c_float), _nthreads())
    return out


def _check_out(out: np.ndarray, n: int) -> np.ndarray:
    """Validate a caller-provided staging destination: contiguous uint8
    [n,32,32,3].  Never copies — the point of the out-parameter is writing
    straight into a reusable arena slot."""
    if out.shape != (n, 32, 32, 3) or out.dtype != np.uint8:
        raise ValueError(f"out must be uint8 [{n},32,32,3], got "
                         f"{out.dtype} {out.shape}")
    if not out.flags.c_contiguous:
        raise ValueError("out must be C-contiguous (an arena row, not a "
                         "strided view)")
    return out


def augment_u8(images: np.ndarray, offsets: np.ndarray, flips: np.ndarray,
               out: Optional[np.ndarray] = None) -> np.ndarray:
    """Pad-4 crop + flip, uint8 -> uint8 (zero padding, no normalize).

    The transfer-compact staging variant: the stochastic transform runs
    host-side; normalization is an affine per-channel map the device step
    fuses for free, so shipping uint8 carries 4x fewer bytes than the f32
    ``augment`` output over the host->device link.

    ``out`` (uint8 [n,32,32,3], contiguous) receives the result in place —
    the chunked staging path passes arena rows here so no per-window stack
    copy exists."""
    n = len(images)
    images = np.ascontiguousarray(images)
    offsets = np.ascontiguousarray(offsets, np.int32)
    flips = np.ascontiguousarray(flips, np.uint8)
    lib = load_library()
    out = np.empty((n, 32, 32, 3), np.uint8) if out is None \
        else _check_out(out, n)
    if lib is None:
        padded = np.pad(images, ((0, 0), (4, 4), (4, 4), (0, 0)))
        for i in range(n):
            oy, ox = offsets[i]
            crop = padded[i, oy:oy + 32, ox:ox + 32]
            out[i] = crop[:, ::-1] if flips[i] else crop
        return out
    lib.fl_augment_u8(_ptr(images, ctypes.c_uint8), n,
                      _ptr(offsets, ctypes.c_int32),
                      _ptr(flips, ctypes.c_uint8),
                      _ptr(out, ctypes.c_uint8), _nthreads())
    return out


def gather_augment_u8(dataset: np.ndarray, indices: np.ndarray,
                      offsets: np.ndarray, flips: np.ndarray,
                      out: Optional[np.ndarray] = None) -> np.ndarray:
    """Fused gather + pad-4 crop + flip from the resident [N,32,32,3] u8
    dataset straight into ``out`` (one host copy instead of the previous
    gather -> augment -> np.stack three).  Same crop/flip semantics as
    ``augment_u8(gather(dataset, indices), ...)`` — pinned elementwise by
    tests/test_native.py."""
    n = len(indices)
    dataset = np.ascontiguousarray(dataset)
    idx = np.ascontiguousarray(indices, np.int64)
    offsets = np.ascontiguousarray(offsets, np.int32)
    flips = np.ascontiguousarray(flips, np.uint8)
    lib = load_library()
    out = np.empty((n, 32, 32, 3), np.uint8) if out is None \
        else _check_out(out, n)
    if lib is None:
        return augment_u8(dataset[idx], offsets, flips, out=out)
    lib.fl_gather_augment_u8(_ptr(dataset, ctypes.c_uint8),
                             _ptr(idx, ctypes.c_int64), n,
                             _ptr(offsets, ctypes.c_int32),
                             _ptr(flips, ctypes.c_uint8),
                             _ptr(out, ctypes.c_uint8), _nthreads())
    return out


class StagingArena:
    """Reusable chunk-aligned uint8 staging buffers for the chunked
    windowed host-augment path (train/loop.py).

    ``nslots`` preallocated [chunk_batches, batch, 32, 32, 3] buffers are
    handed out round-robin by ``acquire()``; ``retire(slot, handle)``
    records the device transfer sourced from a slot (any object with
    ``block_until_ready``, i.e. a jax.Array), and the next ``acquire()`` of
    that slot blocks until the recorded transfer completed before letting
    the producer overwrite the host memory.

    CAVEAT — the fence covers TRANSFER completion only.  On backends with
    a real host->device link (TPU/GPU) the put copies into separate device
    memory, so a completed transfer makes the host row safely rewritable
    and correctness is independent of the slot count (it only sets how far
    the producer runs ahead without stalling).  jax's CPU client instead
    ALIASES suitably-aligned committed numpy buffers (verified empirically
    — mutating the source after ``device_put`` + ``block_until_ready``
    changes the jax array), so there ``retire`` CANNOT make reuse safe and
    the caller must not stage zero-copy at all; Trainer probes the actual
    behavior per backend+sharding (``_probe_put_aliases_host``) and puts
    private copies of the rows where aliasing is detected.

    The aliasing decision is PER BUFFER, not per backend: the CPU client
    zero-copies only 64-byte-aligned arrays, and a long-lived process's
    heap hands ``np.empty`` blocks of this size back at whatever alignment
    the free lists hold (measured in-suite: the same arena with slots
    [no, no, no, YES, YES, no]).  Every slot is therefore allocated at a
    FORCED 64-byte alignment so all slots behave identically and a probe
    of any one of them speaks for the arena; Trainer still probes every
    slot (``StagingArena`` exposes them via ``buffer``) as defense in
    depth."""

    _ALIGN = 64  # jax CPU client's zero-copy alignment threshold

    @classmethod
    def _aligned_empty(cls, shape) -> np.ndarray:
        n = int(np.prod(shape))
        raw = np.empty(n + cls._ALIGN, np.uint8)
        off = (-raw.ctypes.data) % cls._ALIGN
        return raw[off:off + n].reshape(shape)

    def __init__(self, nslots: int, chunk_batches: int, batch: int):
        if nslots < 2:
            raise ValueError(f"need >= 2 slots to overlap, got {nslots}")
        self.chunk_batches = chunk_batches
        self._bufs = [
            self._aligned_empty((chunk_batches, batch, 32, 32, 3))
            for _ in range(nslots)]
        self._pending = [None] * nslots
        self._next = 0

    @property
    def nslots(self) -> int:
        return len(self._bufs)

    def buffer(self, slot: int) -> np.ndarray:
        """Direct access to a slot's backing buffer (aliasing probes,
        tests); training code goes through ``acquire``."""
        return self._bufs[slot]

    def acquire(self, *, fence_timeout_s=None, on_timeout=None):
        """-> (slot_id, buffer): the next writable slot, after fencing any
        in-flight transfer that still reads this slot's memory.

        ``fence_timeout_s``/``on_timeout`` (ft supervision, train/loop.py)
        arm a DETECTION-ONLY watchdog around the fence wait:
        ``block_until_ready`` is a native call that cannot be interrupted
        from Python, so a wedged transfer can only be reported (the
        callback fires, telemetry counts it) — the consumer-side stall
        deadline in the prefetch loop is what converts the report into
        recovery."""
        i = self._next
        self._next = (i + 1) % len(self._bufs)
        dep = self._pending[i]
        if dep is not None:
            if fence_timeout_s is not None:
                from ..ft.supervisor import Watchdog
                with Watchdog(fence_timeout_s, on_timeout=on_timeout):
                    dep.block_until_ready()
            else:
                dep.block_until_ready()
            self._pending[i] = None
        return i, self._bufs[i]

    def retire(self, slot: int, handle) -> None:
        """Record the device array whose host->device transfer reads
        ``slot``; the slot stays unwritable until it completes."""
        self._pending[slot] = handle


def normalize(images: np.ndarray) -> np.ndarray:
    """ToTensor+Normalize (test transform) on host."""
    images = np.ascontiguousarray(images)
    lib = load_library()
    if lib is None:
        return (images.astype(np.float32) / 255.0 - MEAN) / STD
    out = np.empty(images.shape, np.float32)
    lib.fl_normalize_f32(_ptr(images, ctypes.c_uint8), len(images),
                         _ptr(_MEAN32, ctypes.c_float),
                         _ptr(_STD32, ctypes.c_float),
                         _ptr(out, ctypes.c_float), _nthreads())
    return out
