"""ctypes bindings for the native host-side loader (native/fastloader.cpp).

The reference's host data path is native library code (torchvision C
transforms + DataLoader worker processes, /root/reference/src/Part 1/
main.py:96-101).  This is its equivalent here: threaded batch gather and
augmentation in C++.  The library auto-builds on first use (g++, ~2s) and
every entry point has a NumPy fallback, so the framework never hard-depends
on the toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import warnings
from typing import Optional

import numpy as np

from .cifar10 import MEAN, STD

_EXPECTED_VERSION = 2

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "build", "libfastloader.so")

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False
# Why the native path is off, when it is ("" while unattempted/loaded).
# Surfaced in the telemetry manifest (obs/) so a silently-degraded run —
# NumPy fallback where the C++ pipeline was expected — is diagnosable from
# the run artifact; also warned ONCE at load time rather than swallowed.
_load_error: Optional[str] = None


def _nthreads() -> int:
    return max(1, os.cpu_count() or 1)


def load_library(build: bool = True) -> Optional[ctypes.CDLL]:
    """Load (building if needed) libfastloader.so; None when unavailable."""
    global _lib, _load_attempted, _load_error
    if _lib is not None or _load_attempted:
        return _lib
    _load_attempted = True
    try:
        if build:
            # `make` is a cheap no-op when the .so is current, and rebuilds
            # a STALE one (the version assert below would otherwise fail
            # after every source change and silently drop to the fallback).
            # A FAILED build (no toolchain on this host) is non-fatal: a
            # prebuilt current .so must still load.
            try:
                subprocess.run(["make", "-C", _NATIVE_DIR, "-s"], check=True,
                               capture_output=True, timeout=120)
            except Exception:
                pass
        lib = ctypes.CDLL(_SO_PATH)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        f32p = ctypes.POINTER(ctypes.c_float)
        lib.fl_gather_u8.argtypes = [u8p, i64p, ctypes.c_int, u8p,
                                     ctypes.c_int]
        lib.fl_augment_f32.argtypes = [u8p, ctypes.c_int, i32p, u8p, f32p,
                                       f32p, f32p, ctypes.c_int]
        lib.fl_augment_u8.argtypes = [u8p, ctypes.c_int, i32p, u8p, u8p,
                                      ctypes.c_int]
        lib.fl_normalize_f32.argtypes = [u8p, ctypes.c_int, f32p, f32p, f32p,
                                         ctypes.c_int]
        lib.fl_version.restype = ctypes.c_int
        version = lib.fl_version()
        if version != _EXPECTED_VERSION:
            raise RuntimeError(
                f"libfastloader ABI version {version} != expected "
                f"{_EXPECTED_VERSION} (stale build?)")
        _lib = lib
    except Exception as e:
        _lib = None
        _load_error = f"{type(e).__name__}: {e}"
        warnings.warn(
            f"native host loader unavailable ({_load_error}); falling back "
            f"to the NumPy data path — expect slower host-side "
            f"gather/augment", RuntimeWarning, stacklevel=2)
    return _lib


def available() -> bool:
    """True when the native library loaded (attempting the load if needed);
    when False, ``load_error()`` says why."""
    return load_library() is not None


def load_error() -> Optional[str]:
    """Why the native library is unavailable (None while it is loaded or
    the load has not been attempted yet)."""
    return _load_error


def _ptr(a: np.ndarray, ct):
    return a.ctypes.data_as(ctypes.POINTER(ct))


_MEAN32 = np.ascontiguousarray(MEAN, np.float32)
_STD32 = np.ascontiguousarray(STD, np.float32)


def gather(dataset: np.ndarray, indices: np.ndarray) -> np.ndarray:
    """out[i] = dataset[indices[i]] for a [N,32,32,3] uint8 dataset."""
    lib = load_library()
    if lib is None:
        return dataset[indices]
    dataset = np.ascontiguousarray(dataset)
    idx = np.ascontiguousarray(indices, np.int64)
    out = np.empty((len(idx), 32, 32, 3), np.uint8)
    lib.fl_gather_u8(_ptr(dataset, ctypes.c_uint8), _ptr(idx, ctypes.c_int64),
                     len(idx), _ptr(out, ctypes.c_uint8), _nthreads())
    return out


def augment(images: np.ndarray, offsets: np.ndarray, flips: np.ndarray
            ) -> np.ndarray:
    """Pad-4 crop + flip + normalize; images [N,32,32,3] u8 -> f32.

    offsets: [N,2] int32 in [0,8]; flips: [N] bool/uint8.
    """
    n = len(images)
    images = np.ascontiguousarray(images)
    offsets = np.ascontiguousarray(offsets, np.int32)
    flips = np.ascontiguousarray(flips, np.uint8)
    lib = load_library()
    out = np.empty((n, 32, 32, 3), np.float32)
    if lib is None:
        padded = np.pad(images, ((0, 0), (4, 4), (4, 4), (0, 0)))
        for i in range(n):
            oy, ox = offsets[i]
            crop = padded[i, oy:oy + 32, ox:ox + 32]
            if flips[i]:
                crop = crop[:, ::-1]
            out[i] = (crop.astype(np.float32) / 255.0 - MEAN) / STD
        return out
    lib.fl_augment_f32(_ptr(images, ctypes.c_uint8), n,
                       _ptr(offsets, ctypes.c_int32),
                       _ptr(flips, ctypes.c_uint8),
                       _ptr(_MEAN32, ctypes.c_float),
                       _ptr(_STD32, ctypes.c_float),
                       _ptr(out, ctypes.c_float), _nthreads())
    return out


def augment_u8(images: np.ndarray, offsets: np.ndarray, flips: np.ndarray
               ) -> np.ndarray:
    """Pad-4 crop + flip, uint8 -> uint8 (zero padding, no normalize).

    The transfer-compact staging variant: the stochastic transform runs
    host-side; normalization is an affine per-channel map the device step
    fuses for free, so shipping uint8 carries 4x fewer bytes than the f32
    ``augment`` output over the host->device link."""
    n = len(images)
    images = np.ascontiguousarray(images)
    offsets = np.ascontiguousarray(offsets, np.int32)
    flips = np.ascontiguousarray(flips, np.uint8)
    lib = load_library()
    out = np.empty((n, 32, 32, 3), np.uint8)
    if lib is None:
        padded = np.pad(images, ((0, 0), (4, 4), (4, 4), (0, 0)))
        for i in range(n):
            oy, ox = offsets[i]
            crop = padded[i, oy:oy + 32, ox:ox + 32]
            out[i] = crop[:, ::-1] if flips[i] else crop
        return out
    lib.fl_augment_u8(_ptr(images, ctypes.c_uint8), n,
                      _ptr(offsets, ctypes.c_int32),
                      _ptr(flips, ctypes.c_uint8),
                      _ptr(out, ctypes.c_uint8), _nthreads())
    return out


def normalize(images: np.ndarray) -> np.ndarray:
    """ToTensor+Normalize (test transform) on host."""
    images = np.ascontiguousarray(images)
    lib = load_library()
    if lib is None:
        return (images.astype(np.float32) / 255.0 - MEAN) / STD
    out = np.empty(images.shape, np.float32)
    lib.fl_normalize_f32(_ptr(images, ctypes.c_uint8), len(images),
                         _ptr(_MEAN32, ctypes.c_float),
                         _ptr(_STD32, ctypes.c_float),
                         _ptr(out, ctypes.c_float), _nthreads())
    return out
