"""cs744_ddp_tpu — a TPU-native (JAX/XLA) data-parallel training framework.

Re-implements, TPU-first, the capability set of the reference
harsh-rawat/CS744-Distributed-Data-Parallel (see SURVEY.md): synchronous
data-parallel training of VGG/ResNet CNNs on CIFAR-10 with three
interchangeable gradient-synchronization strategies

  * ``gather``    — root-mediated gather -> mean -> broadcast
                    (reference: src/Part 2a/main.py:117-127)
  * ``allreduce`` — one all-reduce per parameter leaf
                    (reference: src/Part 2b/main.py:116-119)
  * ``ddp``       — bucketed, fused all-reduce, the DistributedDataParallel
                    equivalent (reference: src/Part 3/main.py:61)

expressed as XLA collectives over a ``jax.sharding.Mesh`` inside
``shard_map``-compiled SPMD programs, instead of eager Gloo collectives.
"""

__version__ = "0.1.0"
