"""Elastic resume planning: map a checkpoint taken at world=N onto world=M.

The whole layer rides one invariant of ``data/sharding.py``: the canonical
epoch order is a pure function of (seed, epoch) — rank r of world w deals
positions ``r::w`` of the SAME permutation for every w (torch
DistributedSampler semantics, wrap-pad tiled).  So "which examples has the
run consumed" is world-independent, and a resume plan only has to translate
the step counter between batch geometries.

Two declared protocols:

* ``strong`` — the global batch is pinned (reference: 256) and re-bucketed
  across the new world.  Under the elastic step program
  (``step_elastic.py``) the math is bitwise world-invariant, so the step
  counter carries over unchanged: ``start_step = step``, zero replay, and
  the loss trajectory at world 1→2→4 is identical (CI-pinned).
* ``weak``   — the PER-CHIP batch is pinned, so the global batch scales
  with the world.  Progress is measured in examples; the new step counter
  is ``examples_done // new_global_batch`` (floor), which re-processes up
  to one new-batch of examples rather than skipping any.  Deterministic,
  but not replay-exact — the replayed-example count is reported in the
  plan, not hidden.

``world_of`` is the forward/backward-compat seam: checkpoints from before
round 6 carry no world metadata and restore as ``world=1`` with a one-time
warning instead of a KeyError.
"""

from __future__ import annotations

import warnings
import zlib
from typing import NamedTuple, Optional, Tuple

import numpy as np

from ..data.sharding import canonical_epoch_order

PROTOCOLS = ("weak", "strong")

# How many leading indices of each rank stream the data-order key digests.
_KEY_PREFIX = 64

_warned_missing_world = False


class ElasticConfig(NamedTuple):
    """Elastic-mode knobs carried by the Trainer.

    protocol    : "strong" (pinned global batch, bitwise world-invariant
                  math) or "weak" (pinned per-chip batch).
    microshards : S — the fixed decomposition of every strong-protocol
                  global batch.  Must be a power of two and divide the
                  global batch; every world size M with M | S can run the
                  SAME per-microshard math (rank r scans S/M microshards),
                  which is what makes the trajectory world-invariant.
    """

    protocol: str = "strong"
    microshards: int = 4


class ResumePlan(NamedTuple):
    """The output of ``plan_resume`` — everything the trainer needs to
    continue a run at a different world size."""

    protocol: str
    old_world: int
    new_world: int
    old_global_batch: int
    new_global_batch: int
    start_epoch: int
    start_step: int
    examples_replayed: int  # weak protocol floor-rounding; 0 under strong
    steps_lost: int         # completed old steps whose work is re-executed


def flat_meta(meta: Optional[dict]) -> dict:
    """One flat view over both checkpoint metadata shapes: mid-epoch
    sidecars nest the topology/data-order keys under ``data_order``
    (historical shape, kept for compat), epoch sidecars keep them
    top-level.  Returns {} for None."""
    if not meta:
        return {}
    flat = {k: v for k, v in meta.items() if k != "data_order"}
    flat.update(meta.get("data_order") or {})
    return flat


def world_of(meta: Optional[dict]) -> int:
    """The world size recorded in checkpoint metadata — with the
    backward-compat default: pre-round-6 checkpoints carry no ``world``
    key and restore as world=1 (the reference's Part 1 case), warning
    once per process instead of raising KeyError."""
    global _warned_missing_world
    if meta and "world" in meta:
        return int(meta["world"])
    if not _warned_missing_world:
        _warned_missing_world = True
        warnings.warn(
            "checkpoint metadata carries no world size (pre-elastic "
            "format); assuming world=1 — re-save under round 6+ to "
            "record topology", stacklevel=2)
    return 1


def rank_data_keys(n: int, world: int, *, seed: int = 0, epoch: int = 0,
                   shuffle: bool = True,
                   reshuffle_each_epoch: bool = False) -> Tuple[int, ...]:
    """Per-rank data-order keys: a crc32 digest of the first
    ``_KEY_PREFIX`` indices each rank deals in ``epoch``.  Written into
    checkpoint metadata at save time and re-derived at resume time —
    a mismatch means the dataset/seed changed under the checkpoint, which
    would silently desynchronize the resumed stream."""
    num = -(-n // world) * world
    order = canonical_epoch_order(
        n, seed=seed, shuffle=shuffle, epoch=epoch,
        reshuffle_each_epoch=reshuffle_each_epoch, pad_to=num)
    return tuple(
        int(zlib.crc32(np.ascontiguousarray(
            order[r::world][:_KEY_PREFIX], dtype=np.int64).tobytes()))
        for r in range(world))


def validate_rank_keys(meta: dict, n: int) -> None:
    """Check the saved per-rank data-order keys against a fresh
    derivation; no-op when the metadata predates them (compat).  Accepts
    either metadata shape (flattens internally)."""
    flat = flat_meta(meta)
    saved = flat.get("rank_keys")
    if not saved:
        return
    fresh = rank_data_keys(
        n, world_of(flat), seed=int(flat.get("seed", 0)),
        epoch=int(flat.get("epoch", 0)),
        shuffle=bool(flat.get("shuffle", True)),
        reshuffle_each_epoch=bool(flat.get("reshuffle_each_epoch", False)))
    if tuple(saved) != fresh:
        raise ValueError(
            "checkpoint data-order keys do not match this dataset/seed — "
            f"saved {tuple(saved)}, derived {fresh}; resuming would "
            "desynchronize the example stream")


def plan_resume(meta: Optional[dict], new_world: int, *,
                protocol: Optional[str] = None,
                microshards: Optional[int] = None,
                default_global_batch: Optional[int] = None) -> ResumePlan:
    """Translate checkpoint progress at ``world_of(meta)`` into a start
    position at ``new_world`` under the declared protocol."""
    meta = meta or {}
    old_world = world_of(meta)
    proto = protocol or meta.get("protocol") or "strong"
    if proto not in PROTOCOLS:
        raise ValueError(f"unknown elastic protocol {proto!r}; "
                         f"expected one of {PROTOCOLS}")
    if new_world < 1:
        raise ValueError(f"new world must be >= 1, got {new_world}")
    old_gb = meta.get("global_batch", default_global_batch)
    if old_gb is None:
        raise ValueError("checkpoint metadata carries no global_batch and "
                         "no default was provided")
    old_gb = int(old_gb)
    epoch = int(meta.get("epoch", 0))
    step = int(meta.get("step", 0))

    if proto == "strong":
        if old_gb % new_world:
            raise ValueError(
                f"strong scaling: global batch {old_gb} not divisible by "
                f"new world {new_world}")
        if microshards is not None:
            if microshards % new_world:
                raise ValueError(
                    f"strong scaling: microshards {microshards} not "
                    f"divisible by new world {new_world}")
            if old_gb % microshards:
                raise ValueError(
                    f"strong scaling: global batch {old_gb} not divisible "
                    f"by microshards {microshards}")
        # Global batch b covers canonical positions [b*B, (b+1)*B) at
        # EVERY world size, so the step counter is world-invariant.
        return ResumePlan(proto, old_world, new_world, old_gb, old_gb,
                          epoch, step, 0, 0)

    # weak scaling: pinned per-chip batch, example-measured progress.
    if old_gb % old_world:
        raise ValueError(f"weak scaling: saved global batch {old_gb} not "
                         f"divisible by saved world {old_world}")
    per_chip = old_gb // old_world
    new_gb = per_chip * new_world
    examples_done = step * old_gb
    start_step = examples_done // new_gb
    replayed = examples_done - start_step * new_gb
    steps_lost = step - (start_step * new_gb) // old_gb
    return ResumePlan(proto, old_world, new_world, old_gb, new_gb,
                      epoch, start_step, replayed, steps_lost)


def plan_shrink(world: int, global_batch: int, *,
                microshards: Optional[int] = None) -> int:
    """The shrink rung of the degradation ladder: the LARGEST world
    w <= world-1 the batch geometry admits (global batch divisible, and
    under strong scaling w | microshards so the elastic program exists).
    Always reaches 1 — the synchronous single-rank fallback divides
    everything."""
    if world < 2:
        raise ValueError(f"cannot shrink below world 1 (world={world})")
    for w in range(world - 1, 0, -1):
        if global_batch % w:
            continue
        if microshards is not None and microshards % w:
            continue
        return w
    return 1
