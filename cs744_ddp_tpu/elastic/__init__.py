"""Elastic training: checkpoint-based world-resize resume (round 6).

A run interrupted at world=N resumes at world=M with re-sharded data order
and pinned math.  Layers:

* ``protocol``     — resume planning (weak/strong scaling), shrink
                     planning, per-rank data-order keys, and the
                     backward-compat ``world_of`` default;
* ``step_elastic`` — the strong-scaling microshard window whose update is
                     bitwise world-invariant (CI-pinned at world 1→2→4);
* ``coordinator``  — membership + the retry → shrink → single-rank
                     degradation ladder over rank-level chaos
                     (``ft/chaos.py``: rank_death, slow_rank,
                     coordinator_loss);
* ``straggler``    — EWMA-vs-peers step-time outlier detection over the
                     per-rank gauges the trainer emits.
"""

from .coordinator import ElasticCoordinator                     # noqa: F401
from .protocol import (ElasticConfig, PROTOCOLS, ResumePlan,    # noqa: F401
                       flat_meta, plan_resume, plan_shrink,
                       rank_data_keys, validate_rank_keys, world_of)
from .step_elastic import (make_elastic_train_window,           # noqa: F401
                           tree_combine_mean)
from .straggler import StragglerDetector                        # noqa: F401

__all__ = [
    "ElasticConfig", "ElasticCoordinator", "PROTOCOLS", "ResumePlan",
    "StragglerDetector", "flat_meta", "make_elastic_train_window",
    "plan_resume", "plan_shrink", "rank_data_keys", "tree_combine_mean",
    "validate_rank_keys", "world_of",
]
