"""Straggler detection over per-rank step-time gauges.

One EWMA of step time per rank; a rank is flagged when its smoothed time
exceeds ``threshold`` x the median of the OTHER ranks' EWMAs (median, not
mean: a single extreme straggler must not drag the baseline up to meet
itself).  Detection-only — the coordinator decides what to do with a flag;
on the CPU virtual mesh (one process drives all "ranks" inside one SPMD
program) the per-rank times are the shared window wall time plus any
chaos-attributed stall, so the detector is exercised honestly by the
``slow_rank`` site: the injected stall is attributed to exactly one rank's
gauge and must be the only thing that trips the threshold.
"""

from __future__ import annotations

from typing import Dict, List, Optional


class StragglerDetector:
    """EWMA-vs-peers step-time outlier detection, one stream per rank."""

    def __init__(self, world: int, *, alpha: float = 0.3,
                 threshold: float = 2.0, min_steps: int = 3):
        if world < 1:
            raise ValueError(f"world must be >= 1, got {world}")
        if not (0.0 < alpha <= 1.0):
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        if threshold <= 1.0:
            raise ValueError(f"threshold must be > 1, got {threshold}")
        self.world = world
        self.alpha = alpha
        self.threshold = threshold
        self.min_steps = min_steps
        self._ewma: List[Optional[float]] = [None] * world
        self._count = [0] * world
        self.flag_counts: Dict[int, int] = {}

    def ewma(self, rank: int) -> Optional[float]:
        return self._ewma[rank]

    def observe(self, rank: int, step_time_s: float) -> None:
        if not (0 <= rank < self.world):
            raise ValueError(f"rank {rank} out of range for world "
                             f"{self.world}")
        prev = self._ewma[rank]
        self._ewma[rank] = step_time_s if prev is None else (
            self.alpha * step_time_s + (1.0 - self.alpha) * prev)
        self._count[rank] += 1

    @staticmethod
    def _median(xs: List[float]) -> float:
        xs = sorted(xs)
        n = len(xs)
        mid = n // 2
        return xs[mid] if n % 2 else 0.5 * (xs[mid - 1] + xs[mid])

    def check(self) -> List[int]:
        """Ranks currently straggling (world 1 has no peers to lag)."""
        flagged = []
        for r in range(self.world):
            if self._count[r] < self.min_steps:
                continue
            peers = [self._ewma[p] for p in range(self.world)
                     if p != r and self._ewma[p] is not None
                     and self._count[p] >= self.min_steps]
            if not peers:
                continue
            med = self._median(peers)
            if med > 0 and self._ewma[r] > self.threshold * med:
                flagged.append(r)
                self.flag_counts[r] = self.flag_counts.get(r, 0) + 1
        return flagged

    def summary(self) -> dict:
        """Telemetry/report-shaped view of the detector state."""
        return {
            "world": self.world,
            "threshold": self.threshold,
            "ewma_step_s": {str(r): self._ewma[r]
                            for r in range(self.world)
                            if self._ewma[r] is not None},
            "flag_counts": {str(r): c for r, c in
                            sorted(self.flag_counts.items())},
        }
