"""Elastic coordinator: cluster membership and the degradation ladder.

The coordinator owns what the SPMD trainer cannot: the decision of WHAT
WORLD SIZE to run at.  It drives trainers built by a ``make_trainer(world)``
factory; when a run comes back with ``trainer.rank_death`` set (the trainer
already wrote its emergency mid-epoch checkpoint before returning), the
coordinator walks the ladder:

  1. **retry**  — if the reported rank probes healthy
     (``parallel.mesh.probe_devices``) and ``trust_probe`` is set, the
     fault is treated as transient and the SAME world is retried (bounded
     by ``max_retries``).  Off by default: on the CPU virtual mesh every
     probe passes, so a chaos-injected death must be taken at face value
     or the shrink path would never run.
  2. **shrink** — rebuild at the LARGEST feasible world <= M-1
     (``protocol.plan_shrink``: global-batch divisibility, and under
     strong scaling microshard divisibility).  The resumed run restores
     the emergency checkpoint; under strong scaling its remaining
     trajectory is bitwise-equal to a fault-free run at the target world
     (pinned by tests/test_ft.py).
  3. **single-rank fallback** — repeated deaths keep shrinking until
     world=1, the synchronous degenerate case (``degraded`` is set).

Membership transitions happen UNDER THE SUPERVISOR LOCK — the chaos
``coordinator_loss`` site drops the in-memory membership mid-recovery and
the coordinator must re-derive it from checkpoint metadata alone
(``train.checkpoint.read_*_meta``), which is also why recovery stays
bitwise: nothing the coordinator decides from depends on state that only
lived in memory.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from ..ft import NULL_CHAOS
from ..parallel import mesh as meshlib
from ..train import checkpoint as ckptlib
from .protocol import flat_meta, plan_shrink, world_of


class ElasticCoordinator:
    """Membership + ladder driver over a ``make_trainer(world)`` factory."""

    # Membership transitions must happen under the supervisor lock; the
    # lint_graft lock-ownership rule enforces this statically via the
    # declaration (analysis/pylint_rules.py: class-level ``_lock_owned``).
    _lock_owned = ("world", "members", "generation", "degraded")

    def __init__(self, make_trainer: Callable, *, world: int,
                 global_batch: int, protocol: str = "strong",
                 microshards: Optional[int] = 4, chaos=NULL_CHAOS,
                 max_retries: int = 1, trust_probe: bool = False,
                 log: Callable[[str], None] = print):
        if world < 1:
            raise ValueError(f"world must be >= 1, got {world}")
        self._make_trainer = make_trainer
        self._lock = threading.Lock()
        self.log = log
        self.chaos = chaos
        self.global_batch = global_batch
        self.protocol = protocol
        self.microshards = microshards if protocol == "strong" else None
        self.max_retries = max_retries
        self.trust_probe = trust_probe
        self.retries_used = 0
        self.recoveries = 0
        self.events: List[dict] = []
        self.trainer = None
        # __init__ establishes the membership state (lint: construction
        # writes are exempt); every later transition is lock-guarded.
        self.world = world
        self.members = tuple(range(world))
        self.generation = 0
        self.degraded = world == 1

    # -- the run loop -------------------------------------------------------

    def run(self, epochs: int, checkpoint_dir: str):
        """Train to completion under the ladder; returns the final trainer
        (whose state/telemetry belong to the world that finished)."""
        while True:
            trainer = self._make_trainer(self.world)
            t0 = time.time()
            trainer.run(epochs, checkpoint_dir=checkpoint_dir)
            death = getattr(trainer, "rank_death", None)
            if death is None:
                self.trainer = trainer
                return trainer
            self._recover(trainer, death, checkpoint_dir,
                          run_time_s=time.time() - t0)

    # -- recovery -----------------------------------------------------------

    def _recover(self, trainer, death, checkpoint_dir: str, *,
                 run_time_s: float) -> None:
        rank, epoch, step = death
        self.recoveries += 1
        t0 = time.time()
        if self.chaos.enabled and self.chaos.fire_reached(
                "coordinator_loss", self.recoveries - 1):
            with self._lock:
                self.members = ()
            self.log("chaos: coordinator membership state lost; "
                     "re-deriving from checkpoint metadata")
            self._rederive_membership(checkpoint_dir)
        dead = set(meshlib.probe_devices(trainer.mesh))
        if self.trust_probe and rank not in dead and \
                self.retries_used < self.max_retries:
            # Rung 1: the rank probes healthy — transient fault, retry at
            # the same world.  The emergency checkpoint makes the retry a
            # plain resume; nothing about membership changes.
            self.retries_used += 1
            self.events.append({
                "kind": "retry", "rank": rank, "epoch": epoch,
                "step": step, "world": self.world,
                "recovery_s": time.time() - t0})
            self.log(f"elastic: rank {rank} probes healthy; retrying at "
                     f"world {self.world} "
                     f"({self.retries_used}/{self.max_retries})")
            return
        # Rung 2/3: the rank is gone — shrink to the largest feasible
        # world; repeated deaths walk this down to the world=1 synchronous
        # fallback.
        dead.add(rank)
        if self.world <= 1:
            raise RuntimeError(
                f"rank {rank} died at world 1 — no smaller world to "
                f"degrade to (epoch {epoch} step {step})")
        new_world = plan_shrink(self.world, self.global_batch,
                                microshards=self.microshards)
        with self._lock:
            old_world = self.world
            members = self.members or tuple(range(old_world))
            survivors = tuple(m for m in members if m not in dead)
            self.members = survivors[:new_world]
            self.world = new_world
            self.generation += 1
            self.degraded = new_world == 1
        self.events.append({
            "kind": "shrink", "rank": rank, "epoch": epoch, "step": step,
            "from_world": old_world, "to_world": new_world,
            "run_time_s": run_time_s, "recovery_s": time.time() - t0})
        self.log(f"elastic: rank {rank} died at epoch {epoch} step {step}; "
                 f"shrinking world {old_world} -> {new_world}"
                 + (" (single-rank fallback)" if new_world == 1 else ""))

    def _rederive_membership(self, checkpoint_dir: str) -> None:
        """Rebuild membership from checkpoint metadata alone (the
        ``coordinator_loss`` recovery path): the trainer's emergency save
        always lands before the coordinator recovers, so disk is the
        authoritative record of the world that was running."""
        meta = flat_meta(ckptlib.read_mid_epoch_meta(checkpoint_dir)
                         or ckptlib.read_epoch_meta(checkpoint_dir))
        if not meta:
            raise RuntimeError(
                "coordinator state lost and no checkpoint metadata on "
                "disk to re-derive membership from")
        w = world_of(meta)
        with self._lock:
            self.world = w
            self.members = tuple(range(w))

    # -- reporting ----------------------------------------------------------

    def report(self) -> dict:
        with self._lock:
            return {
                "world": self.world,
                "members": list(self.members),
                "generation": self.generation,
                "degraded": self.degraded,
                "protocol": self.protocol,
                "recoveries": self.recoveries,
                "retries_used": self.retries_used,
                "events": list(self.events),
            }
