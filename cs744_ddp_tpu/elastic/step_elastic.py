"""The strong-scaling elastic train window: bitwise world-invariant math.

The standard step programs (``train/step.py``) are deliberately
world-DEPENDENT in three places: the loss/grad reduction is a
``lax.pmean`` of per-shard means (float reduction order changes with the
shard count), BatchNorm normalizes with the local shard's statistics, and
the augmentation PRNG folds ``lax.axis_index``.  All three are faithful to
the reference — and all three make a world-resize change the trajectory.

This module builds the program whose update is a pure function of the
GLOBAL batch, independent of how many ranks compute it:

* every global batch of B examples is decomposed into S fixed-size
  **microshards** (S a power of two, microshard batch B/S) laid out in
  canonical order;
* rank r of world M (M | S) loops over its k = S/M contiguous microshards
  with a ``lax.fori_loop`` whose trip count is a RUNTIME scalar — the loop
  body is one compiled computation per microshard shape at EVERY world
  size (a static k=1 loop would be inlined and re-fused), so the
  per-microshard loss/grads/BN-stats are the same values whether a rank
  runs 1, 2, or 4 iterations;
* the PRNG key for microshard m = r*k + j folds the batch index first and
  the GLOBAL microshard index second — never the mesh position — so the
  augmentation stream is a function of canonical data position only;
* BatchNorm normalizes with MICROSHARD-local statistics (batch B/S),
  identical at every world size;
* per-microshard results are ``lax.all_gather``-ed over the data axis
  (deterministic rank order → global microshard order) and combined with
  a fixed pairwise binary tree (``x[0::2] + x[1::2]`` until one row
  remains, then / S) — one float summation order, regardless of M;
* the combined (replicated) gradient drives one SGD update per batch.

The gradient all-gather costs S× the allreduce bandwidth of the standard
programs — that is the price of a pinned trajectory, and it is why this is
a separate opt-in window rather than a change to the default step.  The
residual empirical assumption (XLA lowers the loop body identically across
runtime trip counts) is exactly what the world 1→2→4 CI pin checks.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..data import augment as aug
from ..ops import sgd
from ..ops.loss import cross_entropy
from ..parallel.mesh import DATA_AXIS
from ..train.step import (_SHARD_MAP_KW, TrainState, maybe_cast, pvary,
                          shard_map)


def tree_combine_mean(x: jax.Array) -> jax.Array:
    """Mean over the leading axis with a FIXED pairwise summation tree.

    ``x`` has leading dim S (power of two).  Plain ``jnp.mean`` would let
    XLA pick a reduction order that may differ between program variants;
    the explicit tree pins one order: (((x0+x1)+(x2+x3))...)/S.
    """
    s = x.shape[0]
    if s & (s - 1):
        raise ValueError(f"tree combine needs a power-of-two count, got {s}")
    while x.shape[0] > 1:
        x = x[0::2] + x[1::2]
    return x[0] / s


def make_elastic_train_window(apply_fn: Callable, mesh: Mesh,
                              cfg: sgd.SGDConfig = sgd.SGDConfig(), *,
                              microshards: int,
                              augment: bool = True,
                              compute_dtype=None) -> Callable:
    """Build the strong-scaling windowed train program.

    window(state, key, epoch_images[NB,B,...], epoch_labels[NB,B],
           start, length_arr) -> (state, losses[W])

    Same contract as ``make_train_window`` (epoch arrays device-resident,
    W = length_arr.shape[0] static, state donated), but the batch axis B
    is decomposed into ``microshards`` and the gradient reduction is the
    fixed gather+tree combine described in the module docstring.  The
    gradient-sync *strategy* is intentionally absent: the combine IS the
    reduction, and it must not vary with the strategy or the world.
    ``augment`` is True/False only — the host-augment path shards work by
    mesh position and cannot be world-invariant.
    """
    if augment == "host":
        raise ValueError("elastic strong scaling requires on-device "
                         "augmentation (host streams are rank-shaped)")
    world = int(mesh.devices.size)
    s = int(microshards)
    if s < 1 or (s & (s - 1)):
        raise ValueError(f"microshards must be a power of two, got {s}")
    if s % world:
        raise ValueError(f"microshards {s} not divisible by world {world} "
                         "— this world size cannot run the pinned program")
    k = s // world  # microshards per rank

    def window_body(params, bn_state, opt_state, key, epoch_images,
                    epoch_labels, start, length_arr, k_dyn):
        w = length_arr.shape[0]
        imgs = lax.dynamic_slice_in_dim(epoch_images, start, w, axis=0)
        labs = lax.dynamic_slice_in_dim(epoch_labels, start, w, axis=0)
        idxs = start + jnp.arange(w, dtype=jnp.int32)
        rank = lax.axis_index(DATA_AXIS)

        def one(carry, xs):
            params, bn_state, opt_state, key = carry
            images, labels, idx = xs  # local slice: [B/M, ...]
            # Canonical elastic fold order: batch index first, GLOBAL
            # microshard index second (inside the loop below).  The mesh
            # position never enters the stream — rank r merely evaluates
            # the microshards it happens to hold.
            bkey = jax.random.fold_in(key, idx)
            mb = images.shape[0] // k
            imgs_k = images.reshape((k, mb) + images.shape[1:])
            labs_k = labels.reshape((k, mb))
            # Differentiate w.r.t. a device-varying view so the explicit
            # combine below is the ONLY gradient reduction (see
            # train/step.py on the invariant-cotangent auto-psum).
            params_var = jax.tree.map(pvary, params)
            bn_var = jax.tree.map(pvary, bn_state)

            losses0 = jnp.zeros((k,), jnp.float32)
            grads0 = jax.tree.map(
                lambda a: jnp.zeros((k,) + a.shape, a.dtype), params_var)
            bns0 = jax.tree.map(
                lambda a: jnp.zeros((k,) + a.shape, a.dtype), bn_var)

            def micro(j, acc):
                losses_k, grads_k, bns_k = acc
                mimgs = lax.dynamic_index_in_dim(imgs_k, j, keepdims=False)
                mlabs = lax.dynamic_index_in_dim(labs_k, j, keepdims=False)
                mk = jax.random.fold_in(bkey, rank * k + j)
                # Fence the per-microshard math off from its k-shaped
                # surroundings (the [k,...] stacking buffers): inside the
                # barriers the computation depends only on microshard-shaped
                # values, so it lowers identically at every world size.
                mimgs, mlabs, mk = lax.optimization_barrier(
                    (mimgs, mlabs, mk))
                x = aug.augment(mk, mimgs) if augment else aug.normalize(
                    mimgs)
                x = maybe_cast(x, compute_dtype)

                def loss_fn(p):
                    logits, new_bn = apply_fn(p, bn_var, x, train=True)
                    return cross_entropy(logits, mlabs), new_bn

                (loss, new_bn), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params_var)
                loss, grads, new_bn = lax.optimization_barrier(
                    (loss, grads, new_bn))
                upd = lambda buf, v: lax.dynamic_update_index_in_dim(
                    buf, v, j, 0)
                return (upd(losses_k, loss), jax.tree.map(upd, grads_k, grads),
                        jax.tree.map(upd, bns_k, new_bn))

            # The trip count is k at every call — but it is passed as a
            # RUNTIME scalar (``k_dyn``), not baked into the loop, so XLA
            # cannot simplify the k=1 (world == S) case into straight-line
            # code.  An inlined body is re-fused with its surroundings and
            # lowers differently than the same body inside a while loop —
            # observed as 1-ulp drift in the BN running-var aux — so every
            # world size must run the SAME loop-shaped program.
            losses_k, grads_k, bns_k = lax.fori_loop(
                0, k_dyn, micro, (losses0, grads0, bns0))
            # [k, ...] per rank -> [S, ...] in global microshard order
            # (tiled all_gather concatenates in rank order, and rank r's
            # microshards are exactly m = r*k .. r*k+k-1, in order).
            gather = partial(lax.all_gather, axis_name=DATA_AXIS, axis=0,
                             tiled=True)
            losses_s, grads_s, bns_s = jax.tree.map(
                gather, (losses_k, grads_k, bns_k))
            loss = tree_combine_mean(losses_s)
            grads = jax.tree.map(tree_combine_mean, grads_s)
            new_bn = jax.tree.map(tree_combine_mean, bns_s)
            new_params, new_opt = sgd.update(params, grads, opt_state, cfg)
            return (new_params, new_bn, new_opt, key), loss

        (p, bn, opt, _), losses = lax.scan(
            one, (params, bn_state, opt_state, key), (imgs, labs, idxs))
        return p, bn, opt, losses

    mapped = shard_map(
        window_body, mesh=mesh,
        in_specs=(P(), P(), P(), P(), P(None, DATA_AXIS), P(None, DATA_AXIS),
                  P(), P(), P()),
        out_specs=(P(), P(), P(), P()),
        **_SHARD_MAP_KW,
    )

    @partial(jax.jit, donate_argnums=(0,))
    def window_impl(state: TrainState, key, epoch_images, epoch_labels,
                    start, length_arr, k_dyn):
        p, bn, opt, losses = mapped(
            state.params, state.bn_state, state.opt_state, key,
            epoch_images, epoch_labels, start, length_arr, k_dyn)
        return TrainState(p, bn, opt), losses

    # k is fed as a runtime argument (see window_body) — same public
    # contract as make_train_window, including .lower for AOT warmup.
    k_arr = jnp.int32(k)

    def window(state: TrainState, key, epoch_images, epoch_labels, start,
               length_arr):
        return window_impl(state, key, epoch_images, epoch_labels, start,
                           length_arr, k_arr)

    window.lower = lambda *args: window_impl.lower(*args, k_arr)
    return window
