"""Fault-tolerance layer: chaos injection, staging supervision, non-finite
step guard, and preemption-safe mid-epoch resume.

Everything is opt-in through one ``FTConfig`` handed to ``Trainer``; the
default (``ft=None``) leaves every hot path byte-identical to the
unsupervised build — the chaos plan is the stateless ``NULL_CHAOS``
singleton and the guard is never compiled into the step programs.
"""

from __future__ import annotations

from typing import Any, NamedTuple

from .chaos import (NULL_CHAOS, PUBLISH_SITES, RANK_SITES, REPLICA_SITES,
                    ChaosError, ChaosPlan, NullChaos, RankDeathError, SITES)
from .guard import POLICIES, NonFiniteError
from .preempt import PreemptedError, PreemptionGuard
from .supervisor import (StagingStalled, Watchdog, batch_checksums,
                         call_with_retry, verify_checksums)


class FTConfig(NamedTuple):
    """Fault-tolerance knobs (defaults are production-shaped; tests and the
    bench robustness section shrink the timeouts).

    nonfinite         : "off" | "halt" | "skip" | "restore" step-guard policy.
    chaos             : ChaosPlan (or NULL_CHAOS) of deterministic injections.
    put_timeout_s     : watchdog deadline for one chunk device_put (+ arena
                        fence wait); overruns are counted, not interrupted.
    put_retries       : total attempts for a failing chunk put.
    backoff_base_s    : exponential backoff base between put retries.
    stall_timeout_s   : consumer-side deadline with no staged item arriving
                        while the producer looks alive -> treated as a
                        producer failure (restart once, then degrade).
    producer_restarts : producer restart attempts before degrading to the
                        synchronous per-batch staging path.
    verify_chunks     : crc32-verify staged rows right before each put
                        (auto-enabled when the chaos plan corrupts slots).
    degrade_staging   : start in the degraded synchronous staging mode
                        (bench/testing knob — measures the fallback).
    slow_rank_stall_s : stall injected per ``slow_rank`` chaos entry and
                        attributed to the target rank's step-time gauge
                        (elastic/straggler.py must flag it).
    """

    nonfinite: str = "off"
    chaos: Any = NULL_CHAOS
    put_timeout_s: float = 30.0
    put_retries: int = 3
    backoff_base_s: float = 0.05
    stall_timeout_s: float = 120.0
    producer_restarts: int = 1
    verify_chunks: bool = False
    degrade_staging: bool = False
    slow_rank_stall_s: float = 0.25


__all__ = [
    "FTConfig", "ChaosPlan", "ChaosError", "NullChaos", "NULL_CHAOS", "SITES",
    "PUBLISH_SITES", "RANK_SITES", "REPLICA_SITES", "RankDeathError",
    "POLICIES", "NonFiniteError", "PreemptedError", "PreemptionGuard",
    "StagingStalled", "Watchdog", "call_with_retry", "batch_checksums",
    "verify_checksums",
]
