"""Staging supervision primitives: watchdogs, bounded retry, checksums.

The chunked host->device staging pipeline (PR 2) has three ways to die
that a bare ``queue.get`` never surfaces: the producer thread crashes, a
``device_put`` stalls forever (wedged transfer engine / PCIe hiccup), or
staged bytes get silently corrupted (buffer-reuse bug).  The primitives
here make each one *detected* and *bounded*:

* ``Watchdog``        — detection-only timer around a blocking call.  It
                        cannot interrupt a wedged native call (nothing in
                        Python can), so it fires a callback (telemetry
                        counter + log) while the consumer-side stall
                        deadline remains the hard recovery trigger.
* ``call_with_retry`` — bounded attempts with exponential backoff for
                        transient put failures.
* ``StagingStalled``  — raised by the consumer when no staged item has
                        arrived within the deadline although the producer
                        looks alive; handled exactly like a producer
                        crash (restart once, then degrade).
* ``batch_checksums`` / ``verify_checksums`` — crc32 over each staged
                        arena row, computed at fill time and re-verified
                        immediately before the put; a mismatch means the
                        bytes changed underneath us and the row is
                        deterministically re-staged from the dataset.
"""

from __future__ import annotations

import threading
import time
import zlib
from typing import Callable, List, Optional, Sequence


class StagingStalled(RuntimeError):
    """Consumer-side stall deadline expired with the producer still alive."""


class WatchdogTimeout(RuntimeError):
    """Used by ``Watchdog.elapsed_error`` when a caller opts into raising."""


class Watchdog:
    """Context manager that invokes ``on_timeout(elapsed_s)`` once if the
    body runs longer than ``timeout_s``.  Detection only — the body keeps
    running; ``fired`` tells the caller it overran."""

    def __init__(self, timeout_s: Optional[float],
                 on_timeout: Optional[Callable[[float], None]] = None):
        self._timeout_s = timeout_s
        self._on_timeout = on_timeout
        self._timer: Optional[threading.Timer] = None
        self._t0 = 0.0
        # ``_fire`` runs on the Timer thread while ``__exit__``/readers
        # run on the caller's; ``Timer.cancel`` does NOT wait for an
        # in-flight callback, so without the lock + cancelled flag a
        # watchdog could fire (and count a timeout) AFTER its body
        # already completed — the lock makes cancel-vs-fire atomic
        # (regression-tested in tests/test_analysis.py).
        self._lock = threading.Lock()
        self._cancelled = False
        self.fired = False

    def _fire(self):
        with self._lock:
            if self._cancelled:
                return
            self.fired = True
            if self._on_timeout is not None:
                self._on_timeout(time.perf_counter() - self._t0)

    def __enter__(self) -> "Watchdog":
        self._t0 = time.perf_counter()
        with self._lock:
            self.fired = False
            self._cancelled = False
        if self._timeout_s is not None and self._timeout_s > 0:
            self._timer = threading.Timer(self._timeout_s, self._fire)
            self._timer.daemon = True
            self._timer.start()
        return self

    def __exit__(self, *exc):
        if self._timer is not None:
            self._timer.cancel()
        with self._lock:
            # After this point an in-flight ``_fire`` can no longer set
            # ``fired`` or invoke the callback.
            self._cancelled = True
        return False


def call_with_retry(fn: Callable, *, attempts: int, backoff_base_s: float,
                    on_retry: Optional[Callable[[int, BaseException], None]] = None,
                    sleep: Callable[[float], None] = time.sleep):
    """Run ``fn()`` with up to ``attempts`` tries and exponential backoff
    (``backoff_base_s * 2**try``) between them.  ``on_retry(i, exc)`` is
    called before each re-attempt; the final failure propagates."""
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    for a in range(attempts):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 - retry layer is intentionally broad
            if a == attempts - 1:
                raise
            if on_retry is not None:
                on_retry(a, e)
            sleep(backoff_base_s * (2 ** a))


def batch_checksums(rows) -> List[int]:
    """crc32 per staged batch row (C-contiguous uint8 views)."""
    import numpy as np
    return [zlib.crc32(np.ascontiguousarray(r)) for r in rows]


def verify_checksums(rows, expected: Sequence[int]) -> List[int]:
    """Indices of rows whose bytes no longer match their fill-time crc32."""
    got = batch_checksums(rows)
    return [i for i, (g, e) in enumerate(zip(got, expected)) if g != e]
