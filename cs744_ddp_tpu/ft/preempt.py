"""Preemption guard: turn SIGTERM/SIGINT into a clean mid-epoch save.

On TPU pods preemption is routine: the scheduler sends SIGTERM and gives
the process a grace window.  The guard installs handlers that only set a
flag; the training loop polls the flag at step/window boundaries (so the
in-flight dispatch always completes) and raises ``PreemptedError``, which
``Trainer.run`` catches to write an emergency *step-level* checkpoint.
Handlers never do real work — everything heavy happens on the main thread
at a known-consistent point.

``install`` is a no-op off the main thread (Python only delivers signals
to the main thread, and ``signal.signal`` raises elsewhere), and the
previous handlers are restored by ``uninstall`` so library callers — and
pytest — keep their Ctrl-C behaviour outside ``run()``.
"""

from __future__ import annotations

import signal
import threading
from typing import Optional


class PreemptedError(Exception):
    """Raised at a step boundary after SIGTERM/SIGINT; carries the exact
    resume point (epoch, step = batches already trained this epoch)."""

    def __init__(self, epoch: int, step: int):
        super().__init__(f"preempted at epoch {epoch} step {step}")
        self.epoch = epoch
        self.step = step


class PreemptionGuard:
    _SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self, log=None):
        self._event = threading.Event()
        self._prev: dict = {}
        self._log = log
        self.signum: Optional[int] = None

    def _handler(self, signum, frame):
        self.signum = signum
        self._event.set()
        if self._log is not None:
            name = signal.Signals(signum).name
            self._log(f"{name} received; will checkpoint at the next step "
                      f"boundary and exit")

    def install(self) -> "PreemptionGuard":
        if threading.current_thread() is not threading.main_thread():
            return self
        for sig in self._SIGNALS:
            self._prev[sig] = signal.signal(sig, self._handler)
        return self

    def uninstall(self) -> None:
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        self._prev.clear()

    @property
    def requested(self) -> bool:
        return self._event.is_set()

    def check(self, epoch: int, step: int) -> None:
        """Raise ``PreemptedError`` if a preemption signal has arrived."""
        if self._event.is_set():
            raise PreemptedError(epoch, step)
