"""Deterministic fault injection: the seeded chaos plan.

A chaos plan is a list of ``(site, step, seed)`` entries — parsed from CLI
specs ``SITE:step[:seed]`` or built programmatically — that fire EXACTLY
ONCE when training reaches the named step.  Determinism is the point: a
chaos run is reproducible (same plan, same seed, same faults at the same
steps), so the recovery path's output can be pinned against a fault-free
run in CI, which is what turns "we have retry code" into "the retry code
provably preserves the training stream".

Injection sites (each names a real failure mode of the training stack):

* ``producer_crash``   — the host-augment staging producer thread dies
                         (uncaught exception) while filling batch ``step``;
* ``put_delay``        — the chunk ``device_put`` covering ``step`` stalls
                         (sleeps past the watchdog timeout) once;
* ``put_fail``         — that put raises once (transient transfer error);
* ``corrupt_slot``     — the staged arena bytes for batch ``step`` are
                         corrupted (seeded XOR) after checksumming — the
                         signature of a buffer-reuse/aliasing bug;
* ``nonfinite_grad``   — the compiled step's gradients go NaN at batch
                         ``step`` (overflow/instability stand-in);
* ``preempt``          — SIGTERM is delivered to this process at the first
                         step boundary >= ``step`` (pod preemption).

Rank-level sites (elastic/ — round 6's world-resize layer).  For these the
third spec field is the RANK the fault is attributed to, not a payload
seed (``rank_death:step:rank``):

* ``rank_death``       — rank ``rank``'s device fails at the first step
                         boundary >= ``step``; the trainer raises
                         ``RankDeathError`` and the elastic coordinator
                         walks its degradation ladder (retry -> shrink ->
                         single-rank fallback);
* ``slow_rank``        — rank ``rank`` straggles: a configurable stall
                         (``FTConfig.slow_rank_stall_s``) is injected at
                         the step boundary and attributed to that rank's
                         step-time gauge, which the straggler detector
                         must flag;
* ``coordinator_loss`` — the elastic coordinator's in-memory membership
                         state is dropped once recovery progress reaches
                         ``step``; it must re-derive membership from the
                         checkpoint metadata alone.

Replica-level sites (serve/ — round 9's replicated serving tier).  The
third spec field is the target REPLICA index and ``step`` counts that
replica's OWN dispatches (``replica_death:dispatch:replica``):

* ``replica_death``    — the replica's scheduler worker raises
                         ``ChaosError`` at its dispatch ``step``; the
                         router must fail over every unfinished request
                         (in-flight and queued) to survivors — no
                         accepted request is silently dropped;
* ``slow_replica``     — the replica stalls ``slow_stall_s`` before its
                         dispatch ``step`` (a straggling chip); the
                         least-loaded router routes around it as its
                         measured service EWMA inflates;
* ``dispatch_fault``   — dispatch ``step``'s device result is discarded
                         at its COMPLETION fence
                         (``dispatch_fault:dispatch:replica``) — with the
                         pipelined scheduler, while dispatch ``step+1``
                         is already in flight.  The pin: the faulted
                         batch's requests resolve as explicit errors, the
                         in-flight successor resolves normally on the
                         same weights, and recovery is bitwise-identical
                         to the serial path — a completion fault is
                         isolated, never a silent drop and never a
                         replica death;
* ``swap_mid_batch``   — the replica's weight-watcher probe is invoked
                         INSIDE the dispatch hook of dispatch ``step``
                         (``swap_mid_batch:dispatch:replica``): a
                         pending publish races the dispatch already
                         being assembled.  The pin: the racing dispatch
                         is answered bitwise by the OLD weights (the
                         install lands at the next engine-free instant),
                         the next dispatch by the new — never a mix.

Publish-level sites (publish/ — round 10's train-to-serve hot-swap).
``step`` counts the publisher's OWN publishes (0-based) and the third
spec field is a payload seed (``publish_torn:publish[:seed]``):

* ``publish_torn``     — the published bundle's payload bytes are
                         corrupted (seeded XOR) AFTER the atomic rename,
                         so the file is well-formed but fails its
                         per-leaf crc32 — the watcher must reject it and
                         keep serving the old version;
* ``publish_stale``    — the publish re-announces the PREVIOUS version
                         (a duplicate/late publisher): the watcher must
                         skip it without staging or swapping anything.

The disabled plan is ``NULL_CHAOS`` — a stateless singleton exactly like
the telemetry ``NULL`` recorder: ``enabled`` is False, ``fire*`` return
False without allocating, and hot call sites guard on ``.enabled`` so the
no-chaos path costs nothing (pinned by tests/test_ft.py).
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence, Tuple

SITES = ("producer_crash", "put_delay", "put_fail", "corrupt_slot",
         "nonfinite_grad", "preempt", "rank_death", "slow_rank",
         "coordinator_loss", "replica_death", "slow_replica",
         "publish_torn", "swap_mid_batch", "publish_stale",
         "dispatch_fault")
# Sites whose third spec field names the target RANK (elastic/), not a
# payload seed — same wire format, different interpretation.
RANK_SITES = ("rank_death", "slow_rank")
# Sites whose third spec field names the target serving REPLICA and whose
# step counts that replica's own dispatches (serve/replica.py).
REPLICA_SITES = ("replica_death", "slow_replica", "swap_mid_batch",
                 "dispatch_fault")
# Sites fired by the weight publisher (publish/publisher.py): step counts
# the publisher's own publishes, the third field is a payload seed.
PUBLISH_SITES = ("publish_torn", "publish_stale")


class ChaosError(RuntimeError):
    """An injected fault (never raised by real failures — recovery paths
    that catch broadly still distinguish injected faults in telemetry)."""


class RankDeathError(RuntimeError):
    """Rank ``rank``'s device failed at a step boundary.  Raised by the
    trainer's boundary poll (injected by the ``rank_death`` chaos site, or
    by a real device-probe failure); the trainer converts it into an
    emergency mid-epoch checkpoint and the elastic coordinator
    (elastic/coordinator.py) walks its degradation ladder.  Lives here —
    not in elastic/ — because the trainer must catch it without importing
    the elastic layer (which imports the trainer's step machinery)."""

    def __init__(self, rank: int, epoch: int, step: int):
        super().__init__(f"rank {rank} died at epoch {epoch} step {step}")
        self.rank = rank
        self.epoch = epoch
        self.step = step


class NullChaos:
    """The disabled plan: every query is False, no state can ever attach."""
    __slots__ = ()
    enabled = False

    def fire(self, site: str, step: int) -> bool:
        return False

    def fire_range(self, site: str, lo: int, hi: int) -> bool:
        return False

    def fire_reached(self, site: str, step: int) -> bool:
        return False

    def steps(self, site: str) -> Tuple[int, ...]:
        return ()

    def seed_of(self, site: str, step: int) -> int:
        return 0

    def spec(self):
        return []


NULL_CHAOS = NullChaos()


class ChaosPlan:
    """A list of one-shot injections, thread-safe (the staging producer
    thread fires sites too).  ``fired`` records what actually fired, in
    order — the test/telemetry surface."""

    enabled = True

    def __init__(self, entries: Sequence[Tuple[str, int, int]]):
        for site, step, _seed in entries:
            if site not in SITES:
                raise ValueError(f"unknown chaos site {site!r}; "
                                 f"expected one of {SITES}")
            if step < 0:
                raise ValueError(f"chaos step must be >= 0, got {step}")
        self._entries: List[dict] = [
            {"site": s, "step": st, "seed": sd, "fired": False}
            for s, st, sd in entries]
        self._lock = threading.Lock()
        self.fired: List[Tuple[str, int]] = []

    @classmethod
    def parse(cls, specs: Optional[Sequence[str]]):
        """Parse CLI specs ``SITE:step[:seed]`` -> plan (or ``NULL_CHAOS``
        for an empty list, so the disabled path stays the stateless
        singleton)."""
        if not specs:
            return NULL_CHAOS
        entries = []
        for spec in specs:
            parts = spec.split(":")
            if len(parts) not in (2, 3):
                raise ValueError(
                    f"bad chaos spec {spec!r}: expected SITE:step[:seed]")
            site = parts[0]
            try:
                step = int(parts[1])
                seed = int(parts[2]) if len(parts) == 3 else 0
            except ValueError:
                raise ValueError(f"bad chaos spec {spec!r}: step/seed must "
                                 f"be integers") from None
            entries.append((site, step, seed))
        return cls(entries)

    def _fire(self, site: str, match) -> Optional[dict]:
        with self._lock:
            for e in self._entries:
                if e["site"] == site and not e["fired"] and match(e["step"]):
                    e["fired"] = True
                    self.fired.append((site, e["step"]))
                    return e
        return None

    def fire(self, site: str, step: int) -> bool:
        """One-shot: True exactly once per entry whose step == ``step``."""
        return self._fire(site, lambda s: s == step) is not None

    def fire_range(self, site: str, lo: int, hi: int) -> bool:
        """One-shot over a half-open step range [lo, hi) — chunk-level
        sites cover several batches per operation."""
        return self._fire(site, lambda s: lo <= s < hi) is not None

    def fire_reached(self, site: str, step: int) -> bool:
        """One-shot when progress ``step`` reaches/passes the entry —
        boundary-polled sites (preemption is checked between dispatch
        windows, not at every batch)."""
        return self._fire(site, lambda s: step >= s) is not None

    def steps(self, site: str) -> Tuple[int, ...]:
        """All step indices planned for ``site`` (fired or not) — what the
        compiled-in injection closures are built from."""
        return tuple(e["step"] for e in self._entries if e["site"] == site)

    def seed_of(self, site: str, step: int) -> int:
        """The third spec field of the entry planned at (site, step) — a
        payload seed for data-level sites, the target RANK for the
        rank-level sites (RANK_SITES).  0 when no such entry exists."""
        for e in self._entries:
            if e["site"] == site and e["step"] == step:
                return e["seed"]
        return 0

    def spec(self):
        """Manifest-shaped view of the plan (site/step/seed per entry)."""
        return [{"site": e["site"], "step": e["step"], "seed": e["seed"]}
                for e in self._entries]

    def rng(self, site: str, step: int):
        """Seeded generator for an entry's fault payload (corruption byte
        positions/values) — deterministic in (seed, site, step)."""
        import numpy as np
        seed = 0
        for e in self._entries:
            if e["site"] == site and e["step"] == step:
                seed = e["seed"]
                break
        return np.random.default_rng([seed, SITES.index(site), step])
