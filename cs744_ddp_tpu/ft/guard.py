"""Non-finite step guard: on-device finiteness check + conditional update.

A NaN/Inf loss or gradient poisons every subsequent step — by the time a
host-side print shows ``loss: nan`` the params are already garbage.  The
guard checks ``isfinite(loss) & isfinite(sum_g ||g||^2)`` *inside* the
compiled step (one scalar reduction over gradient leaves — noise next to
the backward pass) and selects the update with ``jnp.where``:

* ok     -> the normal SGD update (params, BN stats, momentum all advance);
* not ok -> every component keeps its PRIOR value — params unchanged, BN
            statistics unchanged, momentum unchanged, exactly as if the
            batch had not been seen.

The select is branch-free so the program stays a single trace (windowed
``lax.scan`` included).  Policy semantics live host-side in the Trainer:
``halt`` raises, ``skip`` counts and continues, ``restore`` additionally
rolls params back to the last checkpoint snapshot.  When the policy is
``off`` none of this is compiled in — the step program is byte-identical
to the unguarded one.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class NonFiniteError(RuntimeError):
    """Raised under ``--nonfinite=halt`` when a non-finite step is caught
    (state has NOT absorbed the bad update — the on-device select already
    kept the prior params)."""


POLICIES = ("off", "halt", "skip", "restore")


def grad_sqnorm(grads):
    """Global squared gradient norm as one f32 scalar (NaN/Inf anywhere in
    any leaf propagates into it, which is all the guard needs)."""
    leaves = jax.tree_util.tree_leaves(grads)
    return sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)


def finite_ok(loss, grads):
    """Scalar bool: this step's loss and every gradient entry are finite."""
    return jnp.isfinite(loss) & jnp.isfinite(grad_sqnorm(grads))


def select_update(ok, new_tree, old_tree):
    """Branch-free per-leaf select: ``new`` where ok else ``old``."""
    return jax.tree.map(lambda n, o: jnp.where(ok, n, o), new_tree, old_tree)


def inject_nan(grads, mask=None):
    """Chaos helper: poison gradients with NaN.  ``mask`` (scalar bool or
    None for unconditional) keeps the injection traceable inside a scan —
    the window program folds ``mask = (abs_idx == chaos_step)`` so a single
    compiled program injects at exactly one batch of the epoch."""
    def poison(g):
        bad = jnp.asarray(jnp.nan, g.dtype)
        if mask is None:
            return g + bad
        return g + jnp.where(mask, bad, jnp.zeros((), g.dtype))
    return jax.tree.map(poison, grads)
