"""Serving-side weight watcher: poll the publish directory, validate,
stage, and swap — between dispatches, never during one.

``WeightWatcher`` owns the whole install pipeline for a set of live
``EngineReplica``s:

1. follow the directory's ``LATEST`` pointer (cheap: one small json read
   per poll; unchanged pointer -> no work);
2. skip stale/duplicate versions (``publish_stale`` drill);
3. fully read + crc-verify the bundle (``publish_torn`` -> rejected, the
   old version keeps serving untouched);
4. validate the bundle's pytree structure and per-leaf (shape, dtype)
   against each engine's OWN abstract signature — the exact fields its
   executables were keyed on, so a valid install can never invalidate
   the AOT ladder (zero recompiles by construction);
5. stage the leaves onto each replica's device HERE, on the watcher's
   thread, off the serving worker's critical path;
6. hand each replica's scheduler a flip closure via
   ``request_install`` — the worker runs it at its next loop boundary,
   when no dispatch is in flight, so a batch never sees torn weights
   and every reply's ``model_version`` tag is exact.

Rolling vs all-at-once: with ``rolling=True`` (default) replicas are
swapped one at a time, each install awaited before the next is queued,
so serving capacity never drops to zero; ``rolling=False`` queues every
replica's flip at once (each still lands at that replica's own dispatch
boundary) — the bench's ``run_hotswap`` section measures both.

The ``swap_mid_batch`` chaos site calls ``poll_once(wait=False)`` from
INSIDE a dispatch hook (via ``EngineReplica.swap_probe``).  That path
must never block: it uses a non-blocking lock acquire (a concurrent
poll just reports "busy") and never waits on install futures — the
racing dispatch completes on the old weights, the flip lands at the
next boundary.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Sequence

from ..ft.chaos import NULL_CHAOS
from ..obs import NULL
from . import bundle as bundlelib


class WeightWatcher:
    """Poll/validate/stage/swap driver for one publish directory."""

    # Lock discipline (analysis/pylint_rules.py): every field mutated
    # under self._lock.
    _lock_owned = ("_installed_version", "_pointer", "_counts",
                   "_swap_ms", "_thread", "_stop")

    def __init__(self, directory: str, replicas: Sequence, *,
                 telemetry=None, chaos=NULL_CHAOS, rolling: bool = True,
                 poll_interval_s: float = 0.05,
                 install_timeout_s: float = 30.0,
                 attach_probes: bool = True):
        self.directory = directory
        self.replicas = list(replicas)
        self.telemetry = telemetry if telemetry is not None else NULL
        self.chaos = chaos
        self.rolling = bool(rolling)
        self.poll_interval_s = float(poll_interval_s)
        self.install_timeout_s = float(install_timeout_s)
        self._lock = threading.Lock()
        self._installed_version = 0
        self._pointer: Optional[dict] = None   # last LATEST content seen
        self._counts: Dict[str, int] = {
            "polls": 0, "installed": 0, "rejected": 0, "stale": 0}
        self._swap_ms: List[float] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        if attach_probes:
            for r in self.replicas:
                r.swap_probe = self._probe

    # -- the poll/install pipeline ----------------------------------------

    def _probe(self) -> None:
        """The swap_mid_batch entry point — called inside a dispatch hook
        on the scheduler WORKER thread, so it must never block (waiting
        on an install future would deadlock the worker against itself)."""
        self.poll_once(wait=False)

    def poll_once(self, wait: bool = True) -> str:
        """One poll of the publish directory.  Returns what happened:
        "none" (pointer unchanged / nothing published), "busy" (another
        poll in progress, non-blocking path only), "stale" (version
        already installed or older — skipped), "rejected" (torn bundle
        or signature mismatch — old version keeps serving), "pending"
        (installs queued, not awaited — ``wait=False``), or
        "installed" (every replica flipped)."""
        if not self._lock.acquire(blocking=wait):
            return "busy"
        try:
            return self._poll_locked(wait)
        finally:
            self._lock.release()

    def _poll_locked(self, wait: bool) -> str:
        # Caller (poll_once) holds _lock via the non-blocking acquire;
        # the _locked suffix carries that contract and every call site
        # is verified by analysis/lockgraph.py.
        tel = self.telemetry
        self._counts["polls"] += 1
        try:
            latest = bundlelib.read_latest(self.directory)
        except bundlelib.BundleError:
            # A malformed pointer is a real fault (it is written
            # atomically); reject, keep serving.
            self._reject_locked(tel, "pointer")
            return "rejected"
        if latest is None or latest == self._pointer:
            return "none"
        self._pointer = dict(latest)
        version = int(latest["version"])
        if tel.enabled:
            # The watcher-side freshness signal the PUBLISH_LAG alert
            # rule (obs/alerts.py) tracks: newest LATEST version seen
            # vs what this watcher has installed.
            tel.gauge("publish_latest_seen", version,
                      installed=self._installed_version)
        if version <= self._installed_version:
            self._counts["stale"] += 1
            if tel.enabled:
                tel.counter("publish_stale_skipped", version=version,
                            installed=self._installed_version)
            return "stale"

        path = os.path.join(self.directory, latest["file"])
        try:
            manifest, leaves = bundlelib.read_bundle(path)
        except (bundlelib.BundleError, OSError) as e:
            self._reject_locked(tel, "crc", version=version, error=str(e))
            return "rejected"
        err = self._validate(manifest, leaves)
        if err:
            self._reject_locked(tel, "signature", version=version, error=err)
            return "rejected"

        status = self._install_all_locked(manifest, leaves, version, wait)
        if tel.enabled and status == "installed":
            tel.counter("publish_installed", version=version)
            tel.gauge("installed_version", version)
        return status

    def _reject_locked(self, tel, why: str, **attrs) -> None:
        self._counts["rejected"] += 1
        if tel.enabled:
            tel.counter("publish_rejected", why=why, **attrs)

    def _validate(self, manifest: dict, leaves) -> str:
        """Bundle vs every engine's abstract signature; "" when clean."""
        sig = (manifest["treedef"], bundlelib.leaf_signature(leaves))
        fp_model = manifest.get("fingerprint", {}).get("model")
        for r in self.replicas:
            eng = r.engine
            treedef, eleaves = eng._key_fields["abstract"]
            want = (treedef, tuple((tuple(s), d) for s, d in eleaves))
            if sig != want:
                return (f"bundle signature does not match replica "
                        f"{r.index}'s abstract model signature")
            if fp_model is not None and fp_model != eng.model_name:
                return (f"bundle fingerprint model {fp_model!r} != "
                        f"engine model {eng.model_name!r}")
        return ""

    def _install_all_locked(self, manifest, leaves, version: int,
                     wait: bool) -> str:
        import jax

        futures = []
        for r in self.replicas:
            eng = r.engine
            # Unflatten with the ENGINE's treedef object (the bundle's
            # treedef string was validation only), staging each leaf to
            # this replica's device here on the watcher thread.
            _, treedef = jax.tree_util.tree_flatten(
                (eng.params, eng.bn_state))
            staged = leaves
            if eng.device is not None:
                staged = [jax.device_put(l, eng.device) for l in leaves]
            params, bn_state = jax.tree_util.tree_unflatten(treedef, staged)

            def flip(eng=eng, params=params, bn_state=bn_state):
                eng.install_weights(params, bn_state, version,
                                    assume_staged=True)

            t0 = time.perf_counter()
            fut = r.scheduler.request_install(flip)
            futures.append((r, t0, fut))
            if wait and self.rolling:
                self._await_locked(r, t0, fut)
                futures.pop()
        if wait:
            for r, t0, fut in futures:
                self._await_locked(r, t0, fut)
        # The version is claimed as installed once every flip is queued:
        # each scheduler runs it at its next boundary (or inline at
        # stop()), and re-queueing on the next poll would double-install.
        self._installed_version = version
        self._counts["installed"] += 1
        return "installed" if wait else "pending"

    def _await_locked(self, replica, t0: float, fut) -> None:
        fut.result(timeout=self.install_timeout_s)
        ms = (time.perf_counter() - t0) * 1e3
        self._swap_ms.append(ms)
        if self.telemetry.enabled:
            self.telemetry.gauge("swap_ms", ms, replica=replica.index)

    # -- background polling ------------------------------------------------

    def start(self) -> "WeightWatcher":
        with self._lock:
            if self._thread is not None:
                return self
            self._stop = False
            self._thread = threading.Thread(
                target=self._run, name="weight-watcher", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        with self._lock:
            self._stop = True
            t = self._thread
            self._thread = None
        if t is not None:
            t.join(timeout=self.install_timeout_s)

    def _run(self) -> None:
        while True:
            with self._lock:
                if self._stop:
                    return
            self.poll_once(wait=True)
            time.sleep(self.poll_interval_s)

    # -- reporting ---------------------------------------------------------

    @property
    def installed_version(self) -> int:
        with self._lock:
            return self._installed_version

    def report(self) -> dict:
        with self._lock:
            return {"installed_version": self._installed_version,
                    "swap_ms": list(self._swap_ms),
                    **dict(self._counts)}
