"""Self-describing versioned weight bundle (the publish wire format).

One bundle file carries one model's full weight set (params + BatchNorm
state) as a flat leaf sequence:

    b"CCWB1\\n"  |  u32 manifest length  |  manifest JSON  |  leaf bytes

The manifest is the bundle's self-description — version, publisher
fingerprint (model/strategy/precision/seed/...), the pytree structure as
``str(treedef)``, and one record per leaf (shape, dtype, nbytes, crc32).
Leaf payloads follow back to back in manifest order, each independently
crc32-checksummed (zlib), so a torn or corrupted publish is rejected at
READ time with the exact leaf named — never installed, never partially
installed.

A deliberately boring custom container instead of ``np.savez``: the
serving-side validator needs per-leaf integrity (one flipped byte in leaf
k must fail leaf k's crc, which the ``publish_torn`` chaos site and its
CI pin depend on), and zip-member corruption fails opaquely and
all-or-nothing.  No pickling anywhere — the reader builds arrays straight
from the described shape/dtype, so a bundle is safe to read from an
untrusted directory.

``str(treedef)`` is a VALIDATION token, not a serialization: the
installer compares it against the engine's own treedef string and then
unflattens with the ENGINE's treedef object — a bundle can never smuggle
a foreign pytree structure into a replica.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from typing import Dict, List, Sequence, Tuple

import numpy as np

MAGIC = b"CCWB1\n"
FORMAT = 1

_U32 = struct.Struct("<I")


class BundleError(RuntimeError):
    """A bundle failed validation (bad magic, truncation, crc mismatch,
    malformed manifest) — the watcher's reject signal."""


def leaf_signature(leaves: Sequence[np.ndarray]
                   ) -> Tuple[Tuple[Tuple[int, ...], str], ...]:
    """(shape, dtype-string) per leaf — the shape half of the engine's
    abstract signature (``InferenceEngine._key_fields["abstract"]``)."""
    return tuple((tuple(l.shape), str(l.dtype)) for l in leaves)


def write_bundle(path: str, leaves: Sequence[np.ndarray], *,
                 version: int, treedef: str,
                 fingerprint: Dict | None = None) -> dict:
    """Write one bundle file at ``path`` (NOT atomic — the publisher owns
    the tmp+rename dance); returns the manifest written."""
    leaves = [np.ascontiguousarray(l) for l in leaves]
    records = []
    for l in leaves:
        raw = l.tobytes()
        records.append({"shape": list(l.shape), "dtype": str(l.dtype),
                        "nbytes": len(raw), "crc32": zlib.crc32(raw)})
    manifest = {
        "format": FORMAT,
        "version": int(version),
        "treedef": treedef,
        "fingerprint": dict(fingerprint or {}),
        "leaves": records,
    }
    head = json.dumps(manifest).encode("utf-8")
    with open(path, "wb") as f:
        f.write(MAGIC)
        f.write(_U32.pack(len(head)))
        f.write(head)
        for l in leaves:
            f.write(l.tobytes())
    return manifest


def read_manifest(path: str) -> dict:
    """The manifest alone (no payload read/verify) — what the watcher
    peeks at to decide staleness before paying for the full read."""
    with open(path, "rb") as f:
        magic = f.read(len(MAGIC))
        if magic != MAGIC:
            raise BundleError(f"{path}: bad magic {magic!r}")
        raw = f.read(_U32.size)
        if len(raw) != _U32.size:
            raise BundleError(f"{path}: truncated manifest length")
        (n,) = _U32.unpack(raw)
        head = f.read(n)
    if len(head) != n:
        raise BundleError(f"{path}: truncated manifest ({len(head)}/{n} B)")
    try:
        manifest = json.loads(head.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise BundleError(f"{path}: malformed manifest ({e})") from None
    if manifest.get("format") != FORMAT:
        raise BundleError(f"{path}: unknown bundle format "
                          f"{manifest.get('format')!r}")
    return manifest


def read_bundle(path: str) -> Tuple[dict, List[np.ndarray]]:
    """Read and FULLY VERIFY one bundle: every leaf's byte count and
    crc32 must match its manifest record.  Returns (manifest, leaves);
    raises :class:`BundleError` naming the first bad leaf — a torn
    publish is rejected here, before any replica sees it."""
    manifest = read_manifest(path)
    leaves: List[np.ndarray] = []
    with open(path, "rb") as f:
        # Re-skip the header by its on-disk length field, not by
        # re-encoding the manifest (json key order round-trips, but the
        # payload offset must not depend on that).
        f.read(len(MAGIC))
        (n,) = _U32.unpack(f.read(_U32.size))
        f.read(n)
        for i, rec in enumerate(manifest["leaves"]):
            raw = f.read(int(rec["nbytes"]))
            if len(raw) != int(rec["nbytes"]):
                raise BundleError(
                    f"{path}: leaf {i} truncated "
                    f"({len(raw)}/{rec['nbytes']} B)")
            if zlib.crc32(raw) != int(rec["crc32"]):
                raise BundleError(
                    f"{path}: leaf {i} crc32 mismatch (torn or corrupted "
                    f"publish)")
            leaves.append(np.frombuffer(raw, dtype=np.dtype(rec["dtype"]))
                          .reshape(tuple(rec["shape"])))
        if f.read(1):
            raise BundleError(f"{path}: trailing bytes after last leaf")
    return manifest, leaves


def bundle_nbytes(manifest: dict) -> int:
    return sum(int(r["nbytes"]) for r in manifest["leaves"])


# -- the LATEST pointer ------------------------------------------------------


LATEST = "LATEST"


def read_latest(directory: str) -> dict | None:
    """The publish directory's ``LATEST`` pointer ({"version", "file"})
    or None when nothing has been published yet.  A torn pointer raises
    :class:`BundleError` — the pointer is written atomically, so a
    malformed one is a real fault, not a race."""
    path = os.path.join(directory, LATEST)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        raw = f.read()
    try:
        latest = json.loads(raw)
    except json.JSONDecodeError as e:
        raise BundleError(f"{path}: malformed LATEST pointer ({e})") \
            from None
    if not isinstance(latest, dict) or "version" not in latest \
            or "file" not in latest:
        raise BundleError(f"{path}: LATEST pointer missing version/file")
    return latest
