"""Train-to-serve weight hot-swap (round 10).

The missing link between the trainer and the serving tier: the trainer
publishes versioned, crc-checksummed weight bundles into a watched
directory (``WeightPublisher``), and live replicas install them between
dispatches with zero recompiles, zero dropped requests, and a bitwise
A/B guarantee per request (``WeightWatcher``).  The swap is possible
without recompiling precisely because the serving executables are
weight-AGNOSTIC — weights are runtime arguments, certified unbaked by
the ``analysis/audit.py`` baked-constants rule — so a new version is
just a new argument reference, flipped at a dispatch boundary.
"""

from __future__ import annotations

from .bundle import (LATEST, BundleError, bundle_nbytes, leaf_signature,
                     read_bundle, read_latest, read_manifest, write_bundle)
from .publisher import WeightPublisher
from .watcher import WeightWatcher

__all__ = [
    "WeightPublisher", "WeightWatcher", "BundleError",
    "write_bundle", "read_bundle", "read_manifest", "read_latest",
    "leaf_signature", "bundle_nbytes", "LATEST",
]
