"""Training-side weight publisher: checkpoint state -> atomic bundle.

``WeightPublisher.publish(state)`` flattens the TrainState's serving
half (params + bn_state), writes a versioned bundle (``v000001.ccwb``)
via tmp + ``os.replace`` — complete-or-absent, same discipline as the
checkpoint metadata sidecars — then atomically updates the ``LATEST``
pointer.  A serving-side ``WeightWatcher`` polling the directory can
therefore never observe a half-written bundle through the pointer; the
only torn-bundle path is real corruption, which the per-leaf crc32
catches at read time.

Versions are monotonic: auto-assigned as ``LATEST.version + 1`` (1 when
the directory is empty), so a publisher restarted against an existing
directory continues the sequence instead of re-issuing version 1.

Chaos (``ft/`` harness, keyed by this publisher's 0-based publish
index):

* ``publish_torn:K[:seed]``  — publish K's bundle file has seeded bytes
  of its leaf payload flipped AFTER the atomic rename (the on-disk file
  is structurally valid but fails crc) — the watcher-must-reject drill;
* ``publish_stale:K[:seed]`` — publish K re-announces the PREVIOUS
  version number (a duplicate/late publisher) — the watcher-must-skip
  drill.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

from ..ft.chaos import NULL_CHAOS
from ..obs import NULL
from . import bundle as bundlelib


def _flatten_state(state):
    """(leaves, str(treedef)) of the serving half of a TrainState-like
    object (anything with ``params`` / ``bn_state``) — EXACTLY the
    flatten the engine keys its abstract signature on."""
    import jax
    import numpy as np

    leaves, treedef = jax.tree_util.tree_flatten(
        (state.params, state.bn_state))
    return [np.asarray(l) for l in leaves], str(treedef)


class WeightPublisher:
    """Atomic versioned publisher into one watched directory."""

    def __init__(self, directory: str, *, fingerprint: Optional[Dict] = None,
                 telemetry=None, chaos=NULL_CHAOS):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.fingerprint = dict(fingerprint or {})
        self.telemetry = telemetry if telemetry is not None else NULL
        self.chaos = chaos
        self._publishes = 0          # chaos step counter (0-based)

    def latest_version(self) -> int:
        latest = bundlelib.read_latest(self.directory)
        return int(latest["version"]) if latest else 0

    def _bundle_path(self, version: int) -> str:
        return os.path.join(self.directory, f"v{version:06d}.ccwb")

    def publish(self, state, *, version: Optional[int] = None) -> dict:
        """Publish ``state`` (params + bn_state); returns a record of
        what landed on disk: version, file, bytes, leaves, and which
        chaos faults (if any) were injected into THIS publish."""
        publish_no = self._publishes
        self._publishes += 1
        ch = self.chaos
        prev = self.latest_version()
        stale = ch.enabled and ch.fire("publish_stale", publish_no)
        if version is None:
            # A stale publish re-announces the previous version (or 1
            # when nothing precedes it — then it is merely a duplicate).
            version = prev if stale and prev > 0 else prev + 1
        version = int(version)

        leaves, treedef = _flatten_state(state)
        path = self._bundle_path(version)
        if stale and prev > 0:
            # A duplicate publisher would not overwrite the original
            # bundle byte-for-byte — it lands its own file and re-points
            # LATEST at the old version, so the watcher sees a CHANGED
            # pointer carrying an already-installed version (the skip
            # drill), not a no-op.
            path = os.path.join(self.directory, f"v{version:06d}.dup.ccwb")
        tmp = f"{path}.{os.getpid()}.tmp"
        try:
            manifest = bundlelib.write_bundle(
                tmp, leaves, version=version, treedef=treedef,
                fingerprint=self.fingerprint)
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

        torn = ch.enabled and ch.fire("publish_torn", publish_no)
        if torn:
            self._tear(path, publish_no)

        # Pointer update LAST, atomically: the watcher only ever follows
        # the pointer, so it can never race the bundle write itself.
        latest_path = os.path.join(self.directory, bundlelib.LATEST)
        tmp = f"{latest_path}.{os.getpid()}.tmp"
        try:
            with open(tmp, "w") as f:
                import json
                json.dump({"version": version,
                           "file": os.path.basename(path)}, f)
            os.replace(tmp, latest_path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

        nbytes = bundlelib.bundle_nbytes(manifest)
        tel = self.telemetry
        if tel.enabled:
            tel.counter("publish_count")
            tel.gauge("publish_version", version, bytes=nbytes,
                      leaves=len(leaves))
            if torn or stale:
                tel.counter("publish_chaos_injected",
                            torn=torn, stale=stale)
        return {"version": version, "file": path, "bytes": nbytes,
                "leaves": len(leaves), "torn": torn, "stale": stale}

    def _tear(self, path: str, publish_no: int) -> None:
        """Flip seeded payload bytes of the published file in place (past
        the manifest, so the header still parses and the failure is a
        leaf crc mismatch — the realistic torn-write signature)."""
        rng = self.chaos.rng("publish_torn", publish_no)
        manifest = bundlelib.read_manifest(path)
        size = os.path.getsize(path)
        payload = bundlelib.bundle_nbytes(manifest)
        start = size - payload
        offsets = sorted(set(
            int(o) for o in rng.integers(start, size, size=8)))
        with open(path, "r+b") as f:
            for off in offsets:
                f.seek(off)
                b = f.read(1)
                f.seek(off)
                f.write(bytes([b[0] ^ 0xFF]))
