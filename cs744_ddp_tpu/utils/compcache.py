"""Persistent XLA compilation cache shared by bench.py, cli.py, serve/ and
the test suite.

One knob, one location: the cache lives under <repo>/.jax_cache (gitignored)
and entries below the min-compile-time threshold are not persisted.

Hit/miss accounting: jax reports cache traffic through ``jax.monitoring``
events; a process-wide listener tallies them so the per-run telemetry
manifest can record whether this run's compiles actually came from the
cache (``cache_stats`` — a silent cache regression otherwise just looks
like a slow day).
"""

from __future__ import annotations

import os

_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_MISS_EVENT = "/jax/compilation_cache/cache_misses"

_counts = {"hits": 0, "misses": 0}
_listener_on = False
_enabled_dir: "str | None" = None


def repo_root() -> str:
    """The checkout root (two levels above this file's package)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _listen(event: str, **kw) -> None:
    if event == _HIT_EVENT:
        _counts["hits"] += 1
    elif event == _MISS_EVENT:
        _counts["misses"] += 1


def enable_persistent_compilation_cache(repo_root: str) -> None:
    """Best-effort: older jax without the config knobs just runs uncached."""
    global _listener_on, _enabled_dir
    try:
        import jax
        cache_dir = os.path.join(repo_root, ".jax_cache")
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
        _enabled_dir = cache_dir
        if not _listener_on:
            from jax import monitoring
            monitoring.register_event_listener(_listen)
            _listener_on = True
    except Exception:
        pass


def cache_stats() -> dict:
    """Cache location + hit/miss tallies since the listener went up —
    recorded in the telemetry run manifest (cli.py) so compile-cache
    regressions are visible per run."""
    return {"dir": _enabled_dir, "enabled": _enabled_dir is not None,
            "hits": _counts["hits"], "misses": _counts["misses"]}
