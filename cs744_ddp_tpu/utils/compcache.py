"""Persistent XLA compilation cache shared by bench.py and the test suite.

One knob, one location: the cache lives under <repo>/.jax_cache (gitignored)
and entries below the min-compile-time threshold are not persisted.
"""

from __future__ import annotations

import os


def enable_persistent_compilation_cache(repo_root: str) -> None:
    """Best-effort: older jax without the config knobs just runs uncached."""
    try:
        import jax
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(repo_root, ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
    except Exception:
        pass
