"""Timing/metrics instrumentation with reference-parity reporting.

The reference brackets forward and backward+sync+step with ``time.time()``,
averages over 20-iteration windows, skips the FIRST window from the timing
report (compilation/warmup), and prints running loss every 20 iterations
(``/root/reference/src/Part 1/main.py:28-57``).  This module reproduces that
schedule exactly — the caller is responsible for fencing each timed region
with a VALUE FETCH (``np.asarray``/``float``; ``jax.block_until_ready`` can
return early under the tunneled TPU backend) so the timers measure real
device work rather than async dispatch (SURVEY.md §5 "Tracing / profiling").
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional

from ..obs import NULL

WINDOW = 20  # reference: report every 20 iterations, skip the first window


class WindowedTimers:
    """Per-phase accumulators over 20-iteration windows, warmup excluded.

    ``telemetry`` mirrors every recorded iteration into the structured event
    log ALONGSIDE the reference-parity prints — the stdout schedule is the
    parity surface and is never altered by the recorder (guarded emit: the
    default ``NULL`` recorder costs nothing per step).
    """

    def __init__(self, log: Callable[[str], None] = print, *,
                 telemetry=NULL, epoch: int = 0):
        self.log = log
        self.telemetry = telemetry
        self.epoch = epoch
        self.iter_number = 1
        self.epoch_loss = 0.0
        self.forward_time = 0.0
        self.backward_time = 0.0
        self.total_time = 0.0
        # Full per-iteration loss trajectory (the reference's convergence
        # oracle, SURVEY.md §4) — what equivalence tests compare.
        self.losses: List[float] = []
        # Steady-state samples (first window excluded) for throughput calc.
        self.steady_step_times: List[float] = []
        self.steady_forward_times: List[float] = []

    def record(self, loss: float, step_time: float,
               forward_time: Optional[float] = None, *,
               steady: bool = True, extra: Optional[dict] = None) -> None:
        """Record one iteration. ``forward_time`` is optional because the
        functional step is a single fused program; when the trainer runs the
        split-phase timing mode it supplies both phases (the reference's
        'backward' bucket likewise absorbs sync+step, Part 2a/main.py:92-97).

        ``steady=False`` keeps the sample in the print schedule and epoch
        totals but OUT of the steady-state stats — used for the windowed
        path's ragged tail, whose lone per-dispatch sample carries ~100 ms
        of tunnel latency that the amortized per-window samples do not
        (one outlier per epoch would skew the derived throughput).

        ``extra`` merges additional fields into the telemetry step event
        (ring-drain rows carry grad sqnorm + reconstructed step index);
        the stdout print schedule never changes with it.
        """
        self.epoch_loss += loss
        self.losses.append(loss)
        self.total_time += step_time
        warmup = self.iter_number <= WINDOW
        if self.telemetry.enabled:
            self.telemetry.step(
                epoch=self.epoch, iter=self.iter_number, loss=float(loss),
                step_time=step_time, forward_time=forward_time,
                steady=not warmup and steady, **(extra or {}))
        if forward_time is not None:
            self.forward_time += forward_time
            self.backward_time += step_time - forward_time
            if not warmup and steady:
                self.steady_forward_times.append(forward_time)
        if not warmup and steady:
            self.steady_step_times.append(step_time)

        if self.iter_number % WINDOW == 0:
            self.log(f"Training loss after {self.iter_number} iterations is "
                     f"{self.epoch_loss / WINDOW}")
            self.epoch_loss = 0.0
            if self.iter_number != WINDOW:  # reference warmup skip (main.py:51)
                if forward_time is not None:
                    self.log(f"Forward Pass time in iter {self.iter_number} "
                             f"is {self.forward_time / WINDOW}")
                    self.log(f"Backward Pass time in iter {self.iter_number} "
                             f"is {self.backward_time / WINDOW}")
                self.log(f"Average Pass time in iter {self.iter_number} is "
                         f"{self.total_time / WINDOW}")
            self.forward_time = 0.0
            self.backward_time = 0.0
            self.total_time = 0.0
        self.iter_number += 1

    def steady_images_per_sec(self, global_batch: int) -> Optional[float]:
        if not self.steady_step_times:
            return None
        return global_batch * len(self.steady_step_times) / sum(
            self.steady_step_times)


class Stopwatch:
    def __enter__(self):
        self.t0 = time.time()
        return self

    def __exit__(self, *exc):
        self.elapsed = time.time() - self.t0
        return False


def mfu_fields(ips_per_chip: float, flops_per_image, **kw) -> dict:
    """tflops/MFU fields for one chip's throughput.  Delegates to
    ``analysis.costmodel.mfu_fields`` — the ONE copy of the peak constant
    and rounding that bench.py and the attribution tooling also use, so
    the numbers cannot drift between reports (round 8)."""
    from ..analysis.costmodel import mfu_fields as _mfu
    return _mfu(ips_per_chip, flops_per_image, **kw)
