"""Collective-op statistics parsed from compiled HLO text.

.. deprecated::
    This module is now a thin ADAPTER over the graph-IR implementation in
    :mod:`cs744_ddp_tpu.analysis` (``analysis/hlo_ir.py`` parses the HLO
    text structurally; ``analysis/stats.py`` does the accounting).  The
    public API here (``bytes_of_type`` / ``collective_stats`` /
    ``collective_chain_depth``) is unchanged and simply delegates; new
    callers should import from ``cs744_ddp_tpu.analysis`` directly.  The
    original regex implementation — print-format-sensitive, patched twice
    (metadata-string poisoning, sum-vs-max chain depth) — survives below
    as ``legacy_*`` functions ONLY as the oracle for the differential
    test (tests/test_analysis.py) that pins old == new on every committed
    fixture in tests/assets/hlo/.

Byte accounting convention (both implementations): for every collective
instruction we sum the RESULT buffer sizes (tuple elements included).
For an all-reduce that is the reduced tensor's size; for an all-gather it
is world x the input — the world-times-larger result is precisely the
gather tier's traffic amplification (see BASELINE.md "Gather-tier
traffic accounting").  Async pairs are counted once: the ``-start`` op
contributes the instance count (its result tuple also holds source
buffers, which would overcount bytes), the ``-done`` op contributes the
result bytes.
"""

from __future__ import annotations

import re
from typing import Dict

from ..analysis.stats import (bytes_of_type, collective_chain_depth,
                              collective_stats)

__all__ = ["bytes_of_type", "collective_stats", "collective_chain_depth",
           "legacy_bytes_of_type", "legacy_collective_stats",
           "legacy_collective_chain_depth"]


# ---------------------------------------------------------------------------
# Legacy regex implementation — differential-test oracle only.  Do not add
# callers; the maintained implementation lives in analysis/stats.py.
# ---------------------------------------------------------------------------

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
}

# `%name = <result-type> <collective-op>(...)`; -start before the bare op
# name so the alternation matches the longest form.  The `%` sigil is
# optional: some XLA versions / print options emit HLO text without it.
_COLL_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(?P<type>.+?)\s+"
    r"(?P<op>all-reduce-start|all-reduce-done|all-reduce"
    r"|all-gather-start|all-gather-done|all-gather"
    r"|reduce-scatter-start|reduce-scatter-done|reduce-scatter"
    r"|collective-permute-start|collective-permute-done|collective-permute"
    r"|all-to-all-start|all-to-all-done|all-to-all)\(")

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")


def legacy_bytes_of_type(type_str: str) -> int:
    """Regex oracle for :func:`analysis.stats.bytes_of_type`."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(type_str):
        if dtype not in _DTYPE_BYTES:
            continue  # e.g. token[] / opaque[]
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


# Computation headers come in two prints: optimized modules use
# `%name (params) -> type {`, pre-optimization modules bare `name {`.
_COMP_HEAD_RE = re.compile(
    r"^\s*(?:ENTRY\s+)?(?P<name>%?[\w.\-]+)\s*(?:\([^)]*\))?"
    r"\s*(?:->\s*[^{]*)?\{\s*$")
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?(?P<name>%?[\w.\-]+)\s*=\s*(?P<rhs>.+)$")
# First `word(` after the result type is the opcode (type tokens like
# f32[64,10]{1,0} never put a word directly before '(').
_OP_RE = re.compile(r"(?:^|\s)(?P<op>[a-z][\w\-]*)\(")
# Identifier tokens — the optimized print prefixes names with '%', the
# pre-optimization print doesn't; lookups strip the sigil.  Non-name tokens
# (dtypes, attribute keys) simply miss the def map and are ignored.
_REF_RE = re.compile(r"[%A-Za-z_][\w.\-]*")

# Debug annotations on the instruction RHS that can contain identifier-like
# tokens: `metadata={op_name="..." source_file="..."}` and bare string
# literals.  Strings are removed FIRST so a brace inside a quoted path
# cannot truncate the metadata match.
_STRING_RE = re.compile(r'"(?:[^"\\]|\\.)*"')
_METADATA_RE = re.compile(r"metadata=\{[^{}]*\}")


def _strip_annotations(rhs: str) -> str:
    return _METADATA_RE.sub("", _STRING_RE.sub("", rhs))


_COLL_BASES = ("all-reduce", "all-gather", "reduce-scatter",
               "collective-permute", "all-to-all")


def _collective_weight(op: str) -> int:
    if op.endswith("-done"):
        return 0
    return int(re.sub(r"-start$", "", op) in _COLL_BASES)


def legacy_collective_chain_depth(hlo_text: str) -> int:
    """Regex oracle for :func:`analysis.stats.collective_chain_depth`
    (same semantics: per-computation SSA def-use graph, async pairs
    counted on the start, operand chains and callee internals SUM)."""
    comps: Dict[str, Dict[str, tuple]] = {}
    cur: Dict[str, tuple] = {}
    cur_name = None
    for line in hlo_text.splitlines():
        head = _COMP_HEAD_RE.match(line)
        if head and line.rstrip().endswith("{") and "=" not in line:
            cur_name = head.group("name").lstrip("%")
            cur = comps.setdefault(cur_name, {})
            continue
        if line.strip() == "}":
            cur_name = None
            continue
        if cur_name is None:
            continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        op_m = _OP_RE.search(m.group("rhs"))
        if not op_m:
            continue
        refs = [r.lstrip("%")
                for r in _REF_RE.findall(_strip_annotations(m.group("rhs")))]
        cur[m.group("name").lstrip("%")] = (op_m.group("op"), refs)

    comp_depth: Dict[str, int] = {}

    def depth_of_comp(cname: str, stack=()) -> int:
        if cname in comp_depth:
            return comp_depth[cname]
        if cname in stack:   # recursive reference (shouldn't happen in HLO)
            return 0
        instrs = comps.get(cname, {})
        d: Dict[str, int] = {}
        best = 0
        for name, (op, refs) in instrs.items():
            w0 = _collective_weight(op)
            operand_chain = 0
            callee_depth = 0
            for r in refs:
                if r in d:
                    operand_chain = max(operand_chain, d[r])
                elif r in comps and r != cname:
                    callee_depth = max(callee_depth,
                                       depth_of_comp(r, stack + (cname,)))
            d[name] = w0 + operand_chain + callee_depth
            best = max(best, d[name])
        comp_depth[cname] = best
        return best

    return max((depth_of_comp(c) for c in comps), default=0)


def legacy_collective_stats(hlo_text: str) -> Dict:
    """Regex oracle for :func:`analysis.stats.collective_stats`."""
    ops: Dict[str, Dict[str, float]] = {}
    for line in hlo_text.splitlines():
        m = _COLL_RE.match(line)
        if not m:
            continue
        op = m.group("op")
        base = re.sub(r"-(start|done)$", "", op)
        entry = ops.setdefault(base, {"count": 0, "result_mib": 0.0})
        if not op.endswith("-done"):
            entry["count"] += 1
        if not op.endswith("-start"):
            entry["result_mib"] += legacy_bytes_of_type(m.group("type")) / 2**20
    for entry in ops.values():
        entry["result_mib"] = round(entry["result_mib"], 2)
    return {
        "ops": ops,
        "total_count": sum(e["count"] for e in ops.values()),
        "total_result_mib": round(
            sum(e["result_mib"] for e in ops.values()), 2),
    }
