"""Collective-op statistics parsed from compiled HLO text.

Feeds bench.py's ``spectrum`` section (VERDICT r3 items 3b/7): per-strategy
collective instruction counts and result-buffer bytes from the TPU v5e-8
AOT lowering — a static, wall-clock-noise-free record of each gradient-sync
tier's cost shape.  The reference's tiers differ exactly here: Part 2a pays
two sequential collectives per leaf with world x gather traffic
(``/root/reference/src/Part 2a/main.py:117-127``), Part 2b one all-reduce
per leaf (``Part 2b/main.py:116-119``), Part 3 a few fused bucket reduces
(``Part 3/main.py:61``).

Byte accounting convention: for every collective instruction we sum the
RESULT buffer sizes (tuple elements included).  For an all-reduce that is
the reduced tensor's size; for an all-gather it is world x the input — the
world-times-larger result is precisely the gather tier's traffic
amplification, so the numbers surface the fidelity question VERDICT item 7
asks about (symmetric all_gather vs the reference's root-link bottleneck;
see BASELINE.md "Gather-tier traffic accounting").  Async pairs are counted
once: the ``-start`` op contributes the instance count (its result tuple
also holds source buffers, which would overcount bytes), the ``-done`` op
contributes the result bytes.
"""

from __future__ import annotations

import re
from typing import Dict

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
}

# `%name = <result-type> <collective-op>(...)`; -start before the bare op
# name so the alternation matches the longest form.  The `%` sigil is
# optional: some XLA versions / print options emit HLO text without it, and
# requiring it would silently report zero collectives there (bench.py's
# _collect_spectrum additionally refuses to record all-zero stats for
# strategies that must contain collectives).
_COLL_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(?P<type>.+?)\s+"
    r"(?P<op>all-reduce-start|all-reduce-done|all-reduce"
    r"|all-gather-start|all-gather-done|all-gather"
    r"|reduce-scatter-start|reduce-scatter-done|reduce-scatter"
    r"|collective-permute-start|collective-permute-done|collective-permute"
    r"|all-to-all-start|all-to-all-done|all-to-all)\(")

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")


def bytes_of_type(type_str: str) -> int:
    """Total bytes of every ``dtype[dims]`` shape in an HLO result type
    (a bare shape or a tuple; layout/tiling annotations are ignored)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(type_str):
        if dtype not in _DTYPE_BYTES:
            continue  # e.g. token[] / opaque[]
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


# Computation headers come in two prints: optimized modules use
# `%name (params) -> type {`, pre-optimization modules bare `name {`.
_COMP_HEAD_RE = re.compile(
    r"^\s*(?:ENTRY\s+)?(?P<name>%?[\w.\-]+)\s*(?:\([^)]*\))?"
    r"\s*(?:->\s*[^{]*)?\{\s*$")
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?(?P<name>%?[\w.\-]+)\s*=\s*(?P<rhs>.+)$")
# First `word(` after the result type is the opcode (type tokens like
# f32[64,10]{1,0} never put a word directly before '(').
_OP_RE = re.compile(r"(?:^|\s)(?P<op>[a-z][\w\-]*)\(")
# Identifier tokens — the optimized print prefixes names with '%', the
# pre-optimization print doesn't; lookups strip the sigil.  Non-name tokens
# (dtypes, attribute keys) simply miss the def map and are ignored.
_REF_RE = re.compile(r"[%A-Za-z_][\w.\-]*")

# Debug annotations on the instruction RHS that can contain identifier-like
# tokens: `metadata={op_name="..." source_file="..."}` and bare string
# literals.  Without stripping them, a metadata op_name that happens to
# collide with an instruction (or computation) name fabricates a dependency
# edge and inflates collective_chain_depth.  Strings are removed FIRST so a
# brace inside a quoted path cannot truncate the metadata match; structural
# refs (`to_apply=reducer`, `body=loop_body`) sit outside both and survive.
_STRING_RE = re.compile(r'"(?:[^"\\]|\\.)*"')
_METADATA_RE = re.compile(r"metadata=\{[^{}]*\}")


def _strip_annotations(rhs: str) -> str:
    """RHS with string literals and ``metadata={...}`` blocks removed —
    what reference extraction may safely tokenize."""
    return _METADATA_RE.sub("", _STRING_RE.sub("", rhs))

_COLL_BASES = ("all-reduce", "all-gather", "reduce-scatter",
               "collective-permute", "all-to-all")


def _collective_weight(op: str) -> int:
    """1 for a collective instruction (async start/done pairs counted once,
    on the start), else 0."""
    if op.endswith("-done"):
        return 0
    return int(re.sub(r"-start$", "", op) in _COLL_BASES)


def collective_chain_depth(hlo_text: str) -> int:
    """Longest dependency chain of collectives in the module: the number of
    collectives that must execute SEQUENTIALLY (each consuming a value the
    previous produced), regardless of how many run in total.

    This is the latency SHAPE of a gradient-sync tier, statically: the
    gather tier chains two dependent collectives per parameter leaf behind
    a barrier chain (2 x 34 = 68 deep for VGG-11), the per-param all-reduce
    tier one per leaf (34), the bucketed ddp tier one per ~25 MB bucket
    (2) — the reference's Part 2a / 2b / 3 ordering
    (``/root/reference/src/Part 3/main.py:61`` vs ``Part 2b/main.py:116``),
    pinned even where wall-clock cannot be measured (tests/test_tpu_aot.py).

    Feed it the PRE-OPTIMIZATION module print
    (``lowered.compiler_ir(dialect="hlo").as_hlo_text()``): there the
    strategies' ``optimization_barrier`` chains are still data
    dependencies, so the depth is the sequencing the program semantically
    imposes on the scheduler.  The post-scheduling print is NOT meaningful
    input — barriers are dropped after scheduling and sequencing lives in
    instruction order (and collectives hide inside async-wrapper
    computations), so depth there undercounts.

    Computed per computation over the SSA def-use graph (defs precede uses
    in printed HLO); references to other computations (fusion bodies, while
    bodies, reducers) add that computation's own internal depth.
    """
    # Split the module into computations; names are stored sigil-stripped.
    comps: Dict[str, Dict[str, tuple]] = {}
    cur: Dict[str, tuple] = {}
    cur_name = None
    for line in hlo_text.splitlines():
        head = _COMP_HEAD_RE.match(line)
        if head and line.rstrip().endswith("{") and "=" not in line:
            cur_name = head.group("name").lstrip("%")
            cur = comps.setdefault(cur_name, {})
            continue
        if line.strip() == "}":
            cur_name = None
            continue
        if cur_name is None:
            continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        op_m = _OP_RE.search(m.group("rhs"))
        if not op_m:
            continue
        refs = [r.lstrip("%")
                for r in _REF_RE.findall(_strip_annotations(m.group("rhs")))]
        cur[m.group("name").lstrip("%")] = (op_m.group("op"), refs)

    comp_depth: Dict[str, int] = {}

    def depth_of_comp(cname: str, stack=()) -> int:
        if cname in comp_depth:
            return comp_depth[cname]
        if cname in stack:   # recursive reference (shouldn't happen in HLO)
            return 0
        instrs = comps.get(cname, {})
        d: Dict[str, int] = {}
        best = 0
        for name, (op, refs) in instrs.items():
            w0 = _collective_weight(op)
            # Operand chains and called-computation internals COMPOSE: the
            # callee runs after the instruction's operands are ready, so an
            # instruction whose deepest operand chain is A and whose called
            # computation (while body, reducer, fusion) is internally B
            # deep sits at A + B (+ its own weight) — taking max(A, B)
            # undercounts every collective chain that FEEDS a
            # collective-bearing called computation (pinned by
            # tests/test_hlo_stats.py).
            operand_chain = 0
            callee_depth = 0
            for r in refs:
                if r in d:
                    operand_chain = max(operand_chain, d[r])
                elif r in comps and r != cname:
                    callee_depth = max(callee_depth,
                                       depth_of_comp(r, stack + (cname,)))
            d[name] = w0 + operand_chain + callee_depth
            best = max(best, d[name])
        comp_depth[cname] = best
        return best

    return max((depth_of_comp(c) for c in comps), default=0)


def collective_stats(hlo_text: str) -> Dict:
    """{"ops": {op: {"count", "result_mib"}}, "total_count",
    "total_result_mib"} over every collective instruction in the module."""
    ops: Dict[str, Dict[str, float]] = {}
    for line in hlo_text.splitlines():
        m = _COLL_RE.match(line)
        if not m:
            continue
        op = m.group("op")
        base = re.sub(r"-(start|done)$", "", op)
        entry = ops.setdefault(base, {"count": 0, "result_mib": 0.0})
        if not op.endswith("-done"):
            entry["count"] += 1
        if not op.endswith("-start"):
            entry["result_mib"] += bytes_of_type(m.group("type")) / 2**20
    for entry in ops.values():
        entry["result_mib"] = round(entry["result_mib"], 2)
    return {
        "ops": ops,
        "total_count": sum(e["count"] for e in ops.values()),
        "total_result_mib": round(
            sum(e["result_mib"] for e in ops.values()), 2),
    }
