"""Collective-op statistics parsed from compiled HLO text.

Feeds bench.py's ``spectrum`` section (VERDICT r3 items 3b/7): per-strategy
collective instruction counts and result-buffer bytes from the TPU v5e-8
AOT lowering — a static, wall-clock-noise-free record of each gradient-sync
tier's cost shape.  The reference's tiers differ exactly here: Part 2a pays
two sequential collectives per leaf with world x gather traffic
(``/root/reference/src/Part 2a/main.py:117-127``), Part 2b one all-reduce
per leaf (``Part 2b/main.py:116-119``), Part 3 a few fused bucket reduces
(``Part 3/main.py:61``).

Byte accounting convention: for every collective instruction we sum the
RESULT buffer sizes (tuple elements included).  For an all-reduce that is
the reduced tensor's size; for an all-gather it is world x the input — the
world-times-larger result is precisely the gather tier's traffic
amplification, so the numbers surface the fidelity question VERDICT item 7
asks about (symmetric all_gather vs the reference's root-link bottleneck;
see BASELINE.md "Gather-tier traffic accounting").  Async pairs are counted
once: the ``-start`` op contributes the instance count (its result tuple
also holds source buffers, which would overcount bytes), the ``-done`` op
contributes the result bytes.
"""

from __future__ import annotations

import re
from typing import Dict

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
}

# `%name = <result-type> <collective-op>(...)`; -start before the bare op
# name so the alternation matches the longest form.  The `%` sigil is
# optional: some XLA versions / print options emit HLO text without it, and
# requiring it would silently report zero collectives there (bench.py's
# _collect_spectrum additionally refuses to record all-zero stats for
# strategies that must contain collectives).
_COLL_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(?P<type>.+?)\s+"
    r"(?P<op>all-reduce-start|all-reduce-done|all-reduce"
    r"|all-gather-start|all-gather-done|all-gather"
    r"|reduce-scatter-start|reduce-scatter-done|reduce-scatter"
    r"|collective-permute-start|collective-permute-done|collective-permute"
    r"|all-to-all-start|all-to-all-done|all-to-all)\(")

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")


def bytes_of_type(type_str: str) -> int:
    """Total bytes of every ``dtype[dims]`` shape in an HLO result type
    (a bare shape or a tuple; layout/tiling annotations are ignored)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(type_str):
        if dtype not in _DTYPE_BYTES:
            continue  # e.g. token[] / opaque[]
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def collective_stats(hlo_text: str) -> Dict:
    """{"ops": {op: {"count", "result_mib"}}, "total_count",
    "total_result_mib"} over every collective instruction in the module."""
    ops: Dict[str, Dict[str, float]] = {}
    for line in hlo_text.splitlines():
        m = _COLL_RE.match(line)
        if not m:
            continue
        op = m.group("op")
        base = re.sub(r"-(start|done)$", "", op)
        entry = ops.setdefault(base, {"count": 0, "result_mib": 0.0})
        if not op.endswith("-done"):
            entry["count"] += 1
        if not op.endswith("-start"):
            entry["result_mib"] += bytes_of_type(m.group("type")) / 2**20
    for entry in ops.values():
        entry["result_mib"] = round(entry["result_mib"], 2)
    return {
        "ops": ops,
        "total_count": sum(e["count"] for e in ops.values()),
        "total_result_mib": round(
            sum(e["result_mib"] for e in ops.values()), 2),
    }
