"""Utilities: timing/metrics instrumentation."""

from .metrics import Stopwatch, WindowedTimers             # noqa: F401
