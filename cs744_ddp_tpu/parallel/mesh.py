"""Runtime: multi-host bootstrap + device-mesh construction.

Replaces the reference's process-group bootstrap
(``init_process`` — ``/root/reference/src/Part 2a/main.py:148-153``: export
MASTER_ADDR/MASTER_PORT, ``dist.init_process_group('gloo', rank, world)``)
with the TPU-native equivalents:

  * ``jax.distributed.initialize(coordinator_address, num_processes,
    process_id)`` — DCN rendezvous; on TPU pods topology is auto-discovered.
  * a 1-D ``jax.sharding.Mesh`` over all chips, axis name ``"data"`` — the
    data-parallel axis every collective rides (ICI within a slice).

Unlike the reference (one OS process per worker, eager Gloo calls), the unit
of parallelism is the *device*: one process drives all its local chips and the
strategies are collectives inside one compiled SPMD program.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"


def initialize_distributed(coordinator: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None,
                           port: int = 6585) -> None:
    """Multi-host rendezvous (MASTER_ADDR:6585 ≙ coordinator:port).

    No-op when single-process (the reference's Part 1 case).  The hardcoded
    default port 6585 mirrors ``Part 2a/main.py:172``.
    """
    if (num_processes or 1) <= 1:
        return
    if coordinator is None:
        # The reference makes --master required (Part 2a/main.py:158-159);
        # silently training N independent copies would be wrong.
        raise ValueError("multi-process run (num_processes "
                         f"= {num_processes}) requires a coordinator address")
    # Cross-process collectives on the CPU backend need an implementation;
    # gloo — the reference's own backend (Part 2a/main.py:148) — is the
    # fitting choice.  Inert for TPU meshes (collectives ride ICI/DCN).
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except AttributeError as e:
        # Config renamed/absent on this JAX version: a CPU multi-process run
        # would fail at the first collective, so say why NOW; TPU meshes
        # don't consult it and proceed fine.
        import warnings
        warnings.warn(f"could not enable gloo CPU collectives ({e}); "
                      "multi-process CPU runs will fail at the first "
                      "collective, TPU runs are unaffected")
    addr = coordinator if ":" in coordinator else f"{coordinator}:{port}"
    jax.distributed.initialize(coordinator_address=addr,
                               num_processes=num_processes,
                               process_id=process_id)


def make_mesh(num_devices: Optional[int] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D data-parallel mesh over ``num_devices`` (default: all)."""
    if devices is None:
        devices = jax.devices()
        if num_devices is not None:
            if num_devices > len(devices):
                raise ValueError(
                    f"requested {num_devices} devices, have {len(devices)}")
            devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (DATA_AXIS,))


def probe_devices(mesh: Mesh) -> list:
    """Health-probe every device in ``mesh``: run a tiny computation on each
    and return the list of rank indices that FAILED it.

    This is the elastic coordinator's liveness check — after a
    ``RankDeathError`` (or any suspicion of a sick chip) it probes before
    deciding which rung of the degradation ladder applies: an empty list
    means the fault was transient (retry at the same world), a non-empty
    list names the ranks to exclude when shrinking.  On the CPU virtual
    mesh every device always passes; real failures are simulated by the
    ``rank_death`` chaos site, whose target rank the coordinator merges
    into this probe's result.
    """
    dead = []
    for rank, dev in enumerate(mesh.devices.flat):
        try:
            out = jax.device_put(np.int32(rank), dev)
            if int(out) != rank:
                dead.append(rank)
        except Exception:  # noqa: BLE001 - any failure marks the rank dead
            dead.append(rank)
    return dead


def shrink_mesh(mesh: Mesh, new_world: int, exclude: Sequence[int] = ()) \
        -> Mesh:
    """A 1-D mesh over the first ``new_world`` SURVIVING devices of ``mesh``.

    ``exclude`` lists dead rank indices (from ``probe_devices`` or the
    chaos plan); survivors keep their relative order so rank identities
    stay stable across the shrink — the resume planner's re-shard map
    depends only on (old_world, new_world), never on which physical chips
    remain.
    """
    flat = list(mesh.devices.flat)
    survivors = [d for r, d in enumerate(flat) if r not in set(exclude)]
    if new_world > len(survivors):
        raise ValueError(f"cannot shrink to world {new_world}: only "
                         f"{len(survivors)} of {len(flat)} devices survive")
    if new_world < 1:
        raise ValueError(f"new world must be >= 1, got {new_world}")
    return Mesh(np.asarray(survivors[:new_world]), (DATA_AXIS,))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for [global_batch, ...] arrays: split dim 0 over the mesh."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def put_global(array, sharding: NamedSharding) -> jax.Array:
    """Place a host array as a GLOBAL array under ``sharding``, safely on
    meshes that span multiple processes.

    Single-process: a plain ``device_put`` (the fast batched-transfer path).
    Multi-process: ``device_put`` of a host-global value raises on meshes
    containing non-addressable devices, so each process instead feeds only
    its addressable devices' index-slices via ``make_array_from_callback``.
    Every process passes the same host value — the framework's seed-identical
    invariant (SURVEY.md C12: the reference relies on identical seeds instead
    of a broadcast), which makes the per-process slices globally consistent.
    """
    if jax.process_count() == 1:
        return jax.device_put(array, sharding)
    array = np.asarray(array)
    return jax.make_array_from_callback(
        array.shape, sharding, lambda idx: array[idx])


def put_global_tree(tree, sharding: NamedSharding):
    """``put_global`` over every leaf of a pytree (e.g. a TrainState)."""
    return jax.tree.map(lambda a: put_global(a, sharding), tree)
