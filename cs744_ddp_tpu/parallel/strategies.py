"""The three gradient-synchronization strategies, as collective patterns.

Each strategy is a pure function ``(grads_pytree, axis_name) -> grads_pytree``
running *inside* a ``shard_map``-compiled SPMD program; the strategy
difference is the collective pattern XLA emits, mirroring the reference's
spectrum (SURVEY.md §2.3):

  * ``gather_scatter``  — reference Part 2a (``main.py:117-127``):
    per parameter, rank 0 gathers every worker's grad, means them, scatters
    the average back.  Here: per leaf, ``all_gather`` (a superset of
    gather-to-root on ICI), then the gathered stack is zeroed on every mesh
    position except 0 *before* the mean — so the only mean value that
    reaches the result is the one computed at the root (non-root positions
    reduce zeros) — and the root's mean is broadcast via ``psum``.  Two
    sequential collectives per leaf with root-located compute, preserving
    the deliberately-naive communication shape for honest benchmarking.
    (SPMD executes the same program text everywhere; "root-located" means
    the root's arithmetic is the only contribution to the output, exactly
    as rank 0's ``torch.mean`` is in the reference.)

  * ``per_param_psum``  — reference Part 2b (``main.py:116-119``):
    one all-reduce per parameter leaf, then divide by world size.  Here: one
    ``lax.psum`` per leaf (34 collectives for VGG-11+BN), no fusion.

  * ``bucketed_psum``   — reference Part 3 (``DDP(model)``, ``main.py:61``):
    DDP's bucketed fused reducer.  Here: leaves are flattened into ≤25 MB
    buckets (reverse registration order, like DDP) and each bucket is one
    fused ``psum``; XLA schedules the collectives asynchronously, giving the
    comm/compute overlap DDP gets from backward hooks.

  * ``local``           — reference Part 1: single process, no sync.

XLA note: the strategies are observably distinct at the StableHLO level
(34 vs 2 vs 1 collectives for VGG-11; gather_scatter keeps two DEPENDENT
collectives per leaf — asserted in tests/test_strategies.py).  After XLA
optimization, the all-reduce combiner merges independent psums — so at the
COMPILED level even the per-param strategy reaches DDP-grade fusion, with
bucketed_psum's pre-fusion bounding the combiner's worst case
(tests/test_tpu_aot.py asserts this on real v5e-8 TPU lowerings).
Comm/compute overlap on TPU belongs to XLA's latency-hiding scheduler
(async start/done splits appear where the compiler finds overlap, e.g. the
gather strategy's all-gather); nothing here hand-schedules what the
compiler already does.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from .bucketing import BucketPlan, DEFAULT_BUCKET_BYTES, flatten_to_buckets, \
    make_plan, unflatten_from_buckets

Strategy = Callable[[Any, str], Any]


def local(grads: Any, axis_name: str) -> Any:
    """No synchronization (single-worker Part-1 semantics)."""
    del axis_name
    return grads


def per_param_psum(grads: Any, axis_name: str) -> Any:
    """One all-reduce per leaf; sum then divide by world (Part 2b parity)."""
    world = lax.axis_size(axis_name)
    return jax.tree.map(lambda g: lax.psum(g, axis_name) / world, grads)


def gather_scatter(grads: Any, axis_name: str) -> Any:
    """Root-mediated gather -> mean-on-root -> broadcast (Part 2a parity)."""
    idx = lax.axis_index(axis_name)

    def leaf(g):
        gathered = lax.all_gather(g, axis_name)          # collective 1 (gather)
        # Mask BEFORE the mean: non-root positions reduce zeros, so the
        # mean that survives the psum is computed at mesh position 0 only —
        # root-located compute, like rank 0's torch.mean in the reference.
        rooted = jnp.where(idx == 0, gathered, jnp.zeros_like(gathered))
        mean = jnp.mean(rooted, axis=0)
        return lax.psum(mean, axis_name)                 # collective 2 (scatter/bcast)

    return jax.tree.map(leaf, grads)


def bucketed_psum(grads: Any, axis_name: str, *,
                  plan: Optional[BucketPlan] = None,
                  bucket_bytes: int = DEFAULT_BUCKET_BYTES) -> Any:
    """Bucketed fused all-reduce — the DDP-equivalent performance tier."""
    if plan is None:
        plan = make_plan(grads, bucket_bytes)
    world = lax.axis_size(axis_name)
    buckets = flatten_to_buckets(grads, plan)
    reduced = [lax.psum(b, axis_name) / world for b in buckets]
    return unflatten_from_buckets(reduced, plan)


STRATEGIES = {
    "single": local,
    "gather": gather_scatter,
    "allreduce": per_param_psum,
    "ddp": bucketed_psum,
}


def get_strategy(name: str, bucket_bytes: int = DEFAULT_BUCKET_BYTES) -> Strategy:
    """Resolve a CLI strategy name to a (grads, axis) -> grads function."""
    name = name.lower()
    if name not in STRATEGIES:
        raise ValueError(
            f"unknown strategy {name!r}; expected one of {sorted(STRATEGIES)}")
    if name == "ddp":
        return partial(bucketed_psum, bucket_bytes=bucket_bytes)
    return STRATEGIES[name]
