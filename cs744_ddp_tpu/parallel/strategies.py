"""The three gradient-synchronization strategies, as collective patterns.

Each strategy is a pure function ``(grads_pytree, axis_name) -> grads_pytree``
running *inside* a ``shard_map``-compiled SPMD program; the strategy
difference is the collective pattern XLA emits, mirroring the reference's
spectrum (SURVEY.md §2.3):

  * ``gather_scatter``  — reference Part 2a (``main.py:117-127``):
    per parameter, rank 0 gathers every worker's grad, means them, scatters
    the average back — one blocking gather + one blocking scatter per leaf,
    sequentially.  Here: a ROOT-EQUIVALENT COMM PATTERN WITH REPLICATED
    COMPUTE — per leaf, ``all_gather`` (a superset of gather-to-root on
    ICI), then the gathered stack is zeroed on every mesh position except 0
    before the mean, and the root's mean is broadcast via ``psum``.  In
    SPMD every position executes the (cheap) masked mean; what matches the
    reference's rank-0 bottleneck is the *communication* shape — two
    sequential collectives per leaf — which is the term that dominates its
    cost model.  Leaves are chained through ``optimization_barrier`` so the
    per-leaf collective pairs stay *sequential* in the compiled TPU
    program, preserving the deliberately-naive blocking-loop cost model
    for honest benchmarking.

  * ``per_param_psum``  — reference Part 2b (``main.py:116-119``):
    one blocking all-reduce per parameter leaf, sequentially, no fusion.
    Here: one ``lax.psum`` per leaf (34 collectives for VGG-11+BN), chained
    through ``optimization_barrier`` — without the chain XLA's all-reduce
    combiner would quietly rewrite this tier into the fused one, erasing
    the Part-2b/Part-3 cost distinction the reference exists to measure.

  * ``bucketed_psum``   — reference Part 3 (``DDP(model)``, ``main.py:61``):
    DDP's bucketed fused reducer.  torch materialises ~25 MB flat buffers
    because NCCL wants one contiguous launch; XLA's native fused form is
    the *variadic* all-reduce (exactly what its all-reduce combiner
    produces), so the TPU-native bucket is one multi-operand ``lax.psum``
    over the bucket's leaves — one fused collective per bucket with ZERO
    copy overhead (no flatten/concat/slice round-trip through HBM).
    Buckets are formed in reverse registration order (grads become ready
    last-layer-first) and chained bucket-to-bucket, mirroring DDP's single
    in-order comm stream; comm/compute overlap within the step belongs to
    XLA's latency-hiding scheduler.

  * ``local``           — reference Part 1: single process, no sync.

XLA note: the barrier chains are what keep the tiers *observably distinct
in the compiled TPU program* (SURVEY.md §7 "hard parts"): on the v5e-8
lowering, ``allreduce`` compiles to one all-reduce per leaf while ``ddp``
compiles to bucket-count fused all-reduces (asserted in
tests/test_tpu_aot.py).  The CPU backend used by the unit tests strips
optimization barriers and combines everything — there the tiers are
asserted distinct at the StableHLO level instead
(tests/test_strategies.py), and their wall-clock converges, which is also
asserted: the fused tier must never LOSE to the per-param tier.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
from jax import lax

from .bucketing import BucketPlan, DEFAULT_BUCKET_BYTES, make_plan

Strategy = Callable[[Any, str], Any]


def _axis_size(axis_name: str) -> int:
    """Static mesh-axis size (``lax.axis_size`` where it exists; jax 0.4.x
    spells it ``jax.core.axis_frame``).  Static on purpose: a ``psum(1)``
    spelling would add a collective and distort the strategy spectrum."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    size = jax.core.axis_frame(axis_name)
    return getattr(size, "size", size)


def _after(x, dep):
    """Order ``x``'s consumers after ``dep`` (sequential-collective chains).

    ``optimization_barrier`` makes ``x`` data-depend on ``dep``, so the
    collective fed by ``x`` cannot start — nor be combiner-merged — before
    the collective that produced ``dep`` completes, reproducing the
    reference's blocking per-parameter loops in compiled form."""
    if dep is None:
        return x
    x, _ = lax.optimization_barrier((x, dep))
    return x


def local(grads: Any, axis_name: str) -> Any:
    """No synchronization (single-worker Part-1 semantics)."""
    del axis_name
    return grads


def per_param_psum(grads: Any, axis_name: str) -> Any:
    """One all-reduce per leaf, sequentially; sum / world (Part 2b parity)."""
    world = _axis_size(axis_name)
    leaves, treedef = jax.tree.flatten(grads)
    out: List[Any] = []
    prev = None
    for g in leaves:
        s = lax.psum(_after(g, prev), axis_name)
        out.append(s / world)
        prev = s
    return jax.tree.unflatten(treedef, out)


def gather_scatter(grads: Any, axis_name: str) -> Any:
    """Part 2a parity: root-equivalent comm pattern, replicated compute.

    Two sequential collectives per leaf (all_gather, then psum of the
    root-masked mean) reproduce the reference's gather->mean->scatter
    communication cost; the masked mean itself runs on every position
    (SPMD), not only on the root — see the module docstring."""
    idx = lax.axis_index(axis_name)
    leaves, treedef = jax.tree.flatten(grads)
    out: List[Any] = []
    prev = None
    for g in leaves:
        gathered = lax.all_gather(_after(g, prev), axis_name)  # collective 1
        # Mask BEFORE the mean: non-root positions reduce zeros, so the
        # mean that survives the psum is computed at mesh position 0 only —
        # root-located compute, like rank 0's torch.mean in the reference.
        rooted = jnp.where(idx == 0, gathered, jnp.zeros_like(gathered))
        mean = jnp.mean(rooted, axis=0)
        s = lax.psum(mean, axis_name)                          # collective 2
        out.append(s)
        prev = s
    return jax.tree.unflatten(treedef, out)


def bucketed_psum(grads: Any, axis_name: str, *,
                  plan: Optional[BucketPlan] = None,
                  bucket_bytes: int = DEFAULT_BUCKET_BYTES) -> Any:
    """Bucketed fused all-reduce — the DDP-equivalent performance tier.

    One variadic ``psum`` per bucket: XLA lowers the multi-operand reduce
    to a single fused all-reduce (its combiner's own canonical form), so
    each bucket costs exactly one collective and no data movement beyond
    the wire transfer itself."""
    if plan is None:
        plan = make_plan(grads, bucket_bytes)
    world = _axis_size(axis_name)
    leaves = jax.tree.leaves(grads)
    out: List[Any] = [None] * len(leaves)
    prev = ()
    for bucket in plan.buckets:
        gs = tuple(leaves[i] for i in bucket)
        if prev:
            # Chain on the WHOLE previous bucket: every one of this
            # bucket's reduces must follow every one of the previous
            # bucket's, or the combiner could legally merge collectives
            # across the bucket boundary.
            gs = lax.optimization_barrier(gs + prev)[:len(gs)]
        reduced = lax.psum(gs, axis_name)
        for i, r in zip(bucket, reduced):
            out[i] = r / world
        prev = tuple(reduced)
    return jax.tree.unflatten(plan.treedef, out)


STRATEGIES = {
    "single": local,
    "gather": gather_scatter,
    "allreduce": per_param_psum,
    "ddp": bucketed_psum,
}


def get_strategy(name: str, bucket_bytes: int = DEFAULT_BUCKET_BYTES) -> Strategy:
    """Resolve a CLI strategy name to a (grads, axis) -> grads function."""
    name = name.lower()
    if name not in STRATEGIES:
        raise ValueError(
            f"unknown strategy {name!r}; expected one of {sorted(STRATEGIES)}")
    if name == "ddp":
        return partial(bucketed_psum, bucket_bytes=bucket_bytes)
    return STRATEGIES[name]
