"""The three gradient-synchronization strategies, as collective patterns.

Each strategy is a pure function ``(grads_pytree, axis_name) -> grads_pytree``
running *inside* a ``shard_map``-compiled SPMD program; the strategy
difference is the collective pattern XLA emits, mirroring the reference's
spectrum (SURVEY.md §2.3):

  * ``gather_scatter``  — reference Part 2a (``main.py:117-127``):
    per parameter, rank 0 gathers every worker's grad, means them, scatters
    the average back — one blocking gather + one blocking scatter per leaf,
    sequentially.  Here: a ROOT-EQUIVALENT COMM PATTERN WITH REPLICATED
    COMPUTE — per leaf, ``all_gather`` (a superset of gather-to-root on
    ICI), then the gathered stack is zeroed on every mesh position except 0
    before the mean, and the root's mean is broadcast via ``psum``.  In
    SPMD every position executes the (cheap) masked mean; what matches the
    reference's rank-0 bottleneck is the *communication* shape — two
    sequential collectives per leaf — which is the term that dominates its
    cost model.  Leaves are chained through ``optimization_barrier`` so the
    per-leaf collective pairs stay *sequential* in the compiled TPU
    program, preserving the deliberately-naive blocking-loop cost model
    for honest benchmarking.

  * ``per_param_psum``  — reference Part 2b (``main.py:116-119``):
    one blocking all-reduce per parameter leaf, sequentially, no fusion.
    Here: one ``lax.psum`` per leaf (34 collectives for VGG-11+BN), chained
    through ``optimization_barrier`` — without the chain XLA's all-reduce
    combiner would quietly rewrite this tier into the fused one, erasing
    the Part-2b/Part-3 cost distinction the reference exists to measure.

  * ``bucketed_psum``   — reference Part 3 (``DDP(model)``, ``main.py:61``):
    DDP's bucketed fused reducer.  torch materialises ~25 MB flat buffers
    because NCCL wants one contiguous launch; XLA's native fused form is
    the *variadic* all-reduce (exactly what its all-reduce combiner
    produces), so the TPU-native bucket is one multi-operand ``lax.psum``
    over the bucket's leaves — one fused collective per bucket with ZERO
    copy overhead (no flatten/concat/slice round-trip through HBM).
    Buckets are formed in reverse registration order (grads become ready
    last-layer-first) and chained bucket-to-bucket, mirroring DDP's single
    in-order comm stream; comm/compute overlap within the step belongs to
    XLA's latency-hiding scheduler.

  * ``local``           — reference Part 1: single process, no sync.

Round 9 extends the ladder past the reference (ROADMAP item 3) with an
overlap tier and three compressed tiers:

  * ``overlapped_ddp``  — the ddp bucket plan WITHOUT the inter-bucket
    barrier chain: each bucket's fused all-reduce is gated only by its own
    gradients (bucketing.make_schedule), so comm overlaps the remaining
    backward (torch DDP's backward-hook launches).
  * ``CompressedPsum``  — bf16/int8 quantized all-reduce with per-worker
    error-feedback residuals carried in the optimizer state (>=2x / >=4x
    fewer collective bytes; audit-certified).
  * ``PowerSGD``        — rank-r low-rank factor all-reduce with warm-started
    Q and error feedback (>=8x on VGG-11's conv/fc leaves at rank 4);
    non-matrix leaves ride the bf16 path.

The compressed tiers are STATEFUL: callables with ``stateful = True``
whose ``init_comm(params_like, world)`` state (residuals, Q factors)
lives in ``SGDState.comm``, stacked per worker on a leading mesh axis and
sharded over the data axis through every compiled program — see
train/step.py (threading) and train/checkpoint.py (bitwise resume).

XLA note: the barrier chains are what keep the tiers *observably distinct
in the compiled TPU program* (SURVEY.md §7 "hard parts"): on the v5e-8
lowering, ``allreduce`` compiles to one all-reduce per leaf while ``ddp``
compiles to bucket-count fused all-reduces (asserted in
tests/test_tpu_aot.py).  The CPU backend used by the unit tests strips
optimization barriers and combines everything — there the tiers are
asserted distinct at the StableHLO level instead
(tests/test_strategies.py), and their wall-clock converges, which is also
asserted: the fused tier must never LOSE to the per-param tier.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .bucketing import (BucketPlan, DEFAULT_BUCKET_BYTES, make_plan,
                        make_schedule)

Strategy = Callable[[Any, str], Any]

# Low-rank compression rank (PowerSGD --compress-rank default): rank 4 is
# the paper's sweet spot for conv nets (Vogels et al. 2019, table 2) and
# what the >=8x byte contract in analysis/audit.py is certified at.
DEFAULT_COMPRESS_RANK = 4


def _axis_size(axis_name: str) -> int:
    """Static mesh-axis size (``lax.axis_size`` where it exists; jax 0.4.x
    spells it ``jax.core.axis_frame``).  Static on purpose: a ``psum(1)``
    spelling would add a collective and distort the strategy spectrum."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    size = jax.core.axis_frame(axis_name)
    return getattr(size, "size", size)


def _after(x, dep):
    """Order ``x``'s consumers after ``dep`` (sequential-collective chains).

    ``optimization_barrier`` makes ``x`` data-depend on ``dep``, so the
    collective fed by ``x`` cannot start — nor be combiner-merged — before
    the collective that produced ``dep`` completes, reproducing the
    reference's blocking per-parameter loops in compiled form."""
    if dep is None:
        return x
    x, _ = lax.optimization_barrier((x, dep))
    return x


def local(grads: Any, axis_name: str) -> Any:
    """No synchronization (single-worker Part-1 semantics)."""
    del axis_name
    return grads


def per_param_psum(grads: Any, axis_name: str) -> Any:
    """One all-reduce per leaf, sequentially; sum / world (Part 2b parity)."""
    world = _axis_size(axis_name)
    leaves, treedef = jax.tree.flatten(grads)
    out: List[Any] = []
    prev = None
    for g in leaves:
        s = lax.psum(_after(g, prev), axis_name)
        out.append(s / world)
        prev = s
    return jax.tree.unflatten(treedef, out)


def gather_scatter(grads: Any, axis_name: str) -> Any:
    """Part 2a parity: root-equivalent comm pattern, replicated compute.

    Two sequential collectives per leaf (all_gather, then psum of the
    root-masked mean) reproduce the reference's gather->mean->scatter
    communication cost; the masked mean itself runs on every position
    (SPMD), not only on the root — see the module docstring."""
    idx = lax.axis_index(axis_name)
    leaves, treedef = jax.tree.flatten(grads)
    out: List[Any] = []
    prev = None
    for g in leaves:
        gathered = lax.all_gather(_after(g, prev), axis_name)  # collective 1
        # Mask BEFORE the mean: non-root positions reduce zeros, so the
        # mean that survives the psum is computed at mesh position 0 only —
        # root-located compute, like rank 0's torch.mean in the reference.
        rooted = jnp.where(idx == 0, gathered, jnp.zeros_like(gathered))
        mean = jnp.mean(rooted, axis=0)
        s = lax.psum(mean, axis_name)                          # collective 2
        out.append(s)
        prev = s
    return jax.tree.unflatten(treedef, out)


def bucketed_psum(grads: Any, axis_name: str, *,
                  plan: Optional[BucketPlan] = None,
                  bucket_bytes: int = DEFAULT_BUCKET_BYTES) -> Any:
    """Bucketed fused all-reduce — the DDP-equivalent performance tier.

    One variadic ``psum`` per bucket: XLA lowers the multi-operand reduce
    to a single fused all-reduce (its combiner's own canonical form), so
    each bucket costs exactly one collective and no data movement beyond
    the wire transfer itself."""
    if plan is None:
        plan = make_plan(grads, bucket_bytes)
    world = _axis_size(axis_name)
    leaves = jax.tree.leaves(grads)
    out: List[Any] = [None] * len(leaves)
    prev = ()
    for bucket in plan.buckets:
        gs = tuple(leaves[i] for i in bucket)
        if prev:
            # Chain on the WHOLE previous bucket: every one of this
            # bucket's reduces must follow every one of the previous
            # bucket's, or the combiner could legally merge collectives
            # across the bucket boundary.
            gs = lax.optimization_barrier(gs + prev)[:len(gs)]
        reduced = lax.psum(gs, axis_name)
        for i, r in zip(bucket, reduced):
            out[i] = r / world
        prev = tuple(reduced)
    return jax.tree.unflatten(plan.treedef, out)


def overlapped_ddp(grads: Any, axis_name: str, *,
                   plan: Optional[BucketPlan] = None,
                   bucket_bytes: int = DEFAULT_BUCKET_BYTES) -> Any:
    """Bucketed fused all-reduce with NO cross-bucket ordering — the
    overlap tier (torch DDP's backward-hook launches, ROADMAP item 3a).

    Same bucket plan and one variadic ``psum`` per bucket as
    ``bucketed_psum``, but the inter-bucket ``optimization_barrier`` chain
    is gone: each bucket's collective depends only on its own gradients
    (its gate leaf, bucketing.make_schedule), so XLA's latency-hiding
    scheduler is free to issue bucket k's all-reduce while the backward
    for earlier layers is still computing — comm overlaps compute instead
    of forming a single post-backward chain.  Certified statically by
    analysis/audit.py's overlap rule: same fused-collective count as the
    ddp tier, collective chain depth 1 (no collective consumes another's
    result), and at least one collective whose operand cone excludes part
    of the backward (it can start before backward finishes)."""
    if plan is None:
        plan = make_plan(grads, bucket_bytes)
    sched = make_schedule(plan)
    world = _axis_size(axis_name)
    leaves = jax.tree.leaves(grads)
    out: List[Any] = [None] * len(leaves)
    for b in sched.order:
        gs = tuple(leaves[i] for i in plan.buckets[b])
        reduced = lax.psum(gs, axis_name)
        for i, r in zip(plan.buckets[b], reduced):
            out[i] = r / world
    return jax.tree.unflatten(plan.treedef, out)


def _stack_zeros_like(params_like: Any, world: int) -> Any:
    """Per-worker f32 state stacked on a leading mesh axis: the global
    array is (world, *leaf.shape), carried in the optimizer state and
    sharded P(DATA_AXIS) through the compiled programs (train/step.py
    _opt_specs) so each mesh position reads and writes only its own
    slice — error-feedback residuals are genuinely per-worker."""
    return jax.tree.map(
        lambda p: jnp.zeros((world,) + tuple(p.shape), jnp.float32),
        params_like)


def _local(comm_leaf):
    """A worker's own slice of stacked per-worker comm state (the leading
    mesh axis arrives sharded, so the local block is (1, ...))."""
    return comm_leaf[0]


class CompressedPsum:
    """bf16 / int8 quantized all-reduce with error feedback — ROADMAP 3b.

    Per leaf: ``v = g + residual``; quantize ``v``; all-reduce the
    QUANTIZED values (that is the whole point: the wire carries 2 bytes
    (bf16) or 1 byte (int8) per element instead of 4); dequantize the sum;
    the new residual is ``v - dequant(quant(v))`` — the part this worker
    failed to transmit, re-injected next step so quantization error
    accumulates into the trajectory instead of being lost (Deep Gradient
    Compression / EF-SGD; PAPERS.md).  Residuals are per-worker state in
    the optimizer pytree (``init_comm``), so checkpoints carry them and
    preemption resume stays bitwise (tests/test_ft.py).

    int8 needs a shared scale: per-leaf |v|-maxima are packed into ONE
    vector and pmax'd (a single extra scalar-vector collective), then each
    worker quantizes to ``clip(round(v / scale), -L, L)`` with ``L =
    127 // world`` and ``scale = amax / L`` — per-worker wire values stay
    within +-L, so the summed int8 wire value is bounded by world * L <=
    127 and cannot overflow (a bare ``round`` at scale amax*world/127
    would: world workers at +amax round to world * round(127/world) =
    128 > 127 at world 8, wrapping the sum negative).  Clipped mass lands
    in the residual like any other quantization error.  Worlds beyond 127
    would need a wider wire type; every mesh here is far below that.

    Called with ``comm=None`` (the elastic tail path, where the window's
    fixed-tree combine owns the reduction and no residual state is
    threaded), compression still applies but error feedback is off —
    documented degradation, not an error.
    """

    stateful = True

    def __init__(self, qdtype: str = "bf16"):
        if qdtype not in ("bf16", "int8"):
            raise ValueError(f"qdtype must be bf16 or int8, got {qdtype!r}")
        self.qdtype = qdtype

    @property
    def name(self) -> str:
        return f"compress-{self.qdtype}"

    def init_comm(self, params_like: Any, world: int) -> Any:
        return {"residual": _stack_zeros_like(params_like, world)}

    def __call__(self, grads: Any, axis_name: str, comm: Any = None):
        world = _axis_size(axis_name)
        leaves, treedef = jax.tree.flatten(grads)
        if comm is None:
            vs = [g.astype(jnp.float32) for g in leaves]
        else:
            rs = jax.tree.leaves(comm["residual"])
            vs = [g.astype(jnp.float32) + _local(r)
                  for g, r in zip(leaves, rs)]

        prev = None
        limit = max(1, 127 // world)
        if self.qdtype == "int8":
            # One packed pmax shares every leaf's scale (see class doc).
            amax = jnp.stack([jnp.max(jnp.abs(v)) for v in vs])
            amax = lax.pmax(amax, axis_name)
            scales = jnp.where(amax > 0.0, amax / limit, 1.0)
            prev = scales

        out: List[Any] = []
        new_rs: List[Any] = []
        for i, (g, v) in enumerate(zip(leaves, vs)):
            if self.qdtype == "bf16":
                q = _after(v, prev).astype(jnp.bfloat16)
                s = lax.psum(q, axis_name)
                sent = q.astype(jnp.float32)
                avg = s.astype(jnp.float32) / world
                prev = s
            else:
                q = jnp.clip(jnp.round(_after(v, prev) / scales[i]),
                             -limit, limit).astype(jnp.int8)
                s = lax.psum(q, axis_name)
                sent = q.astype(jnp.float32) * scales[i]
                avg = s.astype(jnp.float32) * scales[i] / world
                prev = s
            out.append(avg.astype(g.dtype))
            new_rs.append((v - sent)[None])
        new_comm = None if comm is None else {
            "residual": jax.tree.unflatten(treedef, new_rs)}
        return jax.tree.unflatten(treedef, out), new_comm


def _orthonormalize(p: jax.Array, eps: float = 1e-8) -> jax.Array:
    """Deterministic modified Gram-Schmidt over the (few) columns of a
    tall matrix; replicated inputs give bitwise-replicated outputs (no
    pivoting, no randomized algorithm).

    A column that is numerically inside the span of the earlier ones is
    DROPPED to zero, not normalized: after the cancellation the remainder
    is amplified rounding noise with a large component along the earlier
    columns, and normalizing it would double-count those directions in
    the ``P @ Q'^T`` reconstruction (a rank-deficient gradient would come
    back scaled ~k x, k the column multiplicity)."""
    cols = []
    for i in range(p.shape[1]):
        c = p[:, i]
        ref = jnp.linalg.norm(c)
        for u in cols:
            c = c - jnp.dot(u, c) * u
        n = jnp.linalg.norm(c)
        keep = n > jnp.maximum(ref * 1e-5, eps)
        c = jnp.where(keep, c / jnp.where(keep, n, 1.0), 0.0)
        cols.append(c)
    return jnp.stack(cols, axis=1)


class PowerSGD:
    """Low-rank gradient compression (Vogels et al. 2019) — ROADMAP 3b.

    Per matrix leaf (reshaped to (m, n) = (prod(shape[:-1]), shape[-1])):
    all-reduce the rank-r factors ``P = mean(M @ Q)`` and ``Q' = mean(M^T
    @ P)`` instead of M itself — r(m+n) wire floats instead of m*n, >=8x
    for VGG-11's conv/fc leaves at the default rank 4.  P is
    orthonormalized (modified Gram-Schmidt, deterministic) before the
    back-projection; Q is warm-started across steps in the comm state, so
    the power iteration converges over the run.  The decompressed update
    is ``P @ Q'^T`` (replicated: both factors come out of psums); error
    feedback keeps ``M - P @ Q'^T`` per worker, like CompressedPsum.

    Leaves where low-rank doesn't pay — vectors (biases, BN scales) and
    matrices with r(m+n) >= m*n — fall back to the bf16 compressed path
    inline.  Q's cold start is a fixed-key normal draw per leaf, identical
    on every worker (and across runs: the key depends only on the leaf
    index), so the whole strategy is deterministic.
    """

    stateful = True
    name = "powersgd"

    def __init__(self, rank: int = DEFAULT_COMPRESS_RANK):
        if rank < 1:
            raise ValueError(f"compress rank must be >= 1, got {rank}")
        self.rank = int(rank)

    def _low_rank(self, shape) -> bool:
        if len(shape) < 2:
            return False
        m = 1
        for d in shape[:-1]:
            m *= int(d)
        n = int(shape[-1])
        return self.rank * (m + n) < m * n

    def _q_init(self, i: int, n: int) -> jax.Array:
        key = jax.random.fold_in(jax.random.PRNGKey(0x9D5C), i)
        return jax.random.normal(key, (n, self.rank), jnp.float32)

    def init_comm(self, params_like: Any, world: int) -> Any:
        leaves = jax.tree.leaves(params_like)
        qs = {}
        for i, p in enumerate(leaves):
            if self._low_rank(p.shape):
                q = self._q_init(i, int(p.shape[-1]))
                # Stacked like the residuals (every worker's slice holds
                # the same replicated Q) so ONE pytree spec covers the
                # whole comm state — see _stack_zeros_like.
                qs[f"{i:03d}"] = jnp.repeat(q[None], world, axis=0)
        return {"residual": _stack_zeros_like(params_like, world), "q": qs}

    def __call__(self, grads: Any, axis_name: str, comm: Any = None):
        world = _axis_size(axis_name)
        leaves, treedef = jax.tree.flatten(grads)
        rs = (jax.tree.leaves(comm["residual"])
              if comm is not None else [None] * len(leaves))

        out: List[Any] = [None] * len(leaves)
        new_rs: List[Any] = [None] * len(leaves)
        new_qs = {}
        prev = None
        for i, (g, r) in enumerate(zip(leaves, rs)):
            v = g.astype(jnp.float32)
            if r is not None:
                v = v + _local(r)
            if self._low_rank(g.shape):
                m_rows = v.size // v.shape[-1]
                mat = v.reshape(m_rows, v.shape[-1])
                if comm is not None:
                    q = _local(comm["q"][f"{i:03d}"])
                else:
                    q = self._q_init(i, int(g.shape[-1]))
                p = lax.psum(_after(mat @ q, prev), axis_name) / world
                p = _orthonormalize(p)
                new_q = lax.psum(mat.T @ p, axis_name) / world
                approx = p @ new_q.T
                out[i] = approx.reshape(g.shape).astype(g.dtype)
                new_rs[i] = (mat - approx).reshape(g.shape)[None]
                new_qs[f"{i:03d}"] = new_q[None]
                prev = new_q
            else:
                # compressed_psum bf16 fallback, inline and chained.
                q16 = _after(v, prev).astype(jnp.bfloat16)
                s = lax.psum(q16, axis_name)
                out[i] = (s.astype(jnp.float32) / world).astype(g.dtype)
                new_rs[i] = (v - q16.astype(jnp.float32))[None]
                prev = s
        new_comm = None if comm is None else {
            "residual": jax.tree.unflatten(treedef, new_rs), "q": new_qs}
        return jax.tree.unflatten(treedef, out), new_comm


def reshard_comm(comm: Any, new_world: int) -> Any:
    """Map an (old_world, ...)-stacked comm pytree onto ``new_world``
    positions — the elastic-resume world resize (train/loop.py).

    Residuals reshard SUM-conservingly: each old worker's residual is mass
    the collective has not yet delivered, so the total is split evenly,
    ``r_new[i] = sum_old(r) / new_world`` — what error feedback re-injects
    into training is invariant to the resize.  Warm-start Q factors hold
    identical replicated content per slice (PowerSGD.init_comm), so the
    mean slice is repeated.  Host-side numpy on purpose: this runs once
    per resume, before the state is committed to the new mesh."""

    def _sum_split(a):
        a = np.asarray(a, dtype=np.float32)
        total = a.sum(axis=0, keepdims=True)
        return np.repeat(total / new_world, new_world, axis=0)

    def _mean_repeat(a):
        a = np.asarray(a, dtype=np.float32)
        return np.repeat(a.mean(axis=0, keepdims=True), new_world, axis=0)

    out = dict(comm)
    out["residual"] = jax.tree.map(_sum_split, comm["residual"])
    if "q" in comm:
        out["q"] = jax.tree.map(_mean_repeat, comm["q"])
    return out


STRATEGIES = {
    "single": local,
    "gather": gather_scatter,
    "allreduce": per_param_psum,
    "ddp": bucketed_psum,
    "overlap": overlapped_ddp,
    "compress-bf16": CompressedPsum("bf16"),
    "compress-int8": CompressedPsum("int8"),
    "powersgd": PowerSGD(),
}


def get_strategy(name: str, bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                 compress_rank: int = DEFAULT_COMPRESS_RANK) -> Strategy:
    """Resolve a CLI strategy name to a gradient-sync callable.

    Stateless strategies are ``(grads, axis) -> grads`` functions; the
    compressed tiers are callables with ``stateful = True`` and an
    ``init_comm(params_like, world)`` hook whose state rides in
    ``SGDState.comm`` (train/step.py apply_strategy dispatches on the
    attribute)."""
    name = name.lower()
    if name not in STRATEGIES:
        raise ValueError(
            f"unknown strategy {name!r}; expected one of {sorted(STRATEGIES)}")
    if name == "ddp":
        return partial(bucketed_psum, bucket_bytes=bucket_bytes)
    if name == "overlap":
        return partial(overlapped_ddp, bucket_bytes=bucket_bytes)
    if name == "powersgd" and compress_rank != DEFAULT_COMPRESS_RANK:
        return PowerSGD(compress_rank)
    return STRATEGIES[name]
