"""Parallelism: mesh runtime, gradient-sync strategies, bucketing."""

from . import bucketing, mesh, strategies                      # noqa: F401
from .mesh import DATA_AXIS, batch_sharding, make_mesh         # noqa: F401
from .strategies import STRATEGIES, get_strategy               # noqa: F401
