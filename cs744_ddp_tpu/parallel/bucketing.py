"""Gradient bucketing: group parameter-gradient leaves into size-bounded
fusion buckets.

This is the TPU-native analogue of torch DDP's C++ reducer bucketing
(reference: ``DDP(model)`` at ``/root/reference/src/Part 3/main.py:61``; the
reducer groups gradients into ~25 MB buckets and all-reduces each bucket as
one flat tensor).  torch flattens buckets into contiguous buffers because
NCCL wants one launch over one buffer; XLA's fused collective is the
*variadic* all-reduce, so here a bucket is just a leaf grouping — the plan
is computed once from the pytree's shapes (host side) and each bucket
becomes one multi-operand ``lax.psum`` (strategies.bucketed_psum), one
fused XLA AllReduce with no flatten/unflatten copies.

Like DDP, leaves are bucketed in *reverse* registration order (gradients
become ready last-layer-first during backward).
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Tuple

import jax
import numpy as np

DEFAULT_BUCKET_BYTES = 25 * 2 ** 20  # torch DDP default bucket_cap_mb=25


class BucketPlan(NamedTuple):
    treedef: Any
    buckets: Tuple[Tuple[int, ...], ...]    # each bucket: leaf indices (orig order ids)

    @property
    def num_buckets(self) -> int:
        return len(self.buckets)


class BucketSchedule(NamedTuple):
    """Issue schedule for overlap-capable bucket reduction.

    ``order`` lists bucket indices in READINESS order: the order in which
    each bucket's last gradient is produced during backward.  Buckets are
    built in reverse registration order (make_plan), so bucket 0 holds the
    last-registered leaves — the first gradients backward produces — and
    readiness order is plan order.  ``gate_leaf`` names, per bucket, the
    member with the LOWEST registration index: its gradient is the last of
    the bucket's to become ready, so it alone gates the bucket's collective.

    The schedule is what makes the overlapped tier ppermute-friendly: each
    bucket's all-reduce depends only on its own gate, never on another
    bucket's collective, so a ring lowering (reduce-scatter/all-gather via
    ``ppermute`` hops) can pipeline bucket k's first hop while bucket k+1's
    gradients are still being produced — the latency-hiding scheduler sees
    independent collective roots instead of one post-backward chain.
    """
    order: Tuple[int, ...]
    gate_leaf: Tuple[int, ...]


def make_schedule(plan: BucketPlan) -> BucketSchedule:
    """Readiness-order issue schedule for ``plan`` (see BucketSchedule)."""
    order = tuple(range(len(plan.buckets)))
    gate = tuple(min(b) for b in plan.buckets)
    return BucketSchedule(order=order, gate_leaf=gate)


def make_plan(params_like: Any,
              bucket_bytes: int = DEFAULT_BUCKET_BYTES) -> BucketPlan:
    leaves, treedef = jax.tree.flatten(params_like)
    nbytes = [int(np.prod(l.shape) if l.shape else 1)
              * np.dtype(l.dtype).itemsize for l in leaves]

    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    for i in reversed(range(len(leaves))):  # DDP: reverse registration order
        if cur and cur_bytes + nbytes[i] > bucket_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes[i]
    if cur:
        buckets.append(cur)

    return BucketPlan(treedef=treedef,
                      buckets=tuple(tuple(b) for b in buckets))
