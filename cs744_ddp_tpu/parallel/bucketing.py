"""Gradient bucketing: flatten parameter-gradient leaves into size-bounded
1-D fusion buckets.

This is the TPU-native analogue of torch DDP's C++ reducer bucketing
(reference: ``DDP(model)`` at ``/root/reference/src/Part 3/main.py:61``; the
reducer groups gradients into ~25 MB buckets and all-reduces each bucket as
one flat tensor).  Here the plan is computed once from the parameter pytree's
shapes (host side), and flatten/unflatten are pure jittable reshape/concat
ops, so each bucket becomes exactly one fused XLA AllReduce.

Like DDP, leaves are bucketed in *reverse* registration order (gradients
become ready last-layer-first during backward).
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_BUCKET_BYTES = 25 * 2 ** 20  # torch DDP default bucket_cap_mb=25


class BucketPlan(NamedTuple):
    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]     # per leaf, original order
    sizes: Tuple[int, ...]                  # per leaf element counts
    order: Tuple[int, ...]                  # leaf index -> position in bucket walk
    buckets: Tuple[Tuple[int, ...], ...]    # each bucket: leaf indices (orig order ids)

    @property
    def num_buckets(self) -> int:
        return len(self.buckets)


def make_plan(params_like: Any,
              bucket_bytes: int = DEFAULT_BUCKET_BYTES) -> BucketPlan:
    leaves, treedef = jax.tree.flatten(params_like)
    shapes = tuple(tuple(l.shape) for l in leaves)
    sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
    nbytes = [sizes[i] * jnp.asarray(leaves[i]).dtype.itemsize
              for i in range(len(leaves))]

    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    for i in reversed(range(len(leaves))):  # DDP: reverse registration order
        if cur and cur_bytes + nbytes[i] > bucket_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes[i]
    if cur:
        buckets.append(cur)

    order = tuple(i for b in buckets for i in b)
    return BucketPlan(treedef=treedef, shapes=shapes, sizes=sizes,
                      order=order, buckets=tuple(tuple(b) for b in buckets))


def flatten_to_buckets(grads: Any, plan: BucketPlan) -> List[jax.Array]:
    """Pytree -> list of 1-D bucket arrays (pure reshapes + concats)."""
    leaves = jax.tree.leaves(grads)
    out = []
    for bucket in plan.buckets:
        flat = [leaves[i].reshape(-1) for i in bucket]
        out.append(flat[0] if len(flat) == 1 else jnp.concatenate(flat))
    return out


def unflatten_from_buckets(buckets: Sequence[jax.Array],
                           plan: BucketPlan) -> Any:
    """Inverse of flatten_to_buckets."""
    leaves: List[Any] = [None] * len(plan.shapes)
    for bucket_ids, flat in zip(plan.buckets, buckets):
        off = 0
        for i in bucket_ids:
            n = plan.sizes[i]
            leaves[i] = jax.lax.slice(flat, (off,), (off + n,)).reshape(
                plan.shapes[i])
            off += n
    return jax.tree.unflatten(plan.treedef, leaves)
