"""Static host-round-trip certifier (round 13).

The ring buffer (round 8) made per-epoch host round-trips a COUNTED
quantity (``host_round_trips`` telemetry counter, CI-pinned), but the
pin is only as good as the run that produced it.  This module derives
the same number STATICALLY — a closed form over the lowered programs'
scan trip counts and the trainer's dispatch structure — so the K-epoch
mega-program (ROADMAP item 3) can be designed against a compile-time
certificate instead of a runtime observation.

The dispatch structure being certified (train/loop.py):

* ``step`` path: one blocking ``_fetch_step`` per batch
  (``step_fetch``), plus one fetch for a ragged tail batch, plus one
  ``eval`` fetch per ``test_model()``;
* ``window``/``host_window`` paths: one fetch per window dispatch —
  windows cut at WINDOW boundaries, so ``ceil(nbatches / window)``
  dispatches per epoch (``window_fetch``, or ``window_drain`` when the
  metrics ring defers the fetch to the drain), plus tail batch + eval
  as above.  The per-step metric writes inside the window are pure
  device-side ring updates — the audit's host-sync rule certifies the
  scanned body has no host transfer, which is what makes the closed
  form exact rather than an estimate.

From the HLO side, each windowed program must actually BE a windowed
program: its scan trip count (``costmodel.cost_report().trip_counts``)
must include the window size the trainer will dispatch, and its
donation set must be non-empty (a non-donating "windowed" program
round-trips the state through host memory every window — the exact
regression this certificate exists to catch).

``certify_zoo`` runs the certificate over an audited zoo
(``audit_zoo(..., collect_hlo=True)``); tests pin the static bound
against the live ``host_round_trips`` counter EXACTLY for every path.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Tuple

from .pylint_rules import LintFinding

#: Counter sites the trainer attributes round-trips to.
TRIP_SITES = ("step_fetch", "window_fetch", "window_drain", "eval")

#: Paths whose epoch cost is one fetch per WINDOW dispatch.
WINDOWED_PATHS = ("window", "host_window")

#: Serving-ladder zoo prefixes (``serve/b{bucket}/{precision}`` and the
#: hot-swap recert twin).  A serving rung must be STRAIGHT-LINE: one
#: dispatch = one fetch, no internal scan trips.  That is the premise of
#: the pipelined scheduler's two-in-flight bound — if a rung hid a host
#: round-trip inside a loop, overlapping two of them would serialize on
#: the host and the occupancy accounting would lie.
SERVING_PATHS = ("serve", "serve_swap")


def serving_inflight_bound() -> int:
    """The static per-replica in-flight dispatch bound (= the scheduler's
    ``PIPELINE_SLOTS`` = the ``StagedIngest`` arena depth).  Tests pin the
    runtime occupancy (``max_serving_inflight``) against this exactly."""
    from ..serve.scheduler import PIPELINE_SLOTS
    return PIPELINE_SLOTS


def max_serving_inflight(records: Iterable[Dict]) -> int:
    """Max observed pipeline occupancy from a recording telemetry's
    ``serve_inflight`` gauges — the runtime half of the bound pin (0 when
    the run never pipelined)."""
    m = 0
    for r in records:
        if r.get("kind") == "gauge" and r.get("name") == "serve_inflight":
            m = max(m, int(r.get("value", 0)))
    return m


def epoch_round_trip_bound(path: str, nbatches: int, window: int = 0, *,
                           tail_batch: bool = False,
                           include_eval: bool = False) -> int:
    """Closed-form host round-trips for ONE epoch of ``nbatches`` full
    batches on ``path`` (+1 for a ragged tail batch, which always runs
    per-step; +1 for the post-epoch eval fetch).  This is an upper bound
    that the runtime counter meets exactly: every dispatch fetches once
    and nothing else touches the host (audited)."""
    if nbatches < 0 or (path in WINDOWED_PATHS and window <= 0):
        raise ValueError(f"bad bound query: path={path!r} "
                         f"nbatches={nbatches} window={window}")
    if path == "step":
        trips = nbatches
    elif path in WINDOWED_PATHS:
        trips = math.ceil(nbatches / window)
    elif path == "eval":
        trips = 1 if nbatches else 0
    else:
        raise ValueError(f"unknown dispatch path {path!r}")
    return trips + (1 if tail_batch else 0) + (1 if include_eval else 0)


def mega_round_trip_bound(k_epochs: int, *, include_eval: bool = True) -> int:
    """Closed-form host round-trips for a K-epoch MEGA-program (ROADMAP
    item 3): the whole run is ONE dispatch whose ring drain is the single
    fetch, plus the final eval fetch when the run evals on device.  The
    windowed baseline pays ``k_epochs x epoch_round_trip_bound(...)``;
    this is the O(1) the mega-program buys, and
    :func:`megaplan.plan_k_epochs` certifies how large K can grow before
    HBM takes it back."""
    if k_epochs <= 0:
        return 0
    return 1 + (1 if include_eval else 0)


@dataclass
class ProgramCert:
    """Static dispatch facts for one lowered program."""

    program: str                  # zoo name, e.g. "train/window/ddp"
    path: str                     # "step" | "window" | "host_window" | ...
    scan_trips: Tuple[int, ...]   # every while-loop trip count in the HLO
    donated: int                  # donated entry parameters (the floor)

    @property
    def window(self) -> Optional[int]:
        """The program's window size: its largest scan trip count."""
        return max(self.scan_trips) if self.scan_trips else None


def _split_zoo_name(name: str) -> Tuple[str, str]:
    """zoo program name -> (path, strategy)."""
    parts = name.split("/")
    if parts[0] == "train" and len(parts) == 3:
        return parts[1], parts[2]
    if parts[0] == "eval":
        return "eval", "eval"
    return parts[0], "/".join(parts[1:])


def certify_program(name: str, hlo_text: str) -> ProgramCert:
    from . import costmodel, hlo_ir
    rep = costmodel.cost_report(hlo_text, name)
    module = hlo_ir.parse(hlo_text)
    path, _ = _split_zoo_name(name)
    return ProgramCert(
        program=name, path=path,
        scan_trips=tuple(sorted(rep.trip_counts.values())),
        donated=module.donated_param_count())


def check_cert(cert: ProgramCert, *, expect_window: Optional[int] = None
               ) -> List[LintFinding]:
    """Static conformance of one program: a windowed program must scan
    the window it claims and must donate its carried state."""
    findings: List[LintFinding] = []
    if cert.path in WINDOWED_PATHS or cert.path == "eval":
        if not cert.scan_trips:
            findings.append(LintFinding(
                "dispatch-no-scan", cert.program, 0,
                f"{cert.program} lowers to a straight-line program — a "
                f"windowed path must scan its window on device, or every "
                f"step round-trips the host"))
        elif expect_window is not None \
                and expect_window not in cert.scan_trips:
            findings.append(LintFinding(
                "dispatch-window-mismatch", cert.program, 0,
                f"{cert.program} scans {list(cert.scan_trips)} trips but "
                f"the trainer dispatches windows of {expect_window} — the "
                f"closed-form round-trip bound would be wrong"))
    if cert.path in WINDOWED_PATHS and cert.donated == 0:
        findings.append(LintFinding(
            "dispatch-donation-zero", cert.program, 0,
            f"{cert.program} donates no entry parameters — the carried "
            f"state bounces through host memory every window"))
    if cert.path in SERVING_PATHS and cert.scan_trips:
        findings.append(LintFinding(
            "dispatch-serving-scan", cert.program, 0,
            f"{cert.program} scans {list(cert.scan_trips)} trips — a "
            f"serving rung must be straight-line (one dispatch = one "
            f"fetch), or the pipelined two-in-flight bound is unsound"))
    return findings


def certify_zoo(result, *, window: int, nbatches: int,
                include_eval: bool = True) -> Dict:
    """The full certificate over an audited zoo (requires
    ``audit_zoo(..., collect_hlo=True)``).  Returns a JSON-ready record:
    per-program window/donation facts and the static per-epoch
    round-trip bound for ``nbatches`` full batches, plus any findings.
    """
    if not getattr(result, "hlo", None):
        raise ValueError("audit result carries no HLO text; re-run "
                         "audit_zoo(..., collect_hlo=True)")
    programs: Dict[str, Dict] = {}
    findings: List[LintFinding] = []
    for name in sorted(result.hlo):
        cert = certify_program(name, result.hlo[name])
        expect = window if cert.path in WINDOWED_PATHS + ("eval",) else None
        findings.extend(check_cert(cert, expect_window=expect))
        entry: Dict = {"path": cert.path, "window": cert.window,
                       "donated": cert.donated}
        if cert.path in ("step",) + WINDOWED_PATHS:
            entry["epoch_round_trips"] = epoch_round_trip_bound(
                cert.path, nbatches, window, include_eval=include_eval)
        programs[name] = entry
    return {
        "window": window,
        "nbatches": nbatches,
        "include_eval": include_eval,
        "programs": programs,
        "findings": [{"rule": f.rule, "program": f.path,
                      "message": f.message} for f in findings],
        "clean": not findings,
    }


def count_runtime_trips(records: Iterable[Dict]) -> Dict[str, int]:
    """Per-site totals of the live ``host_round_trips`` counter from a
    recording telemetry's event list — the number the static bound must
    meet exactly."""
    sites: Dict[str, int] = {}
    for r in records:
        if r.get("kind") == "counter" and r.get("name") == "host_round_trips":
            site = r.get("site", "?")
            sites[site] = sites.get(site, 0) + int(r.get("inc", 1))
    return sites


def total_runtime_trips(records: Iterable[Dict]) -> int:
    return sum(count_runtime_trips(records).values())
