"""Static wire-protocol schema conformance (round 13).

``serve/wire.py`` is the single declarative description of the serving
wire protocol.  This module verifies — WITHOUT importing the codec —
that the codec sources actually implement that table:

* every ``struct.Struct("...")`` assignment and every direct
  ``struct.pack/unpack`` format literal in the covered modules resolves
  to a registered format (an unregistered format is protocol drift the
  table never reviewed);
* a registered constant name bound to a DIFFERENT format than the table
  declares is a mismatch (the deliberately-broken-encoder fixture);
* encoder/decoder symmetry: each registered struct is used by at least
  one ``pack`` and one ``unpack`` site across the covered modules —
  a format that is only ever packed (or only unpacked) is a frame one
  peer can emit and no peer can read;
* TLV tag uniqueness and table agreement for every ``TAG_*`` constant;
* the optional-extension parser can never raise: ``unpack_ext`` carries
  no ``raise`` and every ``unpack_from`` inside it sits behind a length
  guard (checked on the AST), and an exhaustive deterministic corruption
  sweep over truncations/byte-flips of a canonical block confirms it
  (checked on the live function).

Findings reuse the lint's ``LintFinding`` shape so
``tools/lint_graft.py`` prints/serializes them uniformly.  Covered
modules: ``serve/frontend.py``, ``obs/tracing.py``,
``tools/serve_load.py`` (the third must simply contain no wire sites —
clients go through ``FrontendClient``, never raw structs).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from ..serve import wire
from .pylint_rules import LintFinding

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_ROOT = os.path.dirname(_PKG_ROOT)

# Modules the schema must cover (repo-relative).  Everything that packs
# or parses wire bytes lives here; a new module touching the wire must
# be added, or its formats show up as uncovered in the repo scan below.
COVERED = (
    os.path.join("cs744_ddp_tpu", "serve", "frontend.py"),
    os.path.join("cs744_ddp_tpu", "obs", "tracing.py"),
    os.path.join("tools", "serve_load.py"),
)

_PACK_METHODS = frozenset({"pack", "pack_into"})
_UNPACK_METHODS = frozenset({"unpack", "unpack_from", "iter_unpack"})


def _is_struct_ctor(node: ast.AST) -> Optional[str]:
    """``struct.Struct("<fmt>")`` -> the literal format, else None."""
    if (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "Struct"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "struct"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)):
        return node.args[0].value
    return None


def extract_struct_defs(tree: ast.AST) -> Dict[str, Tuple[str, int]]:
    """Module-level ``NAME = struct.Struct("...")`` -> {name: (fmt, line)}."""
    defs: Dict[str, Tuple[str, int]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        fmt = _is_struct_ctor(node.value)
        if fmt is None:
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                defs[t.id] = (fmt, node.lineno)
    return defs


def extract_direct_sites(tree: ast.AST) -> List[Tuple[str, int]]:
    """Direct ``struct.pack("<fmt>", ...)`` / ``struct.unpack(...)`` call
    sites with a literal format -> [(fmt, line)].  These bypass the named
    registry, so each format must still be registered."""
    sites: List[Tuple[str, int]] = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in (_PACK_METHODS | _UNPACK_METHODS
                                       | {"calcsize"})
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "struct"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            sites.append((node.args[0].value, node.lineno))
    return sites


def extract_tags(tree: ast.AST) -> Dict[str, Tuple[int, int]]:
    """Module-level ``TAG_* = <int>`` -> {name: (value, line)}."""
    tags: Dict[str, Tuple[int, int]] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, int)):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name) and t.id.startswith("TAG_"):
                tags[t.id] = (node.value.value, node.lineno)
    return tags


def extract_uses(tree: ast.AST) -> Dict[str, Set[str]]:
    """``NAME.pack(...)`` / ``NAME.unpack_from(...)`` -> {name: {"pack",
    "unpack"}} across the module (the symmetry evidence)."""
    uses: Dict[str, Set[str]] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)):
            continue
        name = node.func.value.id
        if node.func.attr in _PACK_METHODS:
            uses.setdefault(name, set()).add("pack")
        elif node.func.attr in _UNPACK_METHODS:
            uses.setdefault(name, set()).add("unpack")
    return uses


def check_source(source: str, path: str = "<source>",
                 *, registered: Optional[Dict[str, str]] = None,
                 tags: Optional[Dict[str, int]] = None
                 ) -> List[LintFinding]:
    """Formats/tags of ONE module against the schema registry."""
    registered = wire.REGISTERED_FORMATS if registered is None else registered
    tags = wire.REGISTERED_TAGS if tags is None else tags
    tree = ast.parse(source)
    findings: List[LintFinding] = []
    known_fmts = set(registered.values())

    for name, (fmt, line) in sorted(extract_struct_defs(tree).items()):
        want = registered.get(name)
        if want is None:
            findings.append(LintFinding(
                "wire-unregistered-format", path, line,
                f"struct {name} = Struct({fmt!r}) is not registered in "
                f"serve/wire.py — every wire format must live in the "
                f"schema table"))
        elif fmt != want:
            findings.append(LintFinding(
                "wire-format-mismatch", path, line,
                f"struct {name} packs {fmt!r} but serve/wire.py declares "
                f"{want!r} — encoder and schema have drifted"))
    for fmt, line in extract_direct_sites(tree):
        if fmt not in known_fmts:
            findings.append(LintFinding(
                "wire-unregistered-format", path, line,
                f"direct struct call with unregistered format {fmt!r}"))

    seen_tag_values: Dict[int, str] = {}
    for name, (value, line) in sorted(extract_tags(tree).items()):
        prev = seen_tag_values.get(value)
        if prev is not None:
            findings.append(LintFinding(
                "wire-tag-dup", path, line,
                f"{name} reuses TLV tag {value} already taken by {prev} — "
                f"tags must be unique for unknown-tag skipping to work"))
        seen_tag_values[value] = name
        want = tags.get(name)
        if want is None:
            findings.append(LintFinding(
                "wire-unregistered-tag", path, line,
                f"{name} = {value} is not registered in serve/wire.py"))
        elif value != want:
            findings.append(LintFinding(
                "wire-tag-mismatch", path, line,
                f"{name} = {value} but serve/wire.py declares {want}"))
    return findings


def check_ext_parser_total(source: str, path: str) -> List[LintFinding]:
    """``unpack_ext`` must be TOTAL: no ``raise``, and every
    ``unpack_from`` inside it lexically behind a ``len(...)`` bound
    comparison — the extension block is optional forward-compat data, so
    a torn/alien block must degrade to {} rather than kill a frame."""
    tree = ast.parse(source)
    findings: List[LintFinding] = []
    for fn in ast.walk(tree):
        if not (isinstance(fn, ast.FunctionDef)
                and fn.name == "unpack_ext"):
            continue
        guards = 0
        unpacks = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Raise):
                findings.append(LintFinding(
                    "wire-ext-raise", path, node.lineno,
                    "unpack_ext raises — optional-extension parsing must "
                    "degrade to {}, never fail a frame"))
            elif isinstance(node, ast.Compare):
                if any(isinstance(n, ast.Call)
                       and isinstance(n.func, ast.Name)
                       and n.func.id == "len"
                       for n in ast.walk(node)):
                    guards += 1
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Attribute)
                  and node.func.attr in _UNPACK_METHODS):
                unpacks.append(node.lineno)
        if len(unpacks) > guards:
            findings.append(LintFinding(
                "wire-ext-unguarded", path, unpacks[0],
                f"unpack_ext has {len(unpacks)} unpack site(s) but only "
                f"{guards} len() bound check(s) — a short buffer can "
                f"raise out of the optional-extension parser"))
    return findings


def ext_parse_corruption_sweep() -> List[str]:
    """Exhaustive deterministic corruption sweep over the LIVE
    ``unpack_ext``: every truncation of a canonical two-field block, and
    every byte value at every offset.  Returns failure descriptions
    ([] = the parser is total on this corpus)."""
    from ..obs import tracing

    base = tracing.pack_ext({
        wire.REGISTERED_TAGS["TAG_TRACE"]: b"\x01" * 24 + b"origin",
        wire.REGISTERED_TAGS["TAG_SERVER_TIMES"]: b"\x02" * 16,
        0x7F: b"future-field",       # unknown tag: must be skipped
    })
    failures: List[str] = []

    def feed(buf: bytes, what: str) -> None:
        try:
            out = tracing.unpack_ext(buf)
        except Exception as e:       # noqa: BLE001 - the property under test
            failures.append(f"unpack_ext raised {type(e).__name__} on "
                            f"{what}: {e}")
            return
        if not isinstance(out, dict):
            failures.append(f"unpack_ext returned {type(out).__name__} "
                            f"on {what}")

    for cut in range(len(base) + 1):
        feed(base[:cut], f"truncation at {cut}")
    for off in range(len(base)):
        for val in range(256):
            if base[off] == val:
                continue
            feed(base[:off] + bytes([val]) + base[off + 1:],
                 f"byte {off} -> {val}")
    return failures


def _relpath(path: str) -> str:
    return os.path.relpath(path, _REPO_ROOT)


def check_wire(repo_root: str = _REPO_ROOT) -> List[LintFinding]:
    """The full conformance run over the covered modules + the live
    codec.  [] = the wire protocol, its schema table, and its parsers
    agree; anything else is a finding with a file/line to fix."""
    findings: List[LintFinding] = []
    all_uses: Dict[str, Set[str]] = {}
    defined: Set[str] = set()
    for rel in COVERED:
        path = os.path.join(repo_root, rel)
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
        findings.extend(check_source(source, path))
        tree = ast.parse(source)
        defined |= set(extract_struct_defs(tree))
        for name, kinds in extract_uses(tree).items():
            all_uses.setdefault(name, set()).update(kinds)
    # Symmetry: every registered struct must be defined somewhere covered
    # and used by BOTH a pack and an unpack site across the modules.
    for name in sorted(wire.REGISTERED_FORMATS):
        if name not in defined:
            findings.append(LintFinding(
                "wire-missing-struct", COVERED[0], 0,
                f"registered struct {name} is defined in no covered "
                f"module — schema table and codec have diverged"))
            continue
        kinds = all_uses.get(name, set())
        for want in ("pack", "unpack"):
            if want not in kinds:
                findings.append(LintFinding(
                    "wire-asymmetric", COVERED[0], 0,
                    f"struct {name} has no {want} site in any covered "
                    f"module — one peer direction cannot speak it"))
    tracing_path = os.path.join(repo_root, COVERED[1])
    with open(tracing_path, encoding="utf-8") as fh:
        findings.extend(check_ext_parser_total(fh.read(), tracing_path))
    for problem in wire.verify_runtime():
        findings.append(LintFinding(
            "wire-table-drift", os.path.join(repo_root, "cs744_ddp_tpu",
                                             "serve", "wire.py"), 0,
            problem))
    for failure in ext_parse_corruption_sweep():
        findings.append(LintFinding(
            "wire-ext-raise", tracing_path, 0, failure))
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))
