"""Analytic FLOPs/bytes cost model over the :mod:`analysis.hlo_ir` IR.

Walks every instruction of a lowered program and charges:

- **FLOPs** — dots at ``2 x result_elems x K`` (K = product of the lhs
  contracting dims, batch dims fall out of ``result_elems``), convolutions
  at ``2 x result_elems x kernel_elems / C_out`` (grouped convs charge the
  per-group fan-in automatically), elementwise/transcendental ops at one
  flop per result element, reductions at one flop per input element.
- **HBM bytes** — operand + result bytes per instruction (a deliberately
  pessimistic "nothing fuses" model; see the roofline caveat in README),
  minus the donated entry-parameter bytes (a donated buffer is written in
  place, not copied out).
- **Wire bytes** — collective result bytes via the same accounting as
  :func:`stats.collective_bytes` (async pairs once, on the ``-done``).

Loop multiplicity: ``while`` bodies (the windowed paths' ``lax.scan``)
are charged ``trips`` times, with the trip count inferred as the largest
integer constant in the loop's condition computation — exactly where the
scan's bound lands in both print dialects.  Inference failures fall back
to 1 with a note rather than guessing.

Shard-map programs lower with PER-DEVICE shapes inside the manual region,
so a :class:`CostReport` over such a program is per-device; multiply by
the mesh size for machine totals.

This module is the single source of truth for the repo's analytic
FLOP/MFU arithmetic: ``bench._mfu_fields``, ``utils/metrics.mfu_fields``,
``tools/perf_attribution.py`` and ``tools/perf_stage_roofline.py`` all
delegate here (ISSUE 8 consolidation).
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from . import hlo_ir, stats

# v5e datasheet numbers shared by every MFU/roofline consumer in the repo.
# analysis/memlife (the peak-HBM certifier) and analysis/megaplan (the
# K-epoch planner) read the capacity from HERE — tools/lint_graft.py's
# path-less run fails if any of these literals grows a second copy.
V5E_BF16_PEAK_FLOPS = 197e12     # bf16 peak, per chip
V5E_HBM_BYTES_PER_S = 819e9     # HBM bandwidth, per chip
V5E_ICI_BYTES_PER_S = 200e9     # 1600 Gbit/s ICI, per chip per direction
V5E_HBM_CAPACITY_BYTES = 16 * 2**30   # HBM capacity, per chip

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
_INT_DTYPES = ("pred", "s8", "u8", "s16", "u16", "s32", "u32", "s64", "u64")

# One flop per result element.  Pure data movement (reshape, broadcast,
# transpose, slice, dynamic-update-slice, copy, ...) charges 0 flops and
# shows up in the HBM column instead.
_ELEMENTWISE = frozenset((
    "add", "subtract", "multiply", "divide", "remainder", "power",
    "maximum", "minimum", "clamp", "select", "compare",
    "exponential", "exponential-minus-one", "log", "log-plus-one",
    "tanh", "logistic", "sqrt", "rsqrt", "cbrt", "erf",
    "negate", "abs", "sign", "floor", "ceil", "is-finite",
    "round-nearest-afz", "round-nearest-even",
    "cosine", "sine", "tan", "atan2",
    "and", "or", "xor", "not", "convert",
    "shift-left", "shift-right-logical", "shift-right-arithmetic",
))
_REDUCE_OPS = frozenset(("reduce", "reduce-window"))
# Bookkeeping opcodes that move no HBM of their own.
_FREE_OPS = frozenset(("parameter", "constant", "tuple",
                       "get-tuple-element", "bitcast", "after-all",
                       "opt-barrier", "optimization-barrier"))


def mfu_fields(ips_per_chip: float, flops_per_image: Optional[float],
               peak_flops: float = V5E_BF16_PEAK_FLOPS) -> Dict:
    """Achieved TFLOP/s + model-flops-utilization fields for a measured
    per-chip image rate.  Returns ``{}`` when the analytic flop count is
    unavailable — absent keys, never null values (bench head contract)."""
    if not flops_per_image:
        return {}
    tflops = ips_per_chip * flops_per_image / 1e12
    return {
        "tflops_per_sec": round(tflops, 2),
        "mfu_vs_bf16_peak": round(tflops * 1e12 / peak_flops, 4),
    }


def _dims(type_str: Optional[str]) -> Optional[List[int]]:
    """Dims of the first array shape in an HLO type string, or None."""
    m = _SHAPE_RE.search(type_str or "")
    if not m:
        return None
    return [int(d) for d in m.group(2).split(",") if d]


def _elems(type_str: Optional[str]) -> int:
    """Total elements across every array shape in a (possibly tuple)
    HLO type string."""
    total = 0
    for _, dims in _SHAPE_RE.findall(type_str or ""):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n
    return total


def _attr_ints(raw: Optional[str]) -> List[int]:
    return [int(t) for t in re.findall(r"\d+", raw or "")]


def _operand_type(comp: hlo_ir.Computation, ins: hlo_ir.Instruction,
                  i: int) -> Optional[str]:
    """Type of operand ``i``: resolved through the defining instruction
    (the pre-optimization print leaves operands untyped), falling back to
    a type printed inline on the operand (optimized print)."""
    if i >= len(ins.operands):
        return None
    ref = comp.instructions.get(ins.operands[i])
    if ref is not None and ref.result_type:
        return ref.result_type
    if i < len(ins.operand_raw) and _SHAPE_RE.search(ins.operand_raw[i]):
        return ins.operand_raw[i]
    return None


def _called_comp(ins: hlo_ir.Instruction, key: str) -> Optional[str]:
    raw = ins.attr(key)
    if not raw:
        return None
    m = re.search(r"[%A-Za-z_][\w.\-]*", raw)
    return m.group(0).lstrip("%") if m else None


def _infer_trips(module: hlo_ir.Module, ins: hlo_ir.Instruction,
                 notes: List[str]) -> int:
    """Trip count of a ``while``: the largest integer constant in its
    condition computation (where ``lax.scan`` lowers its bound,
    ``lt(counter, constant(W))``, in both print dialects)."""
    cond = _called_comp(ins, "condition")
    comp = module.computations.get(cond) if cond else None
    best = 0
    if comp is not None:
        for c in comp.instructions.values():
            if c.opcode != "constant":
                continue
            if not c.result_type.startswith(_INT_DTYPES):
                continue
            for raw in c.operand_raw:
                try:
                    best = max(best, int(raw.strip().strip("{}")))
                except ValueError:
                    pass
    if best <= 0:
        notes.append(f"while {ins.name}: no integer bound in condition "
                     "computation; charging 1 trip")
        return 1
    return best


@dataclass
class CostReport:
    """Per-program analytic costs (per-device for shard_map programs)."""
    name: str
    flops: float = 0.0
    flops_by_op: Dict[str, float] = field(default_factory=dict)
    hbm_bytes: float = 0.0
    wire_bytes: float = 0.0                 # loop-multiplicity weighted
    wire_by_collective: Dict[str, int] = field(default_factory=dict)
    collective_sizes: List[int] = field(default_factory=list)  # static, per op
    donated_params: int = 0
    donated_bytes: int = 0
    trip_counts: Dict[str, int] = field(default_factory=dict)
    notes: List[str] = field(default_factory=list)

    @property
    def arithmetic_intensity(self) -> float:
        """flops / HBM byte — the roofline x-axis."""
        return self.flops / self.hbm_bytes if self.hbm_bytes else math.inf

    @property
    def comm_compute_flop_ratio(self) -> float:
        """Wire bytes per flop (0 when the program has no collectives)."""
        return self.wire_bytes / self.flops if self.flops else 0.0

    def to_dict(self) -> Dict:
        return {
            "name": self.name,
            "gflops": round(self.flops / 1e9, 4),
            "flops_by_op": {k: round(v / 1e9, 4)
                            for k, v in self.flops_by_op.items()},
            "hbm_mib": round(self.hbm_bytes / 2**20, 3),
            "wire_mib": round(self.wire_bytes / 2**20, 4),
            "wire_by_collective": dict(self.wire_by_collective),
            "donated_params": self.donated_params,
            "donated_mib": round(self.donated_bytes / 2**20, 3),
            "trip_counts": dict(self.trip_counts),
            "arithmetic_intensity": (
                round(self.arithmetic_intensity, 2)
                if self.hbm_bytes else None),
            "notes": list(self.notes),
        }


def _dot_flops(comp: hlo_ir.Computation, ins: hlo_ir.Instruction,
               notes: List[str]) -> float:
    out_elems = _elems(ins.result_type)
    lhs_dims = _dims(_operand_type(comp, ins, 0))
    contracting = _attr_ints(ins.attr("lhs_contracting_dims"))
    if lhs_dims is None or not contracting:
        notes.append(f"dot {ins.name}: lhs shape or contracting dims "
                     "unresolved; charging K=1")
        return 2.0 * out_elems
    k = 1
    for d in contracting:
        if d < len(lhs_dims):
            k *= lhs_dims[d]
    return 2.0 * out_elems * k


def _conv_flops(comp: hlo_ir.Computation, ins: hlo_ir.Instruction,
                notes: List[str]) -> float:
    out_elems = _elems(ins.result_type)
    kern_dims = _dims(_operand_type(comp, ins, 1))
    if kern_dims is None:
        notes.append(f"convolution {ins.name}: kernel shape unresolved; "
                     "charging 1 MAC per output element")
        return 2.0 * out_elems
    labels = ins.attr("dim_labels") or ""
    kern_labels = ""
    if "_" in labels:
        kern_labels = labels.split("_", 1)[1].split("->", 1)[0]
    o_idx = kern_labels.find("o") if "o" in kern_labels else len(kern_dims) - 1
    c_out = kern_dims[o_idx] if 0 <= o_idx < len(kern_dims) else 1
    kern_elems = 1
    for d in kern_dims:
        kern_elems *= d
    return 2.0 * out_elems * (kern_elems / max(c_out, 1))


def _donated_entry_bytes(module: hlo_ir.Module) -> Tuple[int, int]:
    """(donated param count, donated param bytes) from whichever donation
    header this toolchain prints (same forms as
    :meth:`hlo_ir.Module.donated_param_count`)."""
    idxs: set = set()
    for key in ("buffer_donor", "input_output_alias"):
        raw = module.attr(key)
        if raw:
            idxs |= {int(i) for i in re.findall(r"\(\s*(\d+)\s*,", raw)}
    entry = module.entry_computation
    by_index: Dict[int, str] = {}
    if entry is not None:
        for ins in entry.instructions.values():
            if ins.opcode == "parameter" and ins.operand_raw:
                try:
                    by_index[int(ins.operand_raw[0])] = ins.result_type
                except ValueError:
                    pass
    nbytes = sum(stats.bytes_of_type(by_index.get(i, "")) for i in idxs)
    return len(idxs), nbytes


def cost_report(hlo: stats.ModuleOrText, name: str = "program") -> CostReport:
    """Build a :class:`CostReport` for one lowered program.  Accepts raw
    HLO text (either print dialect) or a parsed Module."""
    module = stats._as_module(hlo)
    rep = CostReport(name=name)

    # Execution multiplicity per computation: entry runs once; while
    # bodies/conditions run `trips` times; every other callee (fusions,
    # reducers, branches) inherits the caller's multiplicity.
    mult: Dict[str, float] = {}

    def visit(cname: str, m: float, stack: Tuple[str, ...] = ()) -> None:
        if cname in stack or cname not in module.computations:
            return
        mult[cname] = mult.get(cname, 0.0) + m
        for ins in module.computations[cname].instructions.values():
            if ins.opcode == "while":
                trips = _infer_trips(module, ins, rep.notes)
                rep.trip_counts[ins.name] = trips
                for key, factor in (("body", trips), ("condition", trips)):
                    callee = _called_comp(ins, key)
                    if callee:
                        visit(callee, m * factor, stack + (cname,))
            else:
                for callee in ins.called:
                    visit(callee, m, stack + (cname,))

    entry = module.entry or next(iter(module.computations), None)
    if entry is not None:
        visit(entry, 1.0)

    for cname, comp in module.computations.items():
        m = mult.get(cname, 0.0)
        if m <= 0:
            continue
        for ins in comp.instructions.values():
            # --- FLOPs ---
            fl, key = 0.0, None
            if ins.opcode == "dot":
                fl, key = _dot_flops(comp, ins, rep.notes), "dot"
            elif ins.opcode == "convolution":
                fl, key = _conv_flops(comp, ins, rep.notes), "convolution"
            elif ins.opcode in _ELEMENTWISE:
                fl, key = float(_elems(ins.result_type)), "elementwise"
            elif ins.opcode in _REDUCE_OPS:
                fl, key = float(_elems(_operand_type(comp, ins, 0))), "reduce"
            if fl:
                rep.flops += fl * m
                rep.flops_by_op[key] = rep.flops_by_op.get(key, 0.0) + fl * m
            # --- HBM bytes (operand + result, nothing-fuses model) ---
            if ins.opcode not in _FREE_OPS:
                b = stats.bytes_of_type(ins.result_type)
                for i in range(len(ins.operands)):
                    b += stats.bytes_of_type(
                        _operand_type(comp, ins, i) or "")
                rep.hbm_bytes += b * m
            # --- wire bytes (same async-pair convention as stats) ---
            base = stats.collective_base(ins.opcode)
            if base is not None and not ins.opcode.endswith("-start"):
                b = stats.bytes_of_type(ins.result_type)
                rep.wire_bytes += b * m
                rep.collective_sizes.append(b)

    # Static per-collective bytes: identical accounting to the audit's
    # byte contracts (stats.collective_bytes), unweighted by loop trips.
    rep.wire_by_collective = stats.collective_bytes(module)
    rep.donated_params, rep.donated_bytes = _donated_entry_bytes(module)
    rep.hbm_bytes = max(0.0, rep.hbm_bytes - rep.donated_bytes)
    return rep
